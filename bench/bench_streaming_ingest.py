"""Streaming tiled-ingestion bench: streamed vs monolithic qPCA Gram fit.

Measures the double-buffered streaming engine (``sq_learn_tpu.streaming``)
on the MNIST-shaped qPCA partial-U Gram fit (70k×784 f32 ≈ 220 MB — the
exact upload class that has wedged the accelerator relay mid-transfer,
CLAUDE.md):

- end-to-end fit wall-clock, streamed vs monolithic ingest
  (``vs_baseline`` = monolithic/streamed; ≥ 0.909 ⇔ the streamed path is
  within the 1.10× acceptance bar);
- the maximum bytes of any single ``jax.device_put`` in the streamed fit
  (recorded by wrapping the transfer call — must be ≤ the tile cap, which
  is how the engine caps every transfer below the relay-wedge threshold
  *by construction*);
- overlap efficiency: streamed-Gram-pass wall-clock vs the larger of its
  transfer-only / compute-only halves — 1.0 means the smaller half fully
  hid under the larger (on the CPU backend "transfer" is a host copy, so
  this mostly measures engine overhead; the number is honest either way);
- compile discipline: streaming-kernel compile-cache entries after a sweep
  of 5 row counts vs the distinct (bucket, dtype) signatures the tiler
  planned — bucketing must pin entries to buckets, never to row counts.

Smoke mode subsamples rows; the tile cap scales down with it so the
streamed path still walks several tiles.
"""

import os
import sys
import warnings

import numpy as np

warnings.filterwarnings("ignore")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import emit, probe_backend, smoke_mode, timed  # noqa: E402


def main():
    probe_backend()
    import jax

    from sq_learn_tpu import streaming
    from sq_learn_tpu.models import QPCA

    if smoke_mode():
        n, m, k = 8_000, 128, 10
        tile_bytes = 1 << 20  # 1 MB → ~8 tiles
    else:
        n, m, k = 70_000, 784, 50
        # the relay-safe default (128 MB) gives a 70k×784 f32 matrix only
        # 2 tiles; 32 MB exercises a real tile walk while every transfer
        # stays far under the wedge threshold
        tile_bytes = 32 * (1 << 20)
    X = np.random.default_rng(0).normal(size=(n, m)).astype(np.float32)

    def fit(ingest):
        return QPCA(n_components=k, svd_solver="full", random_state=0,
                    ingest=ingest).fit(X)

    mono_t, mono = timed(fit, "monolithic", warmup=1, reps=2)

    # record every streamed device_put size by wrapping the transfer call
    # (the engine resolves it as `jax.device_put`, so this sees each tile)
    sizes = []
    real_put = jax.device_put

    def recording_put(x, *a, **kw):
        sizes.append(int(getattr(x, "nbytes", 0)))
        return real_put(x, *a, **kw)

    os.environ["SQ_STREAM_TILE_BYTES"] = str(tile_bytes)
    jax.device_put = recording_put
    try:
        stream_t, stream = timed(fit, "streamed", warmup=1, reps=2)
    finally:
        jax.device_put = real_put
    assert stream.ingest_ == "streamed", stream.ingest_
    max_put = max(sizes) if sizes else 0

    parity = float(np.abs(
        np.asarray(stream.explained_variance_ratio_)
        - np.asarray(mono.explained_variance_ratio_)).max())

    try:
        # overlap efficiency of the streamed Gram pass
        def gram_pass():
            out = streaming.streamed_centered_gram(X)
            jax.block_until_ready(out[1])

        gram_t, _ = timed(gram_pass, warmup=1, reps=2)

        def transfer_only():
            last = None
            for tile, _, _ in streaming.stream_tiles(X):
                last = tile
            jax.block_until_ready(last)

        xfer_t, _ = timed(transfer_only, warmup=1, reps=2)
        Xd = jax.device_put(X)

        def compute_only():
            jax.block_until_ready(Xd.T @ Xd)

        comp_t, _ = timed(compute_only, warmup=1, reps=2)
        del Xd
        overlap_eff = max(xfer_t, comp_t) / gram_t if gram_t > 0 else 1.0

        # compile discipline: sweep 5 row counts through the Gram pass,
        # then compare cache entries against the distinct bucket shapes
        # the tiler planned (row counts must NOT mint compiles)
        sweep = [int(n * f) for f in np.linspace(0.55, 0.95, 5)]
        row_bytes = X.nbytes // n
        buckets = set()
        for size in [n] + sweep:
            rows, _ = streaming.plan_row_tiles(size, row_bytes)
            buckets.add(rows)
            tail = size % rows
            if tail:
                buckets.add(streaming._bucket_rows(tail, rows))
        for size in sweep:
            streaming.streamed_centered_gram(X[:size])
        entries = streaming.kernel_cache_sizes()["gram_colsum"]
    finally:
        os.environ.pop("SQ_STREAM_TILE_BYTES", None)

    # SQ_OBS=1: close the run artifact with (a) the watchdog's view of the
    # bucket sweep — the enforced form of the ≤1-compile-per-bucket
    # invariant this bench's cache-entry count measures by hand — and
    # (b) a small quantum-extraction fit so the run's ledger states the
    # paper's accuracy-vs-runtime trade-off (nonzero tomography shots)
    # next to the streamed classical numbers.
    from sq_learn_tpu import obs

    obs_extra = {}
    if obs.enabled():
        report = obs.watchdog.report().get("streaming.gram_colsum", {})
        Xq = X[:512, :64]
        QPCA(n_components=8, svd_solver="full", random_state=0).fit(
            Xq, estimate_all=True, theta_major=1.0, eps=0.1, delta=0.5,
            true_tomography=False)
        totals = obs.ledger.totals()
        obs_extra = {
            "obs_watchdog_gram_compiles": report.get("compiles"),
            "obs_watchdog_gram_budget": report.get("budget"),
            "obs_ledger_tomography_shots":
                totals["queries"].get("tomography_shots", 0),
        }

    emit("streaming_ingest_qpca_gram_fit_wallclock", stream_t,
         **obs_extra,
         vs_baseline=(mono_t / stream_t if stream_t > 0 else None),
         n=n, m=m, k=k, tile_bytes=tile_bytes,
         monolithic_s=round(mono_t, 4),
         max_single_device_put_bytes=int(max_put),
         tile_cap_respected=bool(max_put <= tile_bytes),
         overlap_efficiency=round(float(overlap_eff), 3),
         gram_pass_s=round(gram_t, 4), transfer_only_s=round(xfer_t, 4),
         compute_only_s=round(comp_t, 4),
         gram_kernel_cache_entries=int(entries),
         distinct_tile_buckets=len(buckets),
         compiles_per_bucket_ok=bool(entries <= 2 * len(buckets)),
         ev_ratio_max_abs_dev=parity,
         backend=jax.default_backend())


if __name__ == "__main__":
    main()
