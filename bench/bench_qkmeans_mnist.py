"""BASELINE config #3: quantum KMeans k=10 on full MNIST 70k×784, sharded
over every attached device (one-chip mesh degenerates gracefully).

vs_baseline = sklearn_seconds / ours (>1 ⇒ faster).
"""

import sys
import warnings

import numpy as np

warnings.filterwarnings("ignore")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import (emit, maybe_subsample, probe_backend,  # noqa: E402
                           timed)


def main():
    probe_backend()
    import jax
    from sq_learn_tpu.datasets import load_mnist
    from sq_learn_tpu.models import QKMeans
    from sq_learn_tpu.parallel.mesh import make_mesh

    X, y, real = load_mnist()
    X, y = maybe_subsample(X, y)
    k, n_init, seed = 10, 3, 0
    mesh = make_mesh() if len(jax.devices()) > 1 else None
    # MXU-native precision on TPU: bf16 distance GEMM with exact selected
    # distances (see QKMeans.compute_dtype) — the ARI quality gate below
    # records the effect; CPU/GPU keep the f32 default
    compute_dtype = ("bfloat16" if jax.default_backend() == "tpu" else None)

    def ours_fit():
        est = QKMeans(n_clusters=k, n_init=n_init, max_iter=300,
                      delta=0.5, true_distance_estimate=False,
                      random_state=seed, mesh=mesh,
                      compute_dtype=compute_dtype)
        est.fit(X)
        return est

    ours_t, est = timed(ours_fit, warmup=1, reps=1)

    sk_t, ari = None, None
    try:
        from sklearn.cluster import KMeans as SKKMeans
        from sklearn.metrics import adjusted_rand_score

        def sk_fit():
            return SKKMeans(n_clusters=k, n_init=n_init, max_iter=300,
                            random_state=seed).fit(X)

        sk_t, sk = timed(sk_fit, warmup=0, reps=1)
        ari = float(adjusted_rand_score(sk.labels_, est.labels_))
    except Exception as exc:
        print(f"# sklearn baseline unavailable: {exc}", file=sys.stderr)

    emit("qkmeans_mnist_70kx784_k10_fit_wallclock", ours_t,
         vs_baseline=(sk_t / ours_t) if sk_t else None,
         sklearn_s=sk_t, ari_vs_sklearn=ari,
         devices=len(jax.devices()), real_mnist=real,
         compute_dtype=compute_dtype or "float32")


if __name__ == "__main__":
    main()
