"""Out-of-core fit bench (PR 8): multi-epoch mini-batch q-means over a
disk-backed shard store LARGER than an enforced host-RAM budget
(``SQ_OOC_RAM_BUDGET_BYTES``), on the CPU backend.

Measures the three numbers the out-of-core story lives on:

- **wall-clock** of the uninterrupted 2-epoch fit (the JSON line's
  value, banded by ``make regress``);
- **peak RSS delta** across the fit — the proof the dataset never
  materialized (a resident fit would grow RSS by the store size);
- **resume overhead**: the same fit killed mid-epoch-2 by an injected
  interrupt, then resumed from its mid-epoch checkpoint — the extra
  wall-clock a death costs, with bit-parity asserted against the
  uninterrupted result.

vs_baseline = in-RAM host fit seconds / out-of-core seconds. PR 8's
record sat at 0.818 (the serial read/verify/checkpoint tax); ISSUE 10's
native-CRC verify + readahead prefetcher + async checkpoints set a
declared floor of 0.95 — emitted as ``vs_baseline_floor``, which
`make regress` bands as the history-free lower-bounded ``vs_baseline``
gate. The explicit prefetch-OFF/ON arms (``fit_noprefetch_s`` /
``fit_prefetch_s``, bit-parity asserted against the headline) make the
overlap delta a measured pair in the extras rather than a claim — on a
single-core host the ON arm trails (nothing to overlap with; the 'auto'
depth resolves to 0 there), on multi-core it leads. SQ_BENCH_SMOKE=1
shrinks the store to seconds while keeping every code path (budget
guard, faults, resume).

Compressed-store legs (ISSUE 13, ``SQ_OOC_CODEC=lz4``): the codec's
bytes-on-disk and warm-fit claims are measured on a same-shape
``kind="pixels"`` store — the image-workload twin (sparse, 256-level
quantized rows, the MNIST-like family every headline bench fits) whose
bytes actually compress; the Gaussian surrogate's float mantissas are
near-incompressible by construction (≈0.9 with the byte-shuffle filter
— that arm would measure the filter, not the tier). Two builds of the
SAME pixel data (codec none / lz4), two warm fits, bit-parity asserted:

- ``*_codec_bytes_ratio`` — value = stored / raw bytes (the ≤ 0.7
  acceptance; in-bench hard-fail above it), ``vs_baseline`` =
  raw / stored with a declared floor of 1.4, banded history-free.
- ``*_codec_2epoch_wallclock`` — the tier the motivation names: both
  stores fit under a steady ``cold_tier`` fault profile (per-shard
  request latency + per-MiB bandwidth model — CI-scaled remote object
  storage) with the readahead prefetcher armed. value = compressed-
  store fit seconds; ``vs_baseline`` = uncompressed twin's cold-tier
  fit / compressed fit, declared floor 0.95 — at cold-tier bandwidth
  the compressed store must win (it moves ~1/3 the bytes) and
  decompression must hide behind the I/O overlap, not serialize the
  consumer (injected tier latency is blocking, so the overlap holds
  even on a single-core host). Extras carry the serial compressed arm
  (the prefetch-hides-the-tier pair) AND the warm page-cache fit pair:
  on a warm cache the decode is pure extra CPU — a single-core host
  (this dev container, noted in the record like PR 10's) pays it
  serially; multi-core hosts hide it on the worker pool.

Storage-ledger leg (v11, under ``SQ_OBS=1``): after the fits the bench
flushes :mod:`sq_learn_tpu.obs.storage` and hard-fails unless (a) every
fitted store's per-shard ledger byte totals reconcile exactly with its
manifest, (b) no shard emitted more ``io`` lines than ledger flushes
(pre-aggregation: O(#shards) records, never O(#reads)), and (c) the
tiering advisor replayed over the run's own records recommends
compressing the pixel store the cold-tier pair measured as winning, at
a projected ratio within 20% of the committed ``bytes_ratio``. The
``io_*``/``advice_*`` extras land on the codec wallclock line.
"""

import json
import os
import resource
import shutil
import sys
import tempfile
import time
import warnings

import numpy as np

warnings.filterwarnings("ignore")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import emit, timed  # noqa: E402


def _rss_bytes():
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from sq_learn_tpu import oocore
    from sq_learn_tpu.models import MiniBatchQKMeans
    from sq_learn_tpu.resilience import faults
    from sq_learn_tpu.resilience.faults import InjectedInterrupt

    smoke = os.environ.get("SQ_BENCH_SMOKE") == "1"
    if smoke:
        n, m, k, batch = 20_000, 64, 8, 1024
        shard_bytes, budget = 256 * 1024, 1 << 20
    else:
        n, m, k, batch = 100_000, 784, 10, 2048
        shard_bytes, budget = 16 << 20, 96 << 20

    tmp = tempfile.mkdtemp(prefix="sq_oocore_bench_")
    ckpt_dir = os.path.join(tmp, "ckpt")
    os.makedirs(ckpt_dir)
    try:
        build_s, store = timed(
            oocore.create_synthetic_store, os.path.join(tmp, "store"),
            n, m, n_classes=k, seed=0, shard_bytes=shard_bytes,
            warmup=0, reps=1)
        assert store.nbytes > budget, "store must exceed the RAM budget"

        est_kw = dict(n_clusters=k, batch_size=batch, max_iter=2,
                      max_no_improvement=None, tol=0.0, n_init=1,
                      compute_labels=False, random_state=0)

        os.environ["SQ_OOC_RAM_BUDGET_BYTES"] = str(budget)
        # the budget guard must refuse a whole-store materialization
        try:
            store.read_rows(0, n)
            budget_guard = False
        except oocore.RamBudgetError:
            budget_guard = True

        rss0 = _rss_bytes()
        # warmup=1: the first walk pays the cold page cache for every
        # shard; the timed legs (uninterrupted vs killed+resumed) must
        # compare warm-to-warm or the resume overhead goes negative
        fit_s, est = timed(
            lambda: MiniBatchQKMeans(**est_kw).fit(store),
            warmup=1, reps=1)
        rss_delta = _rss_bytes() - rss0

        # explicit prefetch-OFF / prefetch-ON arms (the headline above
        # runs the 'auto' depth): the readahead's overlap delta lands in
        # the extras as a measured pair (and its bit parity as asserts),
        # not a claim. On a single-core host the ON arm is EXPECTED to
        # trail slightly (threads time-slice the one core — why 'auto'
        # resolves to 0 there); multi-core hosts show the overlap win.
        os.environ["SQ_OOC_PREFETCH_DEPTH"] = "0"
        serial_s, est_serial = timed(
            lambda: MiniBatchQKMeans(**est_kw).fit(store),
            warmup=0, reps=1)
        os.environ["SQ_OOC_PREFETCH_DEPTH"] = "2"
        prefetch_s, est_pf = timed(
            lambda: MiniBatchQKMeans(**est_kw).fit(store),
            warmup=0, reps=1)
        del os.environ["SQ_OOC_PREFETCH_DEPTH"]
        serial_parity = bool(
            np.array_equal(est.cluster_centers_,
                           est_serial.cluster_centers_)
            and np.array_equal(est.cluster_centers_,
                               est_pf.cluster_centers_))

        # compressed-store legs: same pixel data built codec none / lz4,
        # warm fits compared, bit parity asserted; then the cold-tier
        # profile with readahead off/on (see the module docstring)
        px = dict(n_classes=k, seed=1, shard_bytes=shard_bytes,
                  kind="pixels")
        pstore = oocore.create_synthetic_store(
            os.path.join(tmp, "px_none"), n, m, codec="none", **px)
        cstore = oocore.create_synthetic_store(
            os.path.join(tmp, "px_lz4"), n, m, codec="lz4", **px)
        bytes_ratio = cstore.stored_nbytes / cstore.nbytes
        pfit_s, est_px = timed(
            lambda: MiniBatchQKMeans(**est_kw).fit(pstore),
            warmup=1, reps=1)
        cfit_s, est_cx = timed(
            lambda: MiniBatchQKMeans(**est_kw).fit(cstore),
            warmup=1, reps=1)
        codec_parity = bool(np.array_equal(est_px.cluster_centers_,
                                           est_cx.cluster_centers_))

        # smoke shards are ~0.25 MB, so the per-MiB bandwidth term needs
        # to be steep for the bytes-saved signal to dominate the fixed
        # request latency (and the 1 MB smoke budget rightly degrades
        # the readahead to serial — the full-size run overlaps)
        cold_spec = ("cold_tier:s=0.002,per_mb=0.1,times=1000000"
                     if smoke else
                     "cold_tier:s=0.01,per_mb=0.01,times=1000000")

        def cold_fit(src, depth):
            os.environ["SQ_OOC_PREFETCH_DEPTH"] = str(depth)
            faults.arm(cold_spec)
            try:
                s, _ = timed(lambda: MiniBatchQKMeans(**est_kw).fit(src),
                             warmup=0, reps=1)
            finally:
                faults.disarm()
                del os.environ["SQ_OOC_PREFETCH_DEPTH"]
            return s

        cold_serial_s = cold_fit(cstore, 0)
        cold_prefetch_s = cold_fit(cstore, 2)
        cold_none_s = cold_fit(pstore, 2)

        # killed-and-resumed leg: mid-epoch-2 interrupt, checkpointed
        # every 8 batches, resume must be bit-identical
        os.environ["SQ_STREAM_CKPT_DIR"] = ckpt_dir
        os.environ["SQ_STREAM_CKPT_EVERY"] = "8"
        n_batches = -(-n // batch)
        faults.arm(f"abort:tile={n_batches + 2},times=1")
        t0 = time.perf_counter()
        try:
            MiniBatchQKMeans(**est_kw).fit(store)
            raise RuntimeError("injected interrupt did not fire")
        except InjectedInterrupt:
            pass
        dead_s = time.perf_counter() - t0
        faults.disarm()
        resume_s, est_r = timed(
            lambda: MiniBatchQKMeans(**est_kw).fit(store),
            warmup=0, reps=1)
        parity = bool(np.array_equal(est.cluster_centers_,
                                     est_r.cluster_centers_))
        del os.environ["SQ_STREAM_CKPT_DIR"]
        del os.environ["SQ_STREAM_CKPT_EVERY"]

        # in-RAM baseline: lift the budget, materialize, same config
        del os.environ["SQ_OOC_RAM_BUDGET_BYTES"]
        X = store.read_rows(0, n)
        ram_s, _ = timed(lambda: MiniBatchQKMeans(**est_kw).fit(X),
                         warmup=0, reps=1)

        # storage-plane ledger (v11, `make regress` runs this bench
        # under SQ_OBS=1): flush the per-shard io aggregates, reconcile
        # them byte-for-byte against each store's manifest (hard-fail —
        # a ledger that disagrees with the manifest is lying about the
        # bytes it moved), pin the pre-aggregation invariant (a key
        # emits at most one line per flush, never one per read), and
        # replay the tiering advisor over the run's own records: the
        # cold-tier pair above is exactly the experiment the advisor
        # must read back from telemetry alone — compress the pixel
        # store, at a projected ratio consistent with the measured
        # bytes_ratio this bench commits.
        from sq_learn_tpu import obs
        from sq_learn_tpu.obs import storage as obs_storage

        io_extras = {}
        if obs.enabled():
            obs_storage.flush("pass_end")
            orec = obs.get_recorder()
            io_recs = list(orec.io_records)
            view = obs_storage.collect(io_recs)
            ooc_view = view["surfaces"].get("oocore", {})
            for st in (store, pstore, cstore):
                led = ooc_view.get(st.fingerprint, {})
                if not led:
                    print(json.dumps(
                        {"error": "no io records for a fitted store",
                         "store": st.fingerprint}), file=sys.stderr)
                    return 1
                row_nbytes = st.shape[1] * st.dtype.itemsize
                for i, r in led.items():
                    reads = int(r.get("reads", 0))
                    want_raw = st.shard_sizes[i] * row_nbytes * reads
                    want_stored = st.shard_stored_sizes[i] * reads
                    if (int(r.get("bytes_raw", 0)) != want_raw
                            or int(r.get("bytes_stored", 0))
                            != want_stored):
                        print(json.dumps(
                            {"error": "io ledger does not reconcile "
                                      "with the store manifest",
                             "store": st.fingerprint, "shard": i,
                             "ledger": {k: r.get(k) for k in
                                        ("reads", "bytes_raw",
                                         "bytes_stored")},
                             "manifest_raw": want_raw,
                             "manifest_stored": want_stored}),
                            file=sys.stderr)
                        return 1
            per_key = {}
            for r in io_recs:
                kk = (r.get("surface"), r.get("store"), r.get("shard"))
                per_key[kk] = per_key.get(kk, 0) + 1
            flushes = orec._storage._flushes
            if max(per_key.values(), default=0) > flushes:
                print(json.dumps(
                    {"error": "io records flood the sink (more lines "
                              "for one shard than flushes — per-read "
                              "emission, not pre-aggregation)",
                     "worst": max(per_key.values()),
                     "flushes": flushes}), file=sys.stderr)
                return 1
            advice = obs_storage.advise(view)
            aratio = advice.get("ratio")
            if aratio is None or abs(aratio - bytes_ratio) \
                    > 0.2 * bytes_ratio:
                print(json.dumps(
                    {"error": "advisor's measured codec ratio is not "
                              "consistent with the manifest bytes "
                              "ratio", "advice_ratio": aratio,
                     "bytes_ratio": round(bytes_ratio, 3)}),
                    file=sys.stderr)
                return 1
            pshards = [s for s in advice["shards"]
                       if s["store"] == pstore.fingerprint]
            n_compress = sum(1 for s in pshards
                             if s["action"] == "compress")
            if not n_compress:
                print(json.dumps(
                    {"error": "advisor did not recommend compressing "
                              "the pixel store the cold-tier pair "
                              "measured as winning",
                     "actions": sorted({s["action"] for s in pshards})}),
                    file=sys.stderr)
                return 1
            io_extras = dict(
                io_records=len(io_recs),
                io_flushes=int(flushes),
                io_shards_tracked=len(per_key),
                advice_ratio=round(aratio, 3),
                advice_compress_recs=n_compress,
                advice_top_heat=round(
                    advice["shards"][0]["heat"], 3)
                if advice["shards"] else None)

        art_dir = os.environ.get("SQ_OOC_BENCH_ARTIFACT_DIR")
        if art_dir:
            # run_suite.sh archives the store manifest next to the
            # config's obs JSONL — the record stays traceable to the
            # exact shard split and CRCs it measured
            shutil.copy(os.path.join(store.path, "manifest.json"),
                        os.path.join(art_dir, "oocore_manifest.json"))

        from sq_learn_tpu.oocore.prefetch import (prefetch_depth,
                                                  prefetch_threads)

        emit(f"oocore_minibatch_{n // 1000}kx{m}_k{k}_2epoch_wallclock",
             fit_s, vs_baseline=(ram_s / fit_s),
             vs_baseline_floor=0.95,
             store_mb=round(store.nbytes / 2**20, 1),
             ram_budget_mb=round(budget / 2**20, 1),
             budget_guard=budget_guard,
             peak_rss_mb=round(_rss_bytes() / 2**20, 1),
             peak_rss_delta_mb=round(rss_delta / 2**20, 1),
             oocore_resident=bool(rss_delta < store.nbytes),
             build_s=round(build_s, 3), ram_fit_s=round(ram_s, 3),
             fit_noprefetch_s=round(serial_s, 3),
             fit_prefetch_s=round(prefetch_s, 3),
             prefetch_speedup=round(serial_s / prefetch_s, 3),
             prefetch_parity=serial_parity,
             prefetch_depth=prefetch_depth(),
             prefetch_threads=prefetch_threads(),
             dead_fit_s=round(dead_s, 3), resume_fit_s=round(resume_s, 3),
             resume_overhead_s=round(dead_s + resume_s - fit_s, 3),
             resume_parity=parity, n_shards=store.n_shards,
             smoke=smoke)
        emit(f"oocore_codec_{n // 1000}kx{m}_bytes_ratio", bytes_ratio,
             unit="ratio",
             vs_baseline=(cstore.nbytes / cstore.stored_nbytes),
             vs_baseline_floor=1.4,
             raw_bytes=int(cstore.nbytes),
             stored_bytes=int(cstore.stored_nbytes),
             store_kind="pixels", codec="lz4",
             codec_parity=codec_parity, smoke=smoke)
        emit(f"oocore_codec_{n // 1000}kx{m}_2epoch_wallclock",
             cold_prefetch_s,
             vs_baseline=(cold_none_s / cold_prefetch_s),
             vs_baseline_floor=0.95,
             cold_tier_uncompressed_s=round(cold_none_s, 3),
             cold_tier_compressed_s=round(cold_prefetch_s, 3),
             cold_tier_serial_compressed_s=round(cold_serial_s, 3),
             cold_tier_hidden_s=round(cold_serial_s - cold_prefetch_s, 3),
             cold_tier_spec=cold_spec,
             warm_fit_uncompressed_s=round(pfit_s, 3),
             warm_fit_compressed_s=round(cfit_s, 3),
             warm_decode_overhead=round(cfit_s / pfit_s, 3),
             codec_parity=codec_parity,
             single_core_host=(os.cpu_count() or 1) <= 1, smoke=smoke,
             **io_extras)
        if not parity:
            print(json.dumps({"error": "resume parity violated"}),
                  file=sys.stderr)
            return 1
        if not serial_parity:
            print(json.dumps(
                {"error": "prefetch-on vs prefetch-off parity violated"}),
                file=sys.stderr)
            return 1
        if not codec_parity:
            print(json.dumps(
                {"error": "compressed-store fit diverged from the "
                          "uncompressed twin"}), file=sys.stderr)
            return 1
        if bytes_ratio > 0.7:
            print(json.dumps(
                {"error": "compressed pixel store above the 0.7 "
                          "bytes-on-disk acceptance", "ratio":
                 round(bytes_ratio, 3)}), file=sys.stderr)
            return 1
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
