#!/bin/bash
# Run the full BASELINE bench suite (headline + configs #2-#5) and collect
# the JSON lines into one file. Each script probes the accelerator in a
# subprocess and falls back to CPU if the tunnel is wedged, recording
# whichever backend actually ran.
#
# Usage: bash bench/run_suite.sh [outfile]   (default /tmp/bench_suite_run.txt)
set -u
out="${1:-/tmp/bench_suite_run.txt}"
case "$out" in /*) ;; *) out="$(pwd)/$out" ;; esac  # resolve before the cd
cd "$(dirname "$0")/.."
: > "$out"
echo "# suite run $(date -Is)" >> "$out"
for cmd in "python bench.py" \
           "python -m bench.bench_qpca_mnist" \
           "python -m bench.bench_qkmeans_mnist" \
           "python -m bench.bench_randomized_svd_covtype" \
           "python -m bench.bench_qkmeans_cicids_sweep"; do
  echo "## $cmd" >> "$out"
  timeout 1200 $cmd >> "$out" 2>/tmp/bench_last_stderr.txt
  rc=$?
  tail -3 /tmp/bench_last_stderr.txt | sed 's/^/# stderr: /' >> "$out"
  echo "# rc=$rc" >> "$out"
done
echo "done: $out"
