#!/bin/bash
# Run the full BASELINE bench suite (headline + configs #2-#5) and collect
# the JSON lines into one file. Each script probes the accelerator in a
# subprocess and falls back to CPU if the tunnel is wedged at START; the
# probe cannot protect against a tunnel that wedges MID-run (observed: the
# relay died during a 70k×784 upload, hanging the fit until the script
# timeout), so any script that exits non-zero is retried once with the
# backend pinned to CPU — a mid-run tunnel wedge no longer costs a config
# its number (a failure that also reproduces on CPU still records only the
# two rc markers).
#
# Usage: bash bench/run_suite.sh [outfile]   (default /tmp/bench_suite_run.txt)
set -u
stderr_tmp="$(mktemp /tmp/bench_stderr.XXXXXX)"
trap 'rm -f "$stderr_tmp"' EXIT
out="${1:-/tmp/bench_suite_run.txt}"
case "$out" in /*) ;; *) out="$(pwd)/$out" ;; esac  # resolve before the cd
cd "$(dirname "$0")/.."
: > "$out"
echo "# suite run $(date -Is)" >> "$out"

run_and_record() {  # run_and_record <timeout_s> <header> <cmd...>; returns the cmd's rc
  local tmo=$1
  echo "## $2" >> "$out"
  shift 2
  timeout "$tmo" "$@" >> "$out" 2>"$stderr_tmp"
  local rc=$?
  # failures keep a full traceback in the record (the temp file is deleted
  # on exit); successes keep the 3-line summary
  local depth=3
  [ "$rc" -ne 0 ] && depth=40
  tail -"$depth" "$stderr_tmp" | sed 's/^/# stderr: /' >> "$out"
  echo "# rc=$rc" >> "$out"
  return $rc
}

# Order: the two configs that fit inside a short healthy-tunnel window run
# first (the headline, then covtype SVD — the one config still missing an
# honest TPU number of record); the heavy 70k×784 uploads (#2/#3) have
# wedged the relay mid-transfer in three separate windows, so they go last
# where a wedge can no longer cost the small configs their numbers.
# First attempts get 600 s (a healthy run finishes well under that; only a
# wedge reaches the timeout); CPU retries keep the conservative 1200 s.
for cmd in "python bench.py" \
           "python -m bench.bench_randomized_svd_covtype" \
           "python -m bench.bench_qkmeans_cicids_sweep" \
           "python -m bench.bench_qpca_mnist" \
           "python -m bench.bench_qkmeans_mnist"; do
  if ! run_and_record 600 "$cmd" $cmd; then
    # mid-run tunnel wedge (or any accelerator failure): record the CPU
    # fallback number instead of nothing. PYTHONPATH is cleared so the
    # axon sitecustomize never touches the wedged relay (CLAUDE.md).
    run_and_record 1200 "$cmd [cpu retry]" \
      env -u PYTHONPATH JAX_PLATFORMS=cpu $cmd
  fi
done
echo "done: $out"
