#!/bin/bash
# Run the full BASELINE bench suite (headline + configs #2-#5, plus the
# supplementary derived-baseline IPE config) and collect
# the JSON lines into one file. Each script probes the accelerator in a
# subprocess and falls back to CPU if the tunnel is wedged at START; the
# probe cannot protect against a tunnel that wedges MID-run (observed: the
# relay died during a 70k×784 upload, hanging the fit until the script
# timeout), so any script that exits non-zero is retried once with the
# backend pinned to CPU — a mid-run tunnel wedge no longer costs a config
# its number (a failure that also reproduces on CPU still records only the
# two rc markers).
#
# Usage: bash bench/run_suite.sh [outfile]
# Default outfile: bench/records/<UTC date-time>_<backend>.txt — IN THE REPO,
# so every number quoted in BENCH_SUITE.md stays traceable to a committed
# raw record (VERDICT r2 missing #4: the /tmp records of the round-2 TPU
# windows evaporated with the host).
set -u
stderr_tmp="$(mktemp /tmp/bench_stderr.XXXXXX)"
trap 'rm -f "$stderr_tmp"' EXIT
cd "$(dirname "$0")/.."
if [ -n "${1:-}" ]; then
  out="$1"
  case "$out" in /*) ;; *) out="$(pwd)/$out" ;; esac
else
  # label the record with the backend that answers the probe (a wedged
  # tunnel means every config will fall back to CPU anyway)
  backend="$(timeout 60 python -c 'import jax; print(jax.default_backend())' \
             2>/dev/null | tail -1)"
  [ -z "$backend" ] && backend="cpu"
  [ "$backend" = "axon" ] && backend="tpu"
  mkdir -p bench/records
  out="$(pwd)/bench/records/$(date -u +%Y%m%dT%H%M%SZ)_${backend}.txt"
fi
: > "$out"
echo "# suite run $(date -Is)" >> "$out"

# per-config obs JSONL archive (SQ_OBS=1 run-scoped observability): the
# spans/ledger/watchdog artifact of every config lands next to the record
# it explains, committed with it — same traceability rule as the record
# itself (VERDICT r2 missing #4).
obs_dir="${out%.txt}_obs"
mkdir -p "$obs_dir"

run_and_record() {  # run_and_record <timeout_s> <header> <cmd...>; returns the cmd's rc
  local tmo=$1
  echo "## $2" >> "$out"
  local slug
  slug="$(printf '%s' "$2" | tr -c 'A-Za-z0-9._-' '_')"
  shift 2
  timeout "$tmo" env SQ_OBS=1 SQ_OBS_PATH="$obs_dir/${slug}.jsonl" \
    "$@" >> "$out" 2>"$stderr_tmp"
  local rc=$?
  # failures keep a full traceback in the record (the temp file is deleted
  # on exit); successes keep the 3-line summary
  local depth=3
  [ "$rc" -ne 0 ] && depth=40
  tail -"$depth" "$stderr_tmp" | sed 's/^/# stderr: /' >> "$out"
  echo "# rc=$rc" >> "$out"
  # a config killed by its timeout is recorded as a machine-readable
  # outcome line instead of silently missing a number ("config"/"outcome"
  # keys only: bench/_gate.py counts lines carrying "metric", so a later
  # successful CPU retry still contributes exactly one gated line)
  if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    echo "{\"config\": \"${slug}\", \"outcome\": \"timeout\", \"timeout_s\": ${tmo}}" >> "$out"
  elif [ "$rc" -ne 0 ]; then
    echo "{\"config\": \"${slug}\", \"outcome\": \"failed\", \"rc\": ${rc}}" >> "$out"
  fi
  # archive the run's resilience records (fault injections, breaker
  # transitions) next to its obs JSONL — same traceability rule: the
  # artifact that explains a degraded number is committed with it
  if grep -aq '"type": "\(fault\|breaker\)"' "$obs_dir/${slug}.jsonl" \
      2>/dev/null; then
    grep -a '"type": "\(fault\|breaker\)"' "$obs_dir/${slug}.jsonl" \
      > "$obs_dir/${slug}_resilience.jsonl"
  fi
  # rendered views of the same artifact, committed next to it: the
  # Perfetto-loadable trace and the human report (PYTHONPATH cleared so
  # the axon sitecustomize never touches a wedged relay; the obs CLIs
  # are file tools and never initialize jax backends)
  if [ -s "$obs_dir/${slug}.jsonl" ]; then
    env -u PYTHONPATH timeout 60 python -m sq_learn_tpu.obs trace \
      "$obs_dir/${slug}.jsonl" -o "$obs_dir/${slug}_trace.json" \
      >/dev/null 2>&1 || true
    env -u PYTHONPATH timeout 60 python -m sq_learn_tpu.obs report \
      "$obs_dir/${slug}.jsonl" > "$obs_dir/${slug}_report.txt" \
      2>/dev/null || true
    # per-tenant error-budget view (serving configs emit `budget`
    # records; non-serving configs archive the empty table) — the burn
    # evidence is committed next to the number it explains, like the
    # resilience extract above
    if grep -aq '"type": "budget"' "$obs_dir/${slug}.jsonl" \
        2>/dev/null; then
      env -u PYTHONPATH timeout 60 python -m sq_learn_tpu.obs budget \
        "$obs_dir/${slug}.jsonl" > "$obs_dir/${slug}_budget.txt" \
        2>/dev/null || true
    fi
    # controller-decision view (serving configs emit `control` records
    # under the PR 17 autotuner) — every plan/degrade/relax that shaped
    # a number is committed next to it
    if grep -aq '"type": "control"' "$obs_dir/${slug}.jsonl" \
        2>/dev/null; then
      env -u PYTHONPATH timeout 60 python -m sq_learn_tpu.obs control \
        "$obs_dir/${slug}.jsonl" > "$obs_dir/${slug}_control.txt" \
        2>/dev/null || true
    fi
    # storage-plane view (v11 `io` records: per-shard heat/latency over
    # the oocore + serving disk surfaces) with the tiering advice — the
    # per-shard evidence behind an out-of-core number is committed next
    # to it
    if grep -aq '"type": "io"' "$obs_dir/${slug}.jsonl" \
        2>/dev/null; then
      env -u PYTHONPATH timeout 60 python -m sq_learn_tpu.obs storage \
        "$obs_dir/${slug}.jsonl" --advise \
        > "$obs_dir/${slug}_storage.txt" 2>/dev/null || true
    fi
  fi
  # compression (PR 17): the per-config JSONL commits gzipped — every
  # obs reader (trace/report/regress/frontier/budget/control) opens
  # .jsonl.gz transparently — and any rendered view over the cap is
  # gzipped in place (Perfetto loads .json.gz directly). PR 16 committed
  # two ~5 MB plain-text artifact sets; the evidence stays committed,
  # just not as megabytes of text. The tiny resilience extract stays
  # plain so `grep` over the records tree keeps working.
  local view_cap=262144
  for view in "$obs_dir/${slug}_trace.json" "$obs_dir/${slug}_report.txt" \
              "$obs_dir/${slug}_budget.txt" "$obs_dir/${slug}_control.txt" \
              "$obs_dir/${slug}_storage.txt"
  do
    if [ -f "$view" ] && [ "$(wc -c < "$view")" -gt "$view_cap" ]; then
      gzip -9 -f "$view"
    fi
  done
  if [ -s "$obs_dir/${slug}.jsonl" ]; then
    gzip -9 -f "$obs_dir/${slug}.jsonl"
  fi
  return $rc
}

# Order: the two configs that fit inside a short healthy-tunnel window run
# first (the headline, then covtype SVD — the one config still missing an
# honest TPU number of record); the heavy 70k×784 uploads (#2/#3) have
# wedged the relay mid-transfer in three separate windows, so they go last
# where a wedge can no longer cost the small configs their numbers.
# First attempts get 600 s (a healthy run finishes well under that; only a
# wedge reaches the timeout); CPU retries keep the conservative 1200 s.
#
# bench_ipe_digits is the one supplementary (non-BASELINE) config in the
# suite: its vs_baseline is a DERIVED serial-cost ratio (tagged
# baseline_kind="derived" in its JSON line), recorded here so the IPE
# surface always has a committed artifact (VERDICT r4 next #2b). It runs
# right after the headline — it's digit-scale (host-routed, seconds) and
# must not be sacrificed to a mid-suite wedge on the heavy configs.
# bench_streaming_ingest runs in smoke mode inside the suite (the full
# 70k×784 acceptance config is a manual run — see BENCH_SUITE.md): it is
# small and must not be sacrificed to a mid-suite wedge, so it rides in
# the small-config-first block right after the headline.
# bench_sharded_scaling is the second supplementary config (VERDICT r5
# weak #5: the one bench surface with zero committed artifacts): on this
# host it runs the 8-virtual-device CPU mesh in smoke mode (simulated:
# true — layout/collective validation, not chip scaling), tagged
# baseline_kind="derived" since its vs_baseline is a scaling ratio, not
# a measured-sklearn ratio. Small config, so it rides in the
# small-config-first block.
# bench_oocore_fit (PR 8) is CPU/disk-only (no accelerator transfers to
# wedge) and runs last; SQ_OOC_BENCH_ARTIFACT_DIR makes it archive the
# shard-store manifest next to its obs JSONL, and the generic
# resilience-record extraction below captures its injected read faults —
# so the committed record stays traceable to the exact shard split and
# fault schedule it measured.
# bench_elastic_fit (PR 18) is likewise CPU/loopback-only (real worker
# processes over localhost gloo — nothing for the relay to wedge) and
# rides at the very end: its kill leg SIGKILLs one of its own workers,
# so any stray process it could leave on a crash must not precede the
# configs that share the machine.
export SQ_OOC_BENCH_ARTIFACT_DIR="$obs_dir"
for cmd in "python bench.py" \
           "python -m bench.bench_ipe_digits" \
           "env SQ_BENCH_SMOKE=1 python -m bench.bench_streaming_ingest" \
           "env SQ_BENCH_SMOKE=1 python -m bench.bench_sharded_scaling" \
           "python -m bench.bench_randomized_svd_covtype" \
           "python -m bench.bench_qkmeans_cicids_sweep" \
           "python -m bench.bench_qpca_mnist" \
           "python -m bench.bench_qkmeans_mnist" \
           "python -m bench.bench_qkmeans_fused_fit" \
           "python -m bench.bench_oocore_fit" \
           "python -m bench.bench_serving_load" \
           "python -m bench.bench_elastic_fit"; do
  if ! run_and_record 600 "$cmd" $cmd; then
    # mid-run tunnel wedge (or any accelerator failure): record the CPU
    # fallback number instead of nothing. PYTHONPATH is cleared so the
    # axon sitecustomize never touches the wedged relay (CLAUDE.md).
    run_and_record 1200 "$cmd [cpu retry]" \
      env -u PYTHONPATH JAX_PLATFORMS=cpu $cmd
  fi
done

# Perf-regression verdicts: every metric line of this fresh record banded
# (latency, compile_count, total_transfer_bytes, peak HBM) against the
# committed BENCH_r*.json trajectory + bench/records history, appended to
# the record as schema-valid "regression" JSON lines. Report-only here
# (--no-exit-code): the suite's pass/fail authority stays with the
# BASELINE acceptance gate below — regression verdicts on a possibly
# CPU-fallback, load-noisy suite run inform the round, they don't kill it.
env -u PYTHONPATH timeout 60 python -m sq_learn_tpu.obs regress "$out" \
  --root . --no-exit-code >> "$out" 2>/dev/null \
  || echo "# regression analyzer unavailable" >> "$out"

# Accuracy-vs-theoretical-runtime frontier: the sweeps' tradeoff records
# (qkmeans cicids δ-sweep; the qpca sweep when run standalone) rendered
# into one committed table next to the obs artifacts that carry them —
# the thesis artifact stays traceable like every other number.
env -u PYTHONPATH timeout 60 python -m sq_learn_tpu.obs frontier \
  "$obs_dir"/*.jsonl* > "$obs_dir/frontier.txt" 2>/dev/null \
  || echo "# (no tradeoff records this run)" >> "$obs_dir/frontier.txt"

# Fleet timeline (PR 19): the elastic bench copies its kill run's
# per-process obs shards (coordinator + every worker, incl. the
# SIGKILLed one) and the merged clock-aligned timeline into the
# artifact dir; render the fleet view (per-host rollups, shrink
# critical path, commit-ledger reconciliation) next to them, then put
# the shards on the same gzip diet as every per-config JSONL — every
# fleet reader opens .jsonl.gz transparently.
if ls "$obs_dir"/elastic_obs.*.jsonl >/dev/null 2>&1; then
  env -u PYTHONPATH timeout 60 python -m sq_learn_tpu.obs fleet \
    "$obs_dir"/elastic_obs.*.jsonl > "$obs_dir/elastic_fleet.txt" \
    2>/dev/null || true
  gzip -9 -f "$obs_dir"/elastic_obs.*.jsonl
fi
if [ -s "$obs_dir/elastic_fleet_merged.jsonl" ]; then
  gzip -9 -f "$obs_dir/elastic_fleet_merged.jsonl"
fi

# BASELINE acceptance gate (bench/_gate.py: vs_baseline >= 0.5 on every
# line, 16 measured + 2 derived lines expected — the sixth measured line
# is the streaming-ingest smoke config, whose baseline is the monolithic
# ingest of the same fit; the seventh is the PR 6 fused-fit config
# (classical 70k×784 q-means vs sklearn on the SAME δ=0 configuration);
# the eighth is the PR 8 out-of-core config, whose baseline is the
# in-RAM fit of the same store — vs_baseline >= 0.5 reads "fitting from
# disk under a RAM budget costs at most 2x residency";
# the ninth and tenth are the PR 13 compressed-store pair out of the
# same bench (bytes-on-disk ratio of the pixel-kind store, vs_baseline
# = raw/stored with floor 1.4 ⇔ ratio ≤ 0.7; and the cold-tier fit
# pair, vs_baseline = uncompressed/compressed under the same injected
# tier profile with floor 0.95 — fewer bytes ⇔ less tier time);
# the eleventh through fourteenth are the PR 9/11 serving load bench's
# quad (sustained micro-batched QPS vs the sequential per-request arm,
# p99 vs the same, the AOT-warmed cold-start-p99 ratio vs the unwarmed
# arm — its own floor is 5.0 via the vs_baseline regression gate — and
# the bf16 bytes ratio vs the f32 arm, floor 1.8 ⇔ "quantized moves
# ≤ 0.55× the bytes"); the fifteenth is the PR 16 megabatch line from
# the same bench (the 12k mix spread over 48 same-fingerprint alias
# tenants, native+megabatch arm QPS vs the tenant-scoped PR 11 arm,
# floor 1.5 via the vs_baseline regression gate); the sixteenth is the
# PR 17 autotune cost line from the same bench (summed theoretical
# quantum cost of the controller-tuned tenant set vs the statically
# declared set, floor 1.2 via the vs_baseline regression gate — emitted
# only under SQ_OBS=1, which this suite always sets);
# the seventeenth is the PR 18 elastic-mesh line (total wall-clock of a
# real 3-worker fit that loses a worker to SIGKILL mid-epoch and
# shrink-resumes, vs the measured naive-restart pair — dead run + full
# 2-worker rerun — floor 0.6 via the vs_baseline regression gate, bit
# parity and the fold ledger asserted in-bench);
# the derived pair is bench_ipe_digits and the
# sharded-scaling smoke config; missing/null = fail). This
# script is where the bar is enforced — the unit suite only warns, since
# wall-clock there is subject to arbitrary host load.
# (PYTHONPATH cleared + timeout, like the retry path: the bare interpreter
# pre-imports jax via the axon sitecustomize and would hang on a wedged
# relay even though this step only parses JSON; -m bench._gate resolves
# via cwd, which is the repo root here)
env -u PYTHONPATH timeout 60 python -m bench._gate "$out" 17 2
gate_rc=$?
echo "# acceptance gate rc=$gate_rc" >> "$out"
echo "done: $out"
exit $gate_rc
