"""qPCA ε+δ accuracy-vs-runtime sweep — the thesis surface for the second
estimator (VERDICT r3 next #7), recorded the way the reference's own MNIST
experiment frames it (``MnistTrial.py:10-28``: classical fit, exact
tomography applied to the transformed representation at total error ε+δ,
downstream stratified-CV KNN accuracy + F-norm deviation).

Three legs, one record; the headline is the leg where the dial can
actually move (VERDICT r4 next #3):

- **cicids leg** (HEADLINE; low-margin graded near-duplicate classes
  through the qPCA→KNN pipeline): JSON line = KNN CV accuracy at the
  reference's published ε+δ=0.8 point, ``vs_baseline`` = ratio against
  the zero-error classical-transform accuracy. Accuracy degrades
  monotonically with ε+δ while F-norm error grows — a headline that is
  structurally able to vary.
- **mnist-low-margin leg** (``load_mnist_surrogate_low_margin``): the
  MnistTrial pipeline shape (784-d, 10 classes, n_components=61, k=7
  KNN) with graded pair margins *inside* the achievable tomography noise
  band, so the MNIST-shaped leg bends too.
- **mnist-faithful leg** (the ``load_mnist`` surrogate, the reference's
  exact configuration): structurally flat offline — the synthetic
  classes' angular margins exceed the largest error the reference's
  tomography model can produce (N=36·d·ln d/δ² keeps relative noise
  ≤ ~21 % even at ε+δ=3.2), which the extras record as
  ``surrogate_margin_caveat``. Kept as the fidelity control; on real
  MNIST the margins are small and this leg would bend.

Since PR 5 the cicids and low-margin legs additionally price every sweep
point with the framework's own QADRA runtime accountant
(``QPCA.accumulate_q_runtime`` at ε = δ = (ε+δ)/2, evaluated at the
leg's full shape — VERDICT r5 weak #2: the cost models finally gain a
non-test caller) and, under ``SQ_OBS=1``, land as schema-valid
``tradeoff`` records for ``python -m sq_learn_tpu.obs frontier``.

Not a BASELINE config — supplementary surface, like bench_ipe_digits
(which runs inside run_suite.sh; this script is recorded standalone).
"""

import sys
import time
import warnings

import numpy as np

warnings.filterwarnings("ignore")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import emit, probe_backend, smoke_mode  # noqa: E402

ERRORS = (0.2, 0.8, 1.6, 3.2)


def _qada_runtime(X, n_components, errors):
    """Theoretical QADRA extraction runtime per sweep point, from the
    framework's own accountant: a QADRA-flagged twin fit on a ≤1024-row
    subsample (θ at the median retained σ so the top-k selection is
    deterministic), then ``accumulate_q_runtime`` at ε = δ = err/2,
    evaluated at the LEG's full (n, m). Returns {err: runtime | None}.
    """
    import numpy as np

    from sq_learn_tpu.models import QPCA

    sub = np.asarray(X[: min(1024, len(X))])
    probe = QPCA(n_components=n_components, svd_solver="full",
                 random_state=0).fit(sub)
    theta = float(np.median(probe.singular_values_))
    n, m = X.shape
    out = {}
    for err in errors:
        q = QPCA(n_components=n_components, svd_solver="full",
                 random_state=0)
        q.fit(sub, estimate_all=True, theta_major=theta, eps=err / 2,
              delta=err / 2, true_tomography=False)
        cost = q.accumulate_q_runtime(n, m)
        val = float(np.sum([np.asarray(c, float) for c in cost])) \
            if cost else None
        out[err] = val if val is not None and np.isfinite(val) else None
    return out


def _record_tradeoffs(sweep_name, curve, q_runtime, n, m, n_components):
    """One ``tradeoff`` record per sweep point (no-op without SQ_OBS):
    measured KNN accuracy vs the theoretical runtime the budget buys,
    plus the transform-side tomography shot count from the ledger model.
    """
    from sq_learn_tpu.obs import frontier, ledger

    for err, pt in curve.items():
        frontier.record_tradeoff(
            sweep_name, err, accuracy=pt["knn_acc"],
            accuracy_metric="knn_cv_acc", q_runtime=q_runtime.get(err),
            c_runtime=float(n) * float(m) ** 2, wall_s=pt["transform_s"],
            budget={"eps": err / 2, "delta": err / 2},
            estimator="qpca", n=int(n), m=int(m),
            transform_shots=ledger.tomography_shot_count(
                n, n_components, err))


def _sweep(pca, X, y, folds):
    """{ε+δ: accuracy, F-norm error, transform s} + the classical acc."""
    from sq_learn_tpu.model_selection import StratifiedKFold, cross_validate
    from sq_learn_tpu.models import KNeighborsClassifier

    def knn_cv(Z):
        res = cross_validate(
            KNeighborsClassifier(n_neighbors=7), Z, y,
            cv=StratifiedKFold(folds))
        return float(np.mean(res["test_score"]))

    acc_classical = knn_cv(pca.transform(X))
    curve = {}
    for err in ERRORS:
        t0 = time.perf_counter()
        out = pca.transform(
            X, classic_transform=False, epsilon_delta=err,
            quantum_representation=True, norm="est_representation",
            true_tomography=True)
        t_tr = time.perf_counter() - t0
        Xq, _, f_norm = out["quantum_representation_results"]
        curve[err] = {"knn_acc": round(knn_cv(Xq), 4),
                      "f_norm_err": round(float(f_norm), 2),
                      "transform_s": round(t_tr, 3)}
    return acc_classical, curve


def main():
    probe_backend()
    import jax

    from sq_learn_tpu.datasets import (load_cicids, load_mnist,
                                       load_mnist_surrogate_low_margin)
    from sq_learn_tpu.models import QPCA
    from sq_learn_tpu.preprocessing import StandardScaler

    n_rows, folds = (2_000, 3) if smoke_mode() else (10_000, 5)

    # cicids leg (headline) — low angular margins, the dial visibly bends
    Xc_, yc_, real_c = load_cicids(n_samples=max(4_000, n_rows // 2))
    Xc_ = StandardScaler().fit_transform(Xc_).astype(np.float32)
    pca_c = QPCA(n_components=10, svd_solver="full", random_state=0).fit(Xc_)
    acc_c_cicids, cicids_curve = _sweep(pca_c, Xc_, yc_, folds)
    qrt_cicids = _qada_runtime(Xc_, 10, ERRORS)
    for err in ERRORS:
        cicids_curve[err]["q_runtime"] = qrt_cicids[err]
    _record_tradeoffs("qpca_cicids_eps_delta", cicids_curve, qrt_cicids,
                      *Xc_.shape, 10)

    # mnist-low-margin leg — the MnistTrial shape with margins inside the
    # tomography noise band (the pair grades are tuned in the loader)
    Xlm, ylm = load_mnist_surrogate_low_margin(n_rows)
    pca_lm = QPCA(n_components=61, svd_solver="full", random_state=0).fit(Xlm)
    acc_c_lm, lm_curve = _sweep(pca_lm, Xlm, ylm, folds)
    qrt_lm = _qada_runtime(Xlm, 61, ERRORS)
    for err in ERRORS:
        lm_curve[err]["q_runtime"] = qrt_lm[err]
    _record_tradeoffs("qpca_mnist_low_margin_eps_delta", lm_curve, qrt_lm,
                      *Xlm.shape, 61)

    # mnist-faithful leg — the reference's exact experiment shape
    # (fidelity control; flat offline, see module docstring)
    X, y, real = load_mnist()
    X, y = X[:n_rows], y[:n_rows]
    t0 = time.perf_counter()
    pca = QPCA(n_components=61, svd_solver="full", random_state=0).fit(X)
    t_fit = time.perf_counter() - t0
    acc_c_mnist, mnist_curve = _sweep(pca, X, y, folds)

    headline = cicids_curve[0.8]["knn_acc"]
    # rows are reported PER LEG (the legs differ: the cicids headline leg
    # runs on max(4000, n_rows//2) rows, the mnist-shaped legs on n_rows)
    emit("qpca_cicids_eps_delta_sweep_knn_acc_at_0.8", headline,
         unit="accuracy", vs_baseline=headline / acc_c_cicids,
         backend=jax.default_backend(), folds=folds,
         headline_rows=int(Xc_.shape[0]),
         cicids={"classical_knn_acc": round(acc_c_cicids, 4),
                 "rows": int(Xc_.shape[0]),
                 "real": real_c, "sweep": cicids_curve},
         mnist_low_margin={"classical_knn_acc": round(acc_c_lm, 4),
                           "rows": int(Xlm.shape[0]),
                           "real": False, "sweep": lm_curve},
         mnist_faithful={"classical_knn_acc": round(acc_c_mnist, 4),
                         "rows": int(X.shape[0]),
                         "fit_s": round(t_fit, 3), "real": real,
                         "sweep": mnist_curve},
         surrogate_margin_caveat=(
             None if real else
             "the faithful-geometry MNIST surrogate's classes are "
             "angularly separated beyond tomography's achievable noise "
             "(direction-only KNN scores 1.0 on clean data), so that "
             "leg's accuracy stays flat; the cicids headline and the "
             "low-margin MNIST-shaped leg show the dial bending"))


if __name__ == "__main__":
    main()
