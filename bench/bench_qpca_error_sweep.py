"""qPCA ε+δ accuracy-vs-runtime sweep — the thesis surface for the second
estimator (VERDICT r3 next #7), recorded the way the reference's own MNIST
experiment frames it (``MnistTrial.py:10-28``: classical fit, exact
tomography applied to the transformed representation at total error ε+δ,
downstream stratified-CV KNN accuracy + F-norm deviation).

Two legs, one record:

- **mnist leg** (the reference's exact configuration, n_components=61,
  k=7 KNN): headline JSON line = KNN CV accuracy at the reference's
  published ε+δ=0.8 point, ``vs_baseline`` = ratio against the zero-error
  classical-transform accuracy. On the offline surrogate this curve is
  structurally flat: the synthetic classes' angular margins exceed the
  largest error the reference's tomography model can produce (sample
  complexity N=36·d·ln d/δ² floors the achievable noise at ~20-50 %
  relative even as δ→∞), which the extras record as
  ``surrogate_margin_caveat`` — on real MNIST the margins are small and
  the curve bends.
- **cicids leg** (low-margin graded near-duplicate classes through the
  same qPCA→KNN pipeline): demonstrates the dial actually bending —
  accuracy degrades monotonically with ε+δ while F-norm error grows.

Not a BASELINE config — supplementary surface, like bench_ipe_digits.
"""

import sys
import time
import warnings

import numpy as np

warnings.filterwarnings("ignore")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import emit, probe_backend, smoke_mode  # noqa: E402

ERRORS = (0.2, 0.8, 1.6, 3.2)


def _sweep(pca, X, y, folds):
    """{ε+δ: accuracy, F-norm error, transform s} + the classical acc."""
    from sq_learn_tpu.model_selection import StratifiedKFold, cross_validate
    from sq_learn_tpu.models import KNeighborsClassifier

    def knn_cv(Z):
        res = cross_validate(
            KNeighborsClassifier(n_neighbors=7), Z, y,
            cv=StratifiedKFold(folds))
        return float(np.mean(res["test_score"]))

    acc_classical = knn_cv(pca.transform(X))
    curve = {}
    for err in ERRORS:
        t0 = time.perf_counter()
        out = pca.transform(
            X, classic_transform=False, epsilon_delta=err,
            quantum_representation=True, norm="est_representation",
            true_tomography=True)
        t_tr = time.perf_counter() - t0
        Xq, _, f_norm = out["quantum_representation_results"]
        curve[err] = {"knn_acc": round(knn_cv(Xq), 4),
                      "f_norm_err": round(float(f_norm), 2),
                      "transform_s": round(t_tr, 3)}
    return acc_classical, curve


def main():
    probe_backend()
    import jax

    from sq_learn_tpu.datasets import load_cicids, load_mnist
    from sq_learn_tpu.models import QPCA
    from sq_learn_tpu.preprocessing import StandardScaler

    n_rows, folds = (2_000, 3) if smoke_mode() else (10_000, 5)

    # mnist leg — the reference's exact experiment shape
    X, y, real = load_mnist()
    X, y = X[:n_rows], y[:n_rows]
    t0 = time.perf_counter()
    pca = QPCA(n_components=61, svd_solver="full", random_state=0).fit(X)
    t_fit = time.perf_counter() - t0
    acc_c_mnist, mnist_curve = _sweep(pca, X, y, folds)

    # cicids leg — low angular margins, where the dial visibly bends
    Xc_, yc_, real_c = load_cicids(n_samples=max(4_000, n_rows // 2))
    Xc_ = StandardScaler().fit_transform(Xc_).astype(np.float32)
    pca_c = QPCA(n_components=10, svd_solver="full", random_state=0).fit(Xc_)
    acc_c_cicids, cicids_curve = _sweep(pca_c, Xc_, yc_, folds)

    headline = mnist_curve[0.8]["knn_acc"]
    emit("qpca_mnist_eps_delta_sweep_knn_acc_at_0.8", headline,
         unit="accuracy", vs_baseline=headline / acc_c_mnist,
         backend=jax.default_backend(), rows=n_rows, folds=folds,
         mnist={"classical_knn_acc": round(acc_c_mnist, 4),
                "fit_s": round(t_fit, 3), "real": real,
                "sweep": mnist_curve},
         cicids={"classical_knn_acc": round(acc_c_cicids, 4),
                 "real": real_c, "sweep": cicids_curve},
         surrogate_margin_caveat=(
             None if real else
             "synthetic MNIST surrogate classes are angularly separated "
             "beyond tomography's achievable noise (direction-only KNN "
             "scores 1.0 on clean data), so the mnist-leg accuracy stays "
             "flat; the cicids leg shows the dial bending"))


if __name__ == "__main__":
    main()
