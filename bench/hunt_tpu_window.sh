#!/bin/bash
# Round-long automated TPU-window hunter (VERDICT r3 next-round #1).
#
# The axon tunnel wedges for hours and opens in ~7-20 min healthy
# windows at unpredictable times; a human-in-the-loop "try the runbook
# when you remember" cadence missed every window in round 3. This loop
# makes the attempt record automatic: a cheap 60 s subprocess probe
# every ~4 min, the full runbook (bench/run_tpu_window.sh) fired the
# moment a probe answers, and EVERY attempt — wedged probes included —
# appended to bench/records/window_hunt_<round>.log (HUNT_ROUND, default
# r05) so the hunt itself is committable evidence even if no window ever
# opens.
#
# Deliberately does NOT git-commit: the foreground session owns the
# index; it watches the log and .window_landed marker instead.
#
#   HUNT_INTERVAL_S  sleep between wedged probes (default 240)
#   HUNT_MAX_S       total hunt lifetime (default 39600 = 11 h, so the
#                    process exits before the round driver does)
set -u
cd "$(dirname "$0")/.."
round="${HUNT_ROUND:-r05}"
log="bench/records/window_hunt_${round}.log"
mkdir -p bench/records
probe_out="$(mktemp /tmp/hunt_probe.XXXXXX)"
trap 'rm -f "$probe_out"' EXIT
interval="${HUNT_INTERVAL_S:-240}"
max_s="${HUNT_MAX_S:-39600}"
start=$SECONDS
echo "$(date -u +%Y%m%dT%H%M%SZ) HUNT-START interval=${interval}s max=${max_s}s" >> "$log"
while [ $((SECONDS - start)) -lt "$max_s" ]; do
  ts="$(date -u +%Y%m%dT%H%M%SZ)"
  if timeout 60 python -c "import jax; print(jax.devices())" \
       > "$probe_out" 2>&1; then
    echo "$ts PROBE-OK $(tr '\n' ' ' < "$probe_out" | tail -c 200)" >> "$log"
    echo "$ts WINDOW-START" >> "$log"
    bash bench/run_tpu_window.sh >> "$log" 2>&1
    rc=$?
    echo "$(date -u +%Y%m%dT%H%M%SZ) WINDOW-END rc=$rc" >> "$log"
    # marker = "a runbook run actually banked records" — an rc!=0 abort
    # (tunnel wedged between probe and smoke) leaves nothing to commit
    [ "$rc" -eq 0 ] && date -u +%Y%m%dT%H%M%SZ > bench/records/.window_landed
    # a window just ran (or aborted mid-wedge); cool off before
    # re-probing so back-to-back runbook fires don't duplicate records
    sleep 600
  else
    # keep the probe's tail: a broken-env failure (ImportError, plugin
    # error) must stay distinguishable from a genuinely wedged tunnel in
    # the committed hunt log
    echo "$ts PROBE-WEDGED $(tr '\n' ' ' < "$probe_out" | tail -c 160)" >> "$log"
    sleep "$interval"
  fi
done
echo "$(date -u +%Y%m%dT%H%M%SZ) HUNT-END" >> "$log"
