"""Shared benchmark plumbing: timing, JSON-line emission, warm-up."""

import json
import sys
import time


def timed(fn, *args, warmup=1, reps=1, **kwargs):
    """Run ``fn`` with ``warmup`` discarded calls (compile amortization),
    return (best wall-clock of ``reps``, last result)."""
    result = None
    for _ in range(warmup):
        result = fn(*args, **kwargs)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        result = fn(*args, **kwargs)
        best = min(best, time.perf_counter() - t0)
    return best, result


def emit(metric, value, unit="s", vs_baseline=1.0, **extra):
    """Print the ONE machine-readable JSON line (extras go to stderr)."""
    if extra:
        print("# " + json.dumps(extra), file=sys.stderr)
    print(json.dumps({
        "metric": metric,
        "value": round(float(value), 4),
        "unit": unit,
        "vs_baseline": round(float(vs_baseline), 3),
    }))


def smoke_mode():
    """True when invoked with --smoke or SQ_BENCH_SMOKE=1: scripts subsample
    their dataset so the full code path can be validated quickly."""
    import os

    return "--smoke" in sys.argv or os.environ.get("SQ_BENCH_SMOKE") == "1"


def maybe_subsample(X, y=None, n=4000, seed=0):
    """Subsample rows in smoke mode; pass through otherwise."""
    if not smoke_mode() or X.shape[0] <= n:
        return (X, y) if y is not None else X
    import numpy as _np

    idx = _np.random.default_rng(seed).choice(X.shape[0], n, replace=False)
    return (X[idx], y[idx]) if y is not None else X[idx]
