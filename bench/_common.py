"""Shared benchmark plumbing: timing, JSON-line emission, warm-up."""

import json
import sys
import time


def timed(fn, *args, warmup=1, reps=1, **kwargs):
    """Run ``fn`` with ``warmup`` discarded calls (compile amortization),
    return (best wall-clock of ``reps``, last result)."""
    result = None
    for _ in range(warmup):
        result = fn(*args, **kwargs)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        result = fn(*args, **kwargs)
        best = min(best, time.perf_counter() - t0)
    return best, result


def obs_snapshot():
    """The active obs run's summary (compile_count, total_transfer_bytes,
    probe_ms, ...), or None when observability is off — import-safe even
    if sq_learn_tpu is broken (a bench must still print its line)."""
    try:
        from sq_learn_tpu import obs

        return obs.snapshot()
    except Exception:
        return None


def emit(metric, value, unit="s", vs_baseline=1.0, baseline_kind=None,
         vs_baseline_floor=None, **extra):
    """Print the ONE machine-readable JSON line (extras go to stderr).

    ``vs_baseline=None`` means "no baseline was measured" and is emitted
    as JSON null — run_suite.sh's acceptance gate counts that as a MISS,
    so a failed baseline can never silently pass as a 1.0 ratio.

    ``vs_baseline_floor`` also rides IN the JSON line: it is the bench's
    own declared contract ("this ratio may never drop below X") and the
    regression gate (`obs/regress.py`) bands ``vs_baseline`` against it
    as the history-free lower-bounded ``vs_baseline`` gate — a floor in
    the stderr extras would be invisible to every record consumer.

    ``baseline_kind`` rides IN the JSON line (not the stderr extras)
    because cross-record consumers parse only the line: the suite-wide
    convention is a measured-wall-clock ratio, and a script whose
    vs_baseline is on a different scale (e.g. bench_ipe_digits' derived
    serial-cost ratio, order 1e4-1e5) must be distinguishable without
    reading its docstring. None (the default) = measured, and the key is
    omitted to keep the driver's headline line schema untouched.

    With ``SQ_OBS=1`` the line gains an ``obs`` object (compile_count,
    total_transfer_bytes, probe_ms, ...) so bench records track
    observability regressions alongside latency; with observability off
    the schema is byte-identical to pre-obs records."""
    if extra:
        print("# " + json.dumps(extra), file=sys.stderr)
    line = {
        "metric": metric,
        "value": round(float(value), 4),
        "unit": unit,
        "vs_baseline": (None if vs_baseline is None
                        else round(float(vs_baseline), 3)),
    }
    if baseline_kind is not None:
        line["baseline_kind"] = baseline_kind
    if vs_baseline_floor is not None:
        line["vs_baseline_floor"] = float(vs_baseline_floor)
    snap = obs_snapshot()
    if snap is not None:
        line["obs"] = snap
    print(json.dumps(line))


def _enable_compilation_cache():
    """Persist XLA compilations across processes for ACCELERATOR runs:
    healthy tunnel windows are ~7 minutes and scarce, so compiles from
    one window must carry into the next instead of re-lowering the same
    fits. CPU-backend runs never enable it — a persisted CPU executable
    embeds host-specific AOT code, and cross-process reloads emit
    multi-KB machine-feature-mismatch spam (cpu_aot_loader.cc, SIGILL
    warnings) that would pollute the stderr tails run_suite.sh commits
    into bench records (and risk real SIGILL after a host rotation).
    Best-effort — an old jax without the knobs just compiles as before.

    Called only once the caller KNOWS an accelerator is reachable (after
    the subprocess probe): asking jax itself would initialize the
    backend, which is exactly the hang the probe exists to avoid."""
    import os

    try:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("JAX_COMPILATION_CACHE_DIR",
                           "/tmp/sq_jax_compile_cache"))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass


def probe_backend(timeout_s=60):
    """Initialize the configured JAX backend in a throwaway subprocess and
    fall back to the CPU backend when the accelerator tunnel is wedged
    (same contract as the headline bench.py).

    The probe itself (subprocess + timeout + latency/outcome accounting)
    lives in :mod:`sq_learn_tpu.obs.probe` — the one implementation of
    the known axon-wedge escape — so every bench run records probe
    latency and outcome as metrics when ``SQ_OBS=1``. Results are cached
    for ``SQ_PROBE_TTL_S`` (default 300 s) across processes, so the
    suite's back-to-back configs share one real probe instead of each
    paying the ~5-15 s subprocess; probe outcomes also feed the transfer
    circuit breaker (:mod:`sq_learn_tpu.resilience.supervisor`).

    60 s default: a healthy tunnel answers the probe in ~5-15 s; a wedged
    one never answers, so the timeout is pure stall — every observed
    wedge lasted hours, making longer patience pointless."""
    import os

    from sq_learn_tpu.obs.probe import probe_device

    platform = os.environ.get("JAX_PLATFORMS", "")
    if platform == "cpu":
        # the env var alone is NOT sufficient when a sitecustomize
        # pre-imported jax against a wedged accelerator relay: backend init
        # can still hang. The config update is the reliable override.
        import jax

        jax.config.update("jax_platforms", "cpu")
        probe_device(platform=platform)  # records the 'cpu' outcome
        return
    if platform == "":
        probe_device(platform=platform)  # records the 'skipped' outcome
        return
    result = probe_device(timeout_s=timeout_s, platform=platform)
    if result["outcome"] == "ok":
        # accelerator reachable: persist its compiles across processes
        _enable_compilation_cache()
    else:
        print(f"# backend {platform!r} unreachable ({result['outcome']}, "
              f"{result['latency_s']:.1f}s); falling back to CPU",
              file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")


def smoke_mode():
    """True when invoked with --smoke or SQ_BENCH_SMOKE=1: scripts subsample
    their dataset so the full code path can be validated quickly."""
    import os

    return "--smoke" in sys.argv or os.environ.get("SQ_BENCH_SMOKE") == "1"


def maybe_subsample(X, y=None, n=4000, seed=0):
    """Subsample rows in smoke mode; pass through otherwise."""
    if not smoke_mode() or X.shape[0] <= n:
        return (X, y) if y is not None else X
    import numpy as _np

    idx = _np.random.default_rng(seed).choice(X.shape[0], n, replace=False)
    return (X[idx], y[idx]) if y is not None else X[idx]
