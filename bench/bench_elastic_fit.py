"""Elastic-mesh fit bench (ISSUE 18): what does surviving a host death
mid-fit actually cost?

Two REAL multi-process runs of the same window-synchronous fold plan
(separate worker processes, gloo collectives, coordinator-hosted KV
service — the ``make elastic-smoke`` flow, sized up):

- **kill leg** (the JSON line's value): a 3-worker fit whose worker 2
  is SIGKILLed mid-epoch by the coordinator once committed progress
  passes the kill cursor. The survivors must detect the death through
  the lease layer, shrink to a generation-1 2-host world, resume from
  the committed checkpoint, and finish — the value is that run's TOTAL
  wall-clock including detection, shrink and the recomputed voided
  window.
- **naive-restart baseline**: the same kill with the shrink budget at
  zero — the fit dies (:class:`HostFailure`, every joule of pre-death
  work wasted) — plus a fresh uninterrupted 2-worker run from epoch 0
  (``t2``). That sum is what the death costs WITHOUT elasticity: both
  terms are measured wall-clocks of real multi-process runs, nothing
  modeled.

``vs_baseline = (t_dead + t2) / t3k`` therefore reads "shrink-and-
resume recovers a host death at most 1/vs_baseline× the cost of
restarting from scratch". At this deliberately small scale the two
are near break-even (the shrink pays fixed detection ~2×``lease_s`` +
world re-form against the few seconds of salvaged work); every larger
fit moves the ratio up, since the salvaged work grows linearly while
the shrink overhead is lease-bounded and constant. The declared
``vs_baseline_floor`` of 0.6 guards exactly that fixed overhead; the
extras carry the full decomposition — ``uninterrupted_2host_s``,
``dead_run_s``, per-survivor detection latency and shrink wall-clock
mined from the run's schema-v10 ``elastic`` records via
:func:`~sq_learn_tpu.parallel.elastic.collect_elastic_records` — so
the record shows where every second of the recovery went. The kill
run's per-process obs shards are additionally merged into ONE
clock-aligned fleet timeline (:mod:`sq_learn_tpu.obs.fleet`): the
extras gain the generation-1 detect→shrink→re-init→resume critical
path and the commit-ledger reconciliation verdict, and when
``SQ_OOC_BENCH_ARTIFACT_DIR`` is set (the suite sets it) the merged
timeline lands there as ``elastic_fleet_merged.jsonl`` next to the
per-host shards.

Bit parity is asserted in-bench, not just claimed: both real runs must
equal the in-process :func:`elastic_fit_local` reference (the
topology-invariance contract), and the killed run's per-shard fold
ledger must show every shard folded exactly ``epochs`` times — a bench
that times a wrong answer fails instead of emitting.

SQ_BENCH_SMOKE=1 shrinks the store to the smoke scale (seconds) while
keeping every leg, including the real SIGKILL.
"""

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import emit, smoke_mode  # noqa: E402


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from sq_learn_tpu.oocore import create_synthetic_store
    from sq_learn_tpu.parallel import elastic

    smoke = smoke_mode()
    if smoke:
        n, m, k = 2_400, 8, 4
        shard_bytes, epochs, window = 8 * 8 * 120, 2, 4
        heartbeat_s, lease_s = 0.2, 1.0
    else:
        n, m, k = 200_000, 128, 16
        shard_bytes, epochs, window = 128 * 4 * 4000, 2, 4
        heartbeat_s, lease_s = 0.2, 1.0
    seed = 5

    tmp = tempfile.mkdtemp(prefix="sq_elastic_bench_")
    try:
        store_path = os.path.join(tmp, "store")
        store = create_synthetic_store(store_path, n, m, n_classes=k,
                                       seed=0, shard_bytes=shard_bytes)
        n_shards = int(store.n_shards)

        # in-process topology-invariant reference (numpy-only, fast)
        ref = elastic.elastic_fit_local(store, k, n_hosts=2, seed=seed,
                                        epochs=epochs, window=window)

        common = dict(n_clusters=k, seed=seed, epochs=epochs,
                      window=window, devices_per_host=2,
                      heartbeat_s=heartbeat_s, lease_s=lease_s)

        # -- baseline leg: uninterrupted 2-worker world ------------------
        co2 = elastic.ElasticCoordinator(
            os.path.join(tmp, "run2"), store_path, n_workers=2, **common)
        t0 = time.perf_counter()
        r2 = co2.run(timeout_s=600)
        t2 = time.perf_counter() - t0

        # -- kill leg: 3 workers, one SIGKILLed mid-epoch ----------------
        run3 = os.path.join(tmp, "run3")
        co3 = elastic.ElasticCoordinator(
            run3, store_path, n_workers=3,
            kill=(2, 2 * window), **common)
        t0 = time.perf_counter()
        r3 = co3.run(timeout_s=600)
        t3k = time.perf_counter() - t0

        # -- naive-restart baseline: same kill, zero shrink budget -------
        cof = elastic.ElasticCoordinator(
            os.path.join(tmp, "run3f"), store_path, n_workers=3,
            kill=(2, 2 * window), max_shrinks=0, **common)
        t0 = time.perf_counter()
        try:
            cof.run(timeout_s=600)
            print(json.dumps({"error": "budget-0 kill run did not die"}),
                  file=sys.stderr)
            return 1
        except elastic.HostFailure:
            t_dead = time.perf_counter() - t0
        naive_s = t_dead + t2

        parity2 = bool(np.array_equal(r2["centers"], ref["centers"])
                       and np.array_equal(r2["counts"], ref["counts"]))
        parity3 = bool(np.array_equal(r3["centers"], ref["centers"])
                       and np.array_equal(r3["counts"], ref["counts"]))
        ledger_ok = bool((r3["folds"] == epochs).all())
        shrink_ok = (r3["generation"] == 1 and r3["n_hosts"] == 2
                     and r3["shrinks"] == 1
                     and r3["exit_codes"].get(2) == -9)

        recs = elastic.collect_elastic_records(run3)
        detect = [r["detect_s"] for r in recs
                  if r["event"] == "host_fail" and "detect_s" in r]
        shrink = [r["shrink_s"] for r in recs
                  if r["event"] == "world_up" and r["generation"] == 1
                  and "shrink_s" in r]

        # one mesh-wide fleet timeline: critical path + commit ledger
        from sq_learn_tpu.obs import fleet

        shards = fleet.load_shards(run3)
        fsum = fleet.summarize(shards)
        cp1 = [p for p in fsum["critical_path"] if p["generation"] == 1]
        recon = fsum["reconciliation"]
        art_dir = os.environ.get("SQ_OOC_BENCH_ARTIFACT_DIR")
        if art_dir:
            os.makedirs(art_dir, exist_ok=True)
            for fname in sorted(os.listdir(run3)):
                if fname.startswith("obs.") and fname.endswith(".jsonl"):
                    shutil.copy2(os.path.join(run3, fname),
                                 os.path.join(art_dir, f"elastic_{fname}"))
            fleet.write_merged(
                shards, os.path.join(art_dir, "elastic_fleet_merged.jsonl"))

        emit(f"elastic_fit_{n // 1000}kx{m}_k{k}_kill_resume_wallclock",
             t3k, vs_baseline=(naive_s / t3k), vs_baseline_floor=0.6,
             naive_restart_s=round(naive_s, 3),
             dead_run_s=round(t_dead, 3),
             uninterrupted_2host_s=round(t2, 3),
             death_overhead_s=round(t3k - t2, 3),
             detect_s=[round(d, 3) for d in detect],
             shrink_s=[round(s, 3) for s in shrink],
             lease_s=lease_s, heartbeat_s=heartbeat_s,
             epochs=epochs, window=window, n_shards=n_shards,
             generation=int(r3["generation"]),
             n_hosts_final=int(r3["n_hosts"]),
             parity_uninterrupted=parity2, parity_killed=parity3,
             fold_ledger_ok=ledger_ok,
             fleet_run_id=(fsum["run_ids"][0] if fsum["run_ids"]
                           else None),
             fleet_hosts=sorted(fsum["hosts"]),
             critical_path_gen1=(cp1[0] if cp1 else None),
             commit_reconciliation_ok=bool(recon["ok"]),
             committed_windows=int(recon["windows"]), smoke=smoke)

        errors = []
        if not parity2:
            errors.append("uninterrupted run diverges from the reference")
        if not parity3:
            errors.append("killed run diverges from the reference "
                          "(bit parity broken)")
        if not ledger_ok:
            errors.append(f"shards lost or double-folded: "
                          f"{r3['folds'].tolist()}")
        if not shrink_ok:
            errors.append(f"kill leg did not shrink 3->2 exactly once: "
                          f"gen={r3['generation']} n={r3['n_hosts']} "
                          f"shrinks={r3['shrinks']} "
                          f"exits={r3['exit_codes']}")
        if not detect or not all(d > 0 for d in detect):
            errors.append(f"no positive detection latency: {detect}")
        if not shrink or not all(s > 0 for s in shrink):
            errors.append(f"no positive shrink wall-clock: {shrink}")
        n_windows = epochs * (-(-n_shards // window))
        if not recon["ok"] or recon["windows"] != n_windows:
            errors.append(f"fleet commit-ledger reconciliation broken "
                          f"(want {n_windows} windows): {recon}")
        if not cp1 or not isinstance(cp1[0].get("total_s"), (int, float)) \
                or cp1[0]["total_s"] <= 0:
            errors.append(f"no generation-1 fleet critical path: "
                          f"{fsum['critical_path']}")
        if errors:
            print(json.dumps({"error": "; ".join(errors)}),
                  file=sys.stderr)
            return 1
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
