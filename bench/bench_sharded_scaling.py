"""Mesh-scaling benchmark: the sharded q-means Lloyd kernel (and, since
round 5, the train-sharded KNN search) across device counts.

The reference's scaling mechanism is OpenMP threads over row chunks with a
serial partial-centroid reduction (``cluster/_k_means_lloyd.pyx:118-154``);
this framework's is SPMD over a ``jax.sharding.Mesh`` with ``psum`` centroid
reductions over ICI (``sq_learn_tpu/parallel/lloyd.py``). This script times
one full noisy Lloyd run (fixed init, fixed iteration budget) on meshes of
1, 2, 4, ... up to every visible device, and records each mesh size's
deviation from the 1-device centers (tiny: the psum reduction only
reorders float32 sums, and the δ-window picks touch few rows).

On real multi-chip hardware the timings measure ICI scaling. On a single
host the conftest-style virtual CPU devices share one machine, so no
speedup is expected — the value is the layout/collective validation, and
``simulated: true`` is recorded so nobody mistakes the numbers for chip
scaling.

Emits ONE JSON line: wall-clock at the largest mesh, with the full
per-mesh-size table in the stderr extras.
"""

import sys
import warnings

import numpy as np

warnings.filterwarnings("ignore")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import emit, probe_backend, smoke_mode, timed  # noqa: E402


def main():
    probe_backend()
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from sq_learn_tpu.models.qkmeans import kmeans_plusplus
    from sq_learn_tpu.parallel.lloyd import lloyd_single_sharded

    devices = jax.devices()
    if len(devices) == 1 and devices[0].platform == "cpu":
        # single-CPU fallback: force the virtual-device mesh the tests use
        import os
        import subprocess

        if os.environ.get("_SQ_SCALING_CHILD") != "1":
            env = dict(os.environ, _SQ_SCALING_CHILD="1",
                       JAX_PLATFORMS="cpu",
                       XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                                  + " --xla_force_host_platform_device_count=8"
                                  ).strip())
            env.pop("PYTHONPATH", None)
            raise SystemExit(subprocess.run(
                [sys.executable, __file__] + sys.argv[1:], env=env).returncode)

    n = 8192 if smoke_mode() else 65536
    m, k = 64, 10
    rng = np.random.default_rng(0)
    X = np.concatenate([
        rng.normal(loc=c, scale=1.0, size=(n // k, m))
        for c in rng.normal(scale=6.0, size=(k, 1, m))
    ]).astype(np.float32)
    w = np.ones(len(X), np.float32)
    xsq = (X * X).sum(axis=1)

    key = jax.random.PRNGKey(0)
    centers0, _ = kmeans_plusplus(
        key, jnp.asarray(X), jnp.asarray(xsq), k)
    centers0 = np.asarray(centers0)

    static = dict(delta=0.5, mode="delta", max_iter=20, tol=0.0,
                  patience=None, intermediate_error=False,
                  true_tomography=False)

    sizes = []
    d = 1
    while d <= len(jax.devices()):
        sizes.append(d)
        d *= 2
    if sizes[-1] != len(jax.devices()):  # non-power-of-2 device count
        sizes.append(len(jax.devices()))
    table = {}
    ref_centers = None
    # uploaded once — the timed region measures the sharded Lloyd run, not
    # per-rep host-to-device transfers
    Xd, wd = jnp.asarray(X), jnp.asarray(w)
    c0d, xsqd = jnp.asarray(centers0), jnp.asarray(xsq)
    from sq_learn_tpu.parallel.neighbors import (knn_indices_sharded,
                                                 shard_train_rows)

    n_query, knn_k = 2048, 10
    ref_knn_idx = None
    for nd in sizes:
        mesh = Mesh(np.asarray(jax.devices()[:nd]), ("data",))

        def run():
            out = lloyd_single_sharded(
                mesh, key, Xd, wd, c0d, xsqd, **static)
            jax.block_until_ready(out[2])
            return out

        t, out = timed(run, warmup=1, reps=3 if smoke_mode() else 2)
        centers = np.asarray(out[2])
        if ref_centers is None:
            ref_centers = centers
        # same key; deviations come only from float32 psum reduction order
        # and per-shard δ-window streams (fold_in by axis index)
        max_dev = float(np.max(np.abs(centers - ref_centers)))

        # the train-sharded KNN search on the same mesh ladder (corpus
        # placed once per mesh size, outside the timed region — the
        # classifier's fit-time cache discipline)
        state = shard_train_rows(mesh, Xd)

        def run_knn():
            out = knn_indices_sharded(mesh, Xd, Xd[:n_query], knn_k,
                                      presharded=state)
            jax.block_until_ready(out[0])
            return out

        t_knn, (ki, kd) = timed(run_knn, warmup=1,
                                reps=3 if smoke_mode() else 2)
        ki, kd = np.asarray(ki), np.asarray(kd)
        if ref_knn_idx is None:
            ref_knn_idx, ref_knn_d2 = ki, kd
        # the kernel's parity contract is "up to tie order" (near-equal
        # d2 can legitimately swap at the k boundary across shard
        # layouts), so record neighbor-SET overlap + distance deviation,
        # not strict index equality — same spirit as the Lloyd leg's
        # max_center_dev_vs_1dev
        overlap = float(np.mean([
            len(set(a) & set(b)) / knn_k
            for a, b in zip(ki, ref_knn_idx)]))
        d2_dev = float(np.max(np.abs(kd - ref_knn_d2)))
        table[nd] = {"s": round(t, 4), "max_center_dev_vs_1dev": max_dev,
                     "knn_s": round(t_knn, 4),
                     "knn_idx_overlap_1dev": round(overlap, 5),
                     "knn_max_d2_dev_vs_1dev": d2_dev}

    largest = sizes[-1]
    simulated = jax.devices()[0].platform == "cpu"
    # baseline_kind="derived": vs_baseline here is the 1-device/largest
    # SCALING ratio, not a measured-sklearn wall-clock ratio — on the
    # virtual-device CPU mesh (simulated: true) it validates layout and
    # collectives, never chip scaling, so the acceptance gate must count
    # it with the derived configs (like bench_ipe_digits), not against
    # the ≥0.5-of-sklearn bar's measured pool.
    emit("qkmeans_sharded_lloyd_scaling_wallclock", table[largest]["s"],
         vs_baseline=round(table[sizes[0]]["s"] / table[largest]["s"], 3),
         baseline_kind="derived",
         devices=largest, simulated=simulated, table=table,
         n=len(X), m=m, k=k)


if __name__ == "__main__":
    main()
