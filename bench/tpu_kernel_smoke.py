"""Fast Mosaic-lowering smoke for every hand-tiled kernel on the REAL
chip: tiny shapes, seconds of runtime, run FIRST in a healthy-tunnel
window so a lowering rejection surfaces immediately (with the failing
kernel named) instead of mid-way through a burned MFU run.

Exercises, in order: fused Lloyd f32 → Lloyd bf16 → Lloyd δ-window →
fused argkmin → Lloyd under shard_map on a 1-device mesh (the vma/pcast
plumbing against real lowering). Prints one PASS/FAIL line per kernel
and exits non-zero if any fail; on the CPU backend it runs the same
ladder in interpret mode (making the script itself CI-smokeable).
"""

import sys
import traceback
import warnings

import numpy as np

warnings.filterwarnings("ignore")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import probe_backend  # noqa: E402


def main():
    import os

    wanted_chip = os.environ.get("JAX_PLATFORMS", "") not in ("", "cpu")
    probe_backend()
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from sq_learn_tpu.ops.pallas_kernels import (argkmin_pallas,
                                                 lloyd_step_pallas,
                                                 pallas_available)
    from sq_learn_tpu.parallel.lloyd import lloyd_single_sharded

    interpret = not pallas_available()
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.randn(600, 17).astype(np.float32))
    w = jnp.ones(600, jnp.float32)
    C = X[:5]
    xsq = jnp.sum(X * X, axis=1)
    key = jax.random.PRNGKey(0)

    checks = [
        ("lloyd_f32", lambda: lloyd_step_pallas(
            X, w, C, xsq, interpret=interpret)),
        ("lloyd_bf16", lambda: lloyd_step_pallas(
            X, w, C, xsq, interpret=interpret, compute_dtype="bfloat16")),
        ("lloyd_delta", lambda: lloyd_step_pallas(
            X, w, C, xsq, key=key, window=2.0, interpret=interpret)),
        ("argkmin", lambda: argkmin_pallas(
            X, xsq, X[:100], 5, interpret=interpret)),
        ("lloyd_shard_map", lambda: lloyd_single_sharded(
            Mesh(np.array(jax.devices()[:1]), ("data",)), key, X, w, C,
            xsq, mode="delta", delta=0.5, max_iter=2, tol=0.0,
            use_pallas=True, pallas_interpret=interpret)),
    ]
    failed = []
    for name, fn in checks:
        try:
            out = fn()
            jax.block_until_ready(out)
            # fetch one element: async dispatch surfaces runtime errors
            # at transfer time
            float(np.asarray(out[1]).ravel()[0])
            print(f"PASS {name}")
        except Exception as exc:
            failed.append(name)
            print(f"FAIL {name}: {type(exc).__name__}: {exc}")
            traceback.print_exc(limit=3, file=sys.stderr)
    backend = jax.default_backend()
    mode = "interpret" if interpret else "mosaic"
    print(f"kernel smoke on backend={backend} ({mode}): "
          f"{len(checks) - len(failed)}/{len(checks)} pass")
    # rc contract: 1 = kernel failure (always wins — a regression must
    # never be read as a mere tunnel problem), 2 = all kernels passed but
    # only in interpreter fallback (requested chip unreachable), 0 = ok.
    if failed:
        sys.exit(1)
    if wanted_chip and interpret:
        # the tunnel wedged between the caller's probe and ours: these
        # PASSes are interpreter runs, NOT Mosaic validation — refuse to
        # masquerade as chip evidence in a committed window record
        print("NOT-CHIP: accelerator was requested but the probe fell "
              "back to CPU — no Mosaic lowering was exercised")
        sys.exit(2)
    sys.exit(0)


if __name__ == "__main__":
    main()
