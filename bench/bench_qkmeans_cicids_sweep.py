"""BASELINE config #5: qKMeans δ-sweep on cicids intrusion data — the
ARI-vs-δ accuracy/precision trade-off curve that is the framework's whole
point (README.rst:26-44 of the reference), plus wall-clock.

Emits the headline JSON line for the δ=0.5 point; the full sweep goes to
stderr. Every δ > 0 point additionally records the fit's theoretical
q-means cost (``QKMeans.quantum_runtime_model`` — the closed-form model
the reference implements but never ran outside plots) and, under
``SQ_OBS=1``, lands as one schema-valid ``tradeoff`` record so
``python -m sq_learn_tpu.obs frontier`` can render the
accuracy-vs-theoretical-runtime curve with its Pareto frontier
(VERDICT r5 weak #2: the thesis artifact).

Config (50k rows, n_init=3) is pinned by BASELINE.md — the runnable demo
of the same trade-off, ``examples/delta_tradeoff.py``, intentionally uses
n_init=10 at a smaller size so init luck never muddies its curve.
"""

import sys
import warnings

import numpy as np

warnings.filterwarnings("ignore")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import (emit, maybe_subsample, probe_backend,  # noqa: E402
                           timed)


def main():
    probe_backend()
    from sq_learn_tpu.datasets import load_cicids
    from sq_learn_tpu.metrics import adjusted_rand_score
    from sq_learn_tpu.models import QKMeans
    from sq_learn_tpu.preprocessing import StandardScaler

    X, y, real = load_cicids()
    X, y = maybe_subsample(X, y)
    if len(X) > 50_000:
        X, y = X[:50_000], y[:50_000]
    X = StandardScaler().fit_transform(X)
    k = int(len(np.unique(y)))

    from sq_learn_tpu.obs import frontier

    sweep = {}
    headline_t = None
    for delta in (0.0, 0.1, 0.3, 0.5, 1.0):
        def fit():
            return QKMeans(n_clusters=k, n_init=3, delta=delta,
                           true_distance_estimate=False,
                           random_state=0).fit(X)

        t, est = timed(fit, warmup=1, reps=1)
        ari = float(adjusted_rand_score(y, est.labels_))
        sweep[delta] = {"fit_s": round(t, 4), "ari": round(ari, 4)}
        # the thesis join: what theoretical quantum runtime did this δ
        # buy, and what accuracy did it cost (δ=0 short-circuits to the
        # classical computation — no quantum cost exists to trade)
        q_rt = c_rt = None
        if delta > 0:
            quantum, classical = est.quantum_runtime_model(*X.shape)
            q_rt = float(np.ravel(quantum)[0])
            c_rt = float(classical)
        sweep[delta]["q_runtime"] = q_rt
        sweep[delta]["c_runtime"] = c_rt
        frontier.record_tradeoff(
            "qkmeans_cicids_delta", delta, accuracy=ari,
            accuracy_metric="ari", q_runtime=q_rt, c_runtime=c_rt,
            wall_s=t, budget={"delta": delta},
            estimator="qkmeans", n=int(X.shape[0]), m=int(X.shape[1]))
        if delta == 0.5:
            headline_t = t

    # classical wall-clock baseline at the same config: the δ dial the
    # curve demonstrates is only meaningful priced against what classical
    # sklearn charges for the exact answer (reference README.rst:26-44)
    sk_t = sk_ari = None
    try:
        from sklearn.cluster import KMeans as SKKMeans
        from sklearn.metrics import adjusted_rand_score as sk_ars

        def sk_fit():
            return SKKMeans(n_clusters=k, n_init=3, random_state=0).fit(X)

        sk_t, sk_est = timed(sk_fit, warmup=1, reps=1)
        sk_ari = round(float(sk_ars(y, sk_est.labels_)), 4)
    except Exception as exc:
        print(f"# sklearn baseline unavailable: {exc}", file=sys.stderr)

    emit("qkmeans_cicids_delta_sweep_fit_wallclock", headline_t,
         vs_baseline=(sk_t / headline_t) if sk_t else None,
         sweep=sweep, sklearn_s=sk_t, sklearn_ari=sk_ari, real_cicids=real)


if __name__ == "__main__":
    main()
