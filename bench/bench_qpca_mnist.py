"""BASELINE config #2: quantum PCA, n_components=50, MNIST 70k×784.

Measures fit wall-clock vs classical sklearn PCA and explained-variance
parity. vs_baseline = sklearn_seconds / ours (>1 ⇒ faster).
"""

import sys
import warnings

import numpy as np

warnings.filterwarnings("ignore")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import (emit, maybe_subsample, probe_backend,  # noqa: E402
                           timed)


def main():
    probe_backend()
    import jax
    from sq_learn_tpu.datasets import load_mnist
    from sq_learn_tpu.models import QPCA

    X, y, real = load_mnist()
    X, y = maybe_subsample(X, y)
    n_components = 50
    # MXU-native precision on TPU: bf16 Gram GEMMs, exact m×m eigh (see
    # QPCA.compute_dtype) — the explained-variance parity below records
    # the effect; CPU/GPU keep the f32 default
    compute_dtype = ("bfloat16" if jax.default_backend() == "tpu" else None)

    def ours_fit():
        # quantum path: full SVD + gated estimators at a realistic budget
        pca = QPCA(n_components=n_components, svd_solver="full",
                   random_state=0, compute_dtype=compute_dtype).fit(
            X, estimate_all=True, eps=0.1, delta=0.1, theta_major=1e-9,
            true_tomography=False)
        return pca

    ours_t, pca = timed(ours_fit, warmup=1, reps=1)

    sk_t, ev_parity = None, None
    try:
        from sklearn.decomposition import PCA as SKPCA

        def sk_fit():
            return SKPCA(n_components=n_components,
                         svd_solver="full").fit(X)

        sk_t, sk = timed(sk_fit, warmup=0, reps=1)
        ev_parity = float(
            np.sum(pca.explained_variance_ratio_)
            / np.sum(sk.explained_variance_ratio_))
    except Exception as exc:
        print(f"# sklearn baseline unavailable: {exc}", file=sys.stderr)

    # the record carries the precision that actually engaged (the
    # partial-U gate can reject the hint, e.g. subsampled smoke shapes)
    engaged = getattr(pca, "effective_compute_dtype_", None)
    emit("qpca_mnist_70kx784_c50_fit_wallclock", ours_t,
         vs_baseline=(sk_t / ours_t) if sk_t else None,
         sklearn_s=sk_t, explained_variance_parity=ev_parity,
         real_mnist=real, compute_dtype=engaged or "float32")


if __name__ == "__main__":
    main()
