"""Hardware-utilization proof for the flagship pallas Lloyd kernel
(VERDICT r2 missing #2): a compute-dense regime where "beating" the
reference's ``cluster/_k_means_lloyd.pyx:29`` means a measured fraction
of chip peak, not a wall-clock ratio on digit-scale data.

Workload: one fused Lloyd iteration at 512k×1024, k=256 (default;
``--smoke`` shrinks it). FLOP accounting per iteration counts the two
MXU GEMMs the kernel performs — E-step distances (2·n·k·m) + M-step
one-hot centroid sums (2·n·k·m) — i.e. 4·n·k·m; the argmin/compare VPU
work is excluded (undercounting keeps MFU honest). Data is generated
ON DEVICE: no multi-GB host→device upload rides the axon relay, whose
wedge hazard is transfer-triggered (CLAUDE.md).

Sync protocol: every timed run fetches the inertia scalar to the host —
a device→host read cannot complete before the producing computation,
whereas ``block_until_ready`` proved soft on the experimental relay
(the 0.0001 s covtype artifact of round 2).

Peak FLOP/s resolution lives in ``sq_learn_tpu.utils.profiling``
(``TPU_PEAK_FLOPS`` by device kind — bf16 matmul peaks, the MXU's
native rate, so f32 MFU is a conservative lower bound; unknown chips
report raw FLOP/s with no MFU claim). Override with
``SQ_TPU_PEAK_FLOPS`` when the tunnel fronts unlisted hardware.

Emits ONE JSON line: value = achieved TFLOP/s for the best pallas
configuration, ``vs_baseline`` = XLA-path seconds / pallas seconds
(>1 ⇒ the hand-tiled kernel beats XLA's own fusion), extras carry the
MFU and the pallas-vs-XLA ladder across sizes (the crossover table).
"""

import os
import sys
import time
import warnings

import numpy as np

warnings.filterwarnings("ignore")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import emit, probe_backend, smoke_mode  # noqa: E402


def _xla_lloyd_iter(X, centers, x_sq_norms):
    """The plain-XLA twin of the fused kernel: E-step GEMM + argmin,
    then the one-hot M-step GEMM — two HBM sweeps over X, XLA fusion."""
    import jax.numpy as jnp

    d2 = (x_sq_norms[:, None] + jnp.sum(centers * centers, axis=1)[None, :]
          - 2.0 * X @ centers.T)
    labels = jnp.argmin(d2, axis=1)
    min_d2 = jnp.min(d2, axis=1)
    onehot = (labels[:, None] == jnp.arange(centers.shape[0])[None, :]
              ).astype(X.dtype)
    sums = onehot.T @ X
    counts = jnp.sum(onehot, axis=0)
    inertia = jnp.sum(min_d2)
    return labels, min_d2, sums, counts, inertia


def _timed_iter(fn, reps):
    """min-of-reps wall-clock with the fetch-to-host sync."""
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        _ = float(np.asarray(out[-1]))  # inertia scalar → host
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    probe_backend()
    import jax
    import jax.numpy as jnp

    from sq_learn_tpu.ops.pallas_kernels import (lloyd_step_pallas,
                                                 pallas_available)
    from sq_learn_tpu.utils.profiling import (device_peak_flops,
                                              lloyd_iter_flops)

    on_tpu = pallas_available()
    interpret = not on_tpu
    # (n, m, k) ladder: latency-bound digit scale → MNIST scale → the
    # compute-dense headline regime
    if smoke_mode() or not on_tpu:
        sizes = [(2048, 64, 16), (4096, 128, 32)]
        reps = 2
    else:
        sizes = [(8192, 64, 16), (65536, 256, 64), (524288, 1024, 256)]
        reps = 5

    device = jax.devices()[0]
    peak = device_peak_flops(device)
    kind = ("env" if os.environ.get("SQ_TPU_PEAK_FLOPS")
            else getattr(device, "device_kind", "unknown"))
    ladder = []
    headline = None

    for n, m, k in sizes:
        kx, kc = jax.random.split(jax.random.PRNGKey(0))
        X = jax.random.normal(kx, (n, m), jnp.float32)
        centers = jax.random.normal(kc, (k, m), jnp.float32)
        xsq = jnp.sum(X * X, axis=1)
        jax.block_until_ready((X, centers, xsq))
        flops = lloyd_iter_flops(n, m, k)

        xla_iter = jax.jit(_xla_lloyd_iter)
        entry = {"n": n, "m": m, "k": k}
        _timed_iter(lambda: xla_iter(X, centers, xsq), 1)  # compile
        entry["xla_f32_s"] = _timed_iter(
            lambda: xla_iter(X, centers, xsq), reps)
        for dt_name, cdt in (("f32", None), ("bf16", "bfloat16")):
            def pal():
                return lloyd_step_pallas(X, jnp.ones(n, jnp.float32),
                                         centers, xsq, interpret=interpret,
                                         compute_dtype=cdt)

            _timed_iter(pal, 1)  # compile
            t = _timed_iter(pal, reps)
            entry[f"pallas_{dt_name}_s"] = t
            entry[f"pallas_{dt_name}_tflops"] = flops / t / 1e12
            if peak:
                entry[f"pallas_{dt_name}_mfu"] = flops / t / peak
        ladder.append(entry)
        headline = entry  # largest size last

    for e in ladder:
        for key in list(e):
            if isinstance(e[key], float):
                e[key] = round(e[key], 5)

    best_dt = ("bf16" if headline["pallas_bf16_s"] <= headline["pallas_f32_s"]
               else "f32")
    pallas_t = headline[f"pallas_{best_dt}_s"]
    emit(f"pallas_lloyd_tflops_{headline['n']}x{headline['m']}"
         f"_k{headline['k']}",
         headline[f"pallas_{best_dt}_tflops"], unit="TFLOP/s",
         vs_baseline=headline["xla_f32_s"] / pallas_t,
         backend=jax.default_backend(), device_kind=kind,
         peak_flops=peak, best_dtype=best_dt,
         mfu=headline.get(f"pallas_{best_dt}_mfu"), ladder=ladder)


if __name__ == "__main__":
    main()
