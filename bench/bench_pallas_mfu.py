"""Hardware-utilization proof for the flagship pallas Lloyd kernel
(VERDICT r2 missing #2): a compute-dense regime where "beating" the
reference's ``cluster/_k_means_lloyd.pyx:29`` means a measured fraction
of chip peak, not a wall-clock ratio on digit-scale data.

Workload: one fused Lloyd iteration at 512k×1024, k=256 (default;
``--smoke`` shrinks it). FLOP accounting per iteration counts the two
MXU GEMMs the kernel performs — E-step distances (2·n·k·m) + M-step
one-hot centroid sums (2·n·k·m) — i.e. 4·n·k·m; the argmin/compare VPU
work is excluded (undercounting keeps MFU honest). Data is generated
ON DEVICE: no multi-GB host→device upload rides the axon relay, whose
wedge hazard is transfer-triggered (CLAUDE.md).

Sync protocol: every timed run fetches the inertia scalar to the host —
a device→host read cannot complete before the producing computation,
whereas ``block_until_ready`` proved soft on the experimental relay
(the 0.0001 s covtype artifact of round 2).

Peak FLOP/s resolution lives in ``sq_learn_tpu.utils.profiling``
(``TPU_PEAK_FLOPS`` by device kind — bf16 matmul peaks, the MXU's
native rate, so f32 MFU is a conservative lower bound; unknown chips
report raw FLOP/s with no MFU claim). Override with
``SQ_TPU_PEAK_FLOPS`` when the tunnel fronts unlisted hardware.

Emits ONE JSON line: value = achieved TFLOP/s for the best pallas
configuration, ``vs_baseline`` = XLA-twin seconds / pallas seconds **at
the same dtype** (>1 ⇒ the hand-tiling itself beats XLA's fusion —
bf16's GEMM discount is measured on both sides, never attributed to the
kernel), extras carry the MFU and the pallas-vs-XLA ladder across sizes
and dtypes (the crossover table), plus the fused-argkmin ladder.
"""

import os
import sys
import time
import warnings

import numpy as np

warnings.filterwarnings("ignore")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import emit, probe_backend, smoke_mode  # noqa: E402


def _xla_lloyd_iter(X, centers, x_sq_norms, compute_dtype=None):
    """The plain-XLA twin of the fused kernel: E-step GEMM + argmin,
    then the one-hot M-step GEMM — two HBM sweeps over X, XLA fusion.
    ``compute_dtype`` mirrors the pallas kernel's reduced-precision mode
    (GEMM operands cast, f32 accumulation) so the pallas-vs-XLA
    comparison is dtype-fair in both precisions."""
    import jax.numpy as jnp

    cdt = jnp.dtype(compute_dtype) if compute_dtype else X.dtype
    Xc, Cc = X.astype(cdt), centers.astype(cdt)
    gram = jnp.dot(Xc, Cc.T,
                   preferred_element_type=jnp.float32)
    d2 = (x_sq_norms[:, None] + jnp.sum(centers * centers, axis=1)[None, :]
          - 2.0 * gram)
    labels = jnp.argmin(d2, axis=1)
    min_d2 = jnp.min(d2, axis=1)
    onehot = (labels[:, None] == jnp.arange(centers.shape[0])[None, :]
              ).astype(cdt)
    sums = jnp.dot(onehot.T, Xc, preferred_element_type=jnp.float32)
    counts = jnp.sum(onehot.astype(jnp.float32), axis=0)
    inertia = jnp.sum(min_d2)
    return labels, min_d2, sums, counts, inertia


def _timed_iter(fn, reps):
    """min-of-reps wall-clock with the fetch-to-host sync."""
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        # fetch one element of the last output to the host: a
        # device→host read cannot complete before the computation
        _ = float(np.asarray(out[-1]).ravel()[0])
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    probe_backend()
    import jax
    import jax.numpy as jnp

    from sq_learn_tpu.ops.pallas_kernels import (lloyd_step_pallas,
                                                 pallas_available)
    from sq_learn_tpu.utils.profiling import (device_peak_flops,
                                              lloyd_iter_flops)

    on_tpu = pallas_available()
    interpret = not on_tpu
    # (n, m, k) ladder: latency-bound digit scale → MNIST scale → the
    # compute-dense headline regime
    if smoke_mode() or not on_tpu:
        sizes = [(2048, 64, 16), (4096, 128, 32)]
        reps = 2
    else:
        sizes = [(8192, 64, 16), (65536, 256, 64), (524288, 1024, 256)]
        reps = 5

    device = jax.devices()[0]
    peak = device_peak_flops(device)
    kind = ("env" if os.environ.get("SQ_TPU_PEAK_FLOPS")
            else getattr(device, "device_kind", "unknown"))
    ladder = []
    headline = None

    for n, m, k in sizes:
        kx, kc = jax.random.split(jax.random.PRNGKey(0))
        X = jax.random.normal(kx, (n, m), jnp.float32)
        centers = jax.random.normal(kc, (k, m), jnp.float32)
        xsq = jnp.sum(X * X, axis=1)
        jax.block_until_ready((X, centers, xsq))
        flops = lloyd_iter_flops(n, m, k)

        xla_iter = jax.jit(_xla_lloyd_iter,
                           static_argnames=("compute_dtype",))
        entry = {"n": n, "m": m, "k": k}
        for dt_name, cdt in (("f32", None), ("bf16", "bfloat16")):
            _timed_iter(lambda: xla_iter(X, centers, xsq,
                                         compute_dtype=cdt), 1)  # compile
            entry[f"xla_{dt_name}_s"] = _timed_iter(
                lambda: xla_iter(X, centers, xsq, compute_dtype=cdt), reps)
        # tile auto-tune on hardware: VERDICT r2 asks for tuned tile_n if
        # utilization is poor. Small sizes keep the default (the sweep
        # costs compiles); the compute-dense headline size tries three.
        tiles = ((512,) if (interpret or n < 100_000)
                 else (256, 512, 1024))
        for dt_name, cdt in (("f32", None), ("bf16", "bfloat16")):
            best_t, best_tile = float("inf"), tiles[0]
            for tile_n in tiles:
                def pal():
                    return lloyd_step_pallas(
                        X, jnp.ones(n, jnp.float32), centers, xsq,
                        interpret=interpret, compute_dtype=cdt,
                        tile_n=tile_n)

                _timed_iter(pal, 1)  # compile
                t = _timed_iter(pal, reps)
                if t < best_t:
                    best_t, best_tile = t, tile_n
            entry[f"pallas_{dt_name}_s"] = best_t
            entry[f"pallas_{dt_name}_tile"] = best_tile
            entry[f"pallas_{dt_name}_tflops"] = flops / best_t / 1e12
            if peak:
                entry[f"pallas_{dt_name}_mfu"] = flops / best_t / peak
        ladder.append(entry)
        headline = entry  # largest size last

    # second kernel: the fused argkmin (KNN search). HBM-bound rather than
    # MXU-bound — the win over XLA is skipping the (block, n_train)
    # distance-matrix round-trip, so wall-clock ratio is the metric.
    # Guarded so a hardware-specific argkmin failure can never discard the
    # Lloyd MFU evidence measured above (the scarce-window product).
    argk_ladder = []
    try:
        from sq_learn_tpu.models.neighbors import knn_indices
        from sq_learn_tpu.ops.pallas_kernels import argkmin_pallas

        if smoke_mode() or not on_tpu:
            knn_sizes = [(4096, 512, 32, 5)]
        else:
            knn_sizes = [(65536, 8192, 64, 7), (524288, 16384, 128, 7)]
        for nt, nq, m, k in knn_sizes:
            kt, kq = jax.random.split(jax.random.PRNGKey(1))
            Xt = jax.random.normal(kt, (nt, m), jnp.float32)
            Xq = jax.random.normal(kq, (nq, m), jnp.float32)
            xsq = jnp.sum(Xt * Xt, axis=1)
            jax.block_until_ready((Xt, Xq, xsq))
            entry = {"n_train": nt, "n_query": nq, "m": m, "k": k}

            def xla():
                return knn_indices(Xt, Xq, k)

            def pal():
                return argkmin_pallas(Xt, xsq, Xq, k, interpret=interpret)

            _timed_iter(xla, 1)
            entry["xla_s"] = _timed_iter(xla, reps)
            _timed_iter(pal, 1)
            entry["pallas_s"] = _timed_iter(pal, reps)
            entry["pallas_vs_xla"] = entry["xla_s"] / entry["pallas_s"]
            argk_ladder.append(entry)
    except Exception as exc:
        argk_ladder.append({"error": f"{type(exc).__name__}: {exc}"})

    for e in ladder + argk_ladder:
        for key in list(e):
            if isinstance(e[key], float):
                e[key] = round(e[key], 5)

    best_dt = ("bf16" if headline["pallas_bf16_s"] <= headline["pallas_f32_s"]
               else "f32")
    # dtype-fair ratio: best pallas dtype against the XLA twin AT THE SAME
    # dtype — bf16's ~2x GEMM discount must not masquerade as hand-tiling
    emit(f"pallas_lloyd_tflops_{headline['n']}x{headline['m']}"
         f"_k{headline['k']}",
         headline[f"pallas_{best_dt}_tflops"], unit="TFLOP/s",
         vs_baseline=(headline[f"xla_{best_dt}_s"]
                      / headline[f"pallas_{best_dt}_s"]),
         backend=jax.default_backend(), device_kind=kind,
         peak_flops=peak, best_dtype=best_dt,
         mfu=headline.get(f"pallas_{best_dt}_mfu"), ladder=ladder,
         argkmin_ladder=argk_ladder)


if __name__ == "__main__":
    main()
