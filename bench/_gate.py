"""BASELINE acceptance gate over a suite record file.

Enforces BASELINE.md's bar (within 2x of classical sklearn, i.e.
``vs_baseline >= 0.5``) on every JSON line of a `bench/run_suite.sh`
record. Measured BASELINE configs and derived-baseline supplementary
configs (``baseline_kind: "derived"`` in the JSON line — currently just
``bench_ipe_digits``, whose ratio is a serial-cost derivation on the
order of 1e4-1e5) are counted separately: the scales must never mix,
but >= 0.5 still means "not slower than the reference's own (serial)
architecture", so the bar applies to both kinds.

A config that records no JSON line at all (double failure — both the
primary run and the CPU retry died) fails the gate: a missing number is
not a passing number. Likewise ``vs_baseline: null`` ("no baseline was
measured") counts as a miss, never as a free 1.0 pass.

Output is dual: the historical ``# ACCEPT`` comment per metric (humans,
and the committed records that grep for it) plus one machine-readable
``{"gate": ..., "verdict": ...}`` JSON line per criterion — per metric
(gate ``vs_baseline``) and one ``counts`` line for the
expected-vs-present config totals — so downstream tooling (the
perf-regression analyzer, CI annotations) consumes verdicts without
parsing prose.

Exit status 0 = gate green; non-zero with a diagnostic on stderr
otherwise. Lives in its own module (rather than inline in run_suite.sh)
so the counting rules are unit-testable (``tests/test_bench_gate.py``).
"""

import json
import sys


def check(record_path, expected_measured, expected_derived, out=sys.stdout):
    """Return (fails, measured_count, derived_count) for a record file,
    printing one ``# ACCEPT`` comment AND one ``{"gate": ...}`` JSON
    line per metric to ``out``."""
    fails, measured, derived = [], 0, 0
    for line in open(record_path):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "metric" not in rec or "vs_baseline" not in rec:
            continue
        kind = rec.get("baseline_kind", "measured")
        if kind == "derived":
            derived += 1
        else:
            measured += 1
        vb = rec["vs_baseline"]
        ok = isinstance(vb, (int, float)) and vb >= 0.5
        print(f"# ACCEPT {'pass' if ok else 'FAIL'}: {rec['metric']} "
              f"({kind}) vs_baseline={vb}", file=out)
        print(json.dumps({
            "gate": "vs_baseline", "metric": rec["metric"], "kind": kind,
            "value": vb, "threshold": 0.5,
            "verdict": "pass" if ok else "fail"}), file=out)
        if not ok:
            fails.append(rec["metric"])
    return fails, measured, derived


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    record_path, exp_measured, exp_derived = (
        argv[0], int(argv[1]), int(argv[2]))
    fails, measured, derived = check(record_path, exp_measured, exp_derived)
    counts_ok = (not fails and measured == exp_measured
                 and derived == exp_derived)
    print(json.dumps({
        "gate": "counts", "measured": measured,
        "expected_measured": exp_measured, "derived": derived,
        "expected_derived": exp_derived, "fails": fails,
        "verdict": "pass" if counts_ok else "fail"}))
    if not counts_ok:
        sys.exit(f"acceptance gate: fails={fails} "
                 f"measured={measured}/{exp_measured} "
                 f"derived={derived}/{exp_derived}")


if __name__ == "__main__":
    main()
