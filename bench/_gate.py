"""BASELINE acceptance gate over a suite record file.

Enforces BASELINE.md's bar (within 2x of classical sklearn, i.e.
``vs_baseline >= 0.5``) on every JSON line of a `bench/run_suite.sh`
record. Measured BASELINE configs and derived-baseline supplementary
configs (``baseline_kind: "derived"`` in the JSON line — currently just
``bench_ipe_digits``, whose ratio is a serial-cost derivation on the
order of 1e4-1e5) are counted separately: the scales must never mix,
but >= 0.5 still means "not slower than the reference's own (serial)
architecture", so the bar applies to both kinds.

A config that records no JSON line at all (double failure — both the
primary run and the CPU retry died) fails the gate: a missing number is
not a passing number. Likewise ``vs_baseline: null`` ("no baseline was
measured") counts as a miss, never as a free 1.0 pass.

Exit status 0 = gate green; non-zero with a diagnostic on stderr
otherwise. Lives in its own module (rather than inline in run_suite.sh)
so the counting rules are unit-testable (``tests/test_bench_gate.py``).
"""

import json
import sys


def check(record_path, expected_measured, expected_derived, out=sys.stdout):
    """Return (fails, measured_count, derived_count) for a record file,
    printing one ``# ACCEPT`` line per metric to ``out``."""
    fails, measured, derived = [], 0, 0
    for line in open(record_path):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "metric" not in rec or "vs_baseline" not in rec:
            continue
        kind = rec.get("baseline_kind", "measured")
        if kind == "derived":
            derived += 1
        else:
            measured += 1
        vb = rec["vs_baseline"]
        ok = isinstance(vb, (int, float)) and vb >= 0.5
        print(f"# ACCEPT {'pass' if ok else 'FAIL'}: {rec['metric']} "
              f"({kind}) vs_baseline={vb}", file=out)
        if not ok:
            fails.append(rec["metric"])
    return fails, measured, derived


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    record_path, exp_measured, exp_derived = (
        argv[0], int(argv[1]), int(argv[2]))
    fails, measured, derived = check(record_path, exp_measured, exp_derived)
    if fails or measured != exp_measured or derived != exp_derived:
        sys.exit(f"acceptance gate: fails={fails} "
                 f"measured={measured}/{exp_measured} "
                 f"derived={derived}/{exp_derived}")


if __name__ == "__main__":
    main()
