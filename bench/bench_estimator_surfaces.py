"""Secondary estimator surfaces vs their scikit-learn twins: MiniBatch
k-means, brute-force KNN, and the MnistTrial pipeline shape (PCA →
transform → 10-fold KNN CV — the reference's own headline experiment,
``MnistTrial.py:10-28``). Not a BASELINE config; this script makes the
BENCH_SUITE claims for these surfaces reproducible with one command.

Emits one JSON line (the KNN ratio as the headline, every surface in the
extras). SQ_BENCH_SMOKE=1 shrinks the KNN workload to a quick check.
"""

import os
import sys
import warnings

import numpy as np

warnings.filterwarnings("ignore")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import emit, probe_backend, timed  # noqa: E402


def main():
    probe_backend()
    from sklearn.datasets import load_digits

    X = load_digits().data.astype(np.float32)
    y = load_digits().target
    smoke = os.environ.get("SQ_BENCH_SMOKE")
    extras = {}

    # -- MiniBatch k-means ------------------------------------------------
    from sklearn.cluster import MiniBatchKMeans as SKMB

    from sq_learn_tpu.models import MiniBatchQKMeans

    t_ours, est = timed(
        lambda: MiniBatchQKMeans(n_clusters=10, random_state=0,
                                 n_init=3).fit(X), warmup=1, reps=3)
    t_sk, sk = timed(
        lambda: SKMB(n_clusters=10, random_state=0, n_init=3).fit(X),
        warmup=1, reps=3)
    extras["minibatch"] = {
        "ours_s": round(t_ours, 4), "sklearn_s": round(t_sk, 4),
        "ratio": round(t_sk / t_ours, 2),
        "inertia_ratio": round(float(est.inertia_) / sk.inertia_, 4)}

    # -- KNN predict ------------------------------------------------------
    from sklearn.neighbors import KNeighborsClassifier as SKKNN

    from sq_learn_tpu.neighbors import KNeighborsClassifier

    rng = np.random.default_rng(0)
    n_tr, n_q = (2000, 500) if smoke else (20000, 5000)
    Xtr = rng.normal(0, 1, (n_tr, 50)).astype(np.float32)
    ytr = rng.integers(0, 10, n_tr)
    Xq = rng.normal(0, 1, (n_q, 50)).astype(np.float32)
    ours = KNeighborsClassifier(n_neighbors=7).fit(Xtr, ytr)
    sk_knn = SKKNN(n_neighbors=7).fit(Xtr, ytr)
    t_knn, pa = timed(lambda: ours.predict(Xq), warmup=1, reps=3)
    t_sk, pb = timed(lambda: sk_knn.predict(Xq), warmup=1, reps=3)
    knn_ratio = t_sk / t_knn
    extras["knn_predict"] = {
        "shape": f"{n_tr}x50 train / {n_q} queries",
        "ours_s": round(t_knn, 4), "sklearn_s": round(t_sk, 4),
        "ratio": round(knn_ratio, 2),
        "label_agreement": round(float(np.mean(pa == pb)), 4)}

    # -- MnistTrial pipeline shape ---------------------------------------
    from sklearn.decomposition import PCA as SKPCA
    from sklearn.model_selection import StratifiedKFold as SKSKF
    from sklearn.model_selection import cross_validate as sk_cv

    from sq_learn_tpu.decomposition import qPCA
    from sq_learn_tpu.model_selection import StratifiedKFold, cross_validate

    def ours_pipeline():
        pca = qPCA(n_components=16, random_state=0).fit(X)
        Xt = np.asarray(pca.transform(X))
        cv = cross_validate(KNeighborsClassifier(n_neighbors=5), Xt, y,
                            cv=StratifiedKFold(10))
        return float(np.mean(cv["test_score"]))

    def sk_pipeline():
        pca = SKPCA(n_components=16, random_state=0).fit(X)
        cv = sk_cv(SKKNN(n_neighbors=5), pca.transform(X), y,
                   cv=SKSKF(10))
        return float(np.mean(cv["test_score"]))

    t_ours, acc_ours = timed(ours_pipeline, warmup=1, reps=3)
    t_sk, acc_sk = timed(sk_pipeline, warmup=1, reps=3)
    extras["mnist_trial_pipeline"] = {
        "ours_s": round(t_ours, 4), "sklearn_s": round(t_sk, 4),
        "ratio": round(t_sk / t_ours, 2),
        "acc_ours": round(acc_ours, 4), "acc_sklearn": round(acc_sk, 4)}

    emit("knn_predict_20kx50_wallclock", t_knn, vs_baseline=knn_ratio,
         **extras)


if __name__ == "__main__":
    main()
