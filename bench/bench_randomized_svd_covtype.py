"""BASELINE config #4: randomized SVD on covertype 581k×54 via XLA vs
``sklearn.utils.extmath.randomized_svd`` (reference ``extmath.py:246``).

vs_baseline = sklearn_seconds / ours (>1 ⇒ faster).
"""

import sys
import warnings

import numpy as np

warnings.filterwarnings("ignore")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import (emit, maybe_subsample, probe_backend,  # noqa: E402
                           timed)


def main():
    probe_backend()
    import jax
    from sq_learn_tpu._config import as_device_array
    from sq_learn_tpu.datasets import load_covtype
    from sq_learn_tpu.ops.linalg import randomized_svd

    X, y, real = load_covtype()
    X, y = maybe_subsample(X, y)
    n_components = 10
    key = jax.random.PRNGKey(0)
    # covtype f32 is ~125 MB — just UNDER the 128 MB chunk threshold, so
    # this still crosses the relay as one transfer (wedges were only ever
    # observed at >=200 MB); routing through as_device_array simply keeps
    # every bench on the same placement path, and a lowered
    # SQ_TRANSFER_CHUNK_BYTES would engage slicing here too
    Xd = as_device_array(X)

    def ours_run():
        U, S, Vt = randomized_svd(key, Xd, n_components, n_iter=4)
        # sync by fetching the result to the host: a device->host transfer
        # cannot complete before the producing computation, whereas
        # block_until_ready proved soft on the experimental axon relay
        # (recorded 0.1 ms for a >=10-HBM-pass workload)
        return np.asarray(S)

    ours_t, S_ours = timed(ours_run, warmup=1, reps=3)

    sk_t, sv_parity = None, None
    try:
        from sklearn.utils.extmath import randomized_svd as sk_rsvd

        def sk_run():
            return sk_rsvd(X, n_components=n_components, n_iter=4,
                           random_state=0)

        sk_t, (U, S_sk, Vt) = timed(sk_run, warmup=1, reps=1)
        sv_parity = float(np.max(np.abs(
            (np.asarray(S_ours) - S_sk) / S_sk)))
    except Exception as exc:
        print(f"# sklearn baseline unavailable: {exc}", file=sys.stderr)

    emit("randomized_svd_covtype_581kx54_c10_wallclock", ours_t,
         vs_baseline=(sk_t / ours_t) if sk_t else None,
         sklearn_s=sk_t, max_sv_rel_deviation=sv_parity, real_covtype=real)


if __name__ == "__main__":
    main()
