"""Fused-fit pipeline bench (PR 6): classical q-means on MNIST 70k×784,
measuring the rebuilt init→convergence chain — host NumPy prestats,
subsampled k-means++ init, async quantum stats (on the δ>0 leg), and the
native lockstep Lloyd runner — against sklearn's KMeans on the SAME
classical configuration (δ=0), the honest apples-to-apples runtime
baseline the headline's δ=0.5 config is not.

vs_baseline = sklearn_seconds / ours (>1 ⇒ faster).

Emits one JSON line (metric ``qkmeans_mnist_70kx784_k10_fused_fit_
wallclock``); the δ=0.5 leg's wall-clock and the obs stage breakdown ride
the stderr extras / the suite's per-config obs artifact. SQ_BENCH_SMOKE=1
subsamples to 4000 rows (full code path, seconds).
"""

import sys
import warnings

import numpy as np

warnings.filterwarnings("ignore")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import (emit, maybe_subsample, probe_backend,  # noqa: E402
                           timed)


def main():
    probe_backend()
    import jax
    from sq_learn_tpu.datasets import load_mnist
    from sq_learn_tpu.models import QKMeans
    from sq_learn_tpu.parallel.mesh import make_mesh

    X, y, real = load_mnist()
    X, y = maybe_subsample(X, y)
    k, n_init, seed = 10, 3, 0
    mesh = make_mesh() if len(jax.devices()) > 1 else None

    def ours_fit(delta):
        est = QKMeans(n_clusters=k, n_init=n_init, max_iter=300,
                      delta=delta, true_distance_estimate=False,
                      random_state=seed, mesh=mesh)
        est.fit(X)
        return est

    ours_t, est = timed(ours_fit, 0.0, warmup=1, reps=1)
    delta_t, est_d = timed(ours_fit, 0.5, warmup=0, reps=1)

    sk_t, ari = None, None
    try:
        from sklearn.cluster import KMeans as SKKMeans
        from sklearn.metrics import adjusted_rand_score

        def sk_fit():
            return SKKMeans(n_clusters=k, n_init=n_init, max_iter=300,
                            random_state=seed).fit(X)

        sk_t, sk = timed(sk_fit, warmup=0, reps=1)
        ari = float(adjusted_rand_score(sk.labels_, est.labels_))
    except Exception as exc:
        print(f"# sklearn baseline unavailable: {exc}", file=sys.stderr)

    emit("qkmeans_mnist_70kx784_k10_fused_fit_wallclock", ours_t,
         vs_baseline=(sk_t / ours_t) if sk_t else None,
         sklearn_s=sk_t, ari_vs_sklearn=ari, delta05_s=delta_t,
         ingest=est.ingest_, n_iter=est.n_iter_,
         n_iter_delta05=est_d.n_iter_,
         devices=len(jax.devices()), real_mnist=real)


if __name__ == "__main__":
    main()
