"""Serving load bench (PR 9): closed- and open-loop synthetic request
load through the micro-batching dispatcher, on the 8-virtual-device CPU
mesh.

The headline claim of the serving layer: **micro-batched QPS ≥ 5× the
sequential per-request baseline at equal-or-better p99** under the SAME
offered load. Both arms run the identical pre-generated request stream
(mixed tenants, predict/transform ops, request sizes 1–64 rows, mixed
f32/f64 inputs) from the same closed-loop client pool against the same
registry; the only difference is ``coalesce`` — the treatment arm
batches concurrent requests into padded pow2 buckets, the control arm
dispatches one request per batch. Reported per arm: sustained QPS over
the submit→last-response window, p50/p99 request latency (queue wait +
dispatch, host clock), batch occupancy, degrade count.

Two JSON lines land in the record (both banded by ``make regress``):

- ``*_microbatch_qps`` — value = micro-batched sustained QPS
  (``unit: "qps"``, LOWER-bounded ``throughput`` gate),
  ``vs_baseline`` = batched QPS / sequential QPS (the ≥5× claim; the
  suite gate's ≥0.5 bar reads "batching never LOSES throughput").
- ``*_microbatch_p99`` — value = micro-batched p99 seconds
  (``unit: "s"``, latency gate), ``vs_baseline`` = sequential p99 /
  batched p99 (≥1 ⇔ the equal-or-better-p99 half of the claim).

A short open-loop leg (Poisson-free fixed-rate arrivals at half the
measured batched QPS) rides in the stderr extras — the arrival pattern a
closed loop cannot exhibit. Per-request parity is spot-checked against
the estimators' own predict/transform surfaces. SQ_BENCH_SMOKE=1
shrinks the stream (600 requests) while keeping every code path.
"""

import json
import os
import sys
import threading
import time
import warnings

import numpy as np

warnings.filterwarnings("ignore")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import emit  # noqa: E402

#: request row counts — few-row requests dominate real serving traffic
#: (single-sample scoring and small feature batches), which is exactly
#: the regime where per-request dispatch overhead is most wasteful
SIZES = (1, 2, 4, 8, 16)


def _make_requests(rng, n_requests, tenants, m):
    """The pre-generated mixed request stream both arms replay."""
    reqs = []
    for i in range(n_requests):
        rows = rng.normal(size=(SIZES[i % len(SIZES)], m))
        rows = rows.astype(np.float32 if i % 2 else np.float64)
        reqs.append(tenants[i % len(tenants)] + (rows,))
    return reqs


def _run_arm(reg, requests, *, coalesce, threads, max_batch_rows,
             max_wait_ms, window=64):
    """One closed-loop arm: ``threads`` clients replay their slice of
    the stream, each keeping a sliding ``window`` of requests in flight
    (the modern async-client shape — a service sees overlapping
    requests per connection, not strict request-response lockstep).
    Returns the dispatcher's SLO summary."""
    from sq_learn_tpu.serving import MicroBatchDispatcher

    d = MicroBatchDispatcher(reg, coalesce=coalesce,
                             max_batch_rows=max_batch_rows,
                             max_wait_ms=max_wait_ms)
    errors = []

    def client(slice_):
        try:
            for start in range(0, len(slice_), window):
                futs = d.submit_many(slice_[start:start + window])
                for f in futs:
                    f.result(timeout=120)
        except Exception as exc:  # a lost request must fail the bench
            errors.append(repr(exc))

    pool = [threading.Thread(target=client, args=(requests[i::threads],))
            for i in range(threads)]
    t0 = time.perf_counter()
    for t in pool:
        t.start()
    for t in pool:
        t.join()
    wall = time.perf_counter() - t0
    slo = d.close()
    if errors:
        raise RuntimeError(f"requests failed: {errors[:3]}")
    slo["wall_s"] = round(wall, 4)
    return slo


def _open_loop(reg, requests, rate_qps, max_batch_rows, max_wait_ms):
    """Fixed-rate arrivals from one pacing thread; returns the SLO
    summary of the open-loop window."""
    from sq_learn_tpu.serving import MicroBatchDispatcher

    d = MicroBatchDispatcher(reg, max_batch_rows=max_batch_rows,
                             max_wait_ms=max_wait_ms)
    period = 1.0 / max(rate_qps, 1.0)
    futs = []
    start = time.perf_counter()
    for i, (tenant, op, rows) in enumerate(requests):
        target = start + i * period
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        futs.append(d.submit(tenant, op, rows))
    for f in futs:
        f.result(timeout=120)
    return d.close()


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from sq_learn_tpu.models import QKMeans, TruncatedSVD
    from sq_learn_tpu.serving import ModelRegistry, kernel_cache_sizes
    from sq_learn_tpu.serving import cache as serve_cache

    smoke = os.environ.get("SQ_BENCH_SMOKE") == "1"
    n_requests = 600 if smoke else 12_000
    threads = 8
    # best-of-3: this host is load-noisy (CLAUDE.md) and the batched
    # arm's sub-second window is especially exposed to co-tenant spikes
    reps = 1 if smoke else 3
    m = 32
    max_batch_rows, max_wait_ms = 512, 2.0

    rng = np.random.default_rng(0)
    X = (rng.normal(size=(4000, m))
         + 6.0 * rng.integers(0, 8, size=(4000, 1))).astype(np.float32)
    alpha = QKMeans(n_clusters=8, random_state=0, n_init=1).fit(X)
    beta = QKMeans(n_clusters=16, random_state=1, n_init=1).fit(X)
    gamma = TruncatedSVD(n_components=8, random_state=0).fit(X)

    reg = ModelRegistry()
    reg.register("alpha", alpha)
    reg.register("beta", beta)
    reg.register("gamma", gamma)

    tenants = [("alpha", "predict"), ("beta", "predict"),
               ("gamma", "transform"), ("alpha", "transform")]
    requests = _make_requests(rng, n_requests, tenants, m)

    # warmup pass: mint every (bucket, dtype, model-shape) compile into
    # the process-level kernel caches so neither timed arm pays XLA
    # lowering; the result cache is cleared so the timed arms recompute
    warm = requests[: min(len(requests), 1024)]
    _run_arm(reg, warm, coalesce=True, threads=threads,
             max_batch_rows=max_batch_rows, max_wait_ms=max_wait_ms)
    _run_arm(reg, warm[:64], coalesce=False, threads=threads,
             max_batch_rows=max_batch_rows, max_wait_ms=max_wait_ms)

    # reps INTERLEAVE the two arms so a host-load spike lands on both,
    # not one (back-to-back arms made the ratio a lottery on a loaded
    # host); per arm the best-qps rep wins (the bench/_common.timed
    # discipline — a preempted rep is not the architecture's number),
    # and p50/p99 are the winning rep's, never cherry-picked across reps
    batched = sequential = None
    for _ in range(reps):
        serve_cache.clear()
        b = _run_arm(reg, requests, coalesce=True, threads=threads,
                     max_batch_rows=max_batch_rows, max_wait_ms=max_wait_ms)
        serve_cache.clear()
        s = _run_arm(reg, requests, coalesce=False, threads=threads,
                     max_batch_rows=max_batch_rows, max_wait_ms=max_wait_ms)
        if batched is None or b["qps"] > batched["qps"]:
            batched = b
        if sequential is None or s["qps"] > sequential["qps"]:
            sequential = s

    # parity spot-check: the served responses must be the estimators'
    from sq_learn_tpu.serving import MicroBatchDispatcher

    d = MicroBatchDispatcher(reg, background=False)
    parity = True
    for tenant, op, rows in requests[:24]:
        out = d.serve(tenant, op, rows)
        est = {"alpha": alpha, "beta": beta, "gamma": gamma}[tenant]
        ref = (est.predict(rows.astype(np.float32)) if op == "predict"
               else est.transform(rows.astype(np.float32)))
        same = (np.array_equal(out, ref) if op == "predict"
                else np.allclose(out, ref, atol=1e-4))
        parity = parity and bool(same)
    d.close()

    serve_cache.clear()
    open_loop = _open_loop(
        reg, requests[: min(len(requests), 2000)],
        rate_qps=batched["qps"] * 0.5, max_batch_rows=max_batch_rows,
        max_wait_ms=max_wait_ms)

    qps_ratio = (batched["qps"] / sequential["qps"]
                 if sequential["qps"] else None)
    p99_ratio = (sequential["p99_ms"] / batched["p99_ms"]
                 if batched["p99_ms"] else None)
    tag = f"serving_load_{n_requests}req_mixed"
    extras = dict(threads=threads, parity=parity,
                  batched=batched, sequential=sequential,
                  open_loop=open_loop,
                  kernel_compiles=kernel_cache_sizes())
    emit(f"{tag}_microbatch_qps", batched["qps"], unit="qps",
         vs_baseline=qps_ratio, **extras)
    emit(f"{tag}_microbatch_p99", batched["p99_ms"] / 1e3, unit="s",
         vs_baseline=p99_ratio)
    if not parity:
        print(json.dumps({"error": "serving parity violated"}),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
