"""Serving load bench (PR 9 + PR 11): closed- and open-loop synthetic
request load through the micro-batching dispatcher, on the
8-virtual-device CPU mesh.

The headline claim of the serving layer: **micro-batched QPS ≥ 5× the
sequential per-request baseline at equal-or-better p99** under the SAME
offered load. Both arms run the identical pre-generated request stream
(mixed tenants, predict/transform ops, request sizes 1–64 rows, mixed
f32/f64 inputs) from the same closed-loop client pool against the same
AOT-warmed registry; the only difference is ``coalesce`` — the
treatment arm batches concurrent requests into padded pow2 buckets, the
control arm dispatches one request per batch. Reported per arm:
sustained QPS over the submit→last-response window, p50/p99 request
latency (queue wait + dispatch, host clock), batch occupancy, degrade
count, transfer bytes.

Four JSON lines land in the record (all banded by ``make regress``):

- ``*_microbatch_qps`` — value = micro-batched sustained QPS
  (``unit: "qps"``, LOWER-bounded ``throughput`` gate),
  ``vs_baseline`` = batched QPS / sequential QPS (the ≥5× claim; the
  suite gate's ≥0.5 bar reads "batching never LOSES throughput").
- ``*_microbatch_p99`` — value = micro-batched p99 seconds
  (``unit: "s"``, latency gate), ``vs_baseline`` = sequential p99 /
  batched p99 (≥1 ⇔ the equal-or-better-p99 half of the claim).
- ``*_coldstart_p99`` (PR 11) — the open-loop cold-start leg: two
  fresh-model-shape arms replay a bucket-ladder-covering request stream
  one request at a time; per arm, the latency of the FIRST request per
  (op, bucket, dtype) is collected and p99'd. The cold arm pays the
  serving path's lazy XLA compiles; the AOT-warmed arm
  (``registry.warm``) must not. value = warmed arm's cold-start p99
  seconds; ``vs_baseline`` = cold p99 / warmed p99 with a declared
  ``vs_baseline_floor`` of 5.0 — the ISSUE 11 acceptance "warmed
  cold-start p99 ≤ 0.2× unwarmed", banded history-free by the
  ``vs_baseline`` gate.
- ``*_quant_bytes_ratio`` (PR 11) — the batched arm replayed against
  bf16-quantized registrations of the same tenants (same stream, same
  arm code): value = quantized / f32 transfer bytes (≈0.5),
  ``vs_baseline`` = f32 / quantized bytes with a declared floor of
  1.8 (⇔ the "moves ≤ 0.55× the bytes" acceptance). The leg runs with
  live guarantee audits armed; any fold violation fails the bench.
- ``*_megabatch_qps`` (PR 16) — the same 12k-request mix spread across
  48 ALIAS tenants (each base tenant re-registered under 16 names from
  the same fitted estimator ⇒ equal fingerprints): the many-thin-
  tenants shape megabatching exists for. The treatment arm runs the
  dispatcher defaults (native gather/scatter fast path + cross-tenant
  megabatching — full shared launches), the control arm pins
  ``native=False, megabatch=False`` — the PR 11 code path, where every
  batch is tenant-scoped (thin per-tenant buckets, ~n_alias× the
  launches) and assembled per request in numpy. value =
  treatment sustained QPS (``unit: "qps"``), ``vs_baseline`` =
  treatment / control QPS with a declared ``vs_baseline_floor`` of 1.5
  (the ISSUE 16 acceptance), banded history-free by the
  ``vs_baseline`` gate. The leg asserts ≥1 cross-tenant launch
  (``megabatches``), per-tenant-request reconciliation against the
  aggregate (under ``SQ_OBS=1``), and ZERO serving-path jit compiles
  in both arms (it runs before the cold-start leg, which mints lazy
  compiles on purpose). Extras carry the submit_many burst microbench
  (best-of-5 enqueue wall-clock of one pre-sized burst vs per-request
  submits of the same stream).

- ``*_autotune_cost_ratio`` (PR 17; lands only under ``SQ_OBS=1`` — the
  controller exists only under an active recorder): the same mixed
  stream served twice against fresh registries whose tenants declare
  DELIBERATELY over-tight p99 targets plus (ε, δ) headroom
  (``slo_eps``/``slo_delta``). The static arm (``autotune=False``, the
  PR 16 plane) burns its declared budget and trips ≥1 multi-window
  alert; the controller arm must serve the identical load with ZERO
  tripped alerts (degrade + renegotiate before the alert can fire) and
  a LOWER summed theoretical runtime cost — the plan-time frontier pick
  routes the ε-headroom tenants int8 (cost × 0.25) and the underspent
  δ-headroom tenant is relaxed toward the cap (cost ∝ 1/δ²). value =
  autotuned / static summed cost, ``vs_baseline`` = static / autotuned
  with a declared floor of 1.2 (the ISSUE 17 acceptance, banded
  history-free by the ``vs_baseline`` gate; the bench also hard-fails
  below it). Σ per-tenant requests == run aggregate is asserted for the
  controller arm like every other obs-armed arm.

Per-request parity is spot-checked against the estimators' own
predict/transform surfaces. SQ_BENCH_SMOKE=1 shrinks the stream (600
requests) while keeping every code path.

Under ``SQ_OBS=1`` (the ``make regress`` run) the obs artifact
additionally carries the ISSUE 12 per-tenant telemetry — one ``slo``
record per tenant (declared targets, latency decomposition) plus the
error-budget ``budget`` records — and the bench ASSERTS the three
tenants' request counts sum to the batched arm's aggregate: an
attribution leak fails the run like a lost request does.
"""

import json
import os
import sys
import threading
import time
import warnings

import numpy as np

warnings.filterwarnings("ignore")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import emit  # noqa: E402

#: request row counts — few-row requests dominate real serving traffic
#: (single-sample scoring and small feature batches), which is exactly
#: the regime where per-request dispatch overhead is most wasteful
SIZES = (1, 2, 4, 8, 16)

#: one request size per pow2 bucket of the 8..512 serving ladder — the
#: cold-start leg's stream touches every bucket exactly once per op
LADDER_SIZES = (1, 9, 17, 33, 65, 129, 257)


def _make_requests(rng, n_requests, tenants, m):
    """The pre-generated mixed request stream both arms replay."""
    reqs = []
    for i in range(n_requests):
        rows = rng.normal(size=(SIZES[i % len(SIZES)], m))
        rows = rows.astype(np.float32 if i % 2 else np.float64)
        reqs.append(tenants[i % len(tenants)] + (rows,))
    return reqs


def _run_arm(reg, requests, *, coalesce, threads, max_batch_rows,
             max_wait_ms, window=64, **disp_kw):
    """One closed-loop arm: ``threads`` clients replay their slice of
    the stream, each keeping a sliding ``window`` of requests in flight
    (the modern async-client shape — a service sees overlapping
    requests per connection, not strict request-response lockstep).
    ``disp_kw`` pins dispatcher toggles per arm (``native=``,
    ``megabatch=`` — constructor args, never env mutation). Returns the
    dispatcher's SLO summary."""
    from sq_learn_tpu.serving import MicroBatchDispatcher

    d = MicroBatchDispatcher(reg, coalesce=coalesce,
                             max_batch_rows=max_batch_rows,
                             max_wait_ms=max_wait_ms, **disp_kw)
    errors = []

    def client(slice_):
        try:
            for start in range(0, len(slice_), window):
                futs = d.submit_many(slice_[start:start + window])
                for f in futs:
                    f.result(timeout=120)
        except Exception as exc:  # a lost request must fail the bench
            errors.append(repr(exc))

    pool = [threading.Thread(target=client, args=(requests[i::threads],))
            for i in range(threads)]
    t0 = time.perf_counter()
    for t in pool:
        t.start()
    for t in pool:
        t.join()
    wall = time.perf_counter() - t0
    slo = d.close()
    if errors:
        raise RuntimeError(f"requests failed: {errors[:3]}")
    slo["wall_s"] = round(wall, 4)
    # per-tenant attribution (ISSUE 12; populated only under SQ_OBS=1):
    # the regress run reconciles these counts against the aggregate —
    # an attribution leak (a request billed to no tenant, or twice)
    # breaks the error-budget ledger's arithmetic
    slo["tenant_requests"] = {t: s["requests"]
                              for t, s in d.slo.tenant_summaries().items()}
    slo["megabatches"] = d.megabatches()
    return slo


def _burst_microbench(reg, requests, reps=5):
    """The submit_many amortization microbench (ISSUE 16 satellite):
    best-of-``reps`` wall-clock of enqueueing the SAME predict-only
    stream as one pre-sized burst vs per-request submits, on a
    deterministic dispatcher (no worker thread — the number is pure
    client-side submit cost: one clock stamp + one resolve per tenant +
    one pre-sized extend per group, vs one of each per request).
    Returns ``(speedup, burst_s, per_request_s)``."""
    from sq_learn_tpu.serving import MicroBatchDispatcher

    best_many = best_one = float("inf")
    for _ in range(reps):
        d = MicroBatchDispatcher(reg, background=False)
        t0 = time.perf_counter()
        futs = d.submit_many(requests)
        best_many = min(best_many, time.perf_counter() - t0)
        d.flush()
        for f in futs:
            f.result(timeout=120)
        d.close()
        d = MicroBatchDispatcher(reg, background=False)
        t0 = time.perf_counter()
        futs = [d.submit(t, op, rows) for t, op, rows in requests]
        best_one = min(best_one, time.perf_counter() - t0)
        d.flush()
        for f in futs:
            f.result(timeout=120)
        d.close()
    speedup = (best_one / best_many) if best_many else None
    return speedup, best_many, best_one


def _open_loop(reg, requests, rate_qps, max_batch_rows, max_wait_ms):
    """Fixed-rate arrivals from one pacing thread; returns the SLO
    summary of the open-loop window."""
    from sq_learn_tpu.serving import MicroBatchDispatcher

    d = MicroBatchDispatcher(reg, max_batch_rows=max_batch_rows,
                             max_wait_ms=max_wait_ms)
    period = 1.0 / max(rate_qps, 1.0)
    futs = []
    start = time.perf_counter()
    for i, (tenant, op, rows) in enumerate(requests):
        target = start + i * period
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        futs.append(d.submit(tenant, op, rows))
    for f in futs:
        f.result(timeout=120)
    return d.close()


def _coldstart_arm(reg, tenant, ops, m, max_batch_rows, reps=3):
    """One cold-start arm: serve a bucket-ladder-covering stream one
    request at a time (deterministic dispatcher — each request is its
    own padded batch, open-loop at the natural service rate) and return
    the latencies of the FIRST request per (op, bucket, dtype) — the
    latencies the lazy-compile regime hides in its tail. ``reps``
    repeat visits per bucket make the firsts unambiguous firsts."""
    from sq_learn_tpu.serving import MicroBatchDispatcher
    from sq_learn_tpu.streaming import bucket_rows

    rng = np.random.default_rng(42)
    d = MicroBatchDispatcher(reg, background=False,
                             max_batch_rows=max_batch_rows)
    seen, firsts = set(), []
    for _ in range(reps):
        for op in ops:
            for size in LADDER_SIZES:
                rows = rng.normal(size=(size, m)).astype(np.float32)
                key = (op, bucket_rows(size, max_batch_rows, min_rows=8),
                       str(rows.dtype))
                t0 = time.perf_counter()
                d.serve(tenant, op, rows)
                lat = time.perf_counter() - t0
                if key not in seen:
                    seen.add(key)
                    firsts.append(lat)
    d.close()
    return firsts


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from sq_learn_tpu.models import QKMeans, TruncatedSVD
    from sq_learn_tpu.native import native_available
    from sq_learn_tpu.serving import ModelRegistry, kernel_cache_sizes
    from sq_learn_tpu.serving import aot
    from sq_learn_tpu.serving import cache as serve_cache
    from sq_learn_tpu.serving.slo import percentile

    smoke = os.environ.get("SQ_BENCH_SMOKE") == "1"
    n_requests = 600 if smoke else 12_000
    threads = 8
    # best-of-3: this host is load-noisy (CLAUDE.md) and the batched
    # arm's sub-second window is especially exposed to co-tenant spikes
    reps = 1 if smoke else 3
    m = 32
    max_batch_rows, max_wait_ms = 512, 2.0

    rng = np.random.default_rng(0)
    X = (rng.normal(size=(4000, m))
         + 6.0 * rng.integers(0, 8, size=(4000, 1))).astype(np.float32)
    alpha = QKMeans(n_clusters=8, random_state=0, n_init=1).fit(X)
    beta = QKMeans(n_clusters=16, random_state=1, n_init=1).fit(X)
    gamma = TruncatedSVD(n_components=8, random_state=0).fit(X)

    # capacity holds every registration of the run resident — the three
    # base tenants + quantized twins + the megabatch leg's 48 aliases +
    # the cold-start pair; an LRU eviction mid-arm would bill model
    # reloads to whichever arm got unlucky
    reg = ModelRegistry(capacity=64)
    # declared per-tenant SLOs (generous — telemetry, not a gate): the
    # per-tenant slo/budget records in the obs artifact burn against
    # these instead of run-level targets (ISSUE 12)
    reg.register("alpha", alpha, slo_p50_ms=2500.0, slo_p99_ms=5000.0)
    reg.register("beta", beta, slo_p50_ms=2500.0, slo_p99_ms=5000.0)
    reg.register("gamma", gamma, slo_p50_ms=2500.0, slo_p99_ms=5000.0)
    # the quantized leg's registrations: same fitted models, bf16 route
    reg.register("alpha_q", alpha, quantize="bf16")
    reg.register("beta_q", beta, quantize="bf16")
    reg.register("gamma_q", gamma, quantize="bf16")

    tenants = [("alpha", "predict"), ("beta", "predict"),
               ("gamma", "transform"), ("alpha", "transform")]
    tenants_q = [(t + "_q", op) for t, op in tenants]
    requests = _make_requests(rng, n_requests, tenants, m)
    requests_q = [(tenants_q[i % len(tenants_q)][0],
                   tenants_q[i % len(tenants_q)][1], rows)
                  for i, (_, _, rows) in enumerate(requests)]

    # AOT warm: every (kernel, bucket, dtype) executable for the six
    # registered tenants is minted BEFORE the timed arms — the timed
    # serving path compiles nothing (PR 9's jit warm-up pass became the
    # PR 11 warm the production path actually ships)
    reg.warm(["alpha", "beta", "gamma", "alpha_q", "beta_q", "gamma_q"],
             buckets=aot.bucket_ladder(8, max_batch_rows))

    # short shakeout pass (result-cache and scatter paths warm; mints no
    # compiles — the AOT cache serves every signature)
    warm = requests[: min(len(requests), 1024)]
    _run_arm(reg, warm, coalesce=True, threads=threads,
             max_batch_rows=max_batch_rows, max_wait_ms=max_wait_ms)
    _run_arm(reg, warm[:64], coalesce=False, threads=threads,
             max_batch_rows=max_batch_rows, max_wait_ms=max_wait_ms)

    # reps INTERLEAVE the two arms so a host-load spike lands on both,
    # not one (back-to-back arms made the ratio a lottery on a loaded
    # host); per arm the best-qps rep wins (the bench/_common.timed
    # discipline — a preempted rep is not the architecture's number),
    # and p50/p99 are the winning rep's, never cherry-picked across reps
    batched = sequential = None
    for _ in range(reps):
        serve_cache.clear()
        b = _run_arm(reg, requests, coalesce=True, threads=threads,
                     max_batch_rows=max_batch_rows, max_wait_ms=max_wait_ms)
        serve_cache.clear()
        s = _run_arm(reg, requests, coalesce=False, threads=threads,
                     max_batch_rows=max_batch_rows, max_wait_ms=max_wait_ms)
        if batched is None or b["qps"] > batched["qps"]:
            batched = b
        if sequential is None or s["qps"] > sequential["qps"]:
            sequential = s

    # parity spot-check: the served responses must be the estimators'
    from sq_learn_tpu.serving import MicroBatchDispatcher

    d = MicroBatchDispatcher(reg, background=False)
    parity = True
    for tenant, op, rows in requests[:24]:
        out = d.serve(tenant, op, rows)
        est = {"alpha": alpha, "beta": beta, "gamma": gamma}[tenant]
        ref = (est.predict(rows.astype(np.float32)) if op == "predict"
               else est.transform(rows.astype(np.float32)))
        same = (np.array_equal(out, ref) if op == "predict"
                else np.allclose(out, ref, atol=1e-4))
        parity = parity and bool(same)
    d.close()

    serve_cache.clear()
    open_loop = _open_loop(
        reg, requests[: min(len(requests), 2000)],
        rate_qps=batched["qps"] * 0.5, max_batch_rows=max_batch_rows,
        max_wait_ms=max_wait_ms)

    # -- megabatch leg (PR 16): the same 12k rows/ops/sizes spread
    # across 16 alias tenants per base model (each re-registered from
    # the same fitted estimator ⇒ equal fingerprints, shared AOT
    # executables — zero extra compiles). This is the traffic shape
    # megabatching exists for: MANY tenants each sending a trickle, so
    # tenant-scoped batching (the PR 11 path, control arm:
    # native=False, megabatch=False) can only fill thin per-tenant
    # buckets while the treatment arm (dispatcher defaults) coalesces
    # the same rows into full cross-tenant launches. Runs BEFORE the
    # cold-start leg so the zero-compile assertion below has teeth.
    n_alias = 16
    alias_names = []
    for base_name, est in (("alpha", alpha), ("beta", beta),
                           ("gamma", gamma)):
        for j in range(n_alias):
            name = base_name if j == 0 else f"{base_name}{j + 1}"
            if j:  # base names are already registered
                reg.register(name, est, slo_p50_ms=2500.0,
                             slo_p99_ms=5000.0)
            alias_names.append(name)
    # alias phase (i // 4) % n_alias is decorrelated from the stream's
    # tenant (period 4) and dtype (period 2) cycles, so every
    # (fingerprint, op, dtype) group spreads evenly over all 16 names
    requests_m = [(t if (i // 4) % n_alias == 0
                   else f"{t}{(i // 4) % n_alias + 1}", op, rows)
                  for i, (t, op, rows) in enumerate(requests)]
    mega = pr11 = None
    for _ in range(reps):
        serve_cache.clear()
        a = _run_arm(reg, requests_m, coalesce=True, threads=threads,
                     max_batch_rows=max_batch_rows,
                     max_wait_ms=max_wait_ms)
        serve_cache.clear()
        b = _run_arm(reg, requests_m, coalesce=True, threads=threads,
                     max_batch_rows=max_batch_rows,
                     max_wait_ms=max_wait_ms,
                     native=False, megabatch=False)
        if mega is None or a["qps"] > mega["qps"]:
            mega = a
        if pr11 is None or b["qps"] > pr11["qps"]:
            pr11 = b
    if mega["megabatches"] < 1:
        print(json.dumps({"error": "megabatch arm coalesced no "
                          "cross-tenant launches"}), file=sys.stderr)
        return 1
    if pr11["megabatches"] != 0:
        print(json.dumps({"error": "megabatch=False arm still merged "
                          "tenants"}), file=sys.stderr)
        return 1
    compiles_now = sum(kernel_cache_sizes().values())
    if compiles_now != 0:
        print(json.dumps({"error": "serving path minted jit compiles "
                          "post-warm", "compiles": kernel_cache_sizes()}),
              file=sys.stderr)
        return 1
    burst_reqs = [(t, op, rows) for t, op, rows in requests_m[:2000]
                  if op == "predict"]
    burst_speedup, burst_s, per_req_s = _burst_microbench(reg, burst_reqs)
    native_ok = native_available()
    # the amortized burst path must never be materially SLOWER than
    # per-request submits of the same stream (best-of-5 each — pure
    # enqueue cost; 0.9 floor absorbs host-load noise on the sub-20 ms
    # windows, the measured speedup itself lands in the record extras)
    if burst_speedup is not None and burst_speedup < 0.9:
        print(json.dumps({"error": "submit_many burst enqueue slower "
                          "than per-request submits",
                          "speedup": burst_speedup}), file=sys.stderr)
        return 1

    # -- cold-start leg (PR 11): cold vs AOT-warmed first-request-per-
    # bucket latencies, on fresh model shapes (k=9 / k=11 — compile
    # caches are keyed by param shape, so neither arm can ride the main
    # arms' executables)
    cold_est = QKMeans(n_clusters=9, random_state=2, n_init=1).fit(X)
    warm_est = QKMeans(n_clusters=11, random_state=3, n_init=1).fit(X)
    reg.register("cold_t", cold_est)
    reg.register("warm_t", warm_est)
    reg.warm(["warm_t"], buckets=aot.bucket_ladder(8, max_batch_rows))
    cold_firsts = _coldstart_arm(reg, "cold_t", ("predict", "transform"),
                                 m, max_batch_rows)
    warm_firsts = _coldstart_arm(reg, "warm_t", ("predict", "transform"),
                                 m, max_batch_rows)
    cold_p99 = percentile(cold_firsts, 0.99)
    warm_p99 = percentile(warm_firsts, 0.99)

    # -- quantized leg (PR 11): the batched arm against the bf16
    # registrations of the SAME tenants and stream, live audit armed —
    # transfer bytes must halve while every audited draw honors the fold
    os.environ["SQ_SERVE_AUDIT_EVERY"] = "4"
    serve_cache.clear()
    quant = _run_arm(reg, requests_q, coalesce=True, threads=threads,
                     max_batch_rows=max_batch_rows, max_wait_ms=max_wait_ms)
    bytes_f32 = batched["transfer_bytes"]
    bytes_q = quant["transfer_bytes"]
    bytes_ratio = (bytes_q / bytes_f32) if bytes_f32 else None

    # per-tenant attribution reconciliation (ISSUE 12): with a recorder
    # active the dispatcher bills every request — batched-path AND
    # result-cache hits — to exactly one tenant, so the three tenants'
    # per-tenant slo counts must sum to the run aggregate. An
    # attribution leak here would silently corrupt every burn rate the
    # budget ledger reports, so a mismatch fails the bench like a lost
    # request does. (SQ_OBS unset: the dispatcher tracks no tenants by
    # design — the check arms only when the artifact exists.)
    from sq_learn_tpu import obs as _obs

    tenant_counts = batched.get("tenant_requests") or {}
    reconciled = None
    if _obs.enabled():
        reconciled = (len(tenant_counts) == 3
                      and sum(tenant_counts.values())
                      == batched["requests"])
        if not reconciled:
            print(json.dumps({
                "error": "per-tenant request counts do not reconcile "
                         "with the run aggregate",
                "tenant_requests": tenant_counts,
                "aggregate": batched["requests"]}), file=sys.stderr)
            return 1
        # the megabatch arm's honesty gate (ISSUE 16): 48 tenants
        # co-batched into shared launches, every request still billed
        # to exactly one of them
        mega_counts = mega.get("tenant_requests") or {}
        if (len(mega_counts) != 3 * n_alias
                or sum(mega_counts.values()) != mega["requests"]):
            print(json.dumps({
                "error": "megabatched per-tenant counts do not "
                         "reconcile with the run aggregate",
                "tenant_requests": mega_counts,
                "aggregate": mega["requests"]}), file=sys.stderr)
            return 1

    # -- autotune leg (PR 17): the same stream under deliberately
    # over-tight declared SLOs, controller arm vs static arm. Runs only
    # under SQ_OBS=1 (the regress run): the controller follows the
    # disabled-path rule — with no recorder there is nothing to compare.
    autotune = autotune_static = None
    at_cost = st_cost = cost_ratio = None
    at_actions = {}
    if _obs.enabled():
        from sq_learn_tpu.obs import get_recorder
        from sq_learn_tpu.serving.control import theoretical_cost

        tight_ms, delta_slo, eps_slo = 0.01, 1e-3, 0.01
        reg_at = ModelRegistry(capacity=16)
        # per-call override (never env mutation): patience 1 so the
        # relax/recover cycle fits the bench window
        ctl_at = reg_at.controller(patience=1)
        reg_st = ModelRegistry(capacity=16)
        for prefix, r in (("at", reg_at), ("st", reg_st)):
            # alpha/beta: over-tight p99 — the burn the controller must
            # absorb; gamma: generous p99 — the underspend it must bank
            r.register(f"{prefix}_alpha", alpha, quantize=None,
                       slo_p99_ms=tight_ms, slo_eps=eps_slo,
                       slo_delta=delta_slo)
            r.register(f"{prefix}_beta", beta, quantize=None,
                       slo_p99_ms=tight_ms, slo_eps=eps_slo,
                       slo_delta=delta_slo)
            r.register(f"{prefix}_gamma", gamma, quantize=None,
                       slo_p99_ms=5000.0, slo_eps=eps_slo,
                       slo_delta=delta_slo)
        # the plan already re-routed the at_* tenants (int8), so the
        # warm mints their quantized executables before the timed arm
        reg_at.warm(buckets=aot.bucket_ladder(8, max_batch_rows))
        requests_at = [(f"at_{t}", op, rows) for t, op, rows in requests]
        requests_st = [(f"st_{t}", op, rows) for t, op, rows in requests]
        serve_cache.clear()
        autotune = _run_arm(reg_at, requests_at, coalesce=True,
                            threads=threads,
                            max_batch_rows=max_batch_rows,
                            max_wait_ms=max_wait_ms,
                            autotune=True, autotune_every=8)
        serve_cache.clear()
        autotune_static = _run_arm(reg_st, requests_st, coalesce=True,
                                   threads=threads,
                                   max_batch_rows=max_batch_rows,
                                   max_wait_ms=max_wait_ms,
                                   autotune=False)
        arec = get_recorder()
        at_alerts = [a for a in arec.alert_records
                     if str(a.get("tenant", "")).startswith("at_")]
        st_alerts = [a for a in arec.alert_records
                     if str(a.get("tenant", "")).startswith("st_")]
        for r_ in arec.control_records:
            if str(r_.get("tenant", "")).startswith("at_"):
                a_ = r_.get("action")
                at_actions[a_] = at_actions.get(a_, 0) + 1
        contracts = ctl_at.contracts()
        at_cost = sum(c["cost_served"] for c in contracts.values())
        st_cost = len(contracts) * theoretical_cost(delta_slo, None)
        cost_ratio = (st_cost / at_cost) if at_cost else None
        at_counts = autotune.get("tenant_requests") or {}
        if at_alerts:
            print(json.dumps({"error": "the controller arm tripped a "
                              "burn alert", "alerts": at_alerts[:2]}),
                  file=sys.stderr)
            return 1
        if not st_alerts:
            print(json.dumps({"error": "the static arm never tripped an "
                              "alert — the declared SLOs were not "
                              "over-tight"}), file=sys.stderr)
            return 1
        if at_actions.get("degrade", 0) < 1 \
                or at_actions.get("relax", 0) < 1:
            print(json.dumps({"error": "the controller never acted on "
                              "the burn/underspend",
                              "actions": at_actions}), file=sys.stderr)
            return 1
        if (len(at_counts) != 3
                or sum(at_counts.values()) != autotune["requests"]):
            print(json.dumps({
                "error": "controller-arm per-tenant counts do not "
                         "reconcile with the run aggregate",
                "tenant_requests": at_counts,
                "aggregate": autotune["requests"]}), file=sys.stderr)
            return 1
        if cost_ratio is None or cost_ratio < 1.2:
            print(json.dumps({"error": "the controller banked less than "
                              "the 1.2x summed-cost acceptance",
                              "cost_ratio": cost_ratio,
                              "contracts": contracts}), file=sys.stderr)
            return 1

    qps_ratio = (batched["qps"] / sequential["qps"]
                 if sequential["qps"] else None)
    p99_ratio = (sequential["p99_ms"] / batched["p99_ms"]
                 if batched["p99_ms"] else None)
    tag = f"serving_load_{n_requests}req_mixed"
    extras = dict(threads=threads, parity=parity,
                  batched=batched, sequential=sequential,
                  open_loop=open_loop,
                  tenant_requests=tenant_counts,
                  tenants_reconciled=reconciled,
                  kernel_compiles=kernel_cache_sizes(),
                  aot_executables=aot.cache_size())
    emit(f"{tag}_microbatch_qps", batched["qps"], unit="qps",
         vs_baseline=qps_ratio, **extras)
    emit(f"{tag}_microbatch_p99", batched["p99_ms"] / 1e3, unit="s",
         vs_baseline=p99_ratio)
    emit(f"{tag}_coldstart_p99", warm_p99, unit="s",
         vs_baseline=(cold_p99 / warm_p99 if warm_p99 else None),
         vs_baseline_floor=5.0,
         cold_p99_s=round(cold_p99, 4), warm_p99_s=round(warm_p99, 4),
         firsts_per_arm=len(cold_firsts))
    emit(f"{tag}_quant_bytes_ratio", bytes_ratio, unit="ratio",
         vs_baseline=(bytes_f32 / bytes_q if bytes_q else None),
         vs_baseline_floor=1.8,
         bytes_f32=bytes_f32, bytes_quant=bytes_q,
         quant_qps=quant["qps"], quant_p99_ms=quant["p99_ms"])
    emit(f"{tag}_megabatch_qps", mega["qps"], unit="qps",
         vs_baseline=(mega["qps"] / pr11["qps"] if pr11["qps"] else None),
         vs_baseline_floor=1.5,
         pr11_qps=pr11["qps"], megabatches=mega["megabatches"],
         mega_p99_ms=mega["p99_ms"], pr11_p99_ms=pr11["p99_ms"],
         mega_batches=mega["batches"], pr11_batches=pr11["batches"],
         burst_speedup=(round(burst_speedup, 3) if burst_speedup else None),
         burst_s=round(burst_s, 5), per_request_s=round(per_req_s, 5),
         native_available=native_ok)
    if cost_ratio is not None:
        emit(f"{tag}_autotune_cost_ratio",
             round(at_cost / st_cost, 6), unit="ratio",
             vs_baseline=round(cost_ratio, 4), vs_baseline_floor=1.2,
             cost_autotuned=at_cost, cost_static=st_cost,
             autotune_qps=autotune["qps"],
             autotune_p99_ms=autotune["p99_ms"],
             static_qps=autotune_static["qps"],
             static_p99_ms=autotune_static["p99_ms"],
             control_actions=at_actions)
    if not parity:
        print(json.dumps({"error": "serving parity violated"}),
              file=sys.stderr)
        return 1
    if bytes_ratio is None or bytes_ratio > 0.55:
        print(json.dumps({"error": "quantized arm moved more than 0.55x "
                          "the f32 bytes", "ratio": bytes_ratio}),
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
