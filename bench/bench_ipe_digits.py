"""Reference-default q-means configuration: IPE (true-distance-estimate)
mode on digits — the mode the reference ships as its default
(``_dmeans.py`` ``true_distance_estimate=True``), where every E-step
simulates an inner-product-estimation circuit per (sample, centroid).

No classical sklearn twin exists for this surface, but the reference's
own architecture IS a measurable baseline (VERDICT r3 next #6): its
E-step calls one serial python ``ipe()`` per (sample, centroid) pair
(``_dmeans.py:753-761`` — the itertools.product over X × centers), so
its cost for THIS fit is

    per_call_s × n_samples × k × n_iter × n_init

with ``per_call_s`` measured live from the reference's own ``Utility.py``
when the checkout is present (falling back to round 2's recorded 11.4 ms
on this host class). ``vs_baseline`` is that derived serial cost over our
wall-clock; the derivation inputs ride in the extras so the record is
auditable. ``tests/test_reference_differential.py`` pins that both
implementations draw their estimates from identical distributions, which
is what makes the wall-clock comparison apples-to-apples.

Not a BASELINE config, but since round 5 it runs in run_suite.sh as the
suite's one supplementary config (its JSON line is tagged
``baseline_kind="derived"`` and the acceptance gate counts it separately
from the 5 measured configs), so the IPE surface always has a committed
artifact; the TPU window runbook additionally records it last.
"""

import sys
import time
import warnings

import numpy as np

warnings.filterwarnings("ignore")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import emit, probe_backend, smoke_mode, timed  # noqa: E402

#: round-2 fallback (reference Utility.py imported standalone, same host
#: class): one serial python ipe() call — used when /root/reference is
#: absent so the derivation still produces a number
_REF_SECONDS_PER_IPE_CALL = 0.0114

_REF_UTILITY = "/root/reference/sklearn/QuantumUtility/Utility.py"


def _measure_ref_ipe_call(epsilon=0.25, q=5, reps=50):
    """Median wall-clock of one reference ``ipe()`` call, measured from
    the reference's own Utility.py on this host (None when absent).
    Args mirror the E-step's: epsilon=delta/2, Q=5 (_dmeans.py:753)."""
    import importlib.util
    import os

    if not os.path.exists(_REF_UTILITY):
        return None
    try:
        spec = importlib.util.spec_from_file_location("ref_utility_bench",
                                                      _REF_UTILITY)
        mod = importlib.util.module_from_spec(spec)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            spec.loader.exec_module(mod)
        rng = np.random.RandomState(0)
        x, y = rng.randn(64), rng.randn(64)
        mod.ipe(x, y, epsilon, q)  # warm numpy caches
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            mod.ipe(x, y, epsilon, q)
            times.append(time.perf_counter() - t0)
        return float(np.median(times))
    except Exception as exc:
        print(f"# reference ipe() not measurable: {exc}", file=sys.stderr)
        return None


def main():
    probe_backend()
    import jax

    from sklearn.datasets import load_digits

    from sq_learn_tpu.models import QKMeans

    d = load_digits()
    X, y = d.data.astype(np.float32), d.target
    n_init = 1 if smoke_mode() else 10
    if smoke_mode():
        X, y = X[:400], y[:400]

    def fit():
        return QKMeans(n_clusters=10, n_init=n_init, delta=0.5,
                       true_distance_estimate=True,  # IPE mode
                       random_state=0).fit(X)

    t, est = timed(fit, warmup=1, reps=1)
    # the reference runs one ipe() per (sample, centroid) pair per
    # E-step iteration, serially (Pool optional) — _dmeans.py:753-761
    measured = _measure_ref_ipe_call()
    per_call = measured if measured is not None else _REF_SECONDS_PER_IPE_CALL
    pairs_per_iter = X.shape[0] * 10
    ref_serial_s = (per_call * pairs_per_iter
                    * max(1, int(est.n_iter_)) * n_init)
    try:
        from sklearn.metrics import adjusted_rand_score

        ari = round(float(adjusted_rand_score(y, est.labels_)), 3)
    except Exception:
        ari = None
    # baseline_kind="derived" rides in the JSON line: this vs_baseline is
    # a derived serial-cost ratio (order 1e4-1e5), not the suite-wide
    # measured-wall-clock convention — tooling must not mix the scales
    emit("qkmeans_ipe_digits_fit_wallclock", t,
         vs_baseline=ref_serial_s / t,
         baseline_kind="derived",
         backend=jax.default_backend(), n_iter=int(est.n_iter_),
         ari_vs_labels=ari,
         baseline_derivation={
             "ref_ipe_call_s": round(per_call, 6),
             "ref_ipe_call_measured_live": measured is not None,
             "calls": f"{X.shape[0]}x10x{int(est.n_iter_)}x{n_init}",
             "ref_architecture_serial_s": round(ref_serial_s, 1)})


if __name__ == "__main__":
    main()
