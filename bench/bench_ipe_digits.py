"""Reference-default q-means configuration: IPE (true-distance-estimate)
mode on digits — the mode the reference ships as its default
(``_dmeans.py`` ``true_distance_estimate=True``), where every E-step
simulates an inner-product-estimation circuit per (sample, centroid).

No classical twin exists for this surface (sklearn has no quantum noise
model), so ``vs_baseline`` is 1.0 by convention; the meaningful numbers
ride in the extras: our fused-kernel fit wall-clock vs the measured cost
of the reference's own architecture (11.4 ms per serial ``ipe()`` call →
~1.3 h for this fit serial, measured in round 2's differential harness;
``tests/test_reference_differential.py`` pins that both implementations
draw from identical distributions).

Not a BASELINE config — not part of run_suite.sh's 5-config acceptance
gate; the TPU window runbook records it as a supplementary surface.
"""

import sys
import warnings

import numpy as np

warnings.filterwarnings("ignore")
sys.path.insert(0, __file__.rsplit("/", 2)[0])

from bench._common import emit, probe_backend, smoke_mode, timed  # noqa: E402

#: measured in round 2 (reference Utility.py imported standalone, same
#: host class): one serial python ipe() call
_REF_SECONDS_PER_IPE_CALL = 0.0114


def main():
    probe_backend()
    import jax

    from sklearn.datasets import load_digits

    from sq_learn_tpu.models import QKMeans

    d = load_digits()
    X, y = d.data.astype(np.float32), d.target
    n_init = 1 if smoke_mode() else 10
    if smoke_mode():
        X, y = X[:400], y[:400]

    def fit():
        return QKMeans(n_clusters=10, n_init=n_init, delta=0.5,
                       true_distance_estimate=True,  # IPE mode
                       random_state=0).fit(X)

    t, est = timed(fit, warmup=1, reps=1)
    # the reference runs one ipe() per (sample, centroid) pair per
    # E-step iteration, serially (Pool optional)
    pairs_per_iter = X.shape[0] * 10
    ref_serial_s = (_REF_SECONDS_PER_IPE_CALL * pairs_per_iter
                    * max(1, int(est.n_iter_)) * n_init)
    try:
        from sklearn.metrics import adjusted_rand_score

        ari = round(float(adjusted_rand_score(y, est.labels_)), 3)
    except Exception:
        ari = None
    emit("qkmeans_ipe_digits_fit_wallclock", t, vs_baseline=1.0,
         backend=jax.default_backend(), n_iter=int(est.n_iter_),
         ari_vs_labels=ari,
         ref_architecture_serial_estimate_s=round(ref_serial_s, 1),
         ref_vs_ours=round(ref_serial_s / t, 1))


if __name__ == "__main__":
    main()
