#!/bin/bash
# Healthy-tunnel window runbook: bank the round's TPU evidence in strict
# value order, assuming the window may close at any moment (observed
# windows last ~7-20 min; every wedge struck during a >=200 MB upload,
# which chunked_device_put now avoids).
#
#   probe — 60 s; abort immediately if the tunnel is wedged
#   0.    kernel lowering smoke — seconds; names any Mosaic rejection
#         before real window time is spent (exit 2 = fell back to CPU)
#   1/4.  MFU bench — on-device data, no upload risk (VERDICT r2 #2)
#   2/4.  full suite (bench/run_suite.sh) — chunked uploads for #2/#3
#   3/4.  same-window CPU-pinned headline + config #3 — the loaded-host
#         control VERDICT r2 weak #2 asks for (TPU and CPU measured
#         under the same host load, so the ratio is interpretable)
#   4/4.  IPE-mode digits — supplementary surface, lowest value, runs
#         last so a closing window sacrifices it first
#
# All output lands in bench/records/<UTC>_tpu_window/ for committing.
# The persistent compile cache (/tmp/sq_jax_compile_cache) carries
# compiles across windows — a re-run after a mid-window wedge resumes
# cheaply.
set -u
cd "$(dirname "$0")/.."
stamp="$(date -u +%Y%m%dT%H%M%SZ)"
dir="bench/records/${stamp}_tpu_window"
mkdir -p "$dir"

echo "== probe =="
if ! timeout 60 python -c "import jax; print(jax.devices())" \
     > "$dir/probe.txt" 2>&1; then
  echo "tunnel wedged (probe timeout) — aborting window run"
  cat "$dir/probe.txt"
  rm -rf "$dir"   # only the probe log is in it on this path
  exit 1
fi
cat "$dir/probe.txt"

echo "== 0. kernel lowering smoke (seconds; names any Mosaic rejection) =="
timeout 300 python -m bench.tpu_kernel_smoke \
  > "$dir/kernel_smoke.txt" 2>"$dir/kernel_smoke.err"
smoke_rc=$?
cat "$dir/kernel_smoke.txt" 2>/dev/null
if [ "$smoke_rc" -eq 2 ] || [ "$smoke_rc" -ge 124 ]; then
  # rc=2: tunnel wedged between the top probe and the smoke's own probe.
  # rc>=124: the smoke hung (timeout kill) or died on a signal — the
  # wedge struck mid-run before the smoke could classify it. Either way
  # the TPU stages would all burn their probes and record CPU fallbacks
  # masquerading as a window — stop here, like the initial probe abort.
  echo "tunnel lost after initial probe (smoke rc=$smoke_rc) — aborting"
  exit 1
fi
[ "$smoke_rc" -ne 0 ] && echo "kernel smoke rc=$smoke_rc — see" \
  "kernel_smoke.txt (continuing: XLA fallbacks still bank numbers)"

echo "== 1/4 pallas MFU (on-device data) =="
timeout 900 python -m bench.bench_pallas_mfu \
  > "$dir/mfu.txt" 2>"$dir/mfu.err" || echo "mfu rc=$? (continuing)"
tail -2 "$dir/mfu.txt" 2>/dev/null

echo "== 2/4 full suite =="
bash bench/run_suite.sh "$(pwd)/$dir/suite.txt" || echo "suite gate rc=$?"

echo "== 3/4 same-window CPU control (headline + config 3) =="
env -u PYTHONPATH JAX_PLATFORMS=cpu timeout 600 python bench.py \
  > "$dir/cpu_control_headline.txt" 2>/dev/null || true
env -u PYTHONPATH JAX_PLATFORMS=cpu timeout 900 \
  python -m bench.bench_qkmeans_mnist \
  > "$dir/cpu_control_mnist.txt" 2>/dev/null || true
grep -h '^{' "$dir"/cpu_control_*.txt 2>/dev/null

echo "== 3b. chip-path headline (tiny-routing disabled) =="
# The production headline routes digit-scale fits to the host
# (route_tiny_fit_to_host); this run times the CHIP path explicitly so
# the record shows what the fused one-dispatch fit actually costs over
# the tunnel — the measured justification (or refutation) of the rule.
SQ_TINY_FIT_ELEMENTS=0 timeout 600 python bench.py \
  > "$dir/chip_headline_unrouted.txt" 2>/dev/null || true
grep -h '^{' "$dir/chip_headline_unrouted.txt" 2>/dev/null

echo "== 4/4 reference-default IPE mode (supplementary, skippable) =="
timeout 900 python -m bench.bench_ipe_digits \
  > "$dir/ipe.txt" 2>"$dir/ipe.err" || echo "ipe rc=$? (continuing)"
tail -1 "$dir/ipe.txt" 2>/dev/null

echo "window records in $dir — commit them"
