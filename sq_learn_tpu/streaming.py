"""Streaming tiled-ingestion engine: double-buffered host→device row tiles
with on-device accumulation.

Every fit path used to materialize the whole dataset on device before the
first FLOP: ``chunked_device_put`` (``_config.py``) slices the *upload* but
immediately concatenates the pieces back into one device-resident array, so
the monolithic residency cost — and the documented ≥200 MB relay-wedge
trigger (CLAUDE.md) — stayed on the critical path. The out-of-core
factorization literature (Halko et al.'s randomized range finder, which
``ops/linalg.py:randomized_svd`` follows in-core) reduces these workloads to
tile-sequential accumulations, which is exactly the shape XLA's async
dispatch can overlap with transfers. This module is that engine:

- **fixed-byte row tiles**: host data is walked in row slices of at most
  ``stream_tile_bytes()`` bytes, so no single ``jax.device_put`` ever
  exceeds the relay-safe transfer size — by construction, not by policy.
- **double buffering**: the ``device_put`` for tile *i+1* is issued before
  tile *i*'s jitted accumulation kernel is dispatched; nothing calls
  ``block_until_ready`` between tiles, so on an accelerator the upload of
  the next tile overlaps the compute on the current one.
- **bucketed shapes**: tiles are zero-padded to a small set of bucketed row
  counts (the full tile size plus power-of-two tail buckets), so a whole
  pass compiles at most once per bucket — sweeping different dataset sizes
  never recompiles the accumulation kernel for the full-tile bucket.
- **donated accumulators**: every accumulation kernel is jitted with
  ``donate_argnums=(0,)`` so the running state updates in place instead of
  doubling its footprint each tile.

Consumers (qPCA's Gram route, the randomized-SVD range finder, q-means
prestats, streamed predicts) live at the bottom of this module; the mesh
variant — tiles landing sharded, partial Grams reduced over ICI — is
:mod:`sq_learn_tpu.parallel.streaming`.

Resilience (PR 3): every tile's ``device_put`` runs under the transfer
supervisor (:mod:`sq_learn_tpu.resilience.supervisor` — bounded retries,
keyed backoff, per-tile deadline, circuit breaker), and fold passes are
**resumable**: with a checkpoint configured (``SQ_STREAM_CKPT_DIR``, or an
explicit :class:`StreamCheckpoint`), the host-snapshotted accumulator and
tile cursor are saved every M tiles, so a wedge mid-pass resumes from the
last checkpoint instead of re-issuing the uploads that triggered it —
resumed results are bit-identical to an uninterrupted pass (the
accumulator round-trips through npz losslessly and the remaining tiles
replay the same kernels in the same order).

Env knobs: ``SQ_STREAM_TILE_BYTES`` caps the per-tile transfer size
(default: ``SQ_TRANSFER_CHUNK_BYTES``, i.e. the relay-safe 128 MB);
``SQ_STREAM_MIN_BUCKET_ROWS`` floors the tail buckets (default 64 rows);
``SQ_STREAM_CKPT_DIR`` + ``SQ_STREAM_CKPT_EVERY`` (default 8) enable
per-site pass checkpoints; ``SQ_RESILIENCE_STRICT=1`` syncs and checks
the accumulator after every tile, raising
:class:`~sq_learn_tpu.resilience.supervisor.NonFiniteAccumulatorError`
with tile provenance on the first non-finite value (opt-in: the per-tile
host sync defeats the transfer/compute overlap).
"""

import functools
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from . import obs as _obs
from .resilience import faults as _faults
from .resilience import supervisor as _sup
from . import _knobs

__all__ = [
    "StreamCheckpoint",
    "bucket_rows",
    "is_row_source",
    "stream_tile_bytes",
    "plan_row_tiles",
    "stream_tiles",
    "stream_fold",
    "stream_map_rows",
    "streamed_centered_gram",
    "streamed_centered_svd_topk",
    "streamed_randomized_svd",
    "streamed_kmeans_plusplus",
    "streamed_prestats",
    "kernel_cache_sizes",
    "worth_streaming",
]

#: tail tiles are padded up to power-of-two row buckets no smaller than
#: this, bounding the bucket set to ~log2(rows_per_tile) compiled shapes
_MIN_BUCKET_ROWS = _knobs.get_int("SQ_STREAM_MIN_BUCKET_ROWS")


def stream_tile_bytes():
    """Per-tile transfer cap in bytes. ``SQ_STREAM_TILE_BYTES`` overrides;
    the default is the relay-safe ``SQ_TRANSFER_CHUNK_BYTES`` from
    :mod:`sq_learn_tpu._config` (every observed relay wedge hit during a
    single ≥200 MB upload, never during small transfers)."""
    env = _knobs.get_raw("SQ_STREAM_TILE_BYTES")
    if env is not None:
        return int(env)
    from ._config import _TRANSFER_CHUNK_BYTES

    return _TRANSFER_CHUNK_BYTES


def is_row_source(X):
    """True for out-of-core row sources (the shard-store protocol:
    ``shape``/``dtype``/``nbytes``/``fingerprint``/``read_rows`` —
    :mod:`sq_learn_tpu.oocore`). Duck-typed here so the streaming engine
    never imports oocore; a source's rows are read straight from disk
    per tile instead of sliced from a resident ndarray."""
    return all(hasattr(X, a) for a in
               ("shape", "dtype", "nbytes", "fingerprint", "read_rows"))


def worth_streaming(X, max_bytes=None):
    """True when ``X`` is host data large enough that a monolithic upload
    would exceed the per-tile transfer cap — the 'auto' engagement rule
    every streamed consumer shares. jax Arrays are already placed (their
    upload, if any, already happened); only host numpy data streams.
    A disk-backed row source always streams: it has no resident form to
    upload monolithically."""
    if isinstance(X, jax.Array):
        return False
    if is_row_source(X):
        return True
    nbytes = getattr(X, "nbytes", None)
    if nbytes is None:
        return False
    return nbytes > (stream_tile_bytes() if max_bytes is None else max_bytes)


def _bucket_rows(n, full_rows, multiple=1, min_rows=None):
    """Bucketed row count for a tile holding ``n`` valid rows: the full
    tile size for full tiles, else the smallest power-of-two ≥ n (floored
    at ``min_rows``, default the module-level ``_MIN_BUCKET_ROWS`` env
    knob, capped at the full tile size). The bucket set for a pass is
    therefore {full_rows} ∪ {2^j}, so a sweep of dataset sizes compiles
    each kernel at most once per bucket. ``multiple`` rounds every bucket
    up to a device-count multiple (the mesh variant's equal-shard
    requirement)."""
    if n >= full_rows:
        return full_rows
    b = _MIN_BUCKET_ROWS if min_rows is None else int(min_rows)
    while b < n:
        b <<= 1
    b = -(-b // multiple) * multiple
    return min(b, full_rows)


def bucket_rows(n, full_rows, multiple=1, min_rows=None):
    """Public bucket helper: the padded row count a tile of ``n`` valid
    rows dispatches at. ``min_rows`` floors the tail buckets PER CALL —
    consumers with their own bucket regime (the serving dispatcher's
    request-sized 8/64/512 buckets) pick it here instead of mutating the
    process-wide ``SQ_STREAM_MIN_BUCKET_ROWS`` env; ``min_rows=None``
    keeps the env-derived default, bit-identical to the historical
    behavior."""
    return _bucket_rows(int(n), int(full_rows), multiple, min_rows)


def plan_row_tiles(n_rows, row_bytes, max_bytes=None, multiple=1):
    """(rows_per_tile, n_tiles) for streaming ``n_rows`` rows of
    ``row_bytes`` each under the per-tile byte cap; ``multiple`` forces
    the full-tile row count to a device-count multiple for sharded
    landing."""
    if max_bytes is None:
        max_bytes = stream_tile_bytes()
    rows = max(1, int(max_bytes) // max(1, int(row_bytes)))
    rows = min(rows, int(n_rows))
    rows = max(multiple, rows // multiple * multiple)
    n_tiles = -(-int(n_rows) // rows)
    return rows, n_tiles


def padded_rows(n_rows, row_bytes, max_bytes=None, multiple=1):
    """Total row count including the tail tile's bucket padding — the
    buffer size row-output consumers must allocate so the tail tile's
    ``dynamic_update_slice`` never clamps."""
    rows, _ = plan_row_tiles(n_rows, row_bytes, max_bytes, multiple)
    tail = n_rows % rows
    if not tail:
        return n_rows
    return n_rows + (_bucket_rows(tail, rows, multiple) - tail)


def stream_tiles(X, max_bytes=None, device=None, put=None, multiple=1,
                 site=None, start_tile=0):
    """Yield ``(dev_tile, n_valid, start)`` over the row tiles of host
    array ``X``, double-buffered: the ``device_put`` for tile *i+1* is
    issued before tile *i* is yielded (i.e. before the consumer dispatches
    tile *i*'s kernel), and nothing blocks between tiles — on an
    accelerator the next upload overlaps the current tile's compute.
    ``X`` may also be an out-of-core row source (:func:`is_row_source` —
    a :class:`~sq_learn_tpu.oocore.ShardStore`): each tile is then read
    straight from disk (supervised, CRC-verified shard reads) instead of
    sliced from a resident ndarray, so the dataset never materializes on
    the host either.

    Tiles are zero-padded to bucketed row counts (:func:`_bucket_rows`);
    ``n_valid`` is the true row count of each tile and ``start`` its row
    offset in ``X``. ``put`` overrides the placement callable (the mesh
    variant passes a sharded ``device_put``); the default goes through
    ``jax.device_put`` so transfer-accounting tests can monkeypatch it.
    Either way each tile's placement runs under the transfer supervisor
    (:func:`sq_learn_tpu.resilience.supervisor.put`: retries/backoff,
    per-tile deadline, breaker accounting), and armed fault injectors
    (``SQ_FAULTS``) hook the tile boundary here. ``start_tile`` skips the
    leading tiles without staging them — the resume path's whole point is
    NOT re-issuing the uploads already folded in.
    ``site`` names the consuming kernel's retracing-watchdog call site:
    with observability on, each tile's transfer size feeds the
    ``streaming.transfer_bytes``/``streaming.tiles`` counters and each
    planned (bucket, dtype) signature raises the site's compile budget.
    """
    source = is_row_source(X)
    if not source:
        X = np.asarray(X)
    view = None
    if source and hasattr(X, "prefetched"):
        # disk-backed stores opt into the bounded shard readahead
        # (sq_learn_tpu.oocore.prefetch): worker threads materialize and
        # CRC-verify the shards AHEAD of the tile walk; depth 0 returns
        # the store itself (bit-identical serial path). The view starts
        # reading at the first row requested, so a resume's skipped
        # tiles never stage their shards.
        wrapped = X.prefetched()
        if wrapped is not X:
            X = view = wrapped
    # canonicalize on the host exactly like streamed_resident_put: without
    # it the f64→f32 cast would happen device-side, doubling the upload
    # (sources canonicalize at build time; a foreign one casts per tile)
    canonical = jax.dtypes.canonicalize_dtype(X.dtype)
    if not source and X.dtype != canonical:
        X = X.astype(canonical)
    n = X.shape[0]
    rows, n_tiles = plan_row_tiles(n, X.nbytes // max(1, n), max_bytes,
                                   multiple)
    if put is None:
        def put(tile):
            return jax.device_put(tile, device)

    observing = _obs.enabled()
    if observing and site is not None and site in _KERNEL_SITES:
        _obs.watchdog.track(site, _KERNEL_SITES[site])

    def staged(i):
        if _faults._active is not None:
            _faults._active.on_tile(i)  # mid-pass abort injection point
        start = i * rows
        stop = min(start + rows, n)
        valid = stop - start
        bucket = _bucket_rows(valid, rows, multiple)
        tile = X.read_rows(start, stop) if source else X[start:stop]
        if tile.dtype != canonical:
            tile = tile.astype(canonical)
        if valid < bucket:
            pad = np.zeros((bucket - valid,) + tuple(X.shape[1:]),
                           tile.dtype)
            tile = np.concatenate([tile, pad], axis=0)
        if observing:
            _obs.counter_add("streaming.transfer_bytes", int(tile.nbytes))
            _obs.counter_add("streaming.tiles", 1)
            if site is not None and site in _KERNEL_SITES:
                _obs.watchdog.allow(site, (bucket, str(tile.dtype)))
        return _sup.put(put, tile, i, site=site), valid, start

    try:
        nxt = staged(start_tile)
        for i in range(start_tile, n_tiles):
            cur = nxt
            if i + 1 < n_tiles:
                # stage tile i+1 BEFORE the consumer dispatches tile i's
                # kernel: both are async, so the transfer rides under the
                # accumulation compute
                nxt = staged(i + 1)
            yield cur
    finally:
        if view is not None:
            view.close()  # joins the prefetch workers, closes the span


class StreamCheckpoint:
    """Where and how often a fold pass checkpoints: ``path`` is the npz
    file (written atomically via :func:`~sq_learn_tpu.utils.checkpoint.
    save_stream_state`), ``every`` the tile period between snapshots.
    Passing one to :func:`stream_fold` overrides the env-derived default
    (``SQ_STREAM_CKPT_DIR``/``SQ_STREAM_CKPT_EVERY``)."""

    __slots__ = ("path", "every")

    def __init__(self, path, every=None):
        self.path = str(path)
        self.every = int(_knobs.get_int("SQ_STREAM_CKPT_EVERY")
                         if every is None else every)
        if self.every < 1:
            raise ValueError(f"checkpoint every must be >= 1, got {every}")


def _data_digest(Xn, max_rows=64):
    """Content fingerprint of the pass's input: CRC32 over an evenly
    strided sample of up to ``max_rows`` rows, always including the first
    and last. Folded into the checkpoint fingerprint so a checkpoint
    resumes only a rerun over the same data — it catches the realistic
    staleness shapes (different dataset, re-shuffled or re-cleaned rows,
    changed scale), at O(max_rows · row) cost paid once per checkpointed
    pass. It is NOT content-complete: rows between sample points can in
    principle differ undetected, so callers who rewrite data in place
    between runs should clear ``SQ_STREAM_CKPT_DIR`` rather than rely on
    the digest (datasets with ≤ ``max_rows`` rows ARE hashed fully).
    Store-backed passes never use this sample: their fingerprint is the
    manifest's content-complete per-shard-CRC digest (see
    :func:`stream_fold`), so the caveat is closed for the out-of-core
    path."""
    import zlib

    n = Xn.shape[0]
    idx = np.unique(np.linspace(0, max(n - 1, 0), num=min(n, max_rows),
                                dtype=np.int64))
    return zlib.crc32(np.ascontiguousarray(Xn[idx]).tobytes())


def _resolve_checkpoint(checkpoint, site):
    """An explicit ``checkpoint`` wins; else ``SQ_STREAM_CKPT_DIR`` plus a
    ``site`` derives ``<dir>/<site with dots → underscores>.npz``; else
    checkpointing is off. ``checkpoint=False`` opts the fold out even of
    the env-derived default — for folds whose accumulator includes a
    dataset-sized resident buffer, where a periodic host snapshot would
    be an O(n·m) stall, not resilience."""
    if checkpoint is False:
        return None
    if checkpoint is not None:
        if isinstance(checkpoint, StreamCheckpoint):
            return checkpoint
        return StreamCheckpoint(checkpoint)
    ckpt_dir = _knobs.get_raw("SQ_STREAM_CKPT_DIR")
    if not ckpt_dir or site is None:
        return None
    os.makedirs(ckpt_dir, exist_ok=True)
    return StreamCheckpoint(
        os.path.join(ckpt_dir, site.replace(".", "_") + ".npz"))


def _strict_guard():
    return _knobs.get_bool("SQ_RESILIENCE_STRICT")


def _check_finite(acc, site, tile_index, start, n_valid):
    """Host-sync the accumulator and raise with tile provenance on the
    first non-finite value (``SQ_RESILIENCE_STRICT=1`` only)."""
    for j, leaf in enumerate(jax.tree_util.tree_leaves(acc)):
        a = np.asarray(leaf)
        if np.issubdtype(a.dtype, np.floating) and not np.isfinite(a).all():
            raise _sup.NonFiniteAccumulatorError(
                f"non-finite accumulator leaf {j} after tile {tile_index} "
                f"(rows {start}..{start + n_valid}) of pass "
                f"{site or '<unnamed>'}")


def _restore_leaf(host, like):
    """Re-place one checkpointed host leaf like its ``init`` counterpart —
    sharding AND committed-ness included: the mesh variant's replicated
    accumulators resume replicated, while an uncommitted single-device
    init resumes uncommitted (a committed restore would change the jit
    cache key and recompile the very kernel the resume is rejoining)."""
    if isinstance(like, jax.Array):
        if getattr(like, "_committed", False):
            return jax.device_put(jnp.asarray(host, like.dtype),
                                  like.sharding)
        return jnp.asarray(host, like.dtype)
    return jnp.asarray(host)


def stream_fold(X, step, init, *, max_bytes=None, device=None, put=None,
                multiple=1, with_offsets=False, site=None, checkpoint=None,
                pass_tag=None):
    """Fold a donated-accumulator kernel over the row tiles of ``X``.

    ``step(acc, tile)`` (or ``step(acc, tile, n_valid, start)`` with
    ``with_offsets=True``) must be jitted with ``donate_argnums=(0,)`` —
    the engine threads the accumulator through the tiles without ever
    synchronizing, so dispatch of tile *i+1*'s upload and tile *i*'s
    kernel interleave. Tiles arrive zero-padded to bucket shapes; kernels
    that sum over rows need no masking (zero rows contribute nothing),
    kernels that need the true count take ``with_offsets``. ``site``
    (watchdog call site of the underlying kernel) enforces the ≤1 compile
    per (bucket, dtype) invariant after the pass when observability is on.

    With a checkpoint configured (explicit ``checkpoint=`` or
    ``SQ_STREAM_CKPT_DIR`` + ``site``) the pass is **resumable**: every
    ``every`` tiles the accumulator is host-snapshotted (one sync — the
    only blocking points in the pass) and written atomically with the
    tile cursor; a rerun of the same pass — same site, data digest,
    dtype, tile plan, and ``pass_tag`` (the fingerprint) — picks up at
    the cursor and skips the already-folded uploads entirely, and a
    mismatched checkpoint is ignored, never trusted. Consumers that run
    SEVERAL folds over the same site and data (the range finder's power
    iterations) must pass a distinct ``pass_tag`` per fold, or later
    passes could resume an earlier pass's snapshot. ``checkpoint=False``
    opts out even of the env-derived default — required for folds whose
    accumulator contains a dataset-sized resident buffer (the q-means
    ingest), where every snapshot would host-sync and write O(n·m)
    bytes. A completed pass deletes its checkpoint. Resumed results are
    bit-identical to an uninterrupted pass: the npz round-trip is
    lossless and the remaining tiles replay the same kernels in the
    same order.
    """
    source = is_row_source(X)
    if source:
        Xn = X  # out-of-core: rows are read per tile, never materialized
    else:
        Xn = np.asarray(X)
        canonical = jax.dtypes.canonicalize_dtype(Xn.dtype)
        if Xn.dtype != canonical:
            Xn = Xn.astype(canonical)
    if device is not None:
        init = jax.tree.map(lambda a: jax.device_put(a, device), init)
    acc = init
    strict = _strict_guard()
    ckpt = _resolve_checkpoint(checkpoint, site)
    start_tile = 0
    n_tiles = fingerprint = None
    if ckpt is not None:
        from .utils.checkpoint import load_stream_state, save_stream_state

        n = Xn.shape[0]
        rows, n_tiles = plan_row_tiles(n, Xn.nbytes // max(1, n), max_bytes,
                                       multiple)
        # v2: the data digest grew from first/last-row to a strided
        # sample — the version bump keeps a v1 checkpoint from ever
        # matching by coincidence. Store-backed passes use the manifest's
        # CONTENT-COMPLETE fingerprint (CRC over every shard's CRC)
        # instead of the strided sample: any interior mutation of any
        # shard invalidates the checkpoint, closing the documented
        # _data_digest caveat for the out-of-core path.
        data = (f"store:{Xn.fingerprint}" if source
                else f"{_data_digest(Xn):08x}")
        fingerprint = (f"v2|{site}|tag={pass_tag}|shape={tuple(Xn.shape)}"
                       f"|dtype={Xn.dtype}|rows={rows}|multiple={multiple}"
                       f"|data={data}")
        loaded = load_stream_state(ckpt.path, init, fingerprint)
        if loaded is not None:
            host_acc, start_tile = loaded
            acc = jax.tree.map(_restore_leaf, host_acc, init)
            _obs.gauge("resilience.resume_cursor", start_tile, site=site)
            _obs.counter_add("resilience.resumed_passes", 1)
    with _obs.span("streaming.stream_fold", site=site,
                   resumed_from=start_tile or None):
        i = start_tile
        for tile, n_valid, start in stream_tiles(
                Xn, max_bytes, device, put, multiple, site=site,
                start_tile=start_tile):
            if with_offsets:
                acc = step(acc, tile, n_valid, start)
            else:
                acc = step(acc, tile)
            i += 1
            if strict:
                _check_finite(acc, site, i - 1, start, n_valid)
            if ckpt is not None and i < n_tiles and i % ckpt.every == 0:
                host = jax.tree.map(lambda a: np.asarray(a), acc)
                save_stream_state(ckpt.path, host, i, fingerprint)
    if ckpt is not None:
        # a finished pass must not leave state a LATER same-tagged pass
        # (or a rerun) could mistake for its own mid-pass snapshot — the
        # torn-write fallback copy included
        for stale in (ckpt.path, str(ckpt.path) + ".prev"):
            if os.path.exists(stale):
                os.remove(stale)
    if _obs.enabled() and site is not None and site in _KERNEL_SITES:
        # track() is idempotent (first call anchors the compile baseline);
        # re-calling here covers a recorder enabled mid-pass
        _obs.watchdog.track(site, _KERNEL_SITES[site])
        _obs.watchdog.observe(site)
    return acc


def stream_map_rows(X, fn, *, max_bytes=None, device=None, put=None,
                    multiple=1, with_offsets=False, site=None):
    """Apply a row-wise jitted ``fn(tile)`` to every tile and assemble the
    (host) row-aligned outputs — the streamed-inference primitive
    (labels, neighbor lists): tile *i+1* uploads while ``fn`` runs on
    tile *i*; only the small per-tile outputs come back. ``fn`` may
    return an array or a tuple of arrays whose leading axis is the tile
    row axis; with ``with_offsets`` it is called as ``fn(tile, start)``
    (tile-decorrelated RNG streams fold the offset into their key)."""
    outs = []
    with _obs.span("streaming.stream_map_rows", site=site):
        for tile, n_valid, start in stream_tiles(X, max_bytes, device, put,
                                                 multiple, site=site):
            out = fn(tile, start) if with_offsets else fn(tile)
            outs.append((out, n_valid))
    if _obs.enabled() and site is not None and site in _KERNEL_SITES:
        _obs.watchdog.track(site, _KERNEL_SITES[site])
        _obs.watchdog.observe(site)
    first = outs[0][0]
    if isinstance(first, tuple):
        return tuple(
            np.concatenate([np.asarray(o[j])[:v] for o, v in outs], axis=0)
            for j in range(len(first)))
    return np.concatenate([np.asarray(o)[:v] for o, v in outs], axis=0)


# ---------------------------------------------------------------------------
# Accumulation kernels (module-level jits: one compile cache per process,
# at most one entry per (bucket, dtype))
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, donate_argnums=(0,))
def _gram_colsum_step(acc, tile):
    """acc = (G, colsum) ← (G + tileᵀ·tile, colsum + Σrows). Zero-padded
    rows contribute nothing to either sum."""
    G, colsum = acc
    return G + tile.T @ tile, colsum + jnp.sum(tile, axis=0)


@functools.partial(jax.jit, donate_argnums=(0,))
def _colsum_step(acc, tile):
    """acc ← acc + Σrows — the cheap column-mean pass (randomized-SVD
    centering); zero-padded rows contribute nothing."""
    return acc + jnp.sum(tile, axis=0)


@functools.partial(jax.jit, donate_argnums=(0,))
def _ingest_step(acc, tile, n_valid, start):
    """Resident-assembly accumulator: write the tile's rows into the
    donated device buffer (in place — no concatenate, no 2× peak) while
    accumulating column sums / square-sums. ``start`` is traced, so every
    tile of a bucket reuses one compiled kernel."""
    buf, colsum, sqsum = acc
    buf = lax.dynamic_update_slice(buf, tile, (start,) + (0,) * (tile.ndim - 1))
    return (buf, colsum + jnp.sum(tile, axis=0),
            sqsum + jnp.sum(tile * tile, axis=0))


@functools.partial(jax.jit, donate_argnums=(0,))
def _kpp_score_step(acc, tile, n_valid, start, cand, closest, weights):
    """One tile of a streamed k-means++ scoring round: distances of the
    trial candidates ``cand`` (T, m) to the tile's rows, the would-be
    closest-D² update against the resident ``closest`` buffer, and the
    per-trial weighted potential partials. Zero-weight (padding) rows
    contribute nothing to the potentials; their buffer values are
    multiplied by weight 0 wherever they are consumed."""
    buf, pots = acc
    xsq = jnp.sum(tile * tile, axis=1)
    c_sq = jnp.sum(cand * cand, axis=1)
    d2 = jnp.maximum(
        xsq[None, :] + c_sq[:, None] - 2.0 * (cand @ tile.T), 0.0)
    rows = tile.shape[0]
    cl = lax.dynamic_slice(closest, (start,), (rows,))
    wt = lax.dynamic_slice(weights, (start,), (rows,))
    nc = jnp.minimum(cl[None, :], d2)
    buf = lax.dynamic_update_slice(buf, nc, (0, start))
    return buf, pots + jnp.sum(nc * wt[None, :], axis=1)


@functools.partial(jax.jit, donate_argnums=(0,))
def _assemble_step(acc, tile, n_valid, start):
    """Pure resident assembly: write the tile into the donated device
    buffer, nothing else — the streamed replacement for the deprecated
    ``chunked_device_put`` slice-and-concatenate (which held every slice
    AND the concatenated output live: a 2× peak the donated in-place
    write avoids)."""
    return lax.dynamic_update_slice(acc, tile,
                                    (start,) + (0,) * (tile.ndim - 1))


@functools.partial(jax.jit, donate_argnums=(0,))
def _sketch_cheap_step(acc, tile):
    """One tile of the out-of-core sketch cheap pass: running max row
    sq-norm (η), column square-sum partials (‖A‖_F² / max column), and
    max |entry| — every deterministic input the sketch engine's bound
    math needs, accumulated without the matrix ever being resident.
    Zero-padded rows contribute 0 to each (power sums are non-negative,
    so a padding row can never win a max over real data)."""
    eta, colsq, amax = acc
    sq = tile * tile
    return (jnp.maximum(eta, jnp.max(jnp.sum(sq, axis=1))),
            colsq + jnp.sum(sq, axis=0),
            jnp.maximum(amax, jnp.max(jnp.abs(tile))))


@functools.partial(jax.jit, donate_argnums=(0,))
def _matmul_accum_step(acc, tile, Q):
    """acc ← acc + tileᵀ·(tile·Q) — one power-iteration pass of the
    Gram-based range finder, never materializing the (n, size) product."""
    return acc + tile.T @ (tile @ Q)


@functools.partial(jax.jit, donate_argnums=(0,))
def _project_rows_step(acc, tile, n_valid, start, Q):
    """acc[start:start+rows] ← tile·Q (donated row-output buffer)."""
    return lax.dynamic_update_slice(acc, tile @ Q, (start, 0))


@functools.partial(jax.jit, donate_argnums=(0,))
def _qtb_step(acc, tile, n_valid, start, Qn):
    """acc ← acc + Qn[start:start+rows]ᵀ·tile — the B = Qᵀ·A pass of the
    range finder; ``Qn`` is the (row-padded) on-device orthonormal basis,
    sliced per tile with a traced offset. Zero-padded tile rows pair with
    zero-padded Qn rows, so they cancel."""
    rows = tile.shape[0]
    Qt = lax.dynamic_slice(Qn, (start, 0), (rows, Qn.shape[1]))
    return acc + Qt.T @ tile


@functools.partial(jax.jit, donate_argnums=(0,))
def _topk_u_step(acc, tile, n_valid, start, mean, Vk_over_s):
    """acc[start:start+rows] ← (tile − mean)·(Vₖᵀ/σ) — the partial-U
    assembly pass of the streamed Gram-route SVD. The subtraction uses a
    masked mean so zero-padded rows stay exactly zero (they are sliced
    away by the caller anyway, but must not pollute the buffer when a
    tail bucket overlaps the next tile's offset — it never does; this is
    pure hygiene)."""
    rows = tile.shape[0]
    mask = (jnp.arange(rows) < n_valid).astype(tile.dtype)[:, None]
    Uk = ((tile - mean) * mask) @ Vk_over_s
    return lax.dynamic_update_slice(acc, Uk, (start, 0))


# xla cost accounting (obs.xla): each kernel's first call per (bucket,
# dtype) signature under an active run records flops / bytes-accessed /
# peak-HBM as an 'xla_cost' line keyed by its watchdog site. The wrapper
# forwards _cache_size, so the watchdog and kernel_cache_sizes() keep
# reading compile counts through it; disabled mode is one global read.
from .obs import xla as _xla  # noqa: E402  (after kernel definitions)

_gram_colsum_step = _xla.instrument("streaming.gram_colsum",
                                    _gram_colsum_step)
_colsum_step = _xla.instrument("streaming.colsum", _colsum_step)
_ingest_step = _xla.instrument("streaming.ingest", _ingest_step)
_kpp_score_step = _xla.instrument("streaming.kpp_score", _kpp_score_step)
_assemble_step = _xla.instrument("streaming.assemble", _assemble_step)
_sketch_cheap_step = _xla.instrument("streaming.sketch_cheap",
                                     _sketch_cheap_step)
_matmul_accum_step = _xla.instrument("streaming.matmul_accum",
                                     _matmul_accum_step)
_project_rows_step = _xla.instrument("streaming.project_rows",
                                     _project_rows_step)
_qtb_step = _xla.instrument("streaming.qtb", _qtb_step)
_topk_u_step = _xla.instrument("streaming.topk_u", _topk_u_step)

#: kernel registry: short name → jitted step. Watchdog call sites are
#: ``"streaming.<short name>"``; :func:`kernel_cache_sizes` reads the same
#: registry.
_KERNELS = {
    "assemble": _assemble_step,
    "gram_colsum": _gram_colsum_step,
    "colsum": _colsum_step,
    "ingest": _ingest_step,
    "kpp_score": _kpp_score_step,
    "matmul_accum": _matmul_accum_step,
    "sketch_cheap": _sketch_cheap_step,
    "project_rows": _project_rows_step,
    "qtb": _qtb_step,
    "topk_u": _topk_u_step,
}

#: watchdog site → kernel (what stream_fold/stream_tiles resolve ``site``
#: against)
_KERNEL_SITES = {f"streaming.{name}": fn for name, fn in _KERNELS.items()}


def kernel_cache_sizes():
    """Compile-cache entry count per streaming kernel — the observability
    hook the bench and the no-per-shape-recompile tests read. Each entry
    corresponds to one (bucket shape, dtype) signature."""
    return {name: int(fn._cache_size()) for name, fn in _KERNELS.items()}


# ---------------------------------------------------------------------------
# Consumers
# ---------------------------------------------------------------------------


def streamed_centered_gram(X, *, max_bytes=None, device=None,
                           checkpoint=None):
    """(mean, G_centered, n) of host data, built tile-by-tile — X is never
    resident on device.

    One pass accumulates the raw Gram ``G = Σ tileᵀ·tile`` and the column
    sum; the centered Gram follows from the rank-one identity
    ``Xcᵀ·Xc = XᵀX − n·mean·meanᵀ`` (exact in exact arithmetic; in f32 it
    trades the monolithic path's last-ulp agreement for never holding X —
    fine at explained-variance scale, not for σ ≈ 0 tails of badly
    uncentered data). ``checkpoint`` (or ``SQ_STREAM_CKPT_DIR``) makes
    the Gram pass resumable — see :func:`stream_fold`."""
    if not is_row_source(X):
        X = np.asarray(X)
    n, m = X.shape
    dtype = jax.dtypes.canonicalize_dtype(X.dtype)
    init = (jnp.zeros((m, m), dtype), jnp.zeros((m,), dtype))
    with _obs.span("streaming.centered_gram", n=n, m=m):
        G, colsum = stream_fold(X, _gram_colsum_step, init,
                                max_bytes=max_bytes, device=device,
                                site="streaming.gram_colsum",
                                checkpoint=checkpoint)
        mean, Gc = _finalize_centered_gram(G, colsum, n)
    return mean, Gc, n


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("n",))
def _finalize_centered_gram(G, colsum, n):
    mean = colsum / n
    return mean, G - n * jnp.outer(mean, mean)


def streamed_centered_svd_topk(X, n_left, *, compute_dtype=None,
                               max_bytes=None, device=None):
    """Streamed twin of :func:`~sq_learn_tpu.ops.linalg.centered_svd_topk`:
    (mean, Uk, S, Vt) of a tall host matrix via the tiled centered Gram,
    materializing only the first ``n_left`` columns of U.

    Two streamed passes: (1) Gram + column mean, (2) the (n, k) partial-U
    block assembled into a donated device buffer — X itself is never
    device-resident. ``compute_dtype`` applies to the U-block GEMM (the
    Gram pass accumulates in the input dtype: the tile Grams are the
    accuracy-critical reduction).
    """
    from .ops.linalg import gram_spectrum, svd_flip_v

    if not is_row_source(X):
        X = np.asarray(X)
    n, m = X.shape
    mean, Gc, _ = streamed_centered_gram(X, max_bytes=max_bytes,
                                         device=device)
    S, V, safe = gram_spectrum(Gc)
    _, Vt = svd_flip_v(None, V.T)
    k = int(n_left)
    Vk_over_s = (Vt[:k] / safe[:k, None]).T  # (m, k)
    cdt = S.dtype if compute_dtype is None else jnp.dtype(compute_dtype)
    Vk_over_s = Vk_over_s.astype(cdt)
    mean_c = mean.astype(cdt)

    def step(acc, tile, n_valid, start):
        return _topk_u_step(acc, tile.astype(cdt), n_valid, start,
                            mean_c, Vk_over_s)

    # the output buffer is padded like the tiles: the tail bucket's
    # dynamic_update_slice must never clamp (a clamped start would shift
    # the tail rows onto earlier ones)
    n_pad = padded_rows(n, X.nbytes // max(1, n), max_bytes)
    Uk = stream_fold(X, step, jnp.zeros((n_pad, k), cdt),
                     max_bytes=max_bytes, device=device, with_offsets=True,
                     site="streaming.topk_u")
    return mean, Uk[:n].astype(S.dtype), S, Vt


def streamed_randomized_svd(key, X, n_components, *, n_oversamples=10,
                            n_iter=4, center=False, max_bytes=None,
                            device=None, flip=True):
    """Streamed randomized truncated SVD (Halko et al.) of host data:
    the range finder and power iterations run as tiled passes — per pass,
    one (m, size) accumulation ``Σ tileᵀ·(tile·Q)`` — so X is never
    device-resident and every transfer stays under the tile cap.

    Mathematically the same subspace iteration as
    :func:`~sq_learn_tpu.ops.linalg.randomized_svd` (QR-renormalized
    power iterations on AᵀA), reassociated tile-wise; results agree to
    the usual randomized-SVD accuracy, not bitwise. ``center=True``
    factors X − mean via the rank-one correction, never materializing the
    centered matrix. Returns (U, S, Vt) — plus ``mean`` when centering —
    with U (n, k) device-resident.
    """
    from .ops.linalg import svd_flip_v

    X = np.asarray(X)
    n, m = X.shape
    dtype = jax.dtypes.canonicalize_dtype(X.dtype)
    size = min(int(n_components) + int(n_oversamples), min(n, m))

    # pass 0: column mean (only when factoring the centered matrix)
    mean = None
    if center:
        colsum = stream_fold(X, _colsum_step, jnp.zeros((m,), dtype),
                             max_bytes=max_bytes, device=device,
                             site="streaming.colsum")
        mean = colsum / n

    Q = jax.random.normal(key, (m, size), dtype=dtype)
    for it in range(max(1, int(n_iter))):
        # pass_tag: the power iterations are same-site, same-data folds —
        # without a distinct tag, iteration k could resume iteration j's
        # checkpoint after a mid-sweep interrupt
        F = stream_fold(X, functools.partial(_matmul_accum_step, Q=Q),
                        jnp.zeros((m, size), dtype),
                        max_bytes=max_bytes, device=device,
                        site="streaming.matmul_accum",
                        pass_tag=f"power_iter_{it}")
        if center:
            # (Xcᵀ·Xc)·Q = AᵀA·Q − n·mean·(meanᵀ·Q)
            F = F - n * jnp.outer(mean, mean @ Q)
        Q, _ = jnp.linalg.qr(F)

    # Y = Xc·Q assembled row-tile-wise into a donated (n_pad, size) buffer
    n_pad = padded_rows(n, X.nbytes // max(1, n), max_bytes)
    Y = stream_fold(
        X, functools.partial(_project_rows_step, Q=Q),
        jnp.zeros((n_pad, size), dtype),
        max_bytes=max_bytes, device=device, with_offsets=True,
        site="streaming.project_rows")
    if center:
        Y = Y - (mean @ Q)[None, :]
    # zero-pad rows of Y must not enter the QR basis: re-zero them (the
    # centering shift above made them −meanᵀQ)
    if n_pad > n:
        Y = Y.at[n:].set(0.0)
    Qn, _ = jnp.linalg.qr(Y)  # (n_pad, size); padded rows stay zero

    B = stream_fold(
        X, functools.partial(_qtb_step, Qn=Qn),
        jnp.zeros((size, m), dtype),
        max_bytes=max_bytes, device=device, with_offsets=True,
        site="streaming.qtb")
    if center:
        B = B - jnp.outer(jnp.sum(Qn[:n], axis=0), mean)
    Uhat, S, Vt = jnp.linalg.svd(B, full_matrices=False)
    U = (Qn @ Uhat)[:n]
    if flip:
        U, Vt = svd_flip_v(U, Vt)
    k = int(n_components)
    out = (U[:, :k], S[:k], Vt[:k])
    return out + (mean,) if center else out


def streamed_kmeans_plusplus(key, X, n_clusters, *, weights=None,
                             n_local_trials=None, max_bytes=None,
                             device=None):
    """Greedy best-of-trials k-means++ over HOST data, one streamed pass
    per round — the out-of-core init primitive (ROADMAP item 3): X is
    never device-resident, only the (n,) closest-D² buffer and the
    (trials, n) scoring accumulator live on device, and every candidate
    row crosses as part of a bounded tile under the transfer supervisor.
    Each round's scoring kernel (``streaming.kpp_score``) compiles at
    most once per (bucket, dtype) — the ≤1-compile-per-bucket invariant,
    watchdog-enforced like every streaming kernel.

    Same distribution family as the resident kernels
    (:mod:`sq_learn_tpu.parallel.init`): weighted first pick, then k−1
    rounds of D² sampling keeping the best of ``n_local_trials``
    candidates; streams are engine-local, as everywhere else. Returns
    ``(centers (k, m) ndarray, indices (k,) ndarray)``.
    """
    import math as _math

    X = np.asarray(X)
    n, m = X.shape
    dtype = jax.dtypes.canonicalize_dtype(X.dtype)
    if n_local_trials is None:
        n_local_trials = 2 + int(_math.log(n_clusters))
    n_pad = padded_rows(n, X.nbytes // max(1, n), max_bytes)
    w = (np.ones(n, dtype) if weights is None
         else np.asarray(weights, dtype))
    w_dev = jnp.asarray(np.pad(w, (0, n_pad - n)))
    with _obs.span("streaming.kmeans_plusplus", n=n, m=m,
                   n_clusters=int(n_clusters)):
        key, k0 = jax.random.split(key)
        first = int(jax.random.categorical(
            k0, jnp.log(jnp.maximum(jnp.asarray(w), 1e-38))))
        indices = [first]
        centers = [np.ascontiguousarray(X[first], dtype)]
        closest = jnp.full((n_pad,), jnp.inf, dtype)

        def score_pass(cand_rows, closest, tag):
            init = (jnp.zeros((cand_rows.shape[0], n_pad), dtype),
                    jnp.zeros((cand_rows.shape[0],), dtype))
            step = functools.partial(_kpp_score_step,
                                     cand=jnp.asarray(cand_rows),
                                     closest=closest, weights=w_dev)
            return stream_fold(X, step, init, max_bytes=max_bytes,
                               device=device, with_offsets=True,
                               site="streaming.kpp_score",
                               checkpoint=False, pass_tag=tag)

        # the seeding pass replicates the first center across the trial
        # axis so every round's kernel shares ONE (trials, bucket) shape —
        # the ≤1-compile-per-bucket invariant would otherwise be broken by
        # a (1, bucket) first-round signature
        buf, _ = score_pass(
            np.broadcast_to(centers[0], (n_local_trials, m)), closest,
            "round_0")
        closest = buf[0]
        for c in range(1, int(n_clusters)):
            key, kc = jax.random.split(key)
            pot = closest * w_dev
            cum = jnp.cumsum(pot)
            draws = jax.random.uniform(kc, (n_local_trials,), dtype) * cum[-1]
            cand_idx = np.asarray(
                jnp.clip(jnp.searchsorted(cum, draws), 0, n - 1))
            cand_rows = np.ascontiguousarray(X[cand_idx], dtype)
            buf, pots = score_pass(cand_rows, closest, f"round_{c}")
            best = int(jnp.argmin(pots))
            closest = buf[best]
            indices.append(int(cand_idx[best]))
            centers.append(cand_rows[best])
    return np.stack(centers), np.asarray(indices, np.int64)


def streamed_prestats(X, *, quantum=False, mu_grid=(), mu_blocked=False,
                      sketch_idx=None, max_bytes=None, device=None):
    """Streamed twin of :func:`~sq_learn_tpu.models.qkmeans.fit_prestats`:
    assemble the device copy tile-by-tile into ONE donated buffer (bounded
    transfers, no concatenate, upload overlapped with the running
    column-sum/square-sum accumulation), then finalize mean / centering /
    row norms / tolerance scale on device.

    q-means fundamentally needs the data resident (the Lloyd loop sweeps
    it every iteration), so unlike the Gram consumers this path keeps X on
    device — what streaming buys is the bounded per-transfer size and the
    in-place assembly. Returns the same dict as ``fit_prestats``.

    ``sketch_idx`` ((s,) sampled row indices, quantum only) swaps the
    exact σ_min Gram + μ sweep for the sketched component kernel of
    :mod:`sq_learn_tpu.sketch.engine` running on the resident buffer —
    zero extra transfers; the raw components land under a ``"sketch"``
    key and the caller folds the certified bounds in on host.
    """
    X = np.asarray(X)
    n, m = X.shape
    dtype = jax.dtypes.canonicalize_dtype(X.dtype)
    n_pad = padded_rows(n, X.nbytes // max(1, n), max_bytes)
    init = (jnp.zeros((n_pad, m), dtype), jnp.zeros((m,), dtype),
            jnp.zeros((m,), dtype))
    # checkpoint=False: the accumulator IS the (n_pad, m) resident
    # buffer, so an env-derived checkpoint would host-sync and write a
    # dataset-sized npz every SQ_STREAM_CKPT_EVERY tiles — an O(n·m)
    # periodic stall, not resilience. Mid-fit recovery for q-means lives
    # at the Lloyd level (utils/checkpoint.save_pytree), not here.
    buf, colsum, sqsum = stream_fold(X, _ingest_step, init,
                                     max_bytes=max_bytes, device=device,
                                     with_offsets=True,
                                     site="streaming.ingest",
                                     checkpoint=False)
    out = {}
    if quantum:
        # the quantum runtime-model stats read the UNCENTERED matrix;
        # compute them on the resident buffer before it is donated away
        # by the centering finalize
        if sketch_idx is not None:
            _obs.xla.capture("sketch.prestats_kernel",
                             _prestats_quantum_sketched, buf, sketch_idx,
                             n=n, mu_grid=mu_grid)
            out["sketch"] = _prestats_quantum_sketched(buf, sketch_idx,
                                                       n=n, mu_grid=mu_grid)
        else:
            out.update(_prestats_quantum(buf, n, mu_grid, mu_blocked))
    import warnings

    with warnings.catch_warnings():
        # with a ragged tail the (n_pad, m) buffer cannot alias the
        # (n, m) centered output; XLA warns the donation went unused —
        # expected, and the buffer is dead after this call either way
        warnings.filterwarnings("ignore",
                                message="Some donated buffers were not")
        mean, Xc, xsq, var_mean = _finalize_prestats(buf, colsum, sqsum, n)
    out.update({"mean": mean, "Xc": Xc, "xsq": xsq, "var_mean": var_mean})
    return out


def streamed_resident_put(x, device=None, max_bytes=None):
    """Whole-array host→device placement through the streaming engine —
    the supervised successor of the removed ``chunked_device_put``
    slicing branch (``_config.py``).

    Each bounded tile crosses under the transfer supervisor
    (retry/backoff, deadline, breaker accounting) with double-buffered
    uploads and the ``streaming.assemble`` watchdog/xla-cost site, and
    assembles IN PLACE into one donated device buffer — no
    slice-then-concatenate 2× peak. Semantically identical to
    ``jax.device_put(np.asarray(x), device)`` (dtype canonicalization
    included)."""
    Xn = np.asarray(x)
    canonical = jax.dtypes.canonicalize_dtype(Xn.dtype)
    if Xn.dtype != canonical:
        Xn = Xn.astype(canonical)
    n = Xn.shape[0]
    n_pad = padded_rows(n, Xn.nbytes // max(1, n), max_bytes)
    init = jnp.zeros((n_pad,) + Xn.shape[1:], Xn.dtype)
    buf = stream_fold(Xn, _assemble_step, init, max_bytes=max_bytes,
                      device=device, with_offsets=True,
                      site="streaming.assemble", checkpoint=False)
    # a ragged tail pads the buffer past n; the slice is the one
    # remaining transient copy (bounded by a single tile's bucket)
    return buf[:n] if n_pad > n else buf


def streamed_spectral_stats(X, mu_grid, *, delta_stat=None, sketch="auto",
                            rng=None, max_bytes=None, device=None,
                            audit=False):
    """Out-of-core sketched spectral statistics: only the (s, m) sampled
    rows and the (m,)-sized cheap-pass accumulators ever live on device —
    X streams tile-by-tile through :func:`stream_fold` (bounded supervised
    transfers, ``streaming.sketch_cheap`` site, ≤1 compile per bucket)
    while the sample kernel runs async on the already-uploaded sample.
    This is the route for matrices too large to sit resident whose cost
    model still wants (σ_min, μ, ‖A‖_F, η) with certified bounds.

    Zero budget / tiny shapes fall back to the exact engine kernels
    (which do require a resident upload — the exactness contract wins
    over memory by convention; callers that cannot afford it pass an
    explicit ``sketch`` row count). Returns a
    :class:`~sq_learn_tpu.sketch.engine.SpectralStats`.
    """
    from .sketch import engine as _sk

    X = np.asarray(X)
    n, m = X.shape
    if delta_stat is None:
        delta_stat = _sk.sketch_delta_stat()
    rows = _sk.resolve_sketch_rows(n, m, sketch) if delta_stat > 0 else 0
    if not rows:
        return _sk.exact_spectral_stats(X, mu_grid)
    if rng is None:
        rng = np.random.default_rng(0)
    dtype = jax.dtypes.canonicalize_dtype(X.dtype)
    # sample indices BEFORE any dispatch (head-of-line blocking contract)
    idx = _sk.sample_indices(rng, n, rows)
    with _obs.span("sketch.streamed_stats", n=n, m=m, rows=rows):
        Xs = jnp.asarray(np.ascontiguousarray(X[idx], dtype))
        scale = jnp.asarray(n / rows, dtype)
        handle = _sk.dispatch_sample(Xs, scale, tuple(mu_grid), True)
        init = (jnp.zeros((), dtype), jnp.zeros((m,), dtype),
                jnp.zeros((), dtype))
        eta, colsq, amax = stream_fold(
            X, _sketch_cheap_step, init, max_bytes=max_bytes,
            device=device, site="streaming.sketch_cheap")
        colsq = np.asarray(colsq, np.float64)
        header = (float(eta), float(np.sqrt(colsq.sum())), float(amax),
                  float(colsq.max()))
        disp = _sk._HostDispatch(handle, header, n, rows, m,
                                 tuple(mu_grid), True, idx)
        return _sk.finalize_host(disp, delta_stat,
                                 X_for_audit=X if audit else None)


@functools.partial(jax.jit, static_argnames=("n", "mu_grid"))
def _prestats_quantum_sketched(buf, idx, *, n, mu_grid):
    """Sketched twin of :func:`_prestats_quantum`: the component kernel of
    the spectral-stats engine over the resident buffer's real rows — one
    extra dispatch on data already on device, replacing the O(n·m²)-class
    exact sweep (``sketch.prestats_kernel`` xla-cost site)."""
    from .sketch.engine import sketch_components_traced

    return sketch_components_traced(buf[:n], idx, mu_grid)


@functools.partial(jax.jit, static_argnames=("n", "mu_grid", "mu_blocked"))
def _prestats_quantum(buf, n, mu_grid, mu_blocked):
    from .ops.linalg import row_norms, smallest_singular_value
    from .ops.quantum.norms import _mu_grid_blocked, _mu_grid_unblocked

    X = buf[:n]
    sweep = _mu_grid_blocked if mu_blocked else _mu_grid_unblocked
    return {
        "eta": jnp.max(row_norms(X, squared=True)),
        "mu_vals": sweep(X, mu_grid),
        "frob": jnp.linalg.norm(X),
        "sigma_min": smallest_singular_value(X),
    }


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("n",))
def _finalize_prestats(buf, colsum, sqsum, n):
    from .ops.linalg import row_norms

    mean = colsum / n
    Xc = buf[:n] - mean
    xsq = row_norms(Xc, squared=True)
    var_mean = jnp.mean(jnp.maximum(sqsum / n - mean * mean, 0.0))
    return mean, Xc, xsq, var_mean
