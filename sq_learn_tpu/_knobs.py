"""Single source of truth for every environment knob the project reads.

The paper's thesis makes (ε, δ) explicit *contracts*; this module does the
same for the configuration surface. Every ``os.environ`` read in
``sq_learn_tpu/`` (and the bench/test trees it ships with) goes through the
typed accessors below, against a declarative registry entry carrying the
knob's name, kind, default, owning scope, one-line doc, and the
documentation anchor (the file whose prose describes it). The static
checker (:mod:`sq_learn_tpu.analysis`, rule ``knob-registry``) enforces
that no raw read exists outside this module, that every name passed to an
accessor is registered, and that the registry and the knob tables in
``CLAUDE.md`` / ``docs/`` cannot drift apart (``--check-docs``).

Runtime contract:

- Accessors validate the name against the registry and raise
  :class:`UnknownKnobError` on a miss — a typo'd knob read fails loudly at
  the call site instead of silently reading the default forever.
- ``kind="flag"`` knobs follow the project's two historical spellings in
  one rule: a knob whose registered default is **False** is enabled only
  by ``"1"`` (``SQ_OBS_STRICT=1``); a knob whose default is **True** stays
  enabled unless set to ``"0"`` (``SQ_SERVE_CACHE=0``). Both match the
  pre-registry call sites bit-for-bit.
- Family entries (name ending ``*``, e.g. ``SQ_REGRESS_TOL_*``) register a
  whole prefix; dynamic reads like ``SQ_REGRESS_TOL_LATENCY`` resolve
  through them.
- This module imports nothing from the package and nothing heavy — it is
  safe at interpreter start, inside sitecustomize'd processes, and from
  worker threads.
"""

import os

__all__ = [
    "Knob",
    "REGISTRY",
    "UnknownKnobError",
    "get_bool",
    "get_float",
    "get_int",
    "get_raw",
    "get_str",
    "is_set",
    "iter_knobs",
    "knob",
    "resolve",
    "setdefault",
    "snapshot",
]

_UNSET = object()


class UnknownKnobError(KeyError):
    """An environment knob was read that the registry does not declare."""


class Knob:
    """One declared environment knob (immutable value object)."""

    __slots__ = ("name", "kind", "default", "scope", "doc", "anchor")

    def __init__(self, name, kind, default, scope, doc, anchor):
        object.__setattr__(self, "name", name)
        object.__setattr__(self, "kind", kind)
        object.__setattr__(self, "default", default)
        object.__setattr__(self, "scope", scope)
        object.__setattr__(self, "doc", doc)
        object.__setattr__(self, "anchor", anchor)

    def __setattr__(self, name, value):
        raise AttributeError("Knob entries are immutable")

    def __repr__(self):
        return (f"Knob({self.name!r}, kind={self.kind!r}, "
                f"default={self.default!r}, scope={self.scope!r})")

    @property
    def is_family(self):
        return self.name.endswith("*")


def _K(name, kind, default, scope, doc, anchor):
    return Knob(name, kind, default, scope, doc, anchor)


#: kinds: "flag" (bool, see module docstring), "int", "float", "str",
#: "path" (a str naming a file/directory), "spec" (a str with its own
#: mini-grammar parsed at the call site). scopes: "lib" (read inside
#: sq_learn_tpu/), "bench", "test", "external" (owned by jax/XLA/the OS,
#: read or written here but documented upstream).
_ENTRIES = [
    # -- observability (docs/observability.md) ---------------------------
    _K("SQ_OBS", "flag", False, "lib",
       "Enable the run-scoped recorder with a JSONL sink at SQ_OBS_PATH.",
       "docs/observability.md"),
    _K("SQ_OBS_PATH", "path", "sq_obs.jsonl", "lib",
       "JSONL sink path for the SQ_OBS=1 auto-enabled recorder.",
       "docs/observability.md"),
    _K("SQ_OBS_STRICT", "flag", False, "lib",
       "Retracing-watchdog compile-budget violations raise instead of "
       "warning.", "docs/observability.md"),
    _K("SQ_OBS_AUDIT_STRICT", "flag", False, "lib",
       "A flagged (ε, δ)-guarantee audit site raises (Clopper-Pearson "
       "lower bound above the declared δ/γ).", "docs/observability.md"),
    _K("SQ_OBS_BUDGET_STRICT", "flag", False, "lib",
       "A tripped multi-window error-budget burn alert raises "
       "BudgetBurnError.", "docs/observability.md"),
    _K("SQ_OBS_BUDGET_WINDOWS", "spec", "60,600", "lib",
       "Comma-separated rolling error-budget windows in seconds.",
       "docs/observability.md"),
    _K("SQ_OBS_BUDGET_BURN", "float", 2.0, "lib",
       "Multi-window burn-rate alert threshold (must hold in EVERY "
       "window).", "docs/observability.md"),
    _K("SQ_OBS_TRACE", "path", None, "lib",
       "Render the closed run into Chrome trace-event JSON at this path.",
       "docs/observability.md"),
    _K("SQ_OBS_ROTATE_BYTES", "int", 0, "lib",
       "Rotate the JSONL sink to gzipped <path>.<n>.gz segments at this "
       "many written bytes (0 = off).", "docs/observability.md"),
    _K("SQ_OBS_FLEET_RUN_ID", "str", None, "lib",
       "Coordinator-minted fleet run id; when set every record carries "
       "the fleet envelope (run_id/host/pid/gen).",
       "docs/observability.md"),
    _K("SQ_OBS_FLEET_HOST", "str", None, "lib",
       "Stable per-process host label in the fleet envelope (default "
       "pid<pid>).", "docs/observability.md"),
    _K("SQ_OBS_FLEET_DIR", "path", None, "lib",
       "Fleet shard directory: with SQ_OBS=1 and SQ_OBS_PATH unset the "
       "sink lands at <dir>/obs.<host>.jsonl.", "docs/observability.md"),
    _K("SQ_OBS_FLEET_CLOCK_SAMPLES", "int", 64, "lib",
       "Max clock samples recorded per peer per generation from the KV "
       "heartbeat exchanges.", "docs/observability.md"),
    _K("SQ_OBS_XLA_MEMORY", "flag", True, "lib",
       "Compile-and-price memory stats in xla_cost records (0 skips the "
       "compile).", "docs/observability.md"),
    _K("SQ_REGRESS_TOL_*", "float", None, "lib",
       "Per-gate tolerance override for the bench regression gate "
       "(e.g. SQ_REGRESS_TOL_LATENCY).", "docs/observability.md"),
    _K("SQ_REGRESS_SLACK_*", "float", None, "lib",
       "Per-gate additive-slack override for the bench regression gate.",
       "docs/observability.md"),
    _K("SQ_CPU_PEAK_FLOPS", "float", None, "lib",
       "Host peak-FLOPs override for MFU accounting.",
       "docs/observability.md"),
    _K("SQ_TPU_PEAK_FLOPS", "float", None, "lib",
       "Accelerator peak-FLOPs override for MFU accounting.",
       "docs/api.md"),
    # -- resilience / probe (docs/resilience.md) -------------------------
    _K("SQ_FAULTS", "spec", None, "lib",
       "Deterministic fault-injection schedule (armed at import).",
       "docs/resilience.md"),
    _K("SQ_RESILIENCE_STRICT", "flag", False, "lib",
       "Streamed passes raise on non-finite accumulators with tile "
       "provenance.", "docs/resilience.md"),
    _K("SQ_PROBE_TTL_S", "float", 300.0, "lib",
       "TTL of a cached device-health probe result (0 disables caching).",
       "docs/resilience.md"),
    _K("SQ_PROBE_CACHE", "path", None, "lib",
       "Cross-process probe-cache file (default: sq_probe_cache.json in "
       "the temp dir).", "docs/observability.md"),
    _K("SQ_RETRY_MAX", "int", 3, "lib",
       "Supervised-put retry budget.", "docs/resilience.md"),
    _K("SQ_RETRY_BACKOFF_S", "float", 0.05, "lib",
       "Base backoff between supervised-put retries.",
       "docs/resilience.md"),
    _K("SQ_RETRY_SEED", "int", 0, "lib",
       "Seed of the retry-jitter RNG.", "docs/resilience.md"),
    _K("SQ_TILE_DEADLINE_S", "float", 30.0, "lib",
       "Per-tile transfer deadline before a put counts as timed out.",
       "docs/resilience.md"),
    _K("SQ_BREAKER_K", "int", 3, "lib",
       "Consecutive failures that trip the circuit breaker.",
       "docs/resilience.md"),
    _K("SQ_BREAKER_COOLDOWN_S", "float", 60.0, "lib",
       "Open-state cooldown before the breaker half-opens.",
       "docs/resilience.md"),
    # -- streaming engine (docs/streaming.md) ----------------------------
    _K("SQ_STREAM_TILE_BYTES", "int", None, "lib",
       "Streamed-ingest tile size override (unset = auto-sized).",
       "docs/streaming.md"),
    _K("SQ_STREAM_MIN_BUCKET_ROWS", "int", 64, "lib",
       "Smallest padded row bucket the streaming engine mints.",
       "docs/streaming.md"),
    _K("SQ_STREAM_CKPT_DIR", "path", None, "lib",
       "Arm resumable streamed passes: checkpoint directory.",
       "docs/resilience.md"),
    _K("SQ_STREAM_CKPT_EVERY", "int", 8, "lib",
       "Checkpoint cadence in tiles for resumable streamed passes.",
       "docs/resilience.md"),
    _K("SQ_TRANSFER_CHUNK_BYTES", "int", 128 * 2 ** 20, "lib",
       "Largest single host→device transfer transaction.",
       "docs/streaming.md"),
    _K("SQ_TINY_FIT_ELEMENTS", "int", 1 << 18, "lib",
       "Below this element count a fit skips the chip path (0 disables).",
       "docs/api.md"),
    _K("SQ_COMPILE_CACHE_DIR", "path", None, "lib",
       "Persistent XLA compile-cache directory (AOT serving warm path).",
       "docs/serving.md"),
    # -- fit pipeline / sketch (docs/fit_pipeline.md) --------------------
    _K("SQ_INIT_SUBSAMPLE", "int", None, "lib",
       "D²-potential subsample target for k-means++ init (0 disables, "
       "unset = auto).", "docs/fit_pipeline.md"),
    _K("SQ_SKETCH_ROWS", "float", None, "lib",
       "Row-sketch sample target for δ>0 spectral stats (0 disables, "
       "unset = auto).", "docs/fit_pipeline.md"),
    _K("SQ_SKETCH_DELTA", "float", None, "lib",
       "δ_stat of the sketched spectral-stats bounds (0 = exact, unset = "
       "0.05).", "docs/fit_pipeline.md"),
    _K("SQ_SKETCH_AUDIT_ELEMS", "int", None, "lib",
       "Cap on the sketch self-audit's ground-truth element count.",
       "docs/fit_pipeline.md"),
    _K("SQ_STATS_CACHE", "flag", True, "lib",
       "Digest-keyed spectral-stats cache (0 disables).",
       "docs/fit_pipeline.md"),
    # -- out-of-core shard stores (docs/resilience.md §out-of-core) ------
    _K("SQ_OOC_SHARD_BYTES", "int", 8 << 20, "lib",
       "Shard split size for new out-of-core stores.",
       "docs/resilience.md"),
    _K("SQ_OOC_RAM_BUDGET_BYTES", "int", 0, "lib",
       "Enforced single-materialization RAM budget (0 = off); also caps "
       "readahead.", "docs/resilience.md"),
    _K("SQ_OOC_VERIFY", "str", "all", "lib",
       "Read-side CRC policy for shard stores: all | touch | off.",
       "docs/resilience.md"),
    _K("SQ_OOC_REREAD_MAX", "int", 2, "lib",
       "Quarantine re-read budget after a CRC mismatch.",
       "docs/resilience.md"),
    _K("SQ_OOC_CODEC", "str", "none", "lib",
       "Per-shard codec for NEW store builds (lz4 = native LZ4-class + "
       "byte shuffle).", "docs/resilience.md"),
    _K("SQ_OOC_PREFETCH_DEPTH", "int", None, "lib",
       "Shard readahead depth (0 = serial bit-for-bit, unset = auto: 2 "
       "multi-core / 0 single-core).", "docs/resilience.md"),
    _K("SQ_OOC_PREFETCH_THREADS", "int", 2, "lib",
       "Prefetch worker-pool width (also sizes parallel store builds).",
       "docs/resilience.md"),
    _K("SQ_OOC_ASYNC_CKPT", "flag", True, "lib",
       "Async mid-epoch fit snapshots (0 = synchronous writes).",
       "docs/resilience.md"),
    # -- elastic multi-host mesh (docs/resilience.md §elastic) -----------
    _K("SQ_ELASTIC_HEARTBEAT_S", "float", 0.5, "lib",
       "Lease-supervisor heartbeat publish cadence (KV keys, per "
       "worker).", "docs/resilience.md"),
    _K("SQ_ELASTIC_LEASE_S", "float", 3.0, "lib",
       "Lease length: a peer silent for one lease is declared dead.",
       "docs/resilience.md"),
    _K("SQ_ELASTIC_MAX_SHRINKS", "int", 1, "lib",
       "Host-failure budget: shrinks tolerated before the fit aborts.",
       "docs/resilience.md"),
    _K("SQ_ELASTIC_WINDOW", "int", 4, "lib",
       "Commit-window width in visit-order positions (atomic fold+commit "
       "unit).", "docs/resilience.md"),
    _K("SQ_ELASTIC_PORT", "int", 0, "lib",
       "Coordination-service TCP port (0 = pick a free port per "
       "generation).", "docs/resilience.md"),
    # -- serving plane (docs/serving.md) ---------------------------------
    _K("SQ_SERVE_MAX_WAIT_MS", "float", 2.0, "lib",
       "Micro-batch coalescing window.", "docs/serving.md"),
    _K("SQ_SERVE_MAX_BATCH_ROWS", "int", 512, "lib",
       "Row cap of one padded serving batch.", "docs/serving.md"),
    _K("SQ_SERVE_MIN_BUCKET_ROWS", "int", 8, "lib",
       "Smallest padded pow2 serving bucket.", "docs/serving.md"),
    _K("SQ_SERVE_REGISTRY_CAP", "int", 8, "lib",
       "LRU capacity of the checkpoint-backed model registry.",
       "docs/serving.md"),
    _K("SQ_SERVE_AOT", "flag", True, "lib",
       "AOT-compile the bucket ladder at registry warm (0 skips).",
       "docs/serving.md"),
    _K("SQ_SERVE_CACHE", "flag", True, "lib",
       "Digest-keyed transform result cache (0 kills it).",
       "docs/serving.md"),
    _K("SQ_SERVE_CACHE_ENTRIES", "int", 256, "lib",
       "RAM-LRU entry cap of the serving result cache.",
       "docs/serving.md"),
    _K("SQ_SERVE_CACHE_DIR", "path", None, "lib",
       "Arm the serving cache's compressed disk-spill tier.",
       "docs/serving.md"),
    _K("SQ_SERVE_CACHE_DISK_ENTRIES", "int", 4096, "lib",
       "Entry bound of the disk-spill tier.", "docs/serving.md"),
    _K("SQ_SERVE_QUANTIZE", "str", None, "lib",
       "Process-default serving quantization: bf16 | int8 | auto | "
       "none.", "docs/serving.md"),
    _K("SQ_SERVE_QUANT_DELTA", "float", 1e-3, "lib",
       "Declared audit budget δ_q of the quantization fold.",
       "docs/serving.md"),
    _K("SQ_SERVE_AUDIT_EVERY", "int", 8, "lib",
       "Quantization-fold guarantee-draw cadence in batches.",
       "docs/serving.md"),
    _K("SQ_SERVE_SLO_P50_MS", "float", None, "lib",
       "Run-level p50 latency SLO target.", "docs/serving.md"),
    _K("SQ_SERVE_SLO_P99_MS", "float", None, "lib",
       "Run-level p99 latency SLO target.", "docs/serving.md"),
    _K("SQ_SERVE_SLO_STRICT", "flag", False, "lib",
       "A violated SLO raises at dispatcher close.", "docs/serving.md"),
    _K("SQ_SERVE_SLO_FLUSH_BATCHES", "int", 256, "lib",
       "Windowed slo/budget record flush stride in batches (0 "
       "disables).", "docs/serving.md"),
    _K("SQ_SERVE_NATIVE", "flag", True, "lib",
       "Native gather/scatter fast path + pooled assembly buffers (0 = "
       "the per-request numpy path, bit-identical).", "docs/serving.md"),
    _K("SQ_SERVE_MEGABATCH", "flag", True, "lib",
       "Cross-tenant coalescing of same-fingerprint tenants into one "
       "kernel launch (0 = tenant-scoped batches).", "docs/serving.md"),
    _K("SQ_SERVE_AUTOTUNE", "flag", True, "lib",
       "SLO-driven (ε, δ) autotuner + admission control (0 pins the "
       "static serving plane bit-identically).", "docs/serving.md"),
    _K("SQ_SERVE_AUTOTUNE_EVERY", "int", 32, "lib",
       "Controller evaluation cadence in dispatched batches.",
       "docs/serving.md"),
    _K("SQ_SERVE_AUTOTUNE_BURN", "float", 1.5, "lib",
       "Burn rate at which the controller degrades a tenant (below the "
       "alert threshold: act BEFORE the SLO gate trips).",
       "docs/serving.md"),
    _K("SQ_SERVE_AUTOTUNE_RELAX", "float", 0.25, "lib",
       "Burn rate below which a budget counts as underspent (relax "
       "candidate).", "docs/serving.md"),
    _K("SQ_SERVE_AUTOTUNE_PATIENCE", "int", 3, "lib",
       "Consecutive underspent evaluations before the controller "
       "relaxes a tenant's served (ε, δ).", "docs/serving.md"),
    _K("SQ_SERVE_AUTOTUNE_DELTA_CAP", "float", 4.0, "lib",
       "Largest served-δ multiple of the declared δ the relax ladder "
       "may bank.", "docs/serving.md"),
    # -- datasets --------------------------------------------------------
    _K("CICIDS_CSV", "path", None, "lib",
       "Path to a real CICIDS2017 CSV export (unset = deterministic "
       "synthetic surrogate).", None),
    # -- bench / test harness --------------------------------------------
    _K("SQ_BENCH_SMOKE", "flag", False, "bench",
       "Bench scripts run tiny CPU-safe shapes and skip accelerator "
       "probes.", "docs/streaming.md"),
    _K("SQ_OOC_BENCH_ARTIFACT_DIR", "path", None, "bench",
       "Keep the out-of-core bench's store artifacts here (unset = "
       "fresh temp dir).", None),
    _K("SQ_TEST_CLEAR_CACHES", "flag", False, "test",
       "Clear XLA caches between test modules (round-5 segfault "
       "mitigation).", "docs/observability.md"),
    _K("_SQ_SCALING_CHILD", "flag", False, "bench",
       "Internal marker: this process is a sharded-scaling bench child.",
       None),
    # -- external (owned upstream; registered so reads are auditable) ----
    _K("JAX_PLATFORMS", "str", None, "external",
       "jax backend selection (axon tunnel vs cpu; see CLAUDE.md "
       "gotchas).", "CLAUDE.md"),
    _K("JAX_NUM_PROCESSES", "int", 0, "external",
       "Multi-process mesh size for distributed initialization.", None),
    _K("JAX_COMPILATION_CACHE_DIR", "path", None, "external",
       "jax's own persistent compile-cache knob (bench suite).", None),
    _K("XLA_FLAGS", "str", None, "external",
       "XLA backend flags (the conftest's 8 virtual devices ride this).",
       None),
]

#: name → Knob for exact entries; families keep their trailing ``*``
REGISTRY = {e.name: e for e in _ENTRIES}

_FAMILIES = tuple(e for e in _ENTRIES if e.is_family)

if len(REGISTRY) != len(_ENTRIES):  # pragma: no cover - registry bug
    raise RuntimeError("duplicate knob registration")


def resolve(name):
    """The :class:`Knob` entry governing ``name`` (exact match first,
    then family prefix), or None when unregistered."""
    e = REGISTRY.get(name)
    if e is not None:
        return e
    for fam in _FAMILIES:
        if name.startswith(fam.name[:-1]):
            return fam
    return None


def knob(name):
    """The :class:`Knob` entry for ``name``; raises
    :class:`UnknownKnobError` when unregistered."""
    e = resolve(name)
    if e is None:
        raise UnknownKnobError(
            f"environment knob {name!r} is not in the sq_learn_tpu._knobs "
            f"registry — register it there (one line) before reading it")
    return e


def iter_knobs():
    """Every registry entry, name-sorted (the docs generator's input)."""
    return sorted(_ENTRIES, key=lambda e: (e.scope != "lib", e.name))


def is_set(name):
    """True when the (registered) knob is present in the environment."""
    knob(name)
    return name in os.environ


def get_raw(name, default=None):
    """The raw string value of a registered knob, or ``default`` when
    unset. The one accessor whose default is caller-supplied — use the
    typed forms unless the call site owns a computed fallback."""
    knob(name)
    return os.environ.get(name, default)


def _typed(name, default, conv):
    e = knob(name)
    raw = os.environ.get(name)
    if raw is None:
        return e.default if default is _UNSET else default
    return conv(raw)


def get_str(name, default=_UNSET):
    """String knob value (registry default when unset)."""
    return _typed(name, default, str)


def get_int(name, default=_UNSET):
    """Integer knob value (registry default when unset)."""
    return _typed(name, default, int)


def get_float(name, default=_UNSET):
    """Float knob value (registry default when unset)."""
    return _typed(name, default, float)


def get_bool(name):
    """Flag knob value under the project's two historical spellings:
    default-False knobs enable only on ``"1"``; default-True knobs
    disable only on ``"0"`` (any other non-empty value stays on)."""
    e = knob(name)
    raw = os.environ.get(name)
    if raw is None:
        return bool(e.default)
    if e.default:
        return raw != "0"
    return raw == "1"


def setdefault(name, value):
    """``os.environ.setdefault`` for a registered knob (read+write —
    smoke drivers pinning a default for child processes)."""
    knob(name)
    return os.environ.setdefault(name, str(value))


def snapshot(names):
    """{name: raw value or None} for registered knobs — the save half of
    a smoke driver's save/mutate/restore dance. Restore with plain env
    writes (writes are not registry-gated)."""
    return {n: get_raw(n) for n in names}
