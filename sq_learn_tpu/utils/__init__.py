"""Numeric and plumbing utilities (reference layer L1, ``sklearn/utils/``)."""

from .keys import as_key, key_iter, split
from .validation import (
    check_array,
    check_random_state,
    check_sample_weight,
    check_X_y,
)

__all__ = [
    "as_key",
    "key_iter",
    "split",
    "check_array",
    "check_random_state",
    "check_sample_weight",
    "check_X_y",
]
