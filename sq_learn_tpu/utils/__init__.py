"""Numeric and plumbing utilities (reference layer L1, ``sklearn/utils/``)."""

from .checkpoint import (load_estimator, load_pytree, load_stream_state,
                         save_estimator, save_pytree, save_stream_state)
from .keys import as_key, key_iter, split
from ._show_versions import show_versions
from .validation import (
    check_array,
    check_random_state,
    check_sample_weight,
    check_X_y,
    validated_once,
    validation_scope,
)

__all__ = [
    "as_key",
    "key_iter",
    "split",
    "check_array",
    "check_random_state",
    "check_sample_weight",
    "check_X_y",
    "validated_once",
    "validation_scope",
    "save_estimator",
    "load_estimator",
    "save_pytree",
    "load_pytree",
    "save_stream_state",
    "load_stream_state",
    "show_versions",
]
