"""PRNG key discipline.

The reference mixes global ``random.seed`` (``_dmeans.py:22``), per-call
``np.random.RandomState()`` (``Utility.py:53``) and seeded RandomState objects.
On TPU the whole framework threads explicit ``jax.random`` keys instead: every
stochastic routine takes a key, splits it for sub-routines, and never touches
global state. These helpers bridge sklearn-style ``random_state`` arguments to
that discipline so parity tests can still seed deterministically.
"""

import numpy as np
import jax


def as_key(random_state):
    """Coerce a ``random_state``-style argument to a ``jax.random`` key.

    Parameters
    ----------
    random_state : None, int, jax key array, or np.random.RandomState
        ``None`` draws fresh OS entropy (the analogue of the reference's
        per-call ``np.random.RandomState()``); an int seeds deterministically.
    """
    if random_state is None:
        return jax.random.PRNGKey(np.random.SeedSequence().entropy % (2**63))
    if isinstance(random_state, (int, np.integer)):
        return jax.random.PRNGKey(int(random_state))
    if isinstance(random_state, np.random.RandomState):
        return jax.random.PRNGKey(int(random_state.randint(0, 2**31 - 1)))
    if isinstance(random_state, jax.Array):
        return random_state
    raise ValueError(
        f"random_state must be None, an int, a RandomState or a jax key; "
        f"got {type(random_state).__name__}"
    )


def split(key, num=2):
    """Alias for :func:`jax.random.split` kept here for import hygiene."""
    return jax.random.split(key, num)


def key_iter(key):
    """Infinite generator of fresh subkeys (host-side driver loops only)."""
    while True:
        key, sub = jax.random.split(key)
        yield sub
