"""Debug-information printer (reference ``utils/_show_versions.py:76``).

The reference prints platform, Python dependency versions, and its OpenMP
build flag; the TPU-native equivalents of the last section are the JAX
backend and device inventory — the facts a bug report here needs.
"""

import platform
import sys


def _get_sys_info():
    return {
        "python": sys.version.replace("\n", " "),
        "executable": sys.executable,
        "machine": platform.platform(),
    }


def _get_deps_info():
    deps = ["numpy", "scipy", "jax", "jaxlib", "flax", "optax", "sklearn"]
    info = {}
    for modname in deps:
        try:
            mod = __import__(modname)
            info[modname] = getattr(mod, "__version__", "installed")
        except ImportError:
            info[modname] = None
    return info


def _get_backend_info():
    """Backend facts without touching a possibly-wedged accelerator: only
    report devices when a backend is already initialized; otherwise report
    the configured platform string."""
    import jax

    info = {"configured platforms": str(jax.config.jax_platforms)}
    try:
        # devices() on an initialized runtime is cheap; on a cold process
        # it would trigger (and possibly hang) backend discovery, so only
        # report what is already known
        if jax._src.xla_bridge._backends:  # initialized backends only
            devs = jax.devices()
            info["default backend"] = jax.default_backend()
            info["devices"] = ", ".join(str(d) for d in devs)
    except Exception as exc:  # pragma: no cover - defensive
        info["devices"] = f"unavailable ({type(exc).__name__})"
    return info


def show_versions():
    """Print useful debugging information (reference
    ``utils/_show_versions.py:76``)."""
    print("\nSystem:")
    for k, stat in _get_sys_info().items():
        print(f"{k:>12}: {stat}")
    print("\nPython dependencies:")
    for k, stat in _get_deps_info().items():
        print(f"{k:>13}: {stat}")
    print("\nJAX backend:")
    for k, stat in _get_backend_info().items():
        print(f"{k:>20}: {stat}")
