"""Tracing / profiling utilities.

The reference has no profiling infrastructure beyond ad-hoc ``time.time()``
in scripts and ``# cython: profile=True`` on the Lloyd kernel (SURVEY §5);
its *theoretical* runtime accountants live on the estimators
(``QPCA.accumulate_q_runtime``, ``QKMeans.quantum_runtime_model``). This
module supplies the real-measurement side:

- :func:`trace` — context manager around ``jax.profiler`` emitting an XLA
  trace viewable in TensorBoard/Perfetto.
- :class:`Timer` — wall-clock scope timer that blocks on device work, so
  async dispatch doesn't fake instant results.
- :func:`benchmark` — median-of-repeats timing of a jitted callable with a
  compile warm-up, the measurement discipline ``bench.py`` uses.
- :func:`lloyd_iter_flops` / :func:`matmul_flops` — FLOP accounting for
  the MXU-bound kernels, and :func:`device_peak_flops` /
  :func:`mfu` — achieved fraction of chip peak. Together these turn a
  wall-clock into a hardware-utilization statement ("beating" the
  reference's ``cluster/_k_means_lloyd.pyx:29`` on a TPU means a
  roofline number, not a latency ratio on digit-scale data).

There is ONE timing discipline: every scope this module times emits
through the run-scoped recorder (:mod:`sq_learn_tpu.obs`) when a run is
active — ``Timer`` scopes land as synced spans, ``benchmark`` results as
gauges with a compile-vs-execute split (warm-up wall-clock = compile +
first execute; timed median = execute), ``mfu`` as a gauge. With
observability off everything behaves exactly as before at zero extra
cost.
"""

import os
import time
from contextlib import contextmanager

import jax

from .. import obs as _obs
from .. import _knobs

#: bf16 matmul peak FLOP/s per chip generation (public spec sheets /
#: the jax-ml scaling book). The MXU's native rate; f32 MFU reported
#: against it is a conservative lower bound.
TPU_PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "v6 lite": 918e12,
}


def matmul_flops(m, k, n):
    """FLOPs of an (m, k) @ (k, n) GEMM: one multiply + one add per MAC."""
    return 2.0 * m * k * n


def lloyd_iter_flops(n_samples, n_features, n_clusters):
    """MXU FLOPs of one fused Lloyd iteration: the E-step distance GEMM
    plus the M-step one-hot centroid-sum GEMM (2·n·k·m each). VPU work
    (argmin, compares) is excluded — undercounting keeps MFU honest."""
    return (matmul_flops(n_samples, n_features, n_clusters)
            + matmul_flops(n_clusters, n_samples, n_features))


#: f32 FLOPs per core per cycle for the host-CPU peak estimate: two
#: 256-bit FMA ports × 8 lanes × 2 ops — the AVX2 dual-FMA figure, the
#: floor for every x86 server generation this code runs on. An
#: AVX-512 host's true peak is up to 2× higher, so treat CPU MFU as a
#: roofline orientation, not a utilization claim of record (the gauge
#: is tagged ``cpu_estimate`` for exactly this reason).
CPU_FLOPS_PER_CORE_CYCLE = 32.0


def _host_cpu_hz():
    """Best-effort host clock in Hz from /proc/cpuinfo (first 'cpu MHz'
    line); 2 GHz when unreadable — the estimate only needs to be
    order-correct for a finite MFU statement."""
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.lower().startswith("cpu mhz"):
                    return float(line.split(":", 1)[1]) * 1e6
    except (OSError, ValueError, IndexError):
        pass
    return 2.0e9


def host_cpu_peak_flops():
    """Estimated peak f32 FLOP/s of THIS host's CPU: cores × clock ×
    :data:`CPU_FLOPS_PER_CORE_CYCLE`, overridable via
    ``SQ_CPU_PEAK_FLOPS``. An estimate (clock read once, no turbo/AVX512
    modeling) — it exists so CPU-backend runs report a finite MFU
    instead of ``None`` + an ``unknown_chip`` gauge, which left
    ``bench_pallas_mfu`` blind off-TPU."""
    env = _knobs.get_raw("SQ_CPU_PEAK_FLOPS")
    if env:
        return float(env)
    return (os.cpu_count() or 1) * _host_cpu_hz() * CPU_FLOPS_PER_CORE_CYCLE


def device_peak_flops(device=None):
    """Best-known peak FLOP/s for ``device`` (default: the first device).

    Resolution order: the ``SQ_TPU_PEAK_FLOPS`` env override (for tunnels
    fronting unlisted hardware), then the generation table keyed on
    ``device_kind``, then — for CPU devices only — the
    :func:`host_cpu_peak_flops` estimate. Returns None for an unknown
    *accelerator* — callers must then report raw FLOP/s without an MFU
    claim, never guess an accelerator's peak (the host estimate is
    acceptable only because a CPU "MFU" is a roofline orientation, not a
    hardware-utilization claim of record).
    """
    env = _knobs.get_raw("SQ_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for tag, peak in TPU_PEAK_FLOPS.items():
        if tag in kind:
            return peak
    if getattr(device, "platform", "") == "cpu":
        return host_cpu_peak_flops()
    return None


def mfu(flops, seconds, device=None, site=None):
    """Model FLOP utilization: achieved FLOP/s over chip peak.

    ``site`` switches the numerator from the hand formula to the
    *measured* cost: when an obs run holds an ``xla_cost`` record for
    that watchdog site (:mod:`sq_learn_tpu.obs.xla`), its XLA-reported
    FLOP count replaces ``flops`` (gauge tagged ``source="xla_cost"``) —
    callers time one execution of the analyzed kernel and pass its site.

    Degrades gracefully on unknown hardware: CPU devices fall back to
    the :func:`host_cpu_peak_flops` estimate (finite MFU, gauge tagged
    ``cpu_estimate``); an unknown *accelerator* (or non-positive
    ``seconds``) returns None — callers need no pre-check — and records
    a ``profiling.mfu`` gauge tagged ``unknown_chip`` so the run
    artifact says *why* there is no utilization claim instead of
    silently omitting one."""
    attrs = {}
    if site is not None:
        from ..obs import xla as _xla

        measured = _xla.flops_of(site)
        if measured is not None:
            flops = measured
            attrs["source"] = "xla_cost"
            attrs["site"] = site
    peak = device_peak_flops(device)
    if not peak or seconds <= 0:
        kind = "unknown"
        try:
            d = device if device is not None else jax.devices()[0]
            kind = getattr(d, "device_kind", "unknown")
        except Exception:
            pass
        _obs.gauge("profiling.mfu", None, unknown_chip=True,
                   device_kind=kind,
                   reason=("nonpositive_seconds" if peak and seconds <= 0
                           else "unknown_chip"), **attrs)
        return None
    try:
        d = device if device is not None else jax.devices()[0]
        if getattr(d, "platform", "") == "cpu" \
                and not _knobs.get_raw("SQ_TPU_PEAK_FLOPS"):
            attrs["cpu_estimate"] = True
    except Exception:
        pass
    value = (flops / seconds) / peak
    _obs.gauge("profiling.mfu", value, **attrs)
    return value


@contextmanager
def trace(log_dir, create_perfetto_link=False):
    """Capture a device trace of the enclosed block into ``log_dir``
    (and a ``utils.trace`` span in the obs recorder, so the run artifact
    points at the XLA trace it corresponds to)."""
    with _obs.span("utils.trace", log_dir=str(log_dir)):
        jax.profiler.start_trace(log_dir,
                                 create_perfetto_link=create_perfetto_link)
        try:
            yield
        finally:
            jax.profiler.stop_trace()


class Timer:
    """Wall-clock scope timer that waits for device completion.

    When an obs run is active the scope also lands as a synced span
    (name from the ``name`` argument, default ``"utils.Timer"``) — the
    one timing discipline of the framework.

    >>> with Timer() as t:
    ...     out = step(...)  # doctest: +SKIP
    >>> t.elapsed  # doctest: +SKIP
    """

    def __init__(self, block_on=None, name=None):
        self._block_on = block_on
        self.name = name
        self.elapsed = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._block_on is not None:
            jax.block_until_ready(self._block_on)
        else:
            # barrier on every live array: a fresh device_put would NOT be
            # ordered behind pending compute (JAX only orders through data
            # dependencies), so this is the only sound default
            for a in jax.live_arrays():
                a.block_until_ready()
        self.elapsed = time.perf_counter() - self._t0
        _obs.record_span(self.name or "utils.Timer", self.elapsed)
        return False


def benchmark(fn, *args, repeats=5, warmup=1, name=None, **kwargs):
    """Median wall-clock of ``fn(*args, **kwargs)`` with device sync.

    Runs ``warmup`` untimed calls first (compile + cache), then ``repeats``
    timed ones. Returns (median_seconds, all_times). With an obs run
    active, records the compile-vs-execute split as gauges: the warm-up
    wall-clock (compile + first execute) and the timed median (execute
    only), under ``benchmark.<name>.{warmup_s,median_s}``.
    """
    t0 = time.perf_counter()
    for _ in range(warmup):
        jax.block_until_ready(fn(*args, **kwargs))
    warmup_s = time.perf_counter() - t0
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args, **kwargs))
        times.append(time.perf_counter() - t0)
    times.sort()
    median = times[len(times) // 2]
    if _obs.enabled():
        label = name or getattr(fn, "__name__", "fn")
        _obs.gauge(f"benchmark.{label}.warmup_s", round(warmup_s, 6),
                   warmup_calls=warmup)
        _obs.gauge(f"benchmark.{label}.median_s", round(median, 6),
                   repeats=repeats)
    return median, times
