"""Tracing / profiling utilities.

The reference has no profiling infrastructure beyond ad-hoc ``time.time()``
in scripts and ``# cython: profile=True`` on the Lloyd kernel (SURVEY §5);
its *theoretical* runtime accountants live on the estimators
(``QPCA.accumulate_q_runtime``, ``QKMeans.quantum_runtime_model``). This
module supplies the real-measurement side:

- :func:`trace` — context manager around ``jax.profiler`` emitting an XLA
  trace viewable in TensorBoard/Perfetto.
- :class:`Timer` — wall-clock scope timer that blocks on device work, so
  async dispatch doesn't fake instant results.
- :func:`benchmark` — median-of-repeats timing of a jitted callable with a
  compile warm-up, the measurement discipline ``bench.py`` uses.
"""

import time
from contextlib import contextmanager

import jax


@contextmanager
def trace(log_dir, create_perfetto_link=False):
    """Capture a device trace of the enclosed block into ``log_dir``."""
    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Timer:
    """Wall-clock scope timer that waits for device completion.

    >>> with Timer() as t:
    ...     out = step(...)  # doctest: +SKIP
    >>> t.elapsed  # doctest: +SKIP
    """

    def __init__(self, block_on=None):
        self._block_on = block_on
        self.elapsed = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._block_on is not None:
            jax.block_until_ready(self._block_on)
        else:
            # barrier on every live array: a fresh device_put would NOT be
            # ordered behind pending compute (JAX only orders through data
            # dependencies), so this is the only sound default
            for a in jax.live_arrays():
                a.block_until_ready()
        self.elapsed = time.perf_counter() - self._t0
        return False


def benchmark(fn, *args, repeats=5, warmup=1, **kwargs):
    """Median wall-clock of ``fn(*args, **kwargs)`` with device sync.

    Runs ``warmup`` untimed calls first (compile + cache), then ``repeats``
    timed ones. Returns (median_seconds, all_times).
    """
    for _ in range(warmup):
        jax.block_until_ready(fn(*args, **kwargs))
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args, **kwargs))
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2], times
