"""Tracing / profiling utilities.

The reference has no profiling infrastructure beyond ad-hoc ``time.time()``
in scripts and ``# cython: profile=True`` on the Lloyd kernel (SURVEY §5);
its *theoretical* runtime accountants live on the estimators
(``QPCA.accumulate_q_runtime``, ``QKMeans.quantum_runtime_model``). This
module supplies the real-measurement side:

- :func:`trace` — context manager around ``jax.profiler`` emitting an XLA
  trace viewable in TensorBoard/Perfetto.
- :class:`Timer` — wall-clock scope timer that blocks on device work, so
  async dispatch doesn't fake instant results.
- :func:`benchmark` — median-of-repeats timing of a jitted callable with a
  compile warm-up, the measurement discipline ``bench.py`` uses.
- :func:`lloyd_iter_flops` / :func:`matmul_flops` — FLOP accounting for
  the MXU-bound kernels, and :func:`device_peak_flops` /
  :func:`mfu` — achieved fraction of chip peak. Together these turn a
  wall-clock into a hardware-utilization statement ("beating" the
  reference's ``cluster/_k_means_lloyd.pyx:29`` on a TPU means a
  roofline number, not a latency ratio on digit-scale data).
"""

import os
import time
from contextlib import contextmanager

import jax

#: bf16 matmul peak FLOP/s per chip generation (public spec sheets /
#: the jax-ml scaling book). The MXU's native rate; f32 MFU reported
#: against it is a conservative lower bound.
TPU_PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "v6 lite": 918e12,
}


def matmul_flops(m, k, n):
    """FLOPs of an (m, k) @ (k, n) GEMM: one multiply + one add per MAC."""
    return 2.0 * m * k * n


def lloyd_iter_flops(n_samples, n_features, n_clusters):
    """MXU FLOPs of one fused Lloyd iteration: the E-step distance GEMM
    plus the M-step one-hot centroid-sum GEMM (2·n·k·m each). VPU work
    (argmin, compares) is excluded — undercounting keeps MFU honest."""
    return (matmul_flops(n_samples, n_features, n_clusters)
            + matmul_flops(n_clusters, n_samples, n_features))


def device_peak_flops(device=None):
    """Best-known peak FLOP/s for ``device`` (default: the first device).

    Resolution order: the ``SQ_TPU_PEAK_FLOPS`` env override (for tunnels
    fronting unlisted hardware), then the generation table keyed on
    ``device_kind``. Returns None when the chip is unknown — callers must
    then report raw FLOP/s without an MFU claim, never guess a peak.
    """
    env = os.environ.get("SQ_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for tag, peak in TPU_PEAK_FLOPS.items():
        if tag in kind:
            return peak
    return None


def mfu(flops, seconds, device=None):
    """Model FLOP utilization: achieved FLOP/s over chip peak, or None
    when the peak is unknown (see :func:`device_peak_flops`)."""
    peak = device_peak_flops(device)
    if not peak or seconds <= 0:
        return None
    return (flops / seconds) / peak


@contextmanager
def trace(log_dir, create_perfetto_link=False):
    """Capture a device trace of the enclosed block into ``log_dir``."""
    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Timer:
    """Wall-clock scope timer that waits for device completion.

    >>> with Timer() as t:
    ...     out = step(...)  # doctest: +SKIP
    >>> t.elapsed  # doctest: +SKIP
    """

    def __init__(self, block_on=None):
        self._block_on = block_on
        self.elapsed = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._block_on is not None:
            jax.block_until_ready(self._block_on)
        else:
            # barrier on every live array: a fresh device_put would NOT be
            # ordered behind pending compute (JAX only orders through data
            # dependencies), so this is the only sound default
            for a in jax.live_arrays():
                a.block_until_ready()
        self.elapsed = time.perf_counter() - self._t0
        return False


def benchmark(fn, *args, repeats=5, warmup=1, **kwargs):
    """Median wall-clock of ``fn(*args, **kwargs)`` with device sync.

    Runs ``warmup`` untimed calls first (compile + cache), then ``repeats``
    timed ones. Returns (median_seconds, all_times).
    """
    for _ in range(warmup):
        jax.block_until_ready(fn(*args, **kwargs))
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args, **kwargs))
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2], times
