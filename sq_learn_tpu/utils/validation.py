"""Input validation utilities.

A TPU-first re-implementation of the slice of ``sklearn/utils/validation.py``
the quantum estimators rely on (``check_array``, ``check_is_fitted`` — see
``base.py`` — and sample-weight checks). Validation happens on host in NumPy
before arrays are shipped to the device; everything returned is a plain
``np.ndarray`` ready for ``jnp.asarray``.
"""

import numbers
from contextlib import contextmanager

import numpy as np

from .._config import get_config


def check_array(X, *, dtype="float", ensure_2d=True, allow_nd=False, copy=False,
                ensure_min_samples=1, ensure_min_features=1, force_finite=None):
    """Validate an input array (dense only — sparse input is rejected like
    the reference's qPCA does at ``_qPCA.py:517``).

    Returns a C-contiguous ndarray of float32/float64 per the global config.
    """
    if hasattr(X, "toarray"):
        raise TypeError(
            "sparse input is not supported by the quantum estimators; "
            "densify with .toarray() first"
        )
    if dtype == "float":
        cfg = get_config()["default_dtype"]
        np_dtype = np.float64 if cfg == "float64" else np.float32
        X = np.asarray(X)
        if X.dtype not in (np.float32, np.float64):
            X = X.astype(np_dtype)
    elif dtype is not None:
        X = np.asarray(X, dtype=dtype)
    else:
        X = np.asarray(X)

    if copy:
        X = np.array(X, copy=True)

    if ensure_2d:
        if X.ndim == 1:
            raise ValueError(
                f"Expected 2D array, got 1D array instead:\narray={X!r}.\n"
                "Reshape your data either using array.reshape(-1, 1) if your "
                "data has a single feature or array.reshape(1, -1) if it "
                "contains a single sample."
            )
        if X.ndim != 2 and not allow_nd:
            raise ValueError(f"Found array with dim {X.ndim}, expected 2.")

    if force_finite is None:
        force_finite = not get_config()["assume_finite"]
    if force_finite and X.dtype.kind == "f" and not np.isfinite(X).all():
        raise ValueError("Input contains NaN or infinity.")

    if ensure_2d and X.ndim == 2:
        n_samples, n_features = X.shape
        if n_samples < ensure_min_samples:
            raise ValueError(
                f"Found array with {n_samples} sample(s) while a minimum of "
                f"{ensure_min_samples} is required."
            )
        if n_features < ensure_min_features:
            raise ValueError(
                f"Found array with {n_features} feature(s) while a minimum of "
                f"{ensure_min_features} is required."
            )
    return np.ascontiguousarray(X)


@contextmanager
def validation_scope(estimator):
    """Open a validate-once scope on ``estimator``: while active, repeated
    :meth:`~sq_learn_tpu.base.BaseEstimator._validated_X` calls on the
    SAME input object return the first call's validated array instead of
    re-running the full :func:`check_array` contract (dtype/copy/finite
    scans — the finiteness pass alone is a full O(n·m) sweep).

    The cache is keyed by object identity and lives only for the scope
    (a transient ``_validation_scope`` attr, cleared on exit), so nothing
    is ever trusted across estimator calls — a mutated or swapped array
    in a LATER call is always re-validated. Nested scopes share the
    outermost cache (``fit_transform`` wrapping a ``fit`` that opens its
    own scope blesses exactly once).

    This is the validate-once contract of the fused fit pipeline
    (``docs/fit_pipeline.md``): ``fit_transform``/``fit_predict`` surfaces
    open the scope so their fit and transform halves — and the size-aware
    host re-entries inside them — validate each input exactly once.
    """
    prev = getattr(estimator, "_validation_scope", None)
    if prev is None:
        estimator._validation_scope = {}
    try:
        yield
    finally:
        if prev is None:
            try:
                del estimator._validation_scope
            except AttributeError:
                pass


def validated_once(estimator, X, validator):
    """Run ``validator(X)`` under the estimator's validate-once cache (a
    no-op passthrough when no :func:`validation_scope` is open). Both the
    input object and the validated result are blessed, so validating an
    already-validated array is also a cache hit."""
    scope = getattr(estimator, "_validation_scope", None)
    if scope is None:
        return validator(X)
    hit = scope.get(id(X))
    if hit is not None:
        return hit
    out = validator(X)
    scope[id(X)] = out
    scope[id(out)] = out
    return out


def check_X_y(X, y, **kwargs):
    X = check_array(X, **kwargs)
    y = np.asarray(y)
    if y.ndim != 1:
        y = np.ravel(y)
    if len(y) != X.shape[0]:
        raise ValueError(
            f"Found input variables with inconsistent numbers of samples: "
            f"[{X.shape[0]}, {len(y)}]"
        )
    return X, y


def check_sample_weight(sample_weight, X, dtype=None):
    """Validate sample weights (reference ``_check_sample_weight``)."""
    n_samples = X.shape[0]
    if dtype is None:
        dtype = X.dtype if X.dtype in (np.float32, np.float64) else np.float64
    if sample_weight is None:
        return np.ones(n_samples, dtype=dtype)
    if isinstance(sample_weight, numbers.Number):
        return np.full(n_samples, sample_weight, dtype=dtype)
    sample_weight = np.asarray(sample_weight, dtype=dtype)
    if sample_weight.ndim != 1 or sample_weight.shape[0] != n_samples:
        raise ValueError(
            f"sample_weight.shape == {sample_weight.shape}, "
            f"expected ({n_samples},)"
        )
    return sample_weight


def check_random_state(seed):
    """Turn seed into an ``np.random.RandomState`` (host-side init paths)."""
    if seed is None or seed is np.random:
        return np.random.mtrand._rand
    if isinstance(seed, numbers.Integral):
        return np.random.RandomState(int(seed))
    if isinstance(seed, np.random.RandomState):
        return seed
    raise ValueError(f"{seed!r} cannot be used to seed a RandomState instance")
