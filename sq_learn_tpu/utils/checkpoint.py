"""Checkpoint / resume.

The reference has no checkpoint story beyond "pickle the fitted estimator"
(sklearn convention, ``doc/modules/model_persistence.rst``) and the
incremental ``MiniBatchKMeans.partial_fit`` state (``_dmeans.py:2139``).
This module gives both a first-class, pickle-free form:

- :func:`save_estimator` / :func:`load_estimator` — fitted estimators as a
  directory of ``meta.json`` (class path + hyperparams) plus ``state.npz``
  (every public non-hyperparameter attribute). Survives process and host
  boundaries; no code execution on load beyond importing the estimator
  class.
- :func:`save_pytree` / :func:`load_pytree` — arbitrary JAX pytrees (e.g.
  mid-run Lloyd state ``(key, centers, counts)``) flattened to npz as
  positional leaves, restored against a same-structure template tree. This
  is the infra-failure recovery hook
  SURVEY §5 calls for: a q-means run interrupted between Lloyd iterations
  resumes from the last saved state.

Orbax is the natural backend for multi-host async checkpointing; these
helpers intentionally share its layout philosophy (tree → flat keypaths) so
swapping the IO layer for ``orbax.checkpoint`` is mechanical. We keep the
std-lib implementation as the default because single-host estimator state is
kilobytes, not terabytes.
"""

import importlib
import json
import os
import zlib

import numpy as np
import jax


_SCALARS = (int, float, bool, str, type(None))

#: estimator-checkpoint format version history: 1 = PR 1's meta.json +
#: state.npz layout; 2 (PR 9) adds ``state_digest`` (CRC32 over the
#: state.npz bytes) + this ``format_version`` field so a consumer — the
#: serving model registry above all — can reject a stale/bit-rotted/
#: hand-edited checkpoint with a clear error instead of silently serving
#: it. v1 checkpoints (no digest) still load; a FUTURE format version is
#: refused (an unknown layout must fail loudly, the schema-validator
#: rule applied to checkpoints).
FORMAT_VERSION = 2


def _file_crc32(path):
    crc = 0
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return f"{crc:08x}"


def _class_path(obj):
    cls = type(obj)
    return f"{cls.__module__}.{cls.__qualname__}"


def _import_class(path):
    module, _, name = path.rpartition(".")
    mod = importlib.import_module(module)
    obj = mod
    for part in name.split("."):
        obj = getattr(obj, part)
    return obj


def save_estimator(estimator, path):
    """Serialize a fitted estimator to directory ``path``.

    Hyperparameters come from ``get_params(deep=False)``; fitted state is
    every other public instance attribute (private ``_*`` attributes are
    transient by convention). Attributes that are neither arrays nor JSON
    scalars are recorded in ``skipped_state`` so a dropped attribute is
    visible in the checkpoint, not silent. Returns ``path``.
    """
    os.makedirs(path, exist_ok=True)
    hyper = estimator.get_params(deep=False)
    params = {}
    skipped_params = []
    for k, v in hyper.items():
        if isinstance(v, _SCALARS):
            params[k] = v
        elif isinstance(v, (list, tuple)) and all(
                isinstance(x, _SCALARS) for x in v):
            params[k] = list(v)
        elif isinstance(v, (np.ndarray, jax.Array)):
            params[k] = {"__array__": f"param_{k}"}
        else:
            skipped_params.append(k)  # e.g. a Mesh — not serializable

    arrays = {}
    state_scalars = {}
    state_arrays = []
    skipped_state = []
    for k, v in vars(estimator).items():
        if k.startswith("_") or k in hyper:
            continue
        if isinstance(v, (np.ndarray, jax.Array)):
            arrays[f"state_{k}"] = np.asarray(v)
            state_arrays.append(k)
        elif isinstance(v, _SCALARS):
            state_scalars[k] = v
        elif isinstance(v, (np.floating, np.integer, np.bool_)):
            state_scalars[k] = v.item()
        else:
            skipped_state.append(k)

    for k, v in hyper.items():
        if isinstance(v, (np.ndarray, jax.Array)):
            arrays[f"param_{k}"] = np.asarray(v)

    # the npz is written FIRST so its content digest can ride in the
    # meta — load_estimator verifies the digest before reconstructing,
    # turning silent state corruption/substitution into a loud error
    np.savez(os.path.join(path, "state.npz"), **arrays)
    meta = {
        "format": "sq-learn-tpu-estimator-v1",
        "format_version": FORMAT_VERSION,
        "state_digest": _file_crc32(os.path.join(path, "state.npz")),
        "class": _class_path(estimator),
        "params": params,
        "skipped_params": skipped_params,
        "state_scalars": state_scalars,
        "state_arrays": state_arrays,
        "skipped_state": skipped_state,
    }
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1, default=str)
    return path


def load_estimator(path):
    """Reconstruct an estimator saved by :func:`save_estimator`.

    v2 checkpoints are digest-verified: the CRC32 of ``state.npz`` must
    match ``meta.state_digest`` or a :class:`ValueError` names the
    mismatch — the serving registry's stale-model guard. v1 checkpoints
    (no digest) load unchecked; a checkpoint claiming a FUTURE format
    version is refused rather than misread.
    """
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    if meta.get("format") != "sq-learn-tpu-estimator-v1":
        raise ValueError(f"not an estimator checkpoint: {path}")
    version = meta.get("format_version", 1)
    if version > FORMAT_VERSION:
        raise ValueError(
            f"estimator checkpoint {path} has format_version {version}; "
            f"this build reads <= {FORMAT_VERSION} — refusing to guess "
            "at an unknown layout")
    digest = meta.get("state_digest")
    if digest is not None:
        actual = _file_crc32(os.path.join(path, "state.npz"))
        if actual != digest:
            raise ValueError(
                f"estimator checkpoint {path} is stale or corrupt: "
                f"state.npz digest {actual} != recorded {digest} "
                "(refusing to serve a fitted model whose state does not "
                "match its manifest)")
    npz = np.load(os.path.join(path, "state.npz"))
    params = {}
    for k, v in meta["params"].items():
        if isinstance(v, dict) and "__array__" in v:
            params[k] = npz[v["__array__"]]
        else:
            params[k] = v
    cls = _import_class(meta["class"])
    est = cls(**params)
    for k, v in meta["state_scalars"].items():
        setattr(est, k, v)
    for k in meta["state_arrays"]:
        setattr(est, k, npz[f"state_{k}"])
    return est


# ---------------------------------------------------------------------------
# pytree checkpointing (mid-run state)
# ---------------------------------------------------------------------------


def save_pytree(path, tree, step=None):
    """Save a JAX pytree to ``path`` (an ``.npz`` file). ``step`` is an
    optional integer recorded alongside (e.g. the Lloyd iteration)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    arrays["__treedef__"] = np.asarray(str(treedef))
    if step is not None:
        arrays["__step__"] = np.asarray(int(step))
    np.savez(path, **arrays)
    return path


def load_pytree(path, like):
    """Load a pytree saved by :func:`save_pytree`. ``like`` is a pytree with
    the same structure (its leaf values are ignored). Returns
    ``(tree, step)``; ``step`` is None if not recorded."""
    npz = np.load(path if str(path).endswith(".npz") else str(path) + ".npz",
                  allow_pickle=False)
    n = sum(1 for k in npz.files if k.startswith("leaf_"))
    leaves = [npz[f"leaf_{i}"] for i in range(n)]
    treedef = jax.tree_util.tree_structure(like)
    if treedef.num_leaves != n:
        raise ValueError(
            f"checkpoint has {n} leaves; template has {treedef.num_leaves}")
    tree = jax.tree_util.tree_unflatten(treedef, leaves)
    step = int(npz["__step__"]) if "__step__" in npz.files else None
    return tree, step


# ---------------------------------------------------------------------------
# streaming-pass checkpoints (resumable tiled passes)
# ---------------------------------------------------------------------------


def save_stream_state(path, acc, cursor, fingerprint):
    """Checkpoint a streamed pass: the host-snapshotted accumulator pytree
    plus the tile ``cursor`` (the next tile index to process) and the
    pass ``fingerprint`` (the caller's identity string — shape, dtype,
    tile plan, pass sequence — that :func:`load_stream_state` matches
    against so a stale file can never resume the wrong pass).

    The write is torn-write-hardened in three steps: the temp file is
    **fsynced** before it is renamed (a crash after ``os.replace`` must
    never surface a file whose data pages were still in the page cache),
    the previous checkpoint is **retained** as ``<path>.prev`` rather
    than overwritten, and only then does the new file take the primary
    name. A SIGKILL at ANY instant therefore leaves at least one
    complete, durable snapshot for :func:`load_stream_state` — the whole
    point is surviving exactly that kind of death.
    """
    leaves, _ = jax.tree_util.tree_flatten(acc)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    arrays["__cursor__"] = np.asarray(int(cursor))
    arrays["__fingerprint__"] = np.asarray(str(fingerprint))
    tmp = str(path) + ".tmp.npz"
    with open(tmp, "wb") as fh:
        np.savez(fh, **arrays)
        fh.flush()
        os.fsync(fh.fileno())
    if os.path.exists(path):
        os.replace(path, str(path) + ".prev")
    os.replace(tmp, path)
    return path


class AsyncStreamCheckpointer:
    """Background writer for :func:`save_stream_state` snapshots.

    A mid-epoch checkpoint used to stall the batch loop for the full
    npz-write + fsync + rename; this moves the write to ONE worker thread
    while keeping every durability property of the serial path (the
    worker calls the same :func:`save_stream_state` — fsync-before-
    rename, ``.prev`` retention, torn-newest fallback all unchanged):

    - :meth:`submit` deep-copies the accumulator ON THE CALLER'S thread
      (the fit loop mutates its 0-d scalars in place) and hands it to the
      writer — the caller pays a small-array copy, never the I/O.
    - **latest-wins**: a snapshot submitted while the previous one is
      still writing replaces any not-yet-started pending snapshot (the
      ``dropped`` count); resume then replays a few more batches — the
      keyed batch schedule makes any boundary an equally valid resume
      point, so bit-for-bit parity is unaffected.
    - :meth:`close` DRAINS the pending write before returning, so a
      finished fit can delete its checkpoint files without racing a
      late write that would resurrect one; a writer-side error is
      re-raised on the next :meth:`submit`/:meth:`close`.
    """

    #: lock-discipline contract (``sq_learn_tpu.analysis``): writer/
    #: caller shared state is only written under ``self._cond``.
    _GUARDED_BY = {"_cond": ("_pending", "_writing", "_error", "_stop",
                             "writes", "dropped")}

    def __init__(self, path):
        import threading

        self.path = str(path)
        self.writes = 0
        self.dropped = 0
        self._cond = threading.Condition()
        self._pending = None
        self._writing = False
        self._error = None
        self._stop = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="sq-stream-ckpt-writer")
        self._thread.start()

    def _run(self):
        while True:
            with self._cond:
                while self._pending is None and not self._stop:
                    self._cond.wait()
                if self._pending is None:
                    return  # stopped with nothing left to drain
                acc, cursor, fingerprint = self._pending
                self._pending = None
                self._writing = True
            try:
                save_stream_state(self.path, acc, cursor, fingerprint)
            except Exception as exc:  # surfaced on next submit/close
                with self._cond:
                    self._error = exc
            finally:
                with self._cond:
                    self._writing = False
                    self.writes += 1
                    self._cond.notify_all()

    def submit(self, acc, cursor, fingerprint):
        """Queue one snapshot (latest-wins). Raises a previous write's
        error here rather than losing it."""
        host = jax.tree_util.tree_map(
            lambda a: np.array(a, copy=True), acc)
        with self._cond:
            if self._error is not None:
                raise self._error
            if self._pending is not None:
                self.dropped += 1
            self._pending = (host, int(cursor), str(fingerprint))
            self._cond.notify_all()

    def close(self):
        """Drain the pending write, stop the worker, re-raise any writer
        error. Idempotent."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join()
        if self._error is not None:
            raise self._error


def _read_stream_state(path, like, fingerprint):
    """One checkpoint-file read attempt. Returns ``("ok", payload)``,
    ``("absent", None)``, ``("corrupt", None)`` (unreadable/truncated/
    structurally wrong — the torn-write shapes), or
    ``("mismatch", None)`` (a complete checkpoint of a DIFFERENT pass —
    never fall back past it: its ``.prev`` sibling is older still)."""
    if not os.path.exists(path):
        return "absent", None
    try:
        npz = np.load(path, allow_pickle=False)
    except Exception:
        return "corrupt", None
    try:
        with npz:
            if ("__fingerprint__" not in npz.files
                    or "__cursor__" not in npz.files):
                return "corrupt", None
            if str(npz["__fingerprint__"]) != str(fingerprint):
                return "mismatch", None
            treedef = jax.tree_util.tree_structure(like)
            n = sum(1 for k in npz.files if k.startswith("leaf_"))
            if treedef.num_leaves != n:
                return "mismatch", None
            leaves = [npz[f"leaf_{i}"] for i in range(n)]
            cursor = int(npz["__cursor__"])
    except Exception:
        # a zip central directory can parse while a member is truncated:
        # the torn tail surfaces here, on the member read
        return "corrupt", None
    return "ok", (jax.tree_util.tree_unflatten(treedef, leaves), cursor)


def load_stream_state(path, like, fingerprint):
    """Load a streamed-pass checkpoint saved by :func:`save_stream_state`.

    Returns ``(acc_tree, cursor)`` with ``acc_tree`` unflattened against
    the structure of ``like`` (leaf values ignored), or ``None`` when no
    usable checkpoint exists. A newest file that is truncated/corrupt —
    or absent while ``<path>.prev`` exists (the kill-between-renames
    window) — falls back to the retained previous snapshot instead of
    cold-starting: losing one checkpoint interval is recoverable, losing
    the whole pass is the failure this file exists to prevent. A
    complete checkpoint with a different ``fingerprint`` is a different
    pass: ignored without fallback (its ``.prev`` is older still), never
    trusted.
    """
    status, out = _read_stream_state(path, like, fingerprint)
    if status == "ok":
        return out
    if status == "mismatch":
        return None
    status, out = _read_stream_state(str(path) + ".prev", like, fingerprint)
    return out if status == "ok" else None
