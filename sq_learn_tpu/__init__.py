"""sq_learn_tpu — a TPU-native simulated fault-tolerant-quantum ML framework.

Capabilities of the reference (federicomegler/sq-learn — quantum PCA, q-means
clustering, quantum LS-SVM, and the quantum-routine simulation library they
share), re-designed JAX-first: jit'd, vmap-able, key-threaded kernels on XLA,
sharded over device meshes via ``shard_map`` + collectives. See SURVEY.md for
the structural map of the reference this build follows.
"""

from ._config import (config_context, default_dtype, get_config,
                      resolve_device, set_config)
from .base import (
    BaseEstimator,
    ClassifierMixin,
    ClusterMixin,
    NotFittedError,
    TransformerMixin,
    check_is_fitted,
    clone,
)

__version__ = "0.1.0"

# Submodules are imported lazily-but-eagerly here; keep this list in sync with
# the component inventory in SURVEY.md §2.
from . import obs  # noqa: E402  (first: everything else instruments through it)
from . import resilience  # noqa: E402  (second: the streaming engine's puts supervise through it)
from . import ops, utils  # noqa: E402

from . import datasets, metrics, model_selection, models, native, parallel  # noqa: E402
from . import streaming  # noqa: E402
from . import serving  # noqa: E402  (after streaming: buckets come from it)
from . import feature_extraction, pipeline, preprocessing  # noqa: E402
# reference-namespace facades (sklearn/cluster, decomposition, svm,
# neighbors, QuantumUtility) so reference users find familiar paths
from . import QuantumUtility, cluster, decomposition, neighbors, svm  # noqa: E402
from .feature_extraction import FeatureHasher  # noqa: E402
from .models import (  # noqa: E402
    KMeans,
    KNeighborsClassifier,
    MiniBatchKMeans,
    MiniBatchQKMeans,
    PCA,
    QKMeans,
    QLSSVC,
    QPCA,
    TruncatedSVD,
)
from .pipeline import Pipeline, make_pipeline  # noqa: E402
from .utils import show_versions  # noqa: E402

__all__ = [
    "show_versions",
    "config_context",
    "default_dtype",
    "get_config",
    "resolve_device",
    "set_config",
    "BaseEstimator",
    "ClassifierMixin",
    "ClusterMixin",
    "NotFittedError",
    "TransformerMixin",
    "check_is_fitted",
    "clone",
    "obs",
    "ops",
    "resilience",
    "serving",
    "utils",
    "native",
    "parallel",
    "cluster",
    "decomposition",
    "svm",
    "neighbors",
    "QuantumUtility",
    "metrics",
    "datasets",
    "models",
    "model_selection",
    "feature_extraction",
    "pipeline",
    "preprocessing",
    "FeatureHasher",
    "KMeans",
    "KNeighborsClassifier",
    "MiniBatchKMeans",
    "MiniBatchQKMeans",
    "PCA",
    "Pipeline",
    "QKMeans",
    "QLSSVC",
    "QPCA",
    "TruncatedSVD",
    "make_pipeline",
]
