"""Elastic multi-host training plane: survive a host death mid-fit.

ROADMAP item 2. The single-process half of the durability story already
exists — breaker (PR 3), bit-for-bit stream/epoch resume (PR 8),
SIGKILL-survivable fits — but the training plane itself ran on one
process with virtual devices: a dead host meant a dead fit. This module
makes host failure a *handled, observable, resumable* event:

- an :class:`ElasticCoordinator` (parent process, OUTSIDE the mesh)
  launches N workers and hosts one distributed KV/coordination service
  per **generation** (:func:`sq_learn_tpu.parallel.distributed.
  start_coordinator_service`) — any worker, including node 0, may die
  without taking the control plane with it;
- each worker joins via the raw-client path of
  :func:`~sq_learn_tpu.parallel.distributed.initialize`
  (``elastic=True``), certifies the mesh by running the existing
  shard_map Lloyd kernel across it, and publishes **heartbeats** to the
  KV store from a :class:`LeaseSupervisor` thread;
- the fit itself is the **window-synchronous q-means fold** (below):
  host failure is detected when a peer's window partial never lands
  inside its lease, the survivors abort the generation, the coordinator
  re-forms an (N-1)-world on a fresh port with a bumped generation, and
  the fit resumes from the committed checkpoint — **bit-for-bit equal**
  to an uninterrupted (N-1)-host run of the same plan;
- every transition lands a schema-v10 ``elastic`` obs record
  (generation, failed host, detection latency, shrink wall-clock,
  resumed cursor) with a per-generation trace lane; under the PR 19
  fleet contract every process's records additionally carry the
  coordinator-minted ``fleet`` envelope (run_id / host / pid / live
  generation), clock samples piggyback on the existing KV exchanges
  (heartbeats, manifests, progress commits), per-host ``window`` and
  node-0 ``commit`` events mirror the fold ledger, and each worker
  durably flushes its shard at every commit-window boundary and before
  ``os._exit`` — :mod:`sq_learn_tpu.obs.fleet` merges the shards into
  one clock-aligned mesh timeline and reconciles the commit ledger.

Topology-invariant state (the parity argument)
----------------------------------------------
One epoch visits the shards in the canonical order of
:meth:`~sq_learn_tpu.oocore.epochs.EpochPlan.shard_order`; position
``p`` of that order is *owned* by host ``p % n_hosts``
(:meth:`~sq_learn_tpu.oocore.epochs.EpochPlan.host_partition`). Work
advances in **windows** of ``SQ_ELASTIC_WINDOW`` consecutive positions:
at a window boundary every host holds identical state; each host
computes, for its owned positions only, the shard's minibatch partial
(cluster counts / sums / inertia, all float64) **against the centers
frozen at the window start**; partials are exchanged through the KV
store; then every host folds ALL of the window's partials in canonical
position order. The folded state is therefore a pure function of
``(data, seed, k, epochs, window)`` — ownership decides only *who
computes* a partial, never its value or its fold position — so a fit
that shrinks from N to N-1 hosts mid-run lands on exactly the bytes an
uninterrupted N-1-host (or 1-host) run produces. The in-process
:func:`elastic_fit_local` simulator shares this core and is the parity
reference the smoke/bench assert against.

Failure model
-------------
Worker death (SIGKILL, injected ``host_fail``) and worker stall
(``host_stall``) are handled for ANY worker; windows are atomic (a
window folds only when every partial landed, so a death voids the
in-flight window and the next generation recomputes it from the frozen
state — zero shards lost or double-folded, pinned by the per-shard
``folds`` counter carried in the state). Death of the *coordinator
process* (which holds the KV services and the run manifest) is
restart-the-world territory, out of scope here: it is the analogue of
losing the TPU pod's coordinator VM.

Generations and commits
-----------------------
The run directory's newest ``manifest.g<G>.json`` names the live
generation, its service port, and its surviving members. Checkpoints
commit under :func:`commit_fingerprint` — the topology-free base
fingerprint plus ``|gen=G`` — and only node 0 of the live generation
commits, after re-reading the manifest: a stale-generation worker gets
:class:`StaleGenerationError` (and a ``commit_refused`` record), never
a silent overwrite. Resume tries generations newest-first, so a
survivor of generation G loads the last commit of G or any ancestor.
"""

import io
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np

from .. import _knobs
from ..obs import recorder as _recorder
from ..oocore.epochs import EpochPlan
from ..resilience import faults as _faults

__all__ = [
    "ElasticCoordinator",
    "ElasticError",
    "GenerationAbort",
    "HostFailure",
    "LeaseSupervisor",
    "StaleGenerationError",
    "base_fingerprint",
    "collect_elastic_records",
    "commit_fingerprint",
    "elastic_fit_local",
    "fold_partial",
    "init_centers",
    "load_state",
    "new_state",
    "shard_partial",
]

_FMT = "elastic-qkm-v1"

#: worker exit codes: a stale worker (excluded from the new generation)
#: exits STALE without committing anything; an injected ``host_fail``
#: exits INJECTED so logs distinguish the scripted death from a crash
EXIT_OK, EXIT_STALE, EXIT_INJECTED = 0, 3, 17

#: the ``elastic`` obs record's event vocabulary (schema v10: v9's
#: transitions plus the per-host ``window`` fold-progress events and
#: node 0's ``commit`` ledger — the obs twin obs.fleet reconciles)
EVENTS = ("world_up", "resume", "host_fail", "host_stall", "shrink",
          "commit_refused", "stale_exit", "done", "window", "commit")


class ElasticError(RuntimeError):
    """Base of the elastic plane's failures."""


class HostFailure(ElasticError):
    """A host died and the shrink budget (``SQ_ELASTIC_MAX_SHRINKS``)
    is exhausted — the run cannot continue."""


class GenerationAbort(ElasticError):
    """Internal control flow: this generation's world is dead; tear
    down and re-join the next one."""


class StaleGenerationError(ElasticError):
    """A worker of a superseded generation tried to commit."""


def _heartbeat_s():
    return _knobs.get_float("SQ_ELASTIC_HEARTBEAT_S")


def _lease_s():
    return _knobs.get_float("SQ_ELASTIC_LEASE_S")


def _max_shrinks():
    return _knobs.get_int("SQ_ELASTIC_MAX_SHRINKS")


def _default_window():
    return max(1, _knobs.get_int("SQ_ELASTIC_WINDOW"))


def _emit(event, generation, n_hosts, rec=None, **fields):
    rec = rec if rec is not None else _recorder.get_recorder()
    if rec is None:
        return
    rec.record(dict({"type": "elastic", "event": str(event),
                     "generation": int(generation),
                     "n_hosts": int(n_hosts)}, **fields),
               kind="elastic_records")


def _emit_clock(peer, sent_ts, recv_ts, generation, via, rec=None):
    """One KV-carried clock sample (schema-v10 ``clock`` record): a
    value stamped ``time.time()`` by ``peer`` was observed locally at
    ``recv_ts``, so ``recv_ts - sent_ts`` upper-bounds how far this
    process's clock runs ahead of the peer's (the message can only age
    in flight). :func:`sq_learn_tpu.obs.fleet.clock_offsets` takes the
    minimum over samples and pairs the two directions — no extra
    messages beyond the exchanges the elastic plane already does."""
    rec = rec if rec is not None else _recorder.get_recorder()
    if rec is None:
        return
    rec.record({"type": "clock", "peer": str(peer),
                "sent_ts": float(sent_ts), "recv_ts": float(recv_ts),
                "generation": int(generation), "via": str(via)})


# ---------------------------------------------------------------------------
# pure fold-window core (numpy-only, bitwise deterministic: no BLAS in
# the distance/fold path — reductions are numpy's own, so two processes
# computing the same partial produce the same bytes)
# ---------------------------------------------------------------------------


def base_fingerprint(source, n_clusters, seed, epochs, window):
    """Topology-free identity of the fit: data content + plan. Host
    count is deliberately absent — the whole point is that a shrunk
    world resumes the SAME pass."""
    return (f"{_FMT}|data={source.fingerprint}|shards={source.n_shards}"
            f"|k={int(n_clusters)}|seed={int(seed)}|epochs={int(epochs)}"
            f"|window={int(window)}")


def commit_fingerprint(base, generation):
    """The checkpoint fingerprint a generation commits under: stale
    generations fail the fingerprint match instead of resuming the
    wrong world's pass."""
    return f"{base}|gen={int(generation)}"


def init_centers(source, n_clusters, seed):
    """Deterministic k distinct seed rows (keyed RNG, sorted for read
    locality)."""
    rng = np.random.default_rng((int(seed), 0xE1A5))
    rows = np.sort(rng.choice(len(source), size=int(n_clusters),
                              replace=False))
    return np.asarray(source.take(rows), np.float64)


def new_state(n_clusters, n_features, n_shards, centers):
    """The fold state pytree: centers/counts/inertia plus the per-shard
    ``folds`` counter — the ledger that lets the end of the fit assert
    every shard folded exactly ``epochs`` times (zero lost, zero
    double-folded)."""
    return {"centers": np.array(centers, np.float64).reshape(
                int(n_clusters), int(n_features)),
            "counts": np.zeros(int(n_clusters), np.float64),
            "folds": np.zeros(int(n_shards), np.int64),
            "inertia": np.zeros((), np.float64)}


def shard_partial(centers, X):
    """One shard's minibatch partial against frozen ``centers``:
    ``(counts, sums, inertia)`` in float64. Chunked broadcast distances
    + ``np.add.at`` scatter — no matmul, so the result is bitwise
    reproducible across processes regardless of BLAS threading."""
    X = np.asarray(X, np.float64)
    k = centers.shape[0]
    counts = np.zeros(k, np.float64)
    sums = np.zeros_like(centers)
    inertia = 0.0
    for lo in range(0, X.shape[0], 1024):
        blk = X[lo:lo + 1024]
        d2 = ((blk[:, None, :] - centers[None, :, :]) ** 2).sum(axis=2)
        lab = np.argmin(d2, axis=1)
        counts += np.bincount(lab, minlength=k).astype(np.float64)
        np.add.at(sums, lab, blk)
        inertia += float(d2[np.arange(blk.shape[0]), lab].sum())
    return counts, sums, inertia


def fold_partial(state, shard, partial):
    """Fold one position's partial into the state (the minibatch
    k-means center update, reference Utility.py's incremental mean kept
    in float64). MUST be called in canonical position order — that is
    what makes the state topology-invariant."""
    counts_p, sums_p, inertia_p = partial
    counts_p = np.asarray(counts_p, np.float64)
    sums_p = np.asarray(sums_p, np.float64)
    C = state["centers"]
    newv = state["counts"] + counts_p
    nz = counts_p > 0
    C[nz] += (sums_p[nz] - counts_p[nz, None] * C[nz]) / newv[nz, None]
    state["counts"] = newv
    state["inertia"] = state["inertia"] + np.float64(inertia_p)
    state["folds"][int(shard)] += 1


def load_state(path, template, base, generation):
    """Resume from the newest usable commit: try ``generation`` down to
    0 (a survivor of generation G accepts its own or any ancestor's
    commit; a FUTURE generation's commit never matches, so a stale
    worker cannot resume past its world). Returns ``(state, cursor)``
    or None."""
    from ..utils.checkpoint import load_stream_state

    if path is None:
        return None
    for g in range(int(generation), -1, -1):
        out = load_stream_state(path, template, commit_fingerprint(base, g))
        if out is not None:
            state, cursor = out
            return ({k: np.array(v) for k, v in state.items()}, int(cursor))
    return None


def _window_index(epoch, w_lo, n_shards, window):
    return int(epoch) * (-(-int(n_shards) // int(window))) \
        + int(w_lo) // int(window)


# ---------------------------------------------------------------------------
# in-process simulator (the deterministic parity reference + test rig)
# ---------------------------------------------------------------------------


def elastic_fit_local(source, n_clusters, *, n_hosts=1, seed=0, epochs=1,
                      window=None, ckpt_path=None, generation=0,
                      max_shrinks=None):
    """Run the window-synchronous fold with ``n_hosts`` *logical* hosts
    in one process. Shares the exact pure core the real workers run —
    and because the state is topology-invariant, its result for ANY
    ``n_hosts`` is the bit-parity reference for a real multi-process
    run (interrupted or not) of the same plan.

    Armed ``host_fail``/``host_stall`` faults fire through
    :meth:`~sq_learn_tpu.resilience.faults.FaultPlan.host_event` at
    each window boundary (hosts queried in id order): a fail removes
    the host, bumps the generation, and recomputes the voided window
    with the survivors; a stall is recorded and the fit continues —
    both without any real process or clock, which is what makes the
    test matrix deterministic and fast."""
    W = int(window) if window else _default_window()
    budget = _max_shrinks() if max_shrinks is None else int(max_shrinks)
    plan = EpochPlan(seed=seed)
    k, m = int(n_clusters), int(source.shape[1])
    n_shards = int(source.n_shards)
    base = base_fingerprint(source, k, seed, epochs, W)
    template = new_state(k, m, n_shards, np.zeros((k, m)))
    gen = int(generation)
    loaded = load_state(ckpt_path, template, base, gen) if ckpt_path \
        else None
    if loaded is not None:
        state, cursor = loaded
    else:
        state, cursor = new_state(k, m, n_shards,
                                  init_centers(source, k, seed)), 0
    hosts = list(range(int(n_hosts)))
    _recorder.set_generation(gen)
    _emit("world_up", gen, len(hosts))
    _emit("resume", gen, len(hosts), cursor=int(cursor))
    total = int(epochs) * n_shards
    shrinks = 0
    while cursor < total:
        epoch, pos = divmod(cursor, n_shards)
        order = plan.shard_order(source, epoch)
        w_lo, w_hi = pos, min(pos + W, n_shards)
        w_idx = _window_index(epoch, w_lo, n_shards, W)
        fplan = _faults._active
        dead = None
        if fplan is not None:
            for h in hosts:
                ev = fplan.host_event(w_idx, h)
                if ev is not None and ev[0] == "fail":
                    dead = h
                    break
                if ev is not None and ev[0] == "stall":
                    _emit("host_stall", gen, len(hosts), failed_host=h,
                          window=w_idx, stall_s=float(ev[1]))
        if dead is not None:
            _emit("host_fail", gen, len(hosts), failed_host=dead,
                  window=w_idx, detect_s=0.0)
            if shrinks >= budget or len(hosts) <= 1:
                raise HostFailure(
                    f"host {dead} failed at window {w_idx} with the "
                    f"shrink budget exhausted ({shrinks}/{budget})")
            hosts.remove(dead)
            shrinks += 1
            gen += 1
            _recorder.set_generation(gen)
            _emit("shrink", gen, len(hosts), failed_host=dead,
                  shrink_s=0.0)
            _emit("world_up", gen, len(hosts))
            _emit("resume", gen, len(hosts), cursor=int(cursor))
            continue  # the voided window recomputes under the new world
        partials = {}
        for rank in range(len(hosts)):
            for p, s in plan.host_partition(source, epoch, len(hosts),
                                            rank, start_pos=w_lo):
                if p >= w_hi:
                    break
                partials[p] = shard_partial(state["centers"],
                                            source.read_shard(s))
        for p in range(w_lo, w_hi):
            fold_partial(state, int(order[p]), partials[p])
        cursor = epoch * n_shards + w_hi
        _emit("window", gen, len(hosts), window=w_idx, cursor=int(cursor))
        _emit("commit", gen, len(hosts), window=w_idx, cursor=int(cursor))
        if ckpt_path:
            from ..utils.checkpoint import save_stream_state

            save_stream_state(ckpt_path, state, cursor,
                              commit_fingerprint(base, gen))
    assert (state["folds"] == int(epochs)).all(), state["folds"]
    _emit("done", gen, len(hosts), cursor=int(cursor))
    _recorder.set_generation(None)
    return {"centers": state["centers"], "counts": state["counts"],
            "inertia": float(state["inertia"]), "folds": state["folds"],
            "generation": gen, "n_hosts": len(hosts), "shrinks": shrinks}


# ---------------------------------------------------------------------------
# real transport: KV exchange, leases, worker runtime, coordinator
# ---------------------------------------------------------------------------


def _kv_put_bytes(client, key, payload):
    if hasattr(client, "key_value_set_bytes"):
        client.key_value_set_bytes(key, payload)
        return
    import base64

    client.key_value_set(key, base64.b64encode(payload).decode("ascii"))


def _kv_get_bytes(client, key, timeout_ms):
    if hasattr(client, "blocking_key_value_get_bytes"):
        return client.blocking_key_value_get_bytes(key, int(timeout_ms))
    import base64

    return base64.b64decode(client.blocking_key_value_get(
        key, int(timeout_ms)))


def _pack_partial(counts, sums, inertia):
    buf = io.BytesIO()
    np.savez(buf, c=np.asarray(counts, np.float64),
             s=np.asarray(sums, np.float64), i=np.float64(inertia))
    return buf.getvalue()


def _unpack_partial(raw):
    with np.load(io.BytesIO(raw), allow_pickle=False) as npz:
        return (np.array(npz["c"]), np.array(npz["s"]),
                float(npz["i"]))


def _write_json_atomic(path, payload):
    tmp = str(path) + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, str(path))


def _read_manifest(run_dir):
    """The newest ``manifest.g<G>.json`` of the run, or None."""
    best = None
    for name in os.listdir(run_dir):
        if name.startswith("manifest.g") and name.endswith(".json"):
            try:
                g = int(name[len("manifest.g"):-len(".json")])
            except ValueError:
                continue
            if best is None or g > best[0]:
                best = (g, name)
    if best is None:
        return None
    try:
        with open(os.path.join(run_dir, best[1])) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None  # racing the coordinator's atomic replace


def _await_manifest(run_dir, min_generation, timeout_s=120.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        man = _read_manifest(run_dir)
        if man is not None and int(man["generation"]) >= int(min_generation):
            return man
        time.sleep(0.05)
    raise ElasticError(
        f"no generation >= {min_generation} manifest appeared in "
        f"{run_dir} within {timeout_s}s")


def check_commit_generation(run_dir, generation):
    """The commit guard: re-read the run manifest and refuse to commit
    from a superseded generation (``commit_refused`` record +
    :class:`StaleGenerationError`) — a stale worker can never clobber
    the live world's checkpoint."""
    man = _read_manifest(run_dir)
    live = None if man is None else int(man["generation"])
    if live != int(generation):
        _emit("commit_refused", int(generation), 0,
              manifest_generation=live)
        raise StaleGenerationError(
            f"worker of generation {generation} refusing to commit: the "
            f"run manifest is at generation {live}")


class LeaseSupervisor:
    """Heartbeat publisher + peer-lease arbiter of one worker.

    A daemon thread publishes sequence-numbered heartbeat keys
    (``elastic/g<G>/hb/<worker>/<seq>``) every ``SQ_ELASTIC_HEARTBEAT_S``
    seconds; :meth:`peer_alive` blocks on a peer's NEXT sequence number
    for one ``SQ_ELASTIC_LEASE_S`` lease — a timeout is the lease
    expiring, i.e. the peer is declared dead. XLA's own
    missed-heartbeat machinery is parked out of the way (see
    :mod:`.distributed`); this layer owns the failure timeline and
    feeds the PR 3 circuit breaker at every declaration.

    Heartbeat values carry the publisher's ``time.time()`` (PR 19):
    liveness still only checks key EXISTENCE, but the publisher thread
    also reads its ``peers``' fresh heartbeats with a tiny timeout and
    turns each into a ``clock`` record — the samples
    :func:`sq_learn_tpu.obs.fleet.clock_offsets` aligns the mesh
    timeline with, at zero extra protocol messages."""

    #: lock-discipline contract (``sq_learn_tpu.analysis``): the
    #: publisher thread and the fit thread share only these, written
    #: under the lock.
    _GUARDED_BY = {"_lock": ("_stop", "_seq")}

    def __init__(self, client, generation, host_id, heartbeat_s=None,
                 peers=()):
        self._client = client
        self._gen = int(generation)
        self._host = int(host_id)
        self._hb_s = float(heartbeat_s if heartbeat_s is not None
                           else _heartbeat_s())
        self._lock = threading.Lock()
        self._stop = False
        self._seq = 0
        self._last_seen = {}  # fit-thread-only: peer -> last seen seq
        # publisher-thread-only (like _last_seen is fit-thread-only; KV
        # reads are idempotent so the two frontiers never interfere):
        # per-peer heartbeat read frontier + remaining clock-sample
        # budget (SQ_OBS_FLEET_CLOCK_SAMPLES per peer per generation)
        self._clock_peers = [int(p) for p in peers
                             if int(p) != self._host]
        self._clock_next = {p: 1 for p in self._clock_peers}
        budget = max(0, _knobs.get_int("SQ_OBS_FLEET_CLOCK_SAMPLES"))
        self._clock_left = {p: budget for p in self._clock_peers}
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"sq-elastic-lease-w{self._host}")

    def start(self):
        self._thread.start()
        return self

    def _run(self):
        while True:
            with self._lock:
                if self._stop:
                    return
                self._seq += 1
                seq = self._seq
            try:
                self._client.key_value_set(
                    f"elastic/g{self._gen}/hb/{self._host}/{seq}",
                    str(time.time()))
            except Exception:
                return  # world tearing down: never crash the fit thread
            try:
                self._sample_peer_clocks()
            except Exception:
                pass  # clock sampling is best-effort telemetry
            time.sleep(self._hb_s)

    def _sample_peer_clocks(self):
        """Drain each peer's already-published heartbeats (tiny timeout
        — the publisher must never block on a dead peer) and emit one
        ``clock`` record per fresh key, up to the per-peer budget."""
        for peer in self._clock_peers:
            nxt = self._clock_next[peer]
            while self._clock_left[peer] > 0:
                key = f"elastic/g{self._gen}/hb/{peer}/{nxt}"
                try:
                    val = self._client.blocking_key_value_get(key, 5)
                except Exception:
                    break  # frontier: the peer hasn't published nxt yet
                recv = time.time()
                nxt += 1
                try:
                    sent = float(val)
                except (TypeError, ValueError):
                    continue  # unparsable value: count it seen, no sample
                self._clock_left[peer] -= 1
                _emit_clock(f"w{peer}", sent, recv, self._gen, "hb")
            self._clock_next[peer] = nxt

    def stop(self):
        with self._lock:
            self._stop = True

    def peer_alive(self, peer, lease_s=None):
        """Block until ``peer`` publishes a FRESH heartbeat or the lease
        expires. True = alive (late-but-publishing peers are stalls, not
        deaths); False = the lease expired.

        Already-published heartbeats are drained first with a tiny
        timeout — catch-up over a dead peer's backlog is not liveness,
        and without the drain a peer that heartbeat for a while before
        dying would look alive for backlog x lease (observed 31 s
        detection at a 1.5 s lease). Liveness is only the NEXT key,
        the one the peer must still be running to publish."""
        lz = float(lease_s if lease_s is not None else _lease_s())
        peer = int(peer)
        nxt = self._last_seen.get(peer, 0) + 1
        while True:
            key = f"elastic/g{self._gen}/hb/{peer}/{nxt}"
            try:
                self._client.blocking_key_value_get(key, 50)
            except Exception:
                break  # frontier found: key nxt does not exist yet
            self._last_seen[peer] = nxt
            nxt += 1
        key = f"elastic/g{self._gen}/hb/{peer}/{nxt}"
        try:
            self._client.blocking_key_value_get(key, max(1, int(lz * 1000)))
        except Exception:
            return False
        self._last_seen[peer] = nxt
        return True


def _write_failure_file(run_dir, generation, failed, by, detect_s):
    path = os.path.join(run_dir, f"failed.g{int(generation)}.w{int(failed)}"
                                 ".json")
    try:
        with open(path, "x") as fh:
            json.dump({"generation": int(generation), "failed": int(failed),
                       "by": int(by), "detect_s": float(detect_s)}, fh)
    except FileExistsError:
        pass  # both survivors detected; first writer wins


def _await_partial(client, lease, key, peer, lease_s, *, run_dir, gen,
                   n_hosts, worker, stall_budget=20):
    """Wait for a peer's window partial under the lease protocol: a KV
    timeout with the peer still heartbeating is a ``host_stall`` (keep
    waiting, bounded); a timeout with the lease expired is a
    ``host_fail`` — record it, feed the breaker, leave the failure file
    for the coordinator, abort the generation."""
    from ..resilience.supervisor import breaker

    t0 = time.monotonic()
    stalls = 0
    while True:
        try:
            return _kv_get_bytes(client, key,
                                 max(1, int(float(lease_s) * 1000)))
        except Exception:
            pass
        if lease.peer_alive(peer, lease_s) and stalls < stall_budget:
            stalls += 1
            if stalls == 1:
                _emit("host_stall", gen, n_hosts, host=int(worker),
                      failed_host=int(peer))
                breaker.record_failure("elastic_host_stall",
                                       site=f"elastic.g{gen}.w{peer}")
            continue
        detect_s = time.monotonic() - t0
        _emit("host_fail", gen, n_hosts, host=int(worker),
              failed_host=int(peer), detect_s=round(detect_s, 6))
        breaker.record_failure("elastic_host_fail",
                               site=f"elastic.g{gen}.w{peer}")
        _write_failure_file(run_dir, gen, peer, worker, detect_s)
        raise GenerationAbort(
            f"host {peer} lease expired after {detect_s:.3f}s waiting "
            f"for {key}")


def _certify_world(mesh, seed, generation):
    """Run the existing shard_map Lloyd kernel across the fresh world —
    the mesh is certified by a real cross-host collective, not a
    handshake. Deterministic tiny problem keyed on (seed, generation)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from . import distributed as dist
    from .lloyd import lloyd_single_sharded
    from .mesh import DATA_AXIS

    n_dev = int(mesh.devices.size)
    rows, m = 4 * n_dev, 5
    rng = np.random.default_rng((int(seed), int(generation), 0xCE27))
    X = rng.normal(size=(rows, m)).astype(np.float32)
    lo, hi, per = dist.host_shard_bounds(rows)
    shard = np.zeros((per, m), np.float32)
    shard[:hi - lo] = X[lo:hi]
    w = np.zeros((per,), np.float32)
    w[:hi - lo] = 1.0
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    Xg = jax.make_array_from_process_local_data(sharding, shard)
    wg = jax.make_array_from_process_local_data(sharding, w)
    xsqg = jax.make_array_from_process_local_data(
        sharding, (shard * shard).sum(axis=1))
    _, inertia, centers, n_iter, _ = lloyd_single_sharded(
        mesh, jax.random.PRNGKey(0), Xg, wg, X[:3], xsqg,
        delta=0.4, mode="delta", max_iter=2, tol=0.0)
    if not np.isfinite(float(inertia)):
        raise ElasticError(
            f"mesh certification produced non-finite inertia at "
            f"generation {generation}")
    return float(inertia)


# ---------------------------------------------------------------------------
# worker runtime
# ---------------------------------------------------------------------------


def _flush_obs():
    rec = _recorder.get_recorder()
    if rec is not None:
        flush = getattr(rec, "flush", None)
        if callable(flush):
            flush()


def _run_generation(run_dir, source, plan, state, cursor, *, gen, members,
                    node_id, worker_index, client, lease, cfg, base, ckpt):
    """One generation's share of the fit: window loop from ``cursor``
    until done or :class:`GenerationAbort`. Returns the final cursor."""
    from ..oocore.prefetch import iter_shards
    from ..utils.checkpoint import save_stream_state

    n = len(members)
    n_shards = int(source.n_shards)
    W = int(cfg["window"])
    epochs = int(cfg["epochs"])
    lz_s = float(cfg["lease_s"])
    total = epochs * n_shards
    while cursor < total:
        epoch, pos = divmod(cursor, n_shards)
        order = plan.shard_order(source, epoch)
        w_lo, w_hi = pos, min(pos + W, n_shards)
        w_idx = _window_index(epoch, w_lo, n_shards, W)
        fplan = _faults._active
        if fplan is not None:
            ev = fplan.host_event(w_idx, worker_index)
            if ev is not None and ev[0] == "fail":
                _flush_obs()
                sys.stdout.flush()
                os._exit(EXIT_INJECTED)
            if ev is not None and ev[0] == "stall":
                time.sleep(float(ev[1]))
        mine = [(p, s)
                for p, s in plan.host_partition(source, epoch, n, node_id,
                                                start_pos=w_lo)
                if p < w_hi]
        partials = {}
        shards_iter = iter_shards(source, [s for _, s in mine])
        try:
            for (p, s), raw in zip(mine, shards_iter):
                prt = shard_partial(state["centers"], raw)
                partials[p] = prt
                _kv_put_bytes(client,
                              f"elastic/g{gen}/x/{epoch * n_shards + p}",
                              _pack_partial(*prt))
        finally:
            shards_iter.close()
        for p in range(w_lo, w_hi):
            if p in partials:
                continue
            peer = members[p % n]
            raw = _await_partial(
                client, lease, f"elastic/g{gen}/x/{epoch * n_shards + p}",
                peer, lz_s, run_dir=run_dir, gen=gen, n_hosts=n,
                worker=worker_index)
            partials[p] = _unpack_partial(raw)
        for p in range(w_lo, w_hi):
            fold_partial(state, int(order[p]), partials[p])
        cursor = epoch * n_shards + w_hi
        _emit("window", gen, n, host=int(worker_index), window=w_idx,
              cursor=int(cursor))
        if node_id == 0:
            check_commit_generation(run_dir, gen)
            save_stream_state(ckpt, state, cursor,
                              commit_fingerprint(base, gen))
            # the ts doubles as a coordinator-side clock sample
            # (via="progress"): the parent reads it at its next poll
            _write_json_atomic(
                os.path.join(run_dir, "progress.json"),
                {"cursor": int(cursor), "generation": int(gen),
                 "epoch": int(epoch), "ts": time.time()})
            _emit("commit", gen, n, host=int(worker_index),
                  window=w_idx, cursor=int(cursor))
        # crash-safe telemetry: durably flush this worker's shard at
        # every commit-window boundary, so a SIGKILL loses at most the
        # in-flight window's lines — the victim's last flushed
        # ``window`` record is its provable progress
        _flush_obs()
    return cursor


def _worker_main(run_dir, worker_index):
    """The ``--worker`` entrypoint: join generations until the fit is
    done (or this worker is superseded), re-forming the world after
    every :class:`GenerationAbort`."""
    from ..oocore.store import open_store
    from . import distributed as dist

    with open(os.path.join(run_dir, "config.json")) as fh:
        cfg = json.load(fh)
    source = open_store(cfg["store"])
    k, m = int(cfg["n_clusters"]), int(source.shape[1])
    seed, epochs, W = int(cfg["seed"]), int(cfg["epochs"]), \
        int(cfg["window"])
    n_shards = int(source.n_shards)
    total = epochs * n_shards
    plan = EpochPlan(seed=seed)
    base = base_fingerprint(source, k, seed, epochs, W)
    ckpt = os.path.join(run_dir, "ckpt.npz")
    template = new_state(k, m, n_shards, np.zeros((k, m)))
    last_gen, abort_t = -1, None
    while True:
        man = _await_manifest(run_dir, last_gen + 1)
        gen = int(man["generation"])
        _recorder.set_generation(gen)
        if isinstance(man.get("ts"), (int, float)):
            # the coordinator stamped the manifest at write time: its
            # first observation here is a worker->coord clock sample
            _emit_clock("coord", man["ts"], time.time(), gen, "manifest")
        members = [int(x) for x in man["members"]]
        if worker_index not in members:
            _emit("stale_exit", gen, len(members), host=worker_index)
            return EXIT_STALE
        node_id = members.index(worker_index)
        n = len(members)
        dist.initialize(f"127.0.0.1:{man['port']}", n, node_id,
                        generation=gen, elastic=True)
        client = dist.world_client()
        lease = LeaseSupervisor(client, gen, worker_index,
                                cfg["heartbeat_s"],
                                peers=members).start()
        _certify_world(dist.global_mesh(), seed, gen)
        shrink_s = (time.monotonic() - abort_t) if abort_t is not None \
            else 0.0
        _emit("world_up", gen, n, host=worker_index,
              shrink_s=round(shrink_s, 6))
        loaded = load_state(ckpt, template, base, gen)
        if loaded is not None:
            state, cursor = loaded
        else:
            state, cursor = new_state(k, m, n_shards,
                                      init_centers(source, k, seed)), 0
        _emit("resume", gen, n, host=worker_index, cursor=int(cursor))
        try:
            cursor = _run_generation(
                run_dir, source, plan, state, cursor, gen=gen,
                members=members, node_id=node_id,
                worker_index=worker_index, client=client, lease=lease,
                cfg=cfg, base=base, ckpt=ckpt)
        except (GenerationAbort, StaleGenerationError):
            # a stale-commit refusal re-forms exactly like an abort: the
            # next manifest decides whether this worker is still a member
            abort_t = time.monotonic()
            lease.stop()
            dist.shutdown(barrier=False)
            last_gen = gen
            continue
        assert cursor == total, (cursor, total)
        assert (state["folds"] == epochs).all(), state["folds"]
        if node_id == 0:
            check_commit_generation(run_dir, gen)
            np.savez(os.path.join(run_dir, "result.npz"),
                     centers=state["centers"], counts=state["counts"],
                     inertia=state["inertia"], folds=state["folds"])
            _write_json_atomic(
                os.path.join(run_dir, "result.json"),
                {"generation": int(gen), "n_hosts": n,
                 "cursor": int(cursor),
                 "inertia": float(state["inertia"])})
        _emit("done", gen, n, host=worker_index, cursor=int(cursor))
        lease.stop()
        try:
            client.wait_at_barrier(f"elastic/done/g{gen}", 10_000)
        except Exception:
            pass  # peers may already be gone; the fit is committed
        return EXIT_OK


# ---------------------------------------------------------------------------
# coordinator
# ---------------------------------------------------------------------------


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _pick_port():
    port = _knobs.get_int("SQ_ELASTIC_PORT")
    return int(port) if port else _free_port()


def _xla_device_flags(devices_per_host):
    """Compose the child's XLA_FLAGS: strip any inherited virtual-device
    forcing, add ours."""
    flags = [f for f in (_knobs.get_raw("XLA_FLAGS") or "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append("--xla_force_host_platform_device_count="
                 f"{int(devices_per_host)}")
    return " ".join(flags)


def collect_elastic_records(run_dir):
    """All ``elastic`` obs records of a run's workers, in worker order —
    what the smoke/bench mine for detection latency and shrink
    wall-clock. A thin view over the PR 19 fleet loader (which subsumes
    it: :func:`sq_learn_tpu.obs.fleet.summarize` has the merged,
    clock-aligned picture); the coordinator shard is deliberately
    excluded so the mined latencies stay worker-observed."""
    from ..obs import fleet as _fleet

    out = []
    for host, records in _fleet.load_shards(run_dir):
        if not (host.startswith("w") and host[1:].isdigit()):
            continue
        for rec in records:
            if rec.get("type") == "elastic":
                rec = dict(rec)
                rec["_worker"] = host[1:]
                out.append(rec)
    return out


class ElasticCoordinator:
    """Parent-process control plane of one elastic fit.

    Owns the run directory (config + per-generation manifests), hosts
    one KV/coordination service per generation (outside the mesh, so no
    worker death can take it down), spawns the N workers, and reacts to
    deaths: a member process exiting before the result lands — or a
    survivor's lease-detection failure file — triggers a shrink (new
    port, new service, ``manifest.g<G+1>.json`` with the survivors),
    bounded by ``SQ_ELASTIC_MAX_SHRINKS``. The optional ``kill`` leg
    SIGKILLs a chosen worker once the committed cursor passes a
    threshold — the smoke/bench's scripted mid-epoch host death.

    Single-threaded poll loop; the services it holds stay referenced
    until the run object dies (destroying a service under live client
    poll threads QFATALs them)."""

    def __init__(self, run_dir, store_path, *, n_workers=3, n_clusters=8,
                 seed=0, epochs=2, window=None, devices_per_host=2,
                 max_shrinks=None, kill=None, worker_env=None,
                 heartbeat_s=None, lease_s=None, obs=True):
        self.run_dir = str(run_dir)
        self.store_path = str(store_path)
        self.n_workers = int(n_workers)
        self.n_clusters = int(n_clusters)
        self.seed = int(seed)
        self.epochs = int(epochs)
        self.window = int(window) if window else _default_window()
        self.devices_per_host = int(devices_per_host)
        self.max_shrinks = (_max_shrinks() if max_shrinks is None
                            else int(max_shrinks))
        self.kill = kill  # (worker_index, min_committed_cursor) or None
        self.worker_env = dict(worker_env or {})
        self.heartbeat_s = float(heartbeat_s if heartbeat_s is not None
                                 else _heartbeat_s())
        self.lease_s = float(lease_s if lease_s is not None else _lease_s())
        self.obs = bool(obs)
        # the fleet run_id (PR 19): minted here, inherited by every
        # spawned worker via env — an outer SQ_OBS_FLEET_RUN_ID (e.g. a
        # bench parent already inside a fleet) wins so nested runs stay
        # correlated under one id
        self.run_id = (_knobs.get_str("SQ_OBS_FLEET_RUN_ID", "")
                       or f"elastic-{os.urandom(4).hex()}")
        self._obs_rec = None
        self.procs = {}
        self.timeline = []

    def _mark(self, event, **fields):
        self.timeline.append(dict({"t": time.monotonic(),
                                   "event": event}, **fields))

    def _spawn(self, worker_index):
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env.pop("PYTHONSTARTUP", None)
        env["JAX_PLATFORMS"] = "cpu"
        # repo root ONLY: dropping any sitecustomize dir from PYTHONPATH
        # keeps the axon preimport (and a wedged relay) out of workers
        env["PYTHONPATH"] = repo
        env["XLA_FLAGS"] = _xla_device_flags(self.devices_per_host)
        if self.obs:
            env["SQ_OBS"] = "1"
            env["SQ_OBS_PATH"] = os.path.join(
                self.run_dir, f"obs.w{worker_index}.jsonl")
            # fleet correlation (PR 19): the worker's recorder stamps
            # the coordinator-minted run_id + host label on every record
            env["SQ_OBS_FLEET_RUN_ID"] = str(self.run_id)
            env["SQ_OBS_FLEET_HOST"] = f"w{worker_index}"
            env.pop("SQ_OBS_TRACE", None)
        env.update(self.worker_env)
        log = open(os.path.join(self.run_dir,
                                f"worker{worker_index}.log"), "ab")
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "sq_learn_tpu.parallel.elastic",
                 "--worker", self.run_dir, str(worker_index)],
                env=env, stdout=log, stderr=subprocess.STDOUT)
        finally:
            log.close()
        return proc

    def _shrink(self, generation, members, dead):
        from . import distributed as dist

        gen = generation + 1
        members = [i for i in members if i not in dead]
        port = _pick_port()
        self._services.append(dist.start_coordinator_service(
            f"127.0.0.1:{port}", len(members)))
        _write_json_atomic(
            os.path.join(self.run_dir, f"manifest.g{gen}.json"),
            {"generation": gen, "port": port, "members": members,
             "ts": time.time()})
        _emit("shrink", gen, len(members), rec=self._obs_rec,
              failed_host=int(dead[0]))
        self._mark("shrink", generation=gen, members=members, dead=dead)
        return gen, members

    def run(self, timeout_s=300.0):
        from . import distributed as dist

        os.makedirs(self.run_dir, exist_ok=True)
        if self.obs and self._obs_rec is None:
            # PRIVATE recorder, never the global enable(): a bench
            # parent owns the process-global sink, and the coordinator
            # shard must land in the run directory next to the workers'
            self._obs_rec = _recorder.Recorder(
                os.path.join(self.run_dir, "obs.coord.jsonl"),
                run_id=self.run_id, host="coord")
        _write_json_atomic(
            os.path.join(self.run_dir, "config.json"),
            {"store": self.store_path, "n_clusters": self.n_clusters,
             "seed": self.seed, "epochs": self.epochs,
             "window": self.window, "heartbeat_s": self.heartbeat_s,
             "lease_s": self.lease_s})
        self._services = []
        members = list(range(self.n_workers))
        gen = 0
        port = _pick_port()
        self._services.append(dist.start_coordinator_service(
            f"127.0.0.1:{port}", len(members)))
        _write_json_atomic(
            os.path.join(self.run_dir, "manifest.g0.json"),
            {"generation": 0, "port": port, "members": members,
             "ts": time.time()})
        for i in members:
            self.procs[i] = self._spawn(i)
        self._mark("launched", members=list(members))
        result_json = os.path.join(self.run_dir, "result.json")
        shrinks, killed, kill_done = 0, [], self.kill is None
        last_prog_ts = 0.0
        t0 = time.monotonic()
        try:
            while True:
                if time.monotonic() - t0 > timeout_s:
                    raise ElasticError(
                        f"elastic run did not finish in {timeout_s}s "
                        f"(gen {gen}, members {members})")
                prog = None
                try:
                    with open(os.path.join(self.run_dir,
                                           "progress.json")) as fh:
                        prog = json.load(fh)
                except (OSError, ValueError):
                    pass
                if prog and isinstance(prog.get("ts"), (int, float)) \
                        and prog["ts"] > last_prog_ts:
                    # node 0's commit stamp, first observed here: a
                    # coord->node0 clock sample at zero extra messages
                    last_prog_ts = float(prog["ts"])
                    _emit_clock(f"w{members[0]}", prog["ts"], time.time(),
                                prog.get("generation", gen), "progress",
                                rec=self._obs_rec)
                if not kill_done:
                    if prog and prog["cursor"] >= int(self.kill[1]):
                        victim = int(self.kill[0])
                        os.kill(self.procs[victim].pid, signal.SIGKILL)
                        killed.append(victim)
                        kill_done = True
                        self._mark("sigkill", worker=victim,
                                   cursor=prog["cursor"])
                done = os.path.exists(result_json)
                dead = [i for i in members
                        if self.procs[i].poll() is not None]
                for name in os.listdir(self.run_dir):
                    if name.startswith(f"failed.g{gen}.w"):
                        w = int(name[len(f"failed.g{gen}.w"):-len(".json")])
                        if w in members and w not in dead:
                            dead.append(w)
                if dead and not done:
                    shrinks += len(dead)
                    if shrinks > self.max_shrinks or len(members) - \
                            len(dead) < 1:
                        raise HostFailure(
                            f"worker(s) {dead} died with the shrink "
                            f"budget exhausted "
                            f"({shrinks}/{self.max_shrinks})")
                    gen, members = self._shrink(gen, members, dead)
                if done and all(p.poll() is not None
                                for p in self.procs.values()):
                    break
                time.sleep(0.05)
        finally:
            for p in self.procs.values():
                if p.poll() is None:
                    p.kill()
            for p in self.procs.values():
                p.wait(timeout=30)
            if self._obs_rec is not None:
                self._obs_rec.flush()
                self._obs_rec.close()
                self._obs_rec = None
        with open(result_json) as fh:
            summary = json.load(fh)
        with np.load(os.path.join(self.run_dir, "result.npz")) as npz:
            result = {k: np.array(npz[k]) for k in npz.files}
        self._mark("done", generation=summary["generation"])
        return {"centers": result["centers"], "counts": result["counts"],
                "inertia": float(result["inertia"]),
                "folds": result["folds"],
                "generation": int(summary["generation"]),
                "n_hosts": int(summary["n_hosts"]), "shrinks": shrinks,
                "killed": killed, "timeline": list(self.timeline),
                "exit_codes": {i: p.returncode
                               for i, p in self.procs.items()}}


def _main(argv):
    if len(argv) >= 4 and argv[1] == "--worker":
        import jax

        # in-process platform pin: with a sitecustomize that preimported
        # jax, env vars are too late (CLAUDE.md environment gotchas)
        jax.config.update("jax_platforms", "cpu")
        run_dir, widx = argv[2], int(argv[3])
        try:
            rc = _worker_main(run_dir, widx)
        except Exception:
            import traceback

            traceback.print_exc()
            try:
                with open(os.path.join(run_dir, f"error.w{widx}.json"),
                          "w") as fh:
                    json.dump({"worker": widx,
                               "error": traceback.format_exc()}, fh)
            except OSError:
                pass
            rc = 1
        # never return through interpreter teardown with a live client
        # poll thread (observed QFATAL at xla client.h:80)
        _flush_obs()
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(int(rc))
    print("usage: python -m sq_learn_tpu.parallel.elastic "
          "--worker <run_dir> <worker_index>", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(_main(sys.argv))
