"""Device mesh and sharding helpers.

The reference's parallelism is OpenMP threads + fork-join pools (SURVEY §2.3).
The TPU-native equivalent is SPMD over a ``jax.sharding.Mesh``: data-parallel
sharding of the sample axis with ``psum`` reductions over ICI. These helpers
centralize mesh construction and host→device placement so estimators only
say "shard X over the data axis".
"""

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def make_mesh(devices=None, axis_name=DATA_AXIS):
    """Build a 1-D data-parallel mesh over ``devices`` (default: all devices
    of the configured platform)."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def data_sharding(mesh, axis_name=DATA_AXIS):
    """Sharding that splits axis 0 over the mesh."""
    return NamedSharding(mesh, P(axis_name))


def replicated(mesh):
    return NamedSharding(mesh, P())


def pad_to_multiple(X, multiple, pad_value=0.0):
    """Pad axis 0 to a device-count multiple (SPMD needs equal shards).

    Returns (padded_array, original_length). Padding rows carry
    ``pad_value`` and must be masked out by the caller via sample weights.
    """
    import jax.numpy as jnp

    n = X.shape[0]
    remainder = n % multiple
    if remainder == 0:
        return X, n
    pad = multiple - remainder
    pad_width = ((0, pad),) + ((0, 0),) * (X.ndim - 1)
    return jnp.pad(jnp.asarray(X), pad_width, constant_values=pad_value), n


def shard_rows(mesh, *arrays, axis_name=DATA_AXIS):
    """Place arrays with axis 0 sharded over the mesh."""
    sharding = data_sharding(mesh, axis_name)
    out = tuple(jax.device_put(a, sharding) for a in arrays)
    return out if len(out) > 1 else out[0]


def pad_and_shard(mesh, X):
    """Pad axis 0 to a device-count multiple and place (X, padding mask)
    row-sharded — the common preamble of every sharded row routine
    (SVD, tomography, k-NN). Returns (Xp_sharded, mask_sharded,
    n_true_rows); padding rows carry mask 0 and must be masked out or
    banished by the caller."""
    import jax.numpy as jnp

    X = jnp.asarray(X)
    n = X.shape[0]
    Xp, _ = pad_to_multiple(X, int(mesh.devices.size))
    mask = jnp.zeros((Xp.shape[0],), Xp.dtype).at[:n].set(1.0)
    Xp, mask = shard_rows(mesh, Xp, mask)
    return Xp, mask, n
