"""Elastic-mesh smoke: survive a real host death mid-fit, bit-for-bit.

``make elastic-smoke`` runs this module end to end on the CPU backend
(no hardware, no network beyond loopback):

1. build a tiny shard store on disk;
2. pin the topology-invariance claim in-process: the window-synchronous
   fold's :func:`~sq_learn_tpu.parallel.elastic.elastic_fit_local` at
   1, 2 and 3 logical hosts returns bit-identical state;
3. run a REAL uninterrupted 2-worker fit (separate processes, gloo
   collectives, coordinator-hosted KV service) and assert it equals the
   simulator bit-for-bit;
4. run a REAL 3-worker fit with a scripted SIGKILL of one worker
   mid-epoch (the coordinator waits for committed progress first, so
   the death lands in the middle of live fold windows, prefetcher
   armed) — the survivors must detect the death through the lease
   layer, shrink to a 2-host generation-1 world, resume from the
   committed checkpoint, and finish **bit-identical to the
   uninterrupted run** with every shard folded exactly ``epochs`` times
   (zero lost, zero double-folded);
5. validate every worker's obs JSONL against schema v10 and assert the
   elastic transition records (``world_up``/``host_fail``/``resume``/
   ``done`` across generations 0 and 1) carry the detection latency
   and shrink wall-clock the bench mines;
6. merge the run's per-process shards (coordinator + all three
   workers) into ONE fleet timeline (:mod:`sq_learn_tpu.obs.fleet`):
   every shard carries the same coordinator-minted run_id, the merged
   ``ts_fleet`` is monotone (clock-aligned from the KV-piggybacked
   samples), the SIGKILLed worker's shard still holds its fold
   progress up to its last pre-kill flush (crash-safe telemetry), the
   commit ledger reconciles (every committed window exactly once, no
   gaps), generation 1 has a detect→shrink→re-init→resume critical
   path — and the merged timeline is archived (schema-v10-valid)
   outside the scratch dir before it is removed.

Prints one JSON summary line; exit 0 = contract holds, 1 = violation.
"""

import json
import os
import shutil
import sys
import tempfile

import numpy as np

from .. import _knobs


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    from ..obs.schema import validate_jsonl
    from ..oocore.store import open_store, store_from_array
    from . import elastic

    failures = []
    base = tempfile.mkdtemp(prefix="sq_elastic_smoke_")
    summary = {"dir": base}
    try:
        rng = np.random.default_rng(11)
        X = np.asarray(rng.normal(size=(240, 6)), np.float64)
        store_path = os.path.join(base, "store")
        store_from_array(store_path, X, shard_bytes=6 * 48)
        src = open_store(store_path)
        n_shards = int(src.n_shards)
        epochs, window, k, seed = 2, 4, 4, 5
        summary["n_shards"] = n_shards

        # -- 1) topology invariance of the pure core ---------------------
        sims = [elastic.elastic_fit_local(src, k, n_hosts=n, seed=seed,
                                          epochs=epochs, window=window)
                for n in (1, 2, 3)]
        ref = sims[1]
        for n, sim in zip((1, 2, 3), sims):
            if not (np.array_equal(ref["centers"], sim["centers"])
                    and np.array_equal(ref["counts"], sim["counts"])):
                failures.append(f"simulator at n_hosts={n} diverges from "
                                f"the n_hosts=2 reference")
        if not (ref["folds"] == epochs).all():
            failures.append(f"simulator fold ledger broken: {ref['folds']}")

        # -- 2) real uninterrupted 2-worker run --------------------------
        co2 = elastic.ElasticCoordinator(
            os.path.join(base, "run2"), store_path, n_workers=2,
            n_clusters=k, seed=seed, epochs=epochs, window=window,
            devices_per_host=2, heartbeat_s=0.2, lease_s=1.5)
        r2 = co2.run(timeout_s=240)
        summary["uninterrupted"] = {"generation": r2["generation"],
                                    "exit_codes": r2["exit_codes"]}
        if not (np.array_equal(r2["centers"], ref["centers"])
                and np.array_equal(r2["counts"], ref["counts"])):
            failures.append("real 2-worker run diverges from the simulator")
        if r2["generation"] != 0 or any(c != 0
                                        for c in r2["exit_codes"].values()):
            failures.append(f"uninterrupted run not clean: {r2['exit_codes']}")

        # -- 3) real 3-worker run, one worker SIGKILLed mid-epoch --------
        run3 = os.path.join(base, "run3")
        co3 = elastic.ElasticCoordinator(
            run3, store_path, n_workers=3, n_clusters=k, seed=seed,
            epochs=epochs, window=window, devices_per_host=2,
            heartbeat_s=0.2, lease_s=1.5,
            kill=(2, 2 * window))  # death lands mid-epoch-0
        r3 = co3.run(timeout_s=240)
        summary["killed"] = {
            "generation": r3["generation"], "n_hosts": r3["n_hosts"],
            "shrinks": r3["shrinks"], "killed": r3["killed"],
            "exit_codes": r3["exit_codes"]}
        if r3["generation"] != 1 or r3["n_hosts"] != 2 \
                or r3["shrinks"] != 1:
            failures.append(f"kill leg did not shrink 3->2 exactly once: "
                            f"{summary['killed']}")
        if r3["exit_codes"].get(2) != -9:
            failures.append(f"victim did not die by SIGKILL: "
                            f"{r3['exit_codes']}")
        if any(r3["exit_codes"].get(w) != 0 for w in (0, 1)):
            failures.append(f"a survivor exited non-zero: "
                            f"{r3['exit_codes']}")
        # THE claim: interrupted-and-shrunk == uninterrupted, bit for bit
        if not (np.array_equal(r3["centers"], ref["centers"])
                and np.array_equal(r3["counts"], ref["counts"])):
            failures.append("killed run diverges from the uninterrupted "
                            "reference (bit parity broken)")
        if not (r3["folds"] == epochs).all():
            failures.append(f"shards lost or double-folded across the "
                            f"shrink: {r3['folds'].tolist()}")

        # -- 4) the timeline is in the artifact --------------------------
        recs = elastic.collect_elastic_records(run3)
        events = {(r["_worker"], r["event"], r["generation"])
                  for r in recs}
        for w in ("0", "1"):
            for needed in ((w, "world_up", 0), (w, "host_fail", 0),
                           (w, "world_up", 1), (w, "resume", 1),
                           (w, "done", 1)):
                if needed not in events:
                    failures.append(f"missing elastic record {needed}")
        if ("2", "world_up", 0) not in events:
            failures.append("the victim never recorded joining g0")
        detect = [r["detect_s"] for r in recs
                  if r["event"] == "host_fail" and "detect_s" in r]
        shrink = [r["shrink_s"] for r in recs
                  if r["event"] == "world_up" and r["generation"] == 1
                  and "shrink_s" in r]
        if not detect or not all(d > 0 for d in detect):
            failures.append(f"no positive detection latency: {detect}")
        if not shrink or not all(s > 0 for s in shrink):
            failures.append(f"no positive shrink wall-clock: {shrink}")
        summary["detect_s"] = detect
        summary["shrink_s"] = shrink
        for w in (0, 1, 2):
            s = validate_jsonl(os.path.join(run3, f"obs.w{w}.jsonl"))
            if s["errors"]:
                failures.append(f"worker {w} JSONL schema errors: "
                                f"{s['errors'][:3]}")

        # -- 5) one mesh-wide fleet timeline -----------------------------
        from ..obs import fleet

        shards = fleet.load_shards(run3)
        fsum = fleet.summarize(shards)
        summary["fleet"] = {
            "run_ids": fsum["run_ids"], "hosts": fsum["hosts"],
            "generations": fsum["generations"],
            "clock_offsets_s": fsum["clock_offsets_s"],
            "critical_path": fsum["critical_path"],
            "reconciliation": fsum["reconciliation"]}
        if len(fsum["run_ids"]) != 1:
            failures.append(f"shards disagree on the fleet run_id: "
                            f"{fsum['run_ids']}")
        if set(fsum["hosts"]) != {"coord", "w0", "w1", "w2"}:
            failures.append(f"fleet merge does not cover coordinator + "
                            f"all workers: {fsum['hosts']}")
        merged = fleet.merge(shards)
        ts_fleet = [r["ts_fleet"] for r in merged]
        if ts_fleet != sorted(ts_fleet):
            failures.append("merged timeline not monotone in ts_fleet")
        # crash-safe telemetry: the SIGKILLed worker's shard must still
        # hold its fold progress up to the last pre-kill flush
        if not any(r["_host"] == "w2" and r.get("type") == "elastic"
                   and r.get("event") == "window" for r in merged):
            failures.append("the victim's shard lost its flushed "
                            "window records")
        # the commit ledger's obs twin: every committed window exactly
        # once across hosts and generations, no gaps
        n_windows = epochs * (-(-n_shards // window))
        frc = fsum["reconciliation"]
        if not frc["ok"] or frc["windows"] != n_windows:
            failures.append(f"commit-ledger reconciliation broken "
                            f"(want {n_windows} windows): {frc}")
        cp = [p for p in fsum["critical_path"] if p["generation"] == 1]
        if not cp or not isinstance(cp[0]["total_s"], (int, float)) \
                or cp[0]["total_s"] <= 0:
            failures.append(f"no generation-1 shrink critical path: "
                            f"{fsum['critical_path']}")
        if not any(r.get("type") == "clock" and r["_host"] in
                   ("w0", "w1") for r in merged):
            failures.append("no survivor recorded a clock sample")
        # archive the merged, clock-aligned timeline before the scratch
        # dir goes away (CI keeps it as the run's fleet artifact)
        out_dir = (_knobs.get_raw("SQ_OOC_BENCH_ARTIFACT_DIR")
                   or tempfile.gettempdir())
        merged_path = os.path.join(out_dir, "elastic_fleet_merged.jsonl")
        fleet.write_merged(shards, merged_path)
        sm = validate_jsonl(merged_path)
        if sm["errors"]:
            failures.append(f"merged fleet timeline schema errors: "
                            f"{sm['errors'][:3]}")
        summary["merged"] = merged_path
    finally:
        shutil.rmtree(base, ignore_errors=True)

    summary["elastic_smoke"] = "fail" if failures else "ok"
    summary["errors"] = failures
    print(json.dumps(summary))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
