"""Data-parallel Lloyd iteration over a device mesh.

The reference parallelizes Lloyd with OpenMP threads and a GIL-guarded
partial-centroid reduction (``_k_means_lloyd.pyx:118-154``). Here the same
structure runs SPMD: X is sharded over the mesh's data axis, each device runs
the fused E/M kernel on its shard, and the partial centroid sums / counts /
inertia are combined with ``lax.psum`` over ICI inside ``shard_map``. The
entire while-loop executes on device; convergence is decided on the
(replicated) global center shift, so every device exits in lockstep.
"""

import functools

import jax
from jax.sharding import PartitionSpec as P

from .._compat import shard_map

from .mesh import DATA_AXIS, pad_to_multiple
from ..models.qkmeans import lloyd_single


@functools.lru_cache(maxsize=None)
def _sharded_lloyd(mesh, static):
    """Jitted shard_map'd Lloyd kernel, cached per (mesh, static-config) so
    repeated calls (n_init restarts, refits) hit one compile cache instead of
    retracing a fresh closure every call."""
    cfg = dict(static)
    # The pallas HLO *interpreter* (CPU tests of the TPU-pod configuration)
    # evaluates the kernel body as a jaxpr in which literals/iota are
    # vma-unvarying, so shard_map's varying-manual-axes checker rejects any
    # non-trivial kernel. Real-TPU lowering (mosaic) is unaffected — the
    # checker stays ON for every other combination.
    check_vma = not (cfg.get("use_pallas") and cfg.get("pallas_interpret"))
    run = functools.partial(lloyd_single, axis_name=DATA_AXIS, **cfg)
    return jax.jit(shard_map(
        run,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(), P(DATA_AXIS)),
        # labels follow the data sharding; inertia/centers/n_iter and the
        # per-iteration history traces are replicated (P() is a pytree
        # prefix covering the history dict's leaves)
        out_specs=(P(DATA_AXIS), P(), P(), P(), P()),
        check_vma=check_vma,
    ))


def lloyd_single_sharded(mesh, key, X, weights, centers_init, x_sq_norms,
                         **static):
    """Run :func:`~sq_learn_tpu.models.qkmeans.lloyd_single` under
    ``shard_map`` with axis-0 sharding of X / weights / x_sq_norms.

    Pads the sample axis to a device-count multiple (padded rows get weight
    0, so they contribute nothing to sums, counts, or inertia).

    Returns (labels, inertia, centers, n_iter, history) with labels trimmed
    back to the original length.
    """
    from .. import obs as _obs

    n_dev = mesh.devices.size
    with _obs.span("parallel.lloyd.single_sharded", n_devices=int(n_dev),
                   n_samples=int(X.shape[0]),
                   mode=static.get("mode")) as sp:
        X, n = pad_to_multiple(X, n_dev)
        weights, _ = pad_to_multiple(weights, n_dev)
        x_sq_norms, _ = pad_to_multiple(x_sq_norms, n_dev)

        cfg = tuple(sorted(static.items()))
        run = _sharded_lloyd(mesh, cfg)
        _obs.xla.capture("parallel.lloyd.single_sharded", run,
                         key, X, weights, centers_init, x_sq_norms,
                         _extra_key=cfg)
        labels, inertia, centers, n_iter, history = run(
            key, X, weights, centers_init, x_sq_norms
        )
        sp.sync(centers)
    return labels[:n], inertia, centers, n_iter, history
