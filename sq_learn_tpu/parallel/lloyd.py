"""Data-parallel Lloyd iteration over a device mesh.

The reference parallelizes Lloyd with OpenMP threads and a GIL-guarded
partial-centroid reduction (``_k_means_lloyd.pyx:118-154``). Here the same
structure runs SPMD: X is sharded over the mesh's data axis, each device runs
the fused E/M kernel on its shard, and the partial centroid sums / counts /
inertia are combined with ``lax.psum`` over ICI inside ``shard_map``. The
entire while-loop executes on device; convergence is decided on the
(replicated) global center shift, so every device exits in lockstep.
"""

import functools

import numpy as np
import jax
from jax.sharding import PartitionSpec as P
from jax import shard_map

from .mesh import DATA_AXIS
from ..models.qkmeans import lloyd_single


def lloyd_single_sharded(mesh, key, X, weights, centers_init, x_sq_norms,
                         **static):
    """Run :func:`~sq_learn_tpu.models.qkmeans.lloyd_single` under
    ``shard_map`` with axis-0 sharding of X / weights / x_sq_norms.

    Pads the sample axis to a device-count multiple (padded rows get weight
    0, so they contribute nothing to sums, counts, or inertia).

    Returns (labels, inertia, centers, n_iter) with labels trimmed back to
    the original length.
    """
    n_dev = mesh.devices.size
    n = int(X.shape[0])
    pad = (-n) % n_dev
    if pad:
        X = jax.numpy.pad(X, ((0, pad), (0, 0)))
        weights = jax.numpy.pad(weights, (0, pad))
        x_sq_norms = jax.numpy.pad(x_sq_norms, (0, pad))

    run = functools.partial(lloyd_single, axis_name=DATA_AXIS, **static)
    sharded = shard_map(
        run,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(), P(DATA_AXIS)),
        out_specs=(P(DATA_AXIS), P(), P(), P()),
    )
    labels, inertia, centers, n_iter = jax.jit(sharded)(
        key, X, weights, centers_init, x_sq_norms
    )
    return labels[:n], inertia, centers, n_iter
