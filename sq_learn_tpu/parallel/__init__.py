"""SPMD parallelism: device meshes, shardings, and the sharded Lloyd kernel.

(The reference has no distributed backend — its collectives are OpenMP
thread reductions; SURVEY §2.3 maps them to psum over an ICI mesh.)
"""

from . import distributed, streaming
from .neighbors import knn_indices_sharded
from .pca import (centered_svd_sharded, tomography_sharded,
                  uncentered_svd_sharded)
from .mesh import (
    DATA_AXIS,
    data_sharding,
    make_mesh,
    pad_to_multiple,
    replicated,
    shard_rows,
)

__all__ = [
    "DATA_AXIS",
    "centered_svd_sharded",
    "data_sharding",
    "distributed",
    "knn_indices_sharded",
    "make_mesh",
    "pad_to_multiple",
    "replicated",
    "shard_rows",
    "streaming",
    "tomography_sharded",
    "uncentered_svd_sharded",
]
