"""Data-parallel tall-skinny SVD for qPCA over a device mesh.

SURVEY §2.3 names the strategy: shard the sample axis, reduce an m×m Gram
matrix over ICI, keep the small eigendecomposition replicated. There is no
hand-written collective here — the inputs carry ``NamedSharding``
annotations and XLA inserts the psum for the sharded-contraction
``Xcᵀ·Xc`` itself (the sharding/collective recipe the scaling playbook
prescribes). The left factor U = Xc·V/σ stays row-sharded; hosts fetch
only the slices they need (see ``qpca._fit_full``).

The reference has no distributed PCA at all (its ``_qPCA.py:578-583`` is a
single-process LAPACK call); this is the TPU-native scaling path for
matrices whose sample axis exceeds one chip's HBM.
"""

import functools

import jax
import jax.numpy as jnp

from .. import obs as _obs
from ..ops.linalg import gram_spectrum, svd_flip_v
from .mesh import pad_and_shard as _pad_and_shard


@functools.partial(jax.jit, static_argnames=("n", "center"))
def _masked_gram_svd(X, w, n, center):
    """Gram-route thin SVD of the masked rows of X, optionally centered.

    ``w`` zeroes padding rows so they contribute to neither the mean nor
    the Gram matrix; ``n`` is the true row count. Shardings propagate from
    the operands: with X/w row-sharded, the row-sums and the Gram
    contraction lower to per-shard partials + an ICI all-reduce.
    ``center=False`` is the LSA/TruncatedSVD contract — the reference
    factors the raw matrix (``decomposition/_truncated_svd.py:170-182``,
    svds/randomized_svd on X itself, no mean subtraction).
    """
    if center:
        wX = X * w[:, None]
        mean = jnp.sum(wX, axis=0) / n
    else:
        mean = jnp.zeros((X.shape[1],), X.dtype)
    Xc = (X - mean) * w[:, None]
    G = Xc.T @ Xc  # (m, m) — per-shard GEMM + psum
    S, V, safe = gram_spectrum(G)  # replicated
    # thin spectrum: the feature Gram has m eigenvalues but only
    # min(n, m) can be nonzero; slice so the factors match the
    # single-device thin SVD's shapes (n and m are static here)
    r = min(n, X.shape[1])
    S, V, safe = S[:r], V[:, :r], safe[:r]
    # V-based signs: the shared convention (ops.linalg.svd_flip_v) — a
    # U-based flip would also gather argmax over the sharded factor
    _, Vt = svd_flip_v(None, V.T)
    U = (Xc @ Vt.T) / safe[None, :]  # row-sharded
    return mean, U, S, Vt


def centered_svd_sharded(mesh, X):
    """Column-center X and return (mean, U, S, Vt) with deterministic
    signs, computed data-parallel over ``mesh``'s first axis.

    Matches :func:`~sq_learn_tpu.ops.linalg.centered_svd` (method='gram')
    on the same input; U's rows are returned for the unpadded samples only,
    still sharded over the mesh.
    """
    with _obs.span("parallel.pca.centered_svd_sharded",
                   n_devices=int(mesh.devices.size)) as sp:
        Xp, mask, n = _pad_and_shard(mesh, X)
        _obs.xla.capture("parallel.pca.masked_gram_svd", _masked_gram_svd,
                         Xp, mask, n, center=True)
        mean, U, S, Vt = _masked_gram_svd(Xp, mask, n, center=True)
        sp.sync(S)
    return mean, U[:n], S, Vt


def uncentered_svd_sharded(mesh, X):
    """Thin SVD of X without centering, data-parallel over ``mesh``'s
    first axis — the sharded engine behind ``TruncatedSVD(mesh=...)``
    (reference contract: ``decomposition/_truncated_svd.py:170-182``
    factors the raw uncentered matrix). Matches the single-device exact
    path (``thin_svd`` + ``svd_flip_v``) on the same input up to the
    Gram route's conditioning (see the TruncatedSVD docstring); U's rows
    are returned for the unpadded samples only, still sharded over the
    mesh."""
    with _obs.span("parallel.pca.uncentered_svd_sharded",
                   n_devices=int(mesh.devices.size)) as sp:
        Xp, mask, n = _pad_and_shard(mesh, X)
        _obs.xla.capture("parallel.pca.masked_gram_svd", _masked_gram_svd,
                         Xp, mask, n, center=False)
        _, U, S, Vt = _masked_gram_svd(Xp, mask, n, center=False)
        sp.sync(S)
    return U[:n], S, Vt


@functools.partial(jax.jit, static_argnames=("noise", "true_tomography",
                                              "N", "norm"))
def _tomography_rows(key, Ap, mask, noise, true_tomography, N, norm):
    from ..ops.quantum.tomography import tomography

    # padding guard: a zero row would push 0/0 through the per-row state
    # normalization inside the tomography sampler; give padding rows a
    # unit basis vector and mask the estimates back to zero afterwards
    e0 = jnp.zeros((Ap.shape[1],), Ap.dtype).at[0].set(1.0)
    safe = Ap + (1.0 - mask)[:, None] * e0
    est = tomography(key, safe, noise, true_tomography=true_tomography,
                     N=N, norm=norm)
    return est * mask[:, None]


def tomography_sharded(mesh, key, A, noise, true_tomography=True, norm="L2"):
    """Row-sharded tomography of a matrix over the mesh's first axis —
    the quantum-transform side of qPCA at pod scale (reference
    ``qPCA.transform`` → ``compute_quantum_representation``,
    ``_qPCA.py:773-880``): each device draws the tomography estimates of
    its own row shard (vmapped exact sampler, or the truncated-Gaussian
    fast path), so the projected matrix is never gathered onto one
    device. Statistically identical to
    :func:`~sq_learn_tpu.ops.quantum.tomography` on the whole matrix; on
    a 1-device mesh with no padding it is bit-identical to the XLA path
    under the same key.
    """
    from ..ops.quantum.tomography import tomography_n_measurements

    A = jnp.asarray(A)
    if float(noise) == 0.0:
        return A
    Ap, mask, n = _pad_and_shard(mesh, A)
    # N is static host-side arithmetic (d, δ only): resolving it here
    # keeps the jitted body free of shape-dependent python control flow
    N = (tomography_n_measurements(A.shape[1], noise, norm)
         if true_tomography else None)
    out = _tomography_rows(key, Ap, mask, float(noise), true_tomography,
                           N, norm)
    return out[:n]


def centered_sharded(mesh, X, mean):
    """Row-sharded centered copy of X with padding rows exactly zero.

    For reductions that must see the centered matrix (e.g. the μ(A) norm
    grid) without ever replicating it onto one device: zero rows contribute
    nothing to μ's power sums or the Frobenius norm, so downstream jnp
    reductions over this array equal those over the unpadded centered X.
    """
    Xp, mask, _ = _pad_and_shard(mesh, X)
    return (Xp - jnp.asarray(mean)) * mask[:, None]
