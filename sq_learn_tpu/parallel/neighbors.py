"""Train-sharded brute-force k-NN search over a device mesh.

The reference's neighbors module scales through ball/KD trees on one
host (``neighbors/_ball_tree.pyx``, ``_kd_tree.pyx``) — pointer-chasing
structures that neither shard nor vectorize. The TPU-native scaling path
(SURVEY §2.2 "neighbors" row + §2.3's OpenMP→mesh mapping) shards the
TRAINING rows over the mesh's data axis: each device GEMMs query blocks
against its shard on the MXU, keeps a local k-best, and only the
per-shard candidate lists — (n_q, k) distances + global row ids — cross
ICI to be merged into the global top-k. Queries are blocked with
``lax.map`` exactly like the single-device search, so neither the
(n_q, n_train) nor an (n_q, per_shard) distance matrix ever
materializes; the training corpus never leaves its shards. Queries are
replicated, which is the regime these pipelines actually run (a CV fold
of queries against a large fitted corpus).

Exact-precision only: the ``compute_dtype`` shortlist trick and the
single-device pallas argkmin stay on the unsharded path
(``models/neighbors.py``) — per-shard pallas under ``shard_map`` is the
natural extension once Mosaic-validated on hardware.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .._compat import shard_map

from ..ops.linalg import pairwise_sq_distances
from .mesh import DATA_AXIS, pad_and_shard

#: additive distance penalty that pushes padding rows past every real
#: candidate without overflowing float32 arithmetic in the merge
_PAD_PENALTY = 1e30


def shard_train_rows(mesh, X_train):
    """Pad the training rows to a device-count multiple and place them
    (plus the padding mask) sharded over the mesh — the one corpus-sized
    transfer of a sharded search. Returns an opaque ``(Xp, mask, per,
    n)`` state for :func:`knn_indices_sharded`'s ``presharded=``;
    callers with a fitted corpus (``KNeighborsClassifier(mesh=...)``)
    cache it at fit so repeated predicts never re-ship the corpus."""
    Xp, mask, n = pad_and_shard(mesh, X_train)
    per = Xp.shape[0] // int(mesh.devices.size)
    return Xp, mask, per, n


@functools.lru_cache(maxsize=64)
def _sharded_candidates(mesh, k_local, per_shard, block):
    """Jitted shard_map'd local search, cached per (mesh, k_local, shard
    size, query block) like the sharded Lloyd kernel — restarts and
    repeated predicts reuse one compilation.

    The cache is bounded (it holds Mesh references, which pin device
    buffers for process lifetime) and small-query block sizes are
    quantized to power-of-two buckets at the call site, so a stream of
    odd-sized tiny predicts maps to a handful of entries instead of one
    per distinct size."""

    def search(X_local, mask_local, Q, qsq):
        def one_block(args):
            q, qs = args
            d2 = pairwise_sq_distances(q, X_local, x_sq_norms=qs) \
                + (1.0 - mask_local)[None, :] * _PAD_PENALTY
            neg, idx = lax.top_k(-d2, k_local)
            return -neg, idx

        qb = Q.reshape(-1, block, Q.shape[1])
        sb = qsq.reshape(-1, block)
        d2k, idxk = lax.map(one_block, (qb, sb))
        d2k = d2k.reshape(-1, k_local)
        idxk = idxk.reshape(-1, k_local)
        # local row ids -> global: every shard holds exactly per_shard rows
        gidx = idxk + lax.axis_index(DATA_AXIS) * per_shard
        return d2k, gidx

    return jax.jit(shard_map(
        search, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(), P()),
        # candidate lists concatenate along the candidate axis: the merge
        # sees (n_q, n_dev * k_local) — the only cross-device traffic
        out_specs=(P(None, DATA_AXIS), P(None, DATA_AXIS)),
    ))


def knn_indices_sharded(mesh, X_train, X_query, k, presharded=None,
                        block=4096):
    """Indices + squared distances of the k nearest training rows per
    query, computed with the training rows sharded over ``mesh``.

    Matches :func:`~sq_learn_tpu.models.neighbors.knn_indices` (exact
    path) on the same input up to tie order — ties across shard
    boundaries merge in shard order rather than global index order, the
    same freedom sklearn's trees (and our host engines) already have.
    The caller guarantees ``k <= n_train`` (the classifier's
    ``_check_k`` contract). Pass ``presharded`` from
    :func:`shard_train_rows` to skip the per-call corpus placement.
    """
    from .. import obs as _obs

    if presharded is None:
        presharded = shard_train_rows(mesh, X_train)
    Xp, mask, per, n = presharded
    X_query = jnp.asarray(X_query)
    nq = X_query.shape[0]
    with _obs.span("parallel.neighbors.knn_indices_sharded",
                   n_devices=int(mesh.devices.size), n_queries=int(nq),
                   k=int(k)) as sp:
        # a shard can contribute at most `per` candidates; with k <= n the
        # union of shards always holds k real rows
        k_local = min(k, per)
        # query blocking, same discipline as the single-device knn_indices:
        # tiny predicts don't pay a full 4096-row GEMM, huge ones never
        # materialize (n_q, per_shard). Small sizes quantize to power-of-two
        # buckets (min 8 = one lane group) so the compile cache above sees a
        # handful of block shapes, not one per distinct query count.
        if nq < block:
            bucket = 8
            while bucket < nq:
                bucket <<= 1
            block = min(block, bucket)
        qpad = (-nq) % block
        Qp = jnp.pad(X_query, ((0, qpad), (0, 0)))
        qsq = jnp.sum(Qp * Qp, axis=1)
        candidates = _sharded_candidates(mesh, k_local, per, block)
        _obs.xla.capture("parallel.neighbors.sharded_candidates",
                         candidates, Xp, mask, Qp, qsq,
                         _extra_key=(k_local, per, block))
        d2_cand, idx_cand = candidates(Xp, mask, Qp, qsq)
        # replicated merge over n_dev * k_local candidates per query
        neg, pos = lax.top_k(-d2_cand, k)
        idx = jnp.take_along_axis(idx_cand, pos, axis=1)
        sp.sync(idx)
    return idx[:nq], -neg[:nq]
