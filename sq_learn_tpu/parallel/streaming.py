"""Mesh variant of the streaming tiled-ingestion engine.

Same double-buffered tile walk as :mod:`sq_learn_tpu.streaming`, but each
tile lands **sharded** over the mesh's data axis (one bounded
``device_put`` fans the tile's rows across the devices) and the Gram /
column-sum accumulators are replicated: the per-shard partial Grams reduce
over ICI inside the jitted accumulation step — XLA inserts the ``psum``
for the sharded contraction itself, exactly as the resident-matrix path in
:mod:`~sq_learn_tpu.parallel.pca` does. The full sample axis therefore
never exists on any single device NOR in aggregate: per tile, each device
holds ``tile_rows / n_dev`` rows, and between tiles only the (m, m)
accumulator survives.

Tile buckets are rounded to device-count multiples (SPMD needs equal
shards); zero-padded rows contribute nothing to the sums.

The resilience machinery rides along unchanged from the single-device
engine: each sharded ``device_put`` runs under the transfer supervisor
(retry/backoff/deadline/breaker), and the Gram pass is resumable via
``SQ_STREAM_CKPT_DIR``/``checkpoint=`` — the replicated accumulators
snapshot to host npz and re-place **replicated** on resume
(:func:`~sq_learn_tpu.streaming.stream_fold` restores each leaf with its
init counterpart's sharding).
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp

from .. import obs as _obs
from ..streaming import (_gram_colsum_step, _finalize_centered_gram,
                         stream_fold, stream_map_rows)
from .mesh import data_sharding, replicated

__all__ = [
    "streamed_centered_gram_sharded",
    "streamed_centered_svd_topk_sharded",
]


def _sharded_put(mesh):
    """Placement callable handed to the tiler: one ``jax.device_put`` per
    tile, row-sharded over the mesh — the bounded transfer that replaces
    the monolithic corpus placement."""
    sharding = data_sharding(mesh)

    def put(tile):
        return jax.device_put(tile, sharding)

    return put


def streamed_centered_gram_sharded(mesh, X, *, max_bytes=None,
                                   checkpoint=None):
    """(mean, G_centered, n) with every tile landing sharded over the
    mesh and the partial Grams psum-reduced over ICI.

    The replicated (m, m)/(m,) accumulators ride through the same donated
    kernel as the single-device engine; with the tile row-sharded, XLA
    lowers ``tileᵀ·tile`` to per-shard partials + an all-reduce.
    ``checkpoint`` (or ``SQ_STREAM_CKPT_DIR``) makes the pass resumable;
    the snapshot holds the psum-reduced accumulator, so resume re-places
    it replicated and continues mid-sweep.
    """
    X = np.asarray(X)
    n, m = X.shape
    dtype = jax.dtypes.canonicalize_dtype(X.dtype)
    rep = replicated(mesh)
    init = (jax.device_put(jnp.zeros((m, m), dtype), rep),
            jax.device_put(jnp.zeros((m,), dtype), rep))
    with _obs.span("parallel.streaming.centered_gram", n=n, m=m,
                   n_devices=int(mesh.devices.size)):
        G, colsum = stream_fold(
            X, _gram_colsum_step, init, max_bytes=max_bytes,
            put=_sharded_put(mesh), multiple=int(mesh.devices.size),
            site="streaming.gram_colsum", checkpoint=checkpoint)
        mean, Gc = _finalize_centered_gram(G, colsum, n)
    return mean, Gc, n


@jax.jit
def _tile_topk_u(tile, mean, Vk_over_s):
    """Per-tile partial-U rows (tile − mean)·(Vₖᵀ/σ). The tile arrives
    sharded; the (m, k) projector is replicated, so the GEMM runs
    shard-local with no collective. Zero-padded tail rows produce
    −mean·proj garbage, which the caller slices away per tile."""
    return (tile - mean) @ Vk_over_s


def streamed_centered_svd_topk_sharded(mesh, X, n_left, *, max_bytes=None):
    """Streamed mesh twin of the qPCA partial-U Gram route: (mean, Uk, S,
    Vt) with the Gram built from sharded tiles (psum over ICI) and the
    (n, k) U block assembled host-side from per-tile shard-local GEMMs —
    X is never resident, on any device or in aggregate.

    Matches :func:`~sq_learn_tpu.parallel.pca.centered_svd_sharded` on
    the same input up to tile-summation order; ``Uk`` comes back as a
    host array (its k columns are what the fit publishes as ``left_sv``).
    """
    from ..ops.linalg import gram_spectrum, svd_flip_v

    X = np.asarray(X)
    n, m = X.shape
    mean, Gc, _ = streamed_centered_gram_sharded(mesh, X,
                                                 max_bytes=max_bytes)
    S, V, safe = gram_spectrum(Gc)
    _, Vt = svd_flip_v(None, V.T)
    k = int(n_left)
    Vk_over_s = (Vt[:k] / safe[:k, None]).T  # (m, k), replicated
    rep = replicated(mesh)
    mean_r = jax.device_put(mean, rep)
    proj_r = jax.device_put(Vk_over_s, rep)

    def tile_fn(tile):
        return _tile_topk_u(tile, mean_r, proj_r)

    # small per-tile (rows, k) outputs come back to the host
    with _obs.span("parallel.streaming.topk_u", n=n, k=k,
                   n_devices=int(mesh.devices.size)):
        Uk = stream_map_rows(X, tile_fn, max_bytes=max_bytes,
                             put=_sharded_put(mesh),
                             multiple=int(mesh.devices.size))
    if _obs.enabled():
        _obs.watchdog.track("parallel.streaming.tile_topk_u", _tile_topk_u)
        _obs.watchdog.observe("parallel.streaming.tile_topk_u")
    return mean, Uk, S, Vt
