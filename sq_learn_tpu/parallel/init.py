"""Sharded / batched k-means++ initialization kernels.

The reference inits q-means with host-looped greedy k-means++
(``_dmeans.py:153-245``); PR 6 makes initialization a first-class device
kernel family:

- :func:`kmeans_plusplus_batched` — all restarts' D²-sampling inits in ONE
  jit (vmapped over the restart axis), with an optional uniform row
  subsample (the sketch acceleration: on 70k×784 the full-data potential
  scans are ~90 % of init cost, while a 4-8k-row subsample loses <1 %
  final inertia — see ``bench/records`` PR 6 profile).
- :func:`kmeans_plusplus_sharded` — the same kernel under ``shard_map``
  with the sample axis sharded over a mesh and psum-combined potentials.

**Layout invariance.** Both kernels draw every candidate through the same
two-stage hierarchical sampler over a fixed grid of ``n_blocks`` row
blocks (stage 1: inverse-CDF over the per-block potential sums; stage 2:
inverse-CDF inside the owning block), and reduce every potential sum
block-wise before the fixed-order cross-block sum. Because the block grid
is anchored to GLOBAL row indices (never to the shard layout), the
per-block partials are computed from identical data in identical order on
any mesh shape — so a fixed PRNG key selects the SAME center indices on 1
device and on an 8-device mesh (pinned by test and by the driver's
multichip gate). A plain ``psum`` of per-shard float sums would not give
this: float reduction order would change with the layout.

Zero-weight rows (mesh padding, masked samples) carry zero potential and
are never selected — same contract as
:func:`~sq_learn_tpu.models.qkmeans.kmeans_plusplus`, whose host-loop
cumsum sampler these kernels replace on the batched/sharded fit paths.
"""

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import obs as _obs
from .._compat import axis_size, shard_map
from .mesh import DATA_AXIS, pad_to_multiple
from .. import _knobs

__all__ = [
    "NBLOCKS",
    "resolve_init_subsample",
    "kmeans_plusplus_batched",
    "kmeans_plusplus_sharded",
]

#: number of row blocks of the hierarchical sampler — the layout-invariance
#: anchor. Must be a multiple of the mesh device count (blocks never
#: straddle shards); 64 covers every mesh this repo builds.
NBLOCKS = 64


def resolve_init_subsample(n_samples, n_clusters, setting="auto"):
    """Row count of the uniform init subsample (0 = init on the full
    data). ``setting`` is the estimator's ``init_subsample`` hyperparam:
    'auto' targets ``max(128·k, 4096)`` rows (rounded up to a block
    multiple) and only engages when the data is ≥4× larger — small fits
    keep the exact full-data potentials, so the subsample never changes a
    digit-scale result. ``SQ_INIT_SUBSAMPLE`` overrides the 'auto' target
    (0 disables). Explicit integers are used as given (0/None disables).
    """
    if setting == "auto":
        env = _knobs.get_raw("SQ_INIT_SUBSAMPLE")
        if env is not None:
            setting = int(env)
    if setting == "auto":
        target = max(128 * int(n_clusters), 4096)
    elif not setting:
        return 0
    else:
        target = int(setting)
    target = -(-target // NBLOCKS) * NBLOCKS
    return target if n_samples > 4 * target else 0


def _default_trials(n_clusters):
    return 2 + int(math.log(n_clusters))


def _pad_rows(v, n_pad, fill=0.0):
    n = v.shape[0]
    if n == n_pad:
        return v
    return jnp.concatenate(
        [v, jnp.full((n_pad - n,) + v.shape[1:], fill, v.dtype)])


def _block_sums(v, n_blocks, axis_name):
    """(rows,) → (n_blocks,) global per-block sums, replicated. The
    per-block reduction runs over the block's own rows only, so its value
    is independent of how rows are sharded; the sharded gather uses the
    psum-slot trick (axis-invariant output keeps shard_map's
    varying-manual-axes check enabled)."""
    if axis_name is None:
        return v.reshape(n_blocks, -1).sum(axis=1)
    n_sh = axis_size(axis_name)
    local = v.reshape(n_blocks // n_sh, -1).sum(axis=1)
    buf = jnp.zeros((n_sh, local.shape[0]), v.dtype)
    buf = buf.at[lax.axis_index(axis_name)].set(local)
    return lax.psum(buf, axis_name).reshape(-1)


def _pot_total(vals, n_blocks, axis_name):
    """Layout-invariant Σ vals: block partials, then a fixed-order sum."""
    return jnp.sum(_block_sums(vals, n_blocks, axis_name))


def _draw_index(key, pot, n_blocks, axis_name):
    """One global categorical draw ∝ ``pot`` via the two-stage block
    sampler. Returns the global row index (int32). Rows with zero
    potential are never selected (the stage boundaries are strict)."""
    bsums = _block_sums(pot, n_blocks, axis_name)
    cum = jnp.cumsum(bsums)
    total = cum[-1]
    u = jax.random.uniform(key, (), pot.dtype)
    # strictly below the total so side='right' always lands inside a
    # positive-mass block (and inside a positive-potential row within it)
    t = jnp.minimum(u, jnp.asarray(0.999999, pot.dtype)) * total
    b = jnp.clip(jnp.searchsorted(cum, t, side="right"), 0, n_blocks - 1)
    prev = jnp.where(b > 0, cum[jnp.maximum(b - 1, 0)], 0.0)
    if axis_name is None:
        bs = pot.shape[0] // n_blocks
        block = lax.dynamic_slice(pot, (b * bs,), (bs,))
        off = jnp.clip(
            jnp.searchsorted(jnp.cumsum(block), t - prev, side="right"),
            0, bs - 1)
        return (b * bs + off).astype(jnp.int32)
    n_sh = axis_size(axis_name)
    blocks_local = n_blocks // n_sh
    bs = pot.shape[0] // blocks_local
    sh = lax.axis_index(axis_name)
    owner = b // blocks_local
    b_loc = jnp.where(owner == sh, b - owner * blocks_local, 0)
    block = lax.dynamic_slice(pot, (b_loc * bs,), (bs,))
    off = jnp.clip(
        jnp.searchsorted(jnp.cumsum(block), t - prev, side="right"),
        0, bs - 1)
    idx = jnp.where(owner == sh, b * bs + off, 0)
    return lax.psum(idx, axis_name).astype(jnp.int32)


def _take_row(X, idx, axis_name):
    """Gather one global row of the (possibly sharded) sample axis."""
    if axis_name is None:
        return X[idx]
    rows_local = X.shape[0]
    sh = lax.axis_index(axis_name)
    local = idx - sh * rows_local
    inside = jnp.logical_and(local >= 0, local < rows_local)
    row = jnp.where(inside, X[jnp.clip(local, 0, rows_local - 1)], 0.0)
    return lax.psum(row, axis_name)


def _kpp_run(key, X, x_sq, weights, *, n_clusters, n_local_trials,
             n_blocks=NBLOCKS, axis_name=None):
    """One greedy best-of-trials D²-sampling init (the layout-invariant
    core). ``X`` is the local shard (or the whole matrix); the (rows,)
    potential vectors are padded to a block multiple internally —
    zero-weight padding carries zero potential throughout.

    Returns (centers (k, m), global indices (k,)).
    """
    n, m = X.shape
    if axis_name is None:
        bs = -(-n // n_blocks)
        n_pad = bs * n_blocks
    else:
        n_pad = n  # the sharded wrapper pre-pads to a block multiple
    w_pad = _pad_rows(weights, n_pad)

    key, k0 = jax.random.split(key)
    first = _draw_index(k0, w_pad, n_blocks, axis_name)
    c0 = _take_row(X, first, axis_name)
    d0 = jnp.maximum(
        x_sq + jnp.sum(c0 * c0) - 2.0 * (X @ c0), 0.0)
    closest = _pad_rows(d0, n_pad)
    centers = jnp.zeros((n_clusters, m), X.dtype).at[0].set(c0)
    indices = jnp.full((n_clusters,), -1, jnp.int32).at[0].set(first)

    def body(c, carry):
        centers, indices, closest = carry
        kc = jax.random.fold_in(key, c)
        pot = closest * w_pad
        # greedy best-of-trials: each trial is one independent block-
        # sampler draw; the trial GEMM batches all candidates in one pass
        cand_idx = jnp.stack([
            _draw_index(jax.random.fold_in(kc, t), pot, n_blocks, axis_name)
            for t in range(n_local_trials)])
        cand_rows = jnp.stack([
            _take_row(X, cand_idx[t], axis_name)
            for t in range(n_local_trials)])
        c_sq = jnp.sum(cand_rows * cand_rows, axis=1)
        d2 = jnp.maximum(
            x_sq[None, :] + c_sq[:, None] - 2.0 * (cand_rows @ X.T), 0.0)
        new_closest = jnp.minimum(
            closest[None, :],
            jnp.stack([_pad_rows(d2[t], n_pad)
                       for t in range(n_local_trials)]))
        pots = jnp.stack([
            _pot_total(new_closest[t] * w_pad, n_blocks, axis_name)
            for t in range(n_local_trials)])
        best = jnp.argmin(pots)
        closest = new_closest[best]
        centers = centers.at[c].set(cand_rows[best])
        indices = indices.at[c].set(cand_idx[best])
        return centers, indices, closest

    centers, indices, _ = lax.fori_loop(
        1, n_clusters, body, (centers, indices, closest))
    return centers, indices


@functools.partial(
    jax.jit,
    static_argnames=("n_clusters", "n_restarts", "n_local_trials",
                     "subsample"))
def _kpp_batched_jit(key, X, x_sq_norms, weights, *, n_clusters,
                     n_restarts, n_local_trials, subsample):
    n = X.shape[0]
    if subsample and subsample < n:
        key, ks = jax.random.split(key)
        sub = jax.random.choice(ks, n, (subsample,), replace=False)
        Xs, xs, ws = X[sub], x_sq_norms[sub], weights[sub]
    else:
        sub = None
        Xs, xs, ws = X, x_sq_norms, weights
    keys = jax.random.split(key, n_restarts)
    centers, indices = jax.vmap(
        lambda k: _kpp_run(k, Xs, xs, ws, n_clusters=n_clusters,
                           n_local_trials=n_local_trials))(keys)
    if sub is not None:
        indices = sub[indices].astype(jnp.int32)
    return centers, indices


def kmeans_plusplus_batched(key, X, x_sq_norms=None, n_clusters=8, *,
                            n_restarts=1, weights=None, n_local_trials=None,
                            subsample=0):
    """All ``n_restarts`` k-means++ inits as ONE dispatch (vmapped
    restarts). ``subsample`` > 0 draws that many rows uniformly (one
    shared draw, weights preserved) and runs the D² potentials on them —
    the sketch-accelerated init. Returns (centers (R, k, m), indices
    (R, k) into the ORIGINAL rows).

    Traceable: safe to call from inside an enclosing jit (the fused fit
    does); the public eager call registers the obs watchdog site
    ``parallel.init.kmeans_plusplus`` with a ≤1-compile-per-signature
    budget.
    """
    X = jnp.asarray(X)
    if x_sq_norms is None:
        x_sq_norms = jnp.sum(X * X, axis=1)
    if weights is None:
        weights = jnp.ones((X.shape[0],), X.dtype)
    if n_local_trials is None:
        n_local_trials = _default_trials(n_clusters)
    # watchdog accounting only on eager (host-driven) calls — when traced
    # inside an enclosing jit (the fused fit), the outer site accounts
    traced = isinstance(X, jax.core.Tracer)
    if _obs.enabled() and not traced:
        site = "parallel.init.kmeans_plusplus"
        _obs.watchdog.track(site, _kpp_batched_jit)
        _obs.watchdog.allow(site, (X.shape, str(X.dtype), int(n_clusters),
                                   int(n_restarts), int(subsample)))
    out = _kpp_batched_jit(key, X, x_sq_norms, weights,
                           n_clusters=int(n_clusters),
                           n_restarts=int(n_restarts),
                           n_local_trials=int(n_local_trials),
                           subsample=int(subsample))
    if _obs.enabled() and not traced:
        _obs.watchdog.observe("parallel.init.kmeans_plusplus")
    return out


@functools.lru_cache(maxsize=None)
def _sharded_kpp(mesh, n_clusters, n_local_trials):
    run = functools.partial(_kpp_run, n_clusters=n_clusters,
                            n_local_trials=n_local_trials,
                            axis_name=DATA_AXIS)

    def one_restart(key, X, x_sq, weights):
        # same key layout as the batched kernel's n_restarts=1 split, so
        # the two entry points are interchangeable restart-for-restart
        return run(jax.random.split(key, 1)[0], X, x_sq, weights)

    return jax.jit(shard_map(
        one_restart,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P()),
    ))


def kmeans_plusplus_sharded(mesh, key, X, x_sq_norms=None, n_clusters=8, *,
                            weights=None, n_local_trials=None):
    """One k-means++ init under ``shard_map`` with the sample axis sharded
    over ``mesh`` — every potential reduction and candidate draw runs
    through the layout-invariant block sampler, so the selected indices
    (and therefore the centers, which are exact data rows) are IDENTICAL
    to ``kmeans_plusplus_batched(key, ..., n_restarts=1)`` on one device
    with the same key. Zero-weight padding rows are never selected.

    Returns (centers (k, m), indices (k,)).
    """
    n_dev = int(mesh.devices.size)
    if NBLOCKS % n_dev:
        raise ValueError(
            f"mesh of {n_dev} devices does not divide the {NBLOCKS}-block "
            f"sampling grid")
    X = jnp.asarray(X)
    if x_sq_norms is None:
        x_sq_norms = jnp.sum(X * X, axis=1)
    if weights is None:
        weights = jnp.ones((X.shape[0],), X.dtype)
    if n_local_trials is None:
        n_local_trials = _default_trials(n_clusters)
    with _obs.span("parallel.init.kmeans_plusplus_sharded",
                   n_devices=n_dev, n_samples=int(X.shape[0]),
                   n_clusters=int(n_clusters)) as sp:
        Xp, _ = pad_to_multiple(X, NBLOCKS)
        xsq_p, _ = pad_to_multiple(x_sq_norms, NBLOCKS)
        w_p, _ = pad_to_multiple(weights, NBLOCKS)
        run = _sharded_kpp(mesh, int(n_clusters), int(n_local_trials))
        if _obs.enabled():
            site = "parallel.init.kmeans_plusplus_sharded"
            _obs.watchdog.track(site, run)
            _obs.watchdog.allow(site, (Xp.shape, str(Xp.dtype),
                                       int(n_clusters)))
        centers, indices = run(key, Xp, xsq_p, w_p)
        sp.sync(centers)
    if _obs.enabled():
        _obs.watchdog.observe("parallel.init.kmeans_plusplus_sharded")
    return centers, indices


def host_subsample_indices(rng, n_samples, target):
    """Host twin of the in-jit subsample draw (the native engines share
    the same uniform-without-replacement semantics; streams are
    engine-local, as everywhere else)."""
    if not target or target >= n_samples:
        return None
    return np.sort(rng.choice(n_samples, target, replace=False))
