"""Multi-host execution plumbing.

The reference is single-process (SURVEY §2.3: OpenMP threads +
multiprocessing, no NCCL/MPI/Gloo anywhere); its scale-out story is ours to
define. The design (docs/design.md): one SPMD program, data axis sharded
over *all* global devices, XLA collectives riding ICI within a host and DCN
across hosts. This module is the thin host-boundary layer — everything else
(the shard_map kernels) is topology-agnostic.

Typical multi-host launch (same script on every host)::

    from sq_learn_tpu.parallel import distributed as dist

    dist.initialize()               # env-driven (TPU pods auto-detect)
    mesh = dist.global_mesh()       # all devices across all hosts
    est = QKMeans(n_clusters=10, mesh=mesh, ...).fit(local_shard)
"""


import numpy as np
import jax

from .mesh import DATA_AXIS
from .. import _knobs


def initialize(coordinator_address=None, num_processes=None, process_id=None,
               **kwargs):
    """Initialize :mod:`jax.distributed` for multi-host execution.

    On TPU pods every argument auto-detects from the environment; on other
    platforms pass the coordinator host:port and process indices. Safe to
    call once per process, before any backend use. No-op if the runtime is
    already initialized (re-initialization raises in JAX; this wrapper
    makes idempotent use possible in launcher scripts).

    Multi-process runs on the **CPU backend** (the hardware-free DCN
    rehearsal, ``tests/test_distributed_multiprocess.py``) additionally
    need an explicit CPU collectives implementation: without one the CPU
    client executes the first cross-process computation into
    ``INVALID_ARGUMENT: Multiprocess computations aren't implemented on
    the CPU backend``. jaxlib ships gloo TCP collectives, so when this
    initialize is a multi-process one we select
    ``jax_cpu_collectives_implementation=gloo`` before the backend client
    exists (the config is read at CPU client creation; it is inert for
    TPU/GPU backends and for single-process runs we leave it alone).
    """
    n_proc = num_processes
    if n_proc is None:
        try:
            n_proc = _knobs.get_int("JAX_NUM_PROCESSES")
        except ValueError:
            n_proc = 0
    if n_proc and int(n_proc) > 1:
        _select_cpu_collectives("gloo")
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id, **kwargs)
    except RuntimeError as exc:
        # jax raises "distributed.initialize should only be called once."
        # (wording has varied across versions — match both forms)
        msg = str(exc)
        if "only be called once" not in msg and "already initialized" not in msg:
            raise


def _select_cpu_collectives(impl):
    """Select the CPU backend's cross-process collectives implementation
    (no-op when already selected, when the option is unknown to this jax,
    or when the backend client already exists — the flag is read once at
    CPU client creation). On jax 0.4.x the option is a ``Flag`` (no
    ``jax.config.update`` surface), so this falls back to the flag's
    ``_set`` — the same mechanism the ``JAX_CPU_COLLECTIVES_IMPLEMENTATION``
    env var uses, just late enough to work after import."""
    try:
        if jax.config.jax_cpu_collectives_implementation != "none":
            return
        jax.config.update("jax_cpu_collectives_implementation", impl)
        return
    except AttributeError:
        pass
    try:
        from jax._src import xla_bridge as _xb

        flag = _xb.CPU_COLLECTIVES_IMPLEMENTATION
        if flag.value == "none":
            flag._set(impl)
    except Exception:
        pass  # older/newer jax without the option: nothing to select


def global_mesh(axis_name=DATA_AXIS):
    """1-D mesh over every device across every participating host."""
    from .mesh import make_mesh

    return make_mesh(axis_name=axis_name)


def process_info():
    """(process_index, process_count, local_device_count) of this host."""
    return (jax.process_index(), jax.process_count(),
            jax.local_device_count())


def host_shard_bounds(n_rows):
    """(lo, hi, per): row range of the global dataset this host loads, and
    the uniform per-host shard size.

    The standard multi-host input pattern: each host reads rows [lo, hi)
    from storage and pads its slice up to ``per`` rows with zero-weight
    padding (``mesh.pad_to_multiple``) — JAX requires equal per-process
    shard shapes on the data axis, so tail hosts MUST pad, not just load
    fewer rows. With the zero weights the padded rows contribute nothing
    to any reduction.

    ``per`` is additionally rounded up to a multiple of this host's local
    device count so the resulting global axis (process_count · per) tiles
    evenly over every device of the global mesh (device counts are uniform
    across hosts on any sane deployment; a ``NamedSharding`` over the data
    axis requires exact divisibility).
    """
    p, np_, local = process_info()
    per = -(-n_rows // np_)
    per = -(-per // local) * local
    lo = min(p * per, n_rows)
    return lo, min(lo + per, n_rows), per
