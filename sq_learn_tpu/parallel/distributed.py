"""Multi-host execution plumbing.

The reference is single-process (SURVEY §2.3: OpenMP threads +
multiprocessing, no NCCL/MPI/Gloo anywhere); its scale-out story is ours to
define. The design (docs/design.md): one SPMD program, data axis sharded
over *all* global devices, XLA collectives riding ICI within a host and DCN
across hosts. This module is the thin host-boundary layer — everything else
(the shard_map kernels) is topology-agnostic.

Typical multi-host launch (same script on every host)::

    from sq_learn_tpu.parallel import distributed as dist

    dist.initialize()               # env-driven (TPU pods auto-detect)
    mesh = dist.global_mesh()       # all devices across all hosts
    est = QKMeans(n_clusters=10, mesh=mesh, ...).fit(local_shard)
"""


import numpy as np
import jax

from .mesh import DATA_AXIS
from .. import _knobs

#: this process's live world, if any. ``generation`` is the monotonic
#: elastic-mesh epoch (bumped on every shrink); ``client``/``service``
#: are only populated on the raw elastic path — the native
#: ``jax.distributed`` path leaves them None and records the generation
#: so re-init discipline is uniform across both paths.
_WORLD = {"generation": None, "elastic": False, "client": None,
          "service": None, "num_processes": None, "process_id": None,
          "address": None}

#: XLA's own missed-heartbeat machinery is deliberately parked far out of
#: the way (interval x tolerance ~ 3 h): the coordination service must
#: never declare a host dead on its own — a Python missed-heartbeat
#: callback is invoked off-thread by XLA and dies in std::bad_cast
#: (observed std::terminate), and the default callback QFATALs the
#: survivors. Failure detection belongs to the lease layer in
#: :mod:`sq_learn_tpu.parallel.elastic`, which owns the timeline.
_HEARTBEAT_S = 10
_MAX_MISSED_HEARTBEATS = 1000


#: raw clients retired by :func:`shutdown` / a refused handshake — kept
#: alive FOREVER, on purpose (see :func:`_retire_client`).
_CLIENT_GRAVEYARD = []


def _retire_client(client):
    """Park a retired raw client instead of ever destroying it.

    A client whose peer vanished WITHOUT disconnecting (SIGKILL, or
    ``os._exit`` after a generation-mismatch refusal) blocks its C++
    destructor on the coordination service *indefinitely* — the
    service never evicts the ghost peer (heartbeat detection is parked,
    above), so whichever thread drops the last reference hangs, not
    cleans up (observed: the mismatch-refusal survivor wedged in
    ``del client`` for minutes). Holding the reference here means the
    destructor simply never runs: the leak is deliberate and bounded
    (one client per world generation, generations are bounded by the
    shrink budget), the parked heartbeat loop fails quietly for ~3 h
    before XLA's machinery would care, and worker processes exit via
    ``os._exit`` so no leaked destructor ever races interpreter
    teardown."""
    if client is not None:
        _CLIENT_GRAVEYARD.append(client)


class GenerationMismatchError(RuntimeError):
    """A worker tried to join a world whose agreed generation differs
    from its own — the stale-worker shape that would otherwise present
    as a silent gloo hang at the first collective."""


def _xla_extension():
    try:
        from jax._src.lib import xla_extension as xe
    except ImportError:  # pragma: no cover - jaxlib layout drift
        from jaxlib import xla_extension as xe
    return xe


def start_coordinator_service(address, num_processes):
    """Start the distributed KV/coordination service in THIS process and
    return its handle (keep it referenced for the life of the world; let
    it be garbage-collected only after every client is gone — destroying
    it under live client poll threads QFATALs them).

    The elastic coordinator (:class:`sq_learn_tpu.parallel.elastic.
    ElasticCoordinator`) hosts one service per generation in the parent
    process — OUTSIDE the mesh — so any worker, including node 0, may
    die without taking the control plane with it."""
    xe = _xla_extension()
    return xe.get_distributed_runtime_service(
        address, num_nodes=int(num_processes),
        heartbeat_interval=_HEARTBEAT_S,
        max_missing_heartbeats=_MAX_MISSED_HEARTBEATS)


def initialize(coordinator_address=None, num_processes=None, process_id=None,
               *, generation=None, elastic=False, **kwargs):
    """Initialize :mod:`jax.distributed` for multi-host execution.

    On TPU pods every argument auto-detects from the environment; on other
    platforms pass the coordinator host:port and process indices. Safe to
    call once per process, before any backend use. Re-calling with the
    SAME ``generation`` (or with no generation at all — the legacy
    launcher-script contract) is an idempotent no-op; re-calling with a
    DIFFERENT generation while a world is live raises — call
    :func:`shutdown` first. That replaces the old wrapper's silent
    swallow of "already initialized", which let a stale-generation worker
    limp into a mixed-generation world and hang in gloo.

    ``elastic=True`` takes the raw-client path: instead of
    ``jax.distributed.initialize`` (whose client is process-global and
    cannot be re-created), it builds the pybind distributed-runtime
    client directly, connects it to a coordinator service hosted
    elsewhere (see :func:`start_coordinator_service`), and installs it
    into jax's global state — the only route that supports tearing a
    world down and re-forming a smaller one in the same process. The
    joining worker then runs a generation handshake through the KV store
    and refuses a mixed-generation world with
    :class:`GenerationMismatchError` instead of a hang.

    Multi-process runs on the **CPU backend** (the hardware-free DCN
    rehearsal, ``tests/test_distributed_multiprocess.py``) additionally
    need an explicit CPU collectives implementation: without one the CPU
    client executes the first cross-process computation into
    ``INVALID_ARGUMENT: Multiprocess computations aren't implemented on
    the CPU backend``. jaxlib ships gloo TCP collectives, so when this
    initialize is a multi-process one we select
    ``jax_cpu_collectives_implementation=gloo`` before the backend client
    exists (the config is read at CPU client creation; it is inert for
    TPU/GPU backends and for single-process runs we leave it alone).
    """
    n_proc = num_processes
    if n_proc is None:
        try:
            n_proc = _knobs.get_int("JAX_NUM_PROCESSES")
        except ValueError:
            n_proc = 0
    if n_proc and int(n_proc) > 1:
        _select_cpu_collectives("gloo")
    if _WORLD["generation"] is not None:
        if generation is None or generation == _WORLD["generation"]:
            return
        raise RuntimeError(
            f"a generation-{_WORLD['generation']} world is live in this "
            f"process; call shutdown() before re-initializing as "
            f"generation {generation}")
    if elastic:
        if (coordinator_address is None or num_processes is None
                or process_id is None or generation is None):
            raise ValueError(
                "elastic initialize needs explicit coordinator_address, "
                "num_processes, process_id and generation")
        _init_elastic(coordinator_address, int(num_processes),
                      int(process_id), int(generation),
                      init_timeout=kwargs.pop("init_timeout", 30))
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id, **kwargs)
    except RuntimeError as exc:
        # jax raises "distributed.initialize should only be called once."
        # (wording has varied across versions — match both forms)
        msg = str(exc)
        if "only be called once" not in msg and "already initialized" not in msg:
            raise
    _WORLD.update(generation=generation if generation is not None else 0,
                  elastic=False, client=None, service=None,
                  num_processes=num_processes, process_id=process_id,
                  address=coordinator_address)


def _init_elastic(address, num_processes, process_id, generation,
                  init_timeout=30):
    """Form (or join) one generation of an elastic world: raw pybind
    client -> connect -> install into jax global state -> generation
    handshake. On handshake mismatch the half-joined client is torn down
    before raising, so the process can go on to join the right world."""
    xe = _xla_extension()
    from jax._src import distributed as _jdist

    client = xe.get_distributed_runtime_client(
        address, node_id=process_id, heartbeat_interval=_HEARTBEAT_S,
        max_missing_heartbeats=_MAX_MISSED_HEARTBEATS,
        shutdown_on_destruction=False, init_timeout=int(init_timeout))
    client.connect()
    gen_key = "elastic/generation"
    try:
        client.key_value_set(gen_key, str(int(generation)))
    except Exception:
        pass  # a peer set it first; the get below arbitrates
    agreed = int(client.blocking_key_value_get(
        gen_key, int(init_timeout) * 1000))
    if agreed != int(generation):
        _retire_client(client)
        del client
        raise GenerationMismatchError(
            f"this worker carries generation {generation} but the world "
            f"at {address} agreed on generation {agreed}; refusing to "
            f"join (a stale worker in a live mesh hangs gloo)")
    st = _jdist.global_state
    st.client = client
    st.process_id = int(process_id)
    st.num_processes = int(num_processes)
    st.coordinator_address = address
    _WORLD.update(generation=int(generation), elastic=True, client=client,
                  service=None, num_processes=int(num_processes),
                  process_id=int(process_id), address=address)
    _adopt_fleet_run_id(client, int(generation))


def _adopt_fleet_run_id(client, generation, timeout_ms=1000):
    """Thread the fleet run_id through the world's KV store (PR 19):
    a member that already carries one (spawned with
    ``SQ_OBS_FLEET_RUN_ID``) publishes it; a member that joined without
    (a hand-launched replacement, or a bench harness driving
    ``initialize(..., elastic=True)`` directly) adopts the first
    publisher's via :func:`sq_learn_tpu.obs.recorder.set_fleet` — so
    every shard of the mesh correlates under ONE id regardless of how
    its process was started. Best-effort by design: telemetry plumbing
    must never fail a world join."""
    try:
        from ..obs import recorder as _obs_recorder

        rec = _obs_recorder.get_recorder()
        if rec is None:
            return  # obs off: nothing to stamp, don't wait on the KV
        own = rec.fleet_run_id
        key = "fleet/run_id"
        if own:
            try:
                client.key_value_set(key, str(own))
            except Exception:
                pass  # a peer published first; the get below adopts
        agreed = client.blocking_key_value_get(key, int(timeout_ms))
        if agreed:
            _obs_recorder.set_fleet(run_id=agreed)
            _obs_recorder.set_generation(int(generation))
    except Exception:
        pass  # no recorder / no publisher inside the timeout: stay local


def shutdown(*, barrier=True):
    """Tear down this process's world so a new generation can form.

    ``barrier=True`` (the orderly path) rendezvouses the survivors at a
    named KV barrier before dropping the client, so no peer's in-flight
    KV call sees the world half-gone; the abort path
    (``barrier=False``, taken after a detected host failure — the dead
    peer can never reach a barrier) drops straight away. Either way the
    XLA backend caches are cleared: the old world's CPU client pinned
    the gloo topology at creation, and the next :func:`initialize` must
    mint a fresh one."""
    if _WORLD["generation"] is None:
        return
    client = _WORLD["client"]
    if _WORLD["elastic"]:
        if client is not None and barrier:
            try:
                client.wait_at_barrier(
                    f"elastic/shutdown/g{_WORLD['generation']}", 10_000)
            except Exception:
                pass  # a dead peer never reaches the barrier
        from jax._src import distributed as _jdist

        st = _jdist.global_state
        st.client = None
        st.process_id = None
        st.num_processes = None
        st.coordinator_address = None
    else:
        try:
            jax.distributed.shutdown()
        except Exception:
            pass
    _WORLD.update(generation=None, elastic=False, client=None,
                  service=None, num_processes=None, process_id=None,
                  address=None)
    _retire_client(client)
    del client
    # plain `jax.extend.backend` attribute access raises on jax 0.4.x —
    # import the submodule explicitly
    __import__("jax.extend.backend",
               fromlist=["clear_backends"]).clear_backends()


def generation():
    """The live world's generation, or None when no world is up."""
    return _WORLD["generation"]


def world_client():
    """The raw distributed-runtime client of the live elastic world (its
    KV store is the elastic control plane's transport), or None."""
    return _WORLD["client"]


def _select_cpu_collectives(impl):
    """Select the CPU backend's cross-process collectives implementation
    (no-op when already selected, when the option is unknown to this jax,
    or when the backend client already exists — the flag is read once at
    CPU client creation). On jax 0.4.x the option is a ``Flag`` (no
    ``jax.config.update`` surface), so this falls back to the flag's
    ``_set`` — the same mechanism the ``JAX_CPU_COLLECTIVES_IMPLEMENTATION``
    env var uses, just late enough to work after import."""
    try:
        if jax.config.jax_cpu_collectives_implementation != "none":
            return
        jax.config.update("jax_cpu_collectives_implementation", impl)
        return
    except AttributeError:
        pass
    try:
        from jax._src import xla_bridge as _xb

        flag = _xb.CPU_COLLECTIVES_IMPLEMENTATION
        if flag.value == "none":
            flag._set(impl)
    except Exception:
        pass  # older/newer jax without the option: nothing to select


def global_mesh(axis_name=DATA_AXIS):
    """1-D mesh over every device across every participating host."""
    from .mesh import make_mesh

    return make_mesh(axis_name=axis_name)


def process_info():
    """(process_index, process_count, local_device_count) of this host."""
    return (jax.process_index(), jax.process_count(),
            jax.local_device_count())


def host_shard_bounds(n_rows):
    """(lo, hi, per): row range of the global dataset this host loads, and
    the uniform per-host shard size.

    The standard multi-host input pattern: each host reads rows [lo, hi)
    from storage and pads its slice up to ``per`` rows with zero-weight
    padding (``mesh.pad_to_multiple``) — JAX requires equal per-process
    shard shapes on the data axis, so tail hosts MUST pad, not just load
    fewer rows. With the zero weights the padded rows contribute nothing
    to any reduction.

    ``per`` is additionally rounded up to a multiple of this host's local
    device count so the resulting global axis (process_count · per) tiles
    evenly over every device of the global mesh (device counts are uniform
    across hosts on any sane deployment; a ``NamedSharding`` over the data
    axis requires exact divisibility).
    """
    p, np_, local = process_info()
    per = -(-n_rows // np_)
    per = -(-per // local) * local
    lo = min(p * per, n_rows)
    return lo, min(lo + per, n_rows), per
