"""Estimator framework: the contract every model obeys.

Re-implements the estimator contract of the reference (``sklearn/base.py:142,179,203``):
``__init__`` stores hyperparameters verbatim, ``fit`` returns ``self``, learned state
lives in trailing-underscore attributes, and ``get_params``/``set_params``/``clone``
make estimators composable with CV / pipeline tooling. Nothing here touches JAX —
it is pure Python plumbing.
"""

import copy
import inspect
from collections import defaultdict

import numpy as np


class NotFittedError(ValueError, AttributeError):
    """Exception raised when an estimator is used before fitting."""


def _fitted_attributes(estimator):
    return [
        v for v in vars(estimator)
        if v.endswith("_") and not v.startswith("__") and not v.endswith("__")
    ]


def check_is_fitted(estimator, attributes=None):
    """Raise :class:`NotFittedError` if the estimator has no fitted attributes.

    Mirrors ``sklearn/utils/validation.py`` ``check_is_fitted`` behavior.
    """
    if attributes is not None:
        if isinstance(attributes, str):
            attributes = [attributes]
        fitted = all(hasattr(estimator, attr) for attr in attributes)
    else:
        fitted = len(_fitted_attributes(estimator)) > 0
    if not fitted:
        raise NotFittedError(
            f"This {type(estimator).__name__} instance is not fitted yet. "
            "Call 'fit' with appropriate arguments before using this estimator."
        )


def check_n_features(estimator, X):
    """Raise sklearn's clear width-mismatch error when a fitted estimator
    receives inference input whose feature count differs from fit's
    (``n_features_in_`` contract, sklearn ``base.py`` ``_check_n_features``)
    — the alternative is an opaque shape error deep inside a jitted
    kernel. No-op when the estimator never recorded a width."""
    seen = getattr(estimator, "n_features_in_", None)
    if seen is not None and X.shape[-1] != seen:
        raise ValueError(
            f"X has {X.shape[-1]} features, but {type(estimator).__name__} "
            f"is expecting {seen} features as input.")
    return X


def clone(estimator, *, safe=True):
    """Construct an unfitted estimator with the same hyperparameters.

    Mirrors ``sklearn/base.py:30`` semantics: deep-copies parameters, builds a
    fresh instance, and verifies the constructor stored them verbatim.
    """
    if isinstance(estimator, (list, tuple, set, frozenset)):
        return type(estimator)([clone(e, safe=safe) for e in estimator])
    if not hasattr(estimator, "get_params") or isinstance(estimator, type):
        if not safe:
            return copy.deepcopy(estimator)
        raise TypeError(
            f"Cannot clone object {estimator!r}: it does not implement get_params"
        )
    params = estimator.get_params(deep=False)
    new_params = {k: clone(v, safe=False) for k, v in params.items()}
    new_estimator = type(estimator)(**new_params)
    params_set = new_estimator.get_params(deep=False)
    for name in new_params:
        if params_set[name] is not new_params[name]:
            raise RuntimeError(
                f"Cannot clone {estimator!r}: constructor does not set "
                f"parameter {name}"
            )
    return new_estimator


class BaseEstimator:
    """Base class for all estimators in sq_learn_tpu.

    Subclasses must list every hyperparameter as an explicit keyword argument
    of ``__init__`` (no ``*args``/``**kwargs``) and store them unmodified.
    """

    @classmethod
    def _get_param_names(cls):
        init = cls.__init__
        if init is object.__init__:
            return []
        sig = inspect.signature(init)
        params = [
            p for p in sig.parameters.values()
            if p.name != "self" and p.kind not in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
        ]
        return sorted(p.name for p in params)

    def get_params(self, deep=True):
        """Get hyperparameters of this estimator as a dict."""
        out = {}
        for key in self._get_param_names():
            value = getattr(self, key)
            if deep and hasattr(value, "get_params") and not isinstance(value, type):
                for sub_key, sub_value in value.get_params().items():
                    out[f"{key}__{sub_key}"] = sub_value
            out[key] = value
        return out

    def set_params(self, **params):
        """Set hyperparameters of this estimator. Supports ``a__b`` nesting."""
        if not params:
            return self
        valid_params = self.get_params(deep=True)
        nested_params = defaultdict(dict)
        for key, value in params.items():
            key, delim, sub_key = key.partition("__")
            if key not in valid_params:
                raise ValueError(
                    f"Invalid parameter {key!r} for estimator "
                    f"{type(self).__name__}. Valid parameters are: "
                    f"{sorted(valid_params)!r}."
                )
            if delim:
                nested_params[key][sub_key] = value
            else:
                setattr(self, key, value)
        for key, sub_params in nested_params.items():
            getattr(self, key).set_params(**sub_params)
        return self

    def _validated_X(self, X, **check_kw):
        """``check_array`` under the estimator's validate-once cache: inside
        a :func:`~sq_learn_tpu.utils.validation.validation_scope` (opened
        by ``fit_transform``/``fit_predict`` surfaces), the same input
        object is fully validated exactly once per estimator call — the
        dtype/copy/finiteness scans are O(n·m) and were silently re-run by
        every composed stage. Outside a scope this IS ``check_array``."""
        from .utils.validation import check_array, validated_once

        return validated_once(self, X,
                              lambda a: check_array(a, **check_kw))

    def __repr__(self):
        cls = type(self)
        try:
            defaults = {
                name: p.default
                for name, p in inspect.signature(cls.__init__).parameters.items()
            }
            shown = {
                k: v for k, v in self.get_params(deep=False).items()
                if not _param_is_default(v, defaults.get(k, inspect.Parameter.empty))
            }
        except Exception:
            shown = {}
        args = ", ".join(f"{k}={v!r}" for k, v in sorted(shown.items()))
        return f"{cls.__name__}({args})"


def _param_is_default(value, default):
    if default is inspect.Parameter.empty:
        return False
    if isinstance(value, np.ndarray) or isinstance(default, np.ndarray):
        return False
    try:
        return bool(value == default)
    except Exception:
        return value is default


class TransformerMixin:
    """Mixin providing ``fit_transform`` (reference ``base.py:680``).

    The fit and transform halves run under one validate-once scope
    (:func:`~sq_learn_tpu.utils.validation.validation_scope`): the
    transform half reuses the array the fit half already blessed instead
    of re-running the full ``check_array`` contract on it.
    """

    def fit_transform(self, X, y=None, **fit_params):
        from .utils.validation import validation_scope

        with validation_scope(self):
            if y is None:
                return self.fit(X, **fit_params).transform(X)
            return self.fit(X, y, **fit_params).transform(X)


class ClusterMixin:
    """Mixin providing ``fit_predict`` (reference ``base.py:572``)."""

    _estimator_type = "clusterer"

    def fit_predict(self, X, y=None):
        self.fit(X)
        return self.labels_


class ClassifierMixin:
    """Mixin providing accuracy ``score`` for classifiers."""

    _estimator_type = "classifier"

    def score(self, X, y):
        from .metrics import accuracy_score

        return accuracy_score(y, self.predict(X))
