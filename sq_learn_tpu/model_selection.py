"""Model selection: CV splitters, cross-validation, grid search.

Re-implements the slice of the reference's ``sklearn/model_selection`` that
the quantum workloads use (``MnistTrial.py:20-22`` runs
``cross_validate(KNN, ..., cv=StratifiedKFold(10))``): K-fold and stratified
K-fold splitters, ``train_test_split``, ``cross_validate`` /
``cross_val_score``, and an exhaustive ``GridSearchCV``.

Parallelism note: the reference fans folds out with joblib processes
(``n_jobs``, SURVEY §2.3; ``MnistTrial.py:22`` runs ``n_jobs=4``). Here
``n_jobs`` fans folds out over a thread pool instead: every compute-heavy
path in this stack — XLA executions, the native C++ engines, BLAS — drops
the GIL, so threads overlap real work without joblib's process spawn,
pickling, or duplicated device runtimes, and each worker thread inherits
the caller's ``config_context`` snapshot (the config is thread-local).
"""

import warnings
import numbers
import os
import time

import numpy as np

from .base import clone
from .utils import check_random_state


class KFold:
    """K-fold splitter (reference ``model_selection/_split.py`` semantics)."""

    def __init__(self, n_splits=5, *, shuffle=False, random_state=None):
        if n_splits < 2:
            raise ValueError(f"n_splits must be >= 2, got {n_splits}")
        self.n_splits = n_splits
        self.shuffle = shuffle
        self.random_state = random_state

    def get_n_splits(self, X=None, y=None, groups=None):
        return self.n_splits

    def split(self, X, y=None, groups=None):
        n = len(X)
        indices = np.arange(n)
        if self.shuffle:
            check_random_state(self.random_state).shuffle(indices)
        fold_sizes = np.full(self.n_splits, n // self.n_splits, dtype=int)
        fold_sizes[: n % self.n_splits] += 1
        current = 0
        for size in fold_sizes:
            test = indices[current:current + size]
            train = np.concatenate(
                [indices[:current], indices[current + size:]])
            yield train, test
            current += size


class StratifiedKFold(KFold):
    """Stratified K-fold: folds preserve class proportions (the splitter of
    the reference MNIST pipeline, ``MnistTrial.py:21``)."""

    def split(self, X, y, groups=None):
        """Split semantics of the reference splitter
        (``model_selection/_split.py:643`` ``_make_test_folds``), derived
        in closed form from the class counts rather than by materializing
        and striding a sorted label vector.

        Two properties must hold simultaneously: per-fold class counts
        differ by ≤1 AND total fold sizes differ by ≤1. A naive per-class
        round-robin satisfies the first but stacks every class's
        remainder on the low folds. Staggering achieves both: lay the
        classes out in contiguous blocks (class c starting at cumulative
        offset a_c) and give fold i of S the block positions congruent to
        i mod S — then fold i receives ``ceil((count_c - o_ic) / S)``
        members of class c, where ``o_ic = (i - a_c) mod S`` is the
        stagger phase. That count formula IS the allocation; no sorted
        vector is needed.
        """
        y = np.asarray(y)
        n = len(y)
        rng = check_random_state(self.random_state)
        S = self.n_splits
        # classes numbered by order of first appearance in y (reference
        # semantics — NOT lexicographic): rank each lexicographic class
        # by the position where it first occurs
        classes, y_lex = np.unique(y, return_inverse=True)
        n_classes = len(classes)
        first_pos = np.full(n_classes, n)
        np.minimum.at(first_pos, y_lex, np.arange(n))
        appearance_rank = np.argsort(np.argsort(first_pos))
        y_enc = appearance_rank[y_lex]
        y_counts = np.bincount(y_enc, minlength=n_classes)
        if y_counts.max() < S:
            raise ValueError(
                f"n_splits={S} exceeds the number of members in each "
                "class of y.")
        if y_counts.min() < S:
            warnings.warn(
                f"The least populated class in y has only "
                f"{int(y_counts.min())} members, fewer than "
                f"n_splits={S}.", UserWarning)
        block_starts = np.concatenate([[0], np.cumsum(y_counts)[:-1]])
        phase = (np.arange(S)[:, None] - block_starts[None, :]) % S
        # ceil((count - phase) / S), clamped at 0, via floor division
        allocation = -((phase - y_counts[None, :]) // S)
        fold_of = np.empty(n, dtype=int)
        for c in range(n_classes):
            idx = np.flatnonzero(y_enc == c)
            if self.shuffle:
                rng.shuffle(idx)
            fold_of[idx] = np.repeat(np.arange(S), allocation[:, c])
        indices = np.arange(n)
        for f in range(S):
            test = indices[fold_of == f]
            train = indices[fold_of != f]
            yield train, test


def train_test_split(*arrays, test_size=None, train_size=None,
                     random_state=None, shuffle=True, stratify=None):
    """Split arrays into random train/test subsets (reference
    ``model_selection/_split.py`` ``train_test_split`` semantics)."""
    n = len(arrays[0])
    if test_size is None and train_size is None:
        test_size = 0.25
    if isinstance(test_size, float):
        n_test = int(np.ceil(n * test_size))
    elif isinstance(test_size, numbers.Integral):
        n_test = int(test_size)
    else:
        n_test = n - (int(np.floor(n * train_size))
                      if isinstance(train_size, float) else int(train_size))
    n_train = n - n_test

    rng = check_random_state(random_state)
    if stratify is not None:
        stratify = np.asarray(stratify)
        test_idx = []
        for cls in np.unique(stratify):
            idx = np.flatnonzero(stratify == cls)
            if shuffle:
                rng.shuffle(idx)
            k = int(round(len(idx) * n_test / n))
            test_idx.append(idx[:k])
        test_idx = np.concatenate(test_idx)
        mask = np.zeros(n, dtype=bool)
        mask[test_idx] = True
        train_idx = np.flatnonzero(~mask)
        test_idx = np.flatnonzero(mask)
        if shuffle:
            rng.shuffle(train_idx)
            rng.shuffle(test_idx)
    elif shuffle:
        perm = rng.permutation(n)
        test_idx, train_idx = perm[:n_test], perm[n_test:]
    else:
        train_idx = np.arange(n_train)
        test_idx = np.arange(n_train, n)

    out = []
    for a in arrays:
        a = np.asarray(a)
        out.extend([a[train_idx], a[test_idx]])
    return out


def _score(estimator, X, y, scoring):
    if callable(scoring):
        return float(scoring(estimator, X, y))
    if scoring in (None, "accuracy"):
        return float(estimator.score(X, y))
    if scoring == "adjusted_rand_score":
        from .metrics import adjusted_rand_score

        return float(adjusted_rand_score(y, estimator.fit_predict(X)))
    raise ValueError(f"unknown scoring {scoring!r}")


def _resolve_n_jobs(n_jobs, n_tasks):
    """joblib-style ``n_jobs`` semantics: None/1 → serial, -1 → all cores,
    negative k → cores+1+k, capped by the task count."""
    if n_jobs is None:
        return 1
    n_jobs = int(n_jobs)
    if n_jobs == 0:
        raise ValueError("n_jobs == 0 has no meaning (joblib semantics)")
    if n_jobs < 0:
        n_jobs = max(1, (os.cpu_count() or 1) + 1 + n_jobs)
    return max(1, min(n_jobs, n_tasks))


def cross_validate(estimator, X, y=None, *, cv=5, scoring=None, n_jobs=None,
                   return_train_score=False, fit_params=None):
    """Evaluate by cross-validation (reference ``cross_validate``; used at
    ``MnistTrial.py:22`` with ``n_jobs=4``). Folds fan out over a thread
    pool when ``n_jobs`` asks for it — see module docstring."""
    X = np.asarray(X)
    if isinstance(cv, numbers.Integral):
        # sklearn semantics: an int cv stratifies for classifiers
        if (y is not None
                and getattr(estimator, "_estimator_type", "") == "classifier"):
            cv = StratifiedKFold(n_splits=int(cv))
        else:
            cv = KFold(n_splits=int(cv))
    fit_params = fit_params or {}
    y_arr = None if y is None else np.asarray(y)

    def one_fold(train, test):
        est = clone(estimator)
        y_tr = None if y_arr is None else y_arr[train]
        y_te = None if y_arr is None else y_arr[test]
        t0 = time.perf_counter()
        if y_tr is None:
            est.fit(X[train], **fit_params)
        else:
            est.fit(X[train], y_tr, **fit_params)
        t1 = time.perf_counter()
        test_score = _score(est, X[test], y_te, scoring)
        t2 = time.perf_counter()
        train_score = (_score(est, X[train], y_tr, scoring)
                       if return_train_score else None)
        return t1 - t0, t2 - t1, test_score, train_score

    folds = list(cv.split(X, y))
    n_workers = _resolve_n_jobs(n_jobs, len(folds))
    if n_workers == 1:
        fold_results = [one_fold(tr, te) for tr, te in folds]
    else:
        from concurrent.futures import ThreadPoolExecutor

        from ._config import _get_threadlocal_config

        caller_config = _get_threadlocal_config().copy()

        def with_config(args):
            # worker threads materialize a fresh thread-local config from
            # the GLOBAL defaults — propagate the caller's context instead
            _get_threadlocal_config().update(caller_config)
            return one_fold(*args)

        with ThreadPoolExecutor(max_workers=n_workers) as pool:
            fold_results = list(pool.map(with_config, folds))

    results = {
        "fit_time": [r[0] for r in fold_results],
        "score_time": [r[1] for r in fold_results],
        "test_score": [r[2] for r in fold_results],
    }
    if return_train_score:
        results["train_score"] = [r[3] for r in fold_results]
    return {k: np.asarray(v) for k, v in results.items()}


def cross_val_score(estimator, X, y=None, *, cv=5, scoring=None, n_jobs=None):
    return cross_validate(estimator, X, y, cv=cv, scoring=scoring,
                          n_jobs=n_jobs)["test_score"]


class ParameterGrid:
    """Iterate over all combinations of a param grid (reference
    ``model_selection/_search.py`` ``ParameterGrid``)."""

    def __init__(self, param_grid):
        if isinstance(param_grid, dict):
            param_grid = [param_grid]
        self.param_grid = param_grid

    def __iter__(self):
        import itertools

        for grid in self.param_grid:
            keys = sorted(grid)
            for values in itertools.product(*(grid[k] for k in keys)):
                yield dict(zip(keys, values))

    def __len__(self):
        import math

        return sum(
            math.prod(len(v) for v in grid.values()) or 1
            for grid in self.param_grid)


class GridSearchCV:
    """Exhaustive parameter search over cross-validation (reference
    ``GridSearchCV`` essentials: fit → ``best_params_``/``best_score_``/
    ``best_estimator_``/``cv_results_``)."""

    def __init__(self, estimator, param_grid, *, cv=5, scoring=None,
                 n_jobs=None, refit=True):
        self.estimator = estimator
        self.param_grid = param_grid
        self.cv = cv
        self.scoring = scoring
        self.n_jobs = n_jobs
        self.refit = refit

    def fit(self, X, y=None, **fit_params):
        grid = list(ParameterGrid(self.param_grid))
        mean_scores = []
        all_scores = []
        for params in grid:
            est = clone(self.estimator).set_params(**params)
            scores = cross_val_score(est, X, y, cv=self.cv,
                                     scoring=self.scoring,
                                     n_jobs=self.n_jobs)
            all_scores.append(scores)
            mean_scores.append(float(np.mean(scores)))
        best = int(np.argmax(mean_scores))
        self.best_params_ = grid[best]
        self.best_score_ = mean_scores[best]
        self.cv_results_ = {
            "params": grid,
            "mean_test_score": np.asarray(mean_scores),
            "split_test_scores": np.asarray(all_scores),
        }
        if self.refit:
            self.best_estimator_ = clone(self.estimator).set_params(
                **self.best_params_)
            if y is None:
                self.best_estimator_.fit(X, **fit_params)
            else:
                self.best_estimator_.fit(X, y, **fit_params)
        return self

    def predict(self, X):
        return self.best_estimator_.predict(X)

    def score(self, X, y=None):
        return _score(self.best_estimator_, X, y, self.scoring)
