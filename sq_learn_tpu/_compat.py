"""Version-compat shims for the jax API surface this package leans on.

The package targets current jax (top-level ``jax.shard_map``,
``lax.axis_size``, pallas vma plumbing) but must keep importing — and
keep its mesh paths working — on the 0.4.x line some deployment hosts
still run, where ``shard_map`` lives in ``jax.experimental.shard_map``
with a ``check_rep`` kwarg instead of ``check_vma``. Everything here is
a thin dispatch to whichever spelling the installed jax provides; no
behavior differences beyond the names.
"""

import jax
from jax import lax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` with the modern kwarg names, on any jax.

    Newer jax exports ``shard_map`` at top level with ``check_vma``;
    0.4.x has it under ``jax.experimental.shard_map`` with the same
    check under its old name ``check_rep``.
    """
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as sm_legacy

    # the legacy checker predates replication rules for while/scan (it
    # rejects the sharded Lloyd loop outright), so it stays off there —
    # the modern checker runs wherever the modern API exists
    return sm_legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=False)


def random_multinomial(key, n, probs):
    """``jax.random.multinomial`` on any jax.

    0.4.x lacks the primitive; the fallback is the standard conditional
    binomial chain (category i draws Binomial(remaining, pᵢ/tailᵢ)), the
    same decomposition the modern implementation lowers to. ``probs``
    must be normalized along the last axis; counts come back in
    ``probs.dtype`` with the category axis last, matching the modern API.
    """
    if hasattr(jax.random, "multinomial"):
        return jax.random.multinomial(key, n, probs)
    import jax.numpy as jnp

    p = jnp.moveaxis(probs, -1, 0)                     # (d, ...)
    tail = jnp.flip(jnp.cumsum(jnp.flip(p, 0), axis=0), 0)
    keys = jax.random.split(key, p.shape[0])
    n = jnp.broadcast_to(jnp.asarray(n, p.dtype), p.shape[1:])

    def body(remaining, xs):
        ki, pi, ti = xs
        ratio = jnp.clip(jnp.where(ti > 0, pi / ti, 1.0), 0.0, 1.0)
        ci = jax.random.binomial(ki, remaining, ratio, dtype=p.dtype)
        # degenerate rows (NaN/zero mass) propagate NaN like the modern
        # primitive rather than raising
        ci = jnp.where(jnp.isfinite(ratio), ci, jnp.nan)
        return remaining - ci, ci

    _, counts = lax.scan(body, n, (keys, p, tail))
    return jnp.moveaxis(counts, 0, -1)


def axis_size(axis_name):
    """Static size of a mapped axis inside ``shard_map``/``pmap``.

    ``lax.axis_size`` only exists on newer jax; on 0.4.x the documented
    equivalent is ``psum`` of the literal 1, which resolves statically
    (no collective is emitted for a non-tracer operand).
    """
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)
