// sq-learn-tpu native host runtime.
//
// TPU-native framework layout puts the FLOP path on XLA; this library is the
// host-side runtime the reference implements in Cython/C++ (SURVEY §2.2):
//
//  - lloyd_iter_chunked: the CPU-parity fused Lloyd E+M step — chunked
//    pairwise distances via the ||c||^2 - 2 x.c trick, argmin labels,
//    thread-local partial centroid sums with a serial reduction. This is the
//    same algorithm as the reference's `lloyd_iter_chunked_dense`
//    (cluster/_k_means_lloyd.pyx:29): OpenMP prange becomes std::thread.
//  - murmurhash3_x86_32 (+ bulk variant): feature hashing, re-implemented
//    from the public MurmurHash3 algorithm (reference vendors
//    utils/src/MurmurHash3.cpp).
//  - csv_count_rows / csv_parse_floats: a threaded float-CSV ingest path for
//    host-side data loading (the reference leans on numpy/pandas; our
//    loaders stream large CSVs like CICIDS through this).
//
// Exposed as plain C symbols consumed via ctypes (no pybind11 in the image).

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Lloyd iteration (CPU parity kernel)
// ---------------------------------------------------------------------------

// X: (n, m) row-major float32; centers: (k, m); sample_weight: (n)
// out_labels: (n) int32; out_sums: (k, m) float64; out_counts: (k) float64;
// out_inertia: scalar float64. Returns 0 on success.
int lloyd_iter_chunked(const float* X, const float* sample_weight,
                       const float* centers, int64_t n, int64_t m, int64_t k,
                       int32_t* out_labels, double* out_sums,
                       double* out_counts, double* out_inertia,
                       int n_threads) {
  if (n <= 0 || m <= 0 || k <= 0) return -1;
  if (n_threads <= 0) {
    n_threads = (int)std::thread::hardware_concurrency();
    if (n_threads <= 0) n_threads = 1;
  }
  if ((int64_t)n_threads > n) n_threads = (int)n;
  {
    const int64_t nch = (n + 255) / 256;  // one chunk per thread max
    if ((int64_t)n_threads > nch) n_threads = (int)nch;
  }

  // ||c||^2 once
  std::vector<double> c_sq(k);
  for (int64_t j = 0; j < k; ++j) {
    double s = 0.0;
    const float* c = centers + j * m;
    for (int64_t f = 0; f < m; ++f) s += (double)c[f] * c[f];
    c_sq[j] = s;
  }

  const int64_t chunk = 256;  // reference CHUNK_SIZE (_k_means_fast.pyx:31)
  std::atomic<int64_t> next_chunk{0};
  const int64_t n_chunks = (n + chunk - 1) / chunk;

  std::vector<std::vector<double>> t_sums((size_t)n_threads,
                                          std::vector<double>(k * m, 0.0));
  std::vector<std::vector<double>> t_counts((size_t)n_threads,
                                            std::vector<double>(k, 0.0));
  std::vector<double> t_inertia((size_t)n_threads, 0.0);

  auto worker = [&](int tid) {
    std::vector<double>& sums = t_sums[tid];
    std::vector<double>& counts = t_counts[tid];
    double inertia = 0.0;
    for (;;) {
      int64_t c0 = next_chunk.fetch_add(1);
      if (c0 >= n_chunks) break;
      int64_t lo = c0 * chunk, hi = std::min(n, lo + chunk);
      for (int64_t i = lo; i < hi; ++i) {
        const float* x = X + i * m;
        double best = 1e300;
        int32_t best_j = 0;
        for (int64_t j = 0; j < k; ++j) {
          const float* c = centers + j * m;
          double dot = 0.0;
          for (int64_t f = 0; f < m; ++f) dot += (double)x[f] * c[f];
          double d = c_sq[j] - 2.0 * dot;  // ||x||^2 constant in argmin
          if (d < best) { best = d; best_j = (int32_t)j; }
        }
        out_labels[i] = best_j;
        double w = sample_weight ? (double)sample_weight[i] : 1.0;
        double x_sq = 0.0;
        for (int64_t f = 0; f < m; ++f) {
          x_sq += (double)x[f] * x[f];
          sums[best_j * m + f] += w * x[f];
        }
        counts[best_j] += w;
        inertia += w * (best + x_sq);
      }
    }
    t_inertia[tid] = inertia;
  };

  std::vector<std::thread> threads;
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker, t);
  for (auto& th : threads) th.join();

  // serial reduction (the GIL-guarded reduction of _k_means_lloyd.pyx:145)
  std::memset(out_sums, 0, sizeof(double) * k * m);
  std::memset(out_counts, 0, sizeof(double) * k);
  double inertia = 0.0;
  for (int t = 0; t < n_threads; ++t) {
    for (int64_t e = 0; e < k * m; ++e) out_sums[e] += t_sums[t][e];
    for (int64_t j = 0; j < k; ++j) out_counts[j] += t_counts[t][j];
    inertia += t_inertia[t];
  }
  *out_inertia = inertia;
  return 0;
}

// ---------------------------------------------------------------------------
// Windowed (delta-means) Lloyd iteration
// ---------------------------------------------------------------------------

// SplitMix64: tiny stateless per-row generator so the delta-window pick is
// reproducible from (seed, row) without any shared RNG state across threads.
static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

// The delta-means E+M step (reference `delta_means1`/`select_labels`,
// _dmeans.py:742-750/2252): each row picks uniformly among the centroids
// whose squared distance is within `window` of its minimum (window == 0 is
// the classical argmin). Additionally emits per-row min squared distances
// (out_min_d2, may be null) for empty-cluster relocation, and accumulates
// partials for the *picked* labels while inertia uses the true minima —
// matching the XLA e_step exactly.
int lloyd_iter_window(const float* X, const float* sample_weight,
                      const float* centers, int64_t n, int64_t m, int64_t k,
                      double window, uint64_t seed, int32_t* out_labels,
                      float* out_min_d2, double* out_sums, double* out_counts,
                      double* out_inertia, int n_threads) {
  if (n <= 0 || m <= 0 || k <= 0) return -1;
  if (n_threads <= 0) {
    n_threads = (int)std::thread::hardware_concurrency();
    if (n_threads <= 0) n_threads = 1;
  }
  if ((int64_t)n_threads > n) n_threads = (int)n;
  {
    const int64_t nch = (n + 255) / 256;  // one chunk per thread max
    if ((int64_t)n_threads > nch) n_threads = (int)nch;
  }

  std::vector<double> c_sq(k);
  for (int64_t j = 0; j < k; ++j) {
    double s = 0.0;
    const float* c = centers + j * m;
    for (int64_t f = 0; f < m; ++f) s += (double)c[f] * c[f];
    c_sq[j] = s;
  }

  const int64_t chunk = 256;
  std::atomic<int64_t> next_chunk{0};
  const int64_t n_chunks = (n + chunk - 1) / chunk;

  std::vector<std::vector<double>> t_sums((size_t)n_threads,
                                          std::vector<double>(k * m, 0.0));
  std::vector<std::vector<double>> t_counts((size_t)n_threads,
                                            std::vector<double>(k, 0.0));
  std::vector<double> t_inertia((size_t)n_threads, 0.0);

  auto worker = [&](int tid) {
    std::vector<double>& sums = t_sums[tid];
    std::vector<double>& counts = t_counts[tid];
    std::vector<double> d(k);
    double inertia = 0.0;
    for (;;) {
      int64_t c0 = next_chunk.fetch_add(1);
      if (c0 >= n_chunks) break;
      int64_t lo = c0 * chunk, hi = std::min(n, lo + chunk);
      for (int64_t i = lo; i < hi; ++i) {
        const float* x = X + i * m;
        double best = 1e300;
        for (int64_t j = 0; j < k; ++j) {
          const float* c = centers + j * m;
          double dot = 0.0;
          for (int64_t f = 0; f < m; ++f) dot += (double)x[f] * c[f];
          d[j] = c_sq[j] - 2.0 * dot;  // ||x||^2 constant across centers
          if (d[j] < best) best = d[j];
        }
        int32_t pick;
        if (window > 0.0) {
          int64_t cnt = 0;
          for (int64_t j = 0; j < k; ++j) cnt += (d[j] <= best + window);
          uint64_t r = splitmix64(seed ^ (uint64_t)i) % (uint64_t)cnt;
          pick = 0;
          for (int64_t j = 0; j < k; ++j) {
            if (d[j] <= best + window && r-- == 0) { pick = (int32_t)j; break; }
          }
        } else {
          pick = 0;
          for (int64_t j = 0; j < k; ++j) if (d[j] == best) { pick = (int32_t)j; break; }
        }
        out_labels[i] = pick;
        double w = sample_weight ? (double)sample_weight[i] : 1.0;
        double x_sq = 0.0;
        for (int64_t f = 0; f < m; ++f) {
          x_sq += (double)x[f] * x[f];
          sums[pick * m + f] += w * x[f];
        }
        counts[pick] += w;
        double md2 = best + x_sq;
        if (out_min_d2) out_min_d2[i] = (float)md2;
        inertia += w * md2;
      }
    }
    t_inertia[tid] = inertia;
  };

  std::vector<std::thread> threads;
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker, t);
  for (auto& th : threads) th.join();

  std::memset(out_sums, 0, sizeof(double) * k * m);
  std::memset(out_counts, 0, sizeof(double) * k);
  double inertia = 0.0;
  for (int t = 0; t < n_threads; ++t) {
    for (int64_t e = 0; e < k * m; ++e) out_sums[e] += t_sums[t][e];
    for (int64_t j = 0; j < k; ++j) out_counts[j] += t_counts[t][j];
    inertia += t_inertia[t];
  }
  *out_inertia = inertia;
  return 0;
}

// ---------------------------------------------------------------------------
// Elkan iteration (triangle-inequality-pruned classical E-step)
// ---------------------------------------------------------------------------

static inline double sq_dist(const float* x, const float* c, int64_t m) {
  double s = 0.0;
  for (int64_t f = 0; f < m; ++f) {
    double d = (double)x[f] - c[f];
    s += d * d;
  }
  return s;
}

// One Elkan E-step (Elkan 2003; the reference ships it as
// cluster/_k_means_elkan.pyx `elkan_iter_chunked_dense:184`). Works in plain
// (not squared) distance space. Persistent per-point state owned by the
// caller across iterations:
//   labels (n) int32, upper (n) float32 — upper bound on d(x, c_label),
//   lower (n, k) float32 — lower bounds on d(x, c_j).
// Caller-computed per-iteration center geometry:
//   c_half (k, k) = 0.5 * d(c_a, c_j); s (k) = 0.5 * min_{j!=a} d(c_a, c_j).
// With init != 0 all n*k distances are computed to seed the bounds (the
// role of `init_bounds_dense:33`). On exit `upper` is the EXACT assigned
// distance for every point (one extra m-dot for pruned points — ~1/k of the
// work saved — which keeps bounds tight and yields exact per-iteration
// inertia, unlike the reference, which only computes inertia after the
// loop). Outputs match lloyd_iter_window: weighted partial sums/counts,
// exact min_d2 (squared), weighted inertia.
int elkan_iter(const float* X, const float* sample_weight,
               const float* centers, const float* c_half, const float* s,
               int64_t n, int64_t m, int64_t k, int32_t* labels, float* upper,
               float* lower, int init, float* out_min_d2, double* out_sums,
               double* out_counts, double* out_inertia, int n_threads) {
  if (n <= 0 || m <= 0 || k <= 0) return -1;
  if (n_threads <= 0) {
    n_threads = (int)std::thread::hardware_concurrency();
    if (n_threads <= 0) n_threads = 1;
  }
  if ((int64_t)n_threads > n) n_threads = (int)n;
  {
    const int64_t nch = (n + 255) / 256;
    if ((int64_t)n_threads > nch) n_threads = (int)nch;
  }

  const int64_t chunk = 256;
  std::atomic<int64_t> next_chunk{0};
  const int64_t n_chunks = (n + chunk - 1) / chunk;

  std::vector<std::vector<double>> t_sums((size_t)n_threads,
                                          std::vector<double>(k * m, 0.0));
  std::vector<std::vector<double>> t_counts((size_t)n_threads,
                                            std::vector<double>(k, 0.0));
  std::vector<double> t_inertia((size_t)n_threads, 0.0);

  auto worker = [&](int tid) {
    std::vector<double>& sums = t_sums[tid];
    std::vector<double>& counts = t_counts[tid];
    double inertia = 0.0;
    for (;;) {
      int64_t c0 = next_chunk.fetch_add(1);
      if (c0 >= n_chunks) break;
      int64_t lo = c0 * chunk, hi = std::min(n, lo + chunk);
      for (int64_t i = lo; i < hi; ++i) {
        const float* x = X + i * m;
        float* lb = lower + i * k;
        int32_t a;
        float u;
        if (init) {
          double best = 1e300;
          a = 0;
          for (int64_t j = 0; j < k; ++j) {
            double d = std::sqrt(sq_dist(x, centers + j * m, m));
            lb[j] = (float)d;
            if (d < best) { best = d; a = (int32_t)j; }
          }
          u = (float)best;
        } else {
          a = labels[i];
          u = upper[i];
          if (u > s[a]) {
            // u is inflated by the last center shift; tighten lazily on
            // the first center that survives the bound tests
            bool tight = false;
            for (int64_t j = 0; j < k; ++j) {
              if ((int32_t)j == a) continue;
              if (u > lb[j] && u > c_half[(int64_t)a * k + j]) {
                if (!tight) {
                  u = (float)std::sqrt(sq_dist(x, centers + (int64_t)a * m, m));
                  lb[a] = u;
                  tight = true;
                  if (!(u > lb[j] && u > c_half[(int64_t)a * k + j])) continue;
                }
                float d = (float)std::sqrt(sq_dist(x, centers + j * m, m));
                lb[j] = d;
                if (d < u) { u = d; a = (int32_t)j; }
              }
            }
            if (!tight) {
              // every candidate was pruned by the bounds alone; one exact
              // dot keeps `upper` tight for the next iteration
              u = (float)std::sqrt(sq_dist(x, centers + (int64_t)a * m, m));
              lb[a] = u;
            }
          } else {
            u = (float)std::sqrt(sq_dist(x, centers + (int64_t)a * m, m));
            lb[a] = u;
          }
        }
        labels[i] = a;
        upper[i] = u;
        double md2 = (double)u * u;
        if (out_min_d2) out_min_d2[i] = (float)md2;
        double w = sample_weight ? (double)sample_weight[i] : 1.0;
        for (int64_t f = 0; f < m; ++f) sums[(int64_t)a * m + f] += w * x[f];
        counts[a] += w;
        inertia += w * md2;
      }
    }
    t_inertia[tid] = inertia;
  };

  std::vector<std::thread> threads;
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker, t);
  for (auto& th : threads) th.join();

  std::memset(out_sums, 0, sizeof(double) * k * m);
  std::memset(out_counts, 0, sizeof(double) * k);
  double inertia = 0.0;
  for (int t = 0; t < n_threads; ++t) {
    for (int64_t e = 0; e < k * m; ++e) out_sums[e] += t_sums[t][e];
    for (int64_t j = 0; j < k; ++j) out_counts[j] += t_counts[t][j];
    inertia += t_inertia[t];
  }
  *out_inertia = inertia;
  return 0;
}

// ---------------------------------------------------------------------------
// MurmurHash3 x86 32-bit (public domain algorithm, Austin Appleby)
// ---------------------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

uint32_t murmurhash3_x86_32(const void* key, int len, uint32_t seed) {
  const uint8_t* data = (const uint8_t*)key;
  const int nblocks = len / 4;
  uint32_t h1 = seed;
  const uint32_t c1 = 0xcc9e2d51, c2 = 0x1b873593;

  for (int i = 0; i < nblocks; ++i) {
    uint32_t k1;
    std::memcpy(&k1, data + i * 4, 4);
    k1 *= c1; k1 = rotl32(k1, 15); k1 *= c2;
    h1 ^= k1; h1 = rotl32(h1, 13); h1 = h1 * 5 + 0xe6546b64;
  }

  const uint8_t* tail = data + nblocks * 4;
  uint32_t k1 = 0;
  switch (len & 3) {
    case 3: k1 ^= (uint32_t)tail[2] << 16; [[fallthrough]];
    case 2: k1 ^= (uint32_t)tail[1] << 8; [[fallthrough]];
    case 1: k1 ^= tail[0];
      k1 *= c1; k1 = rotl32(k1, 15); k1 *= c2; h1 ^= k1;
  }

  h1 ^= (uint32_t)len;
  h1 ^= h1 >> 16; h1 *= 0x85ebca6b; h1 ^= h1 >> 13;
  h1 *= 0xc2b2ae35; h1 ^= h1 >> 16;
  return h1;
}

// Hash `count` NUL-separated strings from a packed buffer; offsets has
// count+1 entries into buf.
void murmurhash3_bulk(const char* buf, const int64_t* offsets, int64_t count,
                      uint32_t seed, uint32_t* out) {
  for (int64_t i = 0; i < count; ++i) {
    out[i] = murmurhash3_x86_32(buf + offsets[i],
                                (int)(offsets[i + 1] - offsets[i]), seed);
  }
}

// ---------------------------------------------------------------------------
// CSV float ingest
// ---------------------------------------------------------------------------

// Whitespace-only (incl. CRLF) line — skipped by every reader so the
// native and fallback paths agree on row counts.
static bool csv_blank_line(const char* line, ssize_t len) {
  for (ssize_t i = 0; i < len; ++i) {
    char ch = line[i];
    if (ch == '\0') break;
    if (ch != '\n' && ch != '\r' && ch != ' ' && ch != '\t') return false;
  }
  return true;
}

// Count data rows and columns of a delimiter-separated numeric file.
// Returns 0 on success; n_rows excludes `skip_header` lines.
int csv_shape(const char* path, char delim, int skip_header, int64_t* n_rows,
              int64_t* n_cols) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  char* line = nullptr;
  size_t cap = 0;
  int64_t rows = 0, cols = 0;
  int skipped = 0;
  ssize_t len;
  while ((len = getline(&line, &cap, f)) != -1) {
    if (skipped < skip_header) { ++skipped; continue; }
    if (csv_blank_line(line, len)) continue;
    if (rows == 0) {
      cols = 1;
      for (ssize_t i = 0; i < len; ++i)
        if (line[i] == delim) ++cols;
    }
    ++rows;
  }
  std::free(line);
  std::fclose(f);
  *n_rows = rows;
  *n_cols = cols;
  return 0;
}

// Parse one CSV line into n_cols float32 fields. Non-numeric fields parse
// as NaN (strtof stops at junk; empty fields / text labels -> NaN, caller
// decides). One definition for the one-shot and streaming readers.
static void parse_csv_line(char* line, char delim, float* out,
                           int64_t n_cols) {
  char* p = line;
  for (int64_t c = 0; c < n_cols; ++c) {
    char* end = p;
    float v = strtof(p, &end);
    if (end == p) {  // non-numeric field
      v = NAN;
      while (*end && *end != delim && *end != '\n') ++end;
    }
    out[c] = v;
    p = end;
    while (*p && *p != delim && *p != '\n') ++p;
    if (*p == delim) ++p;
  }
}

// Parse the file into a preallocated (n_rows, n_cols) float32 row-major
// buffer. Returns number of rows parsed, or -1 on IO error.
int64_t csv_parse_floats(const char* path, char delim, int skip_header,
                         float* out, int64_t max_rows, int64_t n_cols) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  char* line = nullptr;
  size_t cap = 0;
  int64_t row = 0;
  int skipped = 0;
  ssize_t len;
  while (row < max_rows && (len = getline(&line, &cap, f)) != -1) {
    if (skipped < skip_header) { ++skipped; continue; }
    if (csv_blank_line(line, len)) continue;
    parse_csv_line(line, delim, out + row * n_cols, n_cols);
    ++row;
  }
  std::free(line);
  std::fclose(f);
  return row;
}

// ---------------------------------------------------------------------------
// Streaming CSV batches — a stateful reader handle so larger-than-memory
// files feed incremental fits (MiniBatch partial_fit) batch by batch
// without re-scanning from the top per batch.
// ---------------------------------------------------------------------------

struct CsvStream {
  FILE* f;
  char delim;
  char* line;
  size_t cap;
};

// Open a stream positioned past `skip_header` lines; returns nullptr on IO
// error. Close with csv_stream_close.
void* csv_stream_open(const char* path, char delim, int skip_header) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  char* line = nullptr;
  size_t cap = 0;
  for (int i = 0; i < skip_header; ++i) {
    if (getline(&line, &cap, f) == -1) break;
  }
  CsvStream* s = new CsvStream{f, delim, line, cap};
  return s;
}

// Parse up to max_rows rows into the preallocated row-major float32 buffer
// (same field semantics as csv_parse_floats). Returns rows parsed — 0 at
// EOF — or -1 on a null handle.
int64_t csv_stream_next(void* handle, float* out, int64_t max_rows,
                        int64_t n_cols) {
  CsvStream* s = static_cast<CsvStream*>(handle);
  if (!s) return -1;
  int64_t row = 0;
  ssize_t len;
  while (row < max_rows && (len = getline(&s->line, &s->cap, s->f)) != -1) {
    char* line = s->line;
    if (csv_blank_line(line, len)) continue;
    parse_csv_line(line, s->delim, out + row * n_cols, n_cols);
    ++row;
  }
  return row;
}

void csv_stream_close(void* handle) {
  CsvStream* s = static_cast<CsvStream*>(handle);
  if (!s) return;
  std::free(s->line);
  std::fclose(s->f);
  delete s;
}

}  // extern "C"
