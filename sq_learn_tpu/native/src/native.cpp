// sq-learn-tpu native host runtime.
//
// TPU-native framework layout puts the FLOP path on XLA; this library is the
// host-side runtime the reference implements in Cython/C++ (SURVEY §2.2):
//
//  - lloyd_iter_chunked: the CPU-parity fused Lloyd E+M step — chunked
//    pairwise distances via the ||c||^2 - 2 x.c trick, argmin labels,
//    thread-local partial centroid sums with a serial reduction. This is the
//    same algorithm as the reference's `lloyd_iter_chunked_dense`
//    (cluster/_k_means_lloyd.pyx:29): OpenMP prange becomes std::thread.
//  - murmurhash3_x86_32 (+ bulk variant): feature hashing, re-implemented
//    from the public MurmurHash3 algorithm (reference vendors
//    utils/src/MurmurHash3.cpp).
//  - csv_count_rows / csv_parse_floats: a threaded float-CSV ingest path for
//    host-side data loading (the reference leans on numpy/pandas; our
//    loaders stream large CSVs like CICIDS through this).
//
// Exposed as plain C symbols consumed via ctypes (no pybind11 in the image).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Lloyd iteration (CPU parity kernel)
// ---------------------------------------------------------------------------

// X: (n, m) row-major float32; centers: (k, m); sample_weight: (n)
// out_labels: (n) int32; out_sums: (k, m) float64; out_counts: (k) float64;
// out_inertia: scalar float64. Returns 0 on success.
int lloyd_iter_chunked(const float* X, const float* sample_weight,
                       const float* centers, int64_t n, int64_t m, int64_t k,
                       int32_t* out_labels, double* out_sums,
                       double* out_counts, double* out_inertia,
                       int n_threads) {
  if (n <= 0 || m <= 0 || k <= 0) return -1;
  if (n_threads <= 0) {
    n_threads = (int)std::thread::hardware_concurrency();
    if (n_threads <= 0) n_threads = 1;
  }
  if ((int64_t)n_threads > n) n_threads = (int)n;
  {
    const int64_t nch = (n + 255) / 256;  // one chunk per thread max
    if ((int64_t)n_threads > nch) n_threads = (int)nch;
  }

  // ||c||^2 once
  std::vector<double> c_sq(k);
  for (int64_t j = 0; j < k; ++j) {
    double s = 0.0;
    const float* c = centers + j * m;
    for (int64_t f = 0; f < m; ++f) s += (double)c[f] * c[f];
    c_sq[j] = s;
  }

  const int64_t chunk = 256;  // reference CHUNK_SIZE (_k_means_fast.pyx:31)
  std::atomic<int64_t> next_chunk{0};
  const int64_t n_chunks = (n + chunk - 1) / chunk;

  std::vector<std::vector<double>> t_sums((size_t)n_threads,
                                          std::vector<double>(k * m, 0.0));
  std::vector<std::vector<double>> t_counts((size_t)n_threads,
                                            std::vector<double>(k, 0.0));
  std::vector<double> t_inertia((size_t)n_threads, 0.0);

  auto worker = [&](int tid) {
    std::vector<double>& sums = t_sums[tid];
    std::vector<double>& counts = t_counts[tid];
    double inertia = 0.0;
    for (;;) {
      int64_t c0 = next_chunk.fetch_add(1);
      if (c0 >= n_chunks) break;
      int64_t lo = c0 * chunk, hi = std::min(n, lo + chunk);
      for (int64_t i = lo; i < hi; ++i) {
        const float* x = X + i * m;
        double best = 1e300;
        int32_t best_j = 0;
        for (int64_t j = 0; j < k; ++j) {
          const float* c = centers + j * m;
          double dot = 0.0;
          for (int64_t f = 0; f < m; ++f) dot += (double)x[f] * c[f];
          double d = c_sq[j] - 2.0 * dot;  // ||x||^2 constant in argmin
          if (d < best) { best = d; best_j = (int32_t)j; }
        }
        out_labels[i] = best_j;
        double w = sample_weight ? (double)sample_weight[i] : 1.0;
        double x_sq = 0.0;
        for (int64_t f = 0; f < m; ++f) {
          x_sq += (double)x[f] * x[f];
          sums[best_j * m + f] += w * x[f];
        }
        counts[best_j] += w;
        inertia += w * (best + x_sq);
      }
    }
    t_inertia[tid] = inertia;
  };

  std::vector<std::thread> threads;
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker, t);
  for (auto& th : threads) th.join();

  // serial reduction (the GIL-guarded reduction of _k_means_lloyd.pyx:145)
  std::memset(out_sums, 0, sizeof(double) * k * m);
  std::memset(out_counts, 0, sizeof(double) * k);
  double inertia = 0.0;
  for (int t = 0; t < n_threads; ++t) {
    for (int64_t e = 0; e < k * m; ++e) out_sums[e] += t_sums[t][e];
    for (int64_t j = 0; j < k; ++j) out_counts[j] += t_counts[t][j];
    inertia += t_inertia[t];
  }
  *out_inertia = inertia;
  return 0;
}

// ---------------------------------------------------------------------------
// Windowed (delta-means) Lloyd iteration
// ---------------------------------------------------------------------------

// SplitMix64: tiny stateless per-row generator so the delta-window pick is
// reproducible from (seed, row) without any shared RNG state across threads.
static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

// The delta-means E+M step (reference `delta_means1`/`select_labels`,
// _dmeans.py:742-750/2252): each row picks uniformly among the centroids
// whose squared distance is within `window` of its minimum (window == 0 is
// the classical argmin). Additionally emits per-row min squared distances
// (out_min_d2, may be null) for empty-cluster relocation, and accumulates
// partials for the *picked* labels while inertia uses the true minima —
// matching the XLA e_step exactly.
int lloyd_iter_window(const float* X, const float* sample_weight,
                      const float* centers, int64_t n, int64_t m, int64_t k,
                      double window, uint64_t seed, int32_t* out_labels,
                      float* out_min_d2, double* out_sums, double* out_counts,
                      double* out_inertia, int n_threads) {
  if (n <= 0 || m <= 0 || k <= 0) return -1;
  if (n_threads <= 0) {
    n_threads = (int)std::thread::hardware_concurrency();
    if (n_threads <= 0) n_threads = 1;
  }
  if ((int64_t)n_threads > n) n_threads = (int)n;
  {
    const int64_t nch = (n + 255) / 256;  // one chunk per thread max
    if ((int64_t)n_threads > nch) n_threads = (int)nch;
  }

  std::vector<double> c_sq(k);
  for (int64_t j = 0; j < k; ++j) {
    double s = 0.0;
    const float* c = centers + j * m;
    for (int64_t f = 0; f < m; ++f) s += (double)c[f] * c[f];
    c_sq[j] = s;
  }

  const int64_t chunk = 256;
  std::atomic<int64_t> next_chunk{0};
  const int64_t n_chunks = (n + chunk - 1) / chunk;

  std::vector<std::vector<double>> t_sums((size_t)n_threads,
                                          std::vector<double>(k * m, 0.0));
  std::vector<std::vector<double>> t_counts((size_t)n_threads,
                                            std::vector<double>(k, 0.0));
  std::vector<double> t_inertia((size_t)n_threads, 0.0);

  auto worker = [&](int tid) {
    std::vector<double>& sums = t_sums[tid];
    std::vector<double>& counts = t_counts[tid];
    std::vector<double> d(k);
    double inertia = 0.0;
    for (;;) {
      int64_t c0 = next_chunk.fetch_add(1);
      if (c0 >= n_chunks) break;
      int64_t lo = c0 * chunk, hi = std::min(n, lo + chunk);
      for (int64_t i = lo; i < hi; ++i) {
        const float* x = X + i * m;
        double best = 1e300;
        for (int64_t j = 0; j < k; ++j) {
          const float* c = centers + j * m;
          double dot = 0.0;
          for (int64_t f = 0; f < m; ++f) dot += (double)x[f] * c[f];
          d[j] = c_sq[j] - 2.0 * dot;  // ||x||^2 constant across centers
          if (d[j] < best) best = d[j];
        }
        int32_t pick;
        if (window > 0.0) {
          int64_t cnt = 0;
          for (int64_t j = 0; j < k; ++j) cnt += (d[j] <= best + window);
          uint64_t r = splitmix64(seed ^ (uint64_t)i) % (uint64_t)cnt;
          pick = 0;
          for (int64_t j = 0; j < k; ++j) {
            if (d[j] <= best + window && r-- == 0) { pick = (int32_t)j; break; }
          }
        } else {
          pick = 0;
          for (int64_t j = 0; j < k; ++j) if (d[j] == best) { pick = (int32_t)j; break; }
        }
        out_labels[i] = pick;
        double w = sample_weight ? (double)sample_weight[i] : 1.0;
        double x_sq = 0.0;
        for (int64_t f = 0; f < m; ++f) {
          x_sq += (double)x[f] * x[f];
          sums[pick * m + f] += w * x[f];
        }
        counts[pick] += w;
        double md2 = best + x_sq;
        if (out_min_d2) out_min_d2[i] = (float)md2;
        inertia += w * md2;
      }
    }
    t_inertia[tid] = inertia;
  };

  std::vector<std::thread> threads;
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker, t);
  for (auto& th : threads) th.join();

  std::memset(out_sums, 0, sizeof(double) * k * m);
  std::memset(out_counts, 0, sizeof(double) * k);
  double inertia = 0.0;
  for (int t = 0; t < n_threads; ++t) {
    for (int64_t e = 0; e < k * m; ++e) out_sums[e] += t_sums[t][e];
    for (int64_t j = 0; j < k; ++j) out_counts[j] += t_counts[t][j];
    inertia += t_inertia[t];
  }
  *out_inertia = inertia;
  return 0;
}

// ---------------------------------------------------------------------------
// Full lockstep multi-restart windowed Lloyd run
// ---------------------------------------------------------------------------

// Optional BLAS sgemm, registered from Python (scipy's bundled OpenBLAS via
// ctypes). Standard cblas signature; 101=RowMajor, 111=NoTrans, 112=Trans.
typedef void (*cblas_sgemm_t)(int order, int trans_a, int trans_b, int m,
                              int n, int k, float alpha, const float* a,
                              int lda, const float* b, int ldb, float beta,
                              float* c, int ldc);
static cblas_sgemm_t g_sgemm = nullptr;

void set_sgemm(void* fn) { g_sgemm = (cblas_sgemm_t)fn; }
int has_sgemm() { return g_sgemm != nullptr; }

// G(rows, cols) = A(rows, m) @ B(cols, m)^T — BLAS when registered, else a
// blocked dot-product fallback (auto-vectorized; only hosts where scipy's
// OpenBLAS could not be located pay it).
static void gemm_nt(const float* A, const float* B, float* G, int64_t rows,
                    int64_t cols, int64_t m) {
  if (g_sgemm) {
    g_sgemm(101, 111, 112, (int)rows, (int)cols, (int)m, 1.0f, A, (int)m, B,
            (int)m, 0.0f, G, (int)cols);
    return;
  }
  const int64_t BI = 64, BJ = 48;
  for (int64_t i0 = 0; i0 < rows; i0 += BI) {
    const int64_t i1 = std::min(rows, i0 + BI);
    for (int64_t j0 = 0; j0 < cols; j0 += BJ) {
      const int64_t j1 = std::min(cols, j0 + BJ);
      for (int64_t i = i0; i < i1; ++i) {
        const float* a = A + i * m;
        float* g = G + i * cols;
        for (int64_t j = j0; j < j1; ++j) {
          const float* b = B + j * m;
          float s = 0.0f;
          for (int64_t f = 0; f < m; ++f) s += a[f] * b[f];
          g[j] = s;
        }
      }
    }
  }
}

// The whole `_native_lloyd_run_batched` loop in one call: every restart
// advances in lockstep (one (n, A·k) GEMM per iteration over the still-
// active restarts), with the host runner's exact semantics — δ-window
// uniform pick, true-minima inertia, empty-cluster relocation onto the
// highest-min_d2 points, per-restart best-inertia tracking, shift ≤ tol and
// best-inertia-plateau (patience) stopping, NaN-padded history traces, and
// the final best-of-(last, best) exact re-evaluation per restart with
// window-mode labeling of the single global winner.
//
// In/out: C (R, k, m) holds the initial centers and is left holding each
// restart's LAST centers; out_centers gets the winner's chosen centers.
// inertia_tr / shift_tr are (R, max_iter) float32, prefilled with NaN by the
// caller. patience < 0 disables the plateau rule. Returns 0 on success.
int lloyd_run_batched(const float* X, const float* sample_weight,
                      const float* xsq, float* C, int64_t n, int64_t m,
                      int64_t k, int64_t R, double window, uint64_t seed,
                      int64_t max_iter, double tol, int64_t patience,
                      int32_t* out_labels, float* out_centers,
                      double* out_final, float* inertia_tr, float* shift_tr,
                      int64_t* out_iters, int64_t* out_winner,
                      double* out_winner_inertia, int n_threads) {
  if (n <= 0 || m <= 0 || k <= 0 || R <= 0 || max_iter < 0) return -1;
  const bool auto_threads = n_threads <= 0;
  if (auto_threads) {
    n_threads = (int)std::thread::hardware_concurrency();
    if (n_threads <= 0) n_threads = 1;
    // below ~4M scan ops per iteration the per-iteration thread
    // create/join churn (the pool is not persistent) costs more than the
    // parallelism buys — small fits stay serial in auto mode
    if (n * R * k * (m / 8 + 1) < (int64_t)(4LL << 20)) n_threads = 1;
  }
  {
    const int64_t nch = (n + 255) / 256;  // one row-chunk per thread max
    if ((int64_t)n_threads > nch) n_threads = (int)nch;
    // each extra thread replicates the (R*k, m) double accumulator and
    // adds a serial reduction pass — cap the replication at ~256 MB and
    // never let reduction work rival the scan it parallelizes
    const int64_t repl = std::max((int64_t)1,
                                  (int64_t)(32LL << 20) / (R * k * m));
    if ((int64_t)n_threads > repl) n_threads = (int)repl;
  }

  const int64_t km = k * m;
  std::vector<float> best_centers(C, C + R * km);  // snapshot at best it
  std::vector<double> best_inertia(R, 1e300);
  std::vector<int64_t> best_it(R, 0), it_count(R, 0);
  std::vector<char> active(R, 1);
  std::vector<int64_t> act(R);
  std::vector<float> Call(R * km);        // gathered active centers
  std::vector<float> G(n * R * k);        // X @ Call^T
  std::vector<double> csq(R * k);
  std::vector<double> sums(R * km), counts(R * k), inertia(R);
  std::vector<int32_t> labels(n * R);
  std::vector<float> min_d2(n * R);
  std::vector<int64_t> order;             // relocation candidate scratch

  auto pick_rng = [seed](uint64_t it, uint64_t r, uint64_t row) {
    uint64_t x = splitmix64(seed ^ it);
    x = splitmix64(x ^ (r + 1));
    return splitmix64(x ^ row);
  };

  // thread-local accumulators, allocated ONCE for the whole run (worst
  // case A == R); zeroed per iteration only over the active prefix
  std::vector<std::vector<double>> t_sums, t_counts, t_inertia;
  for (int t = 1; t < n_threads; ++t) {  // thread 0 uses the main buffers
    t_sums.emplace_back(R * km, 0.0);
    t_counts.emplace_back(R * k, 0.0);
    t_inertia.emplace_back(R, 0.0);
  }

  // One windowed E pass of restart r at `centers`, accumulating partials
  // and inertia; shared by the iteration loop (emit=true) and the final
  // re-evaluations (emit=false: exact inertia only).
  // (kept inline in the loop below for cache locality; see scan lambda)

  int64_t it = 0;
  while (it < max_iter) {
    int64_t A = 0;
    for (int64_t r = 0; r < R; ++r)
      if (active[r]) act[A++] = r;
    if (A == 0) break;
    for (int64_t a = 0; a < A; ++a)
      std::memcpy(Call.data() + a * km, C + act[a] * km,
                  sizeof(float) * km);
    const int64_t cols = A * k;
    for (int64_t c = 0; c < cols; ++c) {
      const float* cc = Call.data() + c * m;
      double s = 0.0;
      for (int64_t f = 0; f < m; ++f) s += (double)cc[f] * cc[f];
      csq[c] = s;
    }
    gemm_nt(X, Call.data(), G.data(), n, cols, m);
    std::fill(sums.begin(), sums.begin() + cols * m, 0.0);
    std::fill(counts.begin(), counts.begin() + cols, 0.0);
    std::fill(inertia.begin(), inertia.begin() + A, 0.0);

    // E-scan over rows: threaded with per-thread partial sums (the same
    // thread-local-buffers + serial reduction shape as lloyd_iter_chunked)
    auto scan_rows = [&](int64_t lo, int64_t hi, double* p_sums,
                         double* p_counts, double* p_inertia) {
      for (int64_t i = lo; i < hi; ++i) {
        const float* g = G.data() + i * cols;
        const float* x = X + i * m;
        const double w = sample_weight ? (double)sample_weight[i] : 1.0;
        const double xs = (double)xsq[i];
        for (int64_t a = 0; a < A; ++a) {
          const double* cs = csq.data() + a * k;
          const float* ga = g + a * k;
          double best = 1e300;
          int32_t best_j = 0;
          for (int64_t j = 0; j < k; ++j) {
            const double d = cs[j] - 2.0 * (double)ga[j];
            if (d < best) { best = d; best_j = (int32_t)j; }
          }
          int32_t pick = best_j;
          if (window > 0.0 && k > 1) {
            int64_t cnt = 0;
            for (int64_t j = 0; j < k; ++j)
              cnt += (cs[j] - 2.0 * (double)ga[j] <= best + window);
            if (cnt > 1) {
              uint64_t rr = pick_rng((uint64_t)it, (uint64_t)act[a],
                                     (uint64_t)i) % (uint64_t)cnt;
              for (int64_t j = 0; j < k; ++j) {
                if (cs[j] - 2.0 * (double)ga[j] <= best + window &&
                    rr-- == 0) { pick = (int32_t)j; break; }
              }
            }
          }
          labels[i * R + act[a]] = pick;
          min_d2[i * R + act[a]] = (float)(best + xs);
          double* sa = p_sums + (a * k + pick) * m;
          for (int64_t f = 0; f < m; ++f) sa[f] += w * (double)x[f];
          p_counts[a * k + pick] += w;
          p_inertia[a] += w * (best + xs);
        }
      }
    };
    if (n_threads <= 1) {
      scan_rows(0, n, sums.data(), counts.data(), inertia.data());
    } else {
      const int64_t chunk = 256, n_chunks = (n + chunk - 1) / chunk;
      for (auto& v : t_sums) std::fill(v.begin(), v.begin() + cols * m, 0.0);
      for (auto& v : t_counts) std::fill(v.begin(), v.begin() + cols, 0.0);
      for (auto& v : t_inertia) std::fill(v.begin(), v.begin() + A, 0.0);
      auto t_buf = [&](int t) {  // thread 0 accumulates straight into main
        return t == 0 ? std::make_tuple(sums.data(), counts.data(),
                                        inertia.data())
                      : std::make_tuple(t_sums[t - 1].data(),
                                        t_counts[t - 1].data(),
                                        t_inertia[t - 1].data());
      };
      // STATIC strided chunk->thread assignment (not a work queue): each
      // thread's chunk set — and therefore each accumulator's reduction
      // order — is a pure function of (n, n_threads), keeping fits
      // bit-reproducible at a fixed seed and thread count regardless of
      // OS scheduling. Stride keeps the load balanced like the queue did.
      std::vector<std::thread> pool;
      for (int t = 0; t < n_threads; ++t) {
        pool.emplace_back([&, t]() {
          auto [ps, pc, pi] = t_buf(t);
          for (int64_t c0 = t; c0 < n_chunks; c0 += n_threads)
            scan_rows(c0 * chunk, std::min(n, (c0 + 1) * chunk), ps, pc, pi);
        });
      }
      for (auto& th : pool) th.join();
      for (int t = 1; t < n_threads; ++t) {
        for (int64_t e = 0; e < cols * m; ++e) sums[e] += t_sums[t - 1][e];
        for (int64_t e = 0; e < cols; ++e) counts[e] += t_counts[t - 1][e];
        for (int64_t a = 0; a < A; ++a) inertia[a] += t_inertia[t - 1][a];
      }
    }

    for (int64_t a = 0; a < A; ++a) {
      const int64_t r = act[a];
      double* sa = sums.data() + a * km;
      double* ca = counts.data() + a * k;
      // empty-cluster relocation (reference _k_means_fast.pyx:162 role):
      // each empty cluster takes the not-yet-taken point with the largest
      // weighted-eligible min_d2; its donor cluster gives the point up
      int64_t n_empty = 0;
      for (int64_t j = 0; j < k; ++j) n_empty += (ca[j] <= 0.0);
      if (n_empty > 0) {
        order.resize(n);
        for (int64_t i = 0; i < n; ++i) order[i] = i;
        const int64_t take = std::min(n_empty, n);
        const float* md = min_d2.data();
        const float* sw = sample_weight;
        auto better_cand = [md, sw, R, r](int64_t p, int64_t q) {
          const bool pe = !sw || sw[p] > 0.0f, qe = !sw || sw[q] > 0.0f;
          const double ps = pe ? (double)md[p * R + r] : -1e300;
          const double qs = qe ? (double)md[q * R + r] : -1e300;
          if (ps != qs) return ps > qs;
          return p < q;  // deterministic tie order
        };
        std::partial_sort(order.begin(), order.begin() + take, order.end(),
                          better_cand);
        // snapshot the originally-empty set before relocating (matches the
        // NumPy twin _relocate_empty_np): a donor drained to exactly zero
        // weight mid-pass must not absorb a candidate meant for a
        // later originally-empty cluster
        std::vector<int64_t> empty_js;
        empty_js.reserve(n_empty);
        for (int64_t j = 0; j < k; ++j)
          if (ca[j] <= 0.0) empty_js.push_back(j);
        int64_t t = 0;
        for (const int64_t j : empty_js) {
          if (t >= take) break;
          const int64_t p = order[t++];
          if ((sample_weight && sample_weight[p] <= 0.0f)) continue;
          const double wp = sample_weight ? (double)sample_weight[p] : 1.0;
          const int32_t donor = labels[p * R + r];
          const float* xp = X + p * m;
          double* sd = sa + (int64_t)donor * m;
          double* sj = sa + j * m;
          for (int64_t f = 0; f < m; ++f) {
            sd[f] -= wp * (double)xp[f];
            sj[f] = wp * (double)xp[f];
          }
          ca[donor] -= wp;
          ca[j] = wp;
        }
      }
      // M-step + shift + best tracking + traces + stopping
      float* cr = C + r * km;
      if (inertia[a] < best_inertia[r]) {
        best_inertia[r] = inertia[a];
        std::memcpy(best_centers.data() + r * km, cr, sizeof(float) * km);
        best_it[r] = it;
      }
      double shift = 0.0;
      for (int64_t j = 0; j < k; ++j) {
        float* cj = cr + j * m;
        if (ca[j] > 0.0) {
          const double inv = 1.0 / ca[j];
          for (int64_t f = 0; f < m; ++f) {
            const float nv = (float)(sa[j * m + f] * inv);
            const double dd = (double)nv - (double)cj[f];
            shift += dd * dd;
            cj[f] = nv;
          }
        }  // empty with no candidate: center stays, contributes no shift
      }
      inertia_tr[r * max_iter + it] = (float)inertia[a];
      shift_tr[r * max_iter + it] = (float)shift;
      it_count[r] = it + 1;
      if (shift <= tol) active[r] = 0;
      if (patience >= 0 && (it + 1 - best_it[r]) > patience) active[r] = 0;
    }
    ++it;
  }

  // Exact per-restart re-evaluation of (last, best) candidates, then the
  // global winner. One (n, R·k) GEMM per candidate set.
  std::vector<double> inert_last(R, 0.0), inert_best(R, 0.0);
  const float* cand_sets[2] = {C, best_centers.data()};
  std::vector<double>* cand_out[2] = {&inert_last, &inert_best};
  for (int s = 0; s < 2; ++s) {
    const float* CS = cand_sets[s];
    const int64_t cols = R * k;
    for (int64_t c = 0; c < cols; ++c) {
      const float* cc = CS + c * m;
      double v = 0.0;
      for (int64_t f = 0; f < m; ++f) v += (double)cc[f] * cc[f];
      csq[c] = v;
    }
    gemm_nt(X, CS, G.data(), n, cols, m);
    std::vector<double>& out = *cand_out[s];
    for (int64_t i = 0; i < n; ++i) {
      const float* g = G.data() + i * cols;
      const double w = sample_weight ? (double)sample_weight[i] : 1.0;
      const double xs = (double)xsq[i];
      for (int64_t r = 0; r < R; ++r) {
        double best = 1e300;
        for (int64_t j = 0; j < k; ++j) {
          const double d = csq[r * k + j] - 2.0 * (double)g[r * k + j];
          if (d < best) best = d;
        }
        out[r] += w * (best + xs);
      }
    }
  }
  int64_t r_star = 0;
  double fin_star = 1e300;
  for (int64_t r = 0; r < R; ++r) {
    const double fin = std::min(inert_last[r], inert_best[r]);
    out_final[r] = fin;
    out_iters[r] = it_count[r];
    if (fin < fin_star) { fin_star = fin; r_star = r; }
  }
  const float* c_star = (inert_last[r_star] <= inert_best[r_star]
                             ? C : best_centers.data()) + r_star * km;
  std::memcpy(out_centers, c_star, sizeof(float) * km);

  // window-mode labeling of the winner (the host runner's final E pass)
  const uint64_t fseed = splitmix64(seed ^ 0x517cc1b727220a95ULL);
  for (int64_t c = 0; c < k; ++c) {
    const float* cc = c_star + c * m;
    double v = 0.0;
    for (int64_t f = 0; f < m; ++f) v += (double)cc[f] * cc[f];
    csq[c] = v;
  }
  gemm_nt(X, c_star, G.data(), n, k, m);
  double win_inertia = 0.0;
  for (int64_t i = 0; i < n; ++i) {
    const float* g = G.data() + i * k;
    double best = 1e300;
    int32_t best_j = 0;
    for (int64_t j = 0; j < k; ++j) {
      const double d = csq[j] - 2.0 * (double)g[j];
      if (d < best) { best = d; best_j = (int32_t)j; }
    }
    int32_t pick = best_j;
    if (window > 0.0 && k > 1) {
      int64_t cnt = 0;
      for (int64_t j = 0; j < k; ++j)
        cnt += (csq[j] - 2.0 * (double)g[j] <= best + window);
      if (cnt > 1) {
        uint64_t rr = splitmix64(fseed ^ (uint64_t)i) % (uint64_t)cnt;
        for (int64_t j = 0; j < k; ++j) {
          if (csq[j] - 2.0 * (double)g[j] <= best + window && rr-- == 0) {
            pick = (int32_t)j;
            break;
          }
        }
      }
    }
    out_labels[i] = pick;
    const double w = sample_weight ? (double)sample_weight[i] : 1.0;
    win_inertia += w * (best + (double)xsq[i]);
  }
  *out_winner = r_star;
  *out_winner_inertia = win_inertia;
  return 0;
}

// ---------------------------------------------------------------------------
// ArgKmin — k nearest training rows per query (brute force, chunked)
// ---------------------------------------------------------------------------

// The role of the reference's tree/brute neighbor kernels
// (neighbors/_ball_tree.pyx, _kd_tree.pyx; sklearn's chunked ArgKmin):
// blocked ‖c‖²−2x·c GEMM with a per-row bounded max-heap of size k, so the
// (n_q, n_tr) distance matrix never materializes. Returns indices sorted by
// ascending exact distance (+ xsq_q added at the end; ties keep the
// lower train index). Threads stride over query chunks (deterministic).
int argkmin(const float* Xtr, const float* xsq_tr, const float* Xq,
            const float* xsq_q, int64_t n_tr, int64_t n_q, int64_t m,
            int64_t k, int64_t* out_idx, float* out_d2, int n_threads) {
  if (n_tr <= 0 || n_q <= 0 || m <= 0 || k <= 0 || k > n_tr) return -1;
  if (n_threads <= 0) {
    n_threads = (int)std::thread::hardware_concurrency();
    if (n_threads <= 0) n_threads = 1;
  }
  const int64_t QB = 128, TB = 4096;
  const int64_t n_chunks = (n_q + QB - 1) / QB;
  if ((int64_t)n_threads > n_chunks) n_threads = (int)n_chunks;

  auto worker = [&](int tid) {
    std::vector<float> G(QB * TB);
    // heap entries per in-chunk row: (d2 w/o xsq_q, train idx)
    std::vector<double> hd(QB * k);
    std::vector<int64_t> hi(QB * k);
    for (int64_t c0 = tid; c0 < n_chunks; c0 += n_threads) {
      const int64_t q0 = c0 * QB, q1 = std::min(n_q, q0 + QB);
      const int64_t nq = q1 - q0;
      std::fill(hd.begin(), hd.begin() + nq * k, 1e300);
      std::fill(hi.begin(), hi.begin() + nq * k, (int64_t)-1);
      for (int64_t t0 = 0; t0 < n_tr; t0 += TB) {
        const int64_t t1 = std::min(n_tr, t0 + TB);
        const int64_t nt = t1 - t0;
        gemm_nt(Xq + q0 * m, Xtr + t0 * m, G.data(), nq, nt, m);
        for (int64_t i = 0; i < nq; ++i) {
          double* h = hd.data() + i * k;
          int64_t* hx = hi.data() + i * k;
          const float* g = G.data() + i * nt;
          for (int64_t j = 0; j < nt; ++j) {
            const double d = (double)xsq_tr[t0 + j] - 2.0 * (double)g[j];
            if (d >= h[0]) continue;  // h[0] is the current k-th smallest
            // Replace the heap max with the new entry and sift it down.
            // The heap orders by (d, idx) LEXICOGRAPHICALLY — among tied
            // distances the largest index sits closest to the root and is
            // evicted first — so the kept set is exactly the k smallest
            // (d, idx) pairs: stable-argsort tie semantics. (Candidates
            // arrive in ascending index order, so `d >= h[0]` is already
            // the correct lexicographic eviction test.)
            h[0] = d;
            hx[0] = t0 + j;
            int64_t pos = 0;
            auto lex_gt = [&](int64_t a, int64_t bb) {
              return h[a] > h[bb] || (h[a] == h[bb] && hx[a] > hx[bb]);
            };
            for (;;) {
              const int64_t l = 2 * pos + 1, r = l + 1;
              int64_t big = pos;
              if (l < k && lex_gt(l, big)) big = l;
              if (r < k && lex_gt(r, big)) big = r;
              if (big == pos) break;
              std::swap(h[pos], h[big]);
              std::swap(hx[pos], hx[big]);
              pos = big;
            }
          }
        }
      }
      // heap -> ascending order; ties by lower train index
      std::vector<int64_t> ord(k);
      for (int64_t i = 0; i < nq; ++i) {
        double* h = hd.data() + i * k;
        int64_t* hx = hi.data() + i * k;
        for (int64_t e = 0; e < k; ++e) ord[e] = e;
        std::sort(ord.begin(), ord.end(), [&](int64_t a, int64_t b) {
          if (h[a] != h[b]) return h[a] < h[b];
          return hx[a] < hx[b];
        });
        const double xq = (double)xsq_q[q0 + i];
        for (int64_t e = 0; e < k; ++e) {
          out_idx[(q0 + i) * k + e] = hx[ord[e]];
          out_d2[(q0 + i) * k + e] =
              (float)std::max(0.0, h[ord[e]] + xq);
        }
      }
    }
  };
  if (n_threads <= 1) {
    worker(0);
  } else {
    std::vector<std::thread> pool;
    for (int t = 0; t < n_threads; ++t) pool.emplace_back(worker, t);
    for (auto& th : pool) th.join();
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Batched greedy k-means++ init (D² sampling, best-of-n_trials)
// ---------------------------------------------------------------------------

static inline double u01(uint64_t x) {  // uniform in [0, 1)
  return (double)(x >> 11) * (1.0 / 9007199254740992.0);
}

// R independent greedy k-means++ inits (the host twin of
// `_kmeans_plusplus_np`: weighted first pick, then k-1 rounds of D²
// sampling over `n_trials` candidates keeping the one that minimizes the
// would-be potential). out_centers: (R, k, m). Candidate draws come from
// SplitMix64 streams keyed on (seed, restart, round) — same distribution
// as the NumPy twin, different stream.
int kmeans_pp_batched(const float* X, const float* sample_weight,
                      const float* xsq, int64_t n, int64_t m, int64_t k,
                      int64_t R, int64_t n_trials, uint64_t seed,
                      float* out_centers, int n_threads) {
  if (n <= 0 || m <= 0 || k <= 0 || R <= 0 || n_trials <= 0) return -1;
  if (n_threads <= 0) {
    n_threads = (int)std::thread::hardware_concurrency();
    if (n_threads <= 0) n_threads = 1;
  }
  if ((int64_t)n_threads > R) n_threads = (int)R;
  {
    // per-worker scratch is 4 n-double vectors + the (n, n_trials) GEMM
    // output + (n_trials, m) candidate rows — bound total replication at
    // ~256 MB, as the Lloyd runner does for its accumulators
    const int64_t per_worker = 32 * n + 4 * n * n_trials + 4 * n_trials * m;
    const int64_t cap = std::max(
        (int64_t)1, (int64_t)(256LL << 20) / std::max(per_worker, (int64_t)1));
    if ((int64_t)n_threads > cap) n_threads = (int)cap;
  }
  std::vector<double> cumw(n);
  double wtot = 0.0;
  for (int64_t i = 0; i < n; ++i) {
    wtot += sample_weight ? (double)sample_weight[i] : 1.0;
    cumw[i] = wtot;
  }
  if (wtot <= 0.0) return -2;

  // restarts are independent streams — parallelize across them (BLAS
  // calls from concurrent threads are safe; OpenBLAS serializes its own
  // pool). Results are identical at any thread count: each restart's
  // stream is keyed on (seed, r) alone.
  auto run_restart = [&](int64_t r, std::vector<double>& cum,
                         std::vector<float>& cand_rows, std::vector<float>& D,
                         std::vector<int64_t>& cand,
                         std::vector<double>& closest,
                         std::vector<double>& newc_best,
                         std::vector<double>& newc) {
    uint64_t st = splitmix64(seed ^ splitmix64((uint64_t)r + 0x9E37ULL));
    auto next_u01 = [&st]() {
      st = splitmix64(st);
      return u01(st);
    };
    // weighted first center
    const double u0 = next_u01() * wtot;
    int64_t first = (int64_t)(std::lower_bound(cumw.begin(), cumw.end(), u0)
                              - cumw.begin());
    if (first >= n) first = n - 1;
    float* C = out_centers + r * k * m;
    std::memcpy(C, X + first * m, sizeof(float) * m);
    gemm_nt(X, X + first * m, D.data(), n, 1, m);
    for (int64_t i = 0; i < n; ++i)
      closest[i] = std::max(
          0.0, (double)xsq[i] + (double)xsq[first] - 2.0 * (double)D[i]);

    for (int64_t c = 1; c < k; ++c) {
      double tot = 0.0;
      for (int64_t i = 0; i < n; ++i) {
        const double w = sample_weight ? (double)sample_weight[i] : 1.0;
        tot += closest[i] * w;
        cum[i] = tot;
      }
      for (int64_t t = 0; t < n_trials; ++t) {
        const double u = next_u01() * tot;
        int64_t idx = (int64_t)(std::lower_bound(cum.begin(), cum.end(), u)
                                - cum.begin());
        if (idx >= n) idx = n - 1;
        cand[t] = idx;
        std::memcpy(cand_rows.data() + t * m, X + idx * m,
                    sizeof(float) * m);
      }
      gemm_nt(X, cand_rows.data(), D.data(), n, n_trials, m);
      double best_score = 1e300;
      int64_t best_t = 0;
      for (int64_t t = 0; t < n_trials; ++t) {
        const double cxsq = (double)xsq[cand[t]];
        double score = 0.0;
        for (int64_t i = 0; i < n; ++i) {
          const double d2 = std::max(
              0.0, (double)xsq[i] + cxsq - 2.0 * (double)D[i * n_trials + t]);
          const double v = std::min(closest[i], d2);
          newc[i] = v;
          score += v * (sample_weight ? (double)sample_weight[i] : 1.0);
        }
        if (score < best_score) {
          best_score = score;
          best_t = t;
          std::swap(newc, newc_best);
        }
      }
      closest.swap(newc_best);
      std::memcpy(C + c * m, X + cand[best_t] * m, sizeof(float) * m);
    }
  };

  auto worker = [&](int64_t r0, int64_t r1) {
    std::vector<double> cum(n), closest(n), newc_best(n), newc(n);
    std::vector<float> cand_rows(n_trials * m), D(n * n_trials);
    std::vector<int64_t> cand(n_trials);
    for (int64_t r = r0; r < r1; ++r)
      run_restart(r, cum, cand_rows, D, cand, closest, newc_best, newc);
  };
  if (n_threads <= 1) {
    worker(0, R);
  } else {
    std::vector<std::thread> pool;
    const int64_t per = (R + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; ++t) {
      const int64_t r0 = t * per, r1 = std::min(R, r0 + per);
      if (r0 >= r1) break;
      pool.emplace_back(worker, r0, r1);
    }
    for (auto& th : pool) th.join();
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Elkan iteration (triangle-inequality-pruned classical E-step)
// ---------------------------------------------------------------------------

static inline double sq_dist(const float* x, const float* c, int64_t m) {
  double s = 0.0;
  for (int64_t f = 0; f < m; ++f) {
    double d = (double)x[f] - c[f];
    s += d * d;
  }
  return s;
}

// One Elkan E-step (Elkan 2003; the reference ships it as
// cluster/_k_means_elkan.pyx `elkan_iter_chunked_dense:184`). Works in plain
// (not squared) distance space. Persistent per-point state owned by the
// caller across iterations:
//   labels (n) int32, upper (n) float32 — upper bound on d(x, c_label),
//   lower (n, k) float32 — lower bounds on d(x, c_j).
// Caller-computed per-iteration center geometry:
//   c_half (k, k) = 0.5 * d(c_a, c_j); s (k) = 0.5 * min_{j!=a} d(c_a, c_j).
// With init != 0 all n*k distances are computed to seed the bounds (the
// role of `init_bounds_dense:33`). On exit `upper` is the EXACT assigned
// distance for every point (one extra m-dot for pruned points — ~1/k of the
// work saved — which keeps bounds tight and yields exact per-iteration
// inertia, unlike the reference, which only computes inertia after the
// loop). Outputs match lloyd_iter_window: weighted partial sums/counts,
// exact min_d2 (squared), weighted inertia.
int elkan_iter(const float* X, const float* sample_weight,
               const float* centers, const float* c_half, const float* s,
               int64_t n, int64_t m, int64_t k, int32_t* labels, float* upper,
               float* lower, int init, float* out_min_d2, double* out_sums,
               double* out_counts, double* out_inertia, int n_threads) {
  if (n <= 0 || m <= 0 || k <= 0) return -1;
  if (n_threads <= 0) {
    n_threads = (int)std::thread::hardware_concurrency();
    if (n_threads <= 0) n_threads = 1;
  }
  if ((int64_t)n_threads > n) n_threads = (int)n;
  {
    const int64_t nch = (n + 255) / 256;
    if ((int64_t)n_threads > nch) n_threads = (int)nch;
  }

  const int64_t chunk = 256;
  std::atomic<int64_t> next_chunk{0};
  const int64_t n_chunks = (n + chunk - 1) / chunk;

  std::vector<std::vector<double>> t_sums((size_t)n_threads,
                                          std::vector<double>(k * m, 0.0));
  std::vector<std::vector<double>> t_counts((size_t)n_threads,
                                            std::vector<double>(k, 0.0));
  std::vector<double> t_inertia((size_t)n_threads, 0.0);

  auto worker = [&](int tid) {
    std::vector<double>& sums = t_sums[tid];
    std::vector<double>& counts = t_counts[tid];
    double inertia = 0.0;
    for (;;) {
      int64_t c0 = next_chunk.fetch_add(1);
      if (c0 >= n_chunks) break;
      int64_t lo = c0 * chunk, hi = std::min(n, lo + chunk);
      for (int64_t i = lo; i < hi; ++i) {
        const float* x = X + i * m;
        float* lb = lower + i * k;
        int32_t a;
        float u;
        if (init) {
          double best = 1e300;
          a = 0;
          for (int64_t j = 0; j < k; ++j) {
            double d = std::sqrt(sq_dist(x, centers + j * m, m));
            lb[j] = (float)d;
            if (d < best) { best = d; a = (int32_t)j; }
          }
          u = (float)best;
        } else {
          a = labels[i];
          u = upper[i];
          if (u > s[a]) {
            // u is inflated by the last center shift; tighten lazily on
            // the first center that survives the bound tests
            bool tight = false;
            for (int64_t j = 0; j < k; ++j) {
              if ((int32_t)j == a) continue;
              if (u > lb[j] && u > c_half[(int64_t)a * k + j]) {
                if (!tight) {
                  u = (float)std::sqrt(sq_dist(x, centers + (int64_t)a * m, m));
                  lb[a] = u;
                  tight = true;
                  if (!(u > lb[j] && u > c_half[(int64_t)a * k + j])) continue;
                }
                float d = (float)std::sqrt(sq_dist(x, centers + j * m, m));
                lb[j] = d;
                if (d < u) { u = d; a = (int32_t)j; }
              }
            }
            if (!tight) {
              // every candidate was pruned by the bounds alone; one exact
              // dot keeps `upper` tight for the next iteration
              u = (float)std::sqrt(sq_dist(x, centers + (int64_t)a * m, m));
              lb[a] = u;
            }
          } else {
            u = (float)std::sqrt(sq_dist(x, centers + (int64_t)a * m, m));
            lb[a] = u;
          }
        }
        labels[i] = a;
        upper[i] = u;
        double md2 = (double)u * u;
        if (out_min_d2) out_min_d2[i] = (float)md2;
        double w = sample_weight ? (double)sample_weight[i] : 1.0;
        for (int64_t f = 0; f < m; ++f) sums[(int64_t)a * m + f] += w * x[f];
        counts[a] += w;
        inertia += w * md2;
      }
    }
    t_inertia[tid] = inertia;
  };

  std::vector<std::thread> threads;
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker, t);
  for (auto& th : threads) th.join();

  std::memset(out_sums, 0, sizeof(double) * k * m);
  std::memset(out_counts, 0, sizeof(double) * k);
  double inertia = 0.0;
  for (int t = 0; t < n_threads; ++t) {
    for (int64_t e = 0; e < k * m; ++e) out_sums[e] += t_sums[t][e];
    for (int64_t j = 0; j < k; ++j) out_counts[j] += t_counts[t][j];
    inertia += t_inertia[t];
  }
  *out_inertia = inertia;
  return 0;
}

// ---------------------------------------------------------------------------
// MurmurHash3 x86 32-bit (public domain algorithm, Austin Appleby)
// ---------------------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

uint32_t murmurhash3_x86_32(const void* key, int len, uint32_t seed) {
  const uint8_t* data = (const uint8_t*)key;
  const int nblocks = len / 4;
  uint32_t h1 = seed;
  const uint32_t c1 = 0xcc9e2d51, c2 = 0x1b873593;

  for (int i = 0; i < nblocks; ++i) {
    uint32_t k1;
    std::memcpy(&k1, data + i * 4, 4);
    k1 *= c1; k1 = rotl32(k1, 15); k1 *= c2;
    h1 ^= k1; h1 = rotl32(h1, 13); h1 = h1 * 5 + 0xe6546b64;
  }

  const uint8_t* tail = data + nblocks * 4;
  uint32_t k1 = 0;
  switch (len & 3) {
    case 3: k1 ^= (uint32_t)tail[2] << 16; [[fallthrough]];
    case 2: k1 ^= (uint32_t)tail[1] << 8; [[fallthrough]];
    case 1: k1 ^= tail[0];
      k1 *= c1; k1 = rotl32(k1, 15); k1 *= c2; h1 ^= k1;
  }

  h1 ^= (uint32_t)len;
  h1 ^= h1 >> 16; h1 *= 0x85ebca6b; h1 ^= h1 >> 13;
  h1 *= 0xc2b2ae35; h1 ^= h1 >> 16;
  return h1;
}

// Hash `count` NUL-separated strings from a packed buffer; offsets has
// count+1 entries into buf.
void murmurhash3_bulk(const char* buf, const int64_t* offsets, int64_t count,
                      uint32_t seed, uint32_t* out) {
  for (int64_t i = 0; i < count; ++i) {
    out[i] = murmurhash3_x86_32(buf + offsets[i],
                                (int)(offsets[i + 1] - offsets[i]), seed);
  }
}

// ---------------------------------------------------------------------------
// CSV float ingest
// ---------------------------------------------------------------------------

// Whitespace-only (incl. CRLF) line — skipped by every reader so the
// native and fallback paths agree on row counts.
static bool csv_blank_line(const char* line, ssize_t len) {
  for (ssize_t i = 0; i < len; ++i) {
    char ch = line[i];
    if (ch == '\0') break;
    if (ch != '\n' && ch != '\r' && ch != ' ' && ch != '\t') return false;
  }
  return true;
}

// Count data rows and columns of a delimiter-separated numeric file.
// Returns 0 on success; n_rows excludes `skip_header` lines.
int csv_shape(const char* path, char delim, int skip_header, int64_t* n_rows,
              int64_t* n_cols) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  char* line = nullptr;
  size_t cap = 0;
  int64_t rows = 0, cols = 0;
  int skipped = 0;
  ssize_t len;
  while ((len = getline(&line, &cap, f)) != -1) {
    if (skipped < skip_header) { ++skipped; continue; }
    if (csv_blank_line(line, len)) continue;
    if (rows == 0) {
      cols = 1;
      for (ssize_t i = 0; i < len; ++i)
        if (line[i] == delim) ++cols;
    }
    ++rows;
  }
  std::free(line);
  std::fclose(f);
  *n_rows = rows;
  *n_cols = cols;
  return 0;
}

// Parse one CSV line into n_cols float32 fields. Non-numeric fields parse
// as NaN (strtof stops at junk; empty fields / text labels -> NaN, caller
// decides). One definition for the one-shot and streaming readers.
static void parse_csv_line(char* line, char delim, float* out,
                           int64_t n_cols) {
  char* p = line;
  for (int64_t c = 0; c < n_cols; ++c) {
    char* end = p;
    float v = strtof(p, &end);
    if (end == p) {  // non-numeric field
      v = NAN;
      while (*end && *end != delim && *end != '\n') ++end;
    }
    out[c] = v;
    p = end;
    while (*p && *p != delim && *p != '\n') ++p;
    if (*p == delim) ++p;
  }
}

// Parse the file into a preallocated (n_rows, n_cols) float32 row-major
// buffer. Returns number of rows parsed, or -1 on IO error.
int64_t csv_parse_floats(const char* path, char delim, int skip_header,
                         float* out, int64_t max_rows, int64_t n_cols) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  char* line = nullptr;
  size_t cap = 0;
  int64_t row = 0;
  int skipped = 0;
  ssize_t len;
  while (row < max_rows && (len = getline(&line, &cap, f)) != -1) {
    if (skipped < skip_header) { ++skipped; continue; }
    if (csv_blank_line(line, len)) continue;
    parse_csv_line(line, delim, out + row * n_cols, n_cols);
    ++row;
  }
  std::free(line);
  std::fclose(f);
  return row;
}

// ---------------------------------------------------------------------------
// Streaming CSV batches — a stateful reader handle so larger-than-memory
// files feed incremental fits (MiniBatch partial_fit) batch by batch
// without re-scanning from the top per batch.
// ---------------------------------------------------------------------------

struct CsvStream {
  FILE* f;
  char delim;
  char* line;
  size_t cap;
};

// Open a stream positioned past `skip_header` lines; returns nullptr on IO
// error. Close with csv_stream_close.
void* csv_stream_open(const char* path, char delim, int skip_header) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  char* line = nullptr;
  size_t cap = 0;
  for (int i = 0; i < skip_header; ++i) {
    if (getline(&line, &cap, f) == -1) break;
  }
  CsvStream* s = new CsvStream{f, delim, line, cap};
  return s;
}

// Parse up to max_rows rows into the preallocated row-major float32 buffer
// (same field semantics as csv_parse_floats). Returns rows parsed — 0 at
// EOF — or -1 on a null handle.
int64_t csv_stream_next(void* handle, float* out, int64_t max_rows,
                        int64_t n_cols) {
  CsvStream* s = static_cast<CsvStream*>(handle);
  if (!s) return -1;
  int64_t row = 0;
  ssize_t len;
  while (row < max_rows && (len = getline(&s->line, &s->cap, s->f)) != -1) {
    char* line = s->line;
    if (csv_blank_line(line, len)) continue;
    parse_csv_line(line, s->delim, out + row * n_cols, n_cols);
    ++row;
  }
  return row;
}

void csv_stream_close(void* handle) {
  CsvStream* s = static_cast<CsvStream*>(handle);
  if (!s) return;
  std::free(s->line);
  std::fclose(s->f);
  delete s;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// CRC-32 (zlib polynomial 0xEDB88320 — bit-identical to zlib.crc32)
// ---------------------------------------------------------------------------
//
// The out-of-core shard store CRC-verifies every materialized shard read
// (oocore/store.py); the image's zlib 1.2.11 computes crc32 at ~1 GB/s
// (slice-by-4), which made manifest verification the dominant cost of a
// warm-page-cache store walk. Two implementations, picked at runtime:
//
//  - PCLMUL folding (Intel "Fast CRC Computation Using PCLMULQDQ", the
//    constants the Linux kernel's crc32-pclmul uses): 4x128-bit lanes fold
//    64 B per iteration, then fold to one lane and finish the residual 16
//    bytes + tail through the table path. ~16 GiB/s measured on the dev
//    container. Compiled only when -march=native exposes PCLMUL+SSE4.1.
//  - slice-by-16 tables: the portable fallback (~2x zlib 1.2.11).
//
// Values are bit-identical to zlib.crc32 for every (buffer, init) — pinned
// by tests/test_native.py against the zlib oracle — so manifests written by
// either path verify under the other.

#include <mutex>
#if defined(__PCLMUL__) && defined(__SSE4_1__)
#include <immintrin.h>
#define SQ_HAVE_PCLMUL 1
#endif

namespace {

uint32_t crc_tbl[16][256];
std::once_flag crc_tbl_once;

void crc_init_tables() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int j = 0; j < 8; j++)
      c = (c & 1) ? (0xEDB88320u ^ (c >> 1)) : (c >> 1);
    crc_tbl[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++)
    for (int s = 1; s < 16; s++)
      crc_tbl[s][i] = (crc_tbl[s - 1][i] >> 8)
                      ^ crc_tbl[0][crc_tbl[s - 1][i] & 0xFF];
}

// raw (unconditioned) update: c is the reflected remainder register, i.e.
// ~zlib_crc. Slice-by-16 main loop, byte-at-a-time head/tail.
uint32_t crc32_raw(const uint8_t* p, int64_t len, uint32_t c) {
  while (len && (reinterpret_cast<uintptr_t>(p) & 7)) {
    c = (c >> 8) ^ crc_tbl[0][(c ^ *p++) & 0xFF];
    len--;
  }
  while (len >= 16) {
    uint64_t a, b;
    std::memcpy(&a, p, 8);
    std::memcpy(&b, p + 8, 8);
    a ^= c;
    c = crc_tbl[15][a & 0xFF]         ^ crc_tbl[14][(a >> 8) & 0xFF]
      ^ crc_tbl[13][(a >> 16) & 0xFF] ^ crc_tbl[12][(a >> 24) & 0xFF]
      ^ crc_tbl[11][(a >> 32) & 0xFF] ^ crc_tbl[10][(a >> 40) & 0xFF]
      ^ crc_tbl[9][(a >> 48) & 0xFF]  ^ crc_tbl[8][(a >> 56) & 0xFF]
      ^ crc_tbl[7][b & 0xFF]          ^ crc_tbl[6][(b >> 8) & 0xFF]
      ^ crc_tbl[5][(b >> 16) & 0xFF]  ^ crc_tbl[4][(b >> 24) & 0xFF]
      ^ crc_tbl[3][(b >> 32) & 0xFF]  ^ crc_tbl[2][(b >> 40) & 0xFF]
      ^ crc_tbl[1][(b >> 48) & 0xFF]  ^ crc_tbl[0][(b >> 56) & 0xFF];
    p += 16;
    len -= 16;
  }
  while (len--) c = (c >> 8) ^ crc_tbl[0][(c ^ *p++) & 0xFF];
  return c;
}

#ifdef SQ_HAVE_PCLMUL
// reflected-domain folding constants:
//   x^(512+32) mod P = 0x154442bd4,  x^(512-32) mod P = 0x1c6e41596
//   x^(128+32) mod P = 0x1751997d0,  x^(128-32) mod P = 0x0ccaa009e
inline __m128i crc_fold(__m128i x, __m128i k, __m128i data) {
  return _mm_xor_si128(
      _mm_xor_si128(_mm_clmulepi64_si128(x, k, 0x00),
                    _mm_clmulepi64_si128(x, k, 0x11)),
      data);
}

uint32_t crc32_pclmul(const uint8_t* p, int64_t len, uint32_t c) {
  const __m128i k64 =
      _mm_set_epi64x(0x00000001c6e41596, 0x0000000154442bd4);
  const __m128i k16 =
      _mm_set_epi64x(0x00000000ccaa009e, 0x00000001751997d0);
  __m128i x0 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(p));
  __m128i x1 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(p + 16));
  __m128i x2 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(p + 32));
  __m128i x3 = _mm_loadu_si128(reinterpret_cast<const __m128i*>(p + 48));
  x0 = _mm_xor_si128(x0, _mm_cvtsi32_si128(static_cast<int>(c)));
  p += 64;
  len -= 64;
  while (len >= 64) {
    x0 = crc_fold(x0, k64,
                  _mm_loadu_si128(reinterpret_cast<const __m128i*>(p)));
    x1 = crc_fold(x1, k64,
                  _mm_loadu_si128(reinterpret_cast<const __m128i*>(p + 16)));
    x2 = crc_fold(x2, k64,
                  _mm_loadu_si128(reinterpret_cast<const __m128i*>(p + 32)));
    x3 = crc_fold(x3, k64,
                  _mm_loadu_si128(reinterpret_cast<const __m128i*>(p + 48)));
    p += 64;
    len -= 64;
  }
  __m128i x = crc_fold(x0, k16, x1);
  x = crc_fold(x, k16, x2);
  x = crc_fold(x, k16, x3);
  alignas(16) uint8_t buf[16];
  _mm_store_si128(reinterpret_cast<__m128i*>(buf), x);
  c = crc32_raw(buf, 16, 0);
  return crc32_raw(p, len, c);
}
#endif

}  // namespace

// zlib.crc32-compatible entry: crc32_fast(buf, len, init) == zlib.crc32(
// bytes, init). len 0 returns init (zlib convention).
extern "C" uint32_t crc32_fast(const uint8_t* p, int64_t len, uint32_t init) {
  std::call_once(crc_tbl_once, crc_init_tables);
  uint32_t c = ~init;
#ifdef SQ_HAVE_PCLMUL
  if (len >= 128) return ~crc32_pclmul(p, len, c);
#endif
  return ~crc32_raw(p, len, c);
}

// ---------------------------------------------------------------------------
// LZ4-class block codec (sq-lz: byte-stream match compression)
// ---------------------------------------------------------------------------
//
// The out-of-core shard store reads raw `.npy` at disk bandwidth; at the
// 100x-RAM scale bytes-on-disk and cold-tier latency dominate a store walk
// (ROADMAP item 5). This is the byte-stream codec behind SQ_OOC_CODEC=lz4
// (oocore/store.py) and the serving feature-cache spill tier
// (serving/cache.py): the standard LZ4 block format (token byte = literal
// length nibble | match length nibble, 255-continued extension bytes,
// 2-byte little-endian offsets, min match 4), compressed by a greedy
// single-slot 2^16-entry hash matcher. The matcher is deliberately the
// SIMPLEST deterministic variant — insert at every scanned position,
// forward extension only, no backward extension, no skip acceleration —
// because the pure-Python portable fallback (sq_learn_tpu/native) must
// produce BYTE-IDENTICAL compressed streams (pinned by tests/test_native.py:
// a store written by either path re-opens under the other with the same
// manifest CRCs).
//
// Format invariants (shared with the Python twin):
//  - last LASTLIT(5) bytes are always literals; match search stops
//    MFLIMIT(12) bytes before the end (the classic LZ4 end conditions);
//  - the final sequence is literals-only (no offset follows it);
//  - empty input compresses to an empty stream.
// The decoder bounds-checks every read/write and returns -1 on malformed
// input instead of overrunning — a corrupted compressed shard whose CRC
// was skipped (SQ_OOC_VERIFY=off) must surface as an error with shard
// provenance, not as a segfault.

namespace {

constexpr int64_t kLzMfLimit = 12;   // no match search this close to end
constexpr int64_t kLzLastLit = 5;    // the final 5 bytes stay literal
constexpr int kLzHashBits = 16;

inline uint32_t lz_read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint32_t lz_hash(uint32_t x) {
  return (uint32_t)((x * 2654435761u) >> (32 - kLzHashBits));
}

}  // namespace

// worst-case compressed size for n input bytes (literal-only stream plus
// extension bytes; matches only shrink the output)
extern "C" int64_t lz4_bound(int64_t n) { return n + n / 255 + 16; }

// compress src[0..n) into dst (capacity >= lz4_bound(n)); returns the
// compressed size, or -1 on a capacity overrun (never happens with a
// bound-sized dst — the guard is against caller mistakes).
extern "C" int64_t lz4_compress(const uint8_t* src, int64_t n, uint8_t* dst,
                                int64_t cap) {
  if (n < 0 || (n > 0 && (src == nullptr || dst == nullptr))) return -1;
  if (n == 0) return 0;
  std::vector<int64_t> table((size_t)1 << kLzHashBits, -1);
  int64_t ip = 0, anchor = 0, op = 0;
  const int64_t limit = n - kLzMfLimit;

  // one sequence: literals [anchor, anchor+lit), then (off, mlen) unless
  // off == 0 (the final literal-only sequence)
  auto emit = [&](int64_t lit, int64_t mlen_m4, int64_t off) -> bool {
    int64_t need = 1 + lit + lit / 255 + 1 + (off ? 2 + mlen_m4 / 255 + 1 : 0);
    if (op + need > cap) return false;
    uint8_t tok_lit = lit >= 15 ? 15 : (uint8_t)lit;
    uint8_t tok_mat = off ? (mlen_m4 >= 15 ? 15 : (uint8_t)mlen_m4) : 0;
    dst[op++] = (uint8_t)((tok_lit << 4) | tok_mat);
    for (int64_t rem = lit - 15; rem >= 0; rem -= 255) {
      dst[op++] = (uint8_t)(rem < 255 ? rem : 255);
      if (rem < 255) break;
    }
    std::memcpy(dst + op, src + anchor, (size_t)lit);
    op += lit;
    if (off) {
      dst[op++] = (uint8_t)(off & 0xFF);
      dst[op++] = (uint8_t)(off >> 8);
      for (int64_t rem = mlen_m4 - 15; rem >= 0; rem -= 255) {
        dst[op++] = (uint8_t)(rem < 255 ? rem : 255);
        if (rem < 255) break;
      }
    }
    return true;
  };

  while (ip <= limit) {
    uint32_t seq = lz_read32(src + ip);
    uint32_t h = lz_hash(seq);
    int64_t cand = table[h];
    table[h] = ip;
    if (cand >= 0 && ip - cand <= 0xFFFF && lz_read32(src + cand) == seq) {
      int64_t mlen = 4;
      const int64_t end = n - kLzLastLit;
      while (ip + mlen < end && src[ip + mlen] == src[cand + mlen]) mlen++;
      if (!emit(ip - anchor, mlen - 4, ip - cand)) return -1;
      ip += mlen;
      anchor = ip;
    } else {
      ip++;
    }
  }
  if (!emit(n - anchor, 0, 0)) return -1;
  return op;
}

// decompress src[0..n) into dst[0..raw_n); returns raw_n, or -1 on any
// malformed input (truncated lengths, bad offsets, size mismatch).
extern "C" int64_t lz4_decompress(const uint8_t* src, int64_t n,
                                  uint8_t* dst, int64_t raw_n) {
  if (n < 0 || raw_n < 0) return -1;
  if (raw_n == 0) return n == 0 ? 0 : -1;
  if (src == nullptr || dst == nullptr) return -1;
  int64_t ip = 0, op = 0;
  while (ip < n) {
    uint8_t token = src[ip++];
    int64_t lit = token >> 4;
    if (lit == 15) {
      uint8_t b;
      do {
        if (ip >= n) return -1;
        b = src[ip++];
        lit += b;
      } while (b == 255);
    }
    if (ip + lit > n || op + lit > raw_n) return -1;
    std::memcpy(dst + op, src + ip, (size_t)lit);
    ip += lit;
    op += lit;
    if (ip >= n) break;  // final literal-only sequence
    if (ip + 2 > n) return -1;
    int64_t off = (int64_t)src[ip] | ((int64_t)src[ip + 1] << 8);
    ip += 2;
    if (off == 0 || off > op) return -1;
    int64_t mlen = (token & 0xF) + 4;
    if ((token & 0xF) == 15) {
      uint8_t b;
      do {
        if (ip >= n) return -1;
        b = src[ip++];
        mlen += b;
      } while (b == 255);
    }
    if (op + mlen > raw_n) return -1;
    // overlapping copies (off < mlen) replicate the match window; copy in
    // offset-sized chunks, which is exact for both cases
    int64_t from = op - off;
    while (mlen > 0) {
      int64_t chunk = mlen < off ? mlen : off;
      std::memmove(dst + op, dst + from, (size_t)chunk);
      op += chunk;
      from += chunk;
      mlen -= chunk;
    }
  }
  return op == raw_n ? op : -1;
}

// ---------------------------------------------------------------------------
// Serving-plane batch assembly / scatter (PR 16)
// ---------------------------------------------------------------------------
//
// The micro-batching dispatcher's hot path is pure byte movement: gather N
// request payloads into one padded pow2 bucket buffer before the dispatch,
// slice the result buffer back per request after the fetch. Per-request
// numpy slice assignment pays the full ufunc/indexing machinery (~µs each)
// for what is a memcpy; these two entry points do the whole batch in one
// ctypes call. Pointer arrays arrive as uint64 element addresses (the
// caller passes numpy arrays' .ctypes.data) with per-block byte counts —
// the C side cannot see shapes, so every copy is bounds-checked against
// the destination and rc -1 rejects the whole call (the Python wrapper
// then falls back to the byte-identical numpy path).

// gather: copy n blocks consecutively into dst[0..dst_bytes), zero the
// padding tail. rc 0 on success, -1 on any overrun/null.
extern "C" int serve_gather(const uint64_t* src_ptrs, const int64_t* src_bytes,
                            int64_t n, uint8_t* dst, int64_t dst_bytes) {
  if (n < 0 || dst_bytes < 0) return -1;
  if (n > 0 && (src_ptrs == nullptr || src_bytes == nullptr)) return -1;
  if (dst_bytes > 0 && dst == nullptr) return -1;
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t sz = src_bytes[i];
    if (sz < 0 || sz > dst_bytes - off) return -1;
    if (sz > 0) {
      const uint8_t* src = (const uint8_t*)(uintptr_t)src_ptrs[i];
      if (src == nullptr) return -1;
      std::memcpy(dst + off, src, (size_t)sz);
    }
    off += sz;
  }
  if (off < dst_bytes) std::memset(dst + off, 0, (size_t)(dst_bytes - off));
  return 0;
}

// scatter: copy consecutive slices of src back into n per-request result
// buffers (submission order). rc 0 on success, -1 on any overrun/null.
extern "C" int serve_scatter(const uint8_t* src, int64_t src_bytes,
                             const uint64_t* dst_ptrs,
                             const int64_t* dst_bytes, int64_t n) {
  if (n < 0 || src_bytes < 0) return -1;
  if (n > 0 && (dst_ptrs == nullptr || dst_bytes == nullptr)) return -1;
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t sz = dst_bytes[i];
    if (sz < 0 || sz > src_bytes - off) return -1;
    if (sz > 0) {
      if (src == nullptr) return -1;
      uint8_t* dst = (uint8_t*)(uintptr_t)dst_ptrs[i];
      if (dst == nullptr) return -1;
      std::memcpy(dst, src + off, (size_t)sz);
    }
    off += sz;
  }
  return 0;
}
