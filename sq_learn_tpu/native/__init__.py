"""Native host runtime (C++ via ctypes).

The compute path of this framework is XLA; this package is the host-side
native layer the reference builds in Cython/C++ (SURVEY §2.2):

- :func:`lloyd_iter` — threaded fused Lloyd E+M step, the CPU-parity
  equivalent of the reference's ``lloyd_iter_chunked_dense``
  (``cluster/_k_means_lloyd.pyx:29``).
- :func:`murmurhash3_32` — feature hashing (reference vendors
  ``utils/src/MurmurHash3.cpp``; ours re-implements the public algorithm).
- :func:`csv_read_floats` — threaded float-CSV ingest for large host-side
  datasets (CICIDS et al.).
- :func:`crc32` — zlib-identical CRC-32 at PCLMUL speed (the oocore
  shard-verify fast path).
- :func:`lz4_compress` / :func:`compress_array` — the LZ4-class block
  codec behind compressed shard stores (``SQ_OOC_CODEC=lz4``) and the
  serving feature-cache spill tier, with a byte-identical pure-Python
  fallback (same greedy matcher — streams, not just values, match).
- :func:`serve_gather` / :func:`serve_scatter` — the serving
  dispatcher's batch assembly and result scatter as single ctypes calls
  (one memcpy loop instead of one numpy slice op per request), with
  byte-identical NumPy fallbacks.

The shared library is compiled on first use with ``g++`` and cached next to
the source; every entry point has a NumPy fallback so the package works on
hosts without a toolchain. ``native_available()`` reports which path is
active.
"""

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "src", "native.cpp")
_LIB_PATH = os.path.join(_HERE, "_native.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build():
    cmd = ["g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
           "-pthread", _SRC, "-o", _LIB_PATH]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError):
        # retry without -march=native (portable build)
        try:
            cmd.remove("-march=native")
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            return True
        except (subprocess.SubprocessError, FileNotFoundError):
            return False


def _load():
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_LIB_PATH)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None

        lib.lloyd_iter_chunked.restype = ctypes.c_int
        lib.lloyd_iter_chunked.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double), ctypes.c_int]
        lib.lloyd_iter_window.restype = ctypes.c_int
        lib.lloyd_iter_window.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_double, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double), ctypes.c_int]
        lib.elkan_iter.restype = ctypes.c_int
        lib.elkan_iter.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_double), ctypes.c_int]
        lib.lloyd_run_batched.restype = ctypes.c_int
        lib.lloyd_run_batched.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_double, ctypes.c_uint64, ctypes.c_int64,
            ctypes.c_double, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double),
            ctypes.c_int]
        lib.argkmin.restype = ctypes.c_int
        lib.argkmin.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int]
        lib.kmeans_pp_batched.restype = ctypes.c_int
        lib.kmeans_pp_batched.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int]
        lib.set_sgemm.restype = None
        lib.set_sgemm.argtypes = [ctypes.c_void_p]
        lib.has_sgemm.restype = ctypes.c_int
        lib.has_sgemm.argtypes = []
        _register_blas(lib)
        lib.murmurhash3_x86_32.restype = ctypes.c_uint32
        lib.murmurhash3_x86_32.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_uint32]
        lib.murmurhash3_bulk.restype = None
        lib.murmurhash3_bulk.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint32)]
        lib.csv_shape.restype = ctypes.c_int
        lib.csv_shape.argtypes = [
            ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        lib.csv_parse_floats.restype = ctypes.c_int64
        lib.csv_parse_floats.argtypes = [
            ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64]
        lib.csv_stream_open.restype = ctypes.c_void_p
        lib.csv_stream_open.argtypes = [
            ctypes.c_char_p, ctypes.c_char, ctypes.c_int]
        lib.csv_stream_next.restype = ctypes.c_int64
        lib.csv_stream_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.c_int64]
        lib.csv_stream_close.restype = None
        lib.csv_stream_close.argtypes = [ctypes.c_void_p]
        lib.crc32_fast.restype = ctypes.c_uint32
        lib.crc32_fast.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                   ctypes.c_uint32]
        lib.lz4_bound.restype = ctypes.c_int64
        lib.lz4_bound.argtypes = [ctypes.c_int64]
        lib.lz4_compress.restype = ctypes.c_int64
        lib.lz4_compress.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                     ctypes.c_void_p, ctypes.c_int64]
        lib.lz4_decompress.restype = ctypes.c_int64
        lib.lz4_decompress.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                       ctypes.c_void_p, ctypes.c_int64]
        lib.serve_gather.restype = ctypes.c_int
        lib.serve_gather.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_int64, ctypes.c_void_p,
                                     ctypes.c_int64]
        lib.serve_scatter.restype = ctypes.c_int
        lib.serve_scatter.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                      ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_int64]
        _lib = lib
        return _lib


_blas_handle = None  # keeps the OpenBLAS CDLL alive once registered


def _register_blas(lib):
    """Point the native library at a real BLAS sgemm when one is findable.

    scipy bundles OpenBLAS as a private shared library exporting the
    plain-int (LP64) ``scipy_cblas_sgemm`` — the only symbol/ABI the C++
    ``cblas_sgemm_t`` typedef is valid for. numpy's bundled copy is the
    ILP64 build (``scipy_cblas_sgemm64_``, 64-bit ints) and must NOT be
    registered: binding it to the 32-bit-int signature would pass garbage
    dims. Without a hit the C++ side falls back to its internal blocked
    GEMM.
    """
    global _blas_handle
    import glob

    try:
        import scipy
    except ImportError:
        return
    libdir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(scipy.__file__))),
        "scipy.libs")
    for path in sorted(glob.glob(
            os.path.join(libdir, "libscipy_openblas-*.so*"))):
        try:
            blas = ctypes.CDLL(path)
            fn = blas.scipy_cblas_sgemm
        except (OSError, AttributeError):
            continue
        lib.set_sgemm(ctypes.cast(fn, ctypes.c_void_p))
        _blas_handle = blas
        return


def native_available():
    """True when the C++ library compiled and loaded."""
    return _load() is not None


def argkmin(Xtr, xsq_tr, Xq, xsq_q, k, n_threads=0):
    """k nearest training rows per query — blocked sgemm + per-row bounded
    max-heap (the reference's neighbor-kernel role,
    ``neighbors/_ball_tree.pyx``/``_kd_tree.pyx``; brute-force is the
    TPU-era equivalent, SURVEY §2.2). Returns ``(idx int64 (n_q, k),
    d2 float32 (n_q, k))`` sorted by ascending distance, or None when the
    native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    Xtr = np.ascontiguousarray(Xtr, np.float32)
    Xq = np.ascontiguousarray(Xq, np.float32)
    xsq_tr = np.ascontiguousarray(xsq_tr, np.float32)
    xsq_q = np.ascontiguousarray(xsq_q, np.float32)
    n_q = Xq.shape[0]
    # the C++ side cannot see shape mismatches — it would read past the
    # buffers; validate the public surface here
    if (Xtr.ndim != 2 or Xq.ndim != 2 or Xq.shape[1] != Xtr.shape[1]
            or xsq_tr.shape != (Xtr.shape[0],) or xsq_q.shape != (n_q,)):
        raise ValueError(
            f"argkmin shape mismatch: Xtr {Xtr.shape}, Xq {Xq.shape}, "
            f"xsq_tr {xsq_tr.shape}, xsq_q {xsq_q.shape}")
    idx = np.empty((n_q, int(k)), np.int64)
    d2 = np.empty((n_q, int(k)), np.float32)
    fp = ctypes.POINTER(ctypes.c_float)
    rc = lib.argkmin(
        Xtr.ctypes.data_as(fp), xsq_tr.ctypes.data_as(fp),
        Xq.ctypes.data_as(fp), xsq_q.ctypes.data_as(fp),
        Xtr.shape[0], n_q, Xtr.shape[1], int(k),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        d2.ctypes.data_as(fp), int(n_threads))
    return (idx, d2) if rc == 0 else None


def kmeans_pp_batched(rng, Xn, wn, xsq, k, R, n_trials=None, n_threads=0):
    """R independent greedy k-means++ inits in one native call (the C++
    twin of ``_kmeans_plusplus_np``: weighted first pick, then D² sampling
    keeping the best of ``n_trials`` candidate centers per round). Returns
    a (R, k, m) float32 stack, or None when the native library is
    unavailable."""
    lib = _load()
    if lib is None:
        return None
    Xn = np.ascontiguousarray(Xn, np.float32)
    wn = np.ascontiguousarray(wn, np.float32)
    xsq = np.ascontiguousarray(xsq, np.float32)
    n, m = Xn.shape
    if n_trials is None:
        n_trials = 2 + int(np.log(k))
    out = np.empty((R, k, m), np.float32)
    fp = ctypes.POINTER(ctypes.c_float)
    rc = lib.kmeans_pp_batched(
        Xn.ctypes.data_as(fp), wn.ctypes.data_as(fp), xsq.ctypes.data_as(fp),
        n, m, int(k), int(R), int(n_trials),
        int(rng.integers(0, 2**63 - 1)), out.ctypes.data_as(fp),
        int(n_threads))
    return out if rc == 0 else None


def lloyd_run_batched(rng, Xn, wn, xsq, centers_stack, *, window, max_iter,
                      tol, patience, n_threads=0):
    """Full lockstep multi-restart windowed Lloyd run in ONE native call —
    the C++ engine behind the host runner
    (:func:`sq_learn_tpu.models.qkmeans._native_lloyd_run_batched`, which
    holds the semantics contract and the NumPy twin). Returns the same
    ``(winner, per_restart)`` structure, or None when the native library is
    unavailable (caller falls back to the NumPy lockstep loop).
    """
    lib = _load()
    if lib is None:
        return None
    Xn = np.ascontiguousarray(Xn, np.float32)
    wn = np.ascontiguousarray(wn, np.float32)
    xsq = np.ascontiguousarray(xsq, np.float32)
    C = np.ascontiguousarray(centers_stack, np.float32).copy()
    R, k, m = C.shape
    n = Xn.shape[0]
    max_iter = int(max_iter)
    labels = np.empty(n, np.int32)
    out_centers = np.empty((k, m), np.float32)
    out_final = np.empty(R, np.float64)
    inertia_tr = np.full((R, max_iter), np.nan, np.float32)
    shift_tr = np.full((R, max_iter), np.nan, np.float32)
    out_iters = np.zeros(R, np.int64)
    out_winner = ctypes.c_int64()
    out_inertia = ctypes.c_double()
    fp = ctypes.POINTER(ctypes.c_float)
    rc = lib.lloyd_run_batched(
        Xn.ctypes.data_as(fp), wn.ctypes.data_as(fp), xsq.ctypes.data_as(fp),
        C.ctypes.data_as(fp), n, m, k, R, float(window),
        int(rng.integers(0, 2**63 - 1)), max_iter, float(tol),
        -1 if patience is None else int(patience),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out_centers.ctypes.data_as(fp),
        out_final.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        inertia_tr.ctypes.data_as(fp), shift_tr.ctypes.data_as(fp),
        out_iters.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.byref(out_winner), ctypes.byref(out_inertia), int(n_threads))
    if rc != 0:
        return None
    r_star = int(out_winner.value)
    history = {"inertia": inertia_tr[r_star], "center_shift": shift_tr[r_star]}
    winner = (labels, np.float32(out_inertia.value), out_centers,
              int(out_iters[r_star]), history)
    per_restart = [
        (float(out_final[r]), int(out_iters[r]),
         {"inertia": inertia_tr[r], "center_shift": shift_tr[r]})
        for r in range(R)]
    return winner, per_restart


# ---------------------------------------------------------------------------
# Lloyd iteration
# ---------------------------------------------------------------------------


def lloyd_iter(X, centers, sample_weight=None, n_threads=0):
    """One fused Lloyd E+M step on the host.

    Returns ``(labels int32 (n,), sums float64 (k, m), counts float64 (k,),
    inertia float)``. Native path: threaded C++ chunk kernel; fallback:
    vectorized NumPy.
    """
    X = np.ascontiguousarray(X, dtype=np.float32)
    centers = np.ascontiguousarray(centers, dtype=np.float32)
    n, m = X.shape
    k = centers.shape[0]
    if sample_weight is not None:
        sample_weight = np.ascontiguousarray(sample_weight, dtype=np.float32)

    lib = _load()
    if lib is not None:
        labels = np.empty(n, np.int32)
        sums = np.empty((k, m), np.float64)
        counts = np.empty(k, np.float64)
        inertia = ctypes.c_double()
        w_ptr = (sample_weight.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                 if sample_weight is not None
                 else ctypes.cast(None, ctypes.POINTER(ctypes.c_float)))
        rc = lib.lloyd_iter_chunked(
            X.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), w_ptr,
            centers.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n, m, k,
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            sums.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            ctypes.byref(inertia), int(n_threads))
        if rc == 0:
            return labels, sums, counts, float(inertia.value)

    # NumPy fallback
    w = np.ones(n, np.float64) if sample_weight is None else \
        sample_weight.astype(np.float64)
    c_sq = (centers.astype(np.float64) ** 2).sum(axis=1)
    d = c_sq[None, :] - 2.0 * (X.astype(np.float64) @ centers.T.astype(np.float64))
    labels = np.argmin(d, axis=1).astype(np.int32)
    x_sq = (X.astype(np.float64) ** 2).sum(axis=1)
    inertia = float(np.sum(w * (d[np.arange(n), labels] + x_sq)))
    onehot = np.zeros((n, k))
    onehot[np.arange(n), labels] = w
    sums = onehot.T @ X.astype(np.float64)
    counts = onehot.sum(axis=0)
    return labels, sums, counts, inertia


def host_lloyd_step(rng, Xn, wn, xsq, centers, window, e_only=False):
    """One fused host E+M step on BLAS: sgemm distances (the ‖c‖²−2xcᵀ
    trick, same as the reference's chunked kernel
    ``_k_means_lloyd.pyx:196-203``), optional δ-window uniform pick, one-hot
    sgemm partials. On few-core hosts single-threaded BLAS beats the
    threaded scalar C++ kernel; many-core hosts use
    :func:`lloyd_iter_window` instead.

    Returns ``(labels int32 (n,), min_d2 (n,), sums (k, m), counts (k,),
    inertia float)`` with the same semantics as :func:`lloyd_iter_window`.
    ``e_only`` skips the M-step partials (sums/counts are None) — for
    final-candidate re-evaluation, which only needs labels and inertia.
    """
    n, k = len(Xn), centers.shape[0]
    rows = np.arange(n)
    csq = (centers**2).sum(axis=1)
    d = csq[None, :] - 2.0 * (Xn @ centers.T)        # (n, k) sgemm
    labels = d.argmin(axis=1).astype(np.int32)
    best = d[rows, labels]                           # one scan + gather
    if window > 0 and k > 1:
        # the uniform δ-window pick only matters for rows whose runner-up
        # lies inside the window — with small δ that is a handful of rows,
        # so the full-matrix masking/RNG runs on the ambiguous subset only.
        # Runner-up via mask-the-winner + min: one vectorized pass, cheaper
        # than a partition sort of the whole (n, k) matrix
        d[rows, labels] = np.inf
        second = d.min(axis=1)
        d[rows, labels] = best
        amb = np.flatnonzero(second <= best + window)
        if amb.size:
            sub = d[amb]
            m2 = sub <= best[amb, None] + window
            r = rng.random(sub.shape, dtype=np.float32)
            labels[amb] = np.where(m2, r, -1.0).argmax(axis=1)
    min_d2 = best + xsq
    inertia = float(min_d2 @ wn)
    if e_only:
        return labels, min_d2, None, None, inertia
    onehot = np.zeros(d.shape, np.float32)
    onehot[rows, labels] = wn
    sums = onehot.T @ Xn                             # (k, m) sgemm
    counts = np.bincount(labels, weights=wn, minlength=k)
    return labels, min_d2, sums, counts, inertia


def lloyd_iter_window(X, centers, sample_weight=None, window=0.0, seed=0,
                      n_threads=0):
    """Fused windowed (δ-means) Lloyd E+M step on the host.

    ``window`` > 0 picks each row's label uniformly among centroids within
    ``window`` of its minimum squared distance (the δ-means scrambling,
    reference ``_dmeans.py:742-750``); 0 is the classical argmin. The pick
    is reproducible from ``(seed, row)`` via a stateless per-row SplitMix64.

    Returns ``(labels int32 (n,), min_d2 float32 (n,), sums float64 (k, m),
    counts float64 (k,), inertia float)`` — partials follow the picked
    labels, inertia and min_d2 use the true minima, matching the XLA
    ``e_step``. Native path: threaded C++ kernel; fallback: NumPy.
    """
    X = np.ascontiguousarray(X, dtype=np.float32)
    centers = np.ascontiguousarray(centers, dtype=np.float32)
    n, m = X.shape
    k = centers.shape[0]
    if sample_weight is not None:
        sample_weight = np.ascontiguousarray(sample_weight, dtype=np.float32)

    lib = _load()
    if lib is not None:
        labels = np.empty(n, np.int32)
        min_d2 = np.empty(n, np.float32)
        sums = np.empty((k, m), np.float64)
        counts = np.empty(k, np.float64)
        inertia = ctypes.c_double()
        w_ptr = (sample_weight.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                 if sample_weight is not None
                 else ctypes.cast(None, ctypes.POINTER(ctypes.c_float)))
        rc = lib.lloyd_iter_window(
            X.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), w_ptr,
            centers.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n, m, k, float(window), int(seed) & 0xFFFFFFFFFFFFFFFF,
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            min_d2.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            sums.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            ctypes.byref(inertia), int(n_threads))
        if rc == 0:
            return labels, min_d2, sums, counts, float(inertia.value)

    # BLAS fallback (same semantics; numpy RNG stands in for SplitMix64)
    w = (np.ones(n, np.float32) if sample_weight is None else sample_weight)
    x_sq = (X**2).sum(axis=1)
    return host_lloyd_step(np.random.default_rng(seed), X, w, x_sq, centers,
                           float(window))


def elkan_iter(X, centers, c_half, s, labels, upper, lower,
               sample_weight=None, init=False, n_threads=0):
    """One Elkan E-step (triangle-inequality-pruned classical assignment;
    the reference ships it as ``cluster/_k_means_elkan.pyx:184``).

    ``labels`` (n,) int32, ``upper`` (n,) float32 and ``lower`` (n, k)
    float32 are the persistent bounds state, updated IN PLACE; ``c_half``
    (k, k) and ``s`` (k,) are the caller-computed half center-center
    distances. ``init=True`` seeds the bounds with a full distance pass.

    Returns ``(min_d2 float32 (n,), sums float64 (k, m), counts float64
    (k,), inertia float)`` with the same output contract as
    :func:`lloyd_iter_window` at window=0; ``upper`` is exact on exit.
    The NumPy fallback is the unpruned equivalent: a full distance pass
    that re-seeds the bounds exactly (identical results, no pruning win).
    """
    X = np.ascontiguousarray(X, dtype=np.float32)
    centers = np.ascontiguousarray(centers, dtype=np.float32)
    n, m = X.shape
    k = centers.shape[0]
    # the in-place contract forbids coercion copies of the state arrays, so
    # a wrong dtype/layout must fail loudly, not reinterpret the buffer
    for name, arr, dtype, shape in (("labels", labels, np.int32, (n,)),
                                    ("upper", upper, np.float32, (n,)),
                                    ("lower", lower, np.float32, (n, k))):
        if (arr.dtype != dtype or arr.shape != shape
                or not arr.flags["C_CONTIGUOUS"]):
            raise ValueError(
                f"{name} must be a C-contiguous {np.dtype(dtype).name} "
                f"array of shape {shape} (updated in place), got "
                f"{arr.dtype} {arr.shape}")
    if sample_weight is not None:
        sample_weight = np.ascontiguousarray(sample_weight, dtype=np.float32)

    lib = _load()
    if lib is not None:
        c_half = np.ascontiguousarray(c_half, dtype=np.float32)
        s = np.ascontiguousarray(s, dtype=np.float32)
        min_d2 = np.empty(n, np.float32)
        sums = np.empty((k, m), np.float64)
        counts = np.empty(k, np.float64)
        inertia = ctypes.c_double()
        w_ptr = (sample_weight.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                 if sample_weight is not None
                 else ctypes.cast(None, ctypes.POINTER(ctypes.c_float)))
        rc = lib.elkan_iter(
            X.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), w_ptr,
            centers.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            c_half.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            s.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n, m, k,
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            upper.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            lower.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            int(bool(init)),
            min_d2.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            sums.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            ctypes.byref(inertia), int(n_threads))
        if rc == 0:
            return min_d2, sums, counts, float(inertia.value)

    # NumPy fallback: full (unpruned) pass, bounds re-seeded exactly
    w = (np.ones(n, np.float32) if sample_weight is None else sample_weight)
    x_sq = (X**2).sum(axis=1)
    c_sq = (centers**2).sum(axis=1)
    d = np.sqrt(np.maximum(
        x_sq[:, None] + c_sq[None, :] - 2.0 * (X @ centers.T), 0.0))
    labels[:] = d.argmin(axis=1).astype(np.int32)
    rows = np.arange(n)
    upper[:] = d[rows, labels]
    lower[:] = d
    min_d2 = (upper.astype(np.float64)**2).astype(np.float32)
    onehot = np.zeros((n, k), np.float32)
    onehot[rows, labels] = w
    sums = (onehot.T @ X).astype(np.float64)
    counts = np.bincount(labels, weights=w, minlength=k).astype(np.float64)
    inertia = float((upper.astype(np.float64)**2) @ w)
    return min_d2, sums, counts, inertia


# ---------------------------------------------------------------------------
# CRC-32
# ---------------------------------------------------------------------------


def crc32(data, value=0):
    """CRC-32 of a contiguous buffer — bit-identical to ``zlib.crc32``
    (same polynomial, same conditioning), at native speed: PCLMUL folding
    (~16 GiB/s measured on the dev container vs the image's zlib 1.2.11
    at ~1 GiB/s) with a slice-by-16 portable build and a ``zlib.crc32``
    fallback when the toolchain is absent. The out-of-core shard store
    verifies every materialized shard read against its manifest CRC
    (``oocore/store.py``), which made the old zlib pass the dominant cost
    of a warm store walk; manifests written by either path verify under
    the other (parity pinned by ``tests/test_native.py``).

    ``data`` is a numpy array (any dtype, C-contiguous or copied to be)
    or a bytes-like object; ``value`` is the running CRC to continue.
    """
    import zlib

    lib = _load()
    if isinstance(data, np.ndarray):
        buf = np.ascontiguousarray(data)
        if lib is None:
            return zlib.crc32(buf) if value == 0 \
                else zlib.crc32(buf, value)
        flat = buf.reshape(-1).view(np.uint8) if buf.size else \
            np.empty(0, np.uint8)
        return int(lib.crc32_fast(flat.ctypes.data, flat.size,
                                  value & 0xFFFFFFFF))
    if lib is None:
        return zlib.crc32(data, value) & 0xFFFFFFFF
    flat = np.frombuffer(data, np.uint8)
    return int(lib.crc32_fast(flat.ctypes.data, flat.size,
                              value & 0xFFFFFFFF))


# ---------------------------------------------------------------------------
# LZ4-class block codec (sq-lz)
# ---------------------------------------------------------------------------
#
# The byte-stream codec behind the compressed shard store
# (``SQ_OOC_CODEC=lz4``, ``oocore/store.py``) and the serving feature-cache
# spill tier (``serving/cache.py``). Standard LZ4 block format compressed by
# a deliberately minimal greedy matcher (single-slot 2^16 hash, insert at
# every scanned position, forward extension only) so this pure-Python
# portable fallback produces BYTE-IDENTICAL streams to the C++ kernel — a
# store written by either path re-opens under the other with the same
# manifest CRCs (cross-parity pinned by ``tests/test_native.py``).

_LZ_MFLIMIT = 12   # no match search this close to the end
_LZ_LASTLIT = 5    # the final 5 bytes stay literal
_LZ_HBITS = 16

#: in-band filter codes of :func:`compress_array` payloads (header byte 0)
_ENC_PLAIN, _ENC_SHUFFLE, _ENC_RAW = 0, 1, 2


def lz4_bound(n):
    """Worst-case compressed size for ``n`` input bytes."""
    n = int(n)
    return n + n // 255 + 16


def _as_u8(data):
    """A C-contiguous uint8 view/copy of a bytes-like or ndarray."""
    if isinstance(data, np.ndarray):
        buf = np.ascontiguousarray(data)
        return buf.reshape(-1).view(np.uint8) if buf.size else \
            np.empty(0, np.uint8)
    return np.frombuffer(data, np.uint8)


def lz4_compress(data):
    """Compress a bytes-like/ndarray buffer into an LZ4 block (bytes).

    Native path: the C++ greedy matcher; fallback: the byte-identical
    pure-Python twin (slow — fallback hosts trade speed, never format).
    """
    flat = _as_u8(data)
    n = flat.size
    if n == 0:
        return b""
    lib = _load()
    if lib is not None:
        out = np.empty(lz4_bound(n), np.uint8)
        got = lib.lz4_compress(flat.ctypes.data, n, out.ctypes.data,
                               out.size)
        if got >= 0:
            return out[:got].tobytes()
    return _lz4_compress_py(flat.tobytes())


def lz4_decompress(data, raw_n):
    """Decompress an LZ4 block into a writable uint8 array of ``raw_n``
    bytes. Raises ``ValueError`` on malformed input (both paths bounds-
    check every read/write — corrupt bytes surface as errors, never as
    overruns)."""
    flat = _as_u8(data)
    raw_n = int(raw_n)
    if raw_n == 0:
        if flat.size:
            raise ValueError("malformed LZ4 block: bytes after empty raw")
        return np.empty(0, np.uint8)
    lib = _load()
    if lib is not None:
        out = np.empty(raw_n, np.uint8)
        got = lib.lz4_decompress(flat.ctypes.data, flat.size,
                                 out.ctypes.data, raw_n)
        if got != raw_n:
            raise ValueError(
                f"malformed LZ4 block ({flat.size} bytes for {raw_n} raw)")
        return out
    return np.frombuffer(_lz4_decompress_py(flat.tobytes(), raw_n),
                         np.uint8).copy()


def _lz4_compress_py(src):
    """Pure-Python twin of the C++ ``lz4_compress`` — same greedy matcher,
    byte-identical output (pinned by tests)."""
    n = len(src)
    out = bytearray()
    if n == 0:
        return bytes(out)
    table = [-1] * (1 << _LZ_HBITS)
    pos = anchor = 0
    limit = n - _LZ_MFLIMIT

    def emit(lit, mlen_m4, off):
        out.append((min(lit, 15) << 4) | (min(mlen_m4, 15) if off else 0))
        rem = lit - 15
        while rem >= 0:
            out.append(min(rem, 255))
            if rem < 255:
                break
            rem -= 255
        out.extend(src[anchor:anchor + lit])
        if off:
            out.append(off & 0xFF)
            out.append(off >> 8)
            rem = mlen_m4 - 15
            while rem >= 0:
                out.append(min(rem, 255))
                if rem < 255:
                    break
                rem -= 255

    while pos <= limit:
        seq = src[pos:pos + 4]
        h = ((int.from_bytes(seq, "little") * 2654435761)
             & 0xFFFFFFFF) >> (32 - _LZ_HBITS)
        cand = table[h]
        table[h] = pos
        if cand >= 0 and pos - cand <= 0xFFFF and src[cand:cand + 4] == seq:
            mlen = 4
            end = n - _LZ_LASTLIT
            while pos + mlen < end and src[pos + mlen] == src[cand + mlen]:
                mlen += 1
            emit(pos - anchor, mlen - 4, pos - cand)
            pos += mlen
            anchor = pos
        else:
            pos += 1
    emit(n - anchor, 0, 0)
    return bytes(out)


def _lz4_decompress_py(buf, raw_n):
    """Pure-Python twin of the C++ ``lz4_decompress`` (same bounds checks,
    ``ValueError`` on any malformed input)."""
    n = len(buf)
    out = bytearray(raw_n)
    ip = op = 0
    while ip < n:
        token = buf[ip]
        ip += 1
        lit = token >> 4
        if lit == 15:
            while True:
                if ip >= n:
                    raise ValueError("truncated literal length")
                b = buf[ip]
                ip += 1
                lit += b
                if b != 255:
                    break
        if ip + lit > n or op + lit > raw_n:
            raise ValueError("literal overrun")
        out[op:op + lit] = buf[ip:ip + lit]
        ip += lit
        op += lit
        if ip >= n:
            break  # final literal-only sequence
        if ip + 2 > n:
            raise ValueError("truncated match offset")
        off = buf[ip] | (buf[ip + 1] << 8)
        ip += 2
        if off == 0 or off > op:
            raise ValueError("bad match offset")
        mlen = (token & 0xF) + 4
        if (token & 0xF) == 15:
            while True:
                if ip >= n:
                    raise ValueError("truncated match length")
                b = buf[ip]
                ip += 1
                mlen += b
                if b != 255:
                    break
        if op + mlen > raw_n:
            raise ValueError("match overrun")
        src_i = op - off
        for k in range(mlen):
            out[op + k] = out[src_i + k]
        op += mlen
    if op != raw_n:
        raise ValueError(f"decompressed {op} of {raw_n} bytes")
    return bytes(out)


def byte_shuffle(arr):
    """Blosc-style byte-plane transpose: itemsize-w elements become w
    contiguous byte planes (plane k = byte k of every element, row-major).
    Groups the low-entropy bytes of float data (sign/exponent, shared
    high mantissa bits) into long matchable runs the LZ4 matcher can see;
    which filter wins is data-dependent, so :func:`compress_array` tries
    both and keeps the smaller. Vectorized numpy both ways — no native
    dependency, no parity risk."""
    flat = _as_u8(arr)
    w = arr.dtype.itemsize if isinstance(arr, np.ndarray) else 1
    if w == 1 or flat.size == 0:
        return flat.copy()
    return np.ascontiguousarray(flat.reshape(-1, w).T).reshape(-1)


def byte_unshuffle(flat, itemsize):
    """Inverse of :func:`byte_shuffle` (returns a contiguous uint8
    array)."""
    flat = _as_u8(flat)
    w = int(itemsize)
    if w == 1 or flat.size == 0:
        return flat.copy()
    if flat.size % w:
        raise ValueError(f"{flat.size} bytes is not a multiple of "
                         f"itemsize {w}")
    return np.ascontiguousarray(flat.reshape(w, -1).T).reshape(-1)


def compress_array(arr):
    """Codec payload for one array: a 1-byte in-band filter header
    (0 = plain LZ4, 1 = byte-shuffled LZ4, 2 = stored raw) + body.

    Tries the plain and byte-shuffled LZ4 streams and keeps the smaller;
    a shard that compresses to >= its raw size stores raw (+1 header
    byte) — incompressible data costs one byte, never a blowup. The
    choice is deterministic (both candidates are), so rebuild
    bit-identity (``oocore/store.py``) holds through the codec.
    """
    a = np.ascontiguousarray(arr)
    raw = _as_u8(a)
    best, code = lz4_compress(raw), _ENC_PLAIN
    if a.dtype.itemsize > 1 and a.size:
        shuffled = lz4_compress(byte_shuffle(a))
        if len(shuffled) < len(best):
            best, code = shuffled, _ENC_SHUFFLE
    if len(best) >= raw.size:
        return bytes([_ENC_RAW]) + raw.tobytes()
    return bytes([code]) + best


def decompress_array(payload, dtype, shape):
    """Decode a :func:`compress_array` payload back to the exact array
    (bit-identical round trip). Raises ``ValueError`` on malformed
    payloads — including a decoded size that disagrees with
    ``dtype``/``shape``."""
    dtype = np.dtype(dtype)
    shape = tuple(int(s) for s in shape)
    raw_n = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    buf = _as_u8(payload)
    if buf.size == 0:
        raise ValueError("empty codec payload")
    code, body = int(buf[0]), buf[1:]
    if code == _ENC_RAW:
        if body.size != raw_n:
            raise ValueError(
                f"raw payload is {body.size} bytes, expected {raw_n}")
        flat = body.copy()
    elif code == _ENC_PLAIN:
        flat = lz4_decompress(body, raw_n)
    elif code == _ENC_SHUFFLE:
        flat = byte_unshuffle(lz4_decompress(body, raw_n), dtype.itemsize)
    else:
        raise ValueError(f"unknown codec filter byte {code}")
    return flat.view(dtype).reshape(shape)


# ---------------------------------------------------------------------------
# Serving-plane batch assembly / scatter
# ---------------------------------------------------------------------------


def serve_gather(blocks, out, addrs=None, counts=None, trusted=False):
    """Gather per-request row ``blocks`` consecutively into the padded
    batch buffer ``out`` (leading rows, submission order) and zero the
    padding tail — the serving dispatcher's assembly hot path in ONE
    ctypes call instead of one numpy slice assignment per request. The
    NumPy fallback is byte-identical (rows regions fully overwritten,
    tail zeroed), so pooled buffers never leak stale bytes either way.
    ``out`` is returned for chaining.

    ``addrs`` (optional) are the blocks' base addresses captured when
    the payloads were canonicalized — an ``ndarray.ctypes.data`` read
    costs ~1.5 µs EACH (it mints a fresh ctypes view object), which at
    64 requests per batch is 4× the whole legacy slice loop. The
    dispatcher captures each address ONCE on the submitting client
    thread and hands the plain ints here, so the single-threaded worker
    pays only one ``fromiter`` over attribute reads. Callers passing
    ``addrs`` own the guarantee that they were taken from these exact
    (still-alive, unresized) blocks.

    ``trusted=True`` skips the per-block invariant checks (C-contiguous
    2D blocks of ``out``'s dtype and width) — they cost more than the
    copies themselves at serving block sizes. Only for callers that
    canonicalize every payload on ingest (the dispatcher's ``_prepare``
    does); the native call still bounds-checks the destination, and the
    fallback's slice assignments still raise on shape/dtype mismatch.
    ``counts`` (optional) are the per-block row counts the caller
    already tracks (``_Request.n_rows``) — same ``fromiter``-over-ints
    trick as ``addrs``, sparing a generator over ``shape`` reads."""
    if out.ndim != 2 or not out.flags.c_contiguous:
        raise ValueError("serve_gather needs a C-contiguous 2D out buffer")
    if not trusted:
        total = 0
        for b in blocks:
            if (b.ndim != 2 or b.dtype != out.dtype
                    or b.shape[1] != out.shape[1]
                    or not b.flags.c_contiguous):
                raise ValueError(
                    f"serve_gather block mismatch: {b.shape}/{b.dtype} "
                    f"into {out.shape}/{out.dtype}")
            total += b.shape[0]
        if total > out.shape[0]:
            raise ValueError(
                f"serve_gather overflow: {total} rows into {out.shape[0]}")
    lib = _load()
    if lib is not None:
        n = len(blocks)
        if addrs is not None and len(addrs) == n:
            ptrs = np.fromiter(addrs, np.uint64, n)
        else:
            ptrs = np.fromiter((b.ctypes.data for b in blocks),
                               np.uint64, n)
        row_nbytes = out.strides[0]
        if counts is not None and len(counts) == n:
            sizes = np.fromiter(counts, np.int64, n) * row_nbytes
        else:
            sizes = np.fromiter((b.shape[0] for b in blocks),
                                np.int64, n) * row_nbytes
        rc = lib.serve_gather(ptrs.ctypes.data, sizes.ctypes.data, n,
                              out.ctypes.data, out.nbytes)
        if rc == 0:
            return out
    off = 0
    for b in blocks:
        out[off:off + b.shape[0]] = b
        off += b.shape[0]
    out[off:] = 0
    return out


def serve_scatter(src, counts, via_native=False):
    """Slice the batch result ``src``'s leading rows back into
    per-request arrays of ``counts`` rows each (submission order). The
    returned arrays are C-contiguous row windows of ONE result block
    allocated here (disjoint regions — a client mutating its response
    cannot touch a neighbor's), detached from ``src``; their bytes are
    exactly the legacy per-request ``np.array(src[a:b], copy=True)``
    (bit-identical, pinned by test). Handles 1D results (predict
    labels) and 2D (transforms) alike.

    The one-block design IS the fast path: one allocation + one
    contiguous copy + cheap views, instead of the legacy's per-request
    allocate-and-copy. Because the destination regions are consecutive,
    the default copy is a single vectorized assignment — setting up the
    C entry point's pointer arrays would cost more than it saves.
    ``via_native=True`` forces the copy through the C ``serve_scatter``
    (per-region ``memcpy`` from base-plus-offset pointer arithmetic,
    zero per-request ``.ctypes`` reads) — the parity tests pin the two
    routes byte-identical, and it is the route for any future caller
    whose destinations are NOT one contiguous block."""
    if src.ndim < 1 or not src.flags.c_contiguous:
        raise ValueError("serve_scatter needs a C-contiguous array")
    cnts = np.asarray(counts, np.int64)
    ends = np.cumsum(cnts)
    total = int(ends[-1]) if cnts.size else 0
    if total > src.shape[0] or (cnts.size and int(cnts.min()) < 0):
        raise ValueError(
            f"serve_scatter overflow: rows {list(counts)} from "
            f"{src.shape[0]}")
    block = np.empty((total,) + src.shape[1:], src.dtype)
    done = False
    if via_native and total:
        lib = _load()
        if lib is not None:
            n = cnts.size
            sizes = cnts * block.strides[0]
            ptrs = np.zeros(n, np.uint64)
            np.cumsum(sizes[:-1], out=ptrs[1:].view(np.int64))
            ptrs += block.ctypes.data
            done = lib.serve_scatter(src.ctypes.data, src.nbytes,
                                     ptrs.ctypes.data, sizes.ctypes.data,
                                     n) == 0
    if not done and total:
        block[:] = src[:total]
    outs, lo = [], 0
    for hi in ends.tolist():
        outs.append(block[lo:hi])
        lo = hi
    return outs


# ---------------------------------------------------------------------------
# MurmurHash3
# ---------------------------------------------------------------------------


def _mm3_py(data, seed):
    """Pure-Python MurmurHash3 x86 32-bit (fallback)."""
    c1, c2 = 0xcc9e2d51, 0x1b873593
    h1 = seed & 0xFFFFFFFF
    length = len(data)
    rounded = length & ~3
    for i in range(0, rounded, 4):
        k1 = int.from_bytes(data[i:i + 4], "little")
        k1 = (k1 * c1) & 0xFFFFFFFF
        k1 = ((k1 << 15) | (k1 >> 17)) & 0xFFFFFFFF
        k1 = (k1 * c2) & 0xFFFFFFFF
        h1 ^= k1
        h1 = ((h1 << 13) | (h1 >> 19)) & 0xFFFFFFFF
        h1 = (h1 * 5 + 0xe6546b64) & 0xFFFFFFFF
    k1 = 0
    tail = data[rounded:]
    if len(tail) >= 3:
        k1 ^= tail[2] << 16
    if len(tail) >= 2:
        k1 ^= tail[1] << 8
    if len(tail) >= 1:
        k1 ^= tail[0]
        k1 = (k1 * c1) & 0xFFFFFFFF
        k1 = ((k1 << 15) | (k1 >> 17)) & 0xFFFFFFFF
        k1 = (k1 * c2) & 0xFFFFFFFF
        h1 ^= k1
    h1 ^= length
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85ebca6b) & 0xFFFFFFFF
    h1 ^= h1 >> 13
    h1 = (h1 * 0xc2b2ae35) & 0xFFFFFFFF
    h1 ^= h1 >> 16
    return h1


def murmurhash3_32(key, seed=0):
    """MurmurHash3 x86 32-bit of ``key`` (str or bytes)."""
    if isinstance(key, str):
        key = key.encode("utf-8")
    lib = _load()
    if lib is not None:
        return int(lib.murmurhash3_x86_32(key, len(key), seed & 0xFFFFFFFF))
    return _mm3_py(key, seed)


def murmurhash3_bulk(strings, seed=0):
    """Hash a sequence of str/bytes tokens; returns uint32 array."""
    encoded = []
    for s in strings:
        if isinstance(s, str):
            encoded.append(s.encode("utf-8"))
        elif isinstance(s, (bytes, bytearray)):
            encoded.append(bytes(s))
        else:
            # bytes(int) would allocate an int-sized zero buffer — never
            # what a hashing caller means
            raise TypeError(
                f"tokens must be str or bytes, got {type(s).__name__}")
    lib = _load()
    if lib is not None and encoded:
        buf = b"".join(encoded)
        offsets = np.zeros(len(encoded) + 1, np.int64)
        np.cumsum([len(e) for e in encoded], out=offsets[1:])
        out = np.empty(len(encoded), np.uint32)
        lib.murmurhash3_bulk(
            buf, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(encoded), seed & 0xFFFFFFFF,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
        return out
    return np.array([_mm3_py(e, seed) for e in encoded], np.uint32)


# ---------------------------------------------------------------------------
# CSV ingest
# ---------------------------------------------------------------------------


def csv_read_floats(path, delimiter=",", skip_header=1, max_rows=None):
    """Read a numeric CSV into a float32 array (NaN for non-numeric
    fields). Native path streams with the C parser; fallback is
    ``np.genfromtxt``."""
    path = os.fspath(path)
    lib = _load()
    if lib is not None:
        rows = ctypes.c_int64()
        cols = ctypes.c_int64()
        rc = lib.csv_shape(path.encode(), delimiter.encode(),
                           int(skip_header), ctypes.byref(rows),
                           ctypes.byref(cols))
        if rc == 0 and rows.value > 0:
            n = rows.value if max_rows is None else min(rows.value, max_rows)
            out = np.empty((n, cols.value), np.float32)
            got = lib.csv_parse_floats(
                path.encode(), delimiter.encode(), int(skip_header),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                n, cols.value)
            if got >= 0:
                return out[:got]
    # fallback shares the strtof-parity parser with the streaming reader
    # (np.genfromtxt follows Python float semantics — '1_000' -> 1000.0 —
    # and would diverge from the native path on the same file)
    n_cols = _probe_n_cols(path, delimiter, skip_header)
    if n_cols <= 0:
        return np.empty((0, 0), np.float32)
    lines = []
    with open(path, "r") as f:
        for _ in range(skip_header):
            f.readline()
        for ln in f:
            if max_rows is not None and len(lines) >= max_rows:
                break  # early stop — never materialize the whole file
            if ln.strip():
                lines.append(ln)
    return _parse_lines(lines, delimiter, n_cols)


_NUM_PREFIX = None  # compiled lazily


def _parse_lines(lines, delimiter, n_cols):
    """Streaming-fallback parser matching the native ``parse_csv_line``
    (strtof) contract: each field is its leading numeric prefix (junk
    suffix ignored — so ``1_000`` is 1.0, not Python's 1000.0), with
    inf/nan literals; missing/invalid fields are NaN, extra fields are
    truncated, ragged rows NaN-pad. Known divergence: C hex-float
    literals (``0x1A``) parse as their leading decimal prefix here.
    Prefix-first, never bare ``float()`` — Python accepts literals strtof
    does not."""
    global _NUM_PREFIX
    if _NUM_PREFIX is None:
        import re

        _NUM_PREFIX = re.compile(
            r"^\s*[-+]?(?:inf(?:inity)?|nan"
            r"|(?:\d+\.?\d*|\.\d+)(?:[eE][-+]?\d+)?)",
            re.IGNORECASE)
    rows = np.full((len(lines), n_cols), np.nan, np.float32)
    for i, ln in enumerate(lines):
        parts = ln.rstrip("\r\n").split(delimiter)
        for c in range(min(n_cols, len(parts))):
            m = _NUM_PREFIX.match(parts[c])
            if m:
                rows[i, c] = float(m.group(0))
    return rows


def _probe_n_cols(path, delimiter, skip_header):
    """Column count from the first data line — NOT a full-file scan (the
    whole point of streaming is never reading the file twice)."""
    with open(path, "r") as f:
        for _ in range(skip_header):
            f.readline()
        line = f.readline()
        while line and not line.strip():
            line = f.readline()
        if not line:
            return 0
        return line.count(delimiter) + 1


def csv_stream_batches(path, batch_rows, delimiter=",", skip_header=1,
                       n_cols=None):
    """Yield (batch_rows, n_cols) float32 arrays from a numeric CSV without
    loading the file — the host-side input pipeline for incremental fits
    (``MiniBatchQKMeans.partial_fit``) on larger-than-memory data. The last
    batch may be short; non-numeric/missing fields parse as NaN, extra
    fields are dropped, blank (incl. whitespace-only) lines are skipped.

    Native path keeps one open stream (no per-batch rescan); the NumPy
    fallback implements the identical contract (pinned by tests).
    """
    path = os.fspath(path)
    if batch_rows <= 0:
        raise ValueError(f"batch_rows must be > 0, got {batch_rows}")
    if n_cols is None:
        # one line of lookahead, not csv_shape: that would scan the whole
        # (possibly larger-than-memory) file before the first batch
        n_cols = _probe_n_cols(path, delimiter, skip_header)
    if n_cols <= 0:
        return iter(())
    return _stream_batches(path, batch_rows, delimiter, skip_header, n_cols)


def _stream_batches(path, batch_rows, delimiter, skip_header, n_cols):
    lib = _load()
    if lib is not None:
        handle = lib.csv_stream_open(path.encode(), delimiter.encode(),
                                     int(skip_header))
        if handle:
            try:
                while True:
                    out = np.empty((batch_rows, n_cols), np.float32)
                    got = lib.csv_stream_next(
                        handle,
                        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                        batch_rows, n_cols)
                    if got <= 0:
                        return
                    yield out[:got]
            finally:
                lib.csv_stream_close(handle)
            return
    # NumPy fallback: stream lines, parse per batch with the same field
    # semantics as the native stream
    with open(path, "r") as f:
        for _ in range(skip_header):
            f.readline()
        while True:
            lines = []
            while len(lines) < batch_rows:
                line = f.readline()
                if not line:
                    break
                if line.strip():
                    lines.append(line)
            if not lines:
                return
            yield _parse_lines(lines, delimiter, n_cols)


__all__ = ["native_available", "crc32", "lloyd_iter", "elkan_iter",
           "lloyd_run_batched", "kmeans_pp_batched", "argkmin",
           "murmurhash3_32", "murmurhash3_bulk", "csv_read_floats",
           "csv_stream_batches", "lz4_bound", "lz4_compress",
           "lz4_decompress", "byte_shuffle", "byte_unshuffle",
           "compress_array", "decompress_array", "serve_gather",
           "serve_scatter"]
