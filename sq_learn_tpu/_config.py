"""Global configuration for sq_learn_tpu.

Mirrors the two-level config system of the reference (``sklearn/_config.py:6-110``):
a module-level config dict with ``get_config`` / ``set_config`` / ``config_context``,
extended with the ``device`` switch that BASELINE designates for TPU dispatch and a
default dtype knob (TPUs natively prefer float32/bfloat16).
"""

import threading
from contextlib import contextmanager
from . import _knobs

_global_config = {
    "device": "auto",  # 'auto' | 'tpu' | 'cpu'
    "default_dtype": "float32",
    "assume_finite": False,
    "interactive_checks": True,
}

_threadlocal = threading.local()


def _get_threadlocal_config():
    """Per-thread view of the config (so config_context is thread-safe)."""
    if not hasattr(_threadlocal, "config"):
        _threadlocal.config = _global_config.copy()
    return _threadlocal.config


def get_config():
    """Retrieve current values for configuration set by :func:`set_config`.

    Returns
    -------
    config : dict
        Keys are parameter names that can be passed to :func:`set_config`.
    """
    return _get_threadlocal_config().copy()


def set_config(device=None, default_dtype=None, assume_finite=None,
               interactive_checks=None):
    """Set global sq_learn_tpu configuration.

    Parameters
    ----------
    device : {'auto', 'tpu', 'cpu'}, optional
        Backend selector. 'auto' uses JAX's default backend (TPU when one is
        attached, otherwise CPU). 'cpu' forces the XLA CPU backend — this is
        the NumPy-parity path: identical code, deterministic given the key.
    default_dtype : {'float32', 'float64', 'bfloat16'}, optional
        Default floating dtype for estimator inputs.
    assume_finite : bool, optional
        Skip finiteness validation of input arrays.
    interactive_checks : bool, optional
        Enable the warnings the reference emits on purely-classical paths.
    """
    local_config = _get_threadlocal_config()
    if device is not None:
        _parse_device(device)  # validate eagerly, not at resolve time
        local_config["device"] = device
    if default_dtype is not None:
        if default_dtype not in ("float32", "float64", "bfloat16"):
            raise ValueError(f"unsupported default_dtype {default_dtype!r}")
        local_config["default_dtype"] = default_dtype
        # Without x64, jnp silently downcasts float64 inputs to float32 —
        # honoring the opt-in requires flipping jax's flag. NOTE: unlike the
        # dict config this is process-global (jax has a single x64 mode).
        # Only ever *enable* it here: x64 may have been turned on
        # independently (JAX_ENABLE_X64=1) for work outside this library,
        # so selecting a 32-bit default must not clobber it.
        if default_dtype == "float64":
            import jax

            jax.config.update("jax_enable_x64", True)
    if assume_finite is not None:
        local_config["assume_finite"] = bool(assume_finite)
    if interactive_checks is not None:
        local_config["interactive_checks"] = bool(interactive_checks)


@contextmanager
def config_context(**new_config):
    """Context manager that temporarily overrides the global configuration
    (including jax's process-global x64 mode, which is restored on exit)."""
    import jax

    old_config = get_config()
    old_x64 = jax.config.jax_enable_x64
    set_config(**new_config)
    try:
        yield
    finally:
        local_config = _get_threadlocal_config()
        local_config.clear()
        local_config.update(old_config)
        jax.config.update("jax_enable_x64", old_x64)


def _parse_device(device):
    """Validate a device string; returns (name, index). 'auto' carries no
    index; 'cpu'/'tpu' accept an optional non-negative integer ('cpu:1')."""
    err = ValueError(
        f"device must be 'auto', 'tpu' or 'cpu' (the latter two optionally "
        f"with a non-negative index, e.g. 'cpu:1'), got {device!r}")
    if not isinstance(device, str):
        raise err
    name, sep, idx = device.partition(":")
    if name == "auto":
        if sep:
            raise err
        return name, 0
    if name not in ("tpu", "cpu"):
        raise err
    if not sep:
        return name, 0
    if not idx.isdigit():
        raise err
    return name, int(idx)


def resolve_device():
    """Return the concrete :class:`jax.Device` selected by the config.

    'auto' prefers an accelerator if JAX has one, falling back to CPU.
    'cpu'/'tpu' may carry a device index ('cpu:1') to pin a specific chip.
    """
    import jax

    device = _get_threadlocal_config()["device"]
    name, i = _parse_device(device)
    if name == "auto":
        return jax.devices()[0]
    if name == "cpu":
        pool = jax.devices("cpu")
    else:
        pool = [d for d in jax.devices() if d.platform != "cpu"]
        if not pool:
            raise RuntimeError(
                "device='tpu' requested but no accelerator is attached")
    if i >= len(pool):
        raise RuntimeError(
            f"device {device!r} requested but only {len(pool)} "
            f"{name} devices exist")
    return pool[i]


def on_cpu_backend():
    """True when computation runs on the host CPU — either because it is
    the default backend or because a ``set_config(device='cpu...')`` pin
    is active. The one predicate behind every host-fast-path dispatch
    decision (estimators re-export it as ``_on_cpu_backend``)."""
    import jax

    return (jax.default_backend() == "cpu"
            or _get_threadlocal_config()["device"].startswith("cpu"))


#: Fits whose input has at most this many elements (n_samples × n_features)
#: are dispatch-bound, not compute-bound, on a remote accelerator: at
#: digits scale (1797×64 ≈ 115k elements) the arithmetic is sub-millisecond
#: on either engine, so wall-clock is pure host↔device round-trips — which
#: over the tunneled chip measured 20× slower than the host engines (round-1
#: TPU headline: 1.43 s vs 0.063 s sklearn). 2^18 elements = 1 MiB of f32,
#: comfortably past digits while 3 decades under the MNIST/covtype configs
#: that genuinely use the chip. Set SQ_TINY_FIT_ELEMENTS=0 to disable.
#:
#: PROVISIONAL: the 1.43 s justification predates the fused one-dispatch
#: fit and the persistent compile cache; the current chip-path cost has
#: never been re-measured (the runbook's step 3b,
#: ``bench/run_tpu_window.sh`` "chip_headline_unrouted", exists to do so
#: in the first healthy tunnel window). Until that record lands, treat
#: the cutoff as a conservative policy guess, not a measured constant.
_TINY_FIT_ELEMENTS = _knobs.get_int("SQ_TINY_FIT_ELEMENTS")


def _default_backend_platform_no_init():
    """Platform of jax's default backend WITHOUT forcing backend init.

    Initializing a backend over a wedged accelerator relay can hang
    indefinitely (CLAUDE.md), so a pure dispatch-policy question must
    never be the thing that first touches the tunnel. Three tiers:

    - backends already initialized → the authoritative answer;
    - a ``jax_platforms`` spec is pinned (e.g. this environment's
      ``JAX_PLATFORMS=axon,cpu`` or the test conftest's in-process
      ``jax.config.update("jax_platforms", "cpu")``) → its first entry,
      which is what jax will pick as default once it does initialize;
    - no spec (auto-detect) → ``None``: unknowable without an init.
    """
    import jax
    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        return jax.default_backend()
    spec = jax.config.jax_platforms
    if spec:
        return spec.split(",")[0].strip()
    return None


def route_tiny_fit_to_host(n_elements):
    """Dispatch policy for tiny fits when the default backend is a remote
    accelerator: True = run the fit on the host CPU engines instead of
    paying tunnel round-trips that dominate digit-scale problems.

    Only engages under ``device='auto'`` — an explicit
    ``set_config(device='tpu')`` (or ``'cpu'``) pin is always respected,
    which is also the escape hatch for deliberately timing the chip on a
    tiny problem.

    The DECISION predicate never initializes jax's backends (see
    :func:`_default_backend_platform_no_init`), so asking the question
    cannot itself hang on a wedged tunnel; only auto-detect installs with
    no ``jax_platforms`` spec fall back to a real
    ``jax.default_backend()`` call (local backends, no tunnel). The
    ACTION side is a weaker guarantee: :func:`host_routed_scope` pins the
    CPU backend for the routed work, but entering it still initializes
    jax's platform set, so the FIRST routed call in a process can touch a
    wedged relay during that one-time init — ``bench.py``-style callers
    who need a hard no-hang guarantee must keep their subprocess probe."""
    cfg = _get_threadlocal_config()
    if cfg["device"] != "auto" or _TINY_FIT_ELEMENTS <= 0:
        return False
    platform = _default_backend_platform_no_init()
    if platform is None:
        import jax

        platform = jax.default_backend()
    if platform == "cpu":
        return False
    return n_elements <= _TINY_FIT_ELEMENTS


#: fit_backend_ provenance value recorded by every tiny-routed surface
TINY_ROUTED_BACKEND = "cpu:tiny-routed"


@contextmanager
def host_routed_scope():
    """The ACTION side of :func:`route_tiny_fit_to_host` in one manager:
    a cpu device pin plus the matching ``device_scope``, so every jax op
    inside (key creation, eager casts, jits) stays on the host backend.
    The DECISION side — the size predicate and each estimator's bypass
    conditions (mesh, explicit kernels, dtypes) — stays at the call
    sites, which is where they differ; the routing dance itself must not
    drift across the routed surfaces (QKMeans fit/predict/score/transform,
    QPCA fit/transform — fit_transform's halves route independently —
    QLSSVC predict, minibatch fit/partial_fit, the KNN search)."""
    with config_context(device="cpu"):
        with device_scope():
            yield


def dispatch_tiny_routed(route, impl):
    """The routed-fit contract shared by every fit-shaped surface
    (QKMeans.fit, QPCA.fit, minibatch fit/partial_fit): run ``impl()``
    under :func:`host_routed_scope` when ``route`` is truthy, else on the
    current backend. Returns ``(out, fit_backend_label)`` — the label is
    returned rather than assigned so callers set their public
    ``fit_backend_`` only after ``impl`` has succeeded (a raise mid-fit
    must not leave a fitted-looking attribute behind for checkpointing
    to serialize). The inference-shaped surfaces (QKMeans
    predict/score's cpu-pin re-entry, the KNN search's optional host
    result) keep their own shapes on top of ``host_routed_scope``."""
    if route:
        with host_routed_scope():
            out = impl()
        return out, TINY_ROUTED_BACKEND
    import jax

    backend = "cpu" if on_cpu_backend() else jax.default_backend()
    out = impl()
    return out, backend


def device_scope():
    """Context manager scoping computation to the configured device.

    Under 'auto' this is a no-op. Otherwise ``resolve_device()`` becomes
    jax's default device for the scope, so even implicitly created arrays
    (PRNG keys, ``jnp.ones`` companions, eager casts) never touch the
    default backend — with a wedged accelerator tunnel and
    ``set_config(device='cpu')``, nothing can hang on the tunnel.
    """
    import contextlib

    if _get_threadlocal_config()["device"] == "auto":
        return contextlib.nullcontext()
    import jax

    return jax.default_device(resolve_device())


def with_device_scope(method):
    """Decorator running an estimator method under :func:`device_scope`."""
    import functools

    @functools.wraps(method)
    def wrapper(*args, **kwargs):
        with device_scope():
            return method(*args, **kwargs)

    return wrapper


def enable_persistent_compilation_cache(path=None, min_entry_bytes=0,
                                        min_compile_secs=0.0):
    """Point jax's persistent compilation cache at ``path`` (default
    ``SQ_COMPILE_CACHE_DIR``); returns the directory used, or None when
    neither is set (no-op).

    Process-global, like every ``jax.config`` mutation this module owns
    (x64 above): once enabled, EVERY compile in the process persists
    under the thresholds given. The serving AOT warm
    (:mod:`sq_learn_tpu.serving.aot`) calls this with zero thresholds so
    a restarted server re-loads its warmed executables from disk instead
    of re-lowering them; accelerator bench runs keep using
    ``bench._common._enable_compilation_cache`` (same jax knobs, probe-
    gated so a wedged tunnel is never touched). The CPU-backend caveat
    recorded there (host-specific AOT code + loader warnings after a
    host rotation) applies to long-lived cache dirs; serving smokes use
    a fresh directory per run.
    """
    if path is None:
        path = _knobs.get_raw("SQ_COMPILE_CACHE_DIR")
    if not path:
        return None
    import jax

    jax.config.update("jax_compilation_cache_dir", str(path))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                      int(min_entry_bytes))
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_secs))
    return str(path)


#: Host→device transfers are streamed in slices no larger than this. Every
#: observed axon-relay wedge hit during a single ≥200 MB host→device upload
#: (never during small transfers), so keeping each relay transaction under
#: 128 MB lets full-MNIST-sized operands (70k×784 f32 ≈ 220 MB) reach the
#: chip as two transactions; the full array only ever exists in HBM.
_TRANSFER_CHUNK_BYTES = _knobs.get_int("SQ_TRANSFER_CHUNK_BYTES")


def _put_host(x, device=None, max_bytes=None):
    """Place host data on ``device``, streaming anything larger than
    ``max_bytes`` through the supervised tiled engine.

    Semantically identical to ``jax.device_put(np.asarray(x), device)``
    (dtype canonicalization included). Small operands (and host→host
    copies under the default cap, which can't wedge a relay) take the
    direct ``device_put`` fast path; a large host operand bound for an
    accelerator rides :func:`sq_learn_tpu.streaming.streamed_resident_put`
    — supervised bounded transfers, double-buffered uploads, donated
    in-place assembly (no slice-then-concatenate 2× peak), the
    ``streaming.assemble`` watchdog/xla-cost site. Passing ``max_bytes``
    explicitly forces the streamed assembly on any backend, which is how
    the CPU-backend tests exercise it.
    """
    import jax
    import numpy as np
    import jax.numpy as jnp

    explicit = max_bytes is not None
    if max_bytes is None:
        max_bytes = _TRANSFER_CHUNK_BYTES
    if isinstance(x, jax.Array):
        on_host = all(d.platform == "cpu" for d in x.devices())
        to_accel = device is not None and device.platform != "cpu"
        if not (on_host and to_accel and x.nbytes > max_bytes):
            return jax.device_put(x, device) if device is not None else x
        # a host-backend jax.Array bound for the accelerator is the same
        # oversized relay upload as numpy data — fall through and slice it
    x = np.asarray(x)
    # jnp.asarray canonicalizes on the host before transfer (f64→f32
    # without x64); matching it here also halves the upload for float64
    # host data.
    canonical = jax.dtypes.canonicalize_dtype(x.dtype)
    if x.dtype != canonical:
        x = x.astype(canonical)
    platform = (device.platform if device is not None
                else jax.default_backend())
    if (x.nbytes <= max_bytes or x.ndim == 0
            or (platform == "cpu" and not explicit)):
        return jax.device_put(x, device) if device is not None else jnp.asarray(x)
    from .streaming import streamed_resident_put

    return streamed_resident_put(x, device=device, max_bytes=max_bytes)


def chunked_device_put(x, device=None, max_bytes=None):
    """REMOVED (deprecated since PR 3, all in-repo callers migrated by
    PR 7). The slice-then-concatenate wrapper this name survived for no
    longer exists; raising keeps external callers' failures loud and
    actionable instead of silently changing semantics."""
    raise RuntimeError(
        "chunked_device_put was removed: use "
        "sq_learn_tpu.streaming.streamed_resident_put(x, device=..., "
        "max_bytes=...) for whole-array placement (supervised bounded "
        "tiles, donated in-place assembly), stream_fold for "
        "tile-sequential accumulations, or as_device_array for "
        "config-routed placement.")


def as_device_array(x):
    """``jnp.asarray`` honoring ``set_config(device=...)`` — the dispatch
    hook BASELINE designates on the reference's config system
    (``sklearn/_config.py:6-110``).

    Under 'auto' the array stays uncommitted (JAX's default placement).
    Otherwise it is **committed** to :func:`resolve_device`, which pins
    every downstream jit that consumes it to that device — this is the
    CPU-parity dispatch of SURVEY §7 step 1: identical code, selectable
    backend. Host data is converted with numpy first so a wedged default
    accelerator is never touched when a CPU device is requested.

    Large host operands bound for an accelerator are streamed through
    the supervised tiled engine (see :func:`_put_host`).
    """
    if _get_threadlocal_config()["device"] == "auto":
        return _put_host(x, None)
    return _put_host(x, resolve_device())


def default_dtype():
    import jax.numpy as jnp

    return {
        "float32": jnp.float32,
        "float64": jnp.float64,
        "bfloat16": jnp.bfloat16,
    }[_get_threadlocal_config()["default_dtype"]]
