"""Global configuration for sq_learn_tpu.

Mirrors the two-level config system of the reference (``sklearn/_config.py:6-110``):
a module-level config dict with ``get_config`` / ``set_config`` / ``config_context``,
extended with the ``device`` switch that BASELINE designates for TPU dispatch and a
default dtype knob (TPUs natively prefer float32/bfloat16).
"""

import threading
from contextlib import contextmanager

_global_config = {
    "device": "auto",  # 'auto' | 'tpu' | 'cpu'
    "default_dtype": "float32",
    "assume_finite": False,
    "interactive_checks": True,
}

_threadlocal = threading.local()


def _get_threadlocal_config():
    """Per-thread view of the config (so config_context is thread-safe)."""
    if not hasattr(_threadlocal, "config"):
        _threadlocal.config = _global_config.copy()
    return _threadlocal.config


def get_config():
    """Retrieve current values for configuration set by :func:`set_config`.

    Returns
    -------
    config : dict
        Keys are parameter names that can be passed to :func:`set_config`.
    """
    return _get_threadlocal_config().copy()


def set_config(device=None, default_dtype=None, assume_finite=None,
               interactive_checks=None):
    """Set global sq_learn_tpu configuration.

    Parameters
    ----------
    device : {'auto', 'tpu', 'cpu'}, optional
        Backend selector. 'auto' uses JAX's default backend (TPU when one is
        attached, otherwise CPU). 'cpu' forces the XLA CPU backend — this is
        the NumPy-parity path: identical code, deterministic given the key.
    default_dtype : {'float32', 'float64', 'bfloat16'}, optional
        Default floating dtype for estimator inputs.
    assume_finite : bool, optional
        Skip finiteness validation of input arrays.
    interactive_checks : bool, optional
        Enable the warnings the reference emits on purely-classical paths.
    """
    local_config = _get_threadlocal_config()
    if device is not None:
        if device not in ("auto", "tpu", "cpu"):
            raise ValueError(f"device must be 'auto', 'tpu' or 'cpu', got {device!r}")
        local_config["device"] = device
    if default_dtype is not None:
        if default_dtype not in ("float32", "float64", "bfloat16"):
            raise ValueError(f"unsupported default_dtype {default_dtype!r}")
        local_config["default_dtype"] = default_dtype
        # Without x64, jnp silently downcasts float64 inputs to float32 —
        # honoring the opt-in requires flipping jax's flag. NOTE: unlike the
        # dict config this is process-global (jax has a single x64 mode).
        # Only ever *enable* it here: x64 may have been turned on
        # independently (JAX_ENABLE_X64=1) for work outside this library,
        # so selecting a 32-bit default must not clobber it.
        if default_dtype == "float64":
            import jax

            jax.config.update("jax_enable_x64", True)
    if assume_finite is not None:
        local_config["assume_finite"] = bool(assume_finite)
    if interactive_checks is not None:
        local_config["interactive_checks"] = bool(interactive_checks)


@contextmanager
def config_context(**new_config):
    """Context manager that temporarily overrides the global configuration
    (including jax's process-global x64 mode, which is restored on exit)."""
    import jax

    old_config = get_config()
    old_x64 = jax.config.jax_enable_x64
    set_config(**new_config)
    try:
        yield
    finally:
        local_config = _get_threadlocal_config()
        local_config.clear()
        local_config.update(old_config)
        jax.config.update("jax_enable_x64", old_x64)


def resolve_device():
    """Return the concrete :class:`jax.Device` selected by the config.

    'auto' prefers an accelerator if JAX has one, falling back to CPU.
    """
    import jax

    device = _get_threadlocal_config()["device"]
    if device == "cpu":
        return jax.devices("cpu")[0]
    if device == "tpu":
        for d in jax.devices():
            if d.platform != "cpu":
                return d
        raise RuntimeError("device='tpu' requested but no accelerator is attached")
    return jax.devices()[0]


def default_dtype():
    import jax.numpy as jnp

    return {
        "float32": jnp.float32,
        "float64": jnp.float64,
        "bfloat16": jnp.bfloat16,
    }[_get_threadlocal_config()["default_dtype"]]
