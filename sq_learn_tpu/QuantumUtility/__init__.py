"""QuantumUtility — reference-namespace facade (``sklearn/QuantumUtility``).

The reference re-exports its whole routine library from this package
(``QuantumUtility/__init__.py:5-6``). Same surface here, with the
TPU-native implementations behind the reference's names
(``Utility.py`` symbol → ours):

- ``QuantumState`` (:25), ``tomography`` (:107), ``real_tomography``
  (:259), ``amplitude_estimation`` (:442), ``phase_estimation`` (:591),
  ``consistent_phase_estimation`` (:740), ``ipe`` (:697),
  ``median_evaluation`` (:534) — same names.
- ``introduce_error`` (:68) / ``introduce_error_array`` (:71) — same
  names; ``make_gaussian_est`` (:88) → :func:`gaussian_estimate` (alias
  kept).
- ``best_mu`` (:222) / ``linear_search`` (:215) / ``mu`` (:196) — same.
- ``estimate_wald`` (:61), ``coupon_collect`` (:75),
  ``create_rand_vec`` (:183) — same names.
- ``wrapper_phase_est_arguments`` (:575) / ``unwrap_phase_est_arguments``
  (:584) → :func:`sv_to_theta` / :func:`theta_to_sv` (aliases kept).

``check_division`` (:425), ``check_measure`` (:414),
``amplitude_est_dist`` (:435), ``auxiliary_fun`` (:404) and
``vectorize_aux_fun`` (:409) are kept as drop-in compatibility shims —
nothing internal consumes them (the batched kernels replace the Pool
work-splitting outright, SURVEY §2.3, and the incremental tomography
schedule de-duplicates inline), but reference code that calls them runs
unmodified.
"""

import jax
import jax.numpy as jnp

from ..ops.quantum import (
    QuantumState,
    amplitude_estimation,
    best_mu,
    consistent_phase_estimation,
    coupon_collect,
    estimate_wald,
    gaussian_estimate,
    introduce_error,
    introduce_error_array,
    ipe,
    linear_search,
    median_evaluation,
    mu,
    phase_estimation,
    real_tomography,
    tomography,
    tomography_incremental,
)
from ..ops.quantum.estimation import sv_to_theta, theta_to_sv
from ..ops.quantum.tomography import magnitude_tomography_signed

# reference name (misspelling and all, Utility.py:234) kept as an alias
L2_tomogrphy_fakeSign = magnitude_tomography_signed

# reference aliases
make_gaussian_est = gaussian_estimate
wrapper_phase_est_arguments = sv_to_theta
unwrap_phase_est_arguments = theta_to_sv


def create_rand_vec(key, n_vec, len_vec, scale=1.0, type="uniform"):
    """Random (possibly unnormalized) vectors (reference ``create_rand_vec``,
    ``Utility.py:183``): ``n_vec`` vectors of length ``len_vec``."""
    if type == "uniform":
        v = jax.random.uniform(key, (n_vec, len_vec),
                               minval=-scale, maxval=scale)
    elif type == "normal":
        v = scale * jax.random.normal(key, (n_vec, len_vec))
    else:
        raise ValueError(f"type must be 'uniform' or 'normal', got {type!r}")
    return v


def check_measure(arr, faster_measure_increment):
    """Monotone measure-schedule fixup (reference ``check_measure``,
    ``Utility.py:414``): bump equal/decreasing consecutive entries by
    ``5 + faster_measure_increment`` so the schedule strictly increases.
    Compatibility shim — :func:`tomography_incremental` de-duplicates its
    schedule inline."""
    arr = list(arr)
    incr = 5 + faster_measure_increment
    for i in range(len(arr) - 1):
        if arr[i + 1] == arr[i]:
            arr[i + 1] += incr
        if arr[i + 1] <= arr[i]:
            arr[i + 1] = arr[i] + incr
    return arr


def check_division(v, n_jobs):
    """Split ``v`` work items into ``n_jobs`` near-equal integer chunks
    (reference ``check_division``, ``Utility.py:425``). Compatibility
    shim — the vectorized kernels replaced the reference's process-pool
    fan-out, so nothing internal consumes this."""
    base = int(v) // n_jobs
    out = [base] * n_jobs
    for i in range(int(v) - base * n_jobs):
        out[i] += 1
    return out


def amplitude_est_dist(w0, w1):
    """Circular (mod-1) distance between two phase-grid points (reference
    ``amplitude_est_dist``, ``Utility.py:435``)."""
    d = jnp.asarray(w1) - jnp.asarray(w0)
    return jnp.minimum(jnp.abs(-jnp.ceil(d) + d), jnp.abs(-jnp.floor(d) + d))


def auxiliary_fun(q_state, i, key=None):
    """Measure ``q_state`` ``i`` times (reference ``auxiliary_fun``,
    ``Utility.py:404``). The reference's version draws from a fresh
    process-global RNG; ours takes an explicit key (a fresh
    entropy-seeded key when omitted, for drop-in calls)."""
    if key is None:
        import numpy as _np

        key = jax.random.PRNGKey(int(_np.random.SeedSequence().entropy
                                     & 0x7FFFFFFF))
    return q_state.measure(key, n_times=int(i))


def vectorize_aux_fun(dic, i):
    """√(count fraction) lookup with 0 default (reference
    ``vectorize_aux_fun``, ``Utility.py:409``)."""
    return jnp.sqrt(dic[i]) if i in dic else 0


__all__ = [
    "QuantumState",
    "amplitude_est_dist",
    "auxiliary_fun",
    "check_division",
    "check_measure",
    "vectorize_aux_fun",
    "amplitude_estimation",
    "best_mu",
    "consistent_phase_estimation",
    "coupon_collect",
    "create_rand_vec",
    "estimate_wald",
    "gaussian_estimate",
    "introduce_error",
    "introduce_error_array",
    "ipe",
    "linear_search",
    "make_gaussian_est",
    "median_evaluation",
    "mu",
    "phase_estimation",
    "real_tomography",
    "sv_to_theta",
    "theta_to_sv",
    "tomography",
    "tomography_incremental",
    "unwrap_phase_est_arguments",
    "wrapper_phase_est_arguments",
]
