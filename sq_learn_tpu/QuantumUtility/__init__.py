"""QuantumUtility — reference-namespace facade (``sklearn/QuantumUtility``).

The reference re-exports its whole routine library from this package
(``QuantumUtility/__init__.py:5-6``). Same surface here, with the
TPU-native implementations behind the reference's names
(``Utility.py`` symbol → ours):

- ``QuantumState`` (:25), ``tomography`` (:107), ``real_tomography``
  (:259), ``amplitude_estimation`` (:442), ``phase_estimation`` (:591),
  ``consistent_phase_estimation`` (:740), ``ipe`` (:697),
  ``median_evaluation`` (:534) — same names.
- ``introduce_error`` (:68) / ``introduce_error_array`` (:71) — same
  names; ``make_gaussian_est`` (:88) → :func:`gaussian_estimate` (alias
  kept).
- ``best_mu`` (:222) / ``linear_search`` (:215) / ``mu`` (:196) — same.
- ``estimate_wald`` (:61), ``coupon_collect`` (:75),
  ``create_rand_vec`` (:183) — same names.
- ``wrapper_phase_est_arguments`` (:575) / ``unwrap_phase_est_arguments``
  (:584) → :func:`sv_to_theta` / :func:`theta_to_sv` (aliases kept).

``check_division`` (:425) has no equivalent: it splits work across a
``multiprocessing.Pool``, which the batched kernels replace outright
(SURVEY §2.3). ``check_measure`` (:414) lives inside
:func:`~sq_learn_tpu.ops.quantum.tomography_incremental`'s schedule
handling.
"""

import jax
import jax.numpy as jnp

from ..ops.quantum import (
    QuantumState,
    amplitude_estimation,
    best_mu,
    consistent_phase_estimation,
    coupon_collect,
    estimate_wald,
    gaussian_estimate,
    introduce_error,
    introduce_error_array,
    ipe,
    linear_search,
    median_evaluation,
    mu,
    phase_estimation,
    real_tomography,
    tomography,
    tomography_incremental,
)
from ..ops.quantum.estimation import sv_to_theta, theta_to_sv
from ..ops.quantum.tomography import magnitude_tomography_signed

# reference name (misspelling and all, Utility.py:234) kept as an alias
L2_tomogrphy_fakeSign = magnitude_tomography_signed

# reference aliases
make_gaussian_est = gaussian_estimate
wrapper_phase_est_arguments = sv_to_theta
unwrap_phase_est_arguments = theta_to_sv


def create_rand_vec(key, n_vec, len_vec, scale=1.0, type="uniform"):
    """Random (possibly unnormalized) vectors (reference ``create_rand_vec``,
    ``Utility.py:183``): ``n_vec`` vectors of length ``len_vec``."""
    if type == "uniform":
        v = jax.random.uniform(key, (n_vec, len_vec),
                               minval=-scale, maxval=scale)
    elif type == "normal":
        v = scale * jax.random.normal(key, (n_vec, len_vec))
    else:
        raise ValueError(f"type must be 'uniform' or 'normal', got {type!r}")
    return v


__all__ = [
    "QuantumState",
    "amplitude_estimation",
    "best_mu",
    "consistent_phase_estimation",
    "coupon_collect",
    "create_rand_vec",
    "estimate_wald",
    "gaussian_estimate",
    "introduce_error",
    "introduce_error_array",
    "ipe",
    "linear_search",
    "make_gaussian_est",
    "median_evaluation",
    "mu",
    "phase_estimation",
    "real_tomography",
    "sv_to_theta",
    "theta_to_sv",
    "tomography",
    "tomography_incremental",
    "unwrap_phase_est_arguments",
    "wrapper_phase_est_arguments",
]
