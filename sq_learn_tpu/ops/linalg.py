"""XLA linear algebra (reference layer L1, ``sklearn/utils/extmath.py``).

Everything here is jit-able and shaped for the MXU: tall-skinny SVDs go
through an m×m Gram eigendecomposition (SURVEY §7: "full-SVD of 70k×784 on
TPU → compute Gram 784×784 eigh"), randomized SVD follows Halko et al. as in
``extmath.py:161-392`` (range finder + power iterations + small SVD), and
pairwise distances use the ‖x‖²+‖c‖²−2XCᵀ GEMM trick that the reference's
Cython Lloyd kernel uses (``_k_means_lloyd.pyx:196-203``).
"""

import functools

import jax
import jax.numpy as jnp


def row_norms(X, squared=False):
    """Row-wise L2 norms (reference ``extmath.py:49``)."""
    X = jnp.asarray(X)
    norms = jnp.sum(X * X, axis=1)
    return norms if squared else jnp.sqrt(norms)


def svd_flip(u, v):
    """Sign correction for deterministic SVD output (reference
    ``extmath.py:522``): the largest-|.|-entry column of u is made positive."""
    max_abs_cols = jnp.argmax(jnp.abs(u), axis=0)
    signs = jnp.sign(u[max_abs_cols, jnp.arange(u.shape[1])])
    signs = jnp.where(signs == 0, 1.0, signs)
    return u * signs, v * signs[:, None]


def svd_flip_v(u, v):
    """Sign correction from V's rows (sklearn's ``u_based_decision=False``
    variant of ``svd_flip``): the largest-|.|-entry of each right singular
    vector is made positive. Lets thin SVDs fix signs without ever
    materializing the full U factor; ``u`` may be None or a partial
    (n, k≤r) block — only its first ``len(signs)`` columns are flipped."""
    max_abs_rows = jnp.argmax(jnp.abs(v), axis=1)
    signs = jnp.sign(v[jnp.arange(v.shape[0]), max_abs_rows])
    signs = jnp.where(signs == 0, 1.0, signs)
    if u is not None:
        u = u * signs[: u.shape[1]]
    return u, v * signs[:, None]


def gram_spectrum(G):
    """Descending singular spectrum from a Gram matrix: eigh → flip →
    clamped sqrt. Returns (S, V, safe) with ``safe`` the zero-guarded
    divisor for recovering the paired factor — the one definition shared
    by the single-device and mesh-sharded SVD routes."""
    evals, V = jnp.linalg.eigh(G)  # ascending
    evals = jnp.flip(evals, 0)
    V = jnp.flip(V, 1)
    S = jnp.sqrt(jnp.maximum(evals, 0.0))
    return S, V, jnp.where(S > 0, S, 1.0)


@functools.partial(jax.jit, static_argnames=("method",))
def thin_svd(X, method="auto"):
    """Thin SVD X = U·diag(S)·Vt with U (n,r), S (r,), Vt (r,m), r=min(n,m).

    method 'gram' squares the shorter side (fast on the MXU for very
    rectangular matrices, costs some accuracy for tiny singular values);
    'direct' calls the XLA SVD; 'auto' picks 'gram' when the aspect ratio
    is ≥ 8.
    """
    X = jnp.asarray(X)
    n, m = X.shape
    if method == "auto":
        method = "gram" if max(n, m) >= 8 * min(n, m) else "direct"
    if method == "direct":
        U, S, Vt = jnp.linalg.svd(X, full_matrices=False)
        return U, S, Vt
    if n >= m:
        G = X.T @ X  # (m, m) — one big MXU GEMM
        S, V, safe = gram_spectrum(G)
        U = (X @ V) / safe[None, :]
        return U, S, V.T
    G = X @ X.T  # (n, n)
    S, U, safe = gram_spectrum(G)
    Vt = (U.T @ X) / safe[:, None]
    return U, S, Vt


def centered_svd(X, method="auto"):
    """Column-center X and return (mean, U, S, Vt) with deterministic
    V-based signs (:func:`svd_flip_v` — the convention every PCA path in
    the package shares, so partial-U routes agree with full ones) — the
    core of every PCA fit (reference ``_qPCA.py:578-583``)."""
    X = jnp.asarray(X)
    mean = jnp.mean(X, axis=0)
    U, S, Vt = thin_svd(X - mean, method=method)
    U, Vt = svd_flip_v(U, Vt)
    return mean, U, S, Vt


@functools.partial(jax.jit, static_argnames=("n_left", "compute_dtype"))
def centered_svd_topk(X, n_left, compute_dtype=None):
    """Centered Gram-route SVD of a TALL matrix materializing only the
    first ``n_left`` columns of U.

    The qPCA fit consumes the full spectrum and full Vt but only
    U[:, :n_components]; the full (n, r) U product is the same O(n·m²)
    GEMM as the Gram matrix itself, i.e. half the fit's FLOPs spent on
    output that is sliced away. V-based signs (:func:`svd_flip_v`) never
    need the unmaterialized columns; the U block pairs consistently.

    ``compute_dtype`` runs the two big GEMMs (Gram, U block) in the
    MXU-native reduced precision with input-dtype accumulation; the
    m×m eigh stays exact. Spectrum error is O(eps·‖X‖²) — a perf knob
    for explained-variance-scale work, not for tiny-σ analysis.
    """
    X = jnp.asarray(X)
    n, m = X.shape
    mean = jnp.mean(X, axis=0)
    Xc = X - mean
    G = inner_product(Xc.T, Xc.T, compute_dtype)  # (m, m)
    S, V, safe = gram_spectrum(G)
    _, Vt = svd_flip_v(None, V.T)
    Uk = inner_product(Xc, Vt[:n_left], compute_dtype) / safe[None, :n_left]
    return mean, Uk, S, Vt


@functools.partial(
    jax.jit, static_argnames=("n_components", "n_oversamples", "n_iter", "flip")
)
def randomized_svd(key, X, n_components, n_oversamples=10, n_iter=4, flip=True):
    """Randomized truncated SVD (Halko et al.; reference
    ``extmath.py:246-392``): Gaussian range finder, QR-normalized subspace
    power iterations, exact SVD of the small projected matrix.

    All dense GEMMs — this is the covertype benchmark kernel (BASELINE #4).
    """
    X = jnp.asarray(X)
    n, m = X.shape
    size = min(n_components + n_oversamples, min(n, m))
    transpose = n < m
    A = X.T if transpose else X  # ensure tall

    Q = jax.random.normal(key, (A.shape[1], size), dtype=X.dtype)
    Q = A @ Q
    for _ in range(n_iter):
        Q, _ = jnp.linalg.qr(A.T @ Q)
        Q = A @ Q
    Q, _ = jnp.linalg.qr(Q)
    B = Q.T @ A  # (size, min_dim)
    Uhat, S, Vt = jnp.linalg.svd(B, full_matrices=False)
    U = Q @ Uhat
    if transpose:
        U, S, Vt = Vt.T, S, U.T
    if flip:
        # flip AFTER any transpose-back so the V-based convention (the one
        # every SVD path shares) refers to the caller's orientation
        U, Vt = svd_flip_v(U, Vt)
    return U[:, :n_components], S[:n_components], Vt[:n_components]


def is_reduced(compute_dtype, dtype):
    """True when ``compute_dtype`` actually lowers precision relative to
    ``dtype`` (None or the same dtype is a no-op). The one predicate every
    reduced-precision code path gates on."""
    return compute_dtype is not None and jnp.dtype(compute_dtype) != jnp.dtype(dtype)


def check_compute_dtype(value):
    """Validate a ``compute_dtype`` hyperparameter to a dtype name (or
    None). Only float formats make sense — the point is the MXU-native
    GEMM precision; anything else silently truncates features."""
    if value is None:
        return None
    name = jnp.dtype(value).name
    if name not in ("bfloat16", "float16", "float32"):
        raise ValueError(
            f"compute_dtype must be None or a float dtype "
            f"(bfloat16/float16/float32), got {value!r}")
    return name


def inner_product(X, C, compute_dtype=None):
    """X·Cᵀ, optionally with the operands cast to a reduced
    ``compute_dtype`` (e.g. ``jnp.bfloat16`` — the MXU's native format,
    halving the HBM read of the dominant factor) while the products
    accumulate in the input dtype (``preferred_element_type``). One
    definition for every reduced-precision GEMM in the package."""
    if not is_reduced(compute_dtype, X.dtype):
        return X @ C.T
    return jax.lax.dot_general(
        X.astype(compute_dtype), C.astype(compute_dtype),
        (((1,), (1,)), ((), ())), preferred_element_type=X.dtype)


def pairwise_sq_distances(X, C, x_sq_norms=None, compute_dtype=None):
    """Squared Euclidean distances via ‖x‖² + ‖c‖² − 2·X·Cᵀ
    (the GEMM trick of ``_k_means_lloyd.pyx:191-203``), clipped at 0.

    ``compute_dtype`` runs the GEMM in reduced precision (see
    :func:`inner_product`); the norms/additions stay in the input dtype.
    The distance error is O(eps(compute_dtype) · ‖x‖‖c‖) — fine for
    selection (argmin), but near-centroid distances cancel three large
    terms, so consumers needing accurate VALUES must recompute the
    selected distances exactly (see ``qkmeans.e_step``).
    """
    X = jnp.asarray(X)
    C = jnp.asarray(C)
    if x_sq_norms is None:
        x_sq_norms = jnp.sum(X * X, axis=1)
    c_sq = jnp.sum(C * C, axis=1)
    d2 = x_sq_norms[:, None] + c_sq[None, :] \
        - 2.0 * inner_product(X, C, compute_dtype)
    return jnp.maximum(d2, 0.0)


def stable_cumsum(arr, axis=None):
    """Cumulative sum with float64 accumulation, cast back to the input
    dtype — reference ``extmath.py:829``. When x64 is disabled (the TPU
    default) this is a plain cumsum; enable x64 via
    ``set_config(default_dtype='float64')`` for stable accumulation."""
    arr = jnp.asarray(arr)
    if jax.config.jax_enable_x64 and arr.dtype != jnp.float64:
        return jnp.cumsum(arr.astype(jnp.float64), axis=axis).astype(arr.dtype)
    return jnp.cumsum(arr, axis=axis)


def smallest_singular_value(X):
    """σ_min via Gram eigh — replaces the reference's wasteful full SVD just
    for the condition number (``_dmeans.py:1244-1245``, SURVEY §3.2)."""
    X = jnp.asarray(X)
    n, m = X.shape
    G = X.T @ X if n >= m else X @ X.T
    evals = jnp.linalg.eigvalsh(G)
    return jnp.sqrt(jnp.maximum(evals[0], 0.0))
