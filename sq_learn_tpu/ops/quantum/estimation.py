"""Amplitude / phase / inner-product estimation.

TPU-native re-design of the reference's estimation routines
(``Utility.py:442-531`` amplitude estimation, ``:591-694`` phase estimation,
``:697-737`` IPE, ``:740-792`` consistent PE, ``:534-572`` median boosting).

The reference builds the exact M-point output pmf in a Python loop *per call*
and samples it with ``random.choices`` — O(M) work and memory per scalar, run
n·k times per q-means iteration. Here every routine is batched and jit'd:
the pmf is never materialized; grid indices are drawn by
:func:`~sq_learn_tpu.ops.quantum.sampling.fejer_grid_sample`, which enumerates
only the grid points near the true value (exact when the grid is small,
tail-truncated by O(1/window) otherwise) and supports *per-element traced*
grid sizes M. A batch of n·k estimations with n·k different precisions is one
fused XLA kernel.
"""

import math

import jax
import jax.numpy as jnp

from .sampling import fejer_grid_sample

_MEDIAN_CONST = 2 * (8 / math.pi**2 - 0.5) ** 2


def _eager(*values):
    """True when every value is concrete — the precondition for auditing
    a draw against its ground truth (inside a jit trace there is none)."""
    return not any(isinstance(v, jax.core.Tracer) for v in values)


def _observe_estimate(site, truth, est, tol, fail_prob, circular=False,
                      **attrs):
    """Emit ``guarantee`` records for one eager estimation call: the
    simulator knows the true value it perturbs, so each element of the
    batch is one audited draw of "|estimate − truth| ≤ tol w.p. ≥
    1 − fail_prob" (:mod:`sq_learn_tpu.obs.guarantees`). ``circular``
    measures distance on the unit phase circle (PE's ω ∈ [0, 1) wraps).
    No-op when observability is disabled."""
    from ... import obs as _obs

    if not _obs.guarantees.enabled():
        return
    import numpy as np

    t = np.asarray(truth, np.float64)
    e = np.asarray(est, np.float64)
    err = np.abs(np.broadcast_to(t, e.shape) - e).ravel()
    if circular:
        err = np.minimum(err, 1.0 - err)
    tol_arr = np.broadcast_to(
        np.asarray(tol, np.float64), e.shape).ravel()
    _obs.guarantees.observe(site, err, tol_arr, fail_prob=fail_prob,
                            **attrs)


def median_q(gamma):
    """Number of repetitions Q = ⌈ln(1/γ)/(2(8/π²−½)²)⌉ (odd) for median
    boosting (reference ``median_evaluation``, ``Utility.py:564-568``)."""
    q = int(math.ceil(math.log(1 / gamma) / _MEDIAN_CONST))
    return q + 1 if q % 2 == 0 else q


def median_evaluation(func, key, gamma=0.1, Q=None, **kwargs):
    """Run ``func(key=subkey, **kwargs)`` Q times and return the median.

    Generic failure-probability booster. Batched routines below inline this
    by drawing Q samples in one kernel; this wrapper exists for arbitrary
    callables (parity with reference ``median_evaluation``).
    """
    if Q is None:
        Q = median_q(gamma)
    keys = jax.random.split(key, int(Q))
    estimates = jnp.stack([jnp.asarray(func(key=k, **kwargs)) for k in keys])
    return jnp.median(estimates, axis=0)


def amplitude_estimation_M(epsilon):
    """Grid size M = ⌈(π/2ε)(1+√(1+4ε))⌉ (reference ``Utility.py:484``)."""
    return math.ceil((math.pi / (2 * epsilon)) * (1 + math.sqrt(1 + 4 * epsilon)))


def amplitude_estimation(key, a, epsilon=0.01, gamma=None, M=None, window=64):
    """Simulate amplitude estimation (Brassard et al.).

    θ_a = asin(√a); θ̃ is drawn from the exact M-point AE output distribution
    p(j) = |sin(MΔπ)/(M sin Δπ)|² with circular grid distance Δ; returns
    ã = sin²θ̃. Matches reference ``amplitude_estimation``
    (``Utility.py:442-531``) semantics, batched over ``a``.

    Parameters
    ----------
    key : jax key
    a : scalar or array in [0, 1]
    epsilon : float — target estimation error (sets M when M is None).
    gamma : float or None — failure probability; when given, Q median-boosted
        repetitions are drawn in one kernel (reference routes through
        ``median_evaluation``).
    M : int or None — explicit grid size override.
    window : static int — Fejér sampler half-width.
    """
    a = jnp.asarray(a)
    if M is None:
        M = amplitude_estimation_M(epsilon)
    theta_a = jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))
    w1 = theta_a / jnp.pi  # true value on the unit grid circle
    Q = 1 if gamma is None else median_q(gamma)
    j = fejer_grid_sample(key, w1 * M, float(M), window, sample_shape=(Q,))
    a_tilde = jnp.sin(jnp.pi * j / M) ** 2
    out = jnp.median(a_tilde, axis=0) if Q > 1 else a_tilde[0]
    if _eager(key, a):
        # AE contract: |ã − a| ≤ ε with prob ≥ 1−γ (median-boosted), or
        # ≥ 8/π² for a single draw (Brassard et al. Thm 12). ε stays the
        # declared tolerance even under an explicit (possibly
        # under-budgeted) M override — that mismatch is exactly what the
        # auditor exists to catch.
        _observe_estimate(
            "amplitude_estimation", jnp.clip(a, 0.0, 1.0), out,
            float(epsilon),
            float(gamma) if gamma is not None else 1.0 - 8 / math.pi**2,
            M=int(M))
    return out


def amplitude_estimation_per_eps(key, a, epsilon, Q=1, window=64):
    """Amplitude estimation with a *per-element* precision array.

    ``epsilon`` may be any array broadcastable to ``a``; each element gets its
    own grid size M(ε) as a traced value — this is what lets IPE over all
    (sample, centroid) pairs run as a single kernel instead of the
    reference's ``multiprocessing.Pool`` fan-out (``_dmeans.py:759-763``).
    """
    a = jnp.asarray(a)
    eps = jnp.broadcast_to(jnp.asarray(epsilon, a.dtype), a.shape)
    M = jnp.ceil((jnp.pi / (2 * eps)) * (1 + jnp.sqrt(1 + 4 * eps)))
    theta_a = jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))
    pos = theta_a / jnp.pi * M
    j = fejer_grid_sample(key, pos, M, window, sample_shape=(int(Q),))
    a_tilde = jnp.sin(jnp.pi * j / M) ** 2
    return jnp.median(a_tilde, axis=0) if Q > 1 else a_tilde[0]


def phase_estimation_m(epsilon, gamma=0.1):
    """Qubit count m = ⌈log2(1/ε)⌉ + ⌈log2(2 + 1/2γ)⌉ (Nielsen & Chuang
    eq. 5.35; reference ``Utility.py:635``)."""
    return int(
        math.ceil(math.log2(1 / epsilon)) + math.ceil(math.log2(2 + 1 / (2 * gamma)))
    )


def phase_estimation(key, omega, m=None, epsilon=None, gamma=0.1, window=64):
    """Simulate phase estimation on ω ∈ [0, 1).

    Samples ω̃ = k/M, M = 2^m, from the exact PE output distribution
    (reference ``phase_estimation``, ``Utility.py:591-694``), batched over
    ``omega``. ω ≈ 1 maps to (M−1)/M as in the reference (``:640``).
    """
    declared_eps = epsilon
    if m is None:
        if epsilon is None:
            raise ValueError("specify either m or epsilon")
        m = phase_estimation_m(epsilon, gamma)
    M = 2**m
    omega = jnp.asarray(omega)
    j = fejer_grid_sample(key, omega * M, float(M), window)
    omega_tilde = j / M
    out = jnp.where(
        jnp.isclose(omega, 1.0), (M - 1) / M, omega_tilde
    )
    if declared_eps is not None and _eager(key, omega):
        # PE contract (Nielsen & Chuang eq. 5.35 at the implemented m):
        # circular |ω̃ − ω| ≤ ε with prob ≥ 1−γ. Only ε-declared calls
        # are audited — a bare qubit count carries no contract to hold.
        _observe_estimate("phase_estimation", omega, out,
                          float(declared_eps), float(gamma), circular=True,
                          m=int(m))
    return out


def consistent_phase_estimation(
    key, omega, epsilon, gamma, n=None, shift=None, window=64
):
    """Consistent phase estimation ("Inverting Well Conditioned Matrices in
    Quantum Logspace"; reference ``Utility.py:740-792``).

    Runs PE at precision δ' = ε·γ/(2n) and snaps the output into a fixed
    ε-grid of shifted intervals, so repeated noisy calls almost always agree.
    ``epsilon``/``gamma`` are static; ``omega`` is batched.
    """
    import numpy as np

    if n is None:
        n = phase_estimation_m(epsilon, gamma)
    C = gamma / n
    delta_prime = (epsilon * C) / 2
    L = np.floor(2 / C)
    if shift is None:
        shift = int(L / 2) + 1
    intervals = np.arange(-1 - shift * delta_prime,
                          1 + epsilon - shift * delta_prime, epsilon)
    intervals = np.append(intervals, 1 + epsilon - shift * delta_prime)
    intervals = jnp.asarray(
        intervals, dtype=jnp.result_type(jnp.asarray(omega), jnp.float32))

    pe = phase_estimation(key, omega, epsilon=delta_prime, gamma=gamma, window=window)
    # bisect.bisect is bisect_right
    idx = jnp.clip(
        jnp.searchsorted(intervals, pe, side="right"), 1, intervals.shape[0] - 1
    )
    estimate = (intervals[idx - 1] + intervals[idx]) / 2
    out = jnp.maximum(estimate, 0.0)
    if _eager(key, omega):
        # consistent-PE contract: the snapped output lands within ε of ω
        # with prob ≥ 1−γ (the inner PE ran at δ' = ε·γ/2n, so the snap's
        # ε/2 half-interval plus δ' stays under ε)
        _observe_estimate("consistent_phase_estimation", omega, out,
                          float(epsilon), float(gamma))
    return out


def sv_to_theta(sv, eps):
    """Map a scaled singular value to the PE phase argument
    θ = 2·acos(σ)/(1/ε + π) (reference ``wrapper_phase_est_arguments`` 'sv',
    ``Utility.py:575-578``, combined with the /(1/eps+π) scaling used at each
    call site, e.g. ``_qPCA.py:890,988``)."""
    return 2 * jnp.arccos(jnp.clip(sv, -1.0, 1.0)) / (1 / eps + jnp.pi)


def theta_to_sv(theta, eps):
    """Exact inverse of :func:`sv_to_theta` for the same ``eps``:
    σ = cos(θ·(1/ε + π)/2).

    The reference splits this across ``unwrap_phase_est_arguments`` 'sv'
    (``Utility.py:584-587``, which multiplies by (ε + π)) and call sites that
    pass the *reciprocal* ε to the unwrap (``_qPCA.py:896``) so the round
    trip only works by coincidence of conventions. Here both functions take
    the same ``eps`` and invert exactly.
    """
    return jnp.cos(theta * (1 / eps + jnp.pi) / 2)


def ipe(key, x_sq_norm, y_sq_norm, inner, epsilon, Q=None, gamma=0.1, window=64):
    """Robust Inner Product Estimation (reference ``ipe``,
    ``Utility.py:697-737``; supplemental of "Quantum algorithms for
    feedforward neural networks").

    Encodes a = (‖x‖²+‖y‖²−2⟨x,y⟩) / (2(‖x‖²+‖y‖²)), runs amplitude
    estimation at the rescaled precision ε_a = ε·max(1,|⟨x,y⟩|)/(‖x‖²+‖y‖²),
    and inverts to an inner-product estimate. Fully batched: all arguments
    broadcast, each element gets its own traced grid size.

    Note: the reference's ``Q`` parameter is accepted but silently unused
    (latent bug — AE is always median-boosted via ``gamma``). Here ``Q``
    is honored when given; otherwise Q is derived from ``gamma``.
    """
    x2 = jnp.asarray(x_sq_norm)
    y2 = jnp.asarray(y_sq_norm)
    ip = jnp.asarray(inner)
    ssum = x2 + y2
    a = jnp.clip((ssum - 2 * ip) / (2 * ssum), 0.0, 1.0)
    eps_a = epsilon * jnp.maximum(1.0, jnp.abs(ip)) / ssum
    if Q is None:
        Q = median_q(gamma)
    a_tilde = amplitude_estimation_per_eps(key, a, eps_a, Q=Q, window=window)
    out = ssum * (1 - 2 * a_tilde) / 2
    if _eager(key, ip, x2, y2):
        # robust-IPE contract: |⟨x,y⟩_est − ⟨x,y⟩| ≤ ε·max(1, |⟨x,y⟩|)
        # with prob ≥ 1−γ (the amplitude ran at the rescaled ε_a, and the
        # decode multiplies the amplitude error back by ‖x‖²+‖y‖²)
        _observe_estimate(
            "ipe", ip, out,
            float(epsilon) * jnp.maximum(1.0, jnp.abs(ip)), float(gamma))
    return out


# cap on the Fejér sampler's transient logits tensor (elements of
# (batch, Q, 2·window+1)); ~64 MB of float32. Module-level so tests can
# shrink it to force the blocked path.
_IPE_BLOCK_ELEMS = 1 << 24


def ipe_matrix(key, inner, x_sq, c_sq, epsilon, Q=None, gamma=0.1,
               window=64):
    """IPE over a precomputed (n, k) inner-product matrix with the sampler
    transient capped.

    The batched Fejér sampler materializes (batch, Q, 2·window+1) logits —
    n·k·Q·129 floats in one shot, ~1.8 GB for MNIST-scale (70k, 10) at
    Q=5 — so rows are processed in blocks sized to ``_IPE_BLOCK_ELEMS``.
    Below the cap the single fused call is kept (no scan overhead). This
    is the one bounded implementation behind every matrix-IPE caller
    (q-means E-step, :func:`inner_product_estimates`).
    """
    inner = jnp.asarray(inner)
    x_sq = jnp.asarray(x_sq)
    c_sq = jnp.asarray(c_sq)
    n, k = inner.shape
    q_eff = Q if Q is not None else median_q(gamma)
    per_row = k * q_eff * (2 * window + 1)
    block = max(1, _IPE_BLOCK_ELEMS // max(per_row, 1))
    if block >= n:
        return ipe(key, x_sq[:, None], c_sq[None, :], inner,
                   epsilon=epsilon, Q=Q, gamma=gamma, window=window)
    nb = -(-n // block)
    pad = nb * block - n
    # padding rows: x_sq=1 keeps the amplitude encoding well-defined
    # (0/0 otherwise); their estimates are sliced away below
    innerp = jnp.pad(inner, ((0, pad), (0, 0)))
    xsqp = jnp.pad(x_sq, (0, pad), constant_values=1.0)
    keys = jax.random.split(key, nb)

    def one(args):
        kb, ib, xb = args
        return ipe(kb, xb[:, None], c_sq[None, :], ib,
                   epsilon=epsilon, Q=Q, gamma=gamma, window=window)

    out = jax.lax.map(one, (keys, innerp.reshape(nb, block, k),
                            xsqp.reshape(nb, block)))
    return out.reshape(nb * block, k)[:n]


def inner_product_estimates(key, X, C, epsilon, Q=None, gamma=0.1, window=64):
    """IPE for every (row of X, row of C) pair in one bounded kernel.

    Replaces the reference's ``itertools.product`` + ``pool.map`` over n·k
    scalar calls (``_dmeans.py:753-769``). Returns an (n, k) matrix of
    estimated inner products; the sampler transient is capped by
    :func:`ipe_matrix`'s row blocking.
    """
    from ..linalg import row_norms

    X = jnp.asarray(X)
    C = jnp.asarray(C)
    x2 = row_norms(X, squared=True)
    c2 = row_norms(C, squared=True)
    ip = X @ C.T  # MXU
    return ipe_matrix(key, ip, x2, c2, epsilon, Q=Q, gamma=gamma,
                      window=window)
