"""Truncated-Gaussian noise injectors.

These are the reference's fast-path error models (``Utility.py:68-73,88-104``):
instead of running full tomography, an estimate is approximated by adding
truncnorm(−b, b) noise per component. They double as the framework's
fault-injection system (SURVEY §5). All samplers are key-threaded and batched.
"""

import jax
import jax.numpy as jnp


def truncated_noise(key, bound, shape, dtype=jnp.float32):
    """Standard-normal noise truncated to [−bound, bound] (scipy
    ``truncnorm.rvs(-b, b)`` equivalent). ``bound`` may be an array
    broadcastable to ``shape``; bound == 0 yields exactly 0."""
    bound = jnp.asarray(bound, dtype=dtype)
    safe = jnp.where(bound > 0, bound, 1.0)
    noise = jax.random.truncated_normal(key, -safe, safe, shape, dtype=dtype)
    return jnp.where(bound > 0, noise, 0.0)


def introduce_error(key, value, epsilon):
    """value + truncnorm(−ε, ε) noise (reference ``introduce_error``, :68).

    Batched: ``value`` and ``epsilon`` broadcast together.
    """
    value = jnp.asarray(value)
    eps = jnp.broadcast_to(jnp.asarray(epsilon, value.dtype), value.shape)
    return value + truncated_noise(key, eps, value.shape, value.dtype)


def introduce_error_array(key, array, norm_error):
    """Add truncnorm noise bounded by ``norm_error/√d`` per component
    (reference ``introduce_error_array``, :71) so the L2 perturbation is
    ≤ ``norm_error``."""
    array = jnp.asarray(array)
    d = array.shape[-1]
    bound = jnp.asarray(norm_error) / jnp.sqrt(d)
    bound = jnp.broadcast_to(
        bound[..., None] if jnp.ndim(bound) else bound, array.shape)
    return array + truncated_noise(key, bound, array.shape, array.dtype)


def gaussian_estimate(key, vec, noise):
    """Gaussian-noise approximation of tomography (reference
    ``make_gaussian_est``, :88): adds truncnorm(±noise/√d) per component.

    Unlike the reference — which returns an undefined variable when
    noise == 0 (``Utility.py:97-104``, latent bug) — noise == 0 returns the
    input unchanged.
    """
    vec = jnp.asarray(vec)
    d = vec.shape[-1]
    per_component = jnp.asarray(noise, vec.dtype) / jnp.sqrt(d)
    return vec + truncated_noise(key, per_component, vec.shape, vec.dtype)
