"""Vector-state tomography.

Implements Algorithm 4.1 of "A Quantum Interior Point Method for LPs and
SDPs" (the reference's ``real_tomography``, ``Utility.py:259-402``, and its
dispatcher ``tomography``, ``:107-180``):

part 1  measure the state N times in the computational basis → magnitude
        estimates √p̂ᵢ;
part 2  measure an interference state of 2d registers with amplitudes
        ½(Vᵢ±Pᵢ) N times and resolve the sign of each component by
        thresholding the '+' register counts at 0.4·Pᵢ²·N.

TPU-first: counts are sampled directly from multinomials (the reference
materializes N ≈ 36·d·ln d/δ² ≈ 2e7 draws per vector), the whole procedure is
one jit'd function, and matrices are handled by ``vmap`` over rows instead of
a Python list comprehension (``Utility.py:168-173``).
"""

import math

import jax
import jax.numpy as jnp

from .noise import gaussian_estimate
from .sampling import multinomial_counts


def _observe_guarantee(A, est, noise, norm, preserve_norm, variant):
    """Emit ``guarantee`` records for one eager tomography call (the
    statistical-observability contract, :mod:`sq_learn_tpu.obs.guarantees`):
    the simulation knows its own ground truth, so every eager estimate is
    one audited draw of "realized error ≤ δ w.p. ≥ 1 − fail_prob".

    - ``true``: Algorithm 4.1's contract is on the NORMALIZED vector —
      per-row error of est/‖v‖ vs v/‖v‖ in the declared norm, failure
      probability 1/d^0.83 (QIPM Theorem 4.3's tail at the implemented
      N = 36·d·ln d/δ²).
    - ``gaussian``: the fast path adds truncnorm(±δ/√d) per component of
      the FLATTENED input, so its realized ‖A−Â‖_F ≤ δ by construction —
      declared fail_prob 0 (a violation means the injector itself broke).

    No-op when observability is disabled; never raises into the
    estimate (except the deliberate strict-mode audit escalation).
    """
    from ... import obs as _obs

    if not _obs.guarantees.enabled():
        return
    import numpy as np

    A = np.asarray(A, np.float64)
    E = np.asarray(est, np.float64)
    if variant == "gaussian":
        realized = [float(np.linalg.norm(A - E))]
        _obs.guarantees.observe(
            "tomography.gaussian", realized, float(noise), fail_prob=0.0,
            norm="L2", d=int(A.size))
        return
    if A.ndim == 1:
        A, E = A[None], E[None]
    scale = np.linalg.norm(A, axis=1)
    safe = np.where(scale > 0, scale, 1.0)
    unit = A / safe[:, None]
    Eu = (E / safe[:, None]) if preserve_norm else E
    ord_ = 2 if norm == "L2" else np.inf
    realized = np.linalg.norm(unit - Eu, ord=ord_, axis=1)
    d = A.shape[1]
    _obs.guarantees.observe(
        "tomography.true", realized, float(noise),
        fail_prob=min(1.0, d ** -0.83), norm=norm, d=int(d))


def tomography_n_measurements(d, delta, norm="L2"):
    """Sample complexity N (reference ``Utility.py:307-311``):
    L2: 36·d·ln d/δ²; inf: 36·ln d/δ²."""
    if norm == "L2":
        return int((36 * d * math.log(d)) / (delta**2))
    if norm == "inf":
        return int((36 * math.log(d)) / (delta**2))
    raise ValueError(f"norm must be 'L2' or 'inf', got {norm!r}")


def _tomography_unit(key, v, N):
    """One pass of Algorithm 4.1 on a unit vector ``v`` with N measurements."""
    d = v.shape[0]
    k1, k2 = jax.random.split(key)
    # Part 1 — magnitudes from measurement counts.
    counts = multinomial_counts(k1, N, v * v)
    P = jnp.sqrt(counts / N)
    # Part 2 — sign resolution on the 2d-register interference state.
    amps = 0.5 * jnp.concatenate([v + P, v - P])
    counts2 = multinomial_counts(k2, N, amps * amps)
    plus_counts = counts2[:d]
    sign = jnp.where(plus_counts > 0.4 * P * P * N, 1.0, -1.0)
    return sign * P


def real_tomography(key, v, delta=None, N=None, norm="L2", preserve_norm=True):
    """Tomography estimate of a single vector.

    Parameters
    ----------
    key : jax key
    v : (d,) array — need not be unit norm; it is normalized internally
        exactly as the reference does (``Utility.py:301-304``).
    delta : float — target L2 (or L∞) estimation error; sets N when N is None.
    N : int, optional — explicit number of measurements.
    norm : 'L2' | 'inf'
    preserve_norm : bool, default True
        The reference returns the estimate of the *normalized* vector,
        silently discarding the input's scale (so q-means centroids passed
        through tomography come back unit-norm — ``_centers_update``,
        ``_dmeans.py:825-828``). A fault-tolerant quantum machine would hold
        the norm in a separate register, so by default we rescale the
        estimate by ‖v‖; pass False for raw reference behavior.
    """
    v = jnp.asarray(v)
    d = v.shape[0]
    if N is None:
        N = tomography_n_measurements(d, delta, norm)
    scale = jnp.linalg.norm(v)
    unit = v / jnp.where(scale > 0, scale, 1.0)
    est = _tomography_unit(key, unit, N)
    return est * scale if preserve_norm else est


def _host_real_tomography(rng, v, N, preserve_norm):
    """NumPy twin of :func:`_tomography_unit` + the normalization wrapper:
    identical Algorithm 4.1 math, but counts come from numpy's C
    multinomial (BTPE binomial splitting) — on the CPU backend XLA's
    multinomial lowers to a per-category binomial scan that costs seconds
    per call where numpy's takes milliseconds."""
    import numpy as np

    v = np.asarray(v, np.float64)
    scale = float(np.linalg.norm(v))
    unit = v / (scale if scale > 0 else 1.0)
    d = unit.shape[0]
    p = unit * unit
    psum = p.sum()
    if not np.isfinite(psum) or psum <= 0:
        # degenerate (zero / non-finite) state: the XLA path degrades to
        # NaNs without raising; numpy's multinomial would raise instead
        return np.full(d, np.nan)
    p = p / psum
    counts = rng.multinomial(int(N), p)
    P = np.sqrt(counts / N)
    amps = 0.5 * np.concatenate([unit + P, unit - P])
    p2 = amps * amps
    s2 = p2.sum()
    p2 = p2 / s2 if s2 > 0 else np.full(2 * d, 1.0 / (2 * d))
    counts2 = rng.multinomial(int(N), p2)
    sign = np.where(counts2[:d] > 0.4 * P * P * N, 1.0, -1.0)
    est = sign * P
    return est * scale if preserve_norm else est


def tomography(key, A, noise, true_tomography=True, norm="L2", N=None,
               preserve_norm=True):
    """Tomography dispatcher (reference ``tomography``, ``Utility.py:107-180``).

    noise == 0 returns A unchanged. ``true_tomography=False`` uses the
    truncated-Gaussian fast path; otherwise exact tomography runs per row
    (``vmap``) for 2-D input. Eager calls on the CPU backend route
    through the numpy twin (:func:`_host_real_tomography` — same
    algorithm, different stream, ~100× faster multinomials there); calls
    from inside a trace always stay on the XLA path.

    Eager calls under an active obs run additionally emit ``guarantee``
    records — realized error of each estimated row against the declared
    δ (:func:`_observe_guarantee`); δ = 0 records the short-circuit with
    zero realized error (and zero violations) by construction. Traced
    calls are never audited (no concrete truth exists inside a jit).
    """
    eager = (not isinstance(A, jax.core.Tracer)
             and not isinstance(key, jax.core.Tracer))
    variant = "true" if true_tomography else "gaussian"
    if float(noise) == 0.0:
        if eager:
            from ... import obs as _obs

            if _obs.guarantees.enabled():
                _obs.guarantees.record_guarantee(
                    f"tomography.{variant}", 0.0, 0.0, fail_prob=0.0,
                    short_circuit=True)
        return jnp.asarray(A)
    if true_tomography and eager:
        from ..._config import on_cpu_backend

        if on_cpu_backend():
            import numpy as np

            rng = np.random.default_rng(
                np.asarray(jax.random.key_data(key), np.uint32).tolist())
            An = np.asarray(A)
            N_ = N if N is not None else tomography_n_measurements(
                An.shape[-1], noise, norm)
            if An.ndim == 2:
                est = np.stack([
                    _host_real_tomography(rng, row, N_, preserve_norm)
                    for row in An])
            else:
                est = _host_real_tomography(rng, An, N_, preserve_norm)
            _observe_guarantee(An, est, noise, norm, preserve_norm, "true")
            return jnp.asarray(est.astype(An.dtype))
    A = jnp.asarray(A)
    if not true_tomography:
        if A.ndim == 2:
            flat = gaussian_estimate(key, A.reshape(-1), noise)
            out = flat.reshape(A.shape)
        else:
            out = gaussian_estimate(key, A, noise)
        if eager:
            _observe_guarantee(A, out, noise, norm, preserve_norm,
                               "gaussian")
        return out
    if A.ndim == 2:
        keys = jax.random.split(key, A.shape[0])
        fn = lambda k, row: real_tomography(
            k, row, delta=noise, N=N, norm=norm, preserve_norm=preserve_norm
        )
        out = jax.vmap(fn)(keys, A)
    else:
        out = real_tomography(key, A, delta=noise, N=N, norm=norm,
                              preserve_norm=preserve_norm)
    if eager:
        _observe_guarantee(A, out, noise, norm, preserve_norm, "true")
    return out


def magnitude_tomography_signed(key, v, delta=None, N=None,
                                preserve_norm=False):
    """Magnitude-only tomography with the TRUE signs copied onto the
    estimated magnitudes — the legacy 'fake sign' shortcut (reference
    ``L2_tomogrphy_fakeSign``, ``Utility.py:234-256``): part 1 of Alg. 4.1
    (N = 36·d·ln d/δ² Wald magnitudes from measurement counts) without the
    interference-state sign resolution. Kept for experiments comparing
    sign-resolution cost; ``real_tomography`` is the faithful algorithm.
    The reference's dict-keyed implementation silently merges duplicate
    values; this one is positional, the documented intent. Like the
    reference, the returned estimate is of the NORMALIZED vector
    (``preserve_norm=True`` rescales by ‖v‖, the convention of
    :func:`real_tomography`)."""
    v = jnp.asarray(v)
    d = v.shape[0]
    if N is None:
        if delta is None:
            raise ValueError("provide either N or delta")
        if float(delta) == 0.0:
            # zero error budget short-circuits to the exact vector
            # (normalized, matching the estimate's convention)
            return v if preserve_norm else v / jnp.linalg.norm(v)
        N = tomography_n_measurements(d, delta, "L2")
    counts = multinomial_counts(key, int(N), v * v)
    est = jnp.sign(v) * jnp.sqrt(counts / int(N))
    return est * jnp.linalg.norm(v) if preserve_norm else est


def tomography_incremental(key, v, delta, norm="L2", num_points=100,
                           faster_measure_increment=0, stop_when_reached_accuracy=True):
    """Incremental-measurement tomography (reference ``Utility.py:315-363``).

    Host-driven debug/experiment path: runs Algorithm 4.1 on a geomspace
    schedule of measurement counts, optionally early-stopping when
    ‖V−P‖ ≤ δ. The data-dependent break is jit-hostile by design (SURVEY §7
    "hard parts"), so this stays a Python loop around the jit'd single-N
    core; the hot paths always use :func:`tomography` at the final N.

    Returns
    -------
    dict {n_measurements: estimate (np.ndarray)}
    """
    import numpy as np

    v = jnp.asarray(v)
    d = v.shape[0]
    scale = float(jnp.linalg.norm(v))
    unit = v / (scale if scale > 0 else 1.0)
    N = tomography_n_measurements(d, delta, norm)
    schedule = np.geomspace(1, N, num=num_points, dtype=np.int64)
    # de-duplicate the schedule like reference check_measure (Utility.py:414)
    incr = 5 + faster_measure_increment
    for i in range(len(schedule) - 1):
        if schedule[i + 1] <= schedule[i]:
            schedule[i + 1] = schedule[i] + incr
    ord_ = 2 if norm == "L2" else np.inf
    results = {}
    core = jax.jit(_tomography_unit, static_argnums=2)
    for n in schedule:
        key, sub = jax.random.split(key)
        est = core(sub, unit, int(n))
        results[int(n)] = np.asarray(est)
        if stop_when_reached_accuracy:
            if np.linalg.norm(np.asarray(unit) - results[int(n)], ord=ord_) <= delta:
                break
    return results
