"""μ(A) quantum-memory-model norm search.

μ_p(A) = √(s_{2p}(A) · s_{2(1−p)}(Aᵀ)) with s_q(A) = max_i ‖A_i‖_q^q, grid
minimized over p ∈ [0,1] and compared against the Frobenius norm. This is the
data-structure parameter entering every quantum runtime formula (reference
``__mu``/``linear_search``/``best_mu``, ``Utility.py:196-231``).

TPU-first: each μ_p is a pair of row-wise power-sum reductions (one over A,
one over Aᵀ) — all grid points are evaluated in a single jit'd sweep instead
of the reference's 21 Python-loop passes over the matrix.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# row-block height of the tiled sweep: blocks of ~2^18 elements keep the
# whole multiplication chain (|A| tile, log tile, base, running power)
# cache/VMEM-resident, so the matrix streams from HBM/DRAM exactly once
_TILE_ELEMS = 1 << 18


def _grid_exponents(grid):
    """The exponent set a μ grid needs — 2p for the row factor and 2(1−p)
    for the column factor draw from the same set — plus the uniform-step
    flag that enables the multiplication chain."""
    qs = sorted({round(2 * p, 12) for p in grid}
                | {round(2 * (1 - p), 12) for p in grid})
    qpos = [q for q in qs if q > 0]
    steps = {round(b - a, 12) for a, b in zip(qpos, qpos[1:])}
    uniform = bool(qpos) and (not steps or steps == {round(qpos[0], 12)})
    return qs, qpos, uniform


def _power_sweep(tile, qs, qpos, uniform):
    """Per-tile reductions of |tile|^q for every exponent q.

    Returns ``(row_max, cols)`` stacked over qs: row_max (|qs|,) —
    max_i Σ_j |a_ij|^q (rows are never split across tiles, so the within-
    tile max is exact); cols (|qs|, m) — Σ_i |a_ij|^q column partials.
    With a uniformly-spaced exponent set the powered matrices form a
    multiplication chain |A|^{i·d} = (|A|^d)^i — ONE exp pass, then an
    elementwise multiply per grid point; |a|^q comes from exp(q·ln|a|) on
    one hoisted log (vectorized exp instead of |grid| scalar pow passes).
    """
    absT = jnp.abs(tile)
    nz = absT > 0
    logT = jnp.log(jnp.where(nz, absT, 1.0))
    row_max, cols = {}, {}

    def record(q, P):
        row_max[q] = jnp.max(jnp.sum(P, axis=1))
        cols[q] = jnp.sum(P, axis=0)

    if 0 in qs:
        record(0, nz.astype(tile.dtype))  # reference Utility.py:198-203
    if uniform:
        base = jnp.where(nz, jnp.exp(qpos[0] * logT), 0.0)
        P = base
        for q in qpos:
            record(q, P)
            P = P * base
    else:
        for q in qpos:
            record(q, jnp.where(nz, jnp.exp(q * logT), 0.0))
    return (jnp.stack([row_max[q] for q in qs]),
            jnp.stack([cols[q] for q in qs]))


@functools.partial(jax.jit, static_argnums=1)
def _mu_grid_unblocked(A, grid):
    """One fused elementwise sweep — the variant for traced (in-jit) and
    mesh-sharded operands, whose reductions XLA turns into the right
    collectives (the blocked reshape would all-gather a sharded matrix)."""
    qs, qpos, uniform = _grid_exponents(grid)
    row_max, cols = _power_sweep(jnp.asarray(A), qs, qpos, uniform)
    return _combine(grid, qs, row_max, jnp.max(cols, axis=1))


@functools.partial(jax.jit, static_argnums=1)
def _mu_grid_blocked(A, grid):
    """Row-tiled sweep for large CPU-resident operands.

    The reference walks the matrix 21 times (``Utility.py:196-219``); the
    naive vectorized version still materializes every powered matrix —
    ~2·|grid| full HBM/DRAM passes. Here the row axis is tiled
    (``_TILE_ELEMS``-sized blocks) and each block runs the whole power
    chain in cache/VMEM via ``lax.map``, so A streams from memory once:
    per-tile row maxima are exact (rows are never split) and column
    power-sums accumulate across tiles.
    """
    A = jnp.asarray(A)
    n, m = A.shape
    qs, qpos, uniform = _grid_exponents(grid)
    block = max(1, _TILE_ELEMS // max(m, 1))
    nb = -(-n // block)
    # zero padding rows: they contribute 0 to column sums and their row
    # sums are 0, never the max (power sums are non-negative)
    Ap = jnp.pad(A, ((0, nb * block - n), (0, 0)))
    tiles = Ap.reshape(nb, block, m)
    rows_t, cols_t = lax.map(
        lambda t: _power_sweep(t, qs, qpos, uniform), tiles)
    # rows_t (nb, |qs|) → per-q global max; cols_t (nb, |qs|, m) → per-q
    # column totals, then max
    return _combine(grid, qs, jnp.max(rows_t, axis=0),
                    jnp.max(jnp.sum(cols_t, axis=0), axis=1))


def _combine(grid, qs, row_max, col_max):
    """μ_p = √(s_{2p}(A)·s_{2(1−p)}(Aᵀ)) from the stacked per-q factors."""
    idx = {q: i for i, q in enumerate(qs)}
    vals = [jnp.sqrt(row_max[idx[round(2 * p, 12)]]
                     * col_max[idx[round(2 * (1 - p), 12)]])
            for p in grid]
    return jnp.stack(vals)


def blocked_worthwhile(n, m):
    """True when an (n, m) matrix is large enough for the row-tiled sweep
    to pay off — shared by :func:`_mu_grid`'s dispatch and callers that
    must choose statically (e.g. a jitted prestats kernel whose operand is
    a tracer)."""
    return n > 2 * max(1, _TILE_ELEMS // max(m, 1))


def _mu_grid(A, grid):
    """Evaluate μ_p for every p in the (static) grid.

    Dispatches between the row-tiled single-pass sweep (large concrete
    CPU-resident matrices, where the cache hierarchy limits the repeated
    passes) and the unblocked fused sweep (traced operands inside an
    enclosing jit, small matrices, accelerator-resident operands — which
    stream the fused sweep at HBM bandwidth — and mesh-sharded operands,
    where the tiled reshape would force all-gathers)."""
    if isinstance(A, jax.core.Tracer):
        return _mu_grid_unblocked(A, grid)
    A = jnp.asarray(A)
    n, m = A.shape
    sh = getattr(A, "sharding", None)
    sharded = (sh is not None and len(getattr(sh, "device_set", ())) > 1
               and not sh.is_fully_replicated)
    try:
        on_cpu = all(d.platform == "cpu" for d in A.devices())
    except Exception:  # committed-elsewhere edge: fall back to fused sweep
        on_cpu = False
    if sharded or not on_cpu or not blocked_worthwhile(n, m):
        # accelerators stream the fused sweep at HBM bandwidth — the tiled
        # lax.map only pays off where the cache hierarchy is the limit
        return _mu_grid_unblocked(A, grid)
    return _mu_grid_blocked(A, grid)


def mu(A, p):
    """μ_p(A) for a single p ∈ [0, 1]."""
    p = float(p)
    if not 0.0 <= p <= 1.0:
        raise ValueError(f"mu is defined for p in [0, 1], got {p}")
    return _mu_grid(A, (p,))[0]


def _search_grid(start, end, step):
    """Validated p-grid shared by :func:`linear_search` and
    :func:`best_mu`."""
    if not 0.0 <= start <= end <= 1.0:
        raise ValueError(
            f"mu grid must satisfy 0 <= start <= end <= 1, got "
            f"[{start}, {end}]")
    if step <= 0:
        raise ValueError(f"mu grid step must be > 0, got {step}")
    return tuple(float(p) for p in np.arange(start, end, step)) + (float(end),)


def linear_search(A, start=0.0, end=1.0, step=0.05):
    """Grid-minimize μ_p over p ∈ [start, end] ⊆ [0, 1] (reference
    ``linear_search``, ``Utility.py:215-219``). Returns
    (best_p, best_value)."""
    grid = _search_grid(start, end, step)
    vals = np.asarray(_mu_grid(jnp.asarray(A), grid))
    idx = int(np.argmin(vals))
    return grid[idx], float(vals[idx])


def select_mu(grid, mu_vals, frob):
    """Host-side winner selection between the μ_p grid and the Frobenius
    norm (reference ``best_mu``, ``Utility.py:222-231``) — shared by
    :func:`best_mu` and fused pre-stat paths that computed ``mu_vals`` and
    ``frob`` on device already.

    Returns
    -------
    (description, value) : (str, float)
        description is ``"p=<best_p>"`` or ``"Frobenius"``.
    """
    mu_vals = np.asarray(mu_vals)
    idx = int(np.argmin(mu_vals))
    val = float(mu_vals[idx])
    frob = float(frob)
    if val <= frob:
        return f"p={grid[idx]}", val
    return "Frobenius", frob


def best_mu(A, start=0.0, end=1.0, step=0.05):
    """Best of grid-searched μ_p and the Frobenius norm (reference
    ``best_mu``, ``Utility.py:222-231``).

    Returns
    -------
    (description, value) : (str, float)
        description is ``"p=<best_p>"`` or ``"Frobenius"``.
    """
    grid = _search_grid(start, end, step)
    vals = _mu_grid(jnp.asarray(A), grid)
    frob = jnp.linalg.norm(jnp.asarray(A))
    return select_mu(grid, vals, frob)
