"""μ(A) quantum-memory-model norm search.

μ_p(A) = √(s_{2p}(A) · s_{2(1−p)}(Aᵀ)) with s_q(A) = max_i ‖A_i‖_q^q, grid
minimized over p ∈ [0,1] and compared against the Frobenius norm. This is the
data-structure parameter entering every quantum runtime formula (reference
``__mu``/``linear_search``/``best_mu``, ``Utility.py:196-231``).

TPU-first: each μ_p is a pair of row-wise power-sum reductions (one over A,
one over Aᵀ) — all grid points are evaluated in a single jit'd sweep instead
of the reference's 21 Python-loop passes over the matrix.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnums=1)
def _mu_grid(A, grid):
    """Evaluate μ_p for every p in the (static) grid in one fused sweep."""
    A = jnp.asarray(A)
    absA = jnp.abs(A)

    def s(q, M):
        # s_q(M) = max_i Σ_j |M_ij|^q ; q == 0 counts nonzeros (reference
        # Utility.py:198-203).
        if q == 0:
            return jnp.max(jnp.sum((M != 0).astype(M.dtype), axis=1))
        return jnp.max(jnp.sum(M**q, axis=1))

    vals = [jnp.sqrt(s(2 * p, absA) * s(2 * (1 - p), absA.T)) for p in grid]
    return jnp.stack(vals)


def mu(A, p):
    """μ_p(A) for a single p."""
    return _mu_grid(A, (float(p),))[0]


def linear_search(A, start=0.0, end=1.0, step=0.05):
    """Grid-minimize μ_p over p ∈ [start, end] (reference ``linear_search``,
    ``Utility.py:215-219``). Returns (best_p, best_value)."""
    grid = tuple(float(p) for p in np.arange(start, end, step)) + (float(end),)
    vals = np.asarray(_mu_grid(jnp.asarray(A), grid))
    idx = int(np.argmin(vals))
    return grid[idx], float(vals[idx])


def select_mu(grid, mu_vals, frob):
    """Host-side winner selection between the μ_p grid and the Frobenius
    norm (reference ``best_mu``, ``Utility.py:222-231``) — shared by
    :func:`best_mu` and fused pre-stat paths that computed ``mu_vals`` and
    ``frob`` on device already.

    Returns
    -------
    (description, value) : (str, float)
        description is ``"p=<best_p>"`` or ``"Frobenius"``.
    """
    mu_vals = np.asarray(mu_vals)
    idx = int(np.argmin(mu_vals))
    val = float(mu_vals[idx])
    frob = float(frob)
    if val <= frob:
        return f"p={grid[idx]}", val
    return "Frobenius", frob


def best_mu(A, start=0.0, end=1.0, step=0.05):
    """Best of grid-searched μ_p and the Frobenius norm (reference
    ``best_mu``, ``Utility.py:222-231``).

    Returns
    -------
    (description, value) : (str, float)
        description is ``"p=<best_p>"`` or ``"Frobenius"``.
    """
    grid = tuple(float(p) for p in np.arange(start, end, step)) + (float(end),)
    vals = _mu_grid(jnp.asarray(A), grid)
    frob = jnp.linalg.norm(jnp.asarray(A))
    return select_mu(grid, vals, frob)
