"""μ(A) quantum-memory-model norm search.

μ_p(A) = √(s_{2p}(A) · s_{2(1−p)}(Aᵀ)) with s_q(A) = max_i ‖A_i‖_q^q, grid
minimized over p ∈ [0,1] and compared against the Frobenius norm. This is the
data-structure parameter entering every quantum runtime formula (reference
``__mu``/``linear_search``/``best_mu``, ``Utility.py:196-231``).

TPU-first: each μ_p is a pair of row-wise power-sum reductions (one over A,
one over Aᵀ) — all grid points are evaluated in a single jit'd sweep instead
of the reference's 21 Python-loop passes over the matrix.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnums=1)
def _mu_grid(A, grid):
    """Evaluate μ_p for every p in the (static) grid in one fused sweep.

    Two structural savings over the naive 2·|grid| powered passes:
    s_q(Aᵀ) = max_j Σ_i |a_ij|^q is the column reduction of the SAME powered
    matrix whose row reduction is s_q(A), so each exponent q powers the
    matrix once and serves both factors; and |a|^q is computed as
    exp(q·ln|a|) from one hoisted log — vectorized exp instead of |grid|
    scalar pow passes (a ~10× wall-clock difference on large hosts).
    """
    A = jnp.asarray(A)
    absA = jnp.abs(A)
    nz = absA > 0
    logA = jnp.log(jnp.where(nz, absA, 1.0))

    # the exponents needed across the grid: 2p for the row factor and
    # 2(1−p) for the column factor draw from the same set
    qs = sorted({round(2 * p, 12) for p in grid}
                | {round(2 * (1 - p), 12) for p in grid})
    row_s, col_s = {}, {}

    def record(q, P):
        row_s[q] = jnp.max(jnp.sum(P, axis=1))
        col_s[q] = jnp.max(jnp.sum(P, axis=0))

    if 0 in qs:
        record(0, nz.astype(A.dtype))  # reference Utility.py:198-203
    qpos = [q for q in qs if q > 0]
    steps = {round(b - a, 12) for a, b in zip(qpos, qpos[1:])}
    if qpos and (not steps or steps == {round(qpos[0], 12)}):
        # uniformly-spaced exponents (every standard grid): the powered
        # matrices form a multiplication chain |A|^{i·d} = (|A|^d)^i — ONE
        # exp pass, then an elementwise multiply per grid point
        base = jnp.where(nz, jnp.exp(qpos[0] * logA), 0.0)
        P = base
        for q in qpos:
            record(q, P)
            P = P * base
    else:
        for q in qpos:
            record(q, jnp.where(nz, jnp.exp(q * logA), 0.0))

    vals = [jnp.sqrt(row_s[round(2 * p, 12)] * col_s[round(2 * (1 - p), 12)])
            for p in grid]
    return jnp.stack(vals)


def mu(A, p):
    """μ_p(A) for a single p ∈ [0, 1]."""
    p = float(p)
    if not 0.0 <= p <= 1.0:
        raise ValueError(f"mu is defined for p in [0, 1], got {p}")
    return _mu_grid(A, (p,))[0]


def _search_grid(start, end, step):
    """Validated p-grid shared by :func:`linear_search` and
    :func:`best_mu`."""
    if not 0.0 <= start <= end <= 1.0:
        raise ValueError(
            f"mu grid must satisfy 0 <= start <= end <= 1, got "
            f"[{start}, {end}]")
    if step <= 0:
        raise ValueError(f"mu grid step must be > 0, got {step}")
    return tuple(float(p) for p in np.arange(start, end, step)) + (float(end),)


def linear_search(A, start=0.0, end=1.0, step=0.05):
    """Grid-minimize μ_p over p ∈ [start, end] ⊆ [0, 1] (reference
    ``linear_search``, ``Utility.py:215-219``). Returns
    (best_p, best_value)."""
    grid = _search_grid(start, end, step)
    vals = np.asarray(_mu_grid(jnp.asarray(A), grid))
    idx = int(np.argmin(vals))
    return grid[idx], float(vals[idx])


def select_mu(grid, mu_vals, frob):
    """Host-side winner selection between the μ_p grid and the Frobenius
    norm (reference ``best_mu``, ``Utility.py:222-231``) — shared by
    :func:`best_mu` and fused pre-stat paths that computed ``mu_vals`` and
    ``frob`` on device already.

    Returns
    -------
    (description, value) : (str, float)
        description is ``"p=<best_p>"`` or ``"Frobenius"``.
    """
    mu_vals = np.asarray(mu_vals)
    idx = int(np.argmin(mu_vals))
    val = float(mu_vals[idx])
    frob = float(frob)
    if val <= frob:
        return f"p={grid[idx]}", val
    return "Frobenius", frob


def best_mu(A, start=0.0, end=1.0, step=0.05):
    """Best of grid-searched μ_p and the Frobenius norm (reference
    ``best_mu``, ``Utility.py:222-231``).

    Returns
    -------
    (description, value) : (str, float)
        description is ``"p=<best_p>"`` or ``"Frobenius"``.
    """
    grid = _search_grid(start, end, step)
    vals = _mu_grid(jnp.asarray(A), grid)
    frob = jnp.linalg.norm(jnp.asarray(A))
    return select_mu(grid, vals, frob)
