"""Quantum simulation runtime (reference layer L3, ``sklearn/QuantumUtility/``).

Every routine is a pure, key-threaded, jit-able, batched JAX function — the
TPU-native re-design of ``Utility.py`` (SURVEY §2.1).
"""

from .estimation import (
    amplitude_estimation,
    amplitude_estimation_M,
    amplitude_estimation_per_eps,
    consistent_phase_estimation,
    inner_product_estimates,
    ipe,
    median_evaluation,
    median_q,
    phase_estimation,
    phase_estimation_m,
    sv_to_theta,
    theta_to_sv,
)
from .noise import (
    gaussian_estimate,
    introduce_error,
    introduce_error_array,
    truncated_noise,
)
from .norms import best_mu, linear_search, mu
from .sampling import estimate_wald, fejer_grid_sample, fejer_probs, multinomial_counts
from .state import QuantumState, coupon_collect
from .tomography import (
    magnitude_tomography_signed,
    real_tomography,
    tomography,
    tomography_incremental,
    tomography_n_measurements,
)

__all__ = [
    "QuantumState",
    "amplitude_estimation",
    "amplitude_estimation_M",
    "amplitude_estimation_per_eps",
    "best_mu",
    "consistent_phase_estimation",
    "coupon_collect",
    "estimate_wald",
    "fejer_grid_sample",
    "fejer_probs",
    "gaussian_estimate",
    "inner_product_estimates",
    "introduce_error",
    "introduce_error_array",
    "ipe",
    "linear_search",
    "median_evaluation",
    "median_q",
    "mu",
    "multinomial_counts",
    "phase_estimation",
    "phase_estimation_m",
    "magnitude_tomography_signed",
    "real_tomography",
    "sv_to_theta",
    "theta_to_sv",
    "tomography",
    "tomography_incremental",
    "tomography_n_measurements",
    "truncated_noise",
]
