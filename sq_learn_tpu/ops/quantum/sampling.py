"""Measurement-count sampling primitives.

The reference simulates quantum measurement by materializing N draws from
``np.random.choice`` and counting them (``Utility.py:51-54,61-64``) — at the
tomography sample complexity N = 36·d·ln d/δ² that is ~2e7 draws per vector.
On TPU we never materialize draws: outcome *counts* are sampled directly from
a multinomial (one fused XLA op), which is statistically identical.
"""

import jax
import jax.numpy as jnp


def multinomial_counts(key, n, probs):
    """Sample outcome counts of ``n`` categorical draws.

    Parameters
    ----------
    key : jax key
    n : int or array broadcastable to the batch of ``probs``
        Number of measurements.
    probs : (..., d) array
        Outcome probabilities along the last axis (need not be exactly
        normalized; they are renormalized).

    Returns
    -------
    counts : (..., d) float array summing to ``n`` along the last axis.

    Eager calls on the CPU backend sample through numpy's C multinomial
    (identical distribution, host RNG stream): XLA lowers multinomial to
    a per-category binomial scan that costs seconds per large call on
    this backend. Traced calls always use the XLA path.
    """
    if not any(isinstance(x, jax.core.Tracer) for x in (key, n, probs)):
        from ..._config import on_cpu_backend

        if on_cpu_backend():
            import numpy as np

            p = np.asarray(probs, np.float64)
            psum = p.sum(axis=-1, keepdims=True)
            ok = np.isfinite(psum) & (psum > 0)
            # degenerate rows degrade to NaN like the XLA path (numpy's
            # multinomial would raise); sample them with uniform pvals
            # and overwrite
            safe = np.where(ok, p / np.where(ok, psum, 1.0),
                            1.0 / p.shape[-1])
            try:
                kd = jax.random.key_data(key)
            except TypeError:  # legacy raw uint32 key arrays
                kd = key
            rng = np.random.default_rng(np.asarray(kd, np.uint32).tolist())
            n_arr = np.broadcast_to(np.asarray(n), p.shape[:-1])
            counts = rng.multinomial(n_arr.astype(np.int64), safe).astype(
                jnp.asarray(probs).dtype)
            return jnp.asarray(np.where(ok, counts, np.nan))
    probs = jnp.asarray(probs)
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    n = jnp.broadcast_to(jnp.asarray(n, dtype=probs.dtype), probs.shape[:-1])
    from ..._compat import random_multinomial

    return random_multinomial(key, n, probs)


def estimate_wald(counts, n):
    """Wald (empirical frequency) estimator from measurement counts.

    Equivalent to the reference's ``estimate_wald`` (``Utility.py:61``) which
    builds a Counter over materialized draws.
    """
    return jnp.asarray(counts) / n


def fejer_probs(delta, M):
    """Pointwise Fejér-kernel probability |sin(MΔπ) / (M·sin(Δπ))|².

    This is the exact output distribution of both amplitude estimation
    (``Utility.py:498-506``) and phase estimation (``Utility.py:642-650``)
    at grid distance Δ from the true value, with the removable singularity
    at Δ ∈ ℤ taken to 1.
    """
    delta = jnp.asarray(delta)
    sin_d = jnp.sin(jnp.pi * delta)
    singular = jnp.abs(sin_d) < 1e-12
    safe = jnp.where(singular, 1.0, sin_d)
    p = (jnp.sin(jnp.pi * M * delta) / (M * safe)) ** 2
    return jnp.where(singular, 1.0, p)


def fejer_grid_sample(key, pos, M, window, sample_shape=()):
    """Sample grid indices from the Fejér measurement distribution.

    Draws j ∈ {0, …, M−1} (mod-M wrapped) with
    P(j) ∝ |sin(π(pos−j)) / (M·sin(π(pos−j)/M))|², i.e. the exact
    amplitude/phase-estimation output distribution for a register of M grid
    points whose true value sits at fractional grid position ``pos``.

    TPU-first design: instead of materializing the M-point pmf per element
    (the reference builds it in a Python loop per call — ``Utility.py:498``,
    ``:642``), we enumerate only the ``2·window+1`` grid points nearest
    ``pos``. Entries are masked to at most M unique residues, so when
    M ≤ 2·window+1 the sampler is *exact*; otherwise it truncates a tail of
    total mass O(1/window) (≈0.3% at window=64; the Fejér tail at offset d
    carries ~2/(π²d²)). This makes M a *traced* per-element quantity —
    whole batches of estimations with different precisions run as one
    kernel.

    Effect on the AE/PE guarantees (pinned by
    ``tests/test_quantum_estimation.py::TestFejerTail``): truncation
    renormalizes the removed tail mass onto the near-grid points, so the
    within-ε success probability can only *increase* — the
    within-ε-w.p.-≥1−γ guarantee (and the >½ per-trial success premise of
    median boosting) is conservatively preserved at every M. The trade-off
    is that the simulated routine is ≤0.4% more accurate than the exact
    distribution — negligible against the guarantees' ≥19% slack
    (single-trial success is ≥8/π² ≈ 0.81).

    Parameters
    ----------
    key : jax key
    pos : (...,) float array — true value in grid units (value·M).
    M : (...,) float array or scalar — grid size per element (may be traced).
    window : static int — half-width of the enumerated window.
    sample_shape : tuple — leading shape of independent samples per element.

    Returns
    -------
    j : float array of shape ``sample_shape + pos.shape`` — sampled grid
        indices in [0, M).
    """
    pos = jnp.asarray(pos)
    M = jnp.broadcast_to(jnp.asarray(M, dtype=pos.dtype), pos.shape)
    offs = jnp.arange(-window, window + 1, dtype=pos.dtype)
    base = jnp.floor(pos)
    j = base[..., None] + offs  # (..., 2W+1) candidate (unwrapped) indices
    delta = (pos[..., None] - j) / M[..., None]
    p = fejer_probs(delta, M[..., None])
    # Keep exactly min(2W+1, M) unique residues mod M: offsets in (−M/2, M/2].
    centered = j - base[..., None]
    valid = (centered > -M[..., None] / 2) & (centered <= M[..., None] / 2)
    # Inverse-CDF draw rather than jax.random.categorical: the pmf/cumsum
    # is built ONCE per element and each of the `sample_shape` draws costs
    # one uniform + 2W+1 compares, where Gumbel-max categorical would pay
    # uniform+log per *candidate* per draw — on the q-means IPE E-step
    # (n·k pairs × Q median repetitions) that is ~Q× less transcendental
    # work for an identically-distributed sample.
    cum = jnp.cumsum(jnp.where(valid, p, 0.0), axis=-1)
    # u on (0, 1], not [0, 1): u == 0 would give thresh == 0 and select
    # index 0 even when the leading window entries are masked (cum == 0),
    # sampling a candidate the -inf-logits formulation could never emit
    u = 1.0 - jax.random.uniform(key, sample_shape + pos.shape,
                                 dtype=pos.dtype)
    thresh = u * cum[..., -1]  # broadcast over sample_shape
    idx = jnp.sum(cum < thresh[..., None], axis=-1)
    idx = jnp.clip(idx, 0, 2 * window)
    # the candidate grid is arithmetic (j = base + offs), so selection is
    # too — no (sample_shape, ..., 2W+1) broadcast + gather
    j_sel = base + (idx.astype(pos.dtype) - window)
    return jnp.mod(j_sel, M)
