"""Quantum register simulation.

TPU-native counterpart of the reference's ``QuantumState``
(``Utility.py:25-58``): registers + L2-normalized amplitudes, measured by
sampling register indices with probability amplitude². Measurement is
key-threaded ``jax.random`` (the reference spins up a fresh
``np.random.RandomState()`` per call for process safety — explicit keys make
that a non-issue) and large-N measurement returns multinomial *counts*
instead of materialized draws.
"""

import jax
import jax.numpy as jnp
import numpy as np

from .sampling import estimate_wald, multinomial_counts


class QuantumState:
    """A minimal simulated quantum register.

    Parameters
    ----------
    registers : array-like of shape (d,) or (d, ...)
        Values (or vectors) attached to each basis state.
    amplitudes : array-like of shape (d,)
        Amplitudes; normalized internally so probabilities sum to 1.
    """

    def __init__(self, registers, amplitudes):
        amplitudes = jnp.asarray(amplitudes)
        if amplitudes.ndim != 1:
            raise ValueError("amplitudes must be 1-D")
        self.norm_factor = jnp.linalg.norm(amplitudes)
        self.amplitudes = amplitudes / self.norm_factor
        self.probabilities = self.amplitudes**2
        self.registers = (jnp.asarray(registers)
                          if not isinstance(registers, list) else registers)
        n_reg = (len(self.registers) if isinstance(self.registers, list)
                 else self.registers.shape[0])
        if n_reg != amplitudes.shape[0]:
            raise ValueError("registers and amplitudes must have the same length")
        if not isinstance(self.probabilities, jax.core.Tracer):
            # the reference asserts Σp == 1 (Utility.py:49); after an f32
            # norm+divide the sum is 1 only to a few ulp (~1.2e-7 each for
            # the norm, the divide, and the square/sum), so the check
            # tolerance must be above f32 eps or exact inputs fail it
            np.testing.assert_allclose(
                float(jnp.sum(self.probabilities)), 1.0, atol=1e-5
            )

    def measure_indices(self, key, n_times=1):
        """Sample ``n_times`` basis-state *indices* (jit-friendly)."""
        logits = jnp.log(jnp.maximum(self.probabilities, 1e-38))
        return jax.random.categorical(key, logits, shape=(n_times,))

    def measure(self, key, n_times=1):
        """Sample ``n_times`` register values (reference ``measure``, :51)."""
        idx = self.measure_indices(key, n_times)
        if isinstance(self.registers, list):
            idx = np.asarray(idx)
            return [self.registers[int(i)] for i in idx]
        return jnp.take(self.registers, idx, axis=0)

    def measure_counts(self, key, n_times):
        """Outcome counts of ``n_times`` measurements — O(d) memory
        regardless of N (never materializes draws)."""
        return multinomial_counts(key, n_times, self.probabilities)

    def measure_frequencies(self, key, n_times):
        """Wald frequency estimates per basis state."""
        return estimate_wald(self.measure_counts(key, n_times), n_times)

    def get_state(self):
        """Dict {register: probability} (reference ``get_state``, :57)."""
        probs = np.asarray(self.probabilities)
        if isinstance(self.registers, list):
            return {
                _hashable(r): float(probs[i]) for i, r in enumerate(self.registers)
            }
        regs = np.asarray(self.registers)
        return {_hashable(regs[i]): float(probs[i]) for i in range(len(probs))}


def _hashable(value):
    arr = np.asarray(value)
    if arr.ndim == 0:
        return arr.item()
    return tuple(arr.ravel().tolist())


def coupon_collect(key, quantum_state, max_draws=1_000_000):
    """Number of measurements until every basis state has been observed.

    Reference ``coupon_collect`` (``Utility.py:75-85``), re-expressed as a
    ``lax.while_loop`` with a key carry instead of unbounded Python sampling.
    """
    probs = quantum_state.probabilities
    d = probs.shape[0]
    logits = jnp.log(jnp.maximum(probs, 1e-38))

    def cond(carry):
        _, seen, count = carry
        return jnp.logical_and(~jnp.all(seen), count < max_draws)

    def body(carry):
        k, seen, count = carry
        k, sub = jax.random.split(k)
        idx = jax.random.categorical(sub, logits)
        return k, seen.at[idx].set(True), count + 1

    _, _, count = jax.lax.while_loop(
        cond, body, (key, jnp.zeros(d, dtype=bool), jnp.asarray(0))
    )
    return count
