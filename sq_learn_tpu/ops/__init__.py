"""Compute kernels: quantum simulation primitives + XLA linear algebra."""

from . import linalg, quantum
from .linalg import (
    centered_svd,
    pairwise_sq_distances,
    randomized_svd,
    row_norms,
    smallest_singular_value,
    stable_cumsum,
    svd_flip,
    thin_svd,
)

__all__ = [
    "linalg",
    "quantum",
    "centered_svd",
    "pairwise_sq_distances",
    "randomized_svd",
    "row_norms",
    "smallest_singular_value",
    "stable_cumsum",
    "svd_flip",
    "thin_svd",
]
