"""Pallas TPU kernels — the hand-tiled hot path.

The reference's single most performance-critical native kernel is the
chunked fused Lloyd iteration (``cluster/_k_means_lloyd.pyx:29``:
GEMM distances → argmin → per-thread partial centroid sums → reduction).
This module is its TPU twin: one ``pallas_call`` sweeps sample tiles held in
VMEM, computes ‖x‖²+‖c‖²−2XCᵀ on the MXU, takes the argmin on the VPU, and
accumulates the partial centroid sums / counts / inertia across grid steps
in-place — X is read from HBM exactly once per Lloyd iteration (the XLA
path reads it twice: once for the E-step GEMM, once for the M-step one-hot
GEMM).

Off-TPU the kernel runs in interpreter mode so tests cover it on CPU.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BIG = 1e30  # masking distance for padded centroid rows


def _round_up(x, m):
    return (x + m - 1) // m * m


def _shape_struct(shape, dtype, vma):
    """ShapeDtypeStruct with the vma declaration where the installed jax
    has one (the kwarg only exists on post-0.4.x jax; `vma` is always
    None on the older line — see the `has_vma` resolution at the call
    site)."""
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _make_lloyd_kernel(window):
    """Build the tile kernel; ``window`` > 0 adds the δ-means noisy label
    pick (uniform among centroids within ``window`` of the min squared
    distance, implemented as Gumbel-argmax over pre-sampled noise — RNG
    stays outside the kernel, the selection fuses inside).

    The X/centers blocks may arrive in bfloat16 (MXU-native): both GEMMs
    accumulate in float32 via ``preferred_element_type``, and every
    reduction buffer (sums/counts/inertia/min_d2) stays float32. Sample
    weights never round through bfloat16 asymmetrically: the M-step GEMM
    multiplies them into the x rows in float32 (one rounding of w·x into
    the GEMM dtype) while the onehot operand stays an exact 0/1 mask, and
    counts apply the same float32 weights — so the centroid update's
    numerator and denominator see consistent weights."""
    delta_mode = window > 0

    def kernel(x_ref, xsq_ref, w_ref, c_ref, csq_ref, *refs):
        """One sample tile: fused E-step + M-step partials.

        Grid dim 0 walks sample tiles; sums/counts/inertia map to the same
        output block every step, so `+=` accumulates across the
        (sequential) TPU grid. Padded samples carry weight 0; padded
        centroids carry c_sq = _BIG so no sample ever selects them.
        """
        if delta_mode:
            (gum_ref, labels_ref, mind2_ref, sums_ref, counts_ref,
             inertia_ref) = refs
        else:
            labels_ref, mind2_ref, sums_ref, counts_ref, inertia_ref = refs
        i = pl.program_id(0)

        x = x_ref[:]                      # (T, m)
        c = c_ref[:]                      # (k, m)
        # MXU: the ‖x‖²+‖c‖²−2xcᵀ trick of _k_means_lloyd.pyx:196-203
        d2 = (xsq_ref[:] + csq_ref[:]
              - 2.0 * jnp.dot(x, c.T, preferred_element_type=jnp.float32))
        min_d2 = jnp.min(d2, axis=1, keepdims=True)       # (T, 1)
        if delta_mode:
            mask = d2 <= min_d2 + window
            logits = jnp.where(mask, gum_ref[:], -_BIG)
            labels = jnp.argmax(logits, axis=1)           # (T,)
        else:
            labels = jnp.argmin(d2, axis=1)               # (T,)
        labels_ref[:] = labels[:, None].astype(jnp.int32)
        # per-sample distance to the closest centroid — consumed by the
        # empty-cluster relocation step outside the kernel
        mind2_ref[:] = min_d2

        k = c.shape[0]
        col_ids = jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], k), 1)
        w = w_ref[:]
        onehot = jnp.where(labels[:, None] == col_ids, 1.0, 0.0)

        @pl.when(i == 0)
        def _():
            sums_ref[:] = jnp.zeros_like(sums_ref)
            counts_ref[:] = jnp.zeros_like(counts_ref)
            inertia_ref[:] = jnp.zeros_like(inertia_ref)

        # MXU again: partial centroid sums, accumulated across tiles. The
        # weight multiply happens in f32 on the x rows (one rounding of
        # w·x into the GEMM dtype); the onehot operand is an exact 0/1
        # mask in any dtype, and counts reuse the exact f32 weights — so
        # bf16 mode rounds numerator and denominator consistently.
        xw = (x.astype(jnp.float32) * w).astype(x.dtype)
        sums_ref[:] += jnp.dot(onehot.astype(x.dtype).T, xw,
                               preferred_element_type=jnp.float32)
        counts_ref[:] += jnp.sum(onehot * w, axis=0, keepdims=True)
        inertia_ref[:] += jnp.sum(
            min_d2 * w_ref[:], keepdims=True).reshape(1, 1)

    return kernel


@functools.partial(jax.jit,
                   static_argnames=("tile_n", "interpret", "window",
                                    "axis_name", "compute_dtype"))
def lloyd_step_pallas(X, weights, centers, x_sq_norms, *, key=None,
                      window=0.0, tile_n=512, interpret=False,
                      axis_name=None, compute_dtype=None):
    """Fused Lloyd iteration statistics in one pallas sweep.

    Parameters
    ----------
    X : (n, m) float32 — samples (may carry zero-weight padding rows).
    weights : (n,) — sample weights; 0 masks a row out entirely.
    centers : (k, m) — current centroids.
    x_sq_norms : (n,) — precomputed row norms.
    key : jax key — required when ``window`` > 0 (δ-means label sampling).
    window : static float — δ-means window on squared distances; 0 is the
        classical argmin path.
    tile_n : static — samples per VMEM tile.
    interpret : static — run in interpreter mode (CPU tests).
    axis_name : static — the mesh axis this call runs under when invoked
        inside ``shard_map`` (the TPU-pod configuration). shard_map's
        varying-across-mesh checker requires every pallas output to declare
        its vma; all five outputs derive from the shard-local X, so they
        vary over exactly this axis.
    compute_dtype : static — 'bfloat16' feeds the X/centers VMEM blocks to
        the MXU in its native dtype (halving GEMM cost and VMEM traffic);
        distances, sums, counts and inertia still accumulate in float32.
        None keeps everything float32.

    Returns
    -------
    (labels (n,) int32, min_d2 (n,), sums (k, m), counts (k,), inertia
    scalar) where ``sums``/``counts`` are the weighted per-cluster
    partials — the caller divides (and psums across a mesh, if sharded) —
    and ``min_d2`` is each sample's squared distance to its closest
    centroid (consumed by empty-cluster relocation).
    """
    n, m = X.shape
    k = centers.shape[0]
    # hardware alignment: lanes are 128 wide, f32 sublanes 8 deep. k is
    # padded to a full lane multiple because it appears as the LANE dim
    # of the csq/counts/gumbel blocks and of the in-kernel distance tile
    # (the centers/sums blocks only need sublane alignment, but the MXU
    # computes 128-wide lanes regardless, so the stricter padding costs
    # no real cycles and keeps every block shape in the documented
    # supported set).
    m_p = _round_up(m, 128)
    k_p = _round_up(k, 128)
    n_p = _round_up(n, tile_n)

    cdt = jnp.dtype(compute_dtype) if compute_dtype else jnp.float32
    Xp = jnp.zeros((n_p, m_p), cdt).at[:n, :m].set(X.astype(cdt))
    wp = jnp.zeros((n_p, 1), jnp.float32).at[:n, 0].set(weights)
    xsqp = jnp.zeros((n_p, 1), jnp.float32).at[:n, 0].set(x_sq_norms)
    Cp = jnp.zeros((k_p, m_p), cdt).at[:k, :m].set(centers.astype(cdt))
    # centroid norms stay f32 regardless of the GEMM dtype
    csqp = jnp.full((1, k_p), _BIG, jnp.float32).at[0, :k].set(
        jnp.sum(centers * centers, axis=1))

    tile_spec = pl.BlockSpec((tile_n, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec((tile_n, m_p), lambda i: (i, 0),
                     memory_space=pltpu.VMEM),
        tile_spec,
        tile_spec,
        pl.BlockSpec((k_p, m_p), lambda i: (0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, k_p), lambda i: (0, 0),
                     memory_space=pltpu.VMEM),
    ]
    operands = [Xp, xsqp, wp, Cp, csqp]
    window = float(window)
    if window > 0:
        if key is None:
            raise ValueError("window > 0 requires a PRNG key")
        # Gumbel noise sampled outside the kernel (one XLA op); the
        # masked argmax inside is the uniform δ-window pick
        gum = jax.random.gumbel(key, (n_p, k_p), jnp.float32)
        in_specs.append(pl.BlockSpec((tile_n, k_p), lambda i: (i, 0),
                                     memory_space=pltpu.VMEM))
        operands.append(gum)

    # vma plumbing exists only on newer jax (jax.typeof / lax.pcast /
    # ShapeDtypeStruct(vma=...)); on 0.4.x shard_map's replication checker
    # is disabled for the interpret path anyway (parallel/lloyd.py), so the
    # promotion is simply skipped there
    has_vma = hasattr(jax, "typeof")
    vma = (None if axis_name is None or not has_vma
           else frozenset({axis_name}))
    if axis_name is not None and has_vma:
        # centers (and their norms) enter shard_map replicated while X is
        # shard-varying; the kernel may not mix the two, so promote the
        # replicated operands to varying (a no-op on the data)
        operands = [op if axis_name in jax.typeof(op).vma
                    else jax.lax.pcast(op, axis_name, to="varying")
                    for op in operands]
    grid = (n_p // tile_n,)
    labels, min_d2, sums, counts, inertia = pl.pallas_call(
        _make_lloyd_kernel(window),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            tile_spec,
            tile_spec,
            pl.BlockSpec((k_p, m_p), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k_p), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _shape_struct((n_p, 1), jnp.int32, vma),
            _shape_struct((n_p, 1), jnp.float32, vma),
            _shape_struct((k_p, m_p), jnp.float32, vma),
            _shape_struct((1, k_p), jnp.float32, vma),
            _shape_struct((1, 1), jnp.float32, vma),
        ],
        interpret=interpret,
    )(*operands)

    return (labels[:n, 0], min_d2[:n, 0], sums[:k, :m], counts[0, :k],
            inertia[0, 0])


def _make_argkmin_kernel(k, tile_t):
    """Tile kernel for the fused k-nearest search.

    Grid is (query tiles, train tiles) with the train axis minor (TPU
    grids execute sequentially), so the running k-best per query row
    lives in the output blocks — indexed by query tile only — and is
    merged against each train tile in turn. Selection is ``k`` unrolled
    rounds of masked argmin over [current bests ‖ tile scores]: no sort,
    no HBM distance matrix, ascending output for free. Ties resolve to
    the lowest training index (prior bests come from earlier tiles and
    precede the tile's columns, which are themselves index-ascending) —
    the same order ``lax.top_k`` yields on the XLA path.

    Every buffer keeps its full lane-aligned width: the best lists carry
    ``lane_k`` columns with _BIG/-1 sentinels beyond ``k`` (sentinels can
    never win a round against the ≥k real candidates), and results are
    written back through iota/where masks — no minor-dimension slicing at
    a non-aligned ``k``, no in-kernel pad, the constructs Mosaic versions
    are most likely to reject (ADVICE r3).
    """

    def kernel(q_ref, t_ref, tsq_ref, bestd_ref, besti_ref):
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _():
            bestd_ref[:] = jnp.full_like(bestd_ref, _BIG)
            besti_ref[:] = jnp.full_like(besti_ref, -1)

        q = q_ref[:]                       # (T_q, m)
        t = t_ref[:]                       # (T_t, m)
        # ranking score: ‖t‖² − 2·q·tᵀ (the query norm shifts every
        # column of a row equally, so it cannot change the ranking; the
        # caller adds it back to report true squared distances)
        score = tsq_ref[:] - 2.0 * jnp.dot(
            q, t.T, preferred_element_type=jnp.float32)   # (T_q, T_t)
        col = j * tile_t + jax.lax.broadcasted_iota(
            jnp.int32, score.shape, 1)
        # out-of-range padded train rows carry tsq = _BIG already; the
        # lane_k-width best list's sentinel columns (≥ k) carry _BIG/-1
        cand_d = jnp.concatenate([bestd_ref[:], score], axis=1)
        cand_i = jnp.concatenate([besti_ref[:], col], axis=1)
        cols = jax.lax.broadcasted_iota(jnp.int32, cand_d.shape, 1)
        outcols = jax.lax.broadcasted_iota(
            jnp.int32, bestd_ref.shape, 1)
        new_d = jnp.full_like(bestd_ref, _BIG)
        new_i = jnp.full_like(besti_ref, -1)
        for r in range(k):  # unrolled: k is small + static. Mask/reduce
            # formulation only — no gather/scatter, which Mosaic lacks.
            pos = jnp.argmin(cand_d, axis=1)              # (T_q,)
            sel = cols == pos[:, None]                    # one-hot rows
            dmin = jnp.min(cand_d, axis=1)
            imin = jnp.sum(jnp.where(sel, cand_i, 0), axis=1)
            write = outcols == r
            new_d = jnp.where(write, dmin[:, None], new_d)
            new_i = jnp.where(write, imin[:, None], new_i)
            cand_d = jnp.where(sel, _BIG, cand_d)
        bestd_ref[:] = new_d
        besti_ref[:] = new_i

    return kernel


@functools.partial(jax.jit, static_argnames=("k", "tile_q", "tile_t",
                                             "interpret"))
def argkmin_pallas(X_train, x_sq_train, X_query, k, *, tile_q=256,
                   tile_t=512, interpret=False):
    """Fused k-nearest-neighbor search: indices + squared distances of the
    ``k`` closest training rows per query, ascending.

    The XLA brute-force path (``models/neighbors.knn_indices``) computes
    a (query-block, n_train) distance matrix that round-trips HBM before
    ``lax.top_k`` consumes it. Here the distance tile and the running
    k-best never leave VMEM: the MXU produces a (tile_q, tile_t) score
    tile and the VPU folds it straight into the per-query best lists —
    the TPU twin of the native host runtime's blocked argkmin heap
    (``native.cpp``; reference role: the 2356-LoC ball/KD-tree Cython,
    ``neighbors/_ball_tree.pyx``).
    """
    nq, m = X_query.shape
    nt = X_train.shape[0]
    if not 0 < k <= nt:
        raise ValueError(f"k={k} outside 1..{nt}")
    m_p = _round_up(m, 128)
    lane_k = _round_up(k, 128)            # lane-aligned best-list width
    nq_p = _round_up(nq, tile_q)
    nt_p = _round_up(nt, tile_t)

    Qp = jnp.zeros((nq_p, m_p), jnp.float32).at[:nq, :m].set(X_query)
    Tp = jnp.zeros((nt_p, m_p), jnp.float32).at[:nt, :m].set(X_train)
    # padded train rows score _BIG so they are never selected
    tsqp = jnp.full((1, nt_p), _BIG, jnp.float32).at[0, :nt].set(x_sq_train)

    grid = (nq_p // tile_q, nt_p // tile_t)
    best_d, best_i = pl.pallas_call(
        _make_argkmin_kernel(int(k), tile_t),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_q, m_p), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_t, m_p), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_t), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tile_q, lane_k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_q, lane_k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nq_p, lane_k), jnp.float32),
            jax.ShapeDtypeStruct((nq_p, lane_k), jnp.int32),
        ],
        interpret=interpret,
    )(Qp, Tp, tsqp)

    # restore the query-norm term dropped from the ranking score; clamp
    # the float cancellation at 0 like pairwise_sq_distances does
    d2 = jnp.maximum(
        best_d[:nq, :k] + jnp.sum(X_query * X_query, axis=1)[:, None], 0.0)
    return best_i[:nq, :k], d2


def pallas_available():
    """True when a real TPU backend is attached (otherwise callers should
    pass interpret=True or use the XLA path)."""
    return jax.default_backend() == "tpu"
