"""Guarantee auditor: do the simulated routines honor their (ε, δ) contracts?

The paper's routines are randomized approximators sold with two-sided
contracts: "the realized error is at most ``tol`` with probability at
least ``1 − fail_prob``" (tomography's δ, amplitude/phase estimation's
(ε, γ), IPE's rescaled ε, consistent PE's ε-grid snap). The classical
simulations implement those estimators *exactly*, so every eager call has
a computable ground truth — and until now nobody checked it. This module
is the statistical half of the obs layer:

- **Per-draw records.** Every instrumented routine with a computable
  ground truth (:mod:`sq_learn_tpu.ops.quantum` — tomography, amplitude /
  phase / consistent phase estimation, IPE — plus the estimator-level
  sites in ``models/``) emits one ``guarantee`` JSONL record per audited
  draw: the declared budgets, the realized error, and whether the draw
  violated its tolerance. Calls from inside a jit trace are skipped (no
  concrete truth exists there); large batches are evenly subsampled to
  ``_MAX_DRAWS_PER_CALL`` draws so auditing never rivals the cost of the
  routine it audits.
- **Clopper–Pearson aggregation.** A single violated draw is *expected*
  — the contracts are probabilistic — so :func:`audit` flags a site only
  when the exact binomial lower confidence bound on its empirical failure
  rate exceeds the site's declared failure probability: the data must be
  statistically inconsistent with the contract before anyone is paged.
  No flaky single-draw alarms, by construction.
- **Strict escalation.** ``SQ_OBS_AUDIT_STRICT=1`` re-audits a site on
  every new violated draw and raises :class:`GuaranteeViolationError`
  the moment the lower bound crosses the declared failure probability.
- **Zero-budget short-circuits.** δ=0/ε=0 routes are the exact classical
  computation (framework-wide contract); their records carry
  ``short_circuit: true`` with ``realized = 0`` and ``violated = false``
  *by construction* — tests pin that an all-short-circuit site audits to
  zero violations.

Import-safe without jax (stdlib only): the audit/aggregation half is
consumed by the dependency-free report/frontier CLIs, which must run
with PYTHONPATH cleared while the accelerator relay is wedged.
"""

import math
from .. import _knobs

__all__ = [
    "GuaranteeViolationError",
    "audit",
    "clopper_pearson_lower",
    "enabled",
    "observe",
    "record_guarantee",
    "strict",
]

#: per-call cap on audited draws: a 70k-row tomography call records an
#: evenly strided 64-draw sample, not 70k lines (the audit is a
#: statistical check, not a census; ``n_total`` rides in the record)
_MAX_DRAWS_PER_CALL = 64

#: default confidence level of the Clopper–Pearson lower bound
CONFIDENCE = 0.95


class GuaranteeViolationError(RuntimeError):
    """A site's empirical failure rate is statistically inconsistent with
    its declared failure probability (raised under
    ``SQ_OBS_AUDIT_STRICT=1``)."""


def enabled():
    """True when a recorder is active — the arming condition for every
    instrumentation point (one module-global read when off)."""
    from . import recorder

    return recorder._active is not None


def strict():
    """True when flagged sites must raise (``SQ_OBS_AUDIT_STRICT=1``)."""
    return _knobs.get_bool("SQ_OBS_AUDIT_STRICT")


# ---------------------------------------------------------------------------
# Clopper–Pearson (exact binomial) lower confidence bound — dependency-free
# ---------------------------------------------------------------------------


def _log_binom_tail_geq(n, k, p):
    """log P(X ≥ k) for X ~ Binomial(n, p), exact via lgamma logs.

    Summed in probability space from the (at most n−k+1) upper-tail
    terms; n here is a per-site draw count (hundreds, not millions), so
    the direct sum is both exact enough and cheap.
    """
    if p <= 0.0:
        return -math.inf if k > 0 else 0.0
    if p >= 1.0:
        return 0.0
    lp, lq = math.log(p), math.log1p(-p)
    lgn = math.lgamma(n + 1)
    total = 0.0
    for i in range(k, n + 1):
        lt = (lgn - math.lgamma(i + 1) - math.lgamma(n - i + 1)
              + i * lp + (n - i) * lq)
        total += math.exp(lt)
    return math.log(total) if total > 0 else -math.inf


def clopper_pearson_lower(violations, trials, confidence=CONFIDENCE):
    """Exact (Clopper–Pearson) lower confidence bound on a binomial
    proportion: the largest p such that observing ≥ ``violations`` out of
    ``trials`` draws still has probability ≥ 1 − confidence under p.

    ``violations == 0`` returns 0.0 (no evidence of any failure rate);
    ``violations == trials`` still returns < 1 (finite data can't pin 1).
    Solved by bisection on the exact binomial upper tail — no scipy in
    the image (CLAUDE.md: no installs).
    """
    k, n = int(violations), int(trials)
    if n <= 0 or k <= 0:
        return 0.0
    if k > n:
        raise ValueError(f"violations {k} > trials {n}")
    alpha = 1.0 - float(confidence)
    log_alpha = math.log(alpha)
    lo, hi = 0.0, 1.0
    # P(X ≥ k | p) is increasing in p; the bound is the p where the tail
    # probability equals α. 60 bisection steps ≈ 1 ulp of float64.
    for _ in range(60):
        mid = 0.5 * (lo + hi)
        if _log_binom_tail_geq(n, k, mid) < log_alpha:
            lo = mid
        else:
            hi = mid
    return lo


# ---------------------------------------------------------------------------
# Per-draw records (the instrumentation surface)
# ---------------------------------------------------------------------------


def record_guarantee(site, realized, tol, *, fail_prob=None, violated=None,
                     short_circuit=False, n_total=None, **attrs):
    """Append one ``guarantee`` record (and its JSONL line) to the active
    run. No-op when observability is disabled.

    ``realized``/``tol`` are in the same error units (the routine's own:
    L2/L∞ for tomography, amplitude units for AE, phase units for PE...);
    ``fail_prob`` is the contract's declared failure probability (γ/δ —
    None when the routine declares none, which makes the site
    unflaggable but still measured). ``violated`` defaults to
    ``realized > tol`` — short-circuits record 0/0/False by construction.
    """
    from . import recorder

    rec = recorder.get_recorder()
    if rec is None:
        return
    realized = float(realized)
    tol = float(tol)
    if violated is None:
        violated = bool(realized > tol) and not short_circuit
    entry = {"type": "guarantee", "site": str(site),
             "realized": round(realized, 9), "tol": round(tol, 9),
             "violated": bool(violated),
             "fail_prob": (None if fail_prob is None
                           else round(float(fail_prob), 9))}
    if short_circuit:
        entry["short_circuit"] = True
    if n_total is not None:
        entry["n_total"] = int(n_total)
    if attrs:
        entry["attrs"] = recorder._jsonable(attrs)
    rec.record(entry, kind="guarantee_records")
    if entry["violated"] and strict():
        _enforce(rec, site)


def _enforce(rec, site):
    """Strict-mode escalation: re-audit ``site`` over the run so far and
    raise when the Clopper–Pearson lower bound on its failure rate
    exceeds its declared failure probability. Called only on violated
    draws, so the O(draws) re-audit never touches the happy path."""
    summary = audit(rec.guarantee_records).get(site)
    if summary and summary["flagged"]:
        raise GuaranteeViolationError(
            f"guarantee audit: site {site!r} violates its declared "
            f"contract — {summary['violations']}/{summary['trials']} draws "
            f"over tolerance, failure-rate lower bound "
            f"{summary['lower_bound']:.4f} > declared fail_prob "
            f"{summary['fail_prob']:.4f} (SQ_OBS_AUDIT_STRICT=1)")


def _subsample(n):
    """Evenly strided index sample of ``range(n)`` capped at
    ``_MAX_DRAWS_PER_CALL`` — deterministic, endpoints included."""
    if n <= _MAX_DRAWS_PER_CALL:
        return list(range(n))
    step = (n - 1) / (_MAX_DRAWS_PER_CALL - 1)
    return sorted({min(n - 1, round(i * step))
                   for i in range(_MAX_DRAWS_PER_CALL)})


def observe(site, realized_errors, tol, *, fail_prob=None, **attrs):
    """Record a batch of realized errors against one declared tolerance.

    ``realized_errors`` is a flat sequence (one entry per independent
    draw of the routine); batches beyond :data:`_MAX_DRAWS_PER_CALL` are
    evenly subsampled and the record carries ``n_total``. Scalar ``tol``
    or one per draw. No-op when observability is disabled.
    """
    if not enabled():
        return
    errs = [float(e) for e in realized_errors]
    n = len(errs)
    if n == 0:
        return
    try:
        tols = [float(t) for t in tol]
        if len(tols) != n:
            raise ValueError(
                f"per-draw tol length {len(tols)} != draws {n}")
    except TypeError:
        tols = [float(tol)] * n
    idx = _subsample(n)
    for i in idx:
        record_guarantee(site, errs[i], tols[i], fail_prob=fail_prob,
                         n_total=(n if n > len(idx) else None), **attrs)


# ---------------------------------------------------------------------------
# Aggregation (the auditor proper)
# ---------------------------------------------------------------------------


def audit(records=None, confidence=CONFIDENCE):
    """Aggregate guarantee records per site with Clopper–Pearson bounds.

    ``records`` defaults to the active run's ``guarantee_records``;
    any iterable of decoded record dicts works (the CLIs pass JSONL
    lines). Returns ``{site: {trials, violations, rate, lower_bound,
    fail_prob, flagged, short_circuits}}`` where ``fail_prob`` is the
    LARGEST failure probability the site declared (auditing against the
    loosest declaration is conservative: a flag means even the weakest
    contract is broken) and ``flagged`` means ``lower_bound >
    fail_prob``. Sites that never declared a failure probability are
    measured but unflaggable (``fail_prob: None``).
    """
    if records is None:
        from . import recorder

        rec = recorder.get_recorder()
        records = rec.guarantee_records if rec is not None else []
    sites = {}
    for r in records:
        if not isinstance(r, dict) or r.get("type") != "guarantee":
            continue
        s = sites.setdefault(r.get("site"),
                             {"trials": 0, "violations": 0,
                              "short_circuits": 0, "fail_prob": None})
        s["trials"] += 1
        if r.get("violated"):
            s["violations"] += 1
        if r.get("short_circuit"):
            s["short_circuits"] += 1
        fp = r.get("fail_prob")
        if isinstance(fp, (int, float)) and not isinstance(fp, bool):
            if s["fail_prob"] is None or fp > s["fail_prob"]:
                s["fail_prob"] = float(fp)
    for s in sites.values():
        s["rate"] = s["violations"] / s["trials"] if s["trials"] else 0.0
        s["lower_bound"] = clopper_pearson_lower(
            s["violations"], s["trials"], confidence)
        s["confidence"] = confidence
        s["flagged"] = (s["fail_prob"] is not None
                        and s["lower_bound"] > s["fail_prob"])
    return sites


def render(summary):
    """Format an :func:`audit` summary as the report's audit table."""
    lines = []
    if not summary:
        return "  (no guarantee records)"
    for site in sorted(summary):
        a = summary[site]
        fp = ("-" if a["fail_prob"] is None
              else f"{a['fail_prob']:.4g}")
        flag = "  FLAGGED" if a["flagged"] else ""
        sc = (f" short_circuit={a['short_circuits']}"
              if a["short_circuits"] else "")
        lines.append(
            f"  {a['violations']:4d}/{a['trials']:<5d} over tol  "
            f"lcb={a['lower_bound']:.4f} vs declared {fp:>7}  "
            f"{site}{sc}{flag}")
    return "\n".join(lines)


def main(argv):
    """``audit <jsonl> [more.jsonl ...] [--json] [--confidence C]`` —
    audit the guarantee records of one or more obs JSONL artifacts; exits
    1 when any site is flagged (the CI-friendly contract check)."""
    import json as _json
    import sys

    as_json = "--json" in argv
    confidence = CONFIDENCE
    paths = []
    it = iter(a for a in argv if a != "--json")
    for a in it:
        if a == "--confidence":
            confidence = float(next(it, CONFIDENCE))
        else:
            paths.append(a)
    if not paths:
        print("usage: python -m sq_learn_tpu.obs audit <jsonl> "
              "[more.jsonl ...] [--json] [--confidence C]",
              file=sys.stderr)
        return 2
    from .trace import load_jsonl

    records = []
    for p in paths:
        records.extend(load_jsonl(p))
    summary = audit(records, confidence)
    flagged = sorted(s for s, a in summary.items() if a["flagged"])
    if as_json:
        print(_json.dumps({"audit": summary, "flagged": flagged}))
    else:
        print("== guarantee audit ==")
        print(render(summary))
        print(f"flagged: {flagged if flagged else 'none'}")
    return 1 if flagged else 0
