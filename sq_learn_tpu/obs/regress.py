"""Perf-regression gate over the committed bench trajectory.

The repo commits every round's headline bench line (``BENCH_r*.json``)
and every suite record (``bench/records/*.txt``), and — since PR 2 —
those lines carry an ``obs`` object (compile counts, transfer bytes,
now peak HBM). This module closes the loop the ROADMAP demands ("every
PR makes a hot path measurably faster" is unenforceable without a
comparator): a fresh record is banded against the history per metric,
per gate —

=====================  ====================================================
gate                   red when (tolerance-banded, see ``TOLERANCES``)
=====================  ====================================================
latency                value > tol × median(history values) + slack
compile_count          obs.compile_count over the banded history median —
                       the forced-retracing signature (a shape leak turns
                       "compile once" into "compile per call")
total_transfer_bytes   obs.total_transfer_bytes over the band — a tiling
                       regression re-uploading data
peak_hbm_bytes         obs.peak_hbm_bytes over the band — a kernel's
                       working set growing past its history
accuracy               value of a ``unit: "accuracy"`` line (the frontier
                       sweeps' headlines) UNDER ratio × median − slack —
                       the lower-bounded quality band (replaces the
                       latency gate on those lines)
throughput             value of a ``unit: "qps"`` line (the serving load
                       bench's sustained-QPS headline) UNDER
                       ratio × median − slack — the lower-bounded
                       serving band (replaces the latency gate on those
                       lines)
vs_baseline            a record carrying ``vs_baseline_floor`` whose
                       ``vs_baseline`` drops UNDER floor × ratio − slack
                       — the history-free declared-floor band (the
                       out-of-core fit declares 0.95: store-backed
                       within 5% of in-RAM)
=====================  ====================================================

Verdicts are ``green`` / ``red`` / ``skip`` (skip = no reference on that
gate yet: pre-obs history rounds have no ``obs`` object — honest, not
silently green). Each verdict is one schema-valid ``regression`` JSONL
line, so the same validator/trace/report tooling reads gate output.

Consumers: ``run_suite.sh`` appends verdict lines per config (report-
only — the suite's pass/fail stays with the BASELINE acceptance gate),
``make regress`` runs the headline bench standalone and exits red, and
``make smoke`` runs :func:`selftest` — a real forced-retracing
injection that must flip the verdict red.

Dependency-free for the comparison path (stdlib only; jax is imported
by :func:`selftest` alone), so the CLI runs with PYTHONPATH cleared
while the accelerator relay is wedged.
"""

import glob
import json
import os
import time
from statistics import median
from .. import _knobs

SCHEMA_VERSION = 9  # keep in sync with recorder.SCHEMA_VERSION (no import:
# this module must stay loadable from a bare checkout for CI tooling)

__all__ = ["load_history", "check_record", "check_file", "selftest", "main"]

#: gate → (ratio tolerance, absolute slack). Ratio bands absorb
#: proportional drift (host load for latency, bucket padding for bytes);
#: the absolute slack keeps tiny references from banning tiny noise
#: (ref compile_count=1 must not make 2 compiles red). Env-overridable
#: per gate via SQ_REGRESS_TOL_<GATE> / SQ_REGRESS_SLACK_<GATE>.
#: ``accuracy`` and ``throughput`` are the LOWER-bounded gates (red when
#: the value DROPS below ratio × reference − slack): ``accuracy`` bands
#: the frontier sweeps' accuracy headlines (``unit: "accuracy"``),
#: ``throughput`` bands the serving load bench's sustained-QPS headline
#: (``unit: "qps"``) — a throughput collapse must trip the same analyzer
#: a latency regression does.
TOLERANCES = {
    "latency": (2.0, 0.05),
    "compile_count": (1.5, 2),
    "total_transfer_bytes": (1.25, 4096),
    "peak_hbm_bytes": (1.25, 1 << 20),
    "accuracy": (0.9, 0.02),
    "throughput": (0.5, 0.0),
    # declared-floor gate: a record carrying "vs_baseline_floor" bands
    # its own vs_baseline against it (red when vs_baseline < floor × tol
    # − slack). History-free: the floor is the bench's own contract —
    # the out-of-core fit declares 0.95 ("store-backed within 5% of
    # in-RAM", ISSUE 10 acceptance).
    "vs_baseline": (1.0, 0.0),
}

#: value-gate selection by the record's unit (default: latency)
_UNIT_GATES = {"accuracy": "accuracy", "qps": "throughput"}

#: the lower-bounded gates (value must stay ABOVE ratio × ref − slack)
_LOWER_BOUNDED = ("accuracy", "throughput")

#: gates read from the record's obs object (latency reads "value")
OBS_GATES = ("compile_count", "total_transfer_bytes", "peak_hbm_bytes")


def _tolerance(gate):
    tol, slack = TOLERANCES[gate]
    env_t = _knobs.get_raw(f"SQ_REGRESS_TOL_{gate.upper()}")
    env_s = _knobs.get_raw(f"SQ_REGRESS_SLACK_{gate.upper()}")
    return (float(env_t) if env_t else tol,
            float(env_s) if env_s else slack)


def _metric_lines(path):
    """The machine-readable metric lines of a bench record file (same
    filter as bench/_gate.py: JSON objects carrying "metric")."""
    out = []
    try:
        fh = open(path)
    except OSError:
        return out
    with fh:
        for raw in fh:
            raw = raw.strip()
            if not raw.startswith("{"):
                continue
            try:
                rec = json.loads(raw)
            except ValueError:
                continue
            if isinstance(rec, dict) and "metric" in rec and "value" in rec:
                out.append(rec)
    return out


def load_history(root="."):
    """{metric: [record, ...]} chronologically, from the committed
    ``BENCH_r*.json`` trajectory (each round's parsed headline line)
    plus every ``bench/records/*.txt`` suite record."""
    history = {}

    def add(rec):
        if isinstance(rec, dict) and "metric" in rec and "value" in rec:
            history.setdefault(rec["metric"], []).append(rec)

    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        try:
            doc = json.load(open(path))
        except (OSError, ValueError):
            continue
        add(doc.get("parsed"))
    for path in sorted(glob.glob(os.path.join(root, "bench", "records",
                                              "*.txt"))):
        for rec in _metric_lines(path):
            add(rec)
    return history


def _reference(history_recs, gate):
    """Banding reference for one gate: the median over history entries
    that carry the number (latency always does; obs gates only since the
    obs layer landed)."""
    vals = []
    for rec in history_recs:
        if gate not in OBS_GATES:
            v = rec.get("value")
        else:
            v = (rec.get("obs") or {}).get(gate)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            vals.append(float(v))
    return median(vals) if vals else None


def _current(rec, gate):
    if gate not in OBS_GATES:
        v = rec.get("value")
    else:
        v = (rec.get("obs") or {}).get(gate)
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


def check_record(rec, history):
    """Band one fresh metric record against the history; returns one
    schema-valid ``regression`` record per gate.

    The value gate depends on the record's unit: seconds-valued lines
    get the UPPER-bounded ``latency`` band; ``unit: "accuracy"`` lines
    (the frontier sweeps' headlines) and ``unit: "qps"`` lines (the
    serving load bench's sustained-throughput headline) get the
    LOWER-bounded ``accuracy``/``throughput`` bands — a drop below
    ratio × median(history) − slack is red.
    """
    metric = rec.get("metric", "?")
    past = history.get(metric, [])
    value_gate = _UNIT_GATES.get(rec.get("unit"), "latency")
    verdicts = []
    for gate in (value_gate,) + OBS_GATES:
        cur = _current(rec, gate)
        ref = _reference(past, gate)
        tol, slack = _tolerance(gate)
        if cur is None or ref is None:
            verdict, allowed = "skip", None
        elif gate in _LOWER_BOUNDED:
            allowed = ref * tol - slack
            verdict = "red" if cur < allowed else "green"
        else:
            allowed = ref * tol + slack
            verdict = "red" if cur > allowed else "green"
        verdicts.append({
            "v": SCHEMA_VERSION, "schema_version": SCHEMA_VERSION,
            "ts": round(time.time(), 3), "type": "regression",
            "gate": gate, "metric": metric, "verdict": verdict,
            "current": cur, "reference": ref,
            "tolerance": (round(allowed, 6) if allowed is not None
                          else None),
            "history_n": len(past),
        })
    floor = rec.get("vs_baseline_floor")
    if isinstance(floor, (int, float)) and not isinstance(floor, bool):
        # a record that declares its own vs_baseline floor gets the
        # history-free lower-bounded band (see TOLERANCES["vs_baseline"])
        cur = rec.get("vs_baseline")
        cur = (float(cur) if isinstance(cur, (int, float))
               and not isinstance(cur, bool) else None)
        tol, slack = _tolerance("vs_baseline")
        allowed = float(floor) * tol - slack
        verdicts.append({
            "v": SCHEMA_VERSION, "schema_version": SCHEMA_VERSION,
            "ts": round(time.time(), 3), "type": "regression",
            "gate": "vs_baseline", "metric": metric,
            "verdict": ("skip" if cur is None
                        else "red" if cur < allowed else "green"),
            "current": cur, "reference": float(floor),
            "tolerance": round(allowed, 6),
            "history_n": len(past),
        })
    return verdicts


def check_file(path, root="."):
    """Band every metric line of a fresh record file (a run_suite record
    or a single ``bench.py`` output line) against the committed history
    under ``root``. The fresh file itself is excluded from the history
    it is judged against."""
    history = load_history(root)
    fresh = _metric_lines(path)
    # a fresh file living inside bench/records/ was swept into the
    # history scan — drop its own lines from the reference set, or a run
    # would band against itself and always pass the ratio gates
    base = os.path.realpath(path)
    if base.startswith(os.path.realpath(os.path.join(root, "bench",
                                                     "records"))):
        own = {json.dumps(r, sort_keys=True) for r in fresh}
        history = {
            m: [r for r in recs
                if json.dumps(r, sort_keys=True) not in own]
            for m, recs in history.items()}
    verdicts = []
    for rec in fresh:
        verdicts.extend(check_record(rec, history))
    return verdicts


def selftest():
    """The CI self-test: a REAL injected regression must go red.

    Runs the same tiny jitted kernel three times under fresh obs runs:
    a baseline, an unmodified rerun (must stay green on every comparable
    gate), and a rerun with a deliberately leaked shape — one compile
    per call, the forced-retracing signature the watchdog exists for —
    which must produce a red ``compile_count`` verdict. Returns 0 on
    contract held, 1 otherwise (printed).
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from . import recorder

    def run(shapes):
        import warnings

        recorder.enable()
        f = jax.jit(lambda x: x * 2.0 + 1.0)
        from .watchdog import RetracingWarning, watchdog

        watchdog.track("regress.selftest", f, budget=1)
        for s in shapes:
            f(jnp.ones(s, jnp.float32))
        with warnings.catch_warnings():
            # the leaked run trips the watchdog BY DESIGN — that warning
            # is the injected regression, not selftest noise
            warnings.simplefilter("ignore", RetracingWarning)
            watchdog.observe("regress.selftest")
        snap = recorder.snapshot()
        recorder.disable()
        return {"metric": "regress_selftest", "value": 0.01, "unit": "s",
                "vs_baseline": 1.0, "obs": snap}

    baseline = run([(8,)] * 4)              # 1 compile
    clean = run([(8,)] * 4)                 # identical: 1 compile
    leaked = run([(8,), (16,), (32,), (64,)])  # shape leak: 4 compiles

    history = {"regress_selftest": [baseline]}
    clean_verdicts = check_record(clean, history)
    leaked_verdicts = check_record(leaked, history)
    clean_red = [v for v in clean_verdicts if v["verdict"] == "red"]
    leaked_red = [v for v in leaked_verdicts
                  if v["verdict"] == "red" and v["gate"] == "compile_count"]
    failures = []
    if clean_red:
        failures.append(f"clean rerun went red: {clean_red}")
    if not leaked_red:
        failures.append(
            "injected retracing (4 compiles vs baseline 1) did not go red: "
            f"{leaked_verdicts}")
    print(json.dumps({
        "regress_selftest": "fail" if failures else "ok",
        "clean": [v["verdict"] for v in clean_verdicts],
        "leaked": {v["gate"]: v["verdict"] for v in leaked_verdicts},
        "errors": failures,
    }))
    return 1 if failures else 0


def main(argv):
    """``regress <record-file> [--root DIR] [--no-exit-code]`` or
    ``regress --selftest``. Prints one regression JSONL line per
    (metric, gate) plus a summary line; exits 1 when any verdict is red
    (unless ``--no-exit-code`` — the report-only mode run_suite.sh
    uses)."""
    import sys

    if "--selftest" in argv:
        return selftest()
    root = "."
    exit_code = True
    paths = []
    it = iter(argv)
    for a in it:
        if a == "--root":
            root = next(it, ".")
        elif a == "--no-exit-code":
            exit_code = False
        else:
            paths.append(a)
    if not paths:
        print("usage: python -m sq_learn_tpu.obs regress <record-file> "
              "[--root DIR] [--no-exit-code] | --selftest",
              file=sys.stderr)
        return 2
    verdicts = []
    for p in paths:
        verdicts.extend(check_file(p, root))
    for v in verdicts:
        print(json.dumps(v))
    tally = {"green": 0, "red": 0, "skip": 0}
    for v in verdicts:
        tally[v["verdict"]] += 1
    print(json.dumps({"regression_summary": tally,
                      "metrics": len({v["metric"] for v in verdicts})}))
    if exit_code and tally["red"]:
        return 1
    return 0
