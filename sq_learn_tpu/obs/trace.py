"""Render obs JSONL into Chrome trace-event JSON (Perfetto-viewable).

A run's JSONL is the machine artifact; this module is the timeline view:
spans become duration events, counters/gauges become counter tracks, and
the discrete records (faults, breaker transitions, watchdog
observations, probes, ledger entries, xla_cost compilations) become
instant events on dedicated lanes — so a streamed fit reads as "tiles
marching, a retry blip, a breaker trip, one compile per bucket" instead
of a grep session.

Multi-process merging: every process that opens a sink writes a ``meta``
record carrying its pid first, so lines group onto pid lanes by the most
recent ``meta`` above them; files without one (hand-built fixtures) get
a synthetic per-file pid. Bench-suite runs pass each config's JSONL —
``run_suite.sh`` archives ``<slug>_trace.json`` next to each
``<slug>_obs.jsonl``, and multiple files merge onto separate process
lanes in one trace.

Dependency-free by design (stdlib json only, like
:mod:`~sq_learn_tpu.obs.schema`): the CLI runs with PYTHONPATH cleared
under a wedged accelerator relay, so it must never import jax.

CLI: ``python -m sq_learn_tpu.obs trace run.jsonl [more.jsonl ...]
[-o out.json]`` — default output is ``<first input>.trace.json``.
Env: ``SQ_OBS_TRACE=<path>`` makes
:func:`~sq_learn_tpu.obs.recorder.disable` render the closing run's
sink automatically.
"""

import json
import os

__all__ = ["load_jsonl", "to_chrome_trace", "write_trace", "main"]

#: tid lanes for non-span records — named via thread_name metadata so
#: Perfetto labels them instead of showing bare numbers
_LANES = {
    "span": (0, "spans"),
    "watchdog": (1, "compiles (watchdog)"),
    "xla_cost": (2, "xla cost"),
    "fault": (3, "faults"),
    "breaker": (4, "breaker"),
    "probe": (5, "probe"),
    "ledger": (6, "quantum ledger"),
    "regression": (7, "regression gate"),
    "guarantee": (8, "guarantee audit"),
    "tradeoff": (9, "tradeoff frontier"),
    "slo": (10, "serving slo"),
    "budget": (11, "error budgets"),
    "alert": (12, "budget alerts"),
    "control": (13, "controller decisions"),
    "elastic": (14, "elastic mesh"),
    "clock": (15, "clock samples"),
    "io": (16, "storage io"),
}

#: records that move onto a per-tenant lane when they carry a tenant
#: (the serving plane's per-tenant telemetry reads as one lane per
#: tenant: its slo windows, budget evaluations, alerts, and controller
#: decisions together)
_TENANT_TYPES = ("slo", "budget", "alert", "control")

#: first tid of the dynamically-allocated per-tenant lanes
_TENANT_TID0 = 17


def load_jsonl(path):
    """Decode one obs JSONL file into a list of record dicts (bad lines
    skipped — the trace view of a partially-written run is still a
    view). ``.jsonl.gz`` archives — the bench suite compresses each
    config's artifact after rendering — open transparently."""
    if str(path).endswith(".gz"):
        import gzip

        opener = gzip.open(path, "rt")
    else:
        opener = open(path)
    records = []
    with opener as fh:
        for raw in fh:
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw)
            except ValueError:
                continue
            if isinstance(rec, dict):
                records.append(rec)
    return records


def _args_of(rec, drop=("v", "schema_version", "ts", "type")):
    out = {}
    for k, v in rec.items():
        if k in drop:
            continue
        if isinstance(v, dict):
            out[k] = v
        elif isinstance(v, (str, int, float, bool)) or v is None:
            out[k] = v
        else:
            out[k] = repr(v)
    return out


def _instant_name(rec):
    t = rec["type"]
    if t == "watchdog":
        return (f"compile {rec.get('site')}: {rec.get('compiles')}"
                f"/{rec.get('budget')}")
    if t == "xla_cost":
        return f"xla_cost {rec.get('site')}"
    if t == "fault":
        return f"fault:{rec.get('kind')}"
    if t == "breaker":
        return f"breaker {rec.get('prev')}→{rec.get('state')}"
    if t == "probe":
        return f"probe:{rec.get('outcome')}"
    if t == "ledger":
        return f"ledger {rec.get('estimator')}.{rec.get('step')}"
    if t == "regression":
        return f"regress {rec.get('gate')}:{rec.get('verdict')}"
    if t == "guarantee":
        state = "VIOLATED" if rec.get("violated") else "ok"
        if rec.get("short_circuit"):
            state = "short-circuit"
        return f"guarantee {rec.get('site')}:{state}"
    if t == "tradeoff":
        return (f"tradeoff {rec.get('sweep')}@{rec.get('point')}: "
                f"acc={rec.get('accuracy')}")
    if t == "slo":
        who = rec.get("tenant") or rec.get("site")
        return (f"slo {who}: p99={rec.get('p99_ms')}ms "
                f"qps={rec.get('qps')}")
    if t == "budget":
        state = "ALERTING" if rec.get("alerting") else "ok"
        return (f"budget {rec.get('tenant')}@{rec.get('window_s')}s: "
                f"burn={rec.get('burn_rate')} {state}")
    if t == "alert":
        return f"ALERT {rec.get('tenant')}:{rec.get('kind')}"
    if t == "control":
        return (f"control {rec.get('tenant')}:{rec.get('action')}"
                f"@L{rec.get('level', 0)}")
    if t == "elastic":
        return (f"elastic {rec.get('event')} g{rec.get('generation')} "
                f"n={rec.get('n_hosts')}")
    if t == "clock":
        return f"clock {rec.get('peer')} via {rec.get('via', '?')}"
    if t == "io":
        shard = rec.get("shard")
        where = (f"{rec.get('store')}"
                 if shard is None else f"{rec.get('store')}[{shard}]")
        return (f"io {rec.get('surface')} {where}: "
                f"reads={rec.get('reads')} heat={rec.get('heat')}")
    return t


def to_chrome_trace(record_groups):
    """Build the trace-event dict from ``[(pid_label, records), ...]``
    groups — one group per source file. ``meta`` records inside a group
    re-key the pid lane (multi-process appenders share one file); a
    group with no ``meta`` gets a synthetic pid.
    """
    events = []
    named_pids = set()
    named_lanes = set()
    tenant_tids = {}  # (pid, tenant) -> dedicated lane tid

    def name_process(pid, label):
        if pid in named_pids:
            return
        named_pids.add(pid)
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": label}})

    def name_lane(pid, tid, label):
        if (pid, tid) in named_lanes:
            return
        named_lanes.add((pid, tid))
        events.append({"ph": "M", "name": "thread_name", "pid": pid,
                       "tid": tid, "args": {"name": label}})

    for group_idx, (label, records) in enumerate(record_groups):
        pid = 100000 + group_idx  # synthetic until a meta names the real one
        name_process(pid, label)
        for rec in records:
            t = rec.get("type")
            ts = rec.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            us = ts * 1e6
            if t == "meta":
                real = rec.get("pid")
                if isinstance(real, int):
                    pid = real
                    name_process(pid, f"{label} (pid {real})")
                continue
            if t == "span":
                dur = rec.get("dur_s")
                if not isinstance(dur, (int, float)):
                    continue
                tid, lane = _LANES["span"]
                name_lane(pid, tid, lane)
                events.append({
                    "ph": "X", "cat": "span", "name": str(rec.get("name")),
                    # ts is recorded at span CLOSE: start = end - duration
                    "ts": us - dur * 1e6, "dur": dur * 1e6,
                    "pid": pid, "tid": tid, "args": _args_of(rec),
                })
            elif t in ("counter", "gauge"):
                val = rec.get("value")
                if not isinstance(val, (int, float)) \
                        or isinstance(val, bool):
                    continue  # non-numeric gauges have no counter track
                events.append({
                    "ph": "C", "name": str(rec.get("name")), "ts": us,
                    "pid": pid, "tid": 0, "args": {"value": val},
                })
            elif t in _LANES:
                dyn = None  # label of a dynamically-allocated lane
                if t in _TENANT_TYPES and rec.get("tenant") is not None:
                    # per-tenant lane: a tenant's slo windows, budget
                    # evaluations, and alerts read as one timeline
                    dyn = f"tenant:{rec['tenant']}"
                elif t == "elastic" \
                        and isinstance(rec.get("generation"), int) \
                        and not isinstance(rec.get("generation"), bool):
                    # per-generation lane: each shrink's new world reads
                    # as its own timeline (v9)
                    dyn = f"elastic:g{rec['generation']}"
                if dyn is not None:
                    key = (pid, dyn)
                    tid = tenant_tids.get(key)
                    if tid is None:
                        tid = _TENANT_TID0 + len(tenant_tids)
                        tenant_tids[key] = tid
                    name_lane(pid, tid, dyn)
                else:
                    tid, lane = _LANES[t]
                    name_lane(pid, tid, lane)
                events.append({
                    "ph": "i", "s": "t", "cat": t, "name": _instant_name(rec),
                    "ts": us, "pid": pid, "tid": tid, "args": _args_of(rec),
                })
            # unknown types: skipped — the trace is a view, not a validator
    def _order(e):
        # ts collides at millisecond resolution when a flush emits many
        # lines at once; the v8 monotonic seq (budget/alert/control —
        # spans carry their own) breaks the tie deterministically, and
        # the stable sort preserves file order for records without one
        seq = e.get("args", {}).get("seq")
        return (e["ph"] != "M", e.get("ts", 0.0),
                seq if isinstance(seq, int) and not isinstance(seq, bool)
                else -1)

    events.sort(key=_order)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_trace(paths, out_path):
    """Render one or more obs JSONL files into ``out_path``; returns the
    trace dict."""
    groups = [(os.path.basename(p), load_jsonl(p)) for p in paths]
    trace = to_chrome_trace(groups)
    with open(out_path, "w") as fh:
        json.dump(trace, fh)
    return trace


def main(argv):
    """``trace <jsonl> [more.jsonl ...] [-o out.json]``"""
    import sys

    out = None
    paths = []
    it = iter(argv)
    for a in it:
        if a in ("-o", "--out"):
            out = next(it, None)
        else:
            paths.append(a)
    if not paths or out is None and not paths[0]:
        print("usage: python -m sq_learn_tpu.obs trace <jsonl> "
              "[more.jsonl ...] [-o out.json]", file=sys.stderr)
        return 2
    if out is None:
        out = paths[0] + ".trace.json"
    trace = write_trace(paths, out)
    print(json.dumps({"trace": out, "events": len(trace["traceEvents"]),
                      "sources": len(paths)}))
    return 0
