"""Device-health probe: the subprocess-with-timeout accelerator check,
measured.

The axon TPU tunnel can hang ``jax.devices()`` indefinitely (CLAUDE.md);
the known escape is probing backend init in a throwaway subprocess with a
timeout. That escape was duplicated across bench scripts with no record of
what it found — yet probe latency and outcome are exactly the fleet-health
signals the round-5 failures (judge-host segfault, relay wedges) showed we
were flying blind on. This module is the one implementation, and it records
every probe as a 'probe' JSONL record plus ``probe.latency_s`` /
``probe.ok`` gauges when a recorder is active.

Outcomes:

- ``"ok"``        — the subprocess initialized the backend within the
  timeout (a healthy tunnel answers in ~5–15 s).
- ``"timeout"``   — the subprocess hit the timeout: the wedge signature
  (every observed wedge lasted hours; the timeout is pure stall).
- ``"error"``     — backend init failed fast (version skew, no device).
- ``"cpu"``       — the platform under test is the host CPU; no probe
  subprocess is needed (nothing to wedge).
- ``"skipped"``   — no platform configured (jax auto-detect, local only).
"""

import os
import subprocess
import sys
import time

#: last probe result in this process (outcome, latency_s, platform) —
#: readable even when no recorder was active at probe time
last_probe = None


def _record(outcome, latency_s, platform):
    global last_probe
    last_probe = {"outcome": outcome, "latency_s": round(latency_s, 3),
                  "platform": platform}
    from . import recorder

    rec = recorder.get_recorder()
    if rec is not None:
        rec.record(dict(last_probe, type="probe"), kind="probe_events")
        recorder.gauge("probe.latency_s", round(latency_s, 3))
        # "skipped"/"cpu" are healthy outcomes: nothing to probe ≠ failure
        recorder.gauge("probe.ok", outcome in ("ok", "cpu", "skipped"))
    return last_probe


def probe_device(timeout_s=60, platform=None):
    """Initialize the configured JAX backend in a throwaway subprocess and
    report (never raise) the outcome with its measured latency.

    ``platform`` defaults to ``JAX_PLATFORMS``. CPU platforms and empty
    specs record without spawning (nothing to wedge); otherwise the
    subprocess runs ``import jax; jax.devices()`` under ``timeout_s``.
    The 60 s default matches the bench contract: a healthy tunnel answers
    in ~5–15 s and a wedged one never does, so longer patience is pure
    stall (CLAUDE.md). Returns ``{"outcome", "latency_s", "platform"}``.
    """
    if platform is None:
        platform = os.environ.get("JAX_PLATFORMS", "")
    if platform.split(",")[0].strip() == "cpu":
        return _record("cpu", 0.0, platform)
    if platform == "":
        return _record("skipped", 0.0, platform)
    t0 = time.perf_counter()
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, check=True, capture_output=True)
        outcome = "ok"
    except subprocess.TimeoutExpired:
        outcome = "timeout"
    except (subprocess.CalledProcessError, OSError):
        outcome = "error"
    return _record(outcome, time.perf_counter() - t0, platform)
