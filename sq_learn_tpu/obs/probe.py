"""Device-health probe: the subprocess-with-timeout accelerator check,
measured, cached, and fault-injectable.

The axon TPU tunnel can hang ``jax.devices()`` indefinitely (CLAUDE.md);
the known escape is probing backend init in a throwaway subprocess with a
timeout. That escape was duplicated across bench scripts with no record of
what it found — yet probe latency and outcome are exactly the fleet-health
signals the round-5 failures (judge-host segfault, relay wedges) showed we
were flying blind on. This module is the one implementation, and it records
every probe as a 'probe' JSONL record plus ``probe.latency_s`` /
``probe.ok`` gauges when a recorder is active.

Three resilience hooks ride on top of the measurement:

- **TTL cache** (``SQ_PROBE_TTL_S``, default 300 s): back-to-back bench
  scripts reuse the last real probe result instead of each paying a
  ~5-15 s subprocess — in-process via a module global, across processes
  via a tiny JSON file (``SQ_PROBE_CACHE``, default
  ``$TMPDIR/sq_probe_cache.json`` — the suite's configs are separate
  interpreters). A cached answer is recorded with ``cached: true`` and
  never re-feeds the breaker (no new information). ``force=True``
  bypasses the cache (the breaker's half-open trial must see a FRESH
  probe). The 300 s default is far shorter than any observed wedge
  (hours) or healthy window (~7-20 min), so a cached verdict cannot
  outlive the regime it measured.
- **Breaker feed**: every fresh outcome is reported to
  :data:`sq_learn_tpu.resilience.supervisor.breaker` — probe timeouts
  count toward the trip threshold exactly like mid-stream transfer
  failures.
- **Fault injection**: an armed ``probe_timeout`` injector
  (:mod:`sq_learn_tpu.resilience.faults`) forces the outcome without
  spawning a subprocess, so breaker behavior under wedge signals is
  CI-testable on the CPU backend.

Outcomes:

- ``"ok"``        — the subprocess initialized the backend within the
  timeout (a healthy tunnel answers in ~5–15 s).
- ``"timeout"``   — the subprocess hit the timeout: the wedge signature
  (every observed wedge lasted hours; the timeout is pure stall).
- ``"error"``     — backend init failed fast (version skew, no device).
- ``"cpu"``       — the platform under test is the host CPU; no probe
  subprocess is needed (nothing to wedge).
- ``"skipped"``   — no platform configured (jax auto-detect, local only).
"""

import json
import os
import subprocess
import sys
import tempfile
import time
from .. import _knobs

#: last probe result in this process (outcome, latency_s, platform) —
#: readable even when no recorder was active at probe time
last_probe = None

#: monotonic timestamp of the last FRESH (non-cached) probe, for the TTL
_last_probe_t = None


def probe_ttl_s():
    """TTL of a cached probe result. 300 s default: long enough that a
    bench suite's scripts share one probe, far shorter than any observed
    wedge (hours) or healthy window (~7-20 min). 0 disables caching."""
    return _knobs.get_float("SQ_PROBE_TTL_S")


def _cache_path():
    return _knobs.get_raw(
        "SQ_PROBE_CACHE",
        os.path.join(tempfile.gettempdir(), "sq_probe_cache.json"))


def _cache_read(platform):
    """A fresh-enough cached result for ``platform`` from the cross-process
    cache file, or None. Best-effort: an unreadable/stale/foreign file is
    simply a cache miss."""
    try:
        with open(_cache_path()) as fh:
            ent = json.load(fh)
        if (ent.get("platform") == platform
                and isinstance(ent.get("ts"), (int, float))
                and time.time() - ent["ts"] < probe_ttl_s()
                and isinstance(ent.get("outcome"), str)):
            return ent
    except Exception:
        pass
    return None


def _cache_write(outcome, latency_s, platform):
    """Persist a fresh real-probe result for sibling processes.

    Atomic AND durable: a per-call-unique temp file (``mkstemp`` —
    pid-suffixed names still collide between THREADS of one process,
    where one writer's truncate can race another's rename) is fsynced
    before the atomic rename, so a concurrent reader — sibling bench
    process or probing thread — only ever observes a complete JSON
    document, never a partial or empty one, and a crash after the rename
    cannot lose the data pages. Best-effort: a full disk must not break
    the probe."""
    import tempfile

    try:
        path = _cache_path()
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(path) or ".",
            prefix=os.path.basename(path) + ".", suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as fh:
                json.dump({"outcome": outcome,
                           "latency_s": round(latency_s, 3),
                           "platform": platform,
                           "ts": round(time.time(), 3)}, fh)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        except Exception:
            os.unlink(tmp)
            raise
    except Exception:
        pass


def _record(outcome, latency_s, platform, cached=False):
    global last_probe, _last_probe_t
    last_probe = {"outcome": outcome, "latency_s": round(latency_s, 3),
                  "platform": platform}
    if not cached:
        _last_probe_t = time.monotonic()
    from . import recorder

    rec = recorder.get_recorder()
    if rec is not None:
        ev = dict(last_probe, type="probe")
        if cached:
            ev["cached"] = True
        rec.record(ev, kind="probe_events")
        recorder.gauge("probe.latency_s", round(latency_s, 3))
        # "skipped"/"cpu" are healthy outcomes: nothing to probe ≠ failure
        recorder.gauge("probe.ok", outcome in ("ok", "cpu", "skipped"))
    if not cached:
        # fresh outcomes feed the circuit breaker (a cached answer carries
        # no new health information); lazy import — resilience is optional
        # at probe time and must never break the measurement
        try:
            from ..resilience.supervisor import breaker

            breaker.on_probe(outcome)
        except Exception:
            pass
    return dict(last_probe, cached=True) if cached else last_probe


def probe_device(timeout_s=60, platform=None, force=False):
    """Initialize the configured JAX backend in a throwaway subprocess and
    report (never raise) the outcome with its measured latency.

    ``platform`` defaults to ``JAX_PLATFORMS``. CPU platforms and empty
    specs record without spawning (nothing to wedge); otherwise the
    subprocess runs ``import jax; jax.devices()`` under ``timeout_s``.
    The 60 s default matches the bench contract: a healthy tunnel answers
    in ~5–15 s and a wedged one never does, so longer patience is pure
    stall (CLAUDE.md). Returns ``{"outcome", "latency_s", "platform"}``.

    A result younger than ``SQ_PROBE_TTL_S`` for the same platform is
    returned from cache (``cached: true`` in the returned dict and the
    JSONL record) unless ``force=True``; an armed ``probe_timeout``
    injector forces the outcome without spawning.
    """
    if platform is None:
        platform = _knobs.get_raw("JAX_PLATFORMS", "")
    if platform.split(",")[0].strip() == "cpu":
        return _record("cpu", 0.0, platform)
    if platform == "":
        return _record("skipped", 0.0, platform)
    if not force:
        if (last_probe is not None and _last_probe_t is not None
                and last_probe["platform"] == platform
                and time.monotonic() - _last_probe_t < probe_ttl_s()):
            return _record(last_probe["outcome"], last_probe["latency_s"],
                           platform, cached=True)
        ent = _cache_read(platform)
        if ent is not None:
            return _record(ent["outcome"], ent.get("latency_s", 0.0),
                           platform, cached=True)
    from ..resilience import faults as _faults

    if _faults._active is not None:
        forced = _faults._active.on_probe()
        if forced is not None:
            return _record(forced,
                           float(timeout_s) if forced == "timeout" else 0.0,
                           platform)
    t0 = time.perf_counter()
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, check=True, capture_output=True)
        outcome = "ok"
    except subprocess.TimeoutExpired:
        outcome = "timeout"
    except (subprocess.CalledProcessError, OSError):
        outcome = "error"
    latency = time.perf_counter() - t0
    _cache_write(outcome, latency, platform)
    return _record(outcome, latency, platform)
