"""Retracing watchdog: per-call-site jit compile accounting with budgets.

XLA recompiles silently — a shape leak turns "compile once, run many" into
"compile every call", and nothing in the program text changes. PR 1's
streaming engine promises ≤1 compile per (bucket, dtype); this module turns
that class of promise into an enforced invariant: each call site registers
its jitted kernel, declares the signatures it *expects* to mint compiles
(or a flat integer budget), and :meth:`RetracingWatchdog.observe` compares
the kernel's actual compile-cache growth against the budget — warning
(:class:`RetracingWarning`) or, under ``SQ_OBS_STRICT=1``, raising
(:class:`RetracingError`) on the first excess compile.

Compile counts are read from the jitted function's ``_cache_size()`` (the
same hook ``streaming.kernel_cache_sizes`` uses) and are **baselined at
registration**: entries compiled before a site was tracked (earlier tests
in the same process, warm-up phases outside the run) never count against a
budget declared inside the run. :func:`~sq_learn_tpu.obs.recorder.enable`
resets the whole watchdog, scoping counts to the observability run.

The watchdog is usable standalone (no recorder needed — budgets are an
enforcement tool, not a metric); when a recorder is active, every
observation also lands as a 'watchdog' JSONL record.
"""

import threading
import warnings
from .. import _knobs


class RetracingWarning(RuntimeWarning):
    """A call site recompiled beyond its declared budget."""


class RetracingError(RuntimeError):
    """Strict-mode (``SQ_OBS_STRICT=1``) form of :class:`RetracingWarning`."""


def _cache_size(fn):
    """Compile-cache entry count of a jitted callable, or None when the
    callable exposes no cache (not jitted / future jax API drift)."""
    try:
        return int(fn._cache_size())
    except Exception:
        return None


class RetracingWatchdog:
    """Per-site compile accounting. Sites are plain strings (convention:
    ``"<module>.<kernel>"``); state per site is the tracked callable, a
    baseline cache size, an allowed-signature set, and an optional flat
    budget."""

    def __init__(self):
        self._lock = threading.RLock()
        self._sites = {}

    def reset(self):
        with self._lock:
            self._sites.clear()

    def track(self, site, fn, budget=None):
        """Register ``fn`` under ``site``. First registration snapshots the
        cache baseline; re-registration updates the budget/fn only (the
        baseline is the run's anchor and must not move)."""
        with self._lock:
            st = self._sites.get(site)
            if st is None:
                st = {"fn": fn, "base": _cache_size(fn) or 0, "budget": budget,
                      "signatures": set(), "compiles": 0, "observations": 0,
                      "over_budget": False}
                self._sites[site] = st
            else:
                st["fn"] = fn
                if budget is not None:
                    st["budget"] = budget
            return st

    def allow(self, site, signature):
        """Declare one expected compile signature (e.g. a streaming
        ``(bucket_rows, dtype)`` pair). With no flat budget set, the
        budget is the number of distinct allowed signatures."""
        with self._lock:
            st = self._sites.get(site)
            if st is None:
                raise KeyError(f"watchdog site {site!r} is not tracked")
            st["signatures"].add(signature)

    def budget_of(self, site):
        with self._lock:
            st = self._sites[site]
            if st["budget"] is not None:
                return st["budget"]
            return len(st["signatures"]) or None

    def observe(self, site):
        """Read the site's compile count (cache entries since baseline),
        enforce the budget, and record the observation. Returns the compile
        count, or None when the tracked callable exposes no cache."""
        with self._lock:
            st = self._sites.get(site)
            if st is None:
                raise KeyError(f"watchdog site {site!r} is not tracked")
            size = _cache_size(st["fn"])
            if size is None:
                return None
            compiles = max(0, size - st["base"])
            st["compiles"] = compiles
            st["observations"] += 1
            budget = (st["budget"] if st["budget"] is not None
                      else (len(st["signatures"]) or None))
            over = budget is not None and compiles > budget
            newly_over = over and not st["over_budget"]
            st["over_budget"] = over
        from . import recorder

        rec = recorder.get_recorder()
        if rec is not None:
            rec.record({"type": "watchdog", "site": site,
                        "compiles": compiles, "budget": budget,
                        "over_budget": over}, kind="watchdog_events")
        if newly_over:
            msg = (f"retracing watchdog: call site {site!r} has {compiles} "
                   f"jit compiles, over its declared budget of {budget} — "
                   "a shape/dtype is leaking into the traced signature")
            if _knobs.get_bool("SQ_OBS_STRICT"):
                raise RetracingError(msg)
            warnings.warn(msg, RetracingWarning, stacklevel=2)
        return compiles

    def watch(self, site, fn, budget=None):
        """Wrap a jitted callable so every call is followed by an
        :meth:`observe` — the hammer for suspected retracing hot spots
        (per-call overhead: one cache-size read)."""
        import functools

        self.track(site, fn, budget=budget)

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            out = fn(*args, **kwargs)
            self.observe(site)
            return out

        return wrapped

    def report(self):
        """{site: {compiles, budget, observations, over_budget}} snapshot."""
        with self._lock:
            return {
                site: {"compiles": st["compiles"],
                       "budget": (st["budget"] if st["budget"] is not None
                                  else (len(st["signatures"]) or None)),
                       "observations": st["observations"],
                       "over_budget": st["over_budget"]}
                for site, st in self._sites.items()}


#: the process-wide watchdog every instrumented site shares
watchdog = RetracingWatchdog()
