"""File-tool half of the serving control plane: collect / render / CLI
over ``control`` records (schema v8).

The live half — the SLO-driven (ε, δ) autotuner that *emits* these
records — is :mod:`sq_learn_tpu.serving.control`; it may import numpy
and the serving plane. This module is its read side, and follows the
:mod:`~sq_learn_tpu.obs.budget` split exactly: stdlib only, never
imports jax, safe to run with PYTHONPATH cleared while the accelerator
relay is wedged.

One ``control`` record is one controller evaluation of one tenant: the
telemetry it consumed (``inputs`` — burn rates, Clopper–Pearson bounds,
the frontier point), the decision it took (``decision`` — route,
coalescing floor, renegotiated targets, served (ε, δ)), the decision's
``predicted`` effect, and the ``realized`` effect of the PREVIOUS
decision (measured a full evaluation later, closing the loop). The
``action`` vocabulary: ``plan`` (register/warm-time frontier pick),
``hold`` (evaluated, no change), ``relax`` / ``tighten`` (served (ε, δ)
moved), ``degrade`` / ``recover`` (admission-control ladder moved).

CLI: ``python -m sq_learn_tpu.obs control <jsonl> [more.jsonl ...]
[--json]`` — exits 0 when control records exist, 2 when the artifacts
carry none ("no telemetry" must never read as "nothing to decide",
the same convention as the budget CLI).
"""

__all__ = ["collect", "render", "main"]


def collect(records):
    """Aggregate decoded records into the control view:
    ``{"tenants": {tenant: [records, eval-ordered]}, "actions":
    {action: count}}`` — per-tenant decision histories ordered by
    ``(ts, seq)`` so the ladder walk reads top to bottom."""
    tenants = {}
    actions = {}
    for r in records:
        if not isinstance(r, dict) or r.get("type") != "control":
            continue
        tenants.setdefault(str(r.get("tenant")), []).append(r)
        a = r.get("action")
        actions[a] = actions.get(a, 0) + 1
    for recs in tenants.values():
        recs.sort(key=lambda r: (r.get("ts", 0.0),
                                 r.get("seq") if isinstance(r.get("seq"),
                                                            int) else -1))
    return {"tenants": tenants, "actions": actions}


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float) and (abs(v) >= 1e5 or 0 < abs(v) < 1e-3):
        return f"{v:.3e}"
    return f"{v:.4g}" if isinstance(v, float) else str(v)


def _kv(obj, keys):
    parts = []
    for k in keys:
        if obj.get(k) is not None:
            parts.append(f"{k}={_fmt(obj[k])}")
    return " ".join(parts)


def render(view, last=8):
    """Format a :func:`collect` view as the report's controller-decisions
    section: the action tally, then each tenant's most recent ``last``
    decisions with the inputs they consumed and the predicted vs
    realized effect."""
    lines = []
    out = lines.append
    tenants = view.get("tenants") or {}
    if not tenants:
        return "  (no control records)"
    tally = ", ".join(f"{a}={n}" for a, n in
                      sorted((view.get("actions") or {}).items()))
    out(f"  actions: {tally}")
    for tenant in sorted(tenants, key=str):
        recs = tenants[tenant]
        shown = recs[-last:]
        skipped = len(recs) - len(shown)
        head = f"  {tenant}: {len(recs)} evaluation(s)"
        if skipped:
            head += f" (showing last {len(shown)})"
        out(head)
        for r in shown:
            inputs = r.get("inputs") or {}
            decision = r.get("decision") or {}
            inp = _kv(inputs, ("burn_rate", "slo_burn_rate",
                               "stat_burn_rate", "cp_lower_bound",
                               "requests"))
            dec = _kv(decision, ("route", "min_rows", "delta_served",
                                 "eps_served", "p99_ms", "cost"))
            line = (f"    #{_fmt(r.get('seq'))} {r.get('action')}"
                    f"@L{r.get('level', 0)}")
            if inp:
                line += f"  in[{inp}]"
            if dec:
                line += f"  out[{dec}]"
            out(line)
            pred, real = r.get("predicted"), r.get("realized")
            if pred or real:
                pr = _kv(pred or {}, sorted(pred or {}))
                rl = (_kv(real, sorted(real)) if isinstance(real, dict)
                      else "-")
                out(f"      predicted[{pr}]  realized[{rl}]")
    return "\n".join(lines)


def main(argv):
    """``control <jsonl> [more.jsonl ...] [--json]`` — render the
    controller-decision history of one or more obs JSONL artifacts;
    exits 0 when control records exist, 2 when there are none (empty
    telemetry is distinguishable from a quiet controller: a quiet
    controller still lands ``plan``/``hold`` records)."""
    import json
    import sys

    as_json = "--json" in argv
    paths = [a for a in argv if a != "--json"]
    if not paths:
        print("usage: python -m sq_learn_tpu.obs control <jsonl> "
              "[more.jsonl ...] [--json]", file=sys.stderr)
        return 2
    from .trace import load_jsonl

    records = []
    for p in paths:
        records.extend(load_jsonl(p))
    view = collect(records)
    if not view["tenants"]:
        if as_json:
            print(json.dumps(dict(view, error="no control telemetry")))
        print(f"no control telemetry: zero control records in "
              f"{', '.join(paths)}", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(view))
    else:
        print("== controller decisions (SLO-driven (eps, delta) "
              "autotuner) ==")
        print(render(view))
    return 0
