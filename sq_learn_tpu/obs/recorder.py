"""Run-scoped observability recorder: spans, counters, gauges, JSONL sink.

The paper's thesis makes ε/δ *runtime* parameters, so stating its
accuracy-vs-runtime trade-off requires correlating measured wall-clock with
theoretical quantum query counts per run — and the production north star
(ROADMAP) requires knowing where wall-clock goes at all. This module is the
spine: an in-memory :class:`Recorder` that every instrumented surface
(streaming engine, estimator fits, mesh kernels, bench scripts, the driver
gate) writes through, with an optional append-only JSONL sink.

Design constraints, in order:

1. **Near-zero overhead when disabled.** ``SQ_OBS`` unset means every
   instrumentation point is one module-global read: :func:`span` returns a
   shared no-op context manager, :func:`counter_add`/:func:`gauge` return
   immediately. Nothing allocates, nothing formats, nothing touches jax.
2. **Run-scoped.** :func:`enable` starts a fresh run (empty recorder, reset
   watchdog/ledger state); :func:`disable` closes the sink. ``SQ_OBS=1``
   auto-enables at import with the sink at ``SQ_OBS_PATH`` (default
   ``sq_obs.jsonl`` in the CWD).
3. **Honest timing.** Spans record host wall-clock between enter and exit.
   JAX dispatch is asynchronous, so a span around an unsynced dispatch
   measures dispatch, not compute; pass ``sync=`` (or call ``.sync(x)``)
   to block on device values at exit, and the record carries
   ``synced: true`` only then. Instrumented fit surfaces return host
   arrays, so their spans are synced by construction.

JSONL schema: one JSON object per line, every line carrying
``{"v": 11, "schema_version": 11, "ts": <unix seconds>, "type": <record
type>}`` plus per-type fields — see :mod:`sq_learn_tpu.obs.schema` (the
validator) and ``docs/observability.md`` (the prose). ``v`` is the
original envelope key (kept so pre-2 readers don't break);
``schema_version`` is its explicit alias and the one the validator
version-gates on.
"""

import json
import os
import threading
import time
from .. import _knobs

# v2: +xla_cost / regression record types, +schema_version envelope field
# v3: +guarantee / tradeoff record types (the statistical-observability
#     layer: (ε, δ)-contract audits and accuracy-vs-runtime sweep points)
# v4: +slo record type (the serving layer's per-run p50/p99 latency,
#     sustained QPS, batch-occupancy and degrade accounting)
# v5: +slo.transfer_bytes optional field (the quantized serving route's
#     bytes-moved evidence, PR 11 — no new record types)
# v6: +budget / alert record types (the per-tenant error-budget ledger:
#     rolling-window latency-SLO and (ε, δ) burn rates with multi-window
#     alerting, PR 12) and the optional slo.tenant / slo.stages fields
#     (per-tenant SLO records and the queue/coalesce/transfer/compute/
#     scatter latency decomposition)
# v7: +the compressed-tier codec counters (PR 13 — no new record types):
#     oocore.codec_bytes_in/out (stored vs decoded bytes through the
#     shard codec), serving.cache_spills / serving.cache_disk_hits (the
#     feature-cache disk tier), the cold_tier fault kind, and the
#     oocore.create_store span's codec attr; snapshot grows the matching
#     codec/spill fields
# v8: +control record type (the serving control plane: one SLO-driven
#     autotuner evaluation/action per record — inputs consumed, decision
#     taken, predicted vs realized effect,
#     sq_learn_tpu.serving.control), and the optional monotonic
#     budget.seq / alert.seq fields (deterministic trace-export merge
#     order when timestamps collide)
# v9: +elastic record type (the elastic multi-host mesh, PR 18: one
#     record per transition — world_up / resume / host_fail /
#     host_stall / shrink / commit_refused / stale_exit / done — with
#     generation, host counts, failed host, detection latency, shrink
#     wall-clock and resumed cursor; sq_learn_tpu.parallel.elastic),
#     and the host_fail / host_stall fault kinds' optional
#     fault.host / fault.stall_s fields
# v10: +the fleet envelope (PR 19: an optional per-record ``fleet``
#      sub-object — coordinator-minted run_id, host label, pid, live
#      generation — stamped on every record when SQ_OBS_FLEET_RUN_ID is
#      set, so N workers' shards merge into one mesh-wide timeline),
#      +clock record type (one KV-carried clock sample per heartbeat /
#      manifest / progress exchange; obs.fleet estimates per-host
#      offsets from them), and the elastic ``window`` / ``commit``
#      events (per-host fold progress + node-0 commit ledger — the
#      fold ledger's obs twin that obs.fleet reconciles)
# v11: +io record type (the storage-plane ledger, obs.storage: one
#      CUMULATIVE per-(surface, store, shard) aggregate per flush —
#      stored vs raw bytes, read/CRC/decode/cold latency decomposition,
#      prefetch hit/stall/serial split, retry/quarantine counts,
#      spill/disk-hit/promote traffic for the serving surfaces, EWMA
#      heat — flushed at pass end and recorder close, never per read),
#      +size-based sink rotation (SQ_OBS_ROTATE_BYTES gzips the live
#      sink to ``<path>.<n>.gz`` segments mid-run; the optional
#      meta.segment field stamps each reopened segment), and the
#      snapshot's per-surface storage gauges
SCHEMA_VERSION = 11

#: default sink path when SQ_OBS=1 and SQ_OBS_PATH is unset
DEFAULT_PATH = "sq_obs.jsonl"

_lock = threading.RLock()
_tls = threading.local()

#: the active recorder, or None when observability is off. Module-global so
#: the disabled fast path is a single attribute read.
_active = None


class _NullSpan:
    """The disabled-mode span: a shared, stateless, no-op context manager."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self

    def sync(self, value):
        return value


NULL_SPAN = _NullSpan()


class Span:
    """One timed scope. Created by :func:`span`; closes into a 'span'
    record with nesting metadata (depth, parent seq) from a per-thread
    stack."""

    __slots__ = ("_rec", "name", "attrs", "_sync", "_t0", "_seq", "_parent",
                 "_depth", "_synced")

    def __init__(self, rec, name, sync, attrs):
        self._rec = rec
        self.name = name
        self.attrs = attrs
        self._sync = sync
        self._synced = False

    def set(self, **attrs):
        """Attach attributes discovered mid-scope (resolved solver, engine,
        byte counts); they land in the closed record."""
        self.attrs.update(attrs)
        return self

    def sync(self, value):
        """Block on ``value`` at exit (device sync) and return it — chains
        into expressions: ``out = sp.sync(step(...))``."""
        self._sync = value
        return value

    def __enter__(self):
        stack = getattr(_tls, "span_stack", None)
        if stack is None:
            stack = _tls.span_stack = []
        self._parent = stack[-1]._seq if stack else None
        self._depth = len(stack)
        self._seq = self._rec._next_seq()
        stack.append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._sync is not None:
            import jax

            jax.block_until_ready(self._sync)
            self._synced = True
        dur = time.perf_counter() - self._t0
        stack = getattr(_tls, "span_stack", ())
        if stack and stack[-1] is self:
            stack.pop()
        rec = {"type": "span", "name": self.name, "seq": self._seq,
               "dur_s": round(dur, 6), "depth": self._depth,
               "parent": self._parent, "synced": self._synced}
        if exc_type is not None:
            rec["error"] = exc_type.__name__
        if self.attrs:
            rec["attrs"] = _jsonable(self.attrs)
        self._rec.record(rec, kind="spans")
        return False


def _jsonable(obj):
    """Best-effort conversion of attr values to JSON-serializable types;
    observability must never crash the instrumented computation."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    try:
        return float(obj)  # numpy / jax scalars
    except Exception:
        return repr(obj)


class Recorder:
    """In-memory store of one run's records, with an optional JSONL sink.

    Public views: ``spans``, ``counters``, ``gauges``, ``ledger_entries``,
    ``watchdog_events``, ``probe_events``, ``fault_events``,
    ``breaker_events``, ``xla_cost_records``, ``guarantee_records``,
    ``tradeoff_records``, ``slo_records``, ``budget_records``,
    ``alert_records``, ``control_records``, ``elastic_records``,
    ``io_records`` — all plain Python containers, safe to read at any
    point in the run.
    """

    def __init__(self, path=None, run_id=None, host=None):
        # fleet identity (PR 19): when a coordinator minted a run_id —
        # via SQ_OBS_FLEET_RUN_ID in a spawned worker's env, or passed
        # explicitly for a private (non-global) recorder — every record
        # carries a ``fleet`` envelope so N processes' shards merge into
        # one causally-ordered mesh timeline (obs.fleet). Without a
        # run_id the envelope is absent and records stay byte-identical
        # to a single-process run.
        rid = (run_id if run_id is not None
               else _knobs.get_str("SQ_OBS_FLEET_RUN_ID", ""))
        if rid:
            self.fleet_run_id = str(rid)
            self.fleet_host = str(
                host or _knobs.get_str("SQ_OBS_FLEET_HOST", "")
                or f"pid{os.getpid()}")
        else:
            self.fleet_run_id = None
            self.fleet_host = str(host) if host else None
        self.fleet_generation = None
        self.spans = []
        self.counters = {}
        self.gauges = {}
        self.gauge_events = []
        self.ledger_entries = []
        self.watchdog_events = []
        self.probe_events = []
        self.fault_events = []
        self.breaker_events = []
        self.xla_cost_records = []
        self.guarantee_records = []
        self.tradeoff_records = []
        self.slo_records = []
        self.budget_records = []
        self.alert_records = []
        self.control_records = []
        self.elastic_records = []
        self.io_records = []
        # storage-plane ledger (obs.storage, v11): attached lazily at the
        # first instrumented shard/cache access, flushed by close()
        self._storage = None
        self._xla_seen = set()  # (site, signature) dedup for obs.xla
        self.path = path
        self._seq = 0
        self._sink = None
        # size-based sink rotation (v11): at SQ_OBS_ROTATE_BYTES written
        # bytes the live sink gzips to <path>.<n>.gz and reopens fresh —
        # long fleet runs stay bounded on disk; readers are
        # gzip-transparent. 0 (the default) disables.
        self._rotate_bytes = _knobs.get_int("SQ_OBS_ROTATE_BYTES")
        self._sink_bytes = 0
        self._segments = 0
        if path:
            self._sink = open(path, "a", buffering=1)
            self.record({"type": "meta", "pid": os.getpid(),
                         "schema": SCHEMA_VERSION}, kind=None)

    def _next_seq(self):
        with _lock:
            self._seq += 1
            return self._seq

    def record(self, rec, kind=None):
        """Store ``rec`` in-memory (under ``kind``) and append it to the
        sink as one JSON line."""
        rec.setdefault("v", SCHEMA_VERSION)
        rec.setdefault("schema_version", SCHEMA_VERSION)
        rec.setdefault("ts", round(time.time(), 3))
        if self.fleet_run_id is not None and "fleet" not in rec:
            rec["fleet"] = {"run_id": self.fleet_run_id,
                            "host": self.fleet_host,
                            "pid": os.getpid(),
                            "gen": self.fleet_generation}
        with _lock:
            if kind is not None:
                getattr(self, kind).append(rec)
            if self._sink is not None:
                try:
                    line = json.dumps(rec) + "\n"
                    self._sink.write(line)
                    self._sink_bytes += len(line)
                except Exception:
                    pass  # a full disk must not kill the fit
                else:
                    if (self._rotate_bytes
                            and self._sink_bytes >= self._rotate_bytes):
                        self._rotate_locked()

    def _rotate_locked(self):
        """Rotate the live sink: gzip its contents to the next
        ``<path>.<n>.gz`` segment and reopen the path fresh (with a new
        meta line stamping the segment ordinal). Best-effort — rotation
        trouble degrades to an unrotated sink, never a dead run."""
        try:
            import gzip
            import shutil

            self._sink.flush()
            self._sink.close()
            self._segments += 1
            seg = f"{self.path}.{self._segments}.gz"
            with open(self.path, "rb") as src, \
                    gzip.open(seg, "wb") as dst:
                shutil.copyfileobj(src, dst)
            self._sink = open(self.path, "w", buffering=1)
            meta = {"type": "meta", "pid": os.getpid(),
                    "schema": SCHEMA_VERSION, "segment": self._segments,
                    "v": SCHEMA_VERSION, "schema_version": SCHEMA_VERSION,
                    "ts": round(time.time(), 3)}
            if self.fleet_run_id is not None:
                meta["fleet"] = {"run_id": self.fleet_run_id,
                                 "host": self.fleet_host,
                                 "pid": os.getpid(),
                                 "gen": self.fleet_generation}
            line = json.dumps(meta) + "\n"
            self._sink.write(line)
            self._sink_bytes = len(line)
        except Exception:
            try:
                if self._sink is None or self._sink.closed:
                    self._sink = open(self.path, "a", buffering=1)
                self._rotate_bytes = 0  # stop retrying on every write
            except Exception:
                self._sink = None

    def flush(self, fsync=True):
        """Flush the JSONL sink to the OS — and, with ``fsync`` (the
        default), to disk — so a SIGKILL right after loses at most the
        line currently being written. Elastic workers call this at every
        commit-window boundary and immediately before ``os._exit``
        (`docs/resilience.md` §elastic). Returns True when a sink was
        durably flushed; best-effort like the write path (a full disk
        must not kill the fit)."""
        with _lock:
            sink = self._sink
            if sink is None:
                return False
            try:
                sink.flush()
                if fsync:
                    os.fsync(sink.fileno())
            except Exception:
                return False
            return True

    def close(self):
        with _lock:
            # drain the storage ledger's dirty aggregates first so a run
            # that never hit a pass-end flush still lands its io records
            # (the RLock makes the nested record() calls safe here)
            if self._storage is not None:
                try:
                    self._storage.flush("close")
                except Exception:
                    pass  # obs must never mask the run it observed
            if self._sink is not None:
                try:
                    self._sink.close()
                finally:
                    self._sink = None


# ---------------------------------------------------------------------------
# Module-level API (the instrumentation surface)
# ---------------------------------------------------------------------------


def enabled():
    """True when a recorder is active (``SQ_OBS=1`` or :func:`enable`)."""
    return _active is not None


def get_recorder():
    """The active :class:`Recorder`, or None when observability is off."""
    return _active


def enable(path=None, reset_watchdog=True):
    """Start a fresh observability run.

    ``path`` opens a JSONL sink (None = in-memory only — the test/default
    programmatic mode). Resets the retracing watchdog so compile counts are
    scoped to this run (compiled-cache entries from before the run never
    count against a budget declared inside it).
    """
    global _active
    with _lock:
        disable()
        _active = Recorder(path)
        if reset_watchdog:
            from .watchdog import watchdog

            watchdog.reset()
    return _active


def disable():
    """Close the current run (flushes the sink). Safe to call when off.

    With ``SQ_OBS_TRACE=<path>`` set and the run sinking to a JSONL file,
    the closed run is additionally rendered into Chrome trace-event JSON
    at that path (:mod:`sq_learn_tpu.obs.trace`) — best-effort: a failed
    render never masks the run that produced the records.
    """
    global _active
    with _lock:
        rec = _active
        _active = None
        if rec is not None:
            rec.close()
    trace_path = _knobs.get_raw("SQ_OBS_TRACE")
    if rec is not None and rec.path and trace_path:
        try:
            from .trace import write_trace

            write_trace([rec.path], trace_path)
        except Exception:
            pass
    return rec


def flush(fsync=True):
    """Durably flush the active run's JSONL sink (see
    :meth:`Recorder.flush`). No-op (False) when disabled or in-memory."""
    rec = _active
    if rec is None:
        return False
    return rec.flush(fsync=fsync)


def set_fleet(run_id=None, host=None):
    """Adopt (or override) the active recorder's fleet identity.

    The elastic plane threads the coordinator-minted run_id two ways:
    spawned workers inherit ``SQ_OBS_FLEET_RUN_ID`` via env (picked up
    at :class:`Recorder` creation), and mesh members that joined through
    ``distributed.initialize(..., elastic=True)`` adopt it from the KV
    service through this call — late adoption stamps every *subsequent*
    record. Returns the recorder, or None when disabled.
    """
    rec = _active
    if rec is None:
        return None
    with _lock:
        if run_id:
            rec.fleet_run_id = str(run_id)
        if host:
            rec.fleet_host = str(host)
        if rec.fleet_run_id is not None and rec.fleet_host is None:
            rec.fleet_host = f"pid{os.getpid()}"
    return rec


def set_generation(generation):
    """Stamp the live elastic generation into the active recorder's
    fleet envelope (workers call this at every world join, the local
    sim at every shrink). None clears it; no-op when disabled."""
    rec = _active
    if rec is None:
        return None
    with _lock:
        rec.fleet_generation = (None if generation is None
                                else int(generation))
    return rec


def span(name, sync=None, **attrs):
    """Open a named timed scope. Disabled mode returns a shared no-op
    context manager (one global read, zero allocation)."""
    rec = _active
    if rec is None:
        return NULL_SPAN
    return Span(rec, name, sync, attrs)


def record_span(name, dur_s, **attrs):
    """Record an externally-timed span (e.g. :class:`utils.profiling.Timer`
    scopes, which own their device sync)."""
    rec = _active
    if rec is None:
        return
    rec.record({"type": "span", "name": name, "seq": rec._next_seq(),
                "dur_s": round(float(dur_s), 6), "depth": 0, "parent": None,
                "synced": True, "attrs": _jsonable(attrs) if attrs else {}},
               kind="spans")


def counter_add(name, delta):
    """Add ``delta`` to a cumulative counter (e.g. transfer bytes)."""
    rec = _active
    if rec is None:
        return
    with _lock:
        val = rec.counters.get(name, 0) + delta
        rec.counters[name] = val
    rec.record({"type": "counter", "name": name, "value": val,
                "delta": delta})


def gauge(name, value, **attrs):
    """Set a point-in-time gauge (e.g. probe latency, MFU)."""
    rec = _active
    if rec is None:
        return
    with _lock:
        rec.gauges[name] = value
    out = {"type": "gauge", "name": name, "value": _jsonable(value)}
    if attrs:
        out["attrs"] = _jsonable(attrs)
    rec.record(out, kind="gauge_events")


def snapshot():
    """One-dict summary for bench records: compile/transfer/probe totals.

    Returns None when disabled — callers embed the dict only when a run is
    active, so headline JSON lines keep their pre-obs schema otherwise.
    """
    rec = _active
    if rec is None:
        return None
    from .watchdog import watchdog

    report = watchdog.report()
    compile_count = sum(s["compiles"] for s in report.values())
    probe_ms = None
    if rec.probe_events:
        probe_ms = round(rec.probe_events[-1].get("latency_s", 0.0) * 1e3, 3)
    try:
        from ..resilience.supervisor import breaker

        breaker_state, breaker_trips = breaker.state(), breaker.trips
    except Exception:  # obs must never die on a half-imported package
        breaker_state, breaker_trips = "closed", 0
    peak_hbm = None
    for r in rec.xla_cost_records:
        pb = r.get("peak_bytes")
        if isinstance(pb, (int, float)) and (peak_hbm is None
                                             or pb > peak_hbm):
            peak_hbm = pb
    mfu_gauge = rec.gauges.get("profiling.mfu")
    # statistical-observability view (obs.guarantees / obs.frontier):
    # did the run's simulated routines honor their declared (ε, δ)
    # contracts, and did any sweep state the accuracy-vs-runtime trade-off
    try:
        from .guarantees import audit

        audit_flagged = sorted(
            site for site, a in audit(rec.guarantee_records).items()
            if a["flagged"])
    except Exception:  # obs must never die on a half-imported package
        audit_flagged = []
    return {
        "compile_count": int(compile_count),
        "total_transfer_bytes": int(
            rec.counters.get("streaming.transfer_bytes", 0)),
        "probe_ms": probe_ms,
        "spans": len(rec.spans),
        "ledger_entries": len(rec.ledger_entries),
        "watchdog_over_budget": sorted(
            site for site, s in report.items() if s["over_budget"]),
        "faults_injected": len(rec.fault_events),
        "breaker_state": breaker_state,
        "breaker_trips": int(breaker_trips),
        # the classical-cost view (obs.xla): peak HBM of the run's most
        # memory-hungry compiled kernel, and the run's measured MFU gauge
        # (None until something priced one) — the regression gate bands
        # both alongside latency/compiles/transfer
        "peak_hbm_bytes": (int(peak_hbm) if peak_hbm is not None else None),
        "xla_cost_records": len(rec.xla_cost_records),
        "measured_mfu": (round(float(mfu_gauge), 6)
                         if isinstance(mfu_gauge, (int, float)) else None),
        # (ε, δ)-contract audit (obs.guarantees): draws observed, draws
        # whose realized error exceeded the declared tolerance, and the
        # sites whose Clopper–Pearson lower bound exceeds their declared
        # failure probability (empty = every contract held)
        "guarantee_records": len(rec.guarantee_records),
        "guarantee_violations": sum(
            1 for g in rec.guarantee_records if g.get("violated")),
        "audit_flagged": audit_flagged,
        "tradeoff_records": len(rec.tradeoff_records),
        # spectral-stats engine (sq_learn_tpu.sketch): digest-cache
        # traffic + sketched-estimate count — the per-dataset-not-
        # per-sweep-point reuse the frontier benches rely on
        "stats_cache_hits": int(rec.counters.get("stats_cache.hits", 0)),
        "stats_cache_misses": int(
            rec.counters.get("stats_cache.misses", 0)),
        "sketch_estimates": int(rec.counters.get("sketch.estimates", 0)),
        # out-of-core prefetch (oocore.prefetch): readahead hit/stall
        # traffic — a store-backed bench line's evidence that the shard
        # reads overlapped compute instead of serializing on it
        "prefetch_hits": int(rec.counters.get("oocore.prefetch_hits", 0)),
        "prefetch_stalls": int(
            rec.counters.get("oocore.prefetch_stalls", 0)),
        "prefetch_stall_s": round(float(
            rec.counters.get("oocore.prefetch_stall_s", 0.0)), 6),
        # shard codec (oocore.store, PR 13): stored (compressed) bytes
        # read vs raw bytes decoded — a compressed-store bench line's
        # bytes-on-disk evidence rides this pair
        "codec_bytes_in": int(
            rec.counters.get("oocore.codec_bytes_in", 0)),
        "codec_bytes_out": int(
            rec.counters.get("oocore.codec_bytes_out", 0)),
        # serving layer (sq_learn_tpu.serving): SLO summaries emitted,
        # batches that degraded to the host route, and transform-cache
        # traffic — the bench lines' evidence that a load run's numbers
        # came from the micro-batched device path, not the fallback
        "slo_records": len(rec.slo_records),
        "serving_degraded": int(
            rec.counters.get("serving.degraded_batches", 0)),
        "serve_cache_hits": int(rec.counters.get("serving.cache_hits", 0)),
        "serve_cache_misses": int(
            rec.counters.get("serving.cache_misses", 0)),
        # feature-cache disk tier (serving.cache, PR 13): RAM-LRU
        # evictions spilled to the SQ_SERVE_CACHE_DIR store and the
        # digest-verified hits served back off disk
        "serve_cache_spills": int(
            rec.counters.get("serving.cache_spills", 0)),
        "serve_cache_disk_hits": int(
            rec.counters.get("serving.cache_disk_hits", 0)),
        # AOT-warmed serving (serving.aot, PR 11): executables minted at
        # warm time, dispatch-time executable-cache traffic, persistent
        # compile-cache reloads, and the bytes serving moved host→device
        # (its own counter — streaming.transfer_bytes stays the streamed
        # ingest tally the historical bands were cut against)
        "aot_compiles": int(rec.counters.get("serving.aot_compiles", 0)),
        "aot_cache_hits": int(
            rec.counters.get("serving.aot_cache_hits", 0)),
        "aot_cache_misses": int(
            rec.counters.get("serving.aot_cache_misses", 0)),
        "persistent_cache_hits": int(
            rec.counters.get("serving.persistent_cache_hits", 0)),
        "serving_transfer_bytes": int(
            rec.counters.get("serving.transfer_bytes", 0)),
        # per-tenant error-budget ledger (obs.budget, PR 12): budget
        # evaluations recorded, multi-window burn alerts fired, and the
        # tenants whose budgets tripped — a bench line's evidence that a
        # load run's tenants stayed inside their declared budgets
        "budget_records": len(rec.budget_records),
        "budget_alerts": len(rec.alert_records),
        "budget_alerting_tenants": sorted(
            {str(a.get("tenant")) for a in rec.alert_records}),
        # serving control plane (serving.control, PR 17): autotuner
        # evaluations recorded and the subset that changed a tenant's
        # route/coalescing/targets — the bench lines' evidence that a
        # zero-alert run got there by decisions, not by luck
        "control_records": len(rec.control_records),
        "control_actions": sum(
            1 for c in rec.control_records
            if c.get("action") not in (None, "plan", "hold")),
        # elastic mesh (parallel.elastic, PR 18): transitions recorded,
        # host failures declared, and the highest generation reached —
        # a kill-mid-fit bench line's evidence that its wall-clock
        # includes a real detect → shrink → resume cycle
        "elastic_records": len(rec.elastic_records),
        "elastic_host_failures": sum(
            1 for e in rec.elastic_records
            if e.get("event") == "host_fail"),
        "elastic_generation": max(
            (int(e["generation"]) for e in rec.elastic_records
             if isinstance(e.get("generation"), int)), default=None),
        # storage-plane ledger (obs.storage, v11): io aggregates flushed
        # so far plus the per-surface resident-traffic-vs-budget gauges
        # (ledger rollups joined with the configured caps/budgets)
        "io_records": len(rec.io_records),
        "storage_surfaces": _storage_surfaces(rec),
    }


def _storage_surfaces(rec):
    try:
        from .storage import surfaces_snapshot

        return surfaces_snapshot(rec)
    except Exception:  # obs must never die on a half-imported package
        return None


# SQ_OBS=1 auto-enables at first import, sink at SQ_OBS_PATH (CLAUDE.md
# env knobs). Programmatic enable()/disable() always works regardless.
# The atexit disable flushes the sink and — with SQ_OBS_TRACE set —
# renders the Chrome trace for runs that never call disable() themselves
# (bench scripts, one-shot CLIs).
def _default_path():
    """Sink path for the auto-enabled run: SQ_OBS_PATH wins; with a
    fleet directory set instead, this process's shard lands there as
    ``obs.<host>.jsonl`` (the obs.fleet merge-by-glob layout)."""
    path = _knobs.get_raw("SQ_OBS_PATH")
    if path:
        return path
    fleet_dir = _knobs.get_str("SQ_OBS_FLEET_DIR", "")
    if fleet_dir:
        host = (_knobs.get_str("SQ_OBS_FLEET_HOST", "")
                or f"pid{os.getpid()}")
        try:
            os.makedirs(fleet_dir, exist_ok=True)
            return os.path.join(fleet_dir, f"obs.{host}.jsonl")
        except OSError:
            pass  # unwritable fleet dir degrades to the CWD default
    return DEFAULT_PATH


if _knobs.get_bool("SQ_OBS"):
    enable(_default_path())
    import atexit

    atexit.register(disable)
