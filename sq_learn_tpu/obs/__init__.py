"""Run-scoped observability: spans, counters/gauges, the quantum-runtime
ledger, the retracing watchdog, and the device-health probe.

Quickstart::

    from sq_learn_tpu import obs

    obs.enable("/tmp/run.jsonl")          # or export SQ_OBS=1
    with obs.span("my.step", n=1000):
        ...
    obs.ledger.record("qpca", "tomography",
                      queries={"tomography_shots": 1.2e7},
                      budget={"delta": 0.1}, wall_s=0.8)
    print(obs.ledger.totals())
    print(obs.watchdog.report())
    obs.disable()                          # flush the sink

Env knobs: ``SQ_OBS=1`` auto-enables with a JSONL sink at ``SQ_OBS_PATH``
(default ``sq_obs.jsonl``); ``SQ_OBS_STRICT=1`` makes watchdog budget
violations raise instead of warn; ``SQ_OBS_AUDIT_STRICT=1`` makes
guarantee-audit flags raise (:mod:`~sq_learn_tpu.obs.guarantees`);
``SQ_OBS_BUDGET_STRICT=1`` makes tripped multi-window error-budget
burn alerts raise (:mod:`~sq_learn_tpu.obs.budget`, with
``SQ_OBS_BUDGET_WINDOWS``/``SQ_OBS_BUDGET_BURN`` tuning);
``SQ_OBS_TRACE=<path>`` renders the closing run's JSONL into Chrome
trace-event JSON; ``SQ_OBS_ROTATE_BYTES`` rotates the sink to gzipped
segments mid-run; ``SQ_OBS_FLEET_RUN_ID`` / ``SQ_OBS_FLEET_HOST`` /
``SQ_OBS_FLEET_DIR`` stamp the fleet envelope and shard layout for
multi-process runs (:mod:`~sq_learn_tpu.obs.fleet`). Analysis tooling:
``python -m sq_learn_tpu.obs
{trace,report,regress,audit,frontier,budget,control,fleet,storage}``
and :mod:`~sq_learn_tpu.obs.xla` (per-compilation FLOP/byte/peak-HBM
accounting). Full docs: ``docs/observability.md``.
"""

from . import (budget, control, fleet, frontier, guarantees, ledger, probe,
               regress, report, schema, storage, trace, xla)
from .recorder import (NULL_SPAN, Recorder, counter_add, disable, enable,
                       enabled, flush, gauge, get_recorder, record_span,
                       set_fleet, set_generation, snapshot, span)
from .watchdog import (RetracingError, RetracingWarning, RetracingWatchdog,
                       watchdog)

#: convenience alias: obs.ledger_record(...) == obs.ledger.record(...)
ledger_record = ledger.record

__all__ = [
    "NULL_SPAN",
    "Recorder",
    "RetracingError",
    "RetracingWarning",
    "RetracingWatchdog",
    "budget",
    "control",
    "counter_add",
    "disable",
    "enable",
    "enabled",
    "fleet",
    "flush",
    "frontier",
    "gauge",
    "get_recorder",
    "guarantees",
    "ledger",
    "ledger_record",
    "probe",
    "record_span",
    "regress",
    "report",
    "schema",
    "set_fleet",
    "set_generation",
    "snapshot",
    "span",
    "storage",
    "trace",
    "watchdog",
    "xla",
]
