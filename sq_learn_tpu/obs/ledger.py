"""Quantum-runtime ledger: theoretical query counts next to measured time.

The paper's claim is a trade-off — accuracy against *theoretical* quantum
runtime, with ε/δ as runtime parameters — but until now the two sides lived
apart: theoretical accountants on the estimators
(``QPCA.accumulate_q_runtime``, ``QKMeans.quantum_runtime_model``) and
wall-clock in ad-hoc timers. The ledger joins them per run: every quantum
step records (a) its theoretical quantum query/sample counts (tomography
shots, phase-estimation spectrum queries, amplitude-estimation calls, cost
model evaluations), (b) the ε/δ error budgets that priced those counts,
and (c) the measured wall-clock of the classical simulation of the same
step. One run's entries are one artifact stating the paper's trade-off.

Accounting conventions (the exact formulas tests pin):

- **Tomography shots** (:func:`tomography_shot_count`): Algorithm 4.1
  measures a d-dimensional state N = 36·d·ln d/δ² times for magnitudes
  (part 1) and N more times on the 2d-register interference state for
  signs (part 2), so one vector estimate costs 2·N shots and a matrix of
  r rows costs 2·N·r. The ``'inf'`` norm drops the factor d from N. The
  Gaussian fast path (``true_tomography=False``) simulates the same
  estimator at the same δ, so its *theoretical* shot count is identical.
- **Zero error budget records zero queries**: δ=0/ε=0 short-circuits to
  the exact classical computation (framework-wide contract), and the
  ledger entry says so — 0 shots, 0 queries, ``short_circuit: true``.
- **Phase estimation**: one consistent-PE pass estimates the whole
  spectrum, so a pass over s singular values counts s spectrum queries;
  a fused binary search of n iterations counts n·s (an upper bound for
  early-exiting searches, flagged ``upper_bound``).

Classical estimators (TruncatedSVD, KNN) feed the ledger too — with empty
query dicts — so the artifact carries the classical wall-clock baseline the
quantum counts are traded against.
"""

import time


def tomography_shot_count(n_vectors, d, delta, norm="L2"):
    """Theoretical measurement count of tomography on ``n_vectors`` states
    of dimension ``d`` at error ``delta``: 2·N·n_vectors with N from
    :func:`~sq_learn_tpu.ops.quantum.tomography.tomography_n_measurements`
    (reference ``Utility.py:307-311``). δ=0 is the exact classical
    short-circuit — zero quantum measurements."""
    if float(delta) == 0.0 or n_vectors <= 0:
        return 0
    from ..ops.quantum.tomography import tomography_n_measurements

    return 2 * tomography_n_measurements(int(d), float(delta), norm) \
        * int(n_vectors)


def phase_estimation_queries(n_values, n_iterations=1):
    """Consistent-PE spectrum queries: ``n_values`` per pass over the
    spectrum, ``n_iterations`` passes (1 for a single batched estimate)."""
    return int(n_values) * int(n_iterations)


def record(estimator, step, wall_s=None, queries=None, budget=None, **attrs):
    """Append one ledger entry (and its JSONL line) to the active run.

    ``queries``: dict of theoretical quantum query counts (numeric).
    ``budget``: dict of the error budgets that priced them (ε, δ, η...).
    No-op when observability is disabled.
    """
    from . import recorder

    rec = recorder.get_recorder()
    if rec is None:
        return
    entry = {"type": "ledger", "estimator": estimator, "step": step,
             "queries": {k: float(v) for k, v in (queries or {}).items()},
             "budget": {k: float(v) for k, v in (budget or {}).items()}}
    if wall_s is not None:
        entry["wall_s"] = round(float(wall_s), 6)
    if attrs:
        entry["attrs"] = recorder._jsonable(attrs)
    rec.record(entry, kind="ledger_entries")


def entries():
    """The active run's ledger entries (empty when disabled)."""
    from . import recorder

    rec = recorder.get_recorder()
    return list(rec.ledger_entries) if rec is not None else []


def totals():
    """Aggregate query counts (summed per key) and wall-clock across the
    run's entries — the one-dict statement of the run's trade-off."""
    agg = {}
    wall = 0.0
    for e in entries():
        for k, v in e["queries"].items():
            agg[k] = agg.get(k, 0.0) + v
        wall += e.get("wall_s", 0.0)
    return {"queries": agg, "wall_s": round(wall, 6)}


class timed_step:
    """Context manager pairing a ledger entry with the measured wall-clock
    of its scope::

        with obs.ledger.timed_step("qpca", "topk_extract",
                                   queries={...}, budget={...}):
            <classical simulation of the quantum step>

    Queries/budget may also be filled in mid-scope via ``.set_queries`` /
    ``.set_budget`` (counts often depend on data-dependent selection).
    Records nothing when observability is disabled.
    """

    def __init__(self, estimator, step, queries=None, budget=None, **attrs):
        self.estimator = estimator
        self.step = step
        self.queries = dict(queries or {})
        self.budget = dict(budget or {})
        self.attrs = attrs

    def set_queries(self, **queries):
        self.queries.update(queries)
        return self

    def set_budget(self, **budget):
        self.budget.update(budget)
        return self

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            record(self.estimator, self.step,
                   wall_s=time.perf_counter() - self._t0,
                   queries=self.queries, budget=self.budget, **self.attrs)
        return False
