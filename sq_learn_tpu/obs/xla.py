"""XLA cost/memory accounting: what a compiled kernel *costs*, per run.

PR 2's ledger put the quantum side of the paper's trade-off (tomography
shots, PE/AE queries) next to measured wall-clock; this module supplies
the classical side. For every instrumented jit entry point — the
streaming bucket kernels, the ``parallel/`` pca/lloyd/neighbors
shard_maps, the estimator fit/predict jits, ``__graft_entry__`` — each
distinct compilation (site × abstract signature) records one
``xla_cost`` JSONL line with:

- ``lowered.cost_analysis()``: XLA's static FLOP count and bytes-accessed
  estimate for the lowering, and
- ``compiled.memory_analysis()``: argument/output/temp/generated-code
  buffer sizes, summed into ``peak_bytes`` — the peak-HBM claim of the
  executable (newer jaxlibs expose ``peak_memory_in_bytes`` directly;
  older ones get the component sum).

The record is keyed by the retracing watchdog's site name, so a run
artifact lines up "how many times did this site compile" (watchdog)
with "what does one of those compilations cost" (here), and
:func:`~sq_learn_tpu.utils.profiling.mfu` can price utilization from
the *measured* cost instead of hand formulas (``mfu(..., site=...)``).

Costs, not free:

- **Disabled mode is one module-global read** — :func:`capture` and the
  :func:`instrument` wrapper return immediately when no recorder is
  active; nothing hashes, nothing traces.
- **Enabled mode re-lowers once per (site, signature).** jax's AOT API
  has no public hook into the jit cache, so the analysis pass lowers
  (and, for memory analysis, compiles) the kernel a second time. That
  doubles compile cost for analyzed signatures *under observability
  only*; ``SQ_OBS_XLA_MEMORY=0`` skips the compile half (``peak_bytes``
  degrades to null) when even that is too much.
- **Graceful degradation**: a jax without ``Lowered.cost_analysis`` /
  ``Compiled.memory_analysis`` (or a backend that refuses them) records
  what it can, nulls for the rest, and never raises into the
  instrumented computation.
"""


from . import recorder
from .. import _knobs

__all__ = ["capture", "instrument", "flops_of", "peak_bytes", "records"]


def _leaf_signature(leaf):
    """One leaf's contribution to the abstract signature: arrays as
    dtype[shape], everything else by value-or-type (static kwargs like
    mode strings change the compiled program, so they key the record)."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return f"{dtype}[{','.join(str(d) for d in shape)}]"
    if isinstance(leaf, (str, int, float, bool)) or leaf is None:
        return repr(leaf)
    return type(leaf).__name__


def signature_of(args, kwargs):
    """Compact abstract-signature string of a call — the dedup key (and
    the ``signature`` field of the record)."""
    import jax

    parts = [_leaf_signature(l) for l in jax.tree_util.tree_leaves(args)]
    for k in sorted(kwargs):
        sub = ",".join(_leaf_signature(l)
                       for l in jax.tree_util.tree_leaves(kwargs[k]))
        parts.append(f"{k}={sub}")
    return "(" + ", ".join(parts) + ")"


def _cost_dict(lowered):
    """Normalized ``{flops, bytes_accessed}`` from ``cost_analysis()``,
    which jax has returned as a dict, a list of per-device dicts, and
    (future) nothing at all."""
    try:
        ca = lowered.cost_analysis()
    except Exception:
        return {"flops": None, "bytes_accessed": None}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {"flops": None, "bytes_accessed": None}

    def num(key):
        v = ca.get(key)
        return float(v) if isinstance(v, (int, float)) else None

    return {"flops": num("flops"), "bytes_accessed": num("bytes accessed")}


def _memory_dict(compiled):
    """Normalized buffer sizes from a ``Compiled``'s
    ``memory_analysis()``. ``peak_bytes`` prefers the executable's own
    peak stat and falls back to argument+output+temp+generated-code (the
    live set at launch)."""
    out = {"peak_bytes": None, "argument_bytes": None, "output_bytes": None,
           "temp_bytes": None, "generated_code_bytes": None}
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return out
    if ma is None:
        return out

    def num(attr):
        v = getattr(ma, attr, None)
        return int(v) if isinstance(v, (int, float)) else None

    out["argument_bytes"] = num("argument_size_in_bytes")
    out["output_bytes"] = num("output_size_in_bytes")
    out["temp_bytes"] = num("temp_size_in_bytes")
    out["generated_code_bytes"] = num("generated_code_size_in_bytes")
    peak = num("peak_memory_in_bytes")
    if peak is None:
        parts = [out["argument_bytes"], out["output_bytes"],
                 out["temp_bytes"], out["generated_code_bytes"]]
        known = [p for p in parts if p is not None]
        peak = sum(known) if known else None
    out["peak_bytes"] = peak
    return out


def capture(site, fn, *args, _extra_key=None, **kwargs):
    """Record one ``xla_cost`` line for ``fn`` at this call's signature,
    once per (site, signature) per run. No-op (one global read) when
    observability is off; never raises into the caller.

    ``fn`` must be a jitted callable (exposes ``.lower``); call with the
    exact args/kwargs of the real invocation so statics resolve the same
    program the run executes. ``_extra_key`` folds closure state the
    args can't see (e.g. a shard_map'd kernel's static config tuple)
    into the signature, so two programs sharing arg shapes don't dedup
    into one record.
    """
    rec = recorder._active
    if rec is None:
        return None
    try:
        sig = signature_of(args, kwargs)
        if _extra_key is not None:
            sig += f"|{_extra_key}"
    except Exception:
        return None
    key = (site, sig)
    with recorder._lock:
        if key in rec._xla_seen:
            return None
        rec._xla_seen.add(key)
    entry = {"type": "xla_cost", "site": site, "signature": sig,
             "flops": None, "bytes_accessed": None, "peak_bytes": None}
    try:
        lowered = fn.lower(*args, **kwargs)
    except Exception as exc:
        entry["error"] = type(exc).__name__
        rec.record(entry, kind="xla_cost_records")
        return entry
    entry.update(_cost_dict(lowered))
    if _knobs.get_bool("SQ_OBS_XLA_MEMORY"):
        try:
            entry.update(_memory_dict(lowered.compile()))
        except Exception:
            pass
    try:
        import jax

        entry["backend"] = jax.default_backend()
    except Exception:
        pass
    rec.record(entry, kind="xla_cost_records")
    return entry


def capture_compiled(site, lowered, compiled, *args, **kwargs):
    """Record one ``xla_cost`` line from an ALREADY-lowered-and-compiled
    kernel — the AOT warm path (:mod:`sq_learn_tpu.serving.aot`), where
    the lowering exists anyway and re-lowering for analysis (what
    :func:`capture` must do against a jit cache it cannot reach into)
    would double the warm cost. Same dedup key, record shape, and
    never-raises contract as :func:`capture`; ``args``/``kwargs`` are
    the abstract call signature (``ShapeDtypeStruct``s sign identically
    to the concrete arrays they stand for)."""
    rec = recorder._active
    if rec is None:
        return None
    try:
        sig = signature_of(args, kwargs)
    except Exception:
        return None
    key = (site, sig)
    with recorder._lock:
        if key in rec._xla_seen:
            return None
        rec._xla_seen.add(key)
    entry = {"type": "xla_cost", "site": site, "signature": sig,
             "flops": None, "bytes_accessed": None, "peak_bytes": None}
    entry.update(_cost_dict(lowered))
    if _knobs.get_bool("SQ_OBS_XLA_MEMORY"):
        entry.update(_memory_dict(compiled))
    try:
        import jax

        entry["backend"] = jax.default_backend()
    except Exception:
        pass
    rec.record(entry, kind="xla_cost_records")
    return entry


def instrument(site, fn):
    """Wrap a jitted callable so every call first feeds :func:`capture`
    (new signatures under an active run record their cost), then runs.

    The wrapper forwards the jit's ``_cache_size`` hook so the retracing
    watchdog and ``streaming.kernel_cache_sizes`` keep reading compile
    counts through it, and keeps the raw jit at ``__wrapped__``.
    """
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if recorder._active is not None:
            capture(site, fn, *args, **kwargs)
        return fn(*args, **kwargs)

    cache_size = getattr(fn, "_cache_size", None)
    if cache_size is not None:
        wrapped._cache_size = cache_size
    wrapped._xla_site = site
    wrapped.lower = fn.lower
    return wrapped


def records():
    """The active run's ``xla_cost`` records (empty list when off)."""
    rec = recorder.get_recorder()
    return list(rec.xla_cost_records) if rec is not None else []


def flops_of(site):
    """Largest measured FLOP count recorded for ``site`` this run (the
    dominant signature), or None — the hook
    :func:`~sq_learn_tpu.utils.profiling.mfu` uses to price utilization
    from measured cost instead of hand formulas."""
    vals = [r["flops"] for r in records()
            if r.get("site") == site and isinstance(r.get("flops"),
                                                    (int, float))]
    return max(vals) if vals else None


def peak_bytes():
    """Largest ``peak_bytes`` across the run's records, or None — the
    peak-HBM figure :func:`~sq_learn_tpu.obs.recorder.snapshot` embeds
    in bench lines (and the regression gate bands)."""
    vals = [r["peak_bytes"] for r in records()
            if isinstance(r.get("peak_bytes"), (int, float))]
    return max(vals) if vals else None
