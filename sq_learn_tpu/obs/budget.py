"""Per-tenant error-budget ledger: latency-SLO and (ε, δ) burn rates.

The paper's thesis makes ε and δ *runtime* parameters (SURVEY §0), and
ROADMAP item 1 wants a controller that picks the cheapest (ε, δ) per
tenant — but a controller can only spend a budget the system *observes*
being burned. This module is the observation half: an SRE-style
error-budget ledger that tracks, per tenant and per rolling window, how
fast two budgets burn:

- **Latency-SLO burn.** A tenant's declared p50/p99 targets define an
  error budget: a p99 target *allows* 1 % of requests over it (a p50
  target allows 50 %). ``slo_burn`` is the observed fraction of
  window requests over the p99 target (the p50 target when only p50 is
  declared), and the latency **burn rate** is the observed violating
  fraction divided by the allowed fraction — burn rate 1.0 means the
  budget burns exactly as fast as it refills; 100 means every request
  violates a p99 target.
- **Statistical burn.** Guarantee draws (:mod:`~sq_learn_tpu.obs.
  guarantees`) attributed to the tenant — the live ``serving.quant.*``
  fold audits, and any model-site draw carrying a tenant attr — burn
  the declared δ/γ budget. ``stat_burn`` is the violated-draw fraction;
  the statistical burn rate is the **Clopper–Pearson lower confidence
  bound** on the failure rate divided by the declared failure
  probability, so a single unlucky draw never alarms (the auditor's
  rule): the data must be statistically inconsistent with the contract
  before the rate crosses 1.

**Multi-window alerting** (the SRE burn-rate pattern): each tenant is
evaluated over every configured window (``SQ_OBS_BUDGET_WINDOWS``,
default ``60,600`` seconds — short catches a fast burn, long filters
blips). An ``alert`` record fires only when a kind's burn rate meets the
threshold (``SQ_OBS_BUDGET_BURN``, default 2.0) in **every** window —
and ``SQ_OBS_BUDGET_STRICT=1`` escalates the alert to a raised
:class:`BudgetBurnError`, the same strict-mode pattern as the watchdog
(``SQ_OBS_STRICT``) and the guarantee audit (``SQ_OBS_AUDIT_STRICT``).

Every evaluation lands as ``budget`` JSONL records (one per
tenant × window: ``slo_burn``, ``stat_burn``, ``cp_lower_bound``,
``burn_rate``, ``alerting``, window p50/p99) plus ``alert`` records for
tripped tenants — the dispatcher emits them on its periodic SLO flush
(``SQ_SERVE_SLO_FLUSH_BATCHES``) and at close, so a long-running server
telemeters burn continuously and a crashed process keeps its history.
Since schema v8 each emitted line also carries a ledger-scoped
monotonic ``seq``, so trace-export merge order stays deterministic when
two emissions land on the same wall-clock millisecond.

Import-safe without jax and numpy (stdlib only), like
:mod:`~sq_learn_tpu.obs.guarantees`: the collect/render/CLI half runs
with PYTHONPATH cleared while the accelerator relay is wedged. Zero
overhead when observability is off — the serving plane only constructs
a ledger under an active recorder (pinned by test).
"""

import collections
import math
import threading
import time

from .guarantees import clopper_pearson_lower
from .. import _knobs

__all__ = [
    "BudgetBurnError",
    "BudgetLedger",
    "DEFAULT_BURN_THRESHOLD",
    "DEFAULT_WINDOWS",
    "burn_threshold",
    "collect",
    "main",
    "render",
    "strict",
    "windows",
]

#: default rolling windows in seconds (short, long): the multi-window
#: burn-rate pattern — short catches a fast burn, long filters blips
DEFAULT_WINDOWS = (60.0, 600.0)

#: default burn-rate threshold: budget burning at >= 2x its refill rate
#: in EVERY window trips the alert (2.0 is also the maximum possible
#: rate of a p50 target, so a p50-only tenant alerts exactly when every
#: request violates)
DEFAULT_BURN_THRESHOLD = 2.0

#: burn-rate ceiling recorded in place of an unbounded ratio (a declared
#: fail_prob of 0 with observed violations burns "infinitely fast";
#: JSONL must stay portable, so the record carries this sentinel cap)
MAX_BURN_RATE = 1e6

#: allowed violating fraction per declared percentile target: the error
#: budget a pXX latency target grants by definition
ALLOWED_FRACTION = {"p50": 0.50, "p99": 0.01}


class BudgetBurnError(RuntimeError):
    """A tenant's error budget is burning at or past the threshold in
    every configured window (raised under ``SQ_OBS_BUDGET_STRICT=1``);
    the message carries the per-window burn rates."""


def windows():
    """The configured rolling windows in seconds
    (``SQ_OBS_BUDGET_WINDOWS``, comma-separated, default ``60,600``)."""
    raw = _knobs.get_raw("SQ_OBS_BUDGET_WINDOWS")
    if not raw:
        return DEFAULT_WINDOWS
    out = tuple(sorted(float(w) for w in raw.split(",") if w.strip()))
    return out or DEFAULT_WINDOWS


def burn_threshold():
    """The multi-window alert threshold (``SQ_OBS_BUDGET_BURN``,
    default 2.0): the burn rate that must hold in EVERY window."""
    return _knobs.get_float("SQ_OBS_BUDGET_BURN")


def strict():
    """True when a tripped alert must raise
    (``SQ_OBS_BUDGET_STRICT=1``)."""
    return _knobs.get_bool("SQ_OBS_BUDGET_STRICT")


def _percentile(values, q):
    """Nearest-rank percentile of a non-empty sequence (the SLO read:
    an actually-observed value, never an interpolation)."""
    ordered = sorted(values)
    rank = min(len(ordered), max(1, math.ceil(len(ordered) * q)))
    return ordered[rank - 1]


class _TenantState:
    """One tenant's rolling event history + run-scoped totals."""

    __slots__ = ("requests", "draws", "p50_ms", "p99_ms", "fail_prob",
                 "total_requests", "total_draws")

    def __init__(self):
        #: (ts, latency_ms) — pruned past the longest window
        self.requests = collections.deque()
        #: (ts, violated) — pruned past the longest window
        self.draws = collections.deque()
        self.p50_ms = None
        self.p99_ms = None
        #: LARGEST declared failure probability seen (auditing against
        #: the loosest declaration is conservative — guarantees.audit)
        self.fail_prob = None
        self.total_requests = 0
        self.total_draws = 0


class BudgetLedger:
    """Per-tenant rolling error-budget scoreboard.

    The serving dispatcher owns one (created only under an active
    recorder — the disabled path never allocates), feeds it request
    latencies and guarantee draws attributed to tenants, and calls
    :meth:`emit` on its periodic SLO flush and at close. All ``note_*``
    inputs are host-clock monotonic seconds (``time.perf_counter``
    epoch) so window arithmetic is immune to wall-clock steps; tests
    pass explicit ``ts``/``now`` for determinism.
    """

    #: lock-discipline contract (``sq_learn_tpu.analysis``): tenant state
    #: and the emit counter are only written under ``self._lock``;
    #: ``_state``/``_prune`` are helpers invoked with the lock already
    #: held.
    _GUARDED_BY = {"_lock": ("_tenants", "_emit_seq")}
    _ASSUMES_LOCK = ("_state", "_prune")

    def __init__(self, window_seconds=None, threshold=None,
                 site="serving.dispatcher"):
        self.windows = (windows() if window_seconds is None
                        else tuple(sorted(float(w)
                                          for w in window_seconds)))
        if not self.windows or min(self.windows) <= 0:
            raise ValueError(f"windows must be positive seconds, "
                             f"got {self.windows}")
        self.threshold = (burn_threshold() if threshold is None
                          else float(threshold))
        self.site = site
        self._lock = threading.Lock()
        self._tenants = {}
        self._emit_seq = 0

    # -- inputs ------------------------------------------------------------

    def _state(self, tenant):
        st = self._tenants.get(tenant)
        if st is None:
            st = self._tenants[tenant] = _TenantState()
        return st

    def _prune(self, st, now):
        horizon = now - self.windows[-1]
        while st.requests and st.requests[0][0] < horizon:
            st.requests.popleft()
        while st.draws and st.draws[0][0] < horizon:
            st.draws.popleft()

    def note_request(self, tenant, latency_s, p50_ms=None, p99_ms=None,
                     ts=None):
        """Record one served request for ``tenant`` with the tenant's
        declared targets (None = that percentile undeclared)."""
        self.note_requests(tenant, (latency_s,), p50_ms=p50_ms,
                           p99_ms=p99_ms, ts=ts)

    def note_requests(self, tenant, latencies_s, p50_ms=None, p99_ms=None,
                      ts=None):
        """Batch form: one lock acquisition per dispatched batch (the
        scatter path runs per batch, not per request)."""
        if ts is None:
            ts = time.perf_counter()
        with self._lock:
            st = self._state(str(tenant))
            if p50_ms is not None:
                st.p50_ms = float(p50_ms)
            if p99_ms is not None:
                st.p99_ms = float(p99_ms)
            for lat in latencies_s:
                st.requests.append((ts, float(lat) * 1e3))
                st.total_requests += 1
            self._prune(st, ts)

    def note_draw(self, tenant, violated, fail_prob=None, ts=None):
        """Record one guarantee draw attributed to ``tenant`` against
        its declared failure probability δ/γ."""
        if ts is None:
            ts = time.perf_counter()
        with self._lock:
            st = self._state(str(tenant))
            st.draws.append((ts, bool(violated)))
            st.total_draws += 1
            if fail_prob is not None:
                fp = float(fail_prob)
                if st.fail_prob is None or fp > st.fail_prob:
                    st.fail_prob = fp
            self._prune(st, ts)

    def tenants(self):
        with self._lock:
            return sorted(self._tenants)

    def total_requests(self, tenant):
        """Run-scoped request count for ``tenant`` (the reconciliation
        number the load bench checks against the aggregate slo record)."""
        with self._lock:
            st = self._tenants.get(str(tenant))
            return st.total_requests if st is not None else 0

    # -- burn math ---------------------------------------------------------

    def window_stats(self, tenant, window_s, now=None):
        """One tenant's burn numbers over the trailing ``window_s``
        seconds — the dict one ``budget`` record serializes.

        ``slo_burn`` = violating-request fraction of the budget-defining
        target (p99 when declared, else p50); the latency burn rate is
        the max over declared targets of fraction/allowed. ``stat_burn``
        = violated-draw fraction; the statistical burn rate is
        cp_lower_bound / declared fail_prob. ``burn_rate`` = the worst
        of the two (None when the tenant declared nothing observable).
        """
        if now is None:
            now = time.perf_counter()
        window_s = float(window_s)
        with self._lock:
            st = self._tenants.get(str(tenant))
            if st is None:
                raise KeyError(f"unknown tenant {tenant!r}")
            horizon = now - window_s
            lats = [lat for ts, lat in st.requests if ts >= horizon]
            draws = [v for ts, v in st.draws if ts >= horizon]
            p50_t, p99_t, fail_prob = st.p50_ms, st.p99_ms, st.fail_prob
        n = len(lats)
        over_p50 = (sum(1 for lat in lats if lat > p50_t)
                    if p50_t is not None else None)
        over_p99 = (sum(1 for lat in lats if lat > p99_t)
                    if p99_t is not None else None)
        slo_burn = None
        slo_rate = None
        if n:
            rates = []
            for key, over in (("p50", over_p50), ("p99", over_p99)):
                if over is None:
                    continue
                frac = over / n
                rates.append(frac / ALLOWED_FRACTION[key])
                # the budget-defining target: p99 when declared (the
                # tightest budget), else p50
                if key == "p99" or slo_burn is None:
                    slo_burn = frac
            if rates:
                slo_rate = max(rates)
        d = len(draws)
        viol = sum(1 for v in draws if v)
        stat_burn = (viol / d) if d else None
        cp = clopper_pearson_lower(viol, d) if d else None
        stat_rate = None
        if cp is not None and fail_prob is not None:
            if fail_prob > 0.0:
                stat_rate = min(cp / fail_prob, MAX_BURN_RATE)
            else:
                stat_rate = MAX_BURN_RATE if cp > 0.0 else 0.0
        candidates = [r for r in (slo_rate, stat_rate) if r is not None]
        burn_rate = max(candidates) if candidates else None
        targets = {}
        if p50_t is not None:
            targets["p50_ms"] = p50_t
        if p99_t is not None:
            targets["p99_ms"] = p99_t
        return {
            "tenant": str(tenant),
            "window_s": window_s,
            "requests": n,
            "over_p50": over_p50,
            "over_p99": over_p99,
            "p50_ms": round(_percentile(lats, 0.50), 4) if lats else None,
            "p99_ms": round(_percentile(lats, 0.99), 4) if lats else None,
            "slo_burn": (round(slo_burn, 6) if slo_burn is not None
                         else None),
            "slo_burn_rate": (round(slo_rate, 6) if slo_rate is not None
                              else None),
            "draws": d,
            "draw_violations": viol,
            "stat_burn": (round(stat_burn, 6) if stat_burn is not None
                          else None),
            "cp_lower_bound": round(cp, 6) if cp is not None else None,
            "stat_burn_rate": (round(stat_rate, 6)
                               if stat_rate is not None else None),
            "burn_rate": (round(burn_rate, 6) if burn_rate is not None
                          else None),
            "fail_prob": fail_prob,
            "targets": targets,
            "alerting": (burn_rate is not None
                         and burn_rate >= self.threshold),
        }

    def summary(self, now=None):
        """``{tenant: {window_s: stats}}`` across every configured
        window (no records emitted — the read-only view)."""
        if now is None:
            now = time.perf_counter()
        return {t: {w: self.window_stats(t, w, now) for w in self.windows}
                for t in self.tenants()}

    def alerts(self, now=None, summary=None):
        """Tripped multi-window alerts: one dict per (tenant, kind)
        whose burn rate meets the threshold in EVERY window."""
        summary = self.summary(now) if summary is None else summary
        out = []
        for tenant in sorted(summary):
            per_window = summary[tenant]
            for kind in ("slo_burn", "stat_burn"):
                rates = {w: s.get(f"{kind}_rate")
                         for w, s in per_window.items()}
                if rates and all(r is not None and r >= self.threshold
                                 for r in rates.values()):
                    out.append({
                        "tenant": tenant,
                        "kind": kind,
                        "threshold": self.threshold,
                        "burn_rates": {f"{w:g}s": r
                                       for w, r in rates.items()},
                    })
        return out

    # -- emission ----------------------------------------------------------

    def _next_emit_seq(self):
        """Ledger-scoped monotonic counter stamped on every emitted
        ``budget``/``alert`` line (schema v8): wall-clock ``ts`` values
        collide at millisecond resolution, so the trace exporter breaks
        ties on this instead of file order."""
        with self._lock:
            seq = self._emit_seq
            self._emit_seq = seq + 1
        return seq

    def emit(self, now=None):
        """Record one ``budget`` line per tenant × window plus ``alert``
        lines for tripped tenants; returns ``(summary, alerts)``. Under
        ``SQ_OBS_BUDGET_STRICT=1`` a tripped alert raises AFTER every
        record lands — the artifact must carry the evidence of the burn
        it reports (the SloTracker rule)."""
        from . import recorder

        summary = self.summary(now)
        alerts = self.alerts(summary=summary)
        rec = recorder.get_recorder()
        if rec is not None:
            for tenant in sorted(summary):
                for w in self.windows:
                    s = summary[tenant][w]
                    entry = {"type": "budget", "site": self.site,
                             "seq": self._next_emit_seq()}
                    entry.update(
                        (k, v) for k, v in s.items()
                        if (v is not None and not (k == "targets"
                                                   and not v))
                        or k in ("slo_burn", "stat_burn",
                                 "cp_lower_bound", "burn_rate"))
                    rec.record(entry, kind="budget_records")
            for a in alerts:
                rec.record(dict(a, type="alert", site=self.site,
                                seq=self._next_emit_seq()),
                           kind="alert_records")
        if alerts and strict():
            worst = alerts[0]
            raise BudgetBurnError(
                f"error budget of tenant {worst['tenant']!r} burning at "
                f">= {self.threshold}x in every window "
                f"({worst['kind']}: {worst['burn_rates']}) "
                f"(SQ_OBS_BUDGET_STRICT=1)")
        return summary, alerts


# ---------------------------------------------------------------------------
# File-tool half (collect / render / CLI) — stdlib only, no jax
# ---------------------------------------------------------------------------


def collect(records):
    """Aggregate decoded records into the budget view: ``{"tenants":
    {tenant: {window_s: last budget record}}, "alerts": [...]}`` —
    cumulative rolling windows, so the LAST record per (tenant, window)
    is the run's final word (the counter convention)."""
    tenants = {}
    alerts = []
    for r in records:
        if not isinstance(r, dict):
            continue
        if r.get("type") == "budget":
            t = r.get("tenant")
            w = r.get("window_s")
            tenants.setdefault(t, {})[w] = r
        elif r.get("type") == "alert":
            alerts.append(r)
    return {"tenants": tenants, "alerts": alerts}


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float) and (abs(v) >= 1e5 or 0 < abs(v) < 1e-3):
        return f"{v:.3e}"
    return f"{v:.4g}" if isinstance(v, float) else str(v)


def render(view):
    """Format a :func:`collect` view as the report's tenant
    error-budget table."""
    lines = []
    out = lines.append
    tenants = view.get("tenants") or {}
    if not tenants:
        return "  (no budget records)"
    for tenant in sorted(tenants, key=str):
        for w in sorted(tenants[tenant], key=lambda x: (x is None, x)):
            r = tenants[tenant][w]
            flag = "  ALERTING" if r.get("alerting") else ""
            out(f"  {str(tenant):<12} {_fmt(w):>6}s  "
                f"req={r.get('requests', 0):<6} "
                f"slo_burn={_fmt(r.get('slo_burn')):>8}  "
                f"stat_burn={_fmt(r.get('stat_burn')):>8}  "
                f"cp_lb={_fmt(r.get('cp_lower_bound')):>8}  "
                f"burn_rate={_fmt(r.get('burn_rate')):>8}{flag}")
    for a in view.get("alerts") or []:
        out(f"  ALERT {a.get('tenant')}: {a.get('kind')} >= "
            f"{_fmt(a.get('threshold'))}x in every window "
            f"({a.get('burn_rates')})")
    return "\n".join(lines)


def main(argv):
    """``budget <jsonl> [more.jsonl ...] [--json]`` — render the
    per-tenant error-budget table of one or more obs JSONL artifacts;
    exits 1 when any alert fired or any budget record is alerting (the
    CI-friendly burn check), 0 when budgets are healthy, and 2 when the
    artifacts carry ZERO budget records — "no telemetry" must never
    read as "no burn" in CI."""
    import json
    import sys

    as_json = "--json" in argv
    paths = [a for a in argv if a != "--json"]
    if not paths:
        print("usage: python -m sq_learn_tpu.obs budget <jsonl> "
              "[more.jsonl ...] [--json]", file=sys.stderr)
        return 2
    from .trace import load_jsonl

    records = []
    for p in paths:
        records.extend(load_jsonl(p))
    view = collect(records)
    if not view["tenants"] and not view["alerts"]:
        if as_json:
            print(json.dumps(dict(view, burning=False,
                                  error="no budget telemetry")))
        print(f"no budget telemetry: zero budget records in "
              f"{', '.join(paths)}", file=sys.stderr)
        return 2
    burning = bool(view["alerts"]) or any(
        r.get("alerting") for per_w in view["tenants"].values()
        for r in per_w.values())
    if as_json:
        print(json.dumps(dict(view, burning=burning)))
    else:
        print("== tenant error budgets (multi-window burn rates) ==")
        print(render(view))
        print(f"burning: {sorted({a.get('tenant') for a in view['alerts']}) if burning else 'none'}")
    return 1 if burning else 0
