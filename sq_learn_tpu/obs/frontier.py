"""Accuracy-vs-theoretical-runtime frontier — the paper's thesis artifact.

The whole point of the source framework (reference ``README.rst:26-44``)
is that ε/δ are *runtime* parameters: loosening them buys theoretical
quantum runtime at the price of accuracy. The runtime accountants
(``QPCA.accumulate_q_runtime``, ``QKMeans.quantum_runtime_model``) and
the accuracy sweeps (``bench/bench_qpca_error_sweep.py``,
``bench/bench_qkmeans_cicids_sweep.py``) each existed alone; this module
joins them: every sweep point lands as one schema-validated ``tradeoff``
JSONL record carrying (error budget, measured accuracy, theoretical
quantum runtime, classical cost model, measured classical wall-clock),
and the CLI renders the trade-off table with its Pareto frontier —

    python -m sq_learn_tpu.obs frontier <run.jsonl> [more.jsonl ...]

A point is Pareto-optimal when no other point of the same sweep has both
higher accuracy and lower theoretical quantum runtime: those are the
budgets worth running, everything else is dominated.

Import-safe without jax (stdlib only), like the trace/report/audit CLIs:
it must run with PYTHONPATH cleared while the accelerator relay is
wedged. The emit half (:func:`record_tradeoff`) touches the recorder
lazily and is a no-op when observability is off.
"""

import json

__all__ = ["record_tradeoff", "collect", "pareto", "render", "main"]


def record_tradeoff(sweep, point, *, accuracy, accuracy_metric=None,
                    q_runtime=None, c_runtime=None, wall_s=None,
                    budget=None, **attrs):
    """Append one ``tradeoff`` record (and its JSONL line) to the active
    run. No-op when observability is disabled.

    ``point`` is the sweep's dial value (δ, or ε+δ); ``accuracy`` the
    measured downstream quality at that budget (ARI, CV accuracy, ...);
    ``q_runtime``/``c_runtime`` the framework's theoretical quantum /
    classical cost-model outputs (None when the model declined — e.g.
    δ=0, where the quantum routine short-circuits and has no quantum
    cost); ``wall_s`` the measured classical wall-clock of the simulated
    run.
    """
    from . import recorder

    rec = recorder.get_recorder()
    if rec is None:
        return
    entry = {"type": "tradeoff", "sweep": str(sweep),
             "point": float(point), "accuracy": float(accuracy),
             "q_runtime": (None if q_runtime is None else float(q_runtime)),
             "c_runtime": (None if c_runtime is None else float(c_runtime))}
    if accuracy_metric is not None:
        entry["accuracy_metric"] = str(accuracy_metric)
    if wall_s is not None:
        entry["wall_s"] = round(float(wall_s), 6)
    if budget:
        entry["budget"] = {k: float(v) for k, v in budget.items()}
    if attrs:
        entry["attrs"] = recorder._jsonable(attrs)
    rec.record(entry, kind="tradeoff_records")


def collect(records):
    """The tradeoff records of an iterable of decoded record dicts,
    grouped per sweep: ``{sweep: [record, ...]}`` in input order."""
    sweeps = {}
    for r in records:
        if isinstance(r, dict) and r.get("type") == "tradeoff":
            sweeps.setdefault(r.get("sweep"), []).append(r)
    return sweeps


def pareto(points, acc_key="accuracy", cost_key="q_runtime"):
    """Indices of the Pareto-optimal points: maximal accuracy, minimal
    theoretical runtime. Points without a finite cost (short-circuited
    δ=0 entries, missing models) are never frontier members — they have
    no quantum runtime to trade. Ties on both axes keep the first point.
    """
    idx = [i for i, p in enumerate(points)
           if isinstance(p.get(cost_key), (int, float))
           and isinstance(p.get(acc_key), (int, float))]
    front = []
    for i in idx:
        pi = points[i]
        dominated = False
        for j in idx:
            if j == i:
                continue
            pj = points[j]
            better_eq = (pj[acc_key] >= pi[acc_key]
                         and pj[cost_key] <= pi[cost_key])
            strictly = (pj[acc_key] > pi[acc_key]
                        or pj[cost_key] < pi[cost_key])
            # ties on both axes: the earlier point wins, the later is
            # dominated (keeps the frontier free of duplicates)
            if better_eq and (strictly or j < i):
                dominated = True
                break
        if not dominated:
            front.append(i)
    return front


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float) and (abs(v) >= 1e5 or 0 < abs(v) < 1e-3):
        return f"{v:.3e}"
    return f"{v:.4f}" if isinstance(v, float) else str(v)


def render(sweeps):
    """Format collected tradeoff records as the frontier table: one block
    per sweep, points sorted by budget, Pareto members starred."""
    lines = []
    out = lines.append
    if not sweeps:
        return "  (no tradeoff records)"
    for sweep in sorted(sweeps):
        pts = sorted(sweeps[sweep], key=lambda p: p.get("point", 0.0))
        front = set(pareto(pts))
        out(f"-- sweep {sweep} --")
        out("      point   accuracy     q_runtime     c_runtime    "
            "wall_s  frontier")
        for i, p in enumerate(pts):
            mark = "*" if i in front else " "
            metric = p.get("accuracy_metric")
            out(f"  {mark} {p.get('point', 0.0):7.4g}  "
                f"{_fmt(p.get('accuracy')):>9}  "
                f"{_fmt(p.get('q_runtime')):>12}  "
                f"{_fmt(p.get('c_runtime')):>12}  "
                f"{_fmt(p.get('wall_s')):>8}"
                f"{'  [' + metric + ']' if metric else ''}")
        # the one-line statement of the trade-off: what accuracy the
        # cheapest and the most expensive frontier budgets buy
        fr = [pts[i] for i in sorted(front,
                                     key=lambda i: pts[i]["q_runtime"])]
        if fr:
            lo, hi = fr[0], fr[-1]
            out(f"  frontier: {len(fr)} of {len(pts)} points; "
                f"q_runtime {_fmt(lo['q_runtime'])} buys accuracy "
                f"{_fmt(lo['accuracy'])}, {_fmt(hi['q_runtime'])} buys "
                f"{_fmt(hi['accuracy'])}")
        else:
            out("  frontier: empty (no point carries a finite q_runtime)")
    return "\n".join(lines)


def main(argv):
    """``frontier <jsonl> [more.jsonl ...] [--json]`` — render the
    accuracy-vs-theoretical-runtime table (with Pareto frontier) of one
    or more obs JSONL artifacts. Exits 2 on no input, 1 when the
    artifacts carry no tradeoff records (a frontier view of a run that
    never stated the trade-off is a broken expectation, not an empty
    success), 0 otherwise."""
    import sys

    as_json = "--json" in argv
    paths = [a for a in argv if a != "--json"]
    if not paths:
        print("usage: python -m sq_learn_tpu.obs frontier <jsonl> "
              "[more.jsonl ...] [--json]", file=sys.stderr)
        return 2
    from .trace import load_jsonl

    records = []
    for p in paths:
        records.extend(load_jsonl(p))
    sweeps = collect(records)
    if as_json:
        doc = {}
        for sweep, pts in sweeps.items():
            pts = sorted(pts, key=lambda p: p.get("point", 0.0))
            doc[sweep] = {"points": pts, "pareto": pareto(pts)}
        print(json.dumps(doc))
    else:
        print("== accuracy vs theoretical quantum runtime ==")
        print(render(sweeps))
    return 0 if sweeps else 1
