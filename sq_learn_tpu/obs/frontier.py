"""Accuracy-vs-theoretical-runtime frontier — the paper's thesis artifact.

The whole point of the source framework (reference ``README.rst:26-44``)
is that ε/δ are *runtime* parameters: loosening them buys theoretical
quantum runtime at the price of accuracy. The runtime accountants
(``QPCA.accumulate_q_runtime``, ``QKMeans.quantum_runtime_model``) and
the accuracy sweeps (``bench/bench_qpca_error_sweep.py``,
``bench/bench_qkmeans_cicids_sweep.py``) each existed alone; this module
joins them: every sweep point lands as one schema-validated ``tradeoff``
JSONL record carrying (error budget, measured accuracy, theoretical
quantum runtime, classical cost model, measured classical wall-clock),
and the CLI renders the trade-off table with its Pareto frontier —

    python -m sq_learn_tpu.obs frontier <run.jsonl> [more.jsonl ...]

A point is Pareto-optimal when no other point of the same sweep has both
higher accuracy and lower theoretical quantum runtime: those are the
budgets worth running, everything else is dominated.

Import-safe without jax (stdlib only), like the trace/report/audit CLIs:
it must run with PYTHONPATH cleared while the accelerator relay is
wedged. The emit half (:func:`record_tradeoff`) touches the recorder
lazily and is a no-op when observability is off.
"""

import json

__all__ = ["record_tradeoff", "collect", "effective_contracts", "pareto",
           "render", "render_effective", "main"]


def record_tradeoff(sweep, point, *, accuracy, accuracy_metric=None,
                    q_runtime=None, c_runtime=None, wall_s=None,
                    budget=None, **attrs):
    """Append one ``tradeoff`` record (and its JSONL line) to the active
    run. No-op when observability is disabled.

    ``point`` is the sweep's dial value (δ, or ε+δ); ``accuracy`` the
    measured downstream quality at that budget (ARI, CV accuracy, ...);
    ``q_runtime``/``c_runtime`` the framework's theoretical quantum /
    classical cost-model outputs (None when the model declined — e.g.
    δ=0, where the quantum routine short-circuits and has no quantum
    cost); ``wall_s`` the measured classical wall-clock of the simulated
    run.
    """
    from . import recorder

    rec = recorder.get_recorder()
    if rec is None:
        return
    entry = {"type": "tradeoff", "sweep": str(sweep),
             "point": float(point), "accuracy": float(accuracy),
             "q_runtime": (None if q_runtime is None else float(q_runtime)),
             "c_runtime": (None if c_runtime is None else float(c_runtime))}
    if accuracy_metric is not None:
        entry["accuracy_metric"] = str(accuracy_metric)
    if wall_s is not None:
        entry["wall_s"] = round(float(wall_s), 6)
    if budget:
        entry["budget"] = {k: float(v) for k, v in budget.items()}
    if attrs:
        entry["attrs"] = recorder._jsonable(attrs)
    rec.record(entry, kind="tradeoff_records")


def collect(records):
    """The tradeoff records of an iterable of decoded record dicts,
    grouped per sweep: ``{sweep: [record, ...]}`` in input order."""
    sweeps = {}
    for r in records:
        if isinstance(r, dict) and r.get("type") == "tradeoff":
            sweeps.setdefault(r.get("sweep"), []).append(r)
    return sweeps


def pareto(points, acc_key="accuracy", cost_key="q_runtime"):
    """Indices of the Pareto-optimal points: maximal accuracy, minimal
    theoretical runtime. Points without a finite cost (short-circuited
    δ=0 entries, missing models) are never frontier members — they have
    no quantum runtime to trade. Ties on both axes keep the first point.
    """
    idx = [i for i, p in enumerate(points)
           if isinstance(p.get(cost_key), (int, float))
           and isinstance(p.get(acc_key), (int, float))]
    front = []
    for i in idx:
        pi = points[i]
        dominated = False
        for j in idx:
            if j == i:
                continue
            pj = points[j]
            better_eq = (pj[acc_key] >= pi[acc_key]
                         and pj[cost_key] <= pi[cost_key])
            strictly = (pj[acc_key] > pi[acc_key]
                        or pj[cost_key] < pi[cost_key])
            # ties on both axes: the earlier point wins, the later is
            # dominated (keeps the frontier free of duplicates)
            if better_eq and (strictly or j < i):
                dominated = True
                break
        if not dominated:
            front.append(i)
    return front


def effective_contracts(records):
    """The descriptive per-tenant "effective (ε, δ)" table — what each
    tenant's live guarantee draws say it has *actually* been served,
    next to what was declared. This is the observation table ROADMAP
    item 1's (ε, δ) autotuner consumes: a controller that wants the
    cheapest contract meeting a tenant's accuracy SLO reads the
    realized-error quantiles and the Clopper–Pearson-bounded failure
    rate from here, per tenant, from live traffic.

    Groups ``guarantee`` records by their ``attrs.tenant`` (draws
    without a tenant attr — fit-time model sites — are skipped; they
    have no tenant to bill). Returns ``{tenant: {sites, draws,
    violations, delta_declared, delta_lower_bound, eps_declared,
    eps_effective, eps_max}}`` where ``delta_declared`` is the LARGEST
    declared failure probability (the loosest contract — conservative,
    the auditor's rule), ``delta_lower_bound`` the exact binomial lower
    confidence bound on the realized failure rate, ``eps_declared`` the
    largest declared tolerance, ``eps_effective`` the nearest-rank
    (1 − δ_declared)-quantile of the realized errors (the ε the tenant
    empirically got at its declared confidence), and ``eps_max`` the
    worst realized draw.
    """
    import math

    from .guarantees import clopper_pearson_lower

    tenants = {}
    for r in records:
        if not isinstance(r, dict) or r.get("type") != "guarantee":
            continue
        attrs = r.get("attrs") or {}
        tenant = attrs.get("tenant")
        if tenant is None:
            continue
        e = tenants.setdefault(str(tenant), {
            "sites": set(), "draws": 0, "violations": 0,
            "delta_declared": None, "eps_declared": None,
            "_realized": []})
        e["sites"].add(r.get("site"))
        e["draws"] += 1
        if r.get("violated"):
            e["violations"] += 1
        fp = r.get("fail_prob")
        if isinstance(fp, (int, float)) and not isinstance(fp, bool):
            if e["delta_declared"] is None or fp > e["delta_declared"]:
                e["delta_declared"] = float(fp)
        tol = r.get("tol")
        if isinstance(tol, (int, float)) and not isinstance(tol, bool):
            if e["eps_declared"] is None or tol > e["eps_declared"]:
                e["eps_declared"] = float(tol)
        rl = r.get("realized")
        if isinstance(rl, (int, float)) and not isinstance(rl, bool):
            e["_realized"].append(float(rl))
    for e in tenants.values():
        e["sites"] = sorted(s for s in e["sites"] if s is not None)
        e["delta_lower_bound"] = clopper_pearson_lower(
            e["violations"], e["draws"]) if e["draws"] else 0.0
        realized = sorted(e.pop("_realized"))
        e["eps_max"] = realized[-1] if realized else None
        if realized:
            q = 1.0 - (e["delta_declared"] or 0.0)
            rank = min(len(realized), max(1, math.ceil(len(realized) * q)))
            e["eps_effective"] = realized[rank - 1]
        else:
            e["eps_effective"] = None
    return tenants


def render_effective(tenants):
    """Format an :func:`effective_contracts` table (one line per
    tenant: declared vs empirically-served (ε, δ))."""
    lines = []
    if not tenants:
        return "  (no tenant-attributed guarantee draws)"
    for tenant in sorted(tenants):
        e = tenants[tenant]
        lines.append(
            f"  {tenant:<12} {e['violations']:3d}/{e['draws']:<5d} over "
            f"tol  eps_declared={_fmt(e['eps_declared'])} "
            f"eps_effective={_fmt(e['eps_effective'])} "
            f"eps_max={_fmt(e['eps_max'])}  "
            f"delta_declared={_fmt(e['delta_declared'])} "
            f"delta_lcb={_fmt(e['delta_lower_bound'])}  "
            f"sites={','.join(e['sites'])}")
    return "\n".join(lines)


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float) and (abs(v) >= 1e5 or 0 < abs(v) < 1e-3):
        return f"{v:.3e}"
    return f"{v:.4f}" if isinstance(v, float) else str(v)


def render(sweeps):
    """Format collected tradeoff records as the frontier table: one block
    per sweep, points sorted by budget, Pareto members starred."""
    lines = []
    out = lines.append
    if not sweeps:
        return "  (no tradeoff records)"
    for sweep in sorted(sweeps):
        pts = sorted(sweeps[sweep], key=lambda p: p.get("point", 0.0))
        front = set(pareto(pts))
        out(f"-- sweep {sweep} --")
        out("      point   accuracy     q_runtime     c_runtime    "
            "wall_s  frontier")
        for i, p in enumerate(pts):
            mark = "*" if i in front else " "
            metric = p.get("accuracy_metric")
            out(f"  {mark} {p.get('point', 0.0):7.4g}  "
                f"{_fmt(p.get('accuracy')):>9}  "
                f"{_fmt(p.get('q_runtime')):>12}  "
                f"{_fmt(p.get('c_runtime')):>12}  "
                f"{_fmt(p.get('wall_s')):>8}"
                f"{'  [' + metric + ']' if metric else ''}")
        # the one-line statement of the trade-off: what accuracy the
        # cheapest and the most expensive frontier budgets buy
        fr = [pts[i] for i in sorted(front,
                                     key=lambda i: pts[i]["q_runtime"])]
        if fr:
            lo, hi = fr[0], fr[-1]
            out(f"  frontier: {len(fr)} of {len(pts)} points; "
                f"q_runtime {_fmt(lo['q_runtime'])} buys accuracy "
                f"{_fmt(lo['accuracy'])}, {_fmt(hi['q_runtime'])} buys "
                f"{_fmt(hi['accuracy'])}")
        else:
            out("  frontier: empty (no point carries a finite q_runtime)")
    return "\n".join(lines)


def main(argv):
    """``frontier <jsonl> [more.jsonl ...] [--json]`` — render the
    accuracy-vs-theoretical-runtime table (with Pareto frontier) of one
    or more obs JSONL artifacts, plus the per-tenant effective-(ε, δ)
    table when the artifacts carry tenant-attributed guarantee draws.
    Exits 2 on no input, 1 when the artifacts carry neither tradeoff
    records nor effective contracts (a frontier view of a run that never
    stated any trade-off is a broken expectation, not an empty
    success), 0 otherwise."""
    import sys

    as_json = "--json" in argv
    paths = [a for a in argv if a != "--json"]
    if not paths:
        print("usage: python -m sq_learn_tpu.obs frontier <jsonl> "
              "[more.jsonl ...] [--json]", file=sys.stderr)
        return 2
    from .trace import load_jsonl

    records = []
    for p in paths:
        records.extend(load_jsonl(p))
    sweeps = collect(records)
    effective = effective_contracts(records)
    if as_json:
        doc = {}
        for sweep, pts in sweeps.items():
            pts = sorted(pts, key=lambda p: p.get("point", 0.0))
            doc[sweep] = {"points": pts, "pareto": pareto(pts)}
        print(json.dumps({"sweeps": doc, "effective": effective}))
    else:
        print("== accuracy vs theoretical quantum runtime ==")
        print(render(sweeps))
        print("== effective (eps, delta) per tenant (live draws) ==")
        print(render_effective(effective))
    return 0 if sweeps or effective else 1
