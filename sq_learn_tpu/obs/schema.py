"""JSONL schema for obs records, and a dependency-free validator.

Every line of an obs JSONL file is one JSON object carrying the common
envelope ``{"v": 8, "schema_version": 8, "ts": <unix seconds>,
"type": <t>}`` plus per-type required fields. Version history: v1 (PR 2)
had neither the ``schema_version`` alias nor the ``xla_cost`` /
``regression`` types; v2 (PR 4) added those; v3 (PR 5) adds the
statistical-observability types ``guarantee`` (one realized-vs-declared
(ε, δ) draw) and ``tradeoff`` (one accuracy-vs-theoretical-runtime sweep
point); v4 (PR 9) adds ``slo`` (one serving-run latency/throughput
summary from :mod:`sq_learn_tpu.serving`); v5 (PR 11) adds the optional
``slo.transfer_bytes`` field (the quantized serving route's bytes-moved
evidence — no new record types); v6 (PR 12) adds the per-tenant
error-budget types ``budget`` (one tenant × rolling-window burn-rate
evaluation from :mod:`sq_learn_tpu.obs.budget`) and ``alert`` (one
tripped multi-window burn alert), plus the optional ``slo.tenant`` /
``slo.stages`` fields (per-tenant SLO records and the queue/coalesce/
transfer/compute/scatter latency decomposition); v7 (PR 13) adds the
compressed-tier codec conventions over the EXISTING generic types (no
new record types): the ``oocore.codec_bytes_in`` /
``oocore.codec_bytes_out`` counters (stored vs decoded bytes through
the shard codec, :mod:`sq_learn_tpu.oocore.store`), the
``serving.cache_spills`` / ``serving.cache_disk_hits`` counters (the
feature-cache disk tier, :mod:`sq_learn_tpu.serving.cache`), the
``cold_tier`` fault kind (per-shard remote-storage latency model), and
the ``codec`` attr on ``oocore.create_store`` spans. PR 16 adds one
more counter convention on the same generic type (still v7): the
``serving.megabatches`` counter — kernel launches that coalesced
requests from MORE than one tenant (cross-tenant megabatching,
:mod:`sq_learn_tpu.serving.dispatcher`); each such launch still lands
exactly one set of per-tenant ``slo``/``budget`` records whose request
counts sum to the run aggregate; v8 (PR 17) adds the serving control
plane's ``control`` type (one SLO-driven autotuner evaluation from
:mod:`sq_learn_tpu.serving.control` — the telemetry inputs it consumed,
the decision it took, and the predicted vs realized effect) plus the
optional monotonic ``budget.seq`` / ``alert.seq`` fields (ledger-scoped
counters making trace-export merge order deterministic when timestamps
collide); v9 (PR 18) adds the elastic-mesh ``elastic`` type (one
multi-host world transition from
:mod:`sq_learn_tpu.parallel.elastic` — world formation, resume,
detected host failure/stall, generation-bumping shrink, refused
stale-generation commit, stale-worker exit, completion) plus the
``host_fail`` / ``host_stall`` fault kinds' optional ``fault.host`` /
``fault.stall_s`` fields (which worker index the injector targeted,
and the injected stall length); v10 (PR 19) adds fleet observability
(:mod:`sq_learn_tpu.obs.fleet`): the optional per-record ``fleet``
envelope sub-object (``run_id`` str — coordinator-minted, shared by
every process of one elastic run; ``host`` str — stable per-process
label, e.g. ``coord`` / ``w0``; ``pid`` int; ``gen`` int | null — the
live elastic generation), the ``clock`` record type (one KV-carried
clock sample — a peer's send timestamp paired with the local receive
timestamp — from which per-host offsets are estimated), and the
elastic ``window`` / ``commit`` events (per-host fold progress at
every commit-window boundary, and node 0's committed-window ledger —
the obs twin of the fold ledger that the fleet merge reconciles);
v11 (PR 20) adds the storage-plane ``io`` type
(:mod:`sq_learn_tpu.obs.storage`): one CUMULATIVE
per-``(surface, store, shard)`` ledger aggregate per flush — stored vs
raw bytes, the read/CRC/decode/cold-tier latency decomposition,
prefetch hit/stall/serial attribution, retry/quarantine counts, the
serving surfaces' spill/disk-hit/promote traffic, and the time-decayed
EWMA heat — flushed at pass end and recorder close (never one line per
read: a reader takes the NEWEST record per key, exactly like
counters), plus the size-based sink-rotation convention
(``SQ_OBS_ROTATE_BYTES`` gzips the live sink to ``<path>.<n>.gz``
segments; the optional ``meta.segment`` int stamps each reopened
segment).
Older versions
still validate (their types are a strict subset), any other version is
rejected — an unknown version means a reader that would silently
misinterpret fields, so it must fail loudly.

=========  ==============================================================
type       required fields (beyond the envelope)
=========  ==============================================================
meta       pid (int), schema (int)
span       name (str), seq (int), dur_s (number ≥ 0), depth (int ≥ 0),
           parent (int | null), synced (bool); optional attrs (object),
           error (str)
counter    name (str), value (number), delta (number)
gauge      name (str), value (any JSON scalar); optional attrs (object)
ledger     estimator (str), step (str), queries (object: str → number),
           budget (object: str → number); optional wall_s (number ≥ 0),
           attrs (object)
watchdog   site (str), compiles (int ≥ 0), budget (int | null),
           over_budget (bool)
probe      outcome (str ∈ {ok, timeout, error, cpu, skipped}),
           latency_s (number ≥ 0), platform (str); optional cached (bool)
fault      kind (str), tile (int | null) — one injected fault from the
           ``SQ_FAULTS`` harness (:mod:`sq_learn_tpu.resilience.faults`);
           for the read-side kinds (``read_fail`` / ``read_stall`` /
           ``corrupt_shard`` / ``cold_tier``) ``tile`` carries the SHARD
           index of the out-of-core store (:mod:`sq_learn_tpu.oocore`);
           for the elastic kinds (``host_fail`` / ``host_stall``, v9)
           ``tile`` carries the fold-WINDOW index and the optional
           host (int) / stall_s (number ≥ 0) name the targeted worker
           and the injected stall
breaker    state (str ∈ {closed, open, half_open}), prev (str),
           reason (str), consecutive (int ≥ 0) — one circuit-breaker
           transition (:mod:`sq_learn_tpu.resilience.supervisor`)
xla_cost   site (str), signature (str), flops (number | null),
           bytes_accessed (number | null), peak_bytes (number | null) —
           one compilation's static cost/memory accounting
           (:mod:`sq_learn_tpu.obs.xla`); optional argument_bytes /
           output_bytes / temp_bytes / generated_code_bytes
           (int | null), backend (str), error (str)
regression  gate (str), metric (str),
           verdict (str ∈ {green, red, skip}), current (number | null),
           reference (number | null), tolerance (number | null) — one
           tolerance-banded comparison against the committed bench
           trajectory (:mod:`sq_learn_tpu.obs.regress`)
guarantee  site (str), realized (number ≥ 0), tol (number ≥ 0),
           violated (bool), fail_prob (number in [0, 1] | null) — one
           draw of a simulated routine's realized error against its
           declared (ε, δ) contract
           (:mod:`sq_learn_tpu.obs.guarantees`); optional
           short_circuit (bool), epsilon / delta (number), norm (str),
           estimator (str), attrs (object)
tradeoff   sweep (str), point (number), accuracy (number),
           q_runtime (number | null), c_runtime (number | null),
           wall_s (number ≥ 0 | null) — one sweep point joining measured
           accuracy with the theoretical quantum runtime its error
           budget buys (:mod:`sq_learn_tpu.obs.frontier`); optional
           accuracy_metric (str), budget (object: str → number),
           attrs (object)
slo        site (str), requests (int ≥ 0), p50_ms (number ≥ 0),
           p99_ms (number ≥ 0), qps (number ≥ 0),
           batch_occupancy (number in [0, 1]), degraded (int ≥ 0),
           violated (bool) — one serving run's latency/throughput
           summary against its declared SLO targets
           (:mod:`sq_learn_tpu.serving.slo`); optional batches (int),
           window_s (number ≥ 0), transfer_bytes (int ≥ 0 — padded
           payload bytes moved host→device; the quantized route's
           bytes-halved claim reads off this, v5),
           targets (object: str → number),
           tenant (str — a per-tenant record next to the run
           aggregate, v6), stages (object: str → number ≥ 0 — the
           queue/coalesce/assemble/transfer/compute/scatter latency
           decomposition in seconds, v6), attrs (object)
budget     tenant (str), window_s (number > 0), slo_burn (number in
           [0, 1] | null), stat_burn (number in [0, 1] | null),
           cp_lower_bound (number in [0, 1] | null), burn_rate
           (number ≥ 0 | null), alerting (bool) — one tenant ×
           rolling-window error-budget evaluation
           (:mod:`sq_learn_tpu.obs.budget`); optional requests /
           over_p50 / over_p99 / draws / draw_violations (int ≥ 0),
           p50_ms / p99_ms (number ≥ 0), slo_burn_rate /
           stat_burn_rate (number ≥ 0), fail_prob (number in [0, 1]),
           targets (object: str → number), site (str),
           seq (int ≥ 0 — ledger-scoped monotonic emit counter, v8),
           attrs (object)
alert      tenant (str), kind (str), threshold (number ≥ 0),
           burn_rates (object: str → number) — one tripped
           multi-window burn-rate alert (every configured window at or
           past the threshold); optional site (str),
           seq (int ≥ 0 — ledger-scoped monotonic emit counter, v8),
           attrs (object)
control    tenant (str), action (str ∈ {plan, hold, relax, tighten,
           degrade, recover}), seq (int ≥ 0), inputs (object),
           decision (object) — one serving-control-plane autotuner
           evaluation (:mod:`sq_learn_tpu.serving.control`): the burn/
           CP-bound/frontier telemetry consumed, the decision taken
           (route, coalescing floor, renegotiated targets, served
           (ε, δ)); optional site (str), level (int ≥ 0 — position on
           the degrade ladder), predicted (object — the decision's
           expected effect), realized (object | null — the measured
           effect of the PREVIOUS decision, closing the loop),
           attrs (object)
elastic    event (str ∈ {world_up, resume, host_fail, host_stall,
           shrink, commit_refused, stale_exit, done, window, commit}),
           generation (int ≥ 0), n_hosts (int ≥ 0) — one elastic-mesh
           world transition (:mod:`sq_learn_tpu.parallel.elastic`);
           optional host / failed_host / cursor / window /
           manifest_generation (int), detect_s / shrink_s / stall_s
           (number ≥ 0), attrs (object). ``window`` (v10) is one
           host's folded commit window (host, window, cursor);
           ``commit`` (v10) is node 0's committed window (window,
           cursor) — exactly one per window across the whole fleet
clock      peer (str), sent_ts (number), recv_ts (number) — one clock
           sample carried over an existing KV exchange (heartbeat /
           manifest / progress): ``sent_ts`` is the peer's clock when
           it published, ``recv_ts`` the local clock at observation;
           ``recv_ts − sent_ts`` upper-bounds the local−peer offset
           (one-way), pairs of opposite-direction minima give the
           midpoint estimate (:mod:`sq_learn_tpu.obs.fleet`); optional
           generation (int ≥ 0), via (str)
io         surface (str — ``oocore`` | ``serve_cache`` |
           ``compile_cache``), store (str — store fingerprint or
           backing directory), shard (int ≥ 0 | null — shard ordinal;
           null for the whole-store serving surfaces), reads
           (int ≥ 0), bytes_stored (int ≥ 0), bytes_raw (int ≥ 0) —
           one CUMULATIVE storage-ledger aggregate
           (:mod:`sq_learn_tpu.obs.storage`; newest record per key
           wins, like counters); optional hits / stalls / serial /
           retries / quarantined / spills / disk_hits / promotes /
           misses (int ≥ 0), read_s / crc_s / decode_s / cold_s /
           stall_s / heat (number ≥ 0), codec (str), reason (str —
           what triggered the flush)
=========  ==============================================================

Every record may additionally carry the v10 ``fleet`` envelope
sub-object (run_id str, host str, pid int, gen int | null) — stamped by
the recorder when a fleet identity is active, validated whenever
present.

The out-of-core layer (PR 8) rides the generic types rather than minting
new ones: shard-store reads surface as ``counter`` records
(``oocore.shard_reads`` / ``oocore.shard_read_bytes`` /
``oocore.crc_failures`` / ``oocore.rereads``, plus the v7 codec pair
``oocore.codec_bytes_in`` / ``oocore.codec_bytes_out`` and the serving
feature-cache tier's ``serving.cache_spills`` /
``serving.cache_disk_hits``) and ``span`` records
(``oocore.create_store`` / ``oocore.minibatch_fit`` / ``oocore.epoch`` /
``oocore.assign_labels``), and read faults are ``fault`` records — one
schema reads every layer.

The validator is hand-rolled (no jsonschema in the image — CLAUDE.md: no
installs) and is the contract ``make obs-smoke``, the bench suite, and the
tests all check against.
"""

import json

from .recorder import SCHEMA_VERSION

_NUM = (int, float)

#: versions this validator knows how to read (v1 = PR 2's envelope
#: without schema_version/xla_cost/regression; v2 = PR 4's, without
#: guarantee/tradeoff; v3 = PR 5's, without slo; v4 = PR 9's, without
#: slo.transfer_bytes; v5 = PR 11's, without budget/alert; v6 = PR 12's,
#: without the codec/spill counter conventions; v7 = PR 13's, without
#: control or the budget/alert seq fields; v8 = PR 17's, without the
#: elastic type or the fault.host/fault.stall_s fields; v9 = PR 18's,
#: without the fleet envelope, the clock type, or the elastic
#: window/commit events; v10 = PR 19's, without the io type or sink
#: rotation)
KNOWN_VERSIONS = {1, 2, 3, 4, 5, 6, 7, 8, 9, 10, SCHEMA_VERSION}

#: every record type the schema defines, machine-readable. The static
#: checker (:mod:`sq_learn_tpu.analysis`, rule ``obs-schema``) and the
#: smoke validator consume this tuple instead of re-parsing the
#: validator's dispatch; keep it in lockstep with the table above.
RECORD_TYPES = (
    "meta", "span", "counter", "gauge", "ledger", "watchdog", "probe",
    "fault", "breaker", "xla_cost", "regression", "guarantee", "tradeoff",
    "slo", "budget", "alert", "control", "elastic", "clock", "io",
)

_ELASTIC_EVENTS = {"world_up", "resume", "host_fail", "host_stall",
                   "shrink", "commit_refused", "stale_exit", "done",
                   "window", "commit"}

_CONTROL_ACTIONS = {"plan", "hold", "relax", "tighten", "degrade",
                    "recover"}

_PROBE_OUTCOMES = {"ok", "timeout", "error", "cpu", "skipped"}

_BREAKER_STATES = {"closed", "open", "half_open"}

_REGRESSION_VERDICTS = {"green", "red", "skip"}


def _check(cond, errors, msg):
    if not cond:
        errors.append(msg)


def validate_record(rec):
    """Validate one decoded record; returns a list of error strings
    (empty = valid)."""
    errors = []
    if not isinstance(rec, dict):
        return ["record is not an object"]
    v = rec.get("v")
    _check(v in KNOWN_VERSIONS, errors,
           f"unknown schema version {v!r} (known: {sorted(KNOWN_VERSIONS)})")
    if "schema_version" in rec:
        _check(rec["schema_version"] == v, errors,
               f"schema_version {rec['schema_version']!r} disagrees with "
               f"v {v!r}")
    elif isinstance(v, int) and v >= 2:
        errors.append(f"v{v} records must carry schema_version")
    _check(isinstance(rec.get("ts"), _NUM), errors, "ts must be numeric")
    t = rec.get("type")
    if t == "meta":
        _check(isinstance(rec.get("pid"), int), errors, "meta.pid int")
        _check(isinstance(rec.get("schema"), int), errors, "meta.schema int")
    elif t == "span":
        _check(isinstance(rec.get("name"), str), errors, "span.name str")
        _check(isinstance(rec.get("seq"), int), errors, "span.seq int")
        _check(isinstance(rec.get("dur_s"), _NUM) and rec["dur_s"] >= 0,
               errors, "span.dur_s non-negative number")
        _check(isinstance(rec.get("depth"), int) and rec["depth"] >= 0,
               errors, "span.depth non-negative int")
        _check(rec.get("parent") is None or isinstance(rec["parent"], int),
               errors, "span.parent int or null")
        _check(isinstance(rec.get("synced"), bool), errors,
               "span.synced bool")
        _check(isinstance(rec.get("attrs", {}), dict), errors,
               "span.attrs object")
    elif t == "counter":
        _check(isinstance(rec.get("name"), str), errors, "counter.name str")
        _check(isinstance(rec.get("value"), _NUM), errors,
               "counter.value number")
        _check(isinstance(rec.get("delta"), _NUM), errors,
               "counter.delta number")
    elif t == "gauge":
        _check(isinstance(rec.get("name"), str), errors, "gauge.name str")
        _check("value" in rec, errors, "gauge.value required")
    elif t == "ledger":
        _check(isinstance(rec.get("estimator"), str), errors,
               "ledger.estimator str")
        _check(isinstance(rec.get("step"), str), errors, "ledger.step str")
        for field in ("queries", "budget"):
            obj = rec.get(field)
            ok = isinstance(obj, dict) and all(
                isinstance(k, str) and isinstance(v, _NUM)
                for k, v in obj.items())
            _check(ok, errors, f"ledger.{field} object of str → number")
        if "wall_s" in rec:
            _check(isinstance(rec["wall_s"], _NUM) and rec["wall_s"] >= 0,
                   errors, "ledger.wall_s non-negative number")
    elif t == "watchdog":
        _check(isinstance(rec.get("site"), str), errors, "watchdog.site str")
        _check(isinstance(rec.get("compiles"), int) and rec["compiles"] >= 0,
               errors, "watchdog.compiles non-negative int")
        _check(rec.get("budget") is None or isinstance(rec["budget"], int),
               errors, "watchdog.budget int or null")
        _check(isinstance(rec.get("over_budget"), bool), errors,
               "watchdog.over_budget bool")
    elif t == "probe":
        _check(rec.get("outcome") in _PROBE_OUTCOMES, errors,
               f"probe.outcome in {sorted(_PROBE_OUTCOMES)}")
        _check(isinstance(rec.get("latency_s"), _NUM)
               and rec["latency_s"] >= 0, errors,
               "probe.latency_s non-negative number")
        _check(isinstance(rec.get("platform"), str), errors,
               "probe.platform str")
        if "cached" in rec:
            _check(isinstance(rec["cached"], bool), errors,
                   "probe.cached bool")
    elif t == "fault":
        _check(isinstance(rec.get("kind"), str), errors, "fault.kind str")
        _check(rec.get("tile") is None or isinstance(rec["tile"], int),
               errors, "fault.tile int or null")
        if "host" in rec:
            _check(isinstance(rec["host"], int)
                   and not isinstance(rec["host"], bool), errors,
                   "fault.host int")
        if "stall_s" in rec:
            _check(isinstance(rec["stall_s"], _NUM)
                   and not isinstance(rec["stall_s"], bool)
                   and rec["stall_s"] >= 0, errors,
                   "fault.stall_s non-negative number")
    elif t == "breaker":
        _check(rec.get("state") in _BREAKER_STATES, errors,
               f"breaker.state in {sorted(_BREAKER_STATES)}")
        _check(isinstance(rec.get("prev"), str), errors, "breaker.prev str")
        _check(isinstance(rec.get("reason"), str), errors,
               "breaker.reason str")
        _check(isinstance(rec.get("consecutive"), int)
               and rec["consecutive"] >= 0, errors,
               "breaker.consecutive non-negative int")
    elif t == "xla_cost":
        _check(isinstance(rec.get("site"), str), errors, "xla_cost.site str")
        _check(isinstance(rec.get("signature"), str), errors,
               "xla_cost.signature str")
        for field in ("flops", "bytes_accessed", "peak_bytes"):
            _check(field in rec and (rec[field] is None
                                     or isinstance(rec[field], _NUM)),
                   errors, f"xla_cost.{field} number or null")
        for field in ("argument_bytes", "output_bytes", "temp_bytes",
                      "generated_code_bytes"):
            if field in rec:
                _check(rec[field] is None or isinstance(rec[field], int),
                       errors, f"xla_cost.{field} int or null")
    elif t == "regression":
        _check(isinstance(rec.get("gate"), str), errors,
               "regression.gate str")
        _check(isinstance(rec.get("metric"), str), errors,
               "regression.metric str")
        _check(rec.get("verdict") in _REGRESSION_VERDICTS, errors,
               f"regression.verdict in {sorted(_REGRESSION_VERDICTS)}")
        for field in ("current", "reference", "tolerance"):
            _check(field in rec and (rec[field] is None
                                     or isinstance(rec[field], _NUM)),
                   errors, f"regression.{field} number or null")
    elif t == "guarantee":
        _check(isinstance(rec.get("site"), str), errors,
               "guarantee.site str")
        for field in ("realized", "tol"):
            _check(isinstance(rec.get(field), _NUM)
                   and not isinstance(rec.get(field), bool)
                   and rec[field] >= 0, errors,
                   f"guarantee.{field} non-negative number")
        _check(isinstance(rec.get("violated"), bool), errors,
               "guarantee.violated bool")
        fp = rec.get("fail_prob", None)
        _check("fail_prob" in rec
               and (fp is None or (isinstance(fp, _NUM)
                                   and not isinstance(fp, bool)
                                   and 0.0 <= fp <= 1.0)),
               errors, "guarantee.fail_prob number in [0, 1] or null")
        if "short_circuit" in rec:
            _check(isinstance(rec["short_circuit"], bool), errors,
                   "guarantee.short_circuit bool")
    elif t == "tradeoff":
        _check(isinstance(rec.get("sweep"), str), errors,
               "tradeoff.sweep str")
        for field in ("point", "accuracy"):
            _check(isinstance(rec.get(field), _NUM)
                   and not isinstance(rec.get(field), bool), errors,
                   f"tradeoff.{field} number")
        for field in ("q_runtime", "c_runtime"):
            _check(field in rec and (rec[field] is None
                                     or (isinstance(rec[field], _NUM)
                                         and not isinstance(rec[field],
                                                            bool))),
                   errors, f"tradeoff.{field} number or null")
        if rec.get("wall_s") is not None and "wall_s" in rec:
            _check(isinstance(rec["wall_s"], _NUM) and rec["wall_s"] >= 0,
                   errors, "tradeoff.wall_s non-negative number")
        if "budget" in rec:
            obj = rec["budget"]
            _check(isinstance(obj, dict) and all(
                isinstance(k, str) and isinstance(vv, _NUM)
                for k, vv in obj.items()), errors,
                "tradeoff.budget object of str → number")
    elif t == "slo":
        _check(isinstance(rec.get("site"), str), errors, "slo.site str")
        _check(isinstance(rec.get("requests"), int)
               and not isinstance(rec.get("requests"), bool)
               and rec["requests"] >= 0, errors,
               "slo.requests non-negative int")
        for field in ("p50_ms", "p99_ms", "qps"):
            _check(isinstance(rec.get(field), _NUM)
                   and not isinstance(rec.get(field), bool)
                   and rec[field] >= 0, errors,
                   f"slo.{field} non-negative number")
        occ = rec.get("batch_occupancy")
        _check(isinstance(occ, _NUM) and not isinstance(occ, bool)
               and 0.0 <= occ <= 1.0, errors,
               "slo.batch_occupancy number in [0, 1]")
        _check(isinstance(rec.get("degraded"), int)
               and not isinstance(rec.get("degraded"), bool)
               and rec["degraded"] >= 0, errors,
               "slo.degraded non-negative int")
        _check(isinstance(rec.get("violated"), bool), errors,
               "slo.violated bool")
        if "batches" in rec:
            _check(isinstance(rec["batches"], int)
                   and not isinstance(rec["batches"], bool), errors,
                   "slo.batches int")
        if "transfer_bytes" in rec:
            _check(isinstance(rec["transfer_bytes"], int)
                   and not isinstance(rec["transfer_bytes"], bool)
                   and rec["transfer_bytes"] >= 0, errors,
                   "slo.transfer_bytes non-negative int")
        if "window_s" in rec:
            _check(isinstance(rec["window_s"], _NUM)
                   and rec["window_s"] >= 0, errors,
                   "slo.window_s non-negative number")
        if "targets" in rec:
            obj = rec["targets"]
            _check(isinstance(obj, dict) and all(
                isinstance(k, str) and isinstance(vv, _NUM)
                for k, vv in obj.items()), errors,
                "slo.targets object of str → number")
        if "tenant" in rec:
            _check(isinstance(rec["tenant"], str), errors,
                   "slo.tenant str")
        if "stages" in rec:
            obj = rec["stages"]
            _check(isinstance(obj, dict) and all(
                isinstance(k, str) and isinstance(vv, _NUM)
                and not isinstance(vv, bool) and vv >= 0
                for k, vv in obj.items()), errors,
                "slo.stages object of str → non-negative number")
    elif t == "budget":
        _check(isinstance(rec.get("tenant"), str), errors,
               "budget.tenant str")
        w = rec.get("window_s")
        _check(isinstance(w, _NUM) and not isinstance(w, bool) and w > 0,
               errors, "budget.window_s positive number")
        for field in ("slo_burn", "stat_burn", "cp_lower_bound"):
            v_ = rec.get(field, None)
            _check(field in rec
                   and (v_ is None or (isinstance(v_, _NUM)
                                       and not isinstance(v_, bool)
                                       and 0.0 <= v_ <= 1.0)),
                   errors, f"budget.{field} number in [0, 1] or null")
        br = rec.get("burn_rate", None)
        _check("burn_rate" in rec
               and (br is None or (isinstance(br, _NUM)
                                   and not isinstance(br, bool)
                                   and br >= 0)),
               errors, "budget.burn_rate non-negative number or null")
        _check(isinstance(rec.get("alerting"), bool), errors,
               "budget.alerting bool")
        for field in ("requests", "over_p50", "over_p99", "draws",
                      "draw_violations"):
            if rec.get(field) is not None and field in rec:
                _check(isinstance(rec[field], int)
                       and not isinstance(rec[field], bool)
                       and rec[field] >= 0, errors,
                       f"budget.{field} non-negative int")
        for field in ("p50_ms", "p99_ms", "slo_burn_rate",
                      "stat_burn_rate"):
            if rec.get(field) is not None and field in rec:
                _check(isinstance(rec[field], _NUM)
                       and not isinstance(rec[field], bool)
                       and rec[field] >= 0, errors,
                       f"budget.{field} non-negative number")
        if "targets" in rec:
            obj = rec["targets"]
            _check(isinstance(obj, dict) and all(
                isinstance(k, str) and isinstance(vv, _NUM)
                for k, vv in obj.items()), errors,
                "budget.targets object of str → number")
        if "seq" in rec:
            _check(isinstance(rec["seq"], int)
                   and not isinstance(rec["seq"], bool)
                   and rec["seq"] >= 0, errors,
                   "budget.seq non-negative int")
    elif t == "alert":
        _check(isinstance(rec.get("tenant"), str), errors,
               "alert.tenant str")
        _check(isinstance(rec.get("kind"), str), errors, "alert.kind str")
        th = rec.get("threshold")
        _check(isinstance(th, _NUM) and not isinstance(th, bool)
               and th >= 0, errors, "alert.threshold non-negative number")
        obj = rec.get("burn_rates")
        _check(isinstance(obj, dict) and all(
            isinstance(k, str) and isinstance(vv, _NUM)
            and not isinstance(vv, bool) for k, vv in obj.items()),
            errors, "alert.burn_rates object of str → number")
        if "seq" in rec:
            _check(isinstance(rec["seq"], int)
                   and not isinstance(rec["seq"], bool)
                   and rec["seq"] >= 0, errors,
                   "alert.seq non-negative int")
    elif t == "control":
        _check(isinstance(rec.get("tenant"), str), errors,
               "control.tenant str")
        _check(rec.get("action") in _CONTROL_ACTIONS, errors,
               f"control.action in {sorted(_CONTROL_ACTIONS)}")
        _check(isinstance(rec.get("seq"), int)
               and not isinstance(rec.get("seq"), bool)
               and rec.get("seq", -1) >= 0, errors,
               "control.seq non-negative int")
        for field in ("inputs", "decision"):
            _check(isinstance(rec.get(field), dict), errors,
                   f"control.{field} object")
        if "level" in rec:
            _check(isinstance(rec["level"], int)
                   and not isinstance(rec["level"], bool)
                   and rec["level"] >= 0, errors,
                   "control.level non-negative int")
        if "predicted" in rec:
            _check(isinstance(rec["predicted"], dict), errors,
                   "control.predicted object")
        if "realized" in rec:
            _check(rec["realized"] is None
                   or isinstance(rec["realized"], dict), errors,
                   "control.realized object or null")
        if "site" in rec:
            _check(isinstance(rec["site"], str), errors,
                   "control.site str")
    elif t == "elastic":
        _check(rec.get("event") in _ELASTIC_EVENTS, errors,
               f"elastic.event in {sorted(_ELASTIC_EVENTS)}")
        for field in ("generation", "n_hosts"):
            _check(isinstance(rec.get(field), int)
                   and not isinstance(rec.get(field), bool)
                   and rec.get(field, -1) >= 0, errors,
                   f"elastic.{field} non-negative int")
        for field in ("host", "failed_host", "cursor", "window",
                      "manifest_generation"):
            if field in rec:
                _check(isinstance(rec[field], int)
                       and not isinstance(rec[field], bool), errors,
                       f"elastic.{field} int")
        for field in ("detect_s", "shrink_s", "stall_s"):
            if field in rec:
                _check(isinstance(rec[field], _NUM)
                       and not isinstance(rec[field], bool)
                       and rec[field] >= 0, errors,
                       f"elastic.{field} non-negative number")
        if "attrs" in rec:
            _check(isinstance(rec["attrs"], dict), errors,
                   "elastic.attrs object")
    elif t == "clock":
        _check(isinstance(rec.get("peer"), str), errors, "clock.peer str")
        for field in ("sent_ts", "recv_ts"):
            _check(isinstance(rec.get(field), _NUM)
                   and not isinstance(rec.get(field), bool), errors,
                   f"clock.{field} number")
        if "generation" in rec:
            _check(isinstance(rec["generation"], int)
                   and not isinstance(rec["generation"], bool)
                   and rec["generation"] >= 0, errors,
                   "clock.generation non-negative int")
        if "via" in rec:
            _check(isinstance(rec["via"], str), errors, "clock.via str")
    elif t == "io":
        _check(isinstance(rec.get("surface"), str), errors,
               "io.surface str")
        _check(isinstance(rec.get("store"), str), errors, "io.store str")
        sh = rec.get("shard", -1)
        _check(sh is None or (isinstance(sh, int)
                              and not isinstance(sh, bool) and sh >= 0),
               errors, "io.shard non-negative int or null")
        for field in ("reads", "bytes_stored", "bytes_raw"):
            _check(isinstance(rec.get(field), int)
                   and not isinstance(rec.get(field), bool)
                   and rec.get(field, -1) >= 0, errors,
                   f"io.{field} non-negative int")
        for field in ("hits", "stalls", "serial", "retries",
                      "quarantined", "spills", "disk_hits", "promotes",
                      "misses"):
            if field in rec:
                _check(isinstance(rec[field], int)
                       and not isinstance(rec[field], bool)
                       and rec[field] >= 0, errors,
                       f"io.{field} non-negative int")
        for field in ("read_s", "crc_s", "decode_s", "cold_s",
                      "stall_s", "heat"):
            if field in rec:
                _check(isinstance(rec[field], _NUM)
                       and not isinstance(rec[field], bool)
                       and rec[field] >= 0, errors,
                       f"io.{field} non-negative number")
        for field in ("codec", "reason"):
            if field in rec:
                _check(isinstance(rec[field], str), errors,
                       f"io.{field} str")
    else:
        errors.append(
            f"unknown record type {t!r} (known: {sorted(RECORD_TYPES)})")
    if "fleet" in rec:
        fl = rec["fleet"]
        if not isinstance(fl, dict):
            errors.append("fleet envelope must be an object")
        else:
            _check(isinstance(fl.get("run_id"), str), errors,
                   "fleet.run_id str")
            _check(isinstance(fl.get("host"), str), errors,
                   "fleet.host str")
            _check(isinstance(fl.get("pid"), int)
                   and not isinstance(fl.get("pid"), bool), errors,
                   "fleet.pid int")
            g = fl.get("gen", None)
            _check(g is None or (isinstance(g, int)
                                 and not isinstance(g, bool)
                                 and g >= 0), errors,
                   "fleet.gen non-negative int or null")
    return errors


def validate_jsonl(path, max_errors=20):
    """Validate every line of an obs JSONL file.

    Returns a summary dict {lines, by_type, errors} where ``errors`` is a
    list of "line N: message" strings (truncated at ``max_errors``). An
    empty or missing file is an error — a run that recorded nothing is a
    broken run, not a valid one. ``.jsonl.gz`` archives (the bench
    suite's compressed per-config artifacts) open transparently.
    """
    lines = 0
    by_type = {}
    errors = []
    try:
        if str(path).endswith(".gz"):
            import gzip

            fh = gzip.open(path, "rt")
        else:
            fh = open(path)
    except OSError as exc:
        return {"lines": 0, "by_type": {}, "errors": [str(exc)]}
    with fh:
        for i, raw in enumerate(fh, 1):
            raw = raw.strip()
            if not raw:
                continue
            lines += 1
            try:
                rec = json.loads(raw)
            except ValueError as exc:
                errors.append(f"line {i}: not JSON ({exc})")
                continue
            for msg in validate_record(rec):
                if len(errors) < max_errors:
                    errors.append(f"line {i}: {msg}")
            t = rec.get("type") if isinstance(rec, dict) else None
            by_type[t] = by_type.get(t, 0) + 1
    if lines == 0:
        errors.append("file has no records")
    return {"lines": lines, "by_type": by_type, "errors": errors}
