"""Storage-plane ledger: per-shard heat/latency accounting + the tiering
advisor.

ROADMAP item 4 (the storage autopilot) wants promotion/demotion driven by
telemetry the repo already emits — but until this module that telemetry
was run-aggregate counters only (``oocore.prefetch_hits``,
``oocore.codec_bytes_in/out``, ``serving.cache_spills``): nothing said
*which* shard is hot, what one access actually cost, or how the three
disk surfaces share the machine. This is the same move PR 12 made for
the (ε, δ) autotuner — build the measurement the policy will consume,
before the policy:

- **the ledger**: every shard materialization
  (:meth:`~sq_learn_tpu.oocore.store.ShardStore.read_shard`) feeds a
  per-``(surface, store, shard)`` aggregate — stored vs raw bytes,
  latency decomposed into read / CRC / decompress / injected
  ``cold_tier`` penalty, prefetch hit vs stall vs serial, retry and
  quarantine counts, and a time-decayed EWMA **heat** (half-life
  ``_HALF_LIFE_S``). Worker-thread accesses attribute to the owning
  shard exactly like prefetch errors do (the key is the shard, not the
  thread). The serving feature-cache disk tier
  (:mod:`sq_learn_tpu.serving.cache` — spill / disk-hit / promote) and
  the persistent compile cache (:mod:`sq_learn_tpu.serving.aot`) feed
  the same shape with their ``surface`` tagged.
- **pre-aggregation** (the PR 9 counter-flood rule): never one JSONL
  line per read. Aggregates flush as cumulative schema-v11 ``io``
  records — last-wins per key, like counters — at pass end
  (:meth:`~sq_learn_tpu.oocore.prefetch.ShardPrefetcher.close`, the
  serving cache's counter flush) and at recorder close, so a 100k×784
  bench run lands O(#shards) lines, not O(#reads).
- **disabled-path zero overhead**: with ``SQ_OBS`` unset,
  :func:`active` is one module-global read returning None — the
  instrumented read paths allocate nothing and never touch
  :data:`_now` (tests pin both by monkeypatching it).
- **the advisor** (:func:`advise`): per-shard compress / decompress /
  leave recommendations with projected bytes and wallclock deltas,
  computed from the SAME run's measured codec ratio and per-byte
  read/cold/decode latencies — to the storage autopilot exactly what
  ``frontier.effective_contracts`` was to the (ε, δ) autotuner. No
  compressed observation in the run ⇒ an honest "no ratio measured"
  note instead of an invented one.

CLI: ``python -m sq_learn_tpu.obs storage <jsonl> [...] [--json]
[--advise] [--top N]`` (``make obs-storage``) renders the heat×bytes
table and per-surface accounting; exits 2 on artifacts with zero ``io``
records (the ``obs budget`` / ``obs control`` convention — no telemetry
must never read as healthy). Rotated sink segments
(``<path>.<n>.gz``, ``SQ_OBS_ROTATE_BYTES``) are discovered and read
automatically, oldest first, so last-wins stays correct.

Dependency-free on the collect/render path (stdlib json only, like
:mod:`~sq_learn_tpu.obs.schema`): safe with PYTHONPATH cleared while
the accelerator relay is wedged.
"""

import threading
import time

from .. import _knobs
from . import recorder as _recorder

__all__ = [
    "StorageLedger",
    "active",
    "advise",
    "collect",
    "flush",
    "main",
    "render",
    "surface_rollup",
    "surfaces_snapshot",
]

#: the ledger clock — module-level so the disabled-path test can count
#: reads by monkeypatching (instrumented paths call this ONLY when a
#: ledger is active)
_now = time.perf_counter

#: EWMA heat half-life: an access a minute old counts half of one now
_HALF_LIFE_S = 60.0

#: per-entry cumulative fields, in record order (zero values still emit
#: — a cumulative reader must see explicit zeros, not absent keys)
_INT_FIELDS = ("reads", "bytes_stored", "bytes_raw", "hits", "stalls",
               "retries", "quarantined", "spills", "disk_hits",
               "promotes", "misses")
_TIME_FIELDS = ("read_s", "crc_s", "decode_s", "cold_s", "stall_s")


class StorageLedger:
    """Run-scoped per-``(surface, store, shard)`` access aggregates.

    One instance per :class:`~sq_learn_tpu.obs.recorder.Recorder`,
    created lazily at the first instrumented access (:func:`active`).
    Thread-safe: shard reads land from prefetch worker threads.
    """

    #: lock-discipline contract (``sq_learn_tpu.analysis``): shared
    #: state is only written under ``self._lock``.
    _GUARDED_BY = {"_lock": ("_entries", "_dirty", "_flushes")}

    def __init__(self, rec):
        self._rec = rec
        self._lock = threading.Lock()
        self._entries = {}   # (surface, store, shard) -> aggregate dict
        self._dirty = set()  # keys touched since the last flush
        self._flushes = 0

    def _entry_locked(self, surface, store, shard, codec=None):
        key = (surface, store, shard)
        e = self._entries.get(key)
        if e is None:
            e = {f: 0 for f in _INT_FIELDS}
            e.update({f: 0.0 for f in _TIME_FIELDS})
            e.update(heat=0.0, heat_ts=None, codec=codec)
            self._entries[key] = e
        if codec is not None:
            e["codec"] = codec
        self._dirty.add(key)
        return e

    @staticmethod
    def _touch_heat(e, t):
        prev = e["heat_ts"]
        if prev is not None and t > prev:
            e["heat"] *= 0.5 ** ((t - prev) / _HALF_LIFE_S)
        e["heat"] += 1.0
        e["heat_ts"] = t

    def record_read(self, surface, store, shard, *, stored_bytes,
                    raw_bytes, read_s=0.0, crc_s=0.0, decode_s=0.0,
                    cold_s=0.0, retries=0, quarantined=0, codec=None):
        """One materialized shard read (oocore): bytes moved plus the
        decomposed latency of THIS access, retries/quarantine included.
        Safe from any thread; attribution is by key, not caller."""
        t = _now()
        with self._lock:
            e = self._entry_locked(str(surface), str(store),
                                   None if shard is None else int(shard),
                                   codec=codec)
            e["reads"] += 1
            e["bytes_stored"] += int(stored_bytes)
            e["bytes_raw"] += int(raw_bytes)
            e["read_s"] += float(read_s)
            e["crc_s"] += float(crc_s)
            e["decode_s"] += float(decode_s)
            e["cold_s"] += float(cold_s)
            e["retries"] += int(retries)
            e["quarantined"] += int(quarantined)
            self._touch_heat(e, t)

    def record_prefetch(self, store, shard, *, hit, stall_s=0.0):
        """Prefetch outcome for one consumed position: readahead hit or
        consumer stall (with the seconds the consumer waited). The
        matching :meth:`record_read` already landed from the worker."""
        with self._lock:
            e = self._entry_locked("oocore", str(store), int(shard))
            if hit:
                e["hits"] += 1
            else:
                e["stalls"] += 1
                e["stall_s"] += float(stall_s)

    def record_cache_event(self, surface, store, kind, *, stored_bytes=0,
                           raw_bytes=0, dur_s=0.0):
        """One serving-surface event: ``spill`` / ``disk_hit`` /
        ``promote`` / ``miss`` (feature cache) or ``hit`` / ``miss``
        (persistent compile cache). ``dur_s`` is the timed disk work."""
        t = _now()
        with self._lock:
            e = self._entry_locked(str(surface), str(store), None)
            if kind == "spill":
                e["spills"] += 1
                e["bytes_stored"] += int(stored_bytes)
                e["bytes_raw"] += int(raw_bytes)
            elif kind == "disk_hit":
                e["disk_hits"] += 1
                e["reads"] += 1
                e["bytes_raw"] += int(raw_bytes)
                e["read_s"] += float(dur_s)
            elif kind == "promote":
                e["promotes"] += 1
            elif kind == "hit":
                e["hits"] += 1
            else:
                e["misses"] += 1
                e["read_s"] += float(dur_s)
            self._touch_heat(e, t)

    def flush(self, reason="flush"):
        """Emit one cumulative ``io`` record per dirty key (last-wins
        reader semantics, like counters). Called at pass end and by the
        recorder's own close; O(dirty shards), never O(reads)."""
        t = _now()
        with self._lock:
            self._flushes += 1
            out = []
            for key in sorted(self._dirty,
                              key=lambda k: (k[0], k[1],
                                             -1 if k[2] is None else k[2])):
                e = self._entries[key]
                # decay the heat to the flush instant so records taken
                # at different times compare on one clock
                prev = e["heat_ts"]
                if prev is not None and t > prev:
                    e["heat"] *= 0.5 ** ((t - prev) / _HALF_LIFE_S)
                    e["heat_ts"] = t
                rec = {"type": "io", "surface": key[0], "store": key[1],
                       "shard": key[2]}
                for f in _INT_FIELDS:
                    rec[f] = int(e[f])
                for f in _TIME_FIELDS:
                    rec[f] = round(float(e[f]), 6)
                rec["serial"] = max(
                    0, e["reads"] - e["hits"] - e["stalls"]
                    - e["disk_hits"])
                rec["heat"] = round(float(e["heat"]), 6)
                if e["codec"] is not None:
                    rec["codec"] = str(e["codec"])
                rec["reason"] = str(reason)
                out.append(rec)
            self._dirty.clear()
        for rec in out:
            self._rec.record(rec, kind="io_records")
        return len(out)

    def surfaces(self):
        """Per-surface rollup for the recorder snapshot (gauge-style:
        resident/traffic vs the configured budgets and caps)."""
        with self._lock:
            agg = {}
            for (surface, _store, _shard), e in self._entries.items():
                a = agg.setdefault(surface, {
                    "entries": 0, "reads": 0, "bytes_stored": 0,
                    "bytes_raw": 0, "hits": 0, "stalls": 0, "spills": 0,
                    "disk_hits": 0, "misses": 0})
                a["entries"] += 1
                for f in ("reads", "bytes_stored", "bytes_raw", "hits",
                          "stalls", "spills", "disk_hits", "misses"):
                    a[f] += int(e[f])
        return agg


def _attach(rec):
    with _recorder._lock:
        led = getattr(rec, "_storage", None)
        if led is None:
            led = rec._storage = StorageLedger(rec)
    return led


def active():
    """The active run's :class:`StorageLedger`, or None when
    observability is off — the instrumented read paths' single check
    (one module-global read on the disabled path; the ledger is created
    lazily on the first enabled access)."""
    rec = _recorder._active
    if rec is None:
        return None
    led = rec._storage
    if led is None:
        led = _attach(rec)
    return led


def flush(reason="flush"):
    """Flush the active ledger's dirty aggregates as ``io`` records.
    No-op (0) when disabled or nothing was recorded."""
    rec = _recorder._active
    if rec is None:
        return 0
    led = rec._storage
    if led is None:
        return 0
    return led.flush(reason)


def surfaces_snapshot(rec):
    """The snapshot's per-surface resident-vs-budget gauges: ledger
    traffic rollups joined with the configured caps/budgets (knob reads
    only — no directory scans on the snapshot path; bytes-on-disk for
    the dir-backed surfaces renders in the CLI, which owns its I/O)."""
    led = getattr(rec, "_storage", None)
    agg = led.surfaces() if led is not None else {}
    oocore = dict(agg.get("oocore", {}))
    oocore["ram_budget_bytes"] = _knobs.get_int("SQ_OOC_RAM_BUDGET_BYTES")
    serve = dict(agg.get("serve_cache", {}))
    serve["disk_entry_cap"] = _knobs.get_int("SQ_SERVE_CACHE_DISK_ENTRIES")
    serve["dir"] = _knobs.get_raw("SQ_SERVE_CACHE_DIR") or None
    compile_ = dict(agg.get("compile_cache", {}))
    compile_["dir"] = _knobs.get_raw("SQ_COMPILE_CACHE_DIR") or None
    return {"oocore": oocore, "serve_cache": serve,
            "compile_cache": compile_}


# ---------------------------------------------------------------------------
# Reader half: collect / advise / render / CLI (stdlib-only, jax-free)
# ---------------------------------------------------------------------------


def collect(records):
    """Last-wins per-``(surface, store, shard)`` view of a run's ``io``
    records (they are cumulative, like counters — the newest line per
    key is the total)."""
    entries = {}
    for r in records:
        if not isinstance(r, dict) or r.get("type") != "io":
            continue
        key = (str(r.get("surface")), str(r.get("store")), r.get("shard"))
        entries[key] = r
    surfaces = {}
    for (surface, store, shard), r in sorted(
            entries.items(),
            key=lambda kv: (kv[0][0], kv[0][1],
                            -1 if kv[0][2] is None else kv[0][2])):
        surfaces.setdefault(surface, {}).setdefault(store, {})[shard] = r
    return {"surfaces": surfaces, "records": len(entries)}


def _num(r, field):
    v = r.get(field, 0)
    return float(v) if isinstance(v, (int, float)) else 0.0


def surface_rollup(view):
    """Per-surface totals of a collected view — the compact shape the
    ``obs report`` storage section embeds (the full per-shard table is
    this module's own CLI)."""
    out = {}
    for surface, per_store in (view.get("surfaces") or {}).items():
        a = out.setdefault(surface, {
            "stores": len(per_store), "entries": 0, "reads": 0,
            "bytes_stored": 0, "bytes_raw": 0, "hits": 0, "stalls": 0,
            "spills": 0, "disk_hits": 0, "misses": 0, "read_s": 0.0,
            "cold_s": 0.0})
        for shards in per_store.values():
            for r in shards.values():
                a["entries"] += 1
                for f in ("reads", "bytes_stored", "bytes_raw", "hits",
                          "stalls", "spills", "disk_hits", "misses"):
                    a[f] += int(_num(r, f))
                a["read_s"] += _num(r, "read_s")
                a["cold_s"] += _num(r, "cold_s")
        a["read_s"] = round(a["read_s"], 6)
        a["cold_s"] = round(a["cold_s"], 6)
    return out


def advise(view):
    """Placement recommendations from one run's measured ledger.

    The measured inputs, all from the run itself (never a model):

    - ``ratio`` — stored/raw over every compressed oocore read
      (``None`` when the run observed no compressed shard: the advisor
      then refuses to project compression instead of inventing a ratio);
    - per-store ``t_io`` — (read+cold) seconds per STORED byte: what a
      byte on that store's tier actually costs to move;
    - ``t_dec`` — decode seconds per RAW byte over compressed reads.

    Per raw shard, compressing changes bytes by ``raw×ratio − stored``
    and one access by that same delta × ``t_io`` plus ``raw × t_dec``;
    per compressed shard, decompressing is the mirror image. The
    recommendation is ``compress`` / ``decompress`` when the per-access
    wallclock delta is negative, ``leave`` otherwise; ``projected_*``
    fields scale by the run's observed access count, and shards rank by
    heat so the autopilot spends its migration budget hot-first.
    """
    stores = (view.get("surfaces") or {}).get("oocore", {})
    comp_stored = comp_raw = comp_dec_s = 0.0
    t_io_store = {}
    for store, shards in stores.items():
        io_s = stored_b = 0.0
        for r in shards.values():
            io_s += _num(r, "read_s") + _num(r, "cold_s")
            stored_b += _num(r, "bytes_stored")
            if r.get("codec") not in (None, "none"):
                comp_stored += _num(r, "bytes_stored")
                comp_raw += _num(r, "bytes_raw")
                comp_dec_s += _num(r, "decode_s")
        if stored_b > 0:
            t_io_store[store] = io_s / stored_b
    ratio = (comp_stored / comp_raw) if comp_raw > 0 else None
    t_dec = (comp_dec_s / comp_raw) if comp_raw > 0 else 0.0
    notes = []
    if ratio is None:
        notes.append("no compressed shard observed this run: codec ratio "
                     "unmeasured, compression is not projected")
    shards_out = []
    for store, shards in stores.items():
        t_io = t_io_store.get(store, 0.0)
        for shard, r in shards.items():
            if shard is None:
                continue
            reads = _num(r, "reads")
            raw = _num(r, "bytes_raw") / max(reads, 1.0)
            stored = _num(r, "bytes_stored") / max(reads, 1.0)
            compressed = r.get("codec") not in (None, "none")
            action, dbytes, dt_access = "leave", 0.0, 0.0
            if not compressed and ratio is not None:
                dbytes = raw * ratio - stored
                dt_access = dbytes * t_io + raw * t_dec
                if dt_access < 0:
                    action = "compress"
            elif compressed:
                dec_s = _num(r, "decode_s") / max(reads, 1.0)
                dbytes = raw - stored
                dt_access = dbytes * t_io - dec_s
                if dt_access < 0:
                    action = "decompress"
                else:
                    dbytes, dt_access = 0.0, 0.0
            if action == "leave":
                dbytes = dt_access = 0.0
            shards_out.append({
                "surface": "oocore", "store": store, "shard": shard,
                "action": action, "heat": _num(r, "heat"),
                "reads": int(reads),
                "bytes_raw": int(_num(r, "bytes_raw")),
                "bytes_stored": int(_num(r, "bytes_stored")),
                "projected_bytes_delta": int(round(dbytes)),
                "projected_wallclock_delta_s": round(
                    dt_access * reads, 6)})
    shards_out.sort(key=lambda s: -s["heat"])
    return {"ratio": ratio, "t_dec_per_byte": t_dec,
            "t_io_per_byte": t_io_store, "shards": shards_out,
            "notes": notes}


def _fmt_bytes(n):
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0


def render(view, advice=None, top=20):
    """Human view: per-surface accounting, the heat×bytes shard table
    (hottest first), and — when :func:`advise` ran — the placement
    recommendations."""
    lines = []
    out = lines.append
    surfaces = view.get("surfaces") or {}
    if not surfaces:
        return "  (no io records)"
    for surface in sorted(surfaces):
        per_store = surfaces[surface]
        n_entries = sum(len(s) for s in per_store.values())
        tot = {}
        for shards in per_store.values():
            for r in shards.values():
                for f in _INT_FIELDS + _TIME_FIELDS:
                    tot[f] = tot.get(f, 0) + _num(r, f)
        out(f"  -- {surface}: {len(per_store)} store(s), "
            f"{n_entries} ledger entr{'y' if n_entries == 1 else 'ies'} --")
        out(f"    reads={int(tot.get('reads', 0))} "
            f"stored={_fmt_bytes(tot.get('bytes_stored', 0))} "
            f"raw={_fmt_bytes(tot.get('bytes_raw', 0))} "
            f"read={tot.get('read_s', 0.0):.3f}s "
            f"crc={tot.get('crc_s', 0.0):.3f}s "
            f"decode={tot.get('decode_s', 0.0):.3f}s "
            f"cold={tot.get('cold_s', 0.0):.3f}s")
        if surface == "oocore":
            out(f"    prefetch: hits={int(tot.get('hits', 0))} "
                f"stalls={int(tot.get('stalls', 0))} "
                f"stall={tot.get('stall_s', 0.0):.3f}s "
                f"retries={int(tot.get('retries', 0))} "
                f"quarantined={int(tot.get('quarantined', 0))}")
        else:
            out(f"    spills={int(tot.get('spills', 0))} "
                f"disk_hits={int(tot.get('disk_hits', 0))} "
                f"promotes={int(tot.get('promotes', 0))} "
                f"hits={int(tot.get('hits', 0))} "
                f"misses={int(tot.get('misses', 0))}")
    ranked = []
    for surface, per_store in surfaces.items():
        for store, shards in per_store.items():
            for shard, r in shards.items():
                if shard is not None:
                    ranked.append((surface, store, shard, r))
    ranked.sort(key=lambda x: -_num(x[3], "heat"))
    if ranked:
        out(f"  -- hottest shards (top {min(top, len(ranked))} of "
            f"{len(ranked)}) --")
        out("    surface  store      shard  heat     reads  stored"
            "     raw        read_s   cold_s")
        for surface, store, shard, r in ranked[:top]:
            out(f"    {surface:<8} {store[:10]:<10} {shard:>5}  "
                f"{_num(r, 'heat'):<7.3f}  {int(_num(r, 'reads')):<5} "
                f"{_fmt_bytes(_num(r, 'bytes_stored')):<9} "
                f"{_fmt_bytes(_num(r, 'bytes_raw')):<9}  "
                f"{_num(r, 'read_s'):<7.4f}  {_num(r, 'cold_s'):<7.4f}")
    if advice is not None:
        ratio = advice.get("ratio")
        out("  -- tiering advice --")
        out(f"    measured codec ratio (stored/raw): "
            f"{'unmeasured' if ratio is None else f'{ratio:.3f}'}")
        for note in advice.get("notes") or []:
            out(f"    note: {note}")
        moved = [s for s in advice.get("shards") or []
                 if s["action"] != "leave"]
        out(f"    recommendations: {len(moved)} move(s), "
            f"{len(advice.get('shards') or []) - len(moved)} leave")
        for s in moved[:top]:
            out(f"    {s['action']:<10} {s['store'][:10]:<10} "
                f"shard {s['shard']:>4}  heat={s['heat']:.3f}  "
                f"Δbytes={_fmt_bytes(s['projected_bytes_delta'])}/read  "
                f"Δwall={s['projected_wallclock_delta_s']:+.4f}s/run")
    return "\n".join(lines)


def _with_segments(paths):
    """Expand each path with its rotated gzip segments
    (``<path>.<n>.gz``, oldest first, live file last) so last-wins
    collect semantics survive ``SQ_OBS_ROTATE_BYTES`` rotation."""
    import os

    out = []
    for p in paths:
        segs = []
        n = 1
        while os.path.exists(f"{p}.{n}.gz"):
            segs.append(f"{p}.{n}.gz")
            n += 1
        out.extend(segs)
        out.append(p)
    return out


def main(argv):
    """``storage <jsonl> [more.jsonl ...] [--json] [--advise]
    [--top N]`` — render the storage-plane ledger of one or more obs
    JSONL artifacts; exits 2 when the artifacts carry ZERO ``io``
    records ("no telemetry" must never read as "healthy storage" in
    CI), 0 otherwise."""
    import json
    import sys

    as_json = "--json" in argv
    with_advice = "--advise" in argv
    top = 20
    paths = []
    it = iter(a for a in argv if a not in ("--json", "--advise"))
    for a in it:
        if a == "--top":
            try:
                top = int(next(it))
            except (StopIteration, ValueError):
                print("--top needs an integer", file=sys.stderr)
                return 2
        else:
            paths.append(a)
    if not paths:
        print("usage: python -m sq_learn_tpu.obs storage <jsonl> "
              "[more.jsonl ...] [--json] [--advise] [--top N]",
              file=sys.stderr)
        return 2
    from .trace import load_jsonl

    records = []
    for p in _with_segments(paths):
        records.extend(load_jsonl(p))
    view = collect(records)
    if not view["records"]:
        if as_json:
            print(json.dumps(dict(view, error="no io telemetry")))
        print(f"no storage telemetry: zero io records in "
              f"{', '.join(paths)}", file=sys.stderr)
        return 2
    advice = advise(view) if with_advice else None
    if as_json:
        doc = dict(view)
        if advice is not None:
            doc["advice"] = advice
        print(json.dumps(doc))
    else:
        print("== storage-plane ledger (per-shard heat/latency) ==")
        print(render(view, advice=advice, top=top))
    return 0
