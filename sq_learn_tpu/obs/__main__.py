"""CLI dispatcher:
``python -m sq_learn_tpu.obs
<trace|report|regress|audit|frontier|budget|control|fleet|storage>``.

- ``trace <jsonl> [...] [-o out.json]`` — render a run's JSONL into
  Chrome trace-event JSON (Perfetto-viewable), merging multiple files
  onto pid lanes (:mod:`~sq_learn_tpu.obs.trace`).
- ``report <jsonl> [...] [--json]`` — the human view of a run: top spans
  by self-time, compiles vs budget, transfer bytes, quantum-ledger vs
  xla-cost table, guarantee audit, tradeoff frontier, fault/breaker
  timeline (:mod:`~sq_learn_tpu.obs.report`).
- ``regress <record-file> [--root DIR] [--no-exit-code] | --selftest``
  — tolerance-banded perf verdicts against the committed bench
  trajectory (:mod:`~sq_learn_tpu.obs.regress`).
- ``audit <jsonl> [...] [--json] [--confidence C]`` — Clopper–Pearson
  audit of the run's (ε, δ) guarantee records; exits 1 on any flagged
  site (:mod:`~sq_learn_tpu.obs.guarantees`).
- ``frontier <jsonl> [...] [--json]`` — the accuracy-vs-theoretical-
  quantum-runtime table with its Pareto frontier, plus the per-tenant
  effective-(ε, δ) table from live guarantee draws
  (:mod:`~sq_learn_tpu.obs.frontier`).
- ``budget <jsonl> [...] [--json]`` — the per-tenant error-budget
  table (rolling-window latency-SLO and statistical burn rates); exits
  1 when any tenant's multi-window burn alert fired, 2 when the
  artifacts carry zero budget records
  (:mod:`~sq_learn_tpu.obs.budget`).
- ``control <jsonl> [...] [--json]`` — the serving control plane's
  decision history (one line per autotuner evaluation: inputs consumed,
  action taken, predicted vs realized effect); exits 2 when the
  artifacts carry zero control records
  (:mod:`~sq_learn_tpu.obs.control`).
- ``fleet <run_dir | shard.jsonl ...> [--json] [-o trace.json]
  [--merged merged.jsonl]`` — merge an elastic run's per-process obs
  shards into one clock-aligned mesh timeline: per-host rollups,
  per-generation detect→shrink→re-init→resume critical paths, and the
  committed-window reconciliation; exits 1 when the commit ledger
  disagrees with itself (:mod:`~sq_learn_tpu.obs.fleet`).
- ``storage <jsonl> [...] [--json] [--advise] [--top N]`` — the
  storage-plane ledger: per-surface accounting and the per-shard
  heat×bytes table from the run's ``io`` records, with ``--advise``
  adding compress/decompress/leave placement recommendations projected
  from the run's own measured codec ratio and latencies; exits 2 when
  the artifacts carry zero ``io`` records
  (:mod:`~sq_learn_tpu.obs.storage`).

All subcommands are dependency-free file tools (no jax import on the
comparison/render paths), safe to run with PYTHONPATH cleared while the
accelerator relay is wedged.
"""

import sys


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    cmd, rest = argv[0], argv[1:]
    if cmd == "trace":
        from .trace import main as run
    elif cmd == "report":
        from .report import main as run
    elif cmd == "regress":
        from .regress import main as run
    elif cmd == "audit":
        from .guarantees import main as run
    elif cmd == "frontier":
        from .frontier import main as run
    elif cmd == "budget":
        from .budget import main as run
    elif cmd == "control":
        from .control import main as run
    elif cmd == "fleet":
        from .fleet import main as run
    elif cmd == "storage":
        from .storage import main as run
    else:
        print(f"unknown subcommand {cmd!r} (expected trace, report, "
              "regress, audit, frontier, budget, control, fleet, or "
              "storage)", file=sys.stderr)
        return 2
    return run(rest)


if __name__ == "__main__":
    sys.exit(main())
