"""Observability smoke: tiny instrumented fits + JSONL schema validation.

``make obs-smoke`` runs this module: a streamed qPCA Gram fit (streaming
counters + retracing watchdog), a quantum top-k extraction (nonzero
tomography shots in the ledger), a tiny served tenant with a
declared SLO (per-tenant ``slo`` + error-budget ``budget`` records, plus
the control plane's close-time ``control`` records), and a
fault-injected shrink of the elastic mesh's in-process simulator
(``elastic`` transition records — including the v10 ``window`` /
``commit`` fold-ledger events — plus host-targeted ``fault`` records)
under an active recorder carrying a fleet identity (schema v10: every
record gains the ``fleet`` envelope, a ``clock`` sample lands, and
:mod:`sq_learn_tpu.obs.fleet` must reconcile the artifact's commit
ledger), plus a tiny shard-store pass feeding the storage-plane ledger
(schema v11: per-shard ``io`` records land at flush, cumulative like
counters — :mod:`sq_learn_tpu.obs.storage`), then validates the emitted
JSONL against :mod:`sq_learn_tpu.obs.schema` (legacy v1–v10 records
must keep validating) and asserts the run artifact carries the signals
the layer exists for. Exit code 0 = contract holds; 1 = schema or
content violation (printed).

Pins the CPU backend in-process first (the documented wedge-proof
override, CLAUDE.md) — a health check must never hang on the thing whose
health it reports.
"""

import json
import os
import sys
from .. import _knobs


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from . import disable, enable, ledger, set_fleet, watchdog
    from .schema import validate_jsonl

    path = _knobs.get_raw("SQ_OBS_PATH", "/tmp/sq_obs_smoke.jsonl")
    open(path, "w").close()  # truncate any previous smoke artifact
    enable(path)  # fresh run: resets the watchdog, reopens the sink
    # v10 contract: a fleet identity stamps every subsequent record with
    # the envelope the mesh-timeline merge correlates shards by
    set_fleet("obs-smoke-fleet", host="sim")

    rng = np.random.default_rng(0)
    X = rng.normal(size=(2048, 64)).astype(np.float32)

    from ..models import QPCA

    # streamed Gram-route fit: small tile cap forces a real tile walk
    os.environ["SQ_STREAM_TILE_BYTES"] = str(64 * 1024)
    try:
        QPCA(n_components=4, svd_solver="full", random_state=0,
             ingest="streamed").fit(X)
    finally:
        os.environ.pop("SQ_STREAM_TILE_BYTES", None)

    # quantum extraction: tomography shots + PE queries land in the
    # ledger, and the eager estimators emit (ε, δ) guarantee draws
    QPCA(n_components=4, svd_solver="full", random_state=0).fit(
        X[:256], estimate_all=True, theta_major=1.0, eps=0.1, delta=0.5,
        true_tomography=False)

    # thesis artifact: a tiny δ-sweep point joining measured accuracy
    # with the theoretical quantum runtime its budget buys (the cost
    # model's output consumed by the frontier, not just unit tests)
    from . import frontier, guarantees
    from ..models import QKMeans

    qk = QKMeans(n_clusters=4, n_init=1, delta=0.5,
                 true_distance_estimate=False, random_state=0).fit(X[:512])
    quantum, classical = qk.quantum_runtime_model(*X[:512].shape)
    frontier.record_tradeoff(
        "smoke_qkmeans_delta", 0.5, accuracy=-float(qk.inertia_),
        accuracy_metric="neg_inertia",
        q_runtime=float(np.ravel(quantum)[0]), c_runtime=float(classical))

    # v6 contract: a tiny serving run with a declared tenant SLO — the
    # dispatcher's close must emit the per-tenant slo record and the
    # per-tenant error-budget evaluations (obs.budget)
    from ..serving import MicroBatchDispatcher, ModelRegistry

    sreg = ModelRegistry()
    sreg.register("smoke_tenant", qk, slo_p50_ms=5e3, slo_p99_ms=1e4)
    sd = MicroBatchDispatcher(sreg, background=False)
    for i in range(4):
        sd.serve("smoke_tenant", "predict", X[: 4 + i])
    sd.close()

    # v9 contract: a fault-injected shrink of the elastic mesh's
    # in-process simulator lands the elastic transition records
    # (world_up → host_fail → shrink → resume → done) and the fault
    # records carry their host targets — the timeline of a survived
    # host death is in the artifact, not just the return value
    from ..oocore.store import ArraySource
    from ..parallel import elastic
    from ..resilience import faults

    esrc = ArraySource(
        np.asarray(rng.normal(size=(96, 5)), np.float64), shard_rows=8)
    faults.arm("host_stall:window=0,host=1,times=1,s=0.0;"
               "host_fail:window=1,host=2,times=1")
    try:
        eres = elastic.elastic_fit_local(esrc, 3, n_hosts=3, seed=0,
                                         epochs=1, window=4)
    finally:
        faults.disarm()

    # v10 contract: one clock sample through the elastic plane's
    # emitter — the record type obs.fleet aligns mesh timelines with
    import time as _time

    _now = _time.time()
    elastic._emit_clock("w1", _now - 1e-3, _now, 0, "hb")

    # v11 contract: a tiny shard-store pass feeds the storage-plane
    # ledger — every read lands in the per-(store, shard) aggregates and
    # the pass-end flush emits cumulative io records (O(#shards), never
    # O(#reads))
    import tempfile

    from . import storage as obs_storage
    from ..oocore import store_from_array

    stmp = tempfile.mkdtemp(prefix="sq_obs_smoke_store_")
    sstore = store_from_array(os.path.join(stmp, "store"),
                              np.asarray(X[:256], np.float32),
                              shard_bytes=16 * 1024)
    for i in range(sstore.n_shards):
        sstore.read_shard(i)
        sstore.read_shard(i)  # second touch: reads must aggregate
    io_flushed = obs_storage.flush("pass_end")

    report = watchdog.report()
    totals = ledger.totals()
    audit = guarantees.audit()
    rec = disable()

    summary = validate_jsonl(path)
    failures = list(summary["errors"])
    if totals["queries"].get("tomography_shots", 0) <= 0:
        failures.append("ledger has no tomography shots")
    if rec.counters.get("streaming.transfer_bytes", 0) <= 0:
        failures.append("no streamed transfer bytes recorded")
    # v2 contract: the instrumented streamed kernels record their
    # compilation cost, and every line carries the schema_version field
    # (the validator enforces the latter; re-assert the former here)
    if summary["by_type"].get("xla_cost", 0) <= 0:
        failures.append("no xla_cost records from the instrumented "
                        "streamed kernels")
    else:
        costs = [r for r in rec.xla_cost_records
                 if isinstance(r.get("flops"), (int, float))]
        if not costs:
            failures.append("xla_cost records carry no finite flops "
                            "(cost_analysis degraded on this jax?)")
    gram = report.get("streaming.gram_colsum")
    if gram is None:
        failures.append("watchdog never observed the streamed Gram kernel")
    elif gram["over_budget"]:
        failures.append(f"streamed Gram kernel over compile budget: {gram}")
    # v3 contract: the eager quantum estimators audit their (ε, δ)
    # guarantees and the δ-sweep point lands as a schema-valid tradeoff
    # record with a finite theoretical quantum runtime
    if summary["by_type"].get("guarantee", 0) <= 0:
        failures.append("no guarantee records from the eager estimators")
    flagged = sorted(s for s, a in audit.items() if a["flagged"])
    if flagged:
        failures.append(f"guarantee audit flagged correct routines: "
                        f"{flagged}")
    if summary["by_type"].get("tradeoff", 0) <= 0:
        failures.append("no tradeoff records from the smoke sweep point")
    elif not any(isinstance(t.get("q_runtime"), (int, float))
                 for t in rec.tradeoff_records):
        failures.append("tradeoff records carry no finite theoretical "
                        "quantum runtime")
    # v6 contract: the serving leg's per-tenant error budgets landed,
    # the tenant's slo record carries its declared targets, and legacy
    # schema versions (v1-v6 files) still validate
    if summary["by_type"].get("budget", 0) <= 0:
        failures.append("no budget records from the serving leg")
    if not any(r.get("tenant") == "smoke_tenant" for r in rec.slo_records):
        failures.append("no per-tenant slo record from the serving leg")
    if any(a for a in rec.alert_records):
        failures.append(f"burn alert fired under a generous declared "
                        f"SLO: {rec.alert_records}")
    # v8 contract: the serving close runs the control plane's final
    # evaluation — a quiet controller still lands records (a plan plus
    # a hold per tenant: silence is indistinguishable from death), every
    # budget line carries the monotonic emit seq, and legacy v7 budget
    # records (no seq yet) still validate below
    if summary["by_type"].get("control", 0) <= 0:
        failures.append("no control records from the serving close")
    if not any(r.get("tenant") == "smoke_tenant"
               and r.get("action") == "plan"
               for r in rec.control_records):
        failures.append("the controller never planned the served tenant")
    if not all(isinstance(r.get("seq"), int)
               for r in rec.budget_records):
        failures.append("a budget record landed without its emit seq")
    # v9 contract: the elastic leg survived exactly one host death, the
    # transition records landed schema-valid (validate_jsonl above saw
    # them), and the injected faults carry their host targets
    if eres["shrinks"] != 1 or eres["generation"] != 1:
        failures.append(f"elastic sim did not shrink exactly once: "
                        f"{eres['shrinks']}/{eres['generation']}")
    e_events = [r.get("event") for r in rec.elastic_records]
    for ev in ("world_up", "host_stall", "host_fail", "shrink",
               "resume", "done", "window", "commit"):
        if ev not in e_events:
            failures.append(f"no elastic {ev} record from the sim leg")
    if not any(r.get("kind") in ("host_fail", "host_stall")
               and isinstance(r.get("host"), int)
               for r in rec.fault_events):
        failures.append("no host-targeted fault records from the "
                        "elastic leg")
    # v10 contract: every elastic record carries the fleet envelope
    # (run_id + live generation), a clock sample landed, and the fleet
    # merge reconciles the artifact's commit ledger against itself
    if summary["by_type"].get("clock", 0) <= 0:
        failures.append("no clock records in the artifact")
    if not any(isinstance(r.get("fleet"), dict)
               and r["fleet"].get("run_id") == "obs-smoke-fleet"
               and r["fleet"].get("gen") == 1
               for r in rec.elastic_records):
        failures.append("no elastic record carries the fleet envelope "
                        "with the post-shrink generation")
    from .fleet import summarize as fleet_summarize

    fsum = fleet_summarize([path])
    if fsum["run_ids"] != ["obs-smoke-fleet"]:
        failures.append(f"fleet merge lost the run_id: {fsum['run_ids']}")
    frc = fsum["reconciliation"]
    if not frc["ok"] or frc["windows"] != 3:
        failures.append(f"fleet commit-ledger reconciliation broken: "
                        f"{frc}")
    # v11 contract: the shard-store pass landed one cumulative io record
    # per shard (pre-aggregated — two touches per shard, one line), and
    # the storage CLI's collect/advise run over the artifact
    if io_flushed != sstore.n_shards:
        failures.append(f"storage flush emitted {io_flushed} io records "
                        f"for {sstore.n_shards} shards")
    if summary["by_type"].get("io", 0) < sstore.n_shards:
        failures.append(f"artifact carries "
                        f"{summary['by_type'].get('io', 0)} io records; "
                        f"expected >= {sstore.n_shards}")
    from . import storage as _st

    sview = _st.collect(rec.io_records)
    ooc_led = sview["surfaces"].get("oocore", {}).get(
        sstore.fingerprint, {})
    if sorted(ooc_led) != list(range(sstore.n_shards)):
        failures.append(f"io records missed shards: {sorted(ooc_led)}")
    elif not all(r.get("reads") == 2 for r in ooc_led.values()):
        failures.append("io records did not aggregate both touches "
                        "per shard")
    if _st.advise(sview)["shards"] == []:
        failures.append("storage advisor returned no per-shard rows")
    from .schema import validate_record

    legacy = [
        {"v": 1, "ts": 0.0, "type": "counter", "name": "x", "value": 1,
         "delta": 1},
        {"v": 5, "schema_version": 5, "ts": 0.0, "type": "slo",
         "site": "s", "requests": 1, "p50_ms": 1.0, "p99_ms": 2.0,
         "qps": 3.0, "batch_occupancy": 0.5, "degraded": 0,
         "violated": False},
        {"v": 6, "schema_version": 6, "ts": 0.0, "type": "budget",
         "tenant": "t", "window_s": 60.0, "slo_burn": 0.1,
         "stat_burn": None, "cp_lower_bound": None, "burn_rate": 0.2,
         "alerting": False},
        # v7 (pre-control-plane): budget/alert lines carried no emit seq
        {"v": 7, "schema_version": 7, "ts": 0.0, "type": "alert",
         "tenant": "t", "kind": "slo_burn",
         "burn_rates": {"60": 2.5, "600": 2.1}, "threshold": 2.0},
        # v8 (pre-elastic): the control plane's record type
        {"v": 8, "schema_version": 8, "ts": 0.0, "type": "control",
         "tenant": "t", "action": "hold", "seq": 0, "level": 0,
         "inputs": {"burn": 0.1}, "decision": {"route": "device"}},
        # v9 (pre-fleet): elastic records without the fleet envelope,
        # the clock type, or the window/commit events
        {"v": 9, "schema_version": 9, "ts": 0.0, "type": "elastic",
         "event": "host_fail", "generation": 0, "n_hosts": 3,
         "failed_host": 2, "window": 3, "detect_s": 0.5},
        # v10 (pre-storage-ledger): fleet-enveloped clock samples, no io
        # record type yet
        {"v": 10, "schema_version": 10, "ts": 0.0, "type": "clock",
         "peer": "w1", "sent_ts": 0.0, "recv_ts": 0.001, "via": "hb",
         "generation": 0,
         "fleet": {"run_id": "r", "host": "w1", "gen": 0, "pid": 1}},
    ]
    for r_ in legacy:
        errs = validate_record(r_)
        if errs:
            failures.append(f"legacy schema version v{r_['v']} "
                            f"rejected: {errs}")

    print(json.dumps({
        "obs_smoke": "fail" if failures else "ok",
        "path": path,
        "jsonl": summary["by_type"],
        "ledger_totals": totals,
        "watchdog": report,
        "audit_sites": {s: [a["violations"], a["trials"]]
                        for s, a in sorted(audit.items())},
        "budget_tenants": sorted({r.get("tenant")
                                  for r in rec.budget_records}),
        "errors": failures,
    }))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
