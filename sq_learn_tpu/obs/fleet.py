"""Fleet observability: merge per-process obs shards into one mesh
timeline (PR 19).

The elastic plane (:mod:`sq_learn_tpu.parallel.elastic`) runs N worker
processes plus an out-of-mesh coordinator, each with its own recorder
and JSONL sink. This module is the other half of that contract: given
the per-process shards of ONE run (correlated by the coordinator-minted
``fleet.run_id`` envelope, schema v10), it

- estimates **per-host clock offsets** from the ``clock`` records the
  elastic plane piggybacks on its existing KV exchanges (heartbeats,
  generation manifests, progress commits). Each sample pairs a peer's
  send timestamp with the local receive timestamp, so
  ``recv − sent ≥ offset(local − peer)`` with equality at zero network
  delay: the MINIMUM over samples is the tightest upper bound, and when
  both directions exist the midpoint ``(min_ab − min_ba) / 2`` cancels
  the symmetric part of the delay (classic NTP-style estimation, no
  extra messages). Hosts align to the coordinator's clock through a
  BFS over the pairwise sample graph;
- **merges** the shards into one causally-ordered timeline: every
  record gains ``_host`` and an aligned ``ts_fleet``, and the merge is
  sorted by it (monotone by construction);
- decomposes each shrink's **critical path**
  (detect → shrink → re-init → resume) from the merged elastic events;
- computes **per-host rollups** (record/span/counter totals); and
- **reconciles** the commit ledger: node 0 emits one ``commit`` event
  per committed window, every host emits a ``window`` event per folded
  window — the merge must contain each committed window exactly once,
  with no gaps, or the artifact disagrees with the fold ledger.

Dependency-free by design (stdlib only, like
:mod:`~sq_learn_tpu.obs.schema`): the CLI runs with PYTHONPATH cleared
under a wedged accelerator relay, so it must never import jax.

CLI: ``python -m sq_learn_tpu.obs fleet <run_dir | shard.jsonl ...>
[--json] [-o trace.json] [--merged merged.jsonl]`` — exits 1 when the
commit-ledger reconciliation fails.
"""

import json
import os

from .trace import load_jsonl, to_chrome_trace

__all__ = ["load_shards", "clock_offsets", "merge", "critical_path",
           "rollups", "reconcile", "summarize", "render",
           "write_merged", "main"]

#: the reference host every offset is stated against (the coordinator
#: lives outside the mesh and survives every generation)
COORD_HOST = "coord"


def _shard_host(path, records):
    """Stable host label for one shard: the fleet envelope wins, the
    ``obs.<host>.jsonl`` filename convention is the fallback."""
    for rec in records:
        fl = rec.get("fleet")
        if isinstance(fl, dict) and isinstance(fl.get("host"), str):
            return fl["host"]
    name = os.path.basename(str(path))
    if name.endswith(".gz"):
        name = name[:-len(".gz")]
    if name.startswith("obs.") and name.endswith(".jsonl"):
        return name[len("obs."):-len(".jsonl")]
    return name


def load_shards(source):
    """Load the per-process shards of one fleet run.

    ``source`` is either a run directory — every ``obs.*.jsonl`` /
    ``obs.*.jsonl.gz`` in it is a shard — or an iterable of shard
    paths. Returns ``[(host_label, records), ...]`` sorted by label
    (coordinator first).
    """
    if isinstance(source, (str, os.PathLike)) and os.path.isdir(source):
        paths = sorted(
            os.path.join(source, n) for n in os.listdir(source)
            if n.startswith("obs.")
            and (n.endswith(".jsonl") or n.endswith(".jsonl.gz")))
    elif isinstance(source, (str, os.PathLike)):
        paths = [source]
    else:
        paths = list(source)
    shards = []
    for p in paths:
        records = load_jsonl(p)
        if records:
            shards.append((_shard_host(p, records), records))
    shards.sort(key=lambda hr: (hr[0] != COORD_HOST, hr[0]))
    return shards


def run_ids(shards):
    """Every distinct fleet run_id present (one for a coherent run)."""
    ids = set()
    for _, records in shards:
        for rec in records:
            fl = rec.get("fleet")
            if isinstance(fl, dict) and isinstance(fl.get("run_id"), str):
                ids.add(fl["run_id"])
    return sorted(ids)


def clock_offsets(shards, reference=None):
    """Per-host clock offsets (seconds, ``host_clock − ref_clock``).

    Built from the shards' ``clock`` records: host H recording
    ``{peer: P, sent_ts, recv_ts}`` bounds ``offset(H − P) ≤
    recv_ts − sent_ts`` (the message can only age in flight), so the
    per-(H, P) minimum is the tightest one-way bound and opposite
    minima average into the midpoint estimate. Offsets propagate from
    ``reference`` (default: the coordinator if present, else the first
    host) by BFS; unreachable hosts get offset 0.0 — an unaligned lane
    beats a dropped one.
    """
    hosts = [h for h, _ in shards]
    if not hosts:
        return {}
    if reference is None:
        reference = COORD_HOST if COORD_HOST in hosts else hosts[0]
    # min over samples of (recv - sent) per directed pair (obs, peer)
    one_way = {}
    for host, records in shards:
        for rec in records:
            if rec.get("type") != "clock":
                continue
            sent, recv = rec.get("sent_ts"), rec.get("recv_ts")
            if not isinstance(sent, (int, float)) \
                    or not isinstance(recv, (int, float)):
                continue
            peer = str(rec.get("peer"))
            key = (host, peer)
            d = recv - sent
            if key not in one_way or d < one_way[key]:
                one_way[key] = d

    def pair_offset(a, b):
        """offset(a − b), or None when no samples link the two."""
        ab = one_way.get((a, b))  # bound on offset(a − b)
        ba = one_way.get((b, a))  # bound on offset(b − a)
        if ab is not None and ba is not None:
            return (ab - ba) / 2.0
        if ab is not None:
            return ab
        if ba is not None:
            return -ba
        return None

    offsets = {reference: 0.0}
    frontier = [reference]
    while frontier:
        nxt = []
        for a in frontier:
            for b in hosts:
                if b in offsets:
                    continue
                rel = pair_offset(b, a)
                if rel is not None:
                    offsets[b] = offsets[a] + rel
                    nxt.append(b)
        frontier = nxt
    for h in hosts:
        offsets.setdefault(h, 0.0)
    return offsets


def merge(shards, offsets=None):
    """One causally-ordered timeline from per-host shards.

    Each record is shallow-copied with ``_host`` (its shard's label)
    and ``ts_fleet`` (its ``ts`` minus the host's clock offset, i.e.
    restated on the reference clock), then the merge is sorted by
    ``(ts_fleet, host, file order)`` — monotone in ``ts_fleet`` by
    construction, deterministic under timestamp collisions.
    """
    if offsets is None:
        offsets = clock_offsets(shards)
    out = []
    for host, records in shards:
        off = offsets.get(host, 0.0)
        for idx, rec in enumerate(records):
            ts = rec.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            merged = dict(rec)
            merged["_host"] = host
            merged["ts_fleet"] = round(ts - off, 6)
            out.append((merged["ts_fleet"], host, idx, merged))
    out.sort(key=lambda t: t[:3])
    return [m for _, _, _, m in out]


def critical_path(merged):
    """Per-generation detect → shrink → re-init → resume decomposition.

    For every generation ``g ≥ 1`` reached by a shrink, reads the
    merged (clock-aligned) elastic events:

    - ``detect_s``: the slowest surviving host's lease-layer detection
      latency (its ``host_fail`` record's own measurement);
    - ``shrink_s``: first ``host_fail`` → the coordinator's ``shrink``
      (failure files read, new manifest written);
    - ``reinit_s``: ``shrink`` → last ``world_up`` at g (KV service up,
      collectives re-initialized, leases re-armed);
    - ``resume_s``: ``world_up`` → last ``resume`` at g (checkpoint
      loaded, cursor restated);
    - ``finish_s``: ``resume`` → last ``done`` at g.

    Segments whose anchor events are missing are None; present ones are
    clamped at 0 (clock alignment is an estimate).
    """
    ev = {}
    for rec in merged:
        if rec.get("type") != "elastic":
            continue
        g = rec.get("generation")
        if not isinstance(g, int) or isinstance(g, bool):
            continue
        ev.setdefault((rec.get("event"), g), []).append(rec)

    def _ts(event, g, pick):
        recs = ev.get((event, g))
        if not recs:
            return None
        return pick(r["ts_fleet"] for r in recs)

    gens = sorted({g for (e, g) in ev if e == "world_up" and g > 0})
    paths = []
    for g in gens:
        t_fail = _ts("host_fail", g - 1, min)
        t_shrink = _ts("shrink", g, min)
        t_up = _ts("world_up", g, max)
        t_resume = _ts("resume", g, max)
        t_done = _ts("done", g, max)
        detect = [r.get("detect_s") for r in ev.get(("host_fail", g - 1), [])
                  if isinstance(r.get("detect_s"), (int, float))]

        def seg(a, b):
            if a is None or b is None:
                return None
            return round(max(0.0, b - a), 6)

        path = {
            "generation": g,
            "detect_s": round(max(detect), 6) if detect else None,
            "shrink_s": seg(t_fail, t_shrink),
            "reinit_s": seg(t_shrink, t_up),
            "resume_s": seg(t_up, t_resume),
            "finish_s": seg(t_resume, t_done),
            "total_s": seg(t_fail, t_done),
        }
        paths.append(path)
    return paths


def rollups(shards):
    """Per-host record/span/counter totals: ``{host: {records,
    by_type, span_s, spans, counters}}`` where ``counters`` holds each
    counter's final cumulative value."""
    out = {}
    for host, records in shards:
        by_type = {}
        span_s = 0.0
        n_spans = 0
        counters = {}
        for rec in records:
            t = rec.get("type")
            by_type[t] = by_type.get(t, 0) + 1
            if t == "span" and isinstance(rec.get("dur_s"), (int, float)):
                span_s += rec["dur_s"]
                n_spans += 1
            elif t == "counter" and isinstance(rec.get("name"), str) \
                    and isinstance(rec.get("value"), (int, float)):
                counters[rec["name"]] = rec["value"]
        out[host] = {"records": len(records), "by_type": by_type,
                     "spans": n_spans, "span_s": round(span_s, 6),
                     "counters": counters}
    return out


def reconcile(merged):
    """Check the obs commit ledger against itself: every committed
    window ordinal appears EXACTLY once across hosts and generations
    (node 0 of the live generation owns the commit; a voided window is
    recomputed but never re-committed), with no gaps from 0 to the
    last. Returns ``{ok, windows, committed, duplicates, gaps,
    max_cursor}``.
    """
    commits = [r for r in merged if r.get("type") == "elastic"
               and r.get("event") == "commit"]
    seen = {}
    for r in commits:
        w = r.get("window")
        if isinstance(w, int) and not isinstance(w, bool):
            seen[w] = seen.get(w, 0) + 1
    duplicates = sorted(w for w, n in seen.items() if n > 1)
    gaps = []
    if seen:
        gaps = sorted(set(range(max(seen) + 1)) - set(seen))
    cursors = [r.get("cursor") for r in commits
               if isinstance(r.get("cursor"), int)]
    # vacuously ok with zero commits (a non-elastic fleet run has no
    # ledger to disagree with); consumers that EXPECT commits assert on
    # ``windows`` themselves (elastic_smoke, bench_elastic_fit)
    return {"ok": not duplicates and not gaps,
            "windows": len(seen), "committed": len(commits),
            "duplicates": duplicates, "gaps": gaps,
            "max_cursor": max(cursors) if cursors else None}


def summarize(source):
    """The whole fleet view as one dict: hosts, run ids, clock offsets,
    per-host rollups, per-generation critical paths, and the commit
    reconciliation. ``source`` as in :func:`load_shards`."""
    # a list of (host, records) pairs is already-loaded shards; any
    # other list (e.g. shard paths) goes through load_shards
    if isinstance(source, list) and source \
            and all(isinstance(s, tuple) and len(s) == 2 for s in source):
        shards = source
    else:
        shards = load_shards(source)
    offsets = clock_offsets(shards)
    merged = merge(shards, offsets)
    gens = sorted({r["generation"] for r in merged
                   if r.get("type") == "elastic"
                   and isinstance(r.get("generation"), int)})
    return {
        "run_ids": run_ids(shards),
        "hosts": [h for h, _ in shards],
        "records": len(merged),
        "generations": gens,
        "clock_offsets_s": {h: round(o, 6) for h, o in offsets.items()},
        "rollups": rollups(shards),
        "critical_path": critical_path(merged),
        "reconciliation": reconcile(merged),
    }


def render(summary):
    """Human-readable text view of :func:`summarize`'s dict."""
    lines = []
    rid = ", ".join(summary["run_ids"]) or "(no fleet envelope)"
    lines.append(f"fleet run: {rid}")
    lines.append(f"hosts: {', '.join(summary['hosts'])}  "
                 f"records: {summary['records']}  "
                 f"generations: {summary['generations']}")
    lines.append("")
    lines.append("clock offsets vs reference (s):")
    for h, o in sorted(summary["clock_offsets_s"].items()):
        lines.append(f"  {h:<12} {o:+.6f}")
    lines.append("")
    lines.append(f"{'host':<12} {'records':>8} {'spans':>6} "
                 f"{'span_s':>9}  top types")
    for h, r in sorted(summary["rollups"].items()):
        top = sorted(r["by_type"].items(), key=lambda kv: -kv[1])[:4]
        tops = " ".join(f"{t}:{n}" for t, n in top)
        lines.append(f"{h:<12} {r['records']:>8} {r['spans']:>6} "
                     f"{r['span_s']:>9.3f}  {tops}")
    if summary["critical_path"]:
        lines.append("")
        lines.append("shrink critical path (s):")
        lines.append(f"  {'gen':>3} {'detect':>8} {'shrink':>8} "
                     f"{'reinit':>8} {'resume':>8} {'finish':>8} "
                     f"{'total':>8}")
        for p in summary["critical_path"]:
            vals = [p[k] for k in ("detect_s", "shrink_s", "reinit_s",
                                   "resume_s", "finish_s", "total_s")]
            cells = " ".join(f"{v:>8.3f}" if isinstance(v, (int, float))
                             else f"{'—':>8}" for v in vals)
            lines.append(f"  {p['generation']:>3} {cells}")
    rc = summary["reconciliation"]
    lines.append("")
    state = "OK" if rc["ok"] else "BROKEN"
    lines.append(f"commit ledger: {state} — {rc['windows']} windows "
                 f"committed ({rc['committed']} records), "
                 f"duplicates={rc['duplicates']}, gaps={rc['gaps']}, "
                 f"max cursor={rc['max_cursor']}")
    return "\n".join(lines)


def write_merged(shards, out_path, offsets=None):
    """Write the merged, clock-aligned timeline as one JSONL file —
    every line schema-valid (the added ``_host`` / ``ts_fleet`` keys
    ride outside the validated fields). Returns the merged list."""
    merged = merge(shards, offsets)
    with open(out_path, "w") as fh:
        for rec in merged:
            fh.write(json.dumps(rec) + "\n")
    return merged


def main(argv):
    """``fleet <run_dir | shard.jsonl ...> [--json] [-o trace.json]
    [--merged merged.jsonl]``"""
    import sys

    as_json = False
    trace_out = None
    merged_out = None
    paths = []
    it = iter(argv)
    for a in it:
        if a == "--json":
            as_json = True
        elif a in ("-o", "--out"):
            trace_out = next(it, None)
        elif a == "--merged":
            merged_out = next(it, None)
        else:
            paths.append(a)
    if not paths:
        print("usage: python -m sq_learn_tpu.obs fleet "
              "<run_dir | shard.jsonl ...> [--json] [-o trace.json] "
              "[--merged merged.jsonl]", file=sys.stderr)
        return 2
    source = paths[0] if len(paths) == 1 else paths
    shards = load_shards(source)
    if not shards:
        print(f"no obs shards found in {paths}", file=sys.stderr)
        return 2
    summary = summarize(shards)
    if merged_out:
        write_merged(shards, merged_out,
                     offsets=summary["clock_offsets_s"])
        summary["merged"] = merged_out
    if trace_out:
        trace = to_chrome_trace(shards)
        with open(trace_out, "w") as fh:
            json.dump(trace, fh)
        summary["trace"] = trace_out
    if as_json:
        print(json.dumps(summary))
    else:
        print(render(summary))
    return 0 if summary["reconciliation"]["ok"] else 1
