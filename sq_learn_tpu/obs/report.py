"""Human-readable report over an obs JSONL run artifact.

``python -m sq_learn_tpu.obs report <jsonl>`` prints the run the way a
person asks about it: where did wall-clock go (top spans by SELF time —
a parent's time minus its children's, so ``qpca.fit`` doesn't drown the
tile walk it contains), did anything recompile past budget, how many
bytes moved, what faults/breaker transitions fired, and the paper's
two-sided cost table — theoretical quantum queries (ledger) next to
measured classical kernel cost (xla_cost).

Dependency-free like :mod:`~sq_learn_tpu.obs.schema`/`~.trace` (stdlib
only, never imports jax): it must run with PYTHONPATH cleared while the
accelerator relay is wedged.
"""

import json

from . import budget as _budget
from . import control as _control
from . import frontier as _frontier
from . import guarantees as _guarantees
from . import storage as _storage
from .trace import load_jsonl

__all__ = ["summarize", "render", "main"]


def _fmt_bytes(n):
    if n is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0


def _fmt_num(n):
    if n is None:
        return "-"
    if abs(n) >= 1e15:
        return f"{n:.3e}"
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(n) >= scale:
            return f"{n / scale:.2f}{suffix}"
    return f"{n:.4g}"


def summarize(records):
    """Aggregate one run's records into the report dict ``render`` prints.

    Span self-time: ``dur - Σ(direct children dur)``, children resolved
    through the recorder's ``parent``-seq links (clamped at 0 — async
    overlap can make children sum past the parent's wall-clock).
    """
    spans = [r for r in records if r.get("type") == "span"]
    child_dur = {}
    for s in spans:
        p = s.get("parent")
        if p is not None:
            child_dur[p] = child_dur.get(p, 0.0) + float(s.get("dur_s", 0.0))
    by_name = {}
    for s in spans:
        dur = float(s.get("dur_s", 0.0))
        self_s = max(0.0, dur - child_dur.get(s.get("seq"), 0.0))
        agg = by_name.setdefault(
            s.get("name"), {"count": 0, "total_s": 0.0, "self_s": 0.0,
                            "errors": 0})
        agg["count"] += 1
        agg["total_s"] += dur
        agg["self_s"] += self_s
        agg["errors"] += 1 if "error" in s else 0

    watchdog = {}
    for r in records:
        if r.get("type") == "watchdog":
            watchdog[r.get("site")] = r  # last observation wins

    counters = {}
    for r in records:
        if r.get("type") == "counter":
            counters[r.get("name")] = r.get("value")  # cumulative: last wins

    xla = {}
    for r in records:
        if r.get("type") != "xla_cost":
            continue
        site = xla.setdefault(r.get("site"),
                              {"signatures": 0, "flops": None,
                               "bytes_accessed": None, "peak_bytes": None})
        site["signatures"] += 1
        for field in ("flops", "bytes_accessed", "peak_bytes"):
            v = r.get(field)
            if isinstance(v, (int, float)) and (site[field] is None
                                                or v > site[field]):
                site[field] = v

    ledger_queries = {}
    ledger_wall = 0.0
    for r in records:
        if r.get("type") != "ledger":
            continue
        for k, v in (r.get("queries") or {}).items():
            ledger_queries[k] = ledger_queries.get(k, 0.0) + v
        ledger_wall += float(r.get("wall_s", 0.0))

    timeline = [r for r in records
                if r.get("type") in ("fault", "breaker", "regression")]
    timeline.sort(key=lambda r: r.get("ts", 0.0))

    probes = [r for r in records if r.get("type") == "probe"]
    gauges = {r.get("name"): r.get("value")
              for r in records if r.get("type") == "gauge"}
    by_type = {}
    for r in records:
        t = r.get("type")
        by_type[t] = by_type.get(t, 0) + 1

    # spectral-stats engine: digest-cache traffic + sketched-vs-exact
    # estimated FLOPs (engine counters) + measured sketch wall-clock
    # (every span under the sketch.* namespace, total: the kernels run
    # async, so self-time would under-count the overlapped work)
    sketch_wall = sum(agg["total_s"] for name, agg in by_name.items()
                      if name.startswith("sketch."))
    sketch = {
        "cache_hits": counters.get("stats_cache.hits", 0),
        "cache_misses": counters.get("stats_cache.misses", 0),
        "estimates": counters.get("sketch.estimates", 0),
        "sketch_flops": counters.get("sketch.flops"),
        "exact_equiv_flops": counters.get("sketch.exact_equiv_flops"),
        "wall_s": round(sketch_wall, 6),
    }

    slo = [r for r in records if r.get("type") == "slo"]

    # fleet correlation (v10): which run/hosts/generations the artifact's
    # records came from — stamped by the recorder's fleet envelope, plus
    # the elastic window/commit ledger and clock-sample traffic
    fleet_hosts = {}
    fleet_runs = set()
    for r in records:
        env = r.get("fleet")
        if isinstance(env, dict):
            fleet_runs.add(env.get("run_id"))
            h = env.get("host")
            fleet_hosts[h] = fleet_hosts.get(h, 0) + 1
    elastic_recs = [r for r in records if r.get("type") == "elastic"]
    fleet = {
        "run_ids": sorted(str(x) for x in fleet_runs if x is not None),
        "hosts": fleet_hosts,
        "generations": sorted({r.get("generation") for r in elastic_recs
                               if isinstance(r.get("generation"), int)}),
        "commits": sum(1 for r in elastic_recs
                       if r.get("event") == "commit"),
        "windows": sum(1 for r in elastic_recs
                       if r.get("event") == "window"),
        "clock_samples": by_type.get("clock", 0),
    }

    # AOT-warmed / quantized serving (PR 11): executables minted at warm
    # time vs dispatch-time executable-cache traffic (hits at 100% =
    # zero serving-path compiles), persistent compile-cache reloads, the
    # bytes serving moved, and each quantized residency's declared fold
    serving = {
        "aot_compiles": counters.get("serving.aot_compiles", 0),
        "aot_cache_hits": counters.get("serving.aot_cache_hits", 0),
        "aot_cache_misses": counters.get("serving.aot_cache_misses", 0),
        "persistent_cache_hits": counters.get(
            "serving.persistent_cache_hits", 0),
        "persistent_cache_misses": counters.get(
            "serving.persistent_cache_misses", 0),
        "transfer_bytes": counters.get("serving.transfer_bytes", 0),
        "quant_folds": [r.get("value") for r in records
                        if r.get("type") == "gauge"
                        and r.get("name") == "serving.quant_fold"],
    }

    # out-of-core prefetch: readahead hit/stall traffic plus the measured
    # stall seconds — the numbers that say whether shard reads overlapped
    # compute or the consumer sat waiting on the disk/CRC pass
    pf_spans = by_name.get("oocore.prefetch", {})
    prefetch = {
        "hits": counters.get("oocore.prefetch_hits", 0),
        "stalls": counters.get("oocore.prefetch_stalls", 0),
        "stall_s": counters.get("oocore.prefetch_stall_s", 0.0),
        "occupancy": counters.get("oocore.prefetch_occupancy", 0),
        "prefetchers": pf_spans.get("count", 0),
        "async_ckpt_writes": counters.get("oocore.async_ckpt_writes", 0),
        "async_ckpt_dropped": counters.get("oocore.async_ckpt_dropped", 0),
    }

    # compressed tier (v7): stored-vs-decoded bytes through the shard
    # codec, and the serving feature-cache's spill/disk-hit traffic —
    # the numbers behind the bytes-on-disk and survives-restart claims
    codec = {
        "bytes_in": counters.get("oocore.codec_bytes_in", 0),
        "bytes_out": counters.get("oocore.codec_bytes_out", 0),
        "cache_spills": counters.get("serving.cache_spills", 0),
        "cache_disk_hits": counters.get("serving.cache_disk_hits", 0),
    }

    # storage surfaces (v11): one rollup per disk surface, built from
    # counters every schema version has carried — so the section renders
    # on pre-v11 artifacts too. When the run DOES carry v11 ``io``
    # records, the per-shard ledger rollup rides along (full view:
    # python -m sq_learn_tpu.obs storage).
    cache_gets = (counters.get("serving.cache_hits", 0)
                  + counters.get("serving.cache_misses", 0))
    storage = {
        "oocore": {
            "shard_reads": counters.get("oocore.shard_reads", 0),
            "shard_read_bytes": counters.get("oocore.shard_read_bytes", 0),
            "codec_bytes_in": counters.get("oocore.codec_bytes_in", 0),
            "codec_bytes_out": counters.get("oocore.codec_bytes_out", 0),
            "rereads": counters.get("oocore.rereads", 0),
            "crc_failures": counters.get("oocore.crc_failures", 0),
            "prefetch_hits": counters.get("oocore.prefetch_hits", 0),
            "prefetch_stalls": counters.get("oocore.prefetch_stalls", 0),
        },
        "serve_cache": {
            "gets": cache_gets,
            "spills": counters.get("serving.cache_spills", 0),
            "disk_hits": counters.get("serving.cache_disk_hits", 0),
        },
        "compile_cache": {
            "hits": counters.get("serving.persistent_cache_hits", 0),
            "misses": counters.get("serving.persistent_cache_misses", 0),
        },
        "io_records": by_type.get("io", 0),
        "ledger": (_storage.surface_rollup(_storage.collect(records))
                   if by_type.get("io") else {}),
    }

    return {
        "by_type": by_type,
        "spans": by_name,
        "watchdog": watchdog,
        "slo": slo,
        "serving": serving,
        "counters": counters,
        "xla": xla,
        "ledger": {"queries": ledger_queries,
                   "wall_s": round(ledger_wall, 6)},
        "timeline": timeline,
        "probes": probes,
        "gauges": gauges,
        "sketch": sketch,
        "prefetch": prefetch,
        "codec": codec,
        # the storage-surfaces section (v11-aware, counter-backed): one
        # rollup per disk surface; "ledger" is populated only when the
        # artifact carries io records (pre-v11 runs still render)
        "storage": storage,
        # the fleet-correlation section (v10): run_id / per-host record
        # counts from the fleet envelope, the elastic window/commit
        # ledger, and the clock-sample traffic behind the merged
        # timeline (full mesh view: python -m sq_learn_tpu.obs fleet)
        "fleet": fleet,
        # the statistical-observability sections (v3): per-site
        # Clopper–Pearson audit of the (ε, δ) guarantee draws, and the
        # run's accuracy-vs-theoretical-runtime sweep points
        "audit": _guarantees.audit(records),
        "tradeoffs": _frontier.collect(records),
        # the per-tenant error-budget sections (v6): rolling-window
        # burn rates + tripped alerts, and the effective (ε, δ) each
        # tenant's live draws say it was actually served
        "budgets": _budget.collect(records),
        "effective": _frontier.effective_contracts(records),
        # the control-plane section (v8): the autotuner's per-tenant
        # decision history — every route/coalescing/target change with
        # the telemetry that justified it
        "control": _control.collect(records),
    }


def render(summary, top=12):
    """Format the summary as the report text."""
    lines = []
    out = lines.append
    out("== obs run report ==")
    out("records: " + ", ".join(
        f"{t}={n}" for t, n in sorted(summary["by_type"].items(),
                                      key=lambda kv: -kv[1])))

    out("")
    out(f"-- top spans by self-time (top {top}) --")
    ranked = sorted(summary["spans"].items(),
                    key=lambda kv: -kv[1]["self_s"])[:top]
    if not ranked:
        out("  (no spans)")
    for name, agg in ranked:
        err = f"  errors={agg['errors']}" if agg["errors"] else ""
        out(f"  {agg['self_s']:9.4f}s self  {agg['total_s']:9.4f}s total  "
            f"x{agg['count']:<4d} {name}{err}")

    out("")
    out("-- compiles per site (watchdog, last observation) --")
    if not summary["watchdog"]:
        out("  (no watchdog observations)")
    for site, r in sorted(summary["watchdog"].items()):
        flag = "  OVER BUDGET" if r.get("over_budget") else ""
        out(f"  {r.get('compiles', 0):3d} / budget "
            f"{r.get('budget')!s:>4} {site}{flag}")

    out("")
    out("-- xla cost per site (max over signatures) --")
    if not summary["xla"]:
        out("  (no xla_cost records — pre-v2 run or analysis unavailable)")
    for site, agg in sorted(summary["xla"].items()):
        out(f"  {_fmt_num(agg['flops']):>10} flops  "
            f"{_fmt_bytes(agg['bytes_accessed']):>10} accessed  "
            f"{_fmt_bytes(agg['peak_bytes']):>10} peak  "
            f"sigs={agg['signatures']} {site}")

    out("")
    out("-- transfers / counters --")
    if not summary["counters"]:
        out("  (no counters)")
    for name, val in sorted(summary["counters"].items()):
        shown = _fmt_bytes(val) if "bytes" in name else _fmt_num(val)
        out(f"  {shown:>12} {name}")

    out("")
    out("-- quantum ledger vs measured classical cost --")
    lq = summary["ledger"]["queries"]
    if not lq:
        out("  (no ledger entries)")
    for k, v in sorted(lq.items()):
        out(f"  {_fmt_num(v):>10} {k} (theoretical)")
    out(f"  {summary['ledger']['wall_s']:10.4f}s simulated wall-clock")
    mfu = summary["gauges"].get("profiling.mfu")
    if isinstance(mfu, (int, float)):
        out(f"  {mfu:10.6f} measured MFU (profiling.mfu)")

    out("")
    out("-- spectral-stats cache / sketch savings --")
    sk = summary.get("sketch") or {}
    hits, misses = sk.get("cache_hits", 0), sk.get("cache_misses", 0)
    if not (hits or misses or sk.get("estimates")):
        out("  (no spectral-stats activity)")
    else:
        total = hits + misses
        rate = f" ({hits / total:.0%} hit rate)" if total else ""
        out(f"  {hits} hits / {misses} misses stats cache{rate}")
        sf, ef = sk.get("sketch_flops"), sk.get("exact_equiv_flops")
        if sk.get("estimates"):
            saved = (f", {1.0 - sf / ef:.0%} of the exact sweep saved"
                     if sf and ef else "")
            out(f"  {sk['estimates']:.0f} sketched estimate(s): "
                f"{_fmt_num(sf)} flops vs {_fmt_num(ef)} exact-equivalent"
                f"{saved}")
        out(f"  {sk.get('wall_s', 0.0):.4f}s measured in sketch.* spans "
            f"(async kernels: total, not self)")

    out("")
    out("-- guarantee audit (Clopper-Pearson on declared (eps, delta)) --")
    out(_guarantees.render(summary.get("audit", {})))

    out("")
    out("-- accuracy vs theoretical quantum runtime --")
    tr = summary.get("tradeoffs", {})
    if not tr:
        out("  (no tradeoff records)")
    else:
        for line in _frontier.render(tr).splitlines():
            out("  " + line)

    out("")
    out("-- out-of-core prefetch (shard readahead / async checkpoints) --")
    pf = summary.get("prefetch") or {}
    gets = pf.get("hits", 0) + pf.get("stalls", 0)
    if not gets and not pf.get("async_ckpt_writes"):
        out("  (no prefetch activity)")
    else:
        if gets:
            occ = pf.get("occupancy", 0) / gets
            out(f"  {pf.get('hits', 0)} hits / {pf.get('stalls', 0)} "
                f"stalls across {pf.get('prefetchers', 0)} prefetcher(s) "
                f"({pf.get('hits', 0) / gets:.0%} hit rate, avg depth "
                f"occupancy {occ:.2f})")
            out(f"  {pf.get('stall_s', 0.0):.4f}s total consumer stall "
                f"waiting on shard reads")
        if pf.get("async_ckpt_writes"):
            out(f"  {pf.get('async_ckpt_writes', 0)} async checkpoint "
                f"write(s), {pf.get('async_ckpt_dropped', 0)} superseded "
                f"before writing (latest-wins)")

    out("")
    out("-- compressed tier (shard codec / serving feature cache) --")
    cd = summary.get("codec") or {}
    if not any(cd.values()):
        out("  (no codec or spill activity)")
    else:
        if cd.get("bytes_out"):
            ratio = cd.get("bytes_in", 0) / cd["bytes_out"]
            out(f"  shard codec: {_fmt_bytes(cd.get('bytes_in', 0))} "
                f"stored -> {_fmt_bytes(cd['bytes_out'])} decoded "
                f"(bytes-on-disk ratio {ratio:.3f})")
        if cd.get("cache_spills") or cd.get("cache_disk_hits"):
            out(f"  feature cache: {cd.get('cache_spills', 0)} spill(s) "
                f"to disk, {cd.get('cache_disk_hits', 0)} digest-verified "
                f"disk hit(s)")

    out("")
    out("-- storage surfaces (oocore / feature cache / compile cache) --")
    st = summary.get("storage") or {}
    ooc = st.get("oocore") or {}
    sc = st.get("serve_cache") or {}
    cc = st.get("compile_cache") or {}
    pf_gets = ooc.get("prefetch_hits", 0) + ooc.get("prefetch_stalls", 0)
    cc_gets = cc.get("hits", 0) + cc.get("misses", 0)
    if not (ooc.get("shard_reads") or sc.get("gets") or sc.get("spills")
            or cc_gets):
        out("  (no storage-surface activity)")
    else:
        if ooc.get("shard_reads"):
            ratio_s = ""
            if ooc.get("codec_bytes_out"):
                r = ooc.get("codec_bytes_in", 0) / ooc["codec_bytes_out"]
                ratio_s = f", codec ratio {r:.3f} stored/raw"
            pf_s = (f", prefetch {ooc.get('prefetch_hits', 0) / pf_gets:.0%}"
                    f" hit rate" if pf_gets else "")
            out(f"  oocore: {ooc.get('shard_reads', 0)} shard read(s), "
                f"{_fmt_bytes(ooc.get('shard_read_bytes', 0))} moved"
                f"{ratio_s}{pf_s}, {ooc.get('rereads', 0)} reread(s), "
                f"{ooc.get('crc_failures', 0)} CRC failure(s)")
        if sc.get("gets") or sc.get("spills"):
            hit_s = (f" ({sc.get('disk_hits', 0) / sc['gets']:.0%} of "
                     f"lookups served off disk)" if sc.get("gets") else "")
            out(f"  feature cache: {sc.get('spills', 0)} spill(s), "
                f"{sc.get('disk_hits', 0)} disk hit(s){hit_s}")
        if cc_gets:
            out(f"  compile cache: {cc.get('hits', 0)} reload(s), "
                f"{cc.get('misses', 0)} cold compile(s) "
                f"({cc.get('hits', 0) / cc_gets:.0%} warm)")
        if st.get("io_records"):
            for surface, a in sorted((st.get("ledger") or {}).items()):
                out(f"  ledger[{surface}]: {a.get('entries', 0)} "
                    f"entr{'y' if a.get('entries') == 1 else 'ies'} over "
                    f"{a.get('stores', 0)} store(s), "
                    f"{a.get('reads', 0)} read(s), "
                    f"{_fmt_bytes(a.get('bytes_raw', 0))} raw / "
                    f"{_fmt_bytes(a.get('bytes_stored', 0))} stored")
            out(f"  {st['io_records']} io record(s) — per-shard heat "
                f"table: python -m sq_learn_tpu.obs storage")

    out("")
    out("-- serving SLOs (p50/p99 latency, sustained QPS) --")
    slo = summary.get("slo") or []
    if not slo:
        out("  (no slo records)")
    for r in slo:
        tgt = r.get("targets") or {}
        tgt_s = (" targets p50<=" + _fmt_num(tgt.get("p50_ms"))
                 + "ms p99<=" + _fmt_num(tgt.get("p99_ms")) + "ms"
                 if tgt else "")
        flag = "  SLO VIOLATED" if r.get("violated") else ""
        tb = r.get("transfer_bytes")
        tb_s = f"  moved {tb} B" if tb else ""
        who = r.get("site")
        if r.get("tenant"):
            who = f"{who}[{r['tenant']}]"
        if (r.get("attrs") or {}).get("windowed"):
            who = f"{who} (window #{r['attrs'].get('flush_seq')})"
        out(f"  {who}: {r.get('requests', 0)} req @ "
            f"{_fmt_num(r.get('qps'))} qps  p50 {r.get('p50_ms')}ms  "
            f"p99 {r.get('p99_ms')}ms  occupancy "
            f"{r.get('batch_occupancy')}  degraded {r.get('degraded')}"
            f"{tb_s}{tgt_s}{flag}")
        stages = r.get("stages")
        if stages:
            decomp = "  ".join(f"{k}={v:.4f}s"
                               for k, v in sorted(stages.items()))
            out(f"    stages: {decomp}")

    out("")
    out("-- tenant error budgets (multi-window burn rates) --")
    out(_budget.render(summary.get("budgets") or {}))

    out("")
    out("-- effective (eps, delta) per tenant (live draws) --")
    out(_frontier.render_effective(summary.get("effective") or {}))

    out("")
    out("-- controller decisions (SLO-driven (eps, delta) autotuner) --")
    out(_control.render(summary.get("control") or {}))

    srv = summary.get("serving") or {}
    if (srv.get("aot_compiles") or srv.get("aot_cache_hits")
            or srv.get("quant_folds")):
        out("")
        out("-- serving AOT / quantized routes --")
        gets = srv.get("aot_cache_hits", 0) + srv.get("aot_cache_misses", 0)
        if gets or srv.get("aot_compiles"):
            rate = (srv.get("aot_cache_hits", 0) / gets) if gets else 0.0
            out(f"  {srv.get('aot_compiles', 0)} executable(s) warmed; "
                f"{srv.get('aot_cache_hits', 0)}/{gets} dispatches served "
                f"AOT ({rate:.0%} — 100% means zero serving-path "
                f"compiles)")
        if srv.get("persistent_cache_hits") \
                or srv.get("persistent_cache_misses"):
            out(f"  persistent compile cache: "
                f"{srv.get('persistent_cache_hits', 0)} reload(s), "
                f"{srv.get('persistent_cache_misses', 0)} cold "
                f"compile(s)")
        if srv.get("transfer_bytes"):
            out(f"  {srv.get('transfer_bytes', 0)} padded payload bytes "
                f"moved host->device")
        for fold in srv.get("quant_folds") or []:
            if isinstance(fold, dict):
                out(f"  fold {fold.get('op')}[{fold.get('mode')}]: "
                    f"tol = {fold.get('coef_const')} + "
                    f"{fold.get('coef_amax')}*amax_x ({fold.get('kind')}), "
                    f"delta_q {fold.get('delta')}")

    fl = summary.get("fleet") or {}
    if fl.get("run_ids") or fl.get("hosts") or fl.get("generations"):
        out("")
        out("-- fleet (cross-process correlation) --")
        if fl.get("run_ids"):
            out("  run_id: " + ", ".join(fl["run_ids"]))
        if fl.get("hosts"):
            out("  hosts: " + ", ".join(
                f"{h}={n}" for h, n in sorted(fl["hosts"].items(),
                                              key=lambda kv: str(kv[0]))))
        if fl.get("generations"):
            gens = ", ".join(f"g{g}" for g in fl["generations"])
            out(f"  generations: {gens}  "
                f"({fl.get('windows', 0)} window fold(s), "
                f"{fl.get('commits', 0)} commit(s))")
        if fl.get("clock_samples"):
            out(f"  {fl['clock_samples']} clock sample(s) "
                f"(merged view: python -m sq_learn_tpu.obs fleet)")

    out("")
    out("-- fault / breaker / regression timeline --")
    if not summary["timeline"]:
        out("  (clean run: no faults, breaker transitions, or verdicts)")
    for r in summary["timeline"]:
        t = r["type"]
        if t == "fault":
            out(f"  {r.get('ts')}: fault {r.get('kind')} "
                f"tile={r.get('tile')}")
        elif t == "breaker":
            out(f"  {r.get('ts')}: breaker {r.get('prev')} -> "
                f"{r.get('state')} ({r.get('reason')})")
        else:
            out(f"  {r.get('ts')}: regression {r.get('gate')} "
                f"[{r.get('metric')}] -> {r.get('verdict')}")

    if summary["probes"]:
        out("")
        out("-- probes --")
        for r in summary["probes"]:
            cached = " (cached)" if r.get("cached") else ""
            out(f"  {r.get('outcome')} {r.get('latency_s', 0.0):.2f}s "
                f"platform={r.get('platform')!r}{cached}")
    return "\n".join(lines)


def main(argv):
    """``report <jsonl> [more.jsonl ...] [--json]``"""
    import sys

    as_json = "--json" in argv
    paths = [a for a in argv if a != "--json"]
    if not paths:
        print("usage: python -m sq_learn_tpu.obs report <jsonl> "
              "[more.jsonl ...] [--json]", file=sys.stderr)
        return 2
    records = []
    for p in paths:
        records.extend(load_jsonl(p))
    summary = summarize(records)
    if as_json:
        print(json.dumps(summary, default=repr))
    else:
        print(render(summary))
    return 0
