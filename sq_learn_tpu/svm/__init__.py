"""SVM — reference-namespace facade (``sklearn/svm``).

``QLSSVC`` (``svm/_qSVM.py:10``) is the quantum least-squares SVM; the
classical libsvm/liblinear SMO solvers are out of the quantum capability
surface (SURVEY §2.2) — the LS-SVM formulation is a dense SVD solve that
maps to the MXU.
"""

from ..models.qlssvc import QLSSVC, lssvc_solve

__all__ = ["QLSSVC", "lssvc_solve"]
