"""Clustering / classification scores."""

import jax
import jax.numpy as jnp
import numpy as np


def accuracy_score(y_true, y_pred):
    """Fraction of exact label matches."""
    y_true = jnp.asarray(y_true)
    y_pred = jnp.asarray(y_pred)
    return jnp.mean((y_true == y_pred).astype(jnp.float32))


def _contingency(labels_true, labels_pred):
    """Dense contingency table — exact int64 bincount (label metrics are
    integer bookkeeping; a float32 GEMM stops counting exactly at 2^24,
    which the TPU-scale datasets this library targets can exceed)."""
    labels_true = np.asarray(labels_true)
    labels_pred = np.asarray(labels_pred)
    _, ti = np.unique(labels_true, return_inverse=True)
    _, pi = np.unique(labels_pred, return_inverse=True)
    n_t = int(ti.max()) + 1
    n_p = int(pi.max()) + 1
    return np.bincount(n_p * ti + pi,
                       minlength=n_t * n_p).reshape(n_t, n_p)


def adjusted_rand_score(labels_true, labels_pred):
    """Adjusted Rand Index (reference ``metrics/cluster/_supervised.py:302``):
    ARI = (RI − E[RI]) / (max(RI) − E[RI]) via the contingency-table pair
    counts."""
    c = _contingency(labels_true, labels_pred)
    n = jnp.sum(c)
    sum_comb_c = jnp.sum(c * (c - 1)) / 2.0
    a = jnp.sum(c, axis=1)
    b = jnp.sum(c, axis=0)
    sum_comb_a = jnp.sum(a * (a - 1)) / 2.0
    sum_comb_b = jnp.sum(b * (b - 1)) / 2.0
    total = n * (n - 1) / 2.0
    expected = sum_comb_a * sum_comb_b / total
    max_index = (sum_comb_a + sum_comb_b) / 2.0
    denom = max_index - expected
    return jnp.where(denom == 0, 1.0, (sum_comb_c - expected) / denom)


def inertia(X, centers, labels):
    """Sum of squared distances of samples to their assigned center."""
    X = jnp.asarray(X)
    centers = jnp.asarray(centers)
    diffs = X - centers[jnp.asarray(labels)]
    return jnp.sum(diffs * diffs)


def explained_variance_ratio(singular_values, n_samples, total_variance=None):
    """Per-component explained-variance ratios from singular values
    (reference ``_qPCA.py:589-591``)."""
    ev = jnp.asarray(singular_values) ** 2 / (n_samples - 1)
    total = jnp.sum(ev) if total_variance is None else total_variance
    return ev / total


def normalized_mutual_info_score(labels_true, labels_pred):
    """NMI with arithmetic-mean normalization (the capability surface of
    ``metrics/cluster/_supervised.py``). Host-side float64 — label metrics
    are integer bookkeeping, not FLOPs, and float32 drifts at scale."""
    c = np.asarray(_contingency(labels_true, labels_pred), dtype=np.float64)
    n = c.sum()
    pi = c.sum(axis=1)
    pj = c.sum(axis=0)
    outer = pi[:, None] * pj[None, :]
    nz = c > 0
    with np.errstate(divide="ignore", invalid="ignore"):
        mi = np.sum(np.where(nz, (c / n) * np.log((c * n)
                                                  / np.where(nz, outer, 1.0)),
                             0.0))

    def entropy(p):
        p = p[p > 0] / n
        return -np.sum(p * np.log(p))

    denom = (entropy(pi) + entropy(pj)) / 2
    return float(mi / denom) if denom > 0 else 1.0


def confusion_matrix(y_true, y_pred):
    """Dense confusion matrix over the sorted union of observed labels
    (sklearn semantics — negative labels included). Exact int64 counts via
    bincount."""
    y_true = np.asarray(y_true).ravel()
    y_pred = np.asarray(y_pred).ravel()
    classes, inv = np.unique(np.concatenate([y_true, y_pred]),
                             return_inverse=True)
    k = len(classes)
    yt, yp = inv[: len(y_true)], inv[len(y_true):]
    return np.bincount(k * yt + yp, minlength=k * k).reshape(k, k)


def f1_score(y_true, y_pred, average="binary", pos_label=1):
    """F1 = 2·P·R/(P+R); ``average`` ∈ {'binary', 'macro', 'micro',
    'weighted'}. Binary mode scores ``pos_label``; 'weighted' weights the
    per-class F1 by true-class support (sklearn semantics)."""
    classes, inv = np.unique(
        np.concatenate([np.asarray(y_true).ravel(),
                        np.asarray(y_pred).ravel()]), return_inverse=True)
    n = np.asarray(y_true).size
    yt, yp = inv[:n], inv[n:]
    k = len(classes)
    C = np.bincount(k * yt + yp, minlength=k * k).reshape(k, k).astype(
        np.float64)
    tp = np.diag(C)
    fp = C.sum(axis=0) - tp
    fn = C.sum(axis=1) - tp
    if average == "micro":
        p = tp.sum() / max(tp.sum() + fp.sum(), 1e-12)
        r = tp.sum() / max(tp.sum() + fn.sum(), 1e-12)
        return float(2 * p * r / max(p + r, 1e-12))
    with np.errstate(divide="ignore", invalid="ignore"):
        p = np.where(tp + fp > 0, tp / (tp + fp), 0.0)
        r = np.where(tp + fn > 0, tp / (tp + fn), 0.0)
        f1 = np.where(p + r > 0, 2 * p * r / (p + r), 0.0)
    if average == "macro":
        return float(f1.mean())
    if average == "weighted":
        support = C.sum(axis=1)
        total = support.sum()
        if total == 0:
            return 0.0
        return float((f1 * support).sum() / total)
    if average == "binary":
        where = np.flatnonzero(classes == pos_label)
        if len(where) == 0:
            raise ValueError(
                f"pos_label={pos_label!r} is not a valid label; observed "
                f"labels are {classes.tolist()}")
        return float(f1[where[0]])
    raise ValueError(f"unknown average {average!r}")


def silhouette_score(X, labels, sample_size=None, random_state=0):
    """Mean silhouette coefficient — one fused jnp computation over the
    full (or subsampled) pairwise distance matrix."""
    X = np.asarray(X)
    labels = np.asarray(labels)
    if sample_size is not None and sample_size < len(X):
        rng = np.random.default_rng(random_state)
        idx = rng.choice(len(X), sample_size, replace=False)
        X, labels = X[idx], labels[idx]
    classes, y = np.unique(labels, return_inverse=True)
    if len(classes) < 2 or len(classes) >= len(X):
        raise ValueError(
            "silhouette requires 2 <= n_labels <= n_samples - 1")
    from .pairwise import euclidean_distances

    D = jnp.asarray(euclidean_distances(X, X))
    onehot = jax.nn.one_hot(jnp.asarray(y), len(classes), dtype=D.dtype)
    counts = jnp.sum(onehot, axis=0)                      # (k,)
    sums = D @ onehot                                     # (n, k)
    own = counts[y]
    # a: mean intra-cluster distance excluding self; singletons get a=0
    a = jnp.where(own > 1, sums[jnp.arange(len(y)), y] / jnp.maximum(own - 1, 1), 0.0)
    other = jnp.where(onehot > 0, jnp.inf, sums / counts[None, :])
    b = jnp.min(other, axis=1)
    s = jnp.where(own > 1, (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-12), 0.0)
    return float(jnp.mean(s))
