"""Clustering / classification scores."""

import jax.numpy as jnp
import numpy as np


def accuracy_score(y_true, y_pred):
    """Fraction of exact label matches."""
    y_true = jnp.asarray(y_true)
    y_pred = jnp.asarray(y_pred)
    return jnp.mean((y_true == y_pred).astype(jnp.float32))


def _contingency(labels_true, labels_pred):
    """Dense contingency table via one-hot GEMM (MXU-friendly; replaces the
    reference's sparse COO build in ``metrics/cluster/_supervised.py``)."""
    labels_true = np.asarray(labels_true)
    labels_pred = np.asarray(labels_pred)
    _, ti = np.unique(labels_true, return_inverse=True)
    _, pi = np.unique(labels_pred, return_inverse=True)
    n_t = int(ti.max()) + 1
    n_p = int(pi.max()) + 1
    onehot_t = jnp.zeros((len(ti), n_t)).at[jnp.arange(len(ti)), jnp.asarray(ti)].set(1.0)
    onehot_p = jnp.zeros((len(pi), n_p)).at[jnp.arange(len(pi)), jnp.asarray(pi)].set(1.0)
    return onehot_t.T @ onehot_p


def adjusted_rand_score(labels_true, labels_pred):
    """Adjusted Rand Index (reference ``metrics/cluster/_supervised.py:302``):
    ARI = (RI − E[RI]) / (max(RI) − E[RI]) via the contingency-table pair
    counts."""
    c = _contingency(labels_true, labels_pred)
    n = jnp.sum(c)
    sum_comb_c = jnp.sum(c * (c - 1)) / 2.0
    a = jnp.sum(c, axis=1)
    b = jnp.sum(c, axis=0)
    sum_comb_a = jnp.sum(a * (a - 1)) / 2.0
    sum_comb_b = jnp.sum(b * (b - 1)) / 2.0
    total = n * (n - 1) / 2.0
    expected = sum_comb_a * sum_comb_b / total
    max_index = (sum_comb_a + sum_comb_b) / 2.0
    denom = max_index - expected
    return jnp.where(denom == 0, 1.0, (sum_comb_c - expected) / denom)


def inertia(X, centers, labels):
    """Sum of squared distances of samples to their assigned center."""
    X = jnp.asarray(X)
    centers = jnp.asarray(centers)
    diffs = X - centers[jnp.asarray(labels)]
    return jnp.sum(diffs * diffs)


def explained_variance_ratio(singular_values, n_samples, total_variance=None):
    """Per-component explained-variance ratios from singular values
    (reference ``_qPCA.py:589-591``)."""
    ev = jnp.asarray(singular_values) ** 2 / (n_samples - 1)
    total = jnp.sum(ev) if total_variance is None else total_variance
    return ev / total
