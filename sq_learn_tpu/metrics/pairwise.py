"""Pairwise kernels and distances (reference ``sklearn/metrics/pairwise.py``
slice used by QLSSVC at ``svm/_qSVM.py:375-389`` and q-means transform at
``_dmeans.py:1351``). Pure GEMM + elementwise — exactly what the MXU wants."""

import jax.numpy as jnp

from ..ops.linalg import pairwise_sq_distances


def euclidean_distances(X, Y=None, squared=False):
    X = jnp.asarray(X)
    Y = X if Y is None else jnp.asarray(Y)
    d2 = pairwise_sq_distances(X, Y)
    return d2 if squared else jnp.sqrt(d2)


def linear_kernel(X, Y=None):
    X = jnp.asarray(X)
    Y = X if Y is None else jnp.asarray(Y)
    return X @ Y.T


def polynomial_kernel(X, Y=None, degree=3, gamma=None, coef0=1.0):
    X = jnp.asarray(X)
    Y = X if Y is None else jnp.asarray(Y)
    if gamma is None:
        gamma = 1.0 / X.shape[1]
    return (gamma * (X @ Y.T) + coef0) ** degree


def rbf_kernel(X, Y=None, gamma=None):
    X = jnp.asarray(X)
    Y = X if Y is None else jnp.asarray(Y)
    if gamma is None:
        gamma = 1.0 / X.shape[1]
    return jnp.exp(-gamma * pairwise_sq_distances(X, Y))


def sigmoid_kernel(X, Y=None, gamma=None, coef0=1.0):
    X = jnp.asarray(X)
    Y = X if Y is None else jnp.asarray(Y)
    if gamma is None:
        gamma = 1.0 / X.shape[1]
    return jnp.tanh(gamma * (X @ Y.T) + coef0)


KERNELS = {
    "linear": linear_kernel,
    "poly": polynomial_kernel,
    "polynomial": polynomial_kernel,
    "rbf": rbf_kernel,
    "sigmoid": sigmoid_kernel,
}


def pairwise_kernels(X, Y=None, metric="linear", **kwds):
    try:
        fn = KERNELS[metric]
    except KeyError:
        raise ValueError(
            f"unknown kernel {metric!r}; available: {sorted(set(KERNELS))}"
        ) from None
    return fn(X, Y, **kwds)
