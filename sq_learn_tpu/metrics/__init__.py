"""Evaluation metrics and pairwise kernels (reference layer L5 slice:
``sklearn/metrics`` — ARI at ``metrics/cluster/_supervised.py:302``,
``accuracy_score``, and the ``metrics.pairwise`` kernels the quantum LS-SVM
uses at ``svm/_qSVM.py:4,375-389``). All jnp, all jit-able."""

from .pairwise import (
    euclidean_distances,
    linear_kernel,
    pairwise_kernels,
    polynomial_kernel,
    rbf_kernel,
    sigmoid_kernel,
)
from .scores import (
    accuracy_score,
    adjusted_rand_score,
    confusion_matrix,
    explained_variance_ratio,
    f1_score,
    inertia,
    normalized_mutual_info_score,
    silhouette_score,
)

__all__ = [
    "accuracy_score",
    "adjusted_rand_score",
    "confusion_matrix",
    "euclidean_distances",
    "explained_variance_ratio",
    "f1_score",
    "inertia",
    "linear_kernel",
    "normalized_mutual_info_score",
    "pairwise_kernels",
    "polynomial_kernel",
    "rbf_kernel",
    "sigmoid_kernel",
    "silhouette_score",
]
