"""Evaluation metrics and pairwise kernels (reference layer L5 slice:
``sklearn/metrics`` — ARI at ``metrics/cluster/_supervised.py:302``,
``accuracy_score``, and the ``metrics.pairwise`` kernels the quantum LS-SVM
uses at ``svm/_qSVM.py:4,375-389``). All jnp, all jit-able."""

from .pairwise import (
    euclidean_distances,
    linear_kernel,
    pairwise_kernels,
    polynomial_kernel,
    rbf_kernel,
    sigmoid_kernel,
)
from .scores import (
    accuracy_score,
    adjusted_rand_score,
    explained_variance_ratio,
    inertia,
)

__all__ = [
    "accuracy_score",
    "adjusted_rand_score",
    "euclidean_distances",
    "explained_variance_ratio",
    "inertia",
    "linear_kernel",
    "pairwise_kernels",
    "polynomial_kernel",
    "rbf_kernel",
    "sigmoid_kernel",
]
