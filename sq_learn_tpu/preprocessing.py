"""Preprocessing transformers (reference ``sklearn/preprocessing`` slice
used ahead of PCA/k-means — SURVEY §2.4 "scaling before PCA/k-means").

All statistics are single-pass jnp reductions; transforms are elementwise
XLA ops that fuse into whatever consumes them.
"""

import numpy as np
import jax.numpy as jnp

from .base import (BaseEstimator, TransformerMixin, check_is_fitted,
                   check_n_features)
from .utils import check_array


class StandardScaler(TransformerMixin, BaseEstimator):
    """Standardize features to zero mean / unit variance."""

    def __init__(self, *, with_mean=True, with_std=True, copy=True):
        self.with_mean = with_mean
        self.with_std = with_std
        self.copy = copy

    def fit(self, X, y=None):
        X = jnp.asarray(check_array(X))
        self.n_features_in_ = X.shape[1]
        self.mean_ = (np.asarray(jnp.mean(X, axis=0))
                      if self.with_mean else np.zeros(X.shape[1]))
        if self.with_std:
            var = np.asarray(jnp.var(X, axis=0))
            self.var_ = var
            scale = np.sqrt(var)
            scale[scale == 0.0] = 1.0
            self.scale_ = scale
        else:
            self.var_ = None
            self.scale_ = np.ones(X.shape[1])
        self.n_samples_seen_ = X.shape[0]
        return self

    def transform(self, X):
        check_is_fitted(self, "scale_")
        X = jnp.asarray(check_n_features(self, check_array(X)))
        return np.asarray((X - jnp.asarray(self.mean_))
                          / jnp.asarray(self.scale_))

    def inverse_transform(self, X):
        check_is_fitted(self, "scale_")
        X = jnp.asarray(check_n_features(self, check_array(X)))
        return np.asarray(X * jnp.asarray(self.scale_)
                          + jnp.asarray(self.mean_))


class MinMaxScaler(TransformerMixin, BaseEstimator):
    """Scale features to a [min, max] range."""

    def __init__(self, feature_range=(0, 1), *, copy=True):
        self.feature_range = feature_range
        self.copy = copy

    def fit(self, X, y=None):
        X = jnp.asarray(check_array(X))
        self.n_features_in_ = X.shape[1]
        lo, hi = self.feature_range
        data_min = np.asarray(jnp.min(X, axis=0))
        data_max = np.asarray(jnp.max(X, axis=0))
        rng = data_max - data_min
        rng[rng == 0.0] = 1.0
        self.data_min_ = data_min
        self.data_max_ = data_max
        self.scale_ = (hi - lo) / rng
        self.min_ = lo - data_min * self.scale_
        return self

    def transform(self, X):
        check_is_fitted(self, "scale_")
        X = jnp.asarray(check_n_features(self, check_array(X)))
        return np.asarray(X * jnp.asarray(self.scale_)
                          + jnp.asarray(self.min_))

    def inverse_transform(self, X):
        check_is_fitted(self, "scale_")
        X = jnp.asarray(check_n_features(self, check_array(X)))
        return np.asarray((X - jnp.asarray(self.min_))
                          / jnp.asarray(self.scale_))


class Normalizer(TransformerMixin, BaseEstimator):
    """Scale rows to unit norm (the quantum-state preparation convention —
    amplitudes are L2-normalized, ``Utility.py:43-44``)."""

    def __init__(self, norm="l2", *, copy=True):
        self.norm = norm
        self.copy = copy

    def fit(self, X, y=None):
        check_array(X)
        self.n_features_in_ = np.asarray(X).shape[1]
        return self

    def transform(self, X):
        X = jnp.asarray(check_array(X))
        if self.norm == "l2":
            norms = jnp.linalg.norm(X, axis=1, keepdims=True)
        elif self.norm == "l1":
            norms = jnp.sum(jnp.abs(X), axis=1, keepdims=True)
        elif self.norm == "max":
            norms = jnp.max(jnp.abs(X), axis=1, keepdims=True)
        else:
            raise ValueError(f"unknown norm {self.norm!r}")
        return np.asarray(X / jnp.where(norms == 0, 1.0, norms))
