"""Sketched spectral-statistics engine.

The quantum runtime models (q-means ``_dmeans.py:1440-1449``, QADRA,
QLSSVC's κ·α_F) consume four data statistics — σ_min(A), μ(A), ‖A‖_F and
η = max‖xᵢ‖² — whose exact computation is an O(n·m²)-class sweep (the
σ_min Gram) plus an O(n·m·|grid|) transcendental sweep (μ). The paper's
whole thesis is that error budgets are runtime parameters (SURVEY §0);
this package applies the same treatment to the runtime-model *inputs*:
estimate them from a uniform row sketch with explicit
(error_bound, δ_stat) statements, short-circuiting to the exact kernels
at zero budget or tiny shapes (the framework-wide zero-error-budget
convention).

Public surface:

- :func:`~sq_learn_tpu.sketch.engine.spectral_stats` — synchronous
  estimate of any subset of {σ_min, μ grid, ‖A‖_F, η} with certified
  bounds, returning a :class:`~sq_learn_tpu.sketch.engine.SpectralStats`.
- :func:`~sq_learn_tpu.sketch.engine.dispatch_host` /
  :func:`~sq_learn_tpu.sketch.engine.finalize_host` — the async split the
  q-means host fit route uses (kernel overlapped with the native Lloyd
  engines, bounds folded in at the single fetch).
- :mod:`~sq_learn_tpu.sketch.cache` — the digest-keyed stats cache:
  repeated fits over the same array (every (ε, δ) frontier sweep) compute
  spectral stats once per dataset; hits/misses are obs counters.

Env knobs (``docs/fit_pipeline.md``): ``SQ_SKETCH_ROWS`` overrides the
'auto' sample-size target (0 disables sketching), ``SQ_SKETCH_DELTA`` the
sketch failure budget δ_stat (default 0.05), ``SQ_STATS_CACHE=0``
disables the cache, ``SQ_SKETCH_AUDIT_ELEMS`` caps the matrix size up to
which the guarantee auditor affords exact ground truth for the
``sketch.*`` sites.
"""

from . import cache
from .engine import (SpectralStats, dispatch_host, exact_spectral_stats,
                     finalize_host, frobenius_squared, mu_stats,
                     resolve_sketch_rows, sketch_delta_stat, spectral_stats)

__all__ = [
    "SpectralStats",
    "cache",
    "dispatch_host",
    "exact_spectral_stats",
    "finalize_host",
    "frobenius_squared",
    "mu_stats",
    "resolve_sketch_rows",
    "sketch_delta_stat",
    "spectral_stats",
]
